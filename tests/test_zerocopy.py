"""Zero-copy data path (ISSUE 4): the eager bridge's dlpack/buffer-
protocol adaptation (ops.zerocopy.as_buffer) and the host plane's
scatter-gather ring (csrc RingAllreduceSG behind HVD_ZEROCOPY_THRESHOLD).
"""
import numpy as np
import pytest

from .util import run_single, run_worker_job

from horovod_tpu.ops import zerocopy


def _delta(before, after):
    return {k: after[k] - before[k]
            for k in ("zerocopy_ops", "zerocopy_bytes",
                      "copy_ops", "copy_bytes")}


def test_as_buffer_contiguous_ndarray_passes_through():
    x = np.arange(16, dtype=np.float32)
    s0 = zerocopy.stats()
    arr, zc = zerocopy.as_buffer(x)
    assert zc and arr is x
    d = _delta(s0, zerocopy.stats())
    assert d["zerocopy_ops"] == 1 and d["zerocopy_bytes"] == x.nbytes
    assert d["copy_ops"] == 0 and d["copy_bytes"] == 0


def test_as_buffer_noncontiguous_falls_back_counted():
    x = np.arange(32, dtype=np.float32)[::2]
    s0 = zerocopy.stats()
    arr, zc = zerocopy.as_buffer(x)
    assert not zc
    assert arr.flags["C_CONTIGUOUS"] and np.array_equal(arr, x)
    s1 = zerocopy.stats()
    d = _delta(s0, s1)
    assert d["copy_ops"] == 1 and d["copy_bytes"] == arr.nbytes
    assert (s1["fallback_reasons"]["non-contiguous"]
            == s0["fallback_reasons"].get("non-contiguous", 0) + 1)


def test_as_buffer_dtype_mismatch_falls_back_counted():
    x = np.arange(8, dtype=np.float64)
    s0 = zerocopy.stats()
    arr, zc = zerocopy.as_buffer(x, dtype=np.float32)
    assert not zc and arr.dtype == np.float32
    assert np.array_equal(arr, x.astype(np.float32))
    s1 = zerocopy.stats()
    assert (s1["fallback_reasons"]["dtype-mismatch"]
            == s0["fallback_reasons"].get("dtype-mismatch", 0) + 1)
    # Matching dtype request stays zero-copy.
    arr2, zc2 = zerocopy.as_buffer(x, dtype=np.float64)
    assert zc2 and arr2 is x


def test_as_buffer_buffer_protocol_view():
    raw = bytearray(b"\x01\x02\x03\x04")
    arr, zc = zerocopy.as_buffer(raw)
    assert zc, "bytearray exports the buffer protocol — must not copy"
    raw[0] = 9  # writes through to the view => truly aliased
    assert arr[0] == 9


def test_as_buffer_no_protocol_copies_with_reason():
    s0 = zerocopy.stats()
    arr, zc = zerocopy.as_buffer([1.0, 2.0, 3.0])
    assert not zc and np.array_equal(arr, [1.0, 2.0, 3.0])
    s1 = zerocopy.stats()
    assert (s1["fallback_reasons"]["no-buffer-protocol"]
            == s0["fallback_reasons"].get("no-buffer-protocol", 0) + 1)


def test_bridge_disable_forces_copies():
    x = np.arange(16, dtype=np.float32)
    prev = zerocopy.set_enabled(False)
    try:
        s0 = zerocopy.stats()
        arr, zc = zerocopy.as_buffer(x)
        assert not zc and arr is not x and np.array_equal(arr, x)
        s1 = zerocopy.stats()
        assert (s1["fallback_reasons"]["disabled"]
                == s0["fallback_reasons"].get("disabled", 0) + 1)
    finally:
        zerocopy.set_enabled(prev)


def test_zerocopy_sg_allreduce_2rank():
    """2-rank integration (ISSUE 4 acceptance): above HVD_ZEROCOPY_THRESHOLD
    the host plane performs ZERO staging memcpys — large unfused, fused
    group straddling the threshold, Min/Average/f64 accumulate variants,
    and the below-threshold staged path all asserted via the new
    hvd.zerocopy_stats() counters, with exact numerics throughout."""
    run_worker_job(2, "zerocopy_worker.py",
                   extra_env={"HVD_ZEROCOPY_THRESHOLD": "16384"})


def test_zerocopy_sg_allreduce_4rank():
    run_worker_job(4, "zerocopy_worker.py",
                   extra_env={"HVD_ZEROCOPY_THRESHOLD": "16384"})


def test_zerocopy_disabled_by_env():
    """HVD_ZEROCOPY=0 pins everything to the staged path: the worker's
    zero-staging assertions must fail closed — exercised by asserting the
    state query instead of rerunning the whole worker."""
    run_single("zerocopy_off_worker.py", extra_env={
        "HVD_ZEROCOPY": "0",
        "HVD_ZEROCOPY_THRESHOLD": "4096",
    })


def test_traced_bridge_fails_loudly_on_stale_resize():
    """VERDICT r5 #8: hvd_allgather/hvd_alltoall/hvd_reducescatter hoist
    the process-set size at trace time; a (faked) elastic resize must
    raise the staleness error at the callback, not hand XLA a wrong-sized
    buffer."""
    run_single("bridge_stale_worker.py", timeout=180,
               drop_prefixes=("HVD_",))
