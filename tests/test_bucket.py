"""Backprop-ordered gradient bucketing (ISSUE 8): the ordered bucket
assembler in csrc/tensor_queue.h — plan learning/replay, early launches
overlapping the backward pass, flush/self-disable bounds, graph-change
invalidation, the kill switch, coexistence with the scatter-gather ring,
the TCP_BUCKET_* timeline family, and the autotune bucket arm."""

import json

from .util import run_worker_job


def test_bucket_early_launch():
    """The overlap claim itself: with a 2-bucket plan, the first bucket's
    allreduce launches while the step's later gradients are still
    outstanding (bucket_stats early counter)."""
    run_worker_job(4, "bucket_worker.py", timeout=180, extra_env={
        "HVD_BUCKET": "1",
        "HVD_BUCKET_BYTES": "8192",
        "BUCKET_MODE": "early",
    })


def test_bucket_mixed_dtypes():
    """Bucket members keep their own dtypes through the grouped release;
    f32/f64/i32/i64 results stay exact while bucketing is live."""
    run_worker_job(2, "bucket_worker.py", timeout=180, extra_env={
        "HVD_BUCKET": "1",
        "BUCKET_MODE": "dtypes",
    })


def test_bucket_invalidate_on_graph_change():
    """An unknown gradient name or a resized member drops the plan,
    releases held members ungrouped, and relearns — counted in
    bucket_stats invalidations, with every result still correct."""
    run_worker_job(2, "bucket_worker.py", timeout=180, extra_env={
        "HVD_BUCKET": "1",
        "HVD_BUCKET_BYTES": "8192",
        "BUCKET_MODE": "invalidate",
    })


def test_bucket_flush_self_disable():
    """A blocking synchronous caller (one allreduce at a time) fights the
    plan: held members flush at HVD_BUCKET_FLUSH_MS, and after a few
    flush streaks the assembler self-disables so the stall cost is
    bounded, not recurring."""
    run_worker_job(2, "bucket_worker.py", timeout=180, extra_env={
        "HVD_BUCKET": "1",
        "HVD_BUCKET_FLUSH_MS": "50",
        "BUCKET_MODE": "flush",
    })


def test_bucket_kill_switch():
    """HVD_BUCKET=0 removes bucketing entirely: state off, zero counters,
    plain per-tensor negotiation."""
    run_worker_job(2, "bucket_worker.py", timeout=180, extra_env={
        "HVD_BUCKET": "0",
        "BUCKET_MODE": "off",
    })


def test_bucket_coexists_with_zerocopy():
    """SG coexistence: a bucket whose fused payload crosses
    HVD_ZEROCOPY_THRESHOLD rides the scatter-gather ring (zerocopy_stats
    moves) while the assembler keeps launching buckets early."""
    run_worker_job(2, "bucket_worker.py", timeout=180, extra_env={
        "HVD_BUCKET": "1",
        "HVD_BUCKET_BYTES": "16384",
        "HVD_ZEROCOPY_THRESHOLD": "8192",
        "BUCKET_MODE": "coexist",
    })


def test_bucket_timeline_events(tmp_path):
    """The TCP_BUCKET_* timeline family: assemble spans cover each held
    member, one launch span per released bucket, all inside a valid
    chrome-trace JSON."""
    tl = tmp_path / "bucket_timeline.json"
    run_worker_job(2, "bucket_worker.py", timeout=180, extra_env={
        "HVD_BUCKET": "1",
        "HVD_BUCKET_BYTES": "8192",
        "HVD_TIMELINE": str(tl),
        "BUCKET_MODE": "early",
    })
    events = json.loads(tl.read_text())
    phases = [e["name"] for e in events]
    assert "TCP_BUCKET_ASSEMBLE" in phases, set(phases)
    assert "TCP_BUCKET_LAUNCH" in phases, set(phases)
    # Launch spans close after their members' assemble spans open — the
    # hold window the overlap fraction is derived from (bench.py).
    t_assemble = min(e["ts"] for e in events
                     if e["name"] == "TCP_BUCKET_ASSEMBLE")
    t_launch = max(e["ts"] + e.get("dur", 0) for e in events
                   if e["name"] == "TCP_BUCKET_LAUNCH")
    assert t_launch >= t_assemble


def test_autotune_bucket_arm(tmp_path):
    """The bucket toggle as the sixth autotune categorical arm: with
    zerocopy/pipeline/shm pinned off on a 2-rank pod the (cache, bucket)
    probe rows flip each dim once, the bandit locks a winner, and ships
    it in the ResponseList (autotune_worker.py asserts the phase walk)."""
    log = tmp_path / "autotune_bucket.csv"
    run_worker_job(2, "autotune_worker.py", extra_env={
        "HVD_AUTOTUNE": "1",
        "HVD_AUTOTUNE_LOG": str(log),
        "HVD_AUTOTUNE_CYCLES_PER_SAMPLE": "4",
        "HVD_AUTOTUNE_MAX_SAMPLES": "10",
        "HVD_ZEROCOPY": "0",
        "HVD_RING_PIPELINE": "1",
        "HVD_SHM": "0",
        # wire arm pinned off: covered by test_wire.py::test_autotune_wire_arm
        "HVD_WIRE": "basic",
        "EXPECT_DIMS": "2",
    }, timeout=240)
    # The bucket column really swept both states (d+1 = 3 probe rows).
    rows = [l for l in log.read_text().splitlines()[1:4]
            if not l.startswith("#")]
    assert {l.split(",")[8] for l in rows} == {"0", "1"}, rows
