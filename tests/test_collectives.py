"""Distributed correctness tier (reference: test/parallel/test_torch.py,
test_tensorflow.py — the collective × dtype × shape matrix, process sets,
grouped ops, error paths), executed as N local processes rendezvousing over
localhost TCP (SURVEY.md §4 'fake pod')."""

import pytest

from .util import run_worker_job


@pytest.mark.parametrize("np_", [2, 4])
def test_collective_matrix(np_):
    run_worker_job(np_, "collective_worker.py")


def test_adasum_semantics():
    run_worker_job(2, "adasum_worker.py")


def test_operation_manager_dispatch():
    """Priority-ordered backend dispatch (reference: operation_manager.cc):
    registered lists are observable, selection is per-response (Sum rides
    the terminal ring backend, Adasum the higher-priority adasum one)."""
    run_worker_job(2, "dispatch_worker.py")


def test_process_sets():
    run_worker_job(4, "process_set_worker.py")


def test_negotiation_errors():
    run_worker_job(2, "error_worker.py")


def test_peer_death_raises_internal_error():
    run_worker_job(3, "elastic_error_worker.py")


def test_jax_distributed_optimizer_end_to_end():
    """SURVEY.md §7 stage 4: gradients leave JAX, ride the core, come back
    averaged — eager and inside jit (io_callback)."""
    run_worker_job(2, "jax_dp_worker.py", timeout=300)


def test_response_cache():
    """Steady-state negotiation rides the bit-vector cache path (reference:
    response_cache.cc): hits recorded, invalidation on shape/dtype change,
    grouped + all cacheable op types correct through the cache."""
    run_worker_job(2, "cache_worker.py")


def test_response_cache_capacity_lru():
    run_worker_job(2, "cache_capacity_worker.py",
                   extra_env={"HVD_CACHE_CAPACITY": "2"})


def test_response_cache_disabled():
    run_worker_job(2, "cache_capacity_worker.py",
                   extra_env={"HVD_CACHE_CAPACITY": "0"})


def test_horovod_env_spelling_compat():
    """The reference's HOROVOD_* env names configure the core via the
    EnvRaw fallback (docs/migrating.md), with HVD_* taking precedence."""
    from .util import run_single

    run_single("horovod_env_worker.py", extra_env={
        "HOROVOD_FUSION_THRESHOLD": str(8 * 1024 * 1024),
        "HOROVOD_CYCLE_TIME": "3.0",
        "HOROVOD_CACHE_CAPACITY": "64",
    }, timeout=120, drop_prefixes=("HVD_", "HOROVOD_"))


def test_autotune(tmp_path):
    """--autotune is live: GP+EI search moves fusion/cycle params on a
    synthetic stream, locks, and logs a CSV (reference:
    parameter_manager.cc + optim/bayesian_optimization.cc)."""
    log = tmp_path / "autotune.csv"
    run_worker_job(4, "autotune_worker.py", extra_env={
        "HVD_AUTOTUNE": "1",
        "HVD_AUTOTUNE_LOG": str(log),
        "HVD_AUTOTUNE_CYCLES_PER_SAMPLE": "4",
        # Explicit budget: the bandit sizes its bracket to what fits after
        # the d+1 probes + a minimal numeric phase (autotune.cc Configure).
        "HVD_AUTOTUNE_MAX_SAMPLES": "20",
        # 2 fake hosts x 2 locals: the hierarchical arm is toggleable, so
        # the lattice covers at least (cache, hier, zerocopy, pipeline).
        # HVD_SHM=0 / HVD_BUCKET=0 remove those dimensions; the shm arm is
        # covered by test_hier_shm.py::test_autotune_shm_arm, the bucket
        # arm by test_bucket.py::test_autotune_bucket_arm. The wire dim is
        # UNPINNED (the PR 13 HVD_WIRE=basic workaround is gone): the
        # bandit fits whatever lattice the wire probe yields, so the dim
        # count is env-dependent — hence the >= bound.
        "AT_LOCAL_SIZE": "2",
        "HVD_SHM": "0",
        "HVD_BUCKET": "0",
        "EXPECT_DIMS_MIN": "4",
    }, timeout=240)


def test_autotune_schedule_column(tmp_path):
    """A registered pipeline workload (hvd_register_pipeline_workload)
    stamps its schedule label into every subsequent CSV row's recorded
    `schedule` column — so sweep scores are attributable to the schedule
    that shaped the traffic (docs/autotune.md; the unregistered "-"
    default is asserted by every other autotune run of this worker)."""
    log = tmp_path / "autotune_sched.csv"
    run_worker_job(2, "autotune_worker.py", extra_env={
        "HVD_AUTOTUNE": "1",
        "HVD_AUTOTUNE_LOG": str(log),
        "HVD_AUTOTUNE_CYCLES_PER_SAMPLE": "4",
        "HVD_AUTOTUNE_MAX_SAMPLES": "12",
        "AT_PIPE_SCHEDULE": "interleaved2",
        # single dimension (cache) keeps the tiny budget valid
        "HVD_ZEROCOPY": "0",
        "HVD_RING_PIPELINE": "1",
        "HVD_SHM": "0",
        "HVD_BUCKET": "0",
        "HVD_WIRE": "basic",
        "EXPECT_DIMS": "1",
    }, timeout=240)
    from horovod_tpu.observability.autotune_csv import COLUMNS

    sched_col = COLUMNS.index("schedule")
    rows = [l for l in log.read_text().splitlines()[1:] if l]
    assert all(l.split(",")[sched_col] == "interleaved2" for l in rows), rows[:3]


def test_autotune_beats_defaults_32rank(tmp_path):
    """32-rank fake pod: the locked configuration must move more bytes/sec
    than the (deliberately pathological) defaults — the categorical arms
    (cache x hierarchical) plus the numeric GP search have to find the
    obvious win of a shorter cycle (VERDICT r3 #8; reference:
    parameter_manager.cc)."""
    log = tmp_path / "autotune32.csv"
    run_worker_job(32, "autotune_win_worker.py", extra_env={
        "HVD_AUTOTUNE": "1",
        "HVD_AUTOTUNE_LOG": str(log),
        "HVD_AUTOTUNE_CYCLES_PER_SAMPLE": "3",
        "HVD_AUTOTUNE_MAX_SAMPLES": "8",
        "HVD_CYCLE_TIME_MS": "25",
        "AT_LOCAL_SIZE": "8",  # 4 fake hosts x 8: cache + hier toggleable
        # Pin the zero-copy, ring-pipeline, shm, bucket, and wire arms
        # off: keeps the probe phase at 3 windows + a 2-arm bracket inside
        # the tight 8-sample budget, and keeps the probe-row assertion
        # below deterministic (the wire dim is kernel-dependent). Those
        # arms are covered by test_autotune above,
        # test_hier_shm.py::test_autotune_shm_arm,
        # test_bucket.py::test_autotune_bucket_arm, and test_wire.py.
        "HVD_ZEROCOPY": "0",
        "HVD_RING_PIPELINE": "1",
        "HVD_SHM": "0",
        "HVD_BUCKET": "0",
        "HVD_WIRE": "basic",
    }, timeout=600)
    text = log.read_text()
    assert text.startswith("sample,fusion_kb,cycle_ms,cache,hier,"), text
    # Probe phase recorded: baseline + cache-flip + hier-flip are three
    # distinct (cache, hier) pairs, and each dim took both values.
    probe = [l.split(",") for l in text.splitlines()[1:4]]
    assert len({tuple(l[3:5]) for l in probe}) == 3, probe
    assert {l[3] for l in probe} == {"0", "1"}, probe
    assert {l[4] for l in probe} == {"0", "1"}, probe


def test_join_same_cycle_drain_and_overlap():
    """Joined state survives the whole response pass (an async allreduce
    draining with its rank's join() keeps zero-fill stand-ins), and a
    fully-submitted non-allreduce overlapping a join completes instead of
    erroring (reference: Controller::ComputeResponseList)."""
    run_worker_job(2, "join_race_worker.py", extra_env={
        "HVD_CACHE_CAPACITY": "0",
        "HVD_CYCLE_TIME_MS": "50",
    })


def test_cached_non_allreduce_overlapping_join_fails_fast():
    """A steady-state cached broadcast whose peer joined must surface the
    only-allreduce-may-overlap-join error via bit eviction + repost, not
    hang the bit AND forever."""
    run_worker_job(2, "cache_join_worker.py")


@pytest.mark.parametrize("np_,local", [(4, 2), (8, 4)])
def test_hierarchical_allreduce_correct_and_saves_cross_bytes(np_, local):
    """HVD_HIERARCHICAL_ALLREDUCE on fake pods (2 hosts x `local` ranks;
    reference: NCCLHierarchicalAllreduce): results match the flat ring
    for sum/avg/fused/odd-length, and each rank's cross-plane wire bytes
    drop to ~1/local_size of the flat ring's (local reduce-scatter first,
    so only one shard rides the cross plane)."""
    import os
    import sys

    from horovod_tpu.runner.local import run_local

    from .util import WORKERS, _REPO

    def run(hier):
        out_path = f"/tmp/hier_{os.getpid()}_{np_}_{hier}.log"
        env = {"PYTHONPATH": _REPO, "JAX_PLATFORMS": "cpu",
               "HIER_LOCAL_SIZE": str(local),
               "HVD_HIERARCHICAL_ALLREDUCE": str(hier)}
        with open(out_path, "w") as f:
            codes = run_local(
                np_,
                [sys.executable, os.path.join(WORKERS, "hier_worker.py")],
                env=env, timeout=180, stdout=f)
        with open(out_path) as f:
            out = f.read()
        os.unlink(out_path)
        assert codes == [0] * np_, out
        tx = {}
        for line in out.splitlines():
            if line.startswith("HIERTX"):
                parts = dict(kv.split("=") for kv in line.split()[1:])
                tx[int(parts["rank"])] = int(parts["cross"])
        assert len(tx) == np_, out
        return tx

    flat = run(0)
    hier = run(1)
    # Flat ring: the worst rank ships every byte it forwards across the
    # "host" boundary; hierarchical: only the owned 1/local_size shard
    # does. Expect roughly a local_size-fold drop; assert half that.
    worst_flat = max(flat.values())
    worst_hier = max(hier.values())
    assert worst_hier * (local / 2 + 1) < worst_flat, (flat, hier)


@pytest.mark.parametrize("np_", [2, 3])
def test_join_zero_fill(np_):
    """Join parity (reference HorovodJoinOp): ranks run different step
    counts; joined ranks zero-fill allreduces while survivors continue;
    join() returns the last rank to join."""
    run_worker_job(np_, "join_worker.py")


def test_control_plane_scales_to_32_ranks(tmp_path):
    """VERDICT r2 weak #1: rank 0's RequestList gather must not be O(N)
    sequential round-trips. The coordinator now poll-gathers all workers
    concurrently (csrc/tcp.cc RecvFrameEach); this runs the full collective
    matrix at 32 ranks and compares mean negotiation-cycle latency at 8 vs
    32 ranks. The bound is deliberately loose: this box has ONE core, so 32
    ranks oversubscribe it 32x and scheduler noise dominates — the assert
    catches O(N) blow-ups, not small regressions."""
    import sys, os
    from horovod_tpu.runner.local import run_local
    from .util import _REPO, WORKERS

    run_worker_job(32, "collective_worker.py", timeout=300)

    def mean_cycle(np_):
        out = tmp_path / f"stress-{np_}"
        env = {"PYTHONPATH": _REPO, "JAX_PLATFORMS": "cpu",
               "STRESS_OUT": str(out), "STRESS_ROUNDS": "40"}
        codes = run_local(
            np_, [sys.executable, os.path.join(WORKERS, "stress_worker.py")],
            env=env, timeout=300)
        assert codes == [0] * np_
        return float(out.read_text())

    c8 = mean_cycle(8)
    c32 = mean_cycle(32)
    print(f"mean cycle: 8 ranks {c8*1e3:.2f} ms, 32 ranks {c32*1e3:.2f} ms")
    # Serial gather would scale the control-plane cost ~linearly in N
    # (4x from 8->32) ON TOP of the 4x CPU oversubscription this host
    # already imposes; flat-ish control plane stays well under 8x total.
    assert c32 < max(8 * c8, 0.25), (c8, c32)


def test_grouped_ops_bypass_response_cache():
    """Grouped members must never be cache-signaled: an LRU eviction of
    SOME members would strand the group in the group table forever. Runs
    named grouped collectives under HVD_CACHE_CAPACITY=1 churn."""
    run_worker_job(2, "grouped_cache_worker.py",
                   extra_env={"HVD_CACHE_CAPACITY": "1"})
