"""Helpers for multi-process tests (SURVEY.md §4: the 'fake pod' is N local
processes rendezvousing on localhost)."""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKERS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "workers")


def tpu_isolated_env(*extra_paths):
    """Env pinning spawned test processes OFF the real TPU: repo-only
    PYTHONPATH (a session site hook there would register the tunneled
    TPU platform in every child) and the CPU jax platform. The single
    policy for every harness that spawns workers — run_worker_job,
    run_single, the launcher e2e tests, the elastic harness."""
    path = os.pathsep.join((_REPO,) + tuple(extra_paths))
    return {"PYTHONPATH": path, "JAX_PLATFORMS": "cpu"}


def _worker_path(worker_file):
    """Absolute path accepted as-is; bare names resolve to tests/workers."""
    if os.path.isabs(worker_file):
        return worker_file
    return os.path.join(WORKERS, worker_file)


def run_worker_job(np_, worker_file, extra_env=None, timeout=120,
                   jax_coord=False):
    """Launch `worker_file` (bare name under tests/workers, or an absolute
    script path) as an np_-rank job; assert every rank exits 0.

    ``jax_coord=True`` provisions a jax.distributed coordinator so the ranks
    form one global device mesh (the multi-process ICI-plane tests).
    """
    from horovod_tpu.runner.local import run_local

    env = tpu_isolated_env()
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    codes = run_local(
        np_, [sys.executable, _worker_path(worker_file)],
        env=env, timeout=timeout, jax_coord=jax_coord,
    )
    assert codes == [0] * np_, f"worker exit codes: {codes}"


def run_single(worker_file, extra_env=None, timeout=120,
               drop_prefixes=()):
    """Run one worker process. ``drop_prefixes`` strips ambient env keys
    (e.g. a developer's exported HVD_* tunables) that would otherwise
    leak into a test asserting specific configuration."""
    env = dict(os.environ)
    for k in list(env):
        if any(k.startswith(p) for p in drop_prefixes):
            del env[k]
    env["PYTHONPATH"] = _REPO
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    p = subprocess.run(
        [sys.executable, _worker_path(worker_file)],
        env=env, timeout=timeout, capture_output=True, text=True,
    )
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr}"
