"""Helpers for multi-process tests (SURVEY.md §4: the 'fake pod' is N local
processes rendezvousing on localhost)."""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKERS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "workers")


def tpu_isolated_env(*extra_paths):
    """Env pinning spawned test processes OFF the real TPU: repo-only
    PYTHONPATH (a session site hook there would register the tunneled
    TPU platform in every child) and the CPU jax platform. The single
    policy for every harness that spawns workers — run_worker_job,
    run_single, the launcher e2e tests, the elastic harness."""
    path = os.pathsep.join((_REPO,) + tuple(extra_paths))
    return {"PYTHONPATH": path, "JAX_PLATFORMS": "cpu"}


def run_worker_job(np_, worker_file, extra_env=None, timeout=120,
                   jax_coord=False):
    """Launch `worker_file` as an np_-rank job; assert every rank exits 0.

    ``jax_coord=True`` provisions a jax.distributed coordinator so the ranks
    form one global device mesh (the multi-process ICI-plane tests).
    """
    from horovod_tpu.runner.local import run_local

    env = tpu_isolated_env()
    if extra_env:
        env.update(extra_env)
    codes = run_local(
        np_, [sys.executable, os.path.join(WORKERS, worker_file)],
        env=env, timeout=timeout, jax_coord=jax_coord,
    )
    assert codes == [0] * np_, f"worker exit codes: {codes}"


def run_single(worker_file, extra_env=None, timeout=120):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    p = subprocess.run(
        [sys.executable, os.path.join(WORKERS, worker_file)],
        env=env, timeout=timeout, capture_output=True, text=True,
    )
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr}"
