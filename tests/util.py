"""Helpers for multi-process tests (SURVEY.md §4: the 'fake pod' is N local
processes rendezvousing on localhost)."""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKERS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "workers")


def tpu_isolated_env(*extra_paths):
    """Env pinning spawned test processes OFF the real TPU: repo-only
    PYTHONPATH (a session site hook there would register the tunneled
    TPU platform in every child) and the CPU jax platform. The single
    policy for every harness that spawns workers — run_worker_job,
    run_single, the launcher e2e tests, the elastic harness."""
    path = os.pathsep.join((_REPO,) + tuple(extra_paths))
    return {"PYTHONPATH": path, "JAX_PLATFORMS": "cpu"}


def _worker_path(worker_file):
    """Absolute path accepted as-is; bare names resolve to tests/workers."""
    if os.path.isabs(worker_file):
        return worker_file
    return os.path.join(WORKERS, worker_file)


def run_worker_job(np_, worker_file, extra_env=None, timeout=120,
                   jax_coord=False):
    """Launch `worker_file` (bare name under tests/workers, or an absolute
    script path) as an np_-rank job; assert every rank exits 0.

    ``jax_coord=True`` provisions a jax.distributed coordinator so the ranks
    form one global device mesh (the multi-process ICI-plane tests).
    """
    from horovod_tpu.runner.local import run_local

    env = tpu_isolated_env()
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    codes = run_local(
        np_, [sys.executable, _worker_path(worker_file)],
        env=env, timeout=timeout, jax_coord=jax_coord,
    )
    assert codes == [0] * np_, f"worker exit codes: {codes}"


# ---------------------------------------------------------------------------
# Sanitizer-tier harness (docs/static_analysis.md). One launcher for the
# TSAN/ASAN/UBSAN core builds plus the lockdep `debug` tier: build the
# instrumented .so, point HVD_LIB at it, preload the sanitizer runtime when
# it uses interceptors, run an np_-rank job, and parse the per-rank report
# files down to the reports that name the core.

CSRC = os.path.join(_REPO, "horovod_tpu", "csrc")

SANITIZER_TIERS = {
    # make target == tier name; lib = the HVD_LIB each tier loads.
    # preload: sanitizer runtimes with malloc/pthread interceptors must be
    # first in the link order, i.e. LD_PRELOADed into (uninstrumented)
    # python. UBSAN has no interceptors and the debug tier no runtime at
    # all, so neither needs one. libstdc++ rides along with each runtime:
    # python doesn't link it, so a preloaded sanitizer can't resolve the
    # real __cxa_throw at init — the first C++ throw in the core (e.g.
    # EstablishMesh's re-dial path) would then trip the interceptor's
    # "real___cxa_throw != 0" CHECK and silently _exit with `exitcode`.
    "tsan": {
        "lib": "libhvd_tpu_tsan.so",
        "preload": ["libtsan.so", "libstdc++.so.6"],
        "options_var": "TSAN_OPTIONS",
        "options": "exitcode=0",
    },
    "asan": {
        "lib": "libhvd_tpu_asan.so",
        "preload": ["libasan.so", "libstdc++.so.6"],
        "options_var": "ASAN_OPTIONS",
        "options": "exitcode=0:detect_leaks=1",
    },
    "ubsan": {
        "lib": "libhvd_tpu_ubsan.so",
        "preload": None,
        "options_var": "UBSAN_OPTIONS",
        "options": "exitcode=0:print_stacktrace=1",
    },
    "debug": {  # -O0 -DHVD_DEBUG: lockdep on by default (debug_lock.h)
        "lib": "libhvd_tpu_debug.so",
        "preload": None,
        "options_var": None,
        "options": None,
    },
}


def sanitizer_runtime(libname):
    """Absolute path of gcc's runtime lib (libtsan.so/libasan.so), or None
    when the toolchain can't supply it (the tests skip)."""
    try:
        out = subprocess.run(["gcc", "-print-file-name=%s" % libname],
                             capture_output=True, text=True, check=True)
        path = out.stdout.strip()
        return path if os.path.isabs(path) and os.path.exists(path) else None
    except Exception:
        return None


def _core_reports(tier, tmp_path):
    """Parse a tier's log_path report files down to the reports naming the
    core (hvd frames / the instrumented .so / csrc sources) — reports from
    python's own allocations or third-party libs don't fail the job."""
    texts = []
    for f in sorted(os.listdir(tmp_path)):
        if f.startswith(tier + "."):
            with open(os.path.join(tmp_path, f)) as fh:
                texts.append(fh.read())
    reports = []
    if tier == "tsan":
        for text in texts:
            reports += [b for b in text.split("==================")
                        if "WARNING: ThreadSanitizer" in b]
    elif tier == "asan":
        # ASAN hard errors are one block per file (the process dies on the
        # first); LSAN leak records are blank-line separated within a file.
        for text in texts:
            reports += [b for b in text.split("\n\n")
                        if "ERROR: AddressSanitizer" in b or "leak of " in b]
    elif tier == "ubsan":
        # UBSAN reports are "file:line:col: runtime error: ..." lines
        # followed (print_stacktrace=1) by a stack; one line per finding.
        for text in texts:
            reports += [ln for ln in text.splitlines()
                        if "runtime error:" in ln]
    core = [b for b in reports
            if "hvd" in b or "csrc" in b]
    return core


def run_under_sanitizer(tmp_path, worker, np_, tier="tsan", extra_env=None,
                        timeout=600):
    """Build the `tier` core, run `worker` (under tests/workers) with np_
    ranks against it, and return (proc, core_reports). Skips when the
    sanitizer runtime isn't available from gcc."""
    import pytest

    spec = SANITIZER_TIERS[tier]
    preload = None
    if spec["preload"]:
        libs = [sanitizer_runtime(lib) for lib in spec["preload"]]
        if None in libs:
            missing = spec["preload"][libs.index(None)]
            pytest.skip("gcc/%s unavailable" % missing)
        preload = " ".join(libs)
    subprocess.run(["make", "-s", tier], cwd=CSRC, check=True)

    env = dict(os.environ)
    env.update({
        "PYTHONPATH": _REPO,
        "JAX_PLATFORMS": "cpu",
        "HVD_LIB": os.path.join(_REPO, "horovod_tpu", "lib", spec["lib"]),
        # LeakSanitizer's exit path (Die -> _exit) skips stdio flush: a
        # worker whose process has ambient python-internal leaks would
        # lose its block-buffered PASS line when stdout is a pipe.
        # Unbuffered stdio makes the grading output write-through.
        "PYTHONUNBUFFERED": "1",
    })
    if preload:
        env["LD_PRELOAD"] = preload
    if spec["options_var"]:
        # exitcode=0: we grade on the reports we parse, so an unrelated
        # finding in a third-party lib can't fail the job spuriously.
        # log_path=%p-suffixed files: all ranks share the runner's stderr
        # pipe, where concurrent reports could interleave and tear past
        # the 'hvd' filter in _core_reports.
        env[spec["options_var"]] = "%s:log_path=%s/%s" % (
            spec["options"], tmp_path, tier)
    env.update({k: str(v) for k, v in (extra_env or {}).items()})
    p = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.local", "-np",
         str(np_), sys.executable, os.path.join(WORKERS, worker)],
        env=env, capture_output=True, text=True, timeout=timeout)
    # A failed preload runs everything UNinstrumented with exit 0 — a
    # green result would be vacuous. ld.so names the failure on stderr.
    assert "cannot be preloaded" not in p.stderr, p.stderr[-2000:]
    return p, _core_reports(tier, tmp_path)


def assert_sanitizer_clean(p, np_, core_reports, tier="sanitizer"):
    """The shared grading triple for every sanitizer-tier test: the job
    exited 0, every rank printed PASS, and no report names the core."""
    assert p.returncode == 0, p.stderr[-3000:]
    assert p.stdout.count("PASS") == np_, p.stdout
    assert not core_reports, "%s reports in the core:\n%s" % (
        tier, "\n".join(core_reports[:3]))


def run_single(worker_file, extra_env=None, timeout=120,
               drop_prefixes=()):
    """Run one worker process. ``drop_prefixes`` strips ambient env keys
    (e.g. a developer's exported HVD_* tunables) that would otherwise
    leak into a test asserting specific configuration."""
    env = dict(os.environ)
    for k in list(env):
        if any(k.startswith(p) for p in drop_prefixes):
            del env[k]
    env["PYTHONPATH"] = _REPO
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    p = subprocess.run(
        [sys.executable, _worker_path(worker_file)],
        env=env, timeout=timeout, capture_output=True, text=True,
    )
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr}"


def have_shard_map():
    """jax >= 0.8 probe (the PR 13 availability-gate pattern): the
    parallel package — and every worker script that imports it — needs
    jax.shard_map. Tests that only SPAWN such workers use this to skip
    up front instead of failing on the workers' ImportError."""
    try:
        from jax import shard_map  # noqa: F401
        return True
    except Exception:  # noqa: BLE001 — no jax at all also means no
        return False


def have_torch_native_ext():
    """Whether the torch native extension (csrc/torch_ops.cc) builds and
    loads against the installed torch; the jit build is cached, so the
    probe pays the compile at most once per environment."""
    try:
        from horovod_tpu.torch import native_ext
        return native_ext.lib() is not None
    except Exception:  # noqa: BLE001 — no torch / build failure
        return False
