"""Multi-process ICI-plane tests: tpurun-launched processes form ONE global
jax device mesh (jax.distributed multi-controller), so in-jit collectives
cross process boundaries on device — the composition of the launcher, the
native core control plane, and the XLA data plane (SURVEY.md §7 stage 5;
VERDICT r1 item #1).

The fake pod is 2 processes × 2 virtual CPU devices on localhost (SURVEY §4).
"""

import pytest

pytest.importorskip("jax")

from .util import run_worker_job  # noqa: E402


def test_two_process_global_mesh():
    run_worker_job(2, "jax_multiproc_worker.py", timeout=300, jax_coord=True)
