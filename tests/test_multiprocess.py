"""Multi-process ICI-plane tests: tpurun-launched processes form ONE global
jax device mesh (jax.distributed multi-controller), so in-jit collectives
cross process boundaries on device — the composition of the launcher, the
native core control plane, and the XLA data plane (SURVEY.md §7 stage 5;
VERDICT r1 item #1, widened per VERDICT r2 weak #3 / next-round #7).

The fake pod is N processes × 2 virtual CPU devices on localhost (SURVEY §4).
"""


import pytest

pytest.importorskip("jax")

from .util import run_worker_job  # noqa: E402
from .util import have_shard_map  # noqa: E402


@pytest.mark.parametrize("np_", [2, 4])
@pytest.mark.skipif(not have_shard_map(), reason="jax.shard_map unavailable (jax < 0.8): mesh workers cannot import horovod_tpu.parallel")
def test_global_mesh_train_step(np_):
    """Mesh formation, in-jit psum across processes, full DP train step
    with on-device gradient pmean, host metadata sync, core control plane
    composing in the same process."""
    run_worker_job(np_, "jax_multiproc_worker.py", timeout=300,
                   jax_coord=True)


@pytest.mark.skipif(not have_shard_map(), reason="jax.shard_map unavailable (jax < 0.8): mesh workers cannot import horovod_tpu.parallel")
def test_mesh_collective_matrix_4proc():
    """All five in-mesh collectives × dtypes through a 4-process × 2-device
    global mesh (the ICI analog of the host path's op matrix)."""
    run_worker_job(4, "jax_mesh_matrix_worker.py", timeout=300,
                   jax_coord=True)


@pytest.mark.skipif(not have_shard_map(), reason="jax.shard_map unavailable (jax < 0.8): mesh workers cannot import horovod_tpu.parallel")
def test_mixed_in_mesh_and_core_ops():
    """In-mesh XLA collectives and core-bridged (eager + in-jit io_callback)
    collectives interleaved for several rounds in one program."""
    run_worker_job(2, "jax_mesh_mixed_worker.py", timeout=300,
                   jax_coord=True)


@pytest.mark.skipif(not have_shard_map(), reason="jax.shard_map unavailable (jax < 0.8): mesh workers cannot import horovod_tpu.parallel")
def test_worker_death_while_meshed_fails_fast():
    """A rank dying with the mesh live must surface HorovodInternalError on
    survivors via the core plane promptly — not a coordination-service or
    rendezvous timeout. The worker times the post-death collective itself
    and asserts detection < 10s (TCP close is instant; a heartbeat fallback
    is 60s+), so job spawn/import cost can't mask a regression."""
    run_worker_job(3, "jax_mesh_death_worker.py", timeout=240,
                   jax_coord=True)


def test_rapid_reinit_32rank_no_caller_retries():
    """VERDICT r4 weak #6: rapid, unstaggered init/shutdown/init cycles at
    32 ranks on one fixed controller port must succeed with ZERO
    caller-side retry loops — the rebind backoff (csrc/tcp.cc ListenRetry)
    and the worker-side rendezvous re-dial (csrc/core.cc EstablishMesh)
    absorb the port race inside the library."""
    run_worker_job(32, "reinit_worker.py", timeout=300,
                   extra_env={"REINIT_CYCLES": "3"})
