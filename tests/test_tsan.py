"""ThreadSanitizer harness for the native core (SURVEY.md §5: the
reference has no sanitizer CI; the core's concurrency design — frontend
threads enqueueing into a single background thread over lock-protected
queues — is exactly what TSAN validates cheaply).

Builds libhvd_tpu_tsan.so (`make tsan`), preloads libtsan into python,
points HVD_LIB at the instrumented core, and runs multi-rank jobs. Any
data race inside the core shows up as a ThreadSanitizer report naming
hvd:: frames / the tsan lib.
"""
import os
import subprocess
import sys

import pytest

from .util import _REPO, WORKERS

CSRC = os.path.join(_REPO, "horovod_tpu", "csrc")
TSAN_CORE = os.path.join(_REPO, "horovod_tpu", "lib", "libhvd_tpu_tsan.so")


def _libtsan():
    try:
        out = subprocess.run(["gcc", "-print-file-name=libtsan.so"],
                             capture_output=True, text=True, check=True)
        path = out.stdout.strip()
        return path if os.path.isabs(path) and os.path.exists(path) else None
    except Exception:
        return None


def _run_under_tsan(tmp_path, worker, np_, extra_env=None):
    """Shared harness: instrumented core + preload, run `worker` with
    np_ ranks, return (proc, core_reports)."""
    libtsan = _libtsan()
    if libtsan is None:
        pytest.skip("gcc/libtsan unavailable")
    subprocess.run(["make", "-s", "tsan"], cwd=CSRC, check=True)

    env = dict(os.environ)
    env.update({
        "PYTHONPATH": _REPO,
        "JAX_PLATFORMS": "cpu",
        "LD_PRELOAD": libtsan,
        "HVD_LIB": TSAN_CORE,
        # exitcode=0: we grade on the reports we parse, so an unrelated
        # race in a third-party lib can't fail the job spuriously.
        # log_path=%p-suffixed files: all ranks share the runner's stderr
        # pipe, where concurrent reports could interleave and tear past
        # the 'hvd' filter below.
        "TSAN_OPTIONS": f"exitcode=0:log_path={tmp_path}/tsan",
    })
    env.update({k: str(v) for k, v in (extra_env or {}).items()})
    p = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.local", "-np",
         str(np_), sys.executable, os.path.join(WORKERS, worker)],
        env=env, capture_output=True, text=True, timeout=600)
    # A failed preload runs everything UNinstrumented with exit 0 — a
    # green result would be vacuous. ld.so names the failure on stderr.
    assert "cannot be preloaded" not in p.stderr, p.stderr[-2000:]

    reports = []
    for f in os.listdir(tmp_path):
        if f.startswith("tsan."):
            with open(os.path.join(tmp_path, f)) as fh:
                text = fh.read()
            reports += [b for b in text.split("==================")
                        if "WARNING: ThreadSanitizer" in b]
    core_reports = [b for b in reports
                    if "hvd" in b or "libhvd_tpu_tsan" in b]
    return p, core_reports


def test_core_collective_matrix_under_tsan(tmp_path):
    p, core_reports = _run_under_tsan(tmp_path, "collective_worker.py", 2)
    assert p.returncode == 0, p.stderr[-3000:]
    assert p.stdout.count("PASS") == 2, p.stdout
    assert not core_reports, "TSAN races in the core:\n" + \
        "\n".join(core_reports[:3])


def test_zerocopy_sg_ring_under_tsan(tmp_path):
    """The round-6 scatter-gather data path under the sanitizer: the
    segmented-iovec ring (RingAllreduceSG) reads user input buffers and
    writes user output buffers directly from the background thread while
    frontend threads poll the zerocopy/staging counters — exactly the
    ordering the counters-before-CompleteHandle contract pins down."""
    p, core_reports = _run_under_tsan(
        tmp_path, "zerocopy_worker.py", 2,
        extra_env={"HVD_ZEROCOPY_THRESHOLD": "16384"})
    assert p.returncode == 0, p.stderr[-3000:]
    assert p.stdout.count("PASS") == 2, p.stdout
    assert not core_reports, "TSAN races in the core:\n" + \
        "\n".join(core_reports[:3])


def test_reinit_and_auth_under_tsan(tmp_path):
    """The round-5 rendezvous additions under the sanitizer: rebind
    backoff + worker re-dial (rapid re-init cycles) and the connect-time
    HMAC handshake, including the acceptor thread + dial loop interplay
    (Listener::Shutdown wake path). 4 ranks x 2 unstaggered cycles with
    a job secret."""
    import secrets

    p, core_reports = _run_under_tsan(
        tmp_path, "reinit_worker.py", 4,
        extra_env={"HVD_RENDEZVOUS_SECRET": secrets.token_hex(16),
                   "REINIT_CYCLES": "2"})
    assert p.returncode == 0, p.stderr[-3000:]
    assert p.stdout.count("PASS") == 4, p.stdout
    assert not core_reports, "TSAN races in the core:\n" + \
        "\n".join(core_reports[:3])


def test_streamed_ring_reduce_under_tsan(tmp_path):
    """The streamed ring reduce-scatter (HVD_RING_PIPELINE) under the
    sanitizer: sub-blocks of the receive scratch are handed to Accumulate
    from inside the poll loop while the socket keeps draining the same
    buffer's tail — the delivery bound (only bytes the kernel already
    copied out are reduced) is exactly what TSAN would catch if wrong.
    Covers both the staged and scatter-gather rings plus the vectorized
    reduce kernels and their relaxed dispatch counters."""
    p, core_reports = _run_under_tsan(
        tmp_path, "ring_pipeline_worker.py", 2,
        extra_env={"HVD_RING_PIPELINE": "4",
                   "HVD_ZEROCOPY_THRESHOLD": "16384"})
    assert p.returncode == 0, p.stderr[-3000:]
    assert p.stdout.count("PASS") == 2, p.stdout
    assert not core_reports, "TSAN races in the core:\n" + \
        "\n".join(core_reports[:3])
