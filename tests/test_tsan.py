"""ThreadSanitizer harness for the native core (SURVEY.md §5: the
reference has no sanitizer CI; the core's concurrency design — frontend
threads enqueueing into a single background thread over lock-protected
queues — is exactly what TSAN validates cheaply).

Builds libhvd_tpu_tsan.so (`make tsan`), preloads libtsan into python,
points HVD_LIB at the instrumented core, and runs multi-rank jobs. Any
data race inside the core shows up as a ThreadSanitizer report naming
hvd:: frames / the tsan lib. The build/preload/report plumbing is the
shared sanitizer harness in tests/util.py, which test_sanitizers.py
reuses for the ASAN/UBSAN tiers (docs/static_analysis.md).
"""
import pytest

from .util import assert_sanitizer_clean, run_under_sanitizer

pytestmark = pytest.mark.sanitizer


def _run_under_tsan(tmp_path, worker, np_, extra_env=None):
    return run_under_sanitizer(tmp_path, worker, np_, tier="tsan",
                               extra_env=extra_env)


def test_core_collective_matrix_under_tsan(tmp_path):
    p, core_reports = _run_under_tsan(tmp_path, "collective_worker.py", 2)
    assert_sanitizer_clean(p, 2, core_reports, tier="tsan")


def test_zerocopy_sg_ring_under_tsan(tmp_path):
    """The round-6 scatter-gather data path under the sanitizer: the
    segmented-iovec ring (RingAllreduceSG) reads user input buffers and
    writes user output buffers directly from the background thread while
    frontend threads poll the zerocopy/staging counters — exactly the
    ordering the counters-before-CompleteHandle contract pins down."""
    p, core_reports = _run_under_tsan(
        tmp_path, "zerocopy_worker.py", 2,
        extra_env={"HVD_ZEROCOPY_THRESHOLD": "16384"})
    assert_sanitizer_clean(p, 2, core_reports, tier="tsan")


def test_reinit_and_auth_under_tsan(tmp_path):
    """The round-5 rendezvous additions under the sanitizer: rebind
    backoff + worker re-dial (rapid re-init cycles) and the connect-time
    HMAC handshake, including the acceptor thread + dial loop interplay
    (Listener::Shutdown wake path). 4 ranks x 2 unstaggered cycles with
    a job secret."""
    import secrets

    p, core_reports = _run_under_tsan(
        tmp_path, "reinit_worker.py", 4,
        extra_env={"HVD_RENDEZVOUS_SECRET": secrets.token_hex(16),
                   "REINIT_CYCLES": "2"})
    assert_sanitizer_clean(p, 4, core_reports, tier="tsan")


def test_hier_shm_ring_under_tsan(tmp_path):
    """The intra-host shm ring (csrc/shm.cc) under the sanitizer: SPSC
    slot handoff between background threads of different ranks, the
    on_span reduce callbacks consuming slots while the producer refills
    them, and the reduce worker pool fanning accumulations across lanes
    while the main thread polls the pool counters. 2 single-host ranks,
    hierarchical arm on, 2 pool lanes."""
    p, core_reports = _run_under_tsan(
        tmp_path, "hier_shm_worker.py", 2,
        extra_env={"HVD_HIERARCHICAL_ALLREDUCE": "1",
                   "HVD_REDUCE_THREADS": "2",
                   "EXPECT_SHM": "1"})
    assert_sanitizer_clean(p, 2, core_reports, tier="tsan")


def test_streamed_ring_reduce_under_tsan(tmp_path):
    """The streamed ring reduce-scatter (HVD_RING_PIPELINE) under the
    sanitizer: sub-blocks of the receive scratch are handed to Accumulate
    from inside the poll loop while the socket keeps draining the same
    buffer's tail — the delivery bound (only bytes the kernel already
    copied out are reduced) is exactly what TSAN would catch if wrong.
    Covers both the staged and scatter-gather rings plus the vectorized
    reduce kernels and their relaxed dispatch counters."""
    p, core_reports = _run_under_tsan(
        tmp_path, "ring_pipeline_worker.py", 2,
        extra_env={"HVD_RING_PIPELINE": "4",
                   "HVD_ZEROCOPY_THRESHOLD": "16384"})
    assert_sanitizer_clean(p, 2, core_reports, tier="tsan")


@pytest.mark.slow
def test_eviction_under_load_under_tsan(tmp_path):
    """The peer-liveness eviction path (ISSUE 10) under the sanitizer:
    rank 1 wedges via the in-core blackhole hook while rank 0's
    coordinator counts missed control-plane deadlines, escalates to
    EvictRank, and aborts the in-flight collective — with frontend
    threads on both ranks concurrently polling the heartbeat/eviction
    counters via hvd.elastic_stats(). Generous deadline budget: under
    TSAN a slow cycle must read as SLOW, not wedged."""
    p, core_reports = _run_under_tsan(
        tmp_path, "evict_worker.py", 2,
        extra_env={"EVICT_SYNC": str(tmp_path / "evicted.sync"),
                   "HVD_FAULT_INJECT": "1",
                   "HVD_PEER_TIMEOUT_MS": "2000",
                   "HVD_PEER_EVICT_MISSES": "3"})
    assert_sanitizer_clean(p, 2, core_reports, tier="tsan")


def test_bucketed_ring_under_tsan(tmp_path):
    """The ordered bucket assembler (ISSUE 8) under the sanitizer:
    frontend threads feed PushRequest while the background thread runs
    BucketFilter/ResetPlanLocked over the same held-member maps and
    drains the bounded event buffer into the timeline; bucket_stats()
    polls the counters from the frontend concurrently. 2 ranks, 8 KB
    buckets so the 4-grad burst replays a real 2-bucket plan."""
    p, core_reports = _run_under_tsan(
        tmp_path, "bucket_worker.py", 2,
        extra_env={"HVD_BUCKET": "1",
                   "HVD_BUCKET_BYTES": "8192",
                   "BUCKET_MODE": "early"})
    assert_sanitizer_clean(p, 2, core_reports, tier="tsan")
