"""hvdlint (tools/hvdlint.py) — the repo-clean gate plus fixture-tree
tests proving each rule actually fires (ISSUE 6 satellite: the linter
itself is tested, not just trusted).

The fixture tests build a minimal repo skeleton in tmp_path with ONE
seeded violation each and assert the violation is reported with the
right rule, file, and symbol — so a refactor that silently defangs a
check fails here, not in review.
"""
import os
import subprocess
import sys
import textwrap

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import hvdlint  # noqa: E402


# --- the tier-1 gate: the real repo is clean, zero suppressions ------------

def test_repo_is_clean():
    violations = hvdlint.run(_REPO)
    assert violations == [], "\n".join(str(v) for v in violations)


def test_cli_exit_codes(tmp_path):
    # Clean repo -> 0 and "clean" on stdout; the CLI is what `make check`
    # and CI call, so its contract is part of the tool.
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "hvdlint.py"),
         "--repo", _REPO],
        capture_output=True, text=True)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "clean" in p.stdout
    # --list-knobs inventories every read site; spot-check a C++-read and
    # a Python-read knob so both collectors are exercised end to end.
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "hvdlint.py"),
         "--repo", _REPO, "--list-knobs"],
        capture_output=True, text=True)
    assert p.returncode == 0
    assert "HVD_FUSION_THRESHOLD" in p.stdout
    assert "HVD_METRICS" in p.stdout


# --- fixture tree ----------------------------------------------------------

def _seed_repo(tmp_path):
    """Minimal clean skeleton the rules run against; tests then break it."""
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "running.md").write_text(
        "# Running\n`HVD_DOCUMENTED` is a documented knob.\n")
    (tmp_path / "docs" / "perf_tuning.md").write_text("# Perf\n")
    csrc = tmp_path / "horovod_tpu" / "csrc"
    csrc.mkdir(parents=True)
    (csrc / "logging.h").write_text(
        '// EnvRaw owns getenv\nstatic const char* EnvRaw(const char* n) '
        '{ return getenv(n); }\n')
    (csrc / "core.cc").write_text(textwrap.dedent("""\
        #include "logging.h"
        void ExecAllreduce() {
          g->zerocopy_total++;
          CompleteHandle(h);
          return;
        }
        void Init() { EnvRaw("HVD_DOCUMENTED"); }
        """))
    (csrc / "common.h").write_text("struct Tuned { int8_t tuned_cache; };\n")
    (tmp_path / "horovod_tpu" / "basics.py").write_text(
        "def cache_stats(self):\n    return ()\n")
    runner = tmp_path / "horovod_tpu" / "runner"
    runner.mkdir()
    (runner / "config_parser.py").write_text(textwrap.dedent("""\
        ARG_TO_ENV = {
            "cycle_time_ms": ("HVD_CYCLE_TIME_MS", str),
        }
        _FILE_SECTIONS = {
            "params": {"cycle-time-ms": "cycle_time_ms"},
        }
        """))
    (runner / "launch.py").write_text(textwrap.dedent("""\
        import argparse
        def parse_args():
            ap = argparse.ArgumentParser()
            ap.add_argument("--cycle-time-ms", type=float, default=None)
            return ap.parse_args()
        """))
    return tmp_path


def _by_rule(violations, rule):
    return [v for v in violations if v.rule == rule]


def test_fixture_tree_is_clean(tmp_path):
    # The skeleton itself must be green or every seeded-violation assert
    # below would be ambiguous.
    root = str(_seed_repo(tmp_path))
    assert hvdlint.run(root) == [], \
        "\n".join(str(v) for v in hvdlint.run(root))


def test_undocumented_knob_is_reported(tmp_path):
    root = _seed_repo(tmp_path)
    py = root / "horovod_tpu" / "knobby.py"
    py.write_text('import os\nTHRESH = os.environ.get("HVD_SEEDED_KNOB")\n')
    vs = _by_rule(hvdlint.run(str(root)), "knob-docs")
    assert len(vs) == 1, [str(v) for v in vs]
    v = vs[0]
    assert v.symbol == "HVD_SEEDED_KNOB"
    assert v.path == os.path.join("horovod_tpu", "knobby.py")
    assert v.line == 2
    # Documenting it in either doc clears the violation.
    (root / "docs" / "perf_tuning.md").write_text("`HVD_SEEDED_KNOB`\n")
    assert _by_rule(hvdlint.run(str(root)), "knob-docs") == []


def test_environ_write_is_not_a_read(tmp_path):
    root = _seed_repo(tmp_path)
    (root / "horovod_tpu" / "writer.py").write_text(
        'import os\nos.environ["HVD_WRITTEN"] = "1"\n'
        'del os.environ["HVD_WRITTEN"]\n')
    assert _by_rule(hvdlint.run(str(root)), "knob-docs") == []


def test_yaml_cli_mismatch_is_reported(tmp_path):
    root = _seed_repo(tmp_path)
    # Seed an env mapping whose dest exists in neither the CLI nor YAML.
    (root / "horovod_tpu" / "runner" / "config_parser.py").write_text(
        textwrap.dedent("""\
            ARG_TO_ENV = {
                "cycle_time_ms": ("HVD_CYCLE_TIME_MS", str),
                "orphan_knob": ("HVD_ORPHAN", str),
            }
            _FILE_SECTIONS = {
                "params": {"cycle-time-ms": "cycle_time_ms"},
            }
            """))
    vs = _by_rule(hvdlint.run(str(root)), "config-parity")
    assert {v.symbol for v in vs} == {"orphan_knob"}
    msgs = " | ".join(v.message for v in vs)
    assert "no CLI flag" in msgs and "no YAML key" in msgs
    assert all(v.path == os.path.join(
        "horovod_tpu", "runner", "config_parser.py") for v in vs)


def test_yaml_key_without_env_mapping_is_reported(tmp_path):
    root = _seed_repo(tmp_path)
    (root / "horovod_tpu" / "runner" / "config_parser.py").write_text(
        textwrap.dedent("""\
            ARG_TO_ENV = {
                "cycle_time_ms": ("HVD_CYCLE_TIME_MS", str),
            }
            _FILE_SECTIONS = {
                "params": {"cycle-time-ms": "cycle_time_ms",
                           "ghost-key": "ghost_attr"},
            }
            """))
    vs = _by_rule(hvdlint.run(str(root)), "config-parity")
    assert [v.symbol for v in vs] == ["ghost_attr"]
    assert "missing from ARG_TO_ENV" in vs[0].message


def test_stray_getenv_is_reported(tmp_path):
    root = _seed_repo(tmp_path)
    tcp = root / "horovod_tpu" / "csrc" / "tcp.cc"
    tcp.write_text('const char* s = std::getenv("PATH");\n')
    vs = _by_rule(hvdlint.run(str(root)), "raw-getenv")
    assert len(vs) == 1
    assert vs[0].path == os.path.join("horovod_tpu", "csrc", "tcp.cc")
    assert vs[0].line == 1
    assert "EnvRaw" in vs[0].message
    # logging.h itself stays exempt (EnvRaw's implementation site).
    assert not any(v.path.endswith("logging.h") for v in vs)


def test_missing_arm_stats_is_reported(tmp_path):
    root = _seed_repo(tmp_path)
    (root / "horovod_tpu" / "csrc" / "common.h").write_text(
        "struct Tuned { int8_t tuned_cache; int8_t tuned_newarm; };\n")
    vs = _by_rule(hvdlint.run(str(root)), "arm-stats")
    assert [v.symbol for v in vs] == ["tuned_newarm"]
    assert "newarm_stats()" in vs[0].message


def test_csv_schema_skew_is_reported(tmp_path):
    # The C++ writer's header literal and the shared Python schema table
    # (observability/autotune_csv.py COLUMNS) must agree exactly — a
    # drifted column order silently skews every by-name consumer.
    root = _seed_repo(tmp_path)
    csrc = root / "horovod_tpu" / "csrc"
    (csrc / "autotune.cc").write_text(
        'void Hdr() { fprintf(f, "sample,cache,score_mbps\\n"); }\n')
    obs = root / "horovod_tpu" / "observability"
    obs.mkdir()
    (obs / "autotune_csv.py").write_text(
        'COLUMNS = ("sample", "cache", "score_mbps")\n')
    assert _by_rule(hvdlint.run(str(root)), "arm-stats") == []
    (obs / "autotune_csv.py").write_text(
        'COLUMNS = ("sample", "hier", "score_mbps")\n')
    vs = _by_rule(hvdlint.run(str(root)), "arm-stats")
    assert len(vs) == 1 and vs[0].symbol == "COLUMNS", vs
    assert "header literal" in vs[0].message


def test_counter_after_complete_is_reported(tmp_path):
    root = _seed_repo(tmp_path)
    (root / "horovod_tpu" / "csrc" / "core.cc").write_text(
        textwrap.dedent("""\
            #include "logging.h"
            void ExecAllreduce() {
              CompleteHandle(h);
              g->zerocopy_total++;
              return;
            }
            """))
    vs = _by_rule(hvdlint.run(str(root)), "counter-order")
    assert len(vs) == 1
    assert "AFTER CompleteHandle" in vs[0].message
    assert vs[0].path == os.path.join("horovod_tpu", "csrc", "core.cc")


def test_counter_order_segments_reset_at_return(tmp_path):
    # A counter on a LATER return-delimited path must not be graded
    # against an earlier path's CompleteHandle.
    root = _seed_repo(tmp_path)
    (root / "horovod_tpu" / "csrc" / "core.cc").write_text(
        textwrap.dedent("""\
            #include "logging.h"
            void ExecAllreduce() {
              if (fast) {
                CompleteHandle(h);
                return;
              }
              g->staged_total++;
              CompleteHandle(h);
              return;
            }
            """))
    assert _by_rule(hvdlint.run(str(root)), "counter-order") == []


def test_renamed_exec_allreduce_fails_loud(tmp_path):
    # If the anchor function disappears the check must FAIL, not silently
    # grade nothing.
    root = _seed_repo(tmp_path)
    (root / "horovod_tpu" / "csrc" / "core.cc").write_text(
        "void ExecReduceV2() {}\n")
    vs = _by_rule(hvdlint.run(str(root)), "counter-order")
    assert len(vs) == 1
    assert "not found" in vs[0].message


@pytest.mark.parametrize("snippet,knob", [
    ('import os\nv = os.getenv("HVD_GETENV_FORM")\n', "HVD_GETENV_FORM"),
    ('import os as _os\nv = _os.environ.get("HVD_ALIASED")\n',
     "HVD_ALIASED"),
    ('import os\nv = os.environ["HVD_SUBSCRIPT"]\n', "HVD_SUBSCRIPT"),
])
def test_python_read_forms_are_collected(tmp_path, snippet, knob):
    root = _seed_repo(tmp_path)
    (root / "horovod_tpu" / "forms.py").write_text(snippet)
    reads = {k for k, _, _ in hvdlint.collect_knob_reads(str(root))}
    assert knob in reads
