"""Model zoo tests (virtual 8-device CPU mesh; see conftest.py).

Mirrors the reference's benchmark-model smoke coverage and adds what the
reference never had: sharded-training correctness for tp/sp/ep layouts.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.models import resnet, transformer as tfm

try:  # jax >= 0.8 probe (the PR 13 shard_map gate): the sharded-forward
    # tests also need modern XLA's sharded-matmul numerics — on 0.4.37
    # the virtual-CPU-mesh bf16 reduction order drifts past tolerance.
    from jax import shard_map as _shard_map  # noqa: F401
    _HAVE_SHARD_MAP = True
except ImportError:
    _HAVE_SHARD_MAP = False

try:  # the pallas kernels target jax >= 0.8's pltpu.CompilerParams API
    from jax.experimental.pallas import tpu as _pltpu
    _HAVE_PALLAS = hasattr(_pltpu, "CompilerParams")
except Exception:  # noqa: BLE001
    _HAVE_PALLAS = False

_needs_modern_jax = pytest.mark.skipif(
    not _HAVE_SHARD_MAP,
    reason="jax.shard_map unavailable (jax < 0.8): sharded-mesh "
           "semantics differ here")
_needs_pallas = pytest.mark.skipif(
    not _HAVE_PALLAS,
    reason="pltpu.CompilerParams unavailable (jax < 0.8): the pallas "
           "kernels cannot build here")


def test_resnet50_forward_shapes():
    model, variables = resnet.create_train_state(
        jax.random.PRNGKey(0), image_size=64, num_classes=10)
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    logits = jax.jit(lambda v, x: model.apply(v, x, train=False))(
        variables, x)
    assert logits.shape == (2, 10)
    assert np.isfinite(np.asarray(logits)).all()


def test_resnet50_s2d_stem():
    """The space-to-depth stem (models/resnet.py stem="s2d" — the
    MLPerf-closed equivalent-weights rearrangement used by the TPU
    benchmark) produces the same output geometry as the classic 7x7/2
    stem and trains with finite gradients."""
    model, variables = resnet.create_train_state(
        jax.random.PRNGKey(0), image_size=64, num_classes=10, stem="s2d")
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    logits = jax.jit(lambda v, x: model.apply(v, x, train=False))(
        variables, x)
    assert logits.shape == (2, 10)
    # Stem kernel is 4x4x12 (2x2 space-to-depth of 3 channels).
    k = variables["params"]["conv_init"]["kernel"]
    assert k.shape[:3] == (4, 4, 12), k.shape

    def loss(params):
        out, _ = model.apply(
            {"params": params,
             "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        return jnp.mean(out ** 2)

    grads = jax.jit(jax.grad(loss))(variables["params"])
    flat = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)


def test_transformer_forward_and_loss():
    cfg = tfm.tiny()
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 17)),
        jnp.int32)
    loss = jax.jit(lambda p, b: tfm.loss_fn(p, b, cfg))(
        params, {"tokens": tokens})
    assert np.isfinite(float(loss))


def test_transformer_moe_matches_dense_expert():
    """With 1 expert, MoE must equal the dense FFN given identical weights."""
    cfg_d = tfm.tiny(n_experts=0)
    cfg_m = tfm.tiny(n_experts=1)
    p = tfm.init_params(jax.random.PRNGKey(0), cfg_d)
    pm = tfm.init_params(jax.random.PRNGKey(0), cfg_m)
    for ld, lm in zip(p["layers"], pm["layers"]):
        lm["w_in"] = ld["w_in"][None]
        lm["w_out"] = ld["w_out"][None]
    for k in ("embed", "pos_embed", "final_ln"):
        pm[k] = p[k]
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg_d.vocab_size, (2, 9)),
        jnp.int32)
    out_d = tfm.forward(p, tokens, cfg_d)
    out_m = tfm.forward(pm, tokens, cfg_m)
    np.testing.assert_allclose(np.asarray(out_d, np.float32),
                               np.asarray(out_m, np.float32),
                               rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("axes", [
    {"data": 8},
    {"data": 2, "model": 4},
    {"data": 2, "seq": 2, "model": 2},
])
@_needs_modern_jax
def test_transformer_sharded_matches_single_device(axes):
    """tp/sp/ep-sharded forward == single-device forward (same params)."""
    import dataclasses
    # 8 experts: divisible by the expert-carrying axis in every mesh below
    cfg = dataclasses.replace(tfm.tiny(n_experts=8), expert_axis="data")
    params = tfm.init_params(jax.random.PRNGKey(2), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, (8, 16)),
        jnp.int32)
    ref = tfm.forward(params, tokens, cfg)

    sizes = list(axes.values())
    mesh = Mesh(np.asarray(jax.devices()[:int(np.prod(sizes))])
                .reshape(sizes), tuple(axes.keys()))
    specs = tfm.filter_specs(tfm.param_specs(cfg), mesh)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))
    sharded = jax.device_put(params, shardings)
    tok_sh = jax.device_put(
        tokens, NamedSharding(mesh, P("data" if "data" in axes else None,
                                      None)))
    out = jax.jit(lambda p, t: tfm.forward(p, t, cfg, mesh=mesh))(
        sharded, tok_sh)
    np.testing.assert_allclose(np.asarray(ref, np.float32),
                               np.asarray(out, np.float32),
                               rtol=3e-2, atol=3e-2)


@_needs_modern_jax
def test_graft_entry_dryrun():
    import __graft_entry__ as g
    g.dryrun_multichip(8)


@_needs_modern_jax
def test_transformer_ring_attention_matches_gather():
    """attn_impl='ring' (sequence-parallel K/V rotation) must equal the
    gather implementation on the same sharded mesh."""
    import dataclasses

    cfg = dataclasses.replace(tfm.tiny(), attn_impl="ring")
    cfg_g = tfm.tiny()
    params = tfm.init_params(jax.random.PRNGKey(5), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(6).integers(0, cfg.vocab_size, (4, 16)),
        jnp.int32)
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 2, 2),
                ("data", "seq", "model"))
    specs = tfm.filter_specs(tfm.param_specs(cfg), mesh)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))
    sharded = jax.device_put(params, shardings)
    tok_sh = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
    out_ring = jax.jit(
        lambda p, t: tfm.forward(p, t, cfg, mesh=mesh))(sharded, tok_sh)
    out_gather = jax.jit(
        lambda p, t: tfm.forward(p, t, cfg_g, mesh=mesh))(sharded, tok_sh)
    np.testing.assert_allclose(np.asarray(out_ring, np.float32),
                               np.asarray(out_gather, np.float32),
                               rtol=3e-2, atol=3e-2)


@_needs_pallas
def test_pallas_norm_matches_reference():
    """ops/pallas_norm paired_reduce + batch_norm_train: forward and all
    three gradients must match the naive XLA batch norm (the kernels are
    the measured PERF.md round-4 experiment; norm='pallas' exposes them in
    ResNet)."""
    from horovod_tpu.ops.pallas_norm import batch_norm_train, paired_reduce

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 8, 8, 16)), jnp.float32)
    g = jnp.asarray(rng.standard_normal(16), jnp.float32)
    b = jnp.asarray(rng.standard_normal(16), jnp.float32)

    s, p = paired_reduce(x, x, interpret=True)
    np.testing.assert_allclose(np.asarray(s),
                               np.asarray(x).reshape(-1, 16).sum(0),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(p),
                               (np.asarray(x).reshape(-1, 16) ** 2).sum(0),
                               rtol=1e-5)

    def ref(x, g, b):
        mu = jnp.mean(x, (0, 1, 2))
        var = jnp.var(x, (0, 1, 2))
        return ((x - mu) * jax.lax.rsqrt(var + 1e-5)) * g + b

    def pal(x, g, b):
        y, _, _ = batch_norm_train(x, g, b, 1e-5, True)
        return y

    np.testing.assert_allclose(np.asarray(pal(x, g, b)),
                               np.asarray(ref(x, g, b)),
                               rtol=2e-4, atol=2e-4)
    w = jnp.cos(jnp.arange(16.0))
    gr = jax.grad(lambda *a: jnp.sum(ref(*a) ** 2 * w), (0, 1, 2))(x, g, b)
    gp = jax.grad(lambda *a: jnp.sum(pal(*a) ** 2 * w), (0, 1, 2))(x, g, b)
    for a_, b_, n in zip(gr, gp, "xgb"):
        np.testing.assert_allclose(np.asarray(a_), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3, err_msg=f"d{n}")


def test_bf16stats_norm_matches_flax_bn():
    """Bf16StatsBatchNorm (bf16 partial stats accumulation, f32
    finalization — the VERDICT r5 weak-#1 bench variant): identical
    variable structure to nn.BatchNorm, train-mode output within bf16
    rounding of the f32-stats reference, running stats updated."""
    import flax.linen as nn

    from horovod_tpu.models import resnet

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((4, 8, 8, 16)), jnp.float32)
    kw = dict(use_running_average=False, momentum=0.9, epsilon=1e-5,
              dtype=jnp.bfloat16, param_dtype=jnp.float32)
    ref_m, new_m = nn.BatchNorm(**kw), resnet.Bf16StatsBatchNorm(**kw)
    ref_v = ref_m.init(jax.random.PRNGKey(0), x)
    new_v = new_m.init(jax.random.PRNGKey(0), x)
    assert (jax.tree_util.tree_structure(ref_v)
            == jax.tree_util.tree_structure(new_v))
    y_ref, ref_s = ref_m.apply(ref_v, x, mutable=["batch_stats"])
    y_new, new_s = new_m.apply(new_v, x, mutable=["batch_stats"])
    # bf16 accumulation over 256 elements: tolerance is the variant's
    # honest precision cost, not a bug bar.
    np.testing.assert_allclose(np.asarray(y_new, np.float32),
                               np.asarray(y_ref, np.float32),
                               rtol=0.1, atol=0.1)
    assert not np.allclose(
        np.asarray(new_s["batch_stats"]["mean"], np.float32), 0.0)


def _resnet_norm_trains(norm):
    """Shared body: ResNet(norm=...) runs a training step end-to-end
    (interpret mode on CPU) and produces finite loss + updated stats."""
    import optax

    from horovod_tpu.models import resnet

    model, variables = resnet.create_train_state(
        jax.random.PRNGKey(0), image_size=32, num_classes=10,
        norm=norm)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1)
    opt_state = tx.init(params)

    def loss_fn(params, batch_stats, images, labels):
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        return resnet.cross_entropy_loss(logits, labels), \
            updates["batch_stats"]

    @jax.jit
    def step(params, batch_stats, opt_state, images, labels):
        (loss, batch_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), batch_stats, \
            opt_state, loss

    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.standard_normal((4, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, (4,)), jnp.int32)
    before = np.asarray(
        batch_stats["bn_init"]["mean"], np.float32).copy()
    params, batch_stats, opt_state, loss = step(
        params, batch_stats, opt_state, images, labels)
    assert np.isfinite(float(loss)), loss
    after = np.asarray(batch_stats["bn_init"]["mean"], np.float32)
    assert not np.allclose(before, after), "running stats never updated"


@_needs_pallas
def test_resnet_pallas_norm_trains():
    _resnet_norm_trains("pallas")


def test_resnet_bf16stats_norm_trains():
    _resnet_norm_trains("bf16stats")
