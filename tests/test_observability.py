"""Observability subsystem (horovod_tpu/observability/): metrics registry
semantics, disabled-path no-op guarantees, the Python-side stall
inspector, span recording + Chrome-trace merge, and the /metrics
endpoints — plus the 2-process acceptance run (real collectives must
surface as nonzero series and a mergeable timeline)."""

import json
import os
import subprocess
import sys
import time
import types
import urllib.error
import urllib.request

import pytest

from horovod_tpu.observability import metrics, spans, stall
from horovod_tpu.runner import config_parser, http_server

from .util import run_worker_job


@pytest.fixture
def metrics_on():
    """Enable the registry for one test; leave the process disabled and
    sample-free afterwards (tier-1 runs with HVD_METRICS unset)."""
    metrics.REGISTRY.clear()
    spans.recorder.clear()
    metrics.enable()
    yield
    metrics.disable()
    metrics.REGISTRY.clear()
    spans.recorder.clear()


# ---------------------------------------------------------------------------
# Registry semantics


def test_counter_semantics(metrics_on):
    c = metrics.counter("t_obs_counter", "help", ("op",))
    child = c.labels(op="allreduce")
    child.inc()
    child.inc(5)
    assert c.collect() == [(("allreduce",), {"value": 6.0})]
    with pytest.raises(ValueError):
        child.inc(-1)


def test_gauge_semantics(metrics_on):
    g = metrics.gauge("t_obs_gauge", "help")
    g.set(3.5)
    g.inc(2)
    g.dec(1)
    assert g.collect() == [((), {"value": 4.5})]


def test_histogram_semantics(metrics_on):
    h = metrics.histogram("t_obs_hist", "help", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    [(key, state)] = h.collect()
    assert key == ()
    assert state["buckets"] == [1, 1, 1, 1]  # one per bucket + +Inf
    assert state["count"] == 4
    assert state["sum"] == pytest.approx(55.55)


def test_register_idempotent_and_conflicts(metrics_on):
    a = metrics.counter("t_obs_idem", "h", ("op",))
    assert metrics.counter("t_obs_idem", "h", ("op",)) is a
    with pytest.raises(ValueError):
        metrics.gauge("t_obs_idem")  # type change
    with pytest.raises(ValueError):
        metrics.counter("t_obs_idem", "h", ("other",))  # label change


def test_label_isolation(metrics_on):
    c = metrics.counter("t_obs_labels", "h", ("op", "process_set"))
    c.labels(op="allreduce", process_set="0").inc(7)
    c.labels(op="allreduce", process_set="1").inc(1)
    c.labels(op="allgather", process_set="0").inc(2)
    got = dict((k, v["value"]) for k, v in c.collect())
    assert got == {("allreduce", "0"): 7.0, ("allreduce", "1"): 1.0,
                   ("allgather", "0"): 2.0}
    with pytest.raises(ValueError):
        c.labels(op="allreduce")  # missing a label
    with pytest.raises(ValueError):
        c.labels(op="x", process_set="0", extra="y")


def test_render_text_exposition(metrics_on):
    c = metrics.counter("t_obs_render", "counts stuff", ("op",))
    c.labels(op="a").inc(3)
    h = metrics.histogram("t_obs_render_h", "times stuff",
                          buckets=(0.5, 1.0))
    h.observe(0.2)
    h.observe(2.0)
    text = metrics.render_text()
    assert "# HELP t_obs_render counts stuff" in text
    assert "# TYPE t_obs_render counter" in text
    assert '\nt_obs_render{op="a"} 3\n' in text
    # Histogram: cumulative buckets, +Inf, _sum, _count.
    assert '\nt_obs_render_h_bucket{le="0.5"} 1\n' in text
    assert '\nt_obs_render_h_bucket{le="1"} 1\n' in text
    assert '\nt_obs_render_h_bucket{le="+Inf"} 2\n' in text
    assert "\nt_obs_render_h_sum 2.2\n" in text
    assert "\nt_obs_render_h_count 2\n" in text
    # Every sample line must be "<name>{labels}? <float>".
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_part, value = line.rsplit(" ", 1)
        assert name_part[0].isalpha() or name_part[0] == "_", line
        float(value)  # must parse


def test_snapshot_shape(metrics_on):
    metrics.OP_CALLS.labels(op="allreduce", process_set="0").inc()
    snap = metrics.snapshot()
    fam = snap["hvd_op_calls_total"]
    assert fam["type"] == "counter"
    assert fam["samples"] == [
        {"labels": {"op": "allreduce", "process_set": "0"}, "value": 1.0}]
    json.dumps(snap)  # must be JSON-able (bench.py attaches it)


def test_record_call_families(metrics_on):
    metrics.record_call("allreduce", 0.01, 4096, process_set=3)
    snap = metrics.snapshot()
    assert snap["hvd_op_calls_total"]["samples"][0]["labels"] == {
        "op": "allreduce", "process_set": "3"}
    assert snap["hvd_op_bytes_total"]["samples"][0]["value"] == 4096
    lat = snap["hvd_op_latency_seconds"]["samples"][0]
    assert lat["count"] == 1 and lat["sum"] == pytest.approx(0.01)


# ---------------------------------------------------------------------------
# Disabled path: one flag check — no locks, no samples, no jax.


class _PoisonLock:
    def __enter__(self):
        raise AssertionError("lock acquired on the disabled path")

    def __exit__(self, *exc):
        return False

    def acquire(self, *a, **k):
        raise AssertionError("lock acquired on the disabled path")

    def release(self):
        pass


def test_disabled_path_touches_no_lock():
    assert not metrics.enabled()
    c = metrics.OP_CALLS
    real = c._lock
    c._lock = _PoisonLock()
    try:
        child = c.labels(op="allreduce", process_set="0")
        assert child is metrics._NOOP_CHILD
        child.inc()
        c.inc()  # label-less convenience path
        metrics.OP_SECONDS._lock, real_h = _PoisonLock(), \
            metrics.OP_SECONDS._lock
        try:
            metrics.OP_SECONDS.labels(op="x", process_set="0").observe(1.0)
        finally:
            metrics.OP_SECONDS._lock = real_h
    finally:
        c._lock = real
    assert c.collect() == []  # nothing recorded


def test_disabled_span_is_shared_nullcontext():
    assert not metrics.enabled()
    real = spans.recorder._lock
    spans.recorder._lock = _PoisonLock()
    try:
        cm1 = spans.span("x")
        cm2 = spans.span("y", step=1)
        assert cm1 is cm2 is spans._NOOP  # no per-call allocation
        with cm1:
            pass
        spans.instant("z")
    finally:
        spans.recorder._lock = real
    assert spans.recorder.events() == []


def test_disabled_instrumented_op_skips_metrics(monkeypatch):
    from horovod_tpu.ops import collective_ops

    assert not metrics.enabled()

    def boom(*a, **k):
        raise AssertionError("record_call reached on the disabled path")

    monkeypatch.setattr(metrics, "record_call", boom)
    wrapped = collective_ops._instrumented(lambda *a, **k: "sentinel",
                                           "allreduce")
    assert wrapped(object()) == "sentinel"


def test_observability_import_is_jax_free():
    """`import horovod_tpu.observability` (parent package included) must
    not pull jax — torch/TF-only workers and the bench's wedge-proof
    parent import it unconditionally."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("HVD_", "JAX_"))}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    code = ("import sys\n"
            "import horovod_tpu.observability\n"
            "import horovod_tpu.ops.collective_ops\n"
            "assert 'jax' not in sys.modules, 'jax leaked'\n")
    p = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stderr


# ---------------------------------------------------------------------------
# Stall inspector


def test_stall_inspector_fires_warn_then_shutdown():
    warns = []
    insp = stall.StallInspector(warning_sec=0.1, shutdown_sec=0.3,
                                check_interval=0.03,
                                on_warn=lambda n, dt: warns.append((n, dt)))
    try:
        insp.report_start("allreduce.0")
        deadline = time.monotonic() + 5.0
        while not warns and time.monotonic() < deadline:
            time.sleep(0.02)
        assert warns and warns[0][0] == "allreduce.0"
        assert warns[0][1] >= 0.1
        while not insp.shutdown_fired and time.monotonic() < deadline:
            time.sleep(0.02)
        assert insp.shutdown_fired
        # The watcher thread cannot raise into user code; the pending
        # error surfaces on the next check_shutdown() (instrumented
        # synchronize calls it).
        t0 = time.monotonic()
        while time.monotonic() - t0 < 2.0:
            try:
                insp.check_shutdown()
            except stall.StallError:
                break
            time.sleep(0.02)
        else:
            pytest.fail("pending StallError never surfaced")
        insp.check_shutdown()  # consumed — does not raise twice
    finally:
        insp.stop()


def test_stall_inspector_quiet_under_progress():
    warns = []
    insp = stall.StallInspector(warning_sec=0.25, shutdown_sec=-1,
                                check_interval=0.03,
                                on_warn=lambda n, dt: warns.append(n))
    try:
        insp.report_start("allgather.0")
        t0 = time.monotonic()
        while time.monotonic() - t0 < 0.6:
            insp.report_progress("allgather.0")
            time.sleep(0.02)
        assert warns == []
        insp.report_done("allgather.0")
        assert insp.stalled() == []
        assert not insp.shutdown_fired
    finally:
        insp.stop()


def test_stall_warning_rearms_after_progress():
    warns = []
    insp = stall.StallInspector(warning_sec=0.08, shutdown_sec=-1,
                                check_interval=0.02,
                                on_warn=lambda n, dt: warns.append(n))
    try:
        insp.report_start("op.x")
        deadline = time.monotonic() + 5.0
        while len(warns) < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(warns) == 1
        insp.report_progress("op.x")  # re-arms the episode
        while len(warns) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(warns) == 2 and set(warns) == {"op.x"}
    finally:
        insp.stop()


def test_stalled_view_sorted_worst_first():
    insp = stall.StallInspector(warning_sec=-1, shutdown_sec=-1,
                                check_interval=10)
    try:
        insp.report_start("old")
        time.sleep(0.05)
        insp.report_start("new")
        view = insp.stalled()
        assert [n for n, _ in view] == ["old", "new"]
        assert view[0][1] >= view[1][1]
    finally:
        insp.stop()


def test_stall_warning_increments_metric(metrics_on):
    insp = stall.StallInspector(warning_sec=0.05, shutdown_sec=-1,
                                check_interval=0.02)
    try:
        insp.report_start("op.y")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            snap = metrics.snapshot()["hvd_stall_warnings_total"]["samples"]
            if any(sm["labels"] == {"op": "op.y"} and sm["value"] >= 1
                   for sm in snap):
                break
            time.sleep(0.02)
        else:
            pytest.fail("hvd_stall_warnings_total never incremented")
    finally:
        insp.stop()


def test_stall_configure_reloads_thresholds():
    """configure() swaps thresholds at runtime: a tighter warning fires on
    the next scan, and loosening the shutdown threshold clears a pending
    (not-yet-raised) StallError decided under the old one."""
    warns = []
    insp = stall.StallInspector(warning_sec=100, shutdown_sec=-1,
                                check_interval=100,
                                on_warn=lambda n, dt: warns.append(n))
    try:
        insp.report_start("op.cfg")
        later = time.monotonic() + 5.0
        insp._scan(now=later)
        assert warns == []  # 5s stall, 100s threshold
        insp.configure(warning_sec=1.0)
        insp._scan(now=later)
        assert "op.cfg" in warns
        # Tighten shutdown -> verdict; loosen -> pending error withdrawn.
        insp.configure(shutdown_sec=1.0)
        insp._scan(now=later)
        assert insp.shutdown_fired
        insp.configure(shutdown_sec=1000.0)
        assert not insp.shutdown_fired
        insp.check_shutdown()  # must not raise
    finally:
        insp.stop()


def test_stall_mark_rank_evicted_clears_attributed_ops():
    """Eviction hygiene: ops attributed to an evicted rank leave the stall
    set, later reports for that rank are ignored, and a pending shutdown
    verdict (the stall WAS the dead peer) is withdrawn."""
    insp = stall.StallInspector(warning_sec=-1, shutdown_sec=1.0,
                                check_interval=100)
    try:
        insp.report_start("send.2", rank=2)
        insp.report_start("send.3", rank=3)
        insp.report_start("local.op")
        insp._scan(now=time.monotonic() + 5.0)
        assert insp.shutdown_fired
        insp.mark_rank_evicted(2)
        assert insp.evicted_ranks() == {2}
        assert [n for n, _ in insp.stalled()] \
            and "send.2" not in dict(insp.stalled())
        assert "send.3" in dict(insp.stalled())
        # the eviction superseded the verdict
        assert not insp.shutdown_fired
        insp.check_shutdown()  # must not raise
        insp.report_start("send2.2", rank=2)
        assert "send2.2" not in dict(insp.stalled())
        insp.reset()
        assert insp.evicted_ranks() == set() and insp.stalled() == []
    finally:
        insp.stop()


# ---------------------------------------------------------------------------
# Spans + merge


def test_span_records_complete_events(metrics_on):
    with spans.span("step", step=3):
        time.sleep(0.01)
    spans.instant("marker", epoch=1)
    evs = spans.recorder.events()
    assert len(evs) == 2
    x = next(e for e in evs if e["ph"] == "X")
    assert x["name"] == "step" and x["dur"] >= 10_000 // 2  # µs
    assert x["pid"] == os.getpid() and x["args"] == {"step": 3}
    i = next(e for e in evs if e["ph"] == "i")
    assert i["name"] == "marker" and i["s"] == "p"


def test_dump_and_merge_sorted(tmp_path, metrics_on):
    with spans.span("py.work"):
        pass
    py = spans.dump(str(tmp_path / "py.json"))
    # A core-style timeline: bare JSON array, rank as pid.
    core_events = [
        {"name": "NEGOTIATE_ALLREDUCE", "ph": "X", "ts": 5, "dur": 10,
         "pid": 0, "tid": "t.0"},
        {"name": "cycle", "ph": "i", "ts": 1, "pid": 0, "s": "p"},
    ]
    core = tmp_path / "core.json"
    core.write_text(json.dumps(core_events))
    out = spans.merge_traces(str(tmp_path / "merged.json"), str(core), py)
    data = json.loads((tmp_path / "merged.json").read_text())
    assert out == str(tmp_path / "merged.json")
    evs = data["traceEvents"]
    assert len(evs) == 3
    assert [e.get("ts", 0) for e in evs] == sorted(
        e.get("ts", 0) for e in evs)
    assert {e["name"] for e in evs} == {"NEGOTIATE_ALLREDUCE", "cycle",
                                        "py.work"}


def test_merge_repairs_truncated_core_file(tmp_path):
    # The core writer only emits the closing ] at Shutdown — a file
    # snapshotted mid-job ends with a trailing comma.
    truncated = ('[\n{"name": "a", "ph": "X", "ts": 1, "dur": 2, '
                 '"pid": 0, "tid": "t"},\n'
                 '{"name": "b", "ph": "i", "ts": 3, "pid": 0, "s": "p"},\n')
    p = tmp_path / "trunc.json"
    p.write_text(truncated)
    out = tmp_path / "merged.json"
    spans.merge_traces(str(out), str(p))
    evs = json.loads(out.read_text())["traceEvents"]
    assert [e["name"] for e in evs] == ["a", "b"]


def test_merge_rejects_garbage(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text("this is not a trace {{{")
    with pytest.raises(ValueError, match="not parseable"):
        spans.merge_traces(str(tmp_path / "out.json"), str(p))


# ---------------------------------------------------------------------------
# /metrics endpoints


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, dict(resp.headers), resp.read().decode()


def test_rendezvous_server_serves_metrics_unsigned(metrics_on):
    metrics.OP_CALLS.labels(op="allreduce", process_set="0").inc(2)
    srv = http_server.RendezvousServer(secret_key=b"sekrit",
                                       addr="127.0.0.1")
    port = srv.start(0)
    try:
        status, headers, body = _get(f"http://127.0.0.1:{port}/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert 'hvd_op_calls_total{op="allreduce",process_set="0"} 2' \
            in body
        # KV paths still demand the HMAC signature.
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{port}/scope/key")
        assert ei.value.code == 403
    finally:
        srv.stop()


def test_metrics_server_standalone(metrics_on):
    metrics.ELASTIC_EVENTS.labels(event="reset").inc()
    srv = http_server.MetricsServer(addr="127.0.0.1")
    port = srv.start(0)
    try:
        status, _, body = _get(f"http://127.0.0.1:{port}/metrics")
        assert status == 200
        assert 'hvd_elastic_events_total{event="reset"} 1' in body
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{port}/anything-else")
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_maybe_start_endpoint_disabled_is_noop(monkeypatch):
    from horovod_tpu import observability as obs

    assert not metrics.enabled()
    monkeypatch.setenv("HVD_METRICS_PORT", "9090")
    assert obs.maybe_start_endpoint() is None  # gate: metrics off


def test_maybe_start_endpoint_ephemeral(monkeypatch, metrics_on):
    from horovod_tpu import observability as obs

    monkeypatch.setenv("HVD_METRICS_PORT", "0")
    monkeypatch.setattr(obs, "_endpoint", None)
    port = obs.maybe_start_endpoint()
    try:
        assert port and port > 0
        status, _, body = _get(f"http://127.0.0.1:{port}/metrics")
        assert status == 200 and "# TYPE" in body
    finally:
        obs.stop_endpoint()


# ---------------------------------------------------------------------------
# Config plumbing


def test_config_args_to_env_metrics_keys():
    args = types.SimpleNamespace(metrics=True, metrics_port=9090)
    env = config_parser.args_to_env(args)
    assert env["HVD_METRICS"] == "1"
    assert env["HVD_METRICS_PORT"] == "9090"
    # Unset/False stays out of the env entirely.
    env = config_parser.args_to_env(types.SimpleNamespace(metrics=False))
    assert "HVD_METRICS" not in env


def test_config_file_metrics_section(tmp_path):
    pytest.importorskip("yaml")
    cfg = tmp_path / "hvd.yaml"
    cfg.write_text("metrics:\n  enable: true\n  port: 9100\n")
    args = types.SimpleNamespace(metrics=None, metrics_port=None)
    config_parser.apply_config_file(args, str(cfg))
    assert args.metrics is True and args.metrics_port == 9100
    env = config_parser.args_to_env(args)
    assert env["HVD_METRICS"] == "1" and env["HVD_METRICS_PORT"] == "9100"


# ---------------------------------------------------------------------------
# Instrumented op layer (in-process, no core init needed)


def test_instrumented_records_bytes_latency_and_labels(metrics_on):
    np = pytest.importorskip("numpy")
    from horovod_tpu.ops import collective_ops

    wrapped = collective_ops._instrumented(lambda *a, **k: "ok",
                                           "allreduce")
    x = np.ones(100, dtype=np.float32)
    assert wrapped(x) == "ok"
    assert wrapped(x, process_set=3) == "ok"
    snap = metrics.snapshot()
    by_ps = {sm["labels"]["process_set"]: sm["value"]
             for sm in snap["hvd_op_bytes_total"]["samples"]
             if sm["labels"]["op"] == "allreduce"}
    assert by_ps == {"0": 400.0, "3": 400.0}
    lat = [sm for sm in snap["hvd_op_latency_seconds"]["samples"]
           if sm["labels"]["op"] == "allreduce"]
    assert sum(sm["count"] for sm in lat) == 2


# ---------------------------------------------------------------------------
# End-to-end: the ISSUE acceptance criterion.


def test_two_process_collectives_expose_metrics_and_merged_trace(tmp_path):
    run_worker_job(2, "observability_worker.py",
                   extra_env={"HVD_METRICS": "1",
                              "HVD_TIMELINE": str(tmp_path / "tl.json"),
                              "OBS_TEST_DIR": str(tmp_path)},
                   timeout=180)
    merged = tmp_path / "merged.json"
    assert merged.exists(), "rank 0 never wrote the merged trace"
    events = json.loads(merged.read_text())["traceEvents"]
    assert events and all("name" in e for e in events)


# ---------------------------------------------------------------------------
# Bounded build-lock acquisition (stall-proofing `import horovod_tpu`: an
# orphaned build worker holding csrc/.build.lock must not wedge every
# later import on the machine).


def test_build_lock_acquire_times_out_when_held(tmp_path):
    import fcntl

    from horovod_tpu import _build_lock

    path = tmp_path / "lock"
    holder = open(path, "w")
    fcntl.flock(holder, fcntl.LOCK_EX)
    try:
        with open(path, "w") as lk:
            t0 = time.monotonic()
            assert _build_lock.acquire(lk, 0.3, poll=0.05) is False
            assert time.monotonic() - t0 < 5
    finally:
        holder.close()


def test_build_lock_acquire_takes_free_lock(tmp_path):
    import fcntl

    from horovod_tpu import _build_lock

    path = tmp_path / "lock"
    with open(path, "w") as lk:
        assert _build_lock.acquire(lk, 0.3, poll=0.05) is True
        # Held now: a second descriptor can't take it even non-blocking.
        with open(path, "w") as lk2, pytest.raises(OSError):
            fcntl.flock(lk2, fcntl.LOCK_EX | fcntl.LOCK_NB)
    # timeout <= 0 is the legacy block-forever path; on a free lock it
    # must return immediately.
    with open(path, "w") as lk:
        assert _build_lock.acquire(lk, 0) is True


def test_build_lock_timeout_from_env(monkeypatch):
    from horovod_tpu import _build_lock

    monkeypatch.delenv("HVD_BUILD_LOCK_TIMEOUT", raising=False)
    assert _build_lock.timeout_from_env() == 600.0
    monkeypatch.setenv("HVD_BUILD_LOCK_TIMEOUT", "12.5")
    assert _build_lock.timeout_from_env() == 12.5
    monkeypatch.setenv("HVD_BUILD_LOCK_TIMEOUT", "not-a-number")
    assert _build_lock.timeout_from_env() == 600.0
