"""Pipeline schedule tables (ISSUE 13) — pure-numpy tier-1 coverage.

The schedule machinery in horovod_tpu/parallel/schedules.py is
deliberately jax-free: the tables are trace-time numpy arrays the
compiled scan indexes, so every invariant here — occupancy orderings,
collision freedom, ZB weight-grad placement, knob parsing — is testable
without a jax install. The module is loaded standalone (the parallel
package __init__ imports jax; the tables don't need it), the same way
bench.py's schedule accounting loads it.

Execution parity (every schedule x stage count x dp vs the
single-device reference, outputs AND gradients) lives in
tests/test_pipeline.py, which needs the jax mesh.
"""
import importlib.util
import os

import numpy as np
import pytest

from .util import _REPO


def _load():
    path = os.path.join(_REPO, "horovod_tpu", "parallel", "schedules.py")
    spec = importlib.util.spec_from_file_location("schedules_under_test",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


sched = _load()

GRID = [(s, k * s) for s in (2, 4, 8) for k in (1, 2, 4)]


# ---------------------------------------------------------------------------
# resolve_schedule / knob parsing
# ---------------------------------------------------------------------------


def test_resolve_default_is_gpipe(monkeypatch):
    monkeypatch.delenv("HVD_PIPE_SCHEDULE", raising=False)
    assert sched.resolve_schedule() == ("gpipe", 1)


def test_resolve_env_knob(monkeypatch):
    monkeypatch.setenv("HVD_PIPE_SCHEDULE", "1f1b")
    assert sched.resolve_schedule() == ("1f1b", 1)
    monkeypatch.setenv("HVD_PIPE_SCHEDULE", "interleaved:4")
    assert sched.resolve_schedule() == ("interleaved", 4)


def test_resolve_arg_beats_env(monkeypatch):
    monkeypatch.setenv("HVD_PIPE_SCHEDULE", "zb")
    assert sched.resolve_schedule("gpipe") == ("gpipe", 1)


def test_resolve_interleaved_default_v():
    assert sched.resolve_schedule("interleaved") == ("interleaved", 2)
    assert sched.resolve_schedule("interleaved:3") == ("interleaved", 3)
    assert sched.resolve_schedule("interleaved", 4) == ("interleaved", 4)
    # explicit virtual_stages overrides the inline suffix
    assert sched.resolve_schedule("interleaved:3", 2) == ("interleaved", 2)


def test_resolve_rejects_unknown_name():
    with pytest.raises(ValueError, match="HVD_PIPE_SCHEDULE"):
        sched.resolve_schedule("pipedream")


def test_resolve_rejects_bad_virtual():
    with pytest.raises(ValueError, match="only 'interleaved'"):
        sched.resolve_schedule("1f1b:2")
    with pytest.raises(ValueError, match="virtual_stages >= 2"):
        sched.resolve_schedule("interleaved:1")
    with pytest.raises(ValueError, match="does not take virtual stages"):
        sched.resolve_schedule("zb", 2)


def test_schedule_label():
    assert sched.schedule_label("gpipe", 1) == "gpipe"
    assert sched.schedule_label("interleaved", 2) == "interleaved2"
    # comma-free: the label rides a comma-separated autotune CSV row
    for s in sched.VALID_SCHEDULES:
        assert "," not in sched.schedule_label(s, 2)


def test_suggest_n_microbatches():
    assert sched.suggest_n_microbatches(32, 5) == 4
    assert sched.suggest_n_microbatches(32, 7) == 8
    assert sched.suggest_n_microbatches(32, 9) == 8
    # exact divisor suggests itself; ties resolve to the larger divisor
    assert sched.suggest_n_microbatches(32, 8) == 8
    assert sched.suggest_n_microbatches(12, 5) == 6


# ---------------------------------------------------------------------------
# Table invariants
# ---------------------------------------------------------------------------


def test_interleave_permutation_layout():
    for s, v in ((2, 2), (4, 2), (4, 3), (8, 2)):
        perm = sched.interleave_permutation(s, v)
        assert sorted(perm) == list(range(s * v))
        for dev in range(s):
            chunk = perm[dev * v:(dev + 1) * v]
            # device `dev` holds the non-contiguous slices {dev, S+dev, ...}
            assert list(chunk) == [k * s + dev for k in range(v)]


@pytest.mark.parametrize("s,m", GRID)
def test_forward_tables_each_mb_once_per_virtual_stage(s, m):
    v = 2
    tab = sched._forward_tables(s, m, v)
    exec_mb, exec_chunk = tab["exec_mb"], tab["exec_chunk"]
    assert exec_mb.shape == (tab["T"], s)
    for dev in range(s):
        for k in range(v):
            mbs = exec_mb[(exec_mb[:, dev] >= 0)
                          & (exec_chunk[:, dev] == k), dev]
            assert sorted(mbs.tolist()) == list(range(m)), (dev, k)


@pytest.mark.parametrize("s,m", GRID)
def test_forward_tables_dependency_order(s, m):
    """Virtual stage j+1 never runs microbatch m before stage j did."""
    v = 2
    tab = sched._forward_tables(s, m, v)
    exec_mb, exec_chunk = tab["exec_mb"], tab["exec_chunk"]
    when = {}
    for t in range(tab["T"]):
        for dev in range(s):
            mb = int(exec_mb[t, dev])
            if mb >= 0:
                when[(int(exec_chunk[t, dev]) * s + dev, mb)] = t
    for (j, mb), t in when.items():
        if j > 0:
            assert when[(j - 1, mb)] < t, (j, mb)


@pytest.mark.parametrize("s,m", GRID)
def test_onef1b_tables_shape_and_order(s, m):
    tab = sched._onef1b_tables(s, m)
    assert tab["T"] == m + 2 * s - 2
    f_mb, b_mb = tab["f_mb"], tab["b_mb"]
    for dev in range(s):
        f_ticks = {int(f_mb[t, dev]): t for t in range(tab["T"])
                   if f_mb[t, dev] >= 0}
        b_ticks = {int(b_mb[t, dev]): t for t in range(tab["T"])
                   if b_mb[t, dev] >= 0}
        assert sorted(f_ticks) == list(range(m))
        assert sorted(b_ticks) == list(range(m))
        for mb in range(m):
            # B(m) never precedes F(m); equal only on the last stage,
            # whose in-tick loss vjp seeds the backward immediately.
            if dev == s - 1:
                assert b_ticks[mb] == f_ticks[mb]
            else:
                assert b_ticks[mb] > f_ticks[mb]


@pytest.mark.parametrize("s,m", GRID)
def test_zb_tables_weight_grad_placement(s, m):
    tab = sched._zb_tables(s, m)
    f_mb, b_mb, w_mb = tab["f_mb"], tab["b_mb"], tab["w_mb"]
    assert tab["w_ring"] >= 1
    for dev in range(s):
        placed = {}
        for t in range(tab["T"]):
            mb = int(w_mb[t, dev])
            if mb >= 0:
                assert mb not in placed, "Bw placed twice"
                placed[mb] = t
        assert sorted(placed) == list(range(m))
        for mb, t in placed.items():
            bx_t = 2 * s - 2 - dev + mb
            # Bw at or after its own Bx (co-located = 1F1B degenerate)
            assert t >= bx_t, (dev, mb)
            if t > bx_t:
                # a deferred Bw landed on a genuinely idle 1F1B slot
                assert f_mb[t, dev] < 0 and b_mb[t, dev] < 0


def test_zb_fills_cooldown_tail():
    """The cooldown idle ticks host deferred Bw work — the half-bubble
    ZB-H1 claims. The last stage of S=4, M=8 finishes its B wavefront
    S-1 ticks before the schedule ends; under 1F1B those trailing ticks
    idle, under zb they hold weight-grad work."""
    s, m = 4, 8
    one = sched._onef1b_tables(s, m)
    zb = sched._zb_tables(s, m)
    busy_1f1b = (one["f_mb"] >= 0) | (one["b_mb"] >= 0)
    busy_zb = busy_1f1b | (zb["w_mb"] >= 0)
    tail = slice(one["T"] - (s - 1), one["T"])
    assert busy_1f1b[tail, s - 1].sum() == 0
    assert busy_zb[tail, s - 1].sum() > 0


# ---------------------------------------------------------------------------
# Occupancy accounting: the acceptance orderings
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("s,m", GRID)
def test_bubble_orderings(s, m):
    gpipe = sched.schedule_info("gpipe", s, m)
    onef = sched.schedule_info("1f1b", s, m)
    zb = sched.schedule_info("zb", s, m)
    assert onef.bubble_fraction < gpipe.bubble_fraction
    assert zb.bubble_fraction <= onef.bubble_fraction
    if m == s:  # interleaved divides the bubble at M = S
        il = sched.schedule_info("interleaved", s, m, 2)
        assert il.bubble_fraction < onef.bubble_fraction


@pytest.mark.parametrize("s,m", GRID)
def test_measured_vs_ideal(s, m):
    """gpipe/interleaved measured == ideal exactly; 1f1b exact once
    M >= 2S-2 (below that, mid-schedule gaps make measured > ideal —
    the documented divergence); measured never beats ideal."""
    for name, v in (("gpipe", None), ("interleaved", 2), ("1f1b", None),
                    ("zb", None)):
        info = sched.schedule_info(name, s, m, v)
        assert info.bubble_fraction >= info.ideal_bubble - 1e-9, name
    gp = sched.schedule_info("gpipe", s, m)
    assert gp.bubble_fraction == pytest.approx(gp.ideal_bubble)
    il = sched.schedule_info("interleaved", s, m, 2)
    assert il.bubble_fraction == pytest.approx(il.ideal_bubble)
    if m >= 2 * s - 2:
        onef = sched.schedule_info("1f1b", s, m)
        assert onef.bubble_fraction == pytest.approx(onef.ideal_bubble)


@pytest.mark.parametrize("s,m", GRID)
def test_phases_partition_ticks(s, m):
    for name, v in (("gpipe", None), ("1f1b", None), ("interleaved", 2),
                    ("zb", None)):
        info = sched.schedule_info(name, s, m, v)
        assert (info.warmup_ticks + info.steady_ticks
                + info.cooldown_ticks) == info.ticks, name
        assert info.total_slots == info.ticks * s
        assert 0 < info.busy_slots <= info.total_slots


def test_schedule_info_as_dict():
    d = sched.schedule_info("interleaved", 4, 8, 2).as_dict()
    for key in ("schedule", "label", "stages", "n_microbatches",
                "virtual_stages", "ticks", "busy_slots", "total_slots",
                "bubble_fraction", "ideal_bubble", "warmup_ticks",
                "steady_ticks", "cooldown_ticks"):
        assert key in d
    assert d["label"] == "interleaved2"


def test_activation_residency_claim():
    """The 1F1B residency argument: at most 2S-1 microbatches are ever
    in flight (F issued, B not yet) on any stage — independent of M,
    unlike gpipe's O(M) — which is exactly the fused scan's
    max(1, 2S-1)-slot activation ring. Stage 0 attains the bound."""
    for s, m in GRID:
        tab = sched._onef1b_tables(s, m)
        worst = 0
        for dev in range(s):
            live = 0
            peak = 0
            for t in range(tab["T"]):
                if tab["f_mb"][t, dev] >= 0:
                    live += 1
                    peak = max(peak, live)
                if tab["b_mb"][t, dev] >= 0:
                    live -= 1
            assert peak <= 2 * s - 1, (s, m, dev, peak)
            worst = max(worst, peak)
        assert worst == min(m, 2 * s - 1), (s, m, worst)
