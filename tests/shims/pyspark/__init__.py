"""CI-only pyspark conformance shim (NOT part of horovod_tpu).

Implements the exact API surface ``horovod_tpu.spark.run`` consumes —
``SparkContext.getOrCreate``, ``sc.parallelize(...).barrier()
.mapPartitions(...).collect()``, ``BarrierTaskContext.get`` with
``partitionId`` / ``stageAttemptNumber`` / ``barrier`` — with the one
semantic that matters for a collective job: every barrier task runs
CONCURRENTLY in its own OS process (real Spark: one task per executor
slot). Tasks are shipped to children via cloudpickle like real pyspark
ships closures.

Used by tests/workers/spark_shim_worker.py (prepended to PYTHONPATH) so
the barrier/negotiation path of ``spark.run()`` executes end-to-end in
CI; real-cluster behavior (scheduling, locality, stage retries) is
explicitly NOT simulated. See README "Spark/Ray" descope note.
"""
import os
import subprocess
import sys
import tempfile
import time

import cloudpickle

_SHIM_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class BarrierTaskContext:
    """Per-task context; available inside a barrier task only."""

    _current = None

    def __init__(self, partition_id, n_tasks, barrier_dir, attempt=0):
        self._pid = partition_id
        self._n = n_tasks
        self._dir = barrier_dir
        self._attempt = attempt
        self._epoch = 0

    @classmethod
    def get(cls):
        if cls._current is None:
            raise RuntimeError("not inside a barrier task")
        return cls._current

    def partitionId(self):  # noqa: N802 — pyspark's camelCase API
        return self._pid

    def stageAttemptNumber(self):  # noqa: N802
        return self._attempt

    def barrier(self):
        """Global sync across all tasks of the stage (filesystem
        count-down: one marker per task per epoch)."""
        self._epoch += 1
        my = os.path.join(self._dir, f"b{self._epoch}.{self._pid}")
        with open(my, "w"):
            pass
        deadline = time.time() + 300
        while True:
            seen = sum(
                os.path.exists(os.path.join(self._dir,
                                            f"b{self._epoch}.{i}"))
                for i in range(self._n))
            if seen == self._n:
                return
            if time.time() > deadline:
                raise RuntimeError("barrier() timed out")
            time.sleep(0.01)

    def getTaskInfos(self):  # noqa: N802 — minimal parity
        return [type("TaskInfo", (), {"address": "127.0.0.1"})()
                for _ in range(self._n)]


class _BarrierRDD:
    def __init__(self, sc, n_partitions):
        self._sc = sc
        self._n = n_partitions
        self._fn = None

    def mapPartitions(self, fn):  # noqa: N802
        out = _BarrierRDD(self._sc, self._n)
        out._fn = fn
        return out

    def collect(self):
        if self._fn is None:
            raise RuntimeError("no mapPartitions function")
        n = self._n
        tmp = tempfile.mkdtemp(prefix="fake-spark-")
        fn_path = os.path.join(tmp, "task.pkl")
        with open(fn_path, "wb") as f:
            cloudpickle.dump(self._fn, f)
        outs = [os.path.join(tmp, f"out-{i}.pkl") for i in range(n)]
        errs = [os.path.join(tmp, f"err-{i}.log") for i in range(n)]
        env = dict(os.environ)
        env["PYTHONPATH"] = _SHIM_DIR + os.pathsep \
            + env.get("PYTHONPATH", "")
        procs = []
        try:
            for i in range(n):
                with open(errs[i], "wb") as ef:
                    procs.append(subprocess.Popen(
                        [sys.executable, "-m", "pyspark._task_runner",
                         fn_path, outs[i], str(i), str(n), tmp],
                        env=env, stderr=ef, start_new_session=True))
            deadline = time.time() + 600
            codes = [None] * n
            while any(c is None for c in codes):
                for i, p in enumerate(procs):
                    if codes[i] is None:
                        codes[i] = p.poll()
                        if codes[i] not in (None, 0):
                            with open(errs[i], "rb") as ef:
                                tail = ef.read()[-4000:].decode(
                                    "utf-8", "replace")
                            raise RuntimeError(
                                f"barrier task {i} failed "
                                f"(exit {codes[i]}):\n{tail}")
                if time.time() > deadline:
                    raise RuntimeError("barrier stage timed out")
                time.sleep(0.02)
            results = []
            for i in range(n):
                with open(outs[i], "rb") as f:
                    results.extend(cloudpickle.load(f))
            return results
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)


class _RDD:
    def __init__(self, sc, n_partitions):
        self._sc = sc
        self._n = n_partitions

    def barrier(self):
        return _BarrierRDD(self._sc, self._n)


class SparkContext:
    _instance = None

    def __init__(self, master="local[2]"):
        self.master = master

    @property
    def defaultParallelism(self):  # noqa: N802
        return max(os.cpu_count() or 2, 2)

    @classmethod
    def getOrCreate(cls):  # noqa: N802
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def parallelize(self, data, num_slices):
        return _RDD(self, num_slices)

    def stop(self):
        SparkContext._instance = None


__version__ = "0.0-horovod-tpu-ci-shim"
