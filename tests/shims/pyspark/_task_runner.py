"""Barrier-task child process of the CI pyspark shim: install the task's
BarrierTaskContext, run the cloudpickled mapPartitions function on this
partition's iterator, write the result list back."""
import sys

import cloudpickle


def main():
    fn_path, out_path, pid, n, barrier_dir = sys.argv[1:6]
    pid, n = int(pid), int(n)
    import pyspark

    pyspark.BarrierTaskContext._current = pyspark.BarrierTaskContext(
        pid, n, barrier_dir)
    with open(fn_path, "rb") as f:
        fn = cloudpickle.load(f)
    result = list(fn(iter([pid])))
    with open(out_path, "wb") as f:
        cloudpickle.dump(result, f)


if __name__ == "__main__":
    main()
