"""CI-only pytorch_lightning conformance shim (NOT part of horovod_tpu).

Implements exactly the LightningModule core protocol that
``horovod_tpu.spark.lightning.LightningEstimator`` consumes —
``LightningModule`` as a ``torch.nn.Module`` with ``training_step`` /
``configure_optimizers`` / optional ``validation_step`` hooks and a
no-op ``log`` — so a test can subclass it the way real user code
subclasses ``pl.LightningModule`` and prove the estimator drives the
protocol end-to-end. pytorch_lightning itself is not installable here
(no network). Trainer machinery (loops, callbacks, logging backends,
distributed strategies) is explicitly NOT simulated: the estimator IS
the trainer in this build. See tests/shims/README.md.
"""
import torch


class LightningModule(torch.nn.Module):
    """The core-protocol subset of pytorch_lightning.LightningModule."""

    def log(self, name, value, **kwargs):  # metrics sink: no-op in CI
        pass

    def log_dict(self, metrics, **kwargs):
        pass

    def training_step(self, batch, batch_idx):
        raise NotImplementedError

    def configure_optimizers(self):
        raise NotImplementedError


__version__ = "0.0-horovod-tpu-ci-shim"
