"""Remote-task child process of the CI ray shim: run the cloudpickled
function with its args, write the result back."""
import sys

import cloudpickle


def main():
    fn_path, args_path, out_path = sys.argv[1:4]
    with open(fn_path, "rb") as f:
        remote_fn = cloudpickle.load(f)
    with open(args_path, "rb") as f:
        args = cloudpickle.load(f)
    result = remote_fn(*args)
    with open(out_path, "wb") as f:
        cloudpickle.dump(result, f)


if __name__ == "__main__":
    main()
