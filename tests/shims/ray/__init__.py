"""CI-only ray conformance shim (NOT part of horovod_tpu).

Implements the exact API surface ``horovod_tpu.ray.RayExecutor._run_ray``
consumes — ``ray.init`` / ``ray.is_initialized`` / ``@ray.remote(...)``
returning handles with ``.remote(...)`` / ``ray.get(futures, timeout=)``
/ ``ray.cancel(fut, force=)`` / ``ray.util.get_node_ip_address`` — with
the one semantic that matters for a collective job: each remote call runs
CONCURRENTLY in its own OS process, shipped via cloudpickle like real ray
ships tasks.

Used by tests/workers/ray_shim_worker.py (prepended to PYTHONPATH) so the
``backend="ray"`` path executes end-to-end in CI; real-cluster behavior
(placement groups, scheduling, object store) is explicitly NOT simulated.
See README "Spark/Ray" descope note.
"""
import os
import subprocess
import sys
import tempfile
import time

import cloudpickle

from . import util  # noqa: F401  (ray.util.get_node_ip_address)

_SHIM_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_initialized = False


def init(*args, **kwargs):
    global _initialized
    _initialized = True


def is_initialized():
    return _initialized


def shutdown():
    global _initialized
    _initialized = False


class _Future:
    def __init__(self, fn_blob, args):
        self._tmp = tempfile.mkdtemp(prefix="fake-ray-")
        in_path = os.path.join(self._tmp, "task.pkl")
        self.out_path = os.path.join(self._tmp, "out.pkl")
        self.err_path = os.path.join(self._tmp, "err.log")
        with open(in_path, "wb") as f:
            f.write(fn_blob)
        with open(os.path.join(self._tmp, "args.pkl"), "wb") as f:
            cloudpickle.dump(args, f)
        env = dict(os.environ)
        env["PYTHONPATH"] = _SHIM_DIR + os.pathsep \
            + env.get("PYTHONPATH", "")
        with open(self.err_path, "wb") as ef:
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "ray._task_runner", in_path,
                 os.path.join(self._tmp, "args.pkl"), self.out_path],
                env=env, stderr=ef, start_new_session=True)

    def _error_tail(self):
        try:
            with open(self.err_path, "rb") as ef:
                return ef.read()[-4000:].decode("utf-8", "replace")
        except OSError:
            return "<no stderr captured>"

    def _cleanup(self):
        import shutil

        shutil.rmtree(self._tmp, ignore_errors=True)


class RayTaskError(Exception):
    pass


class _RemoteFunction:
    def __init__(self, fn):
        self._blob = cloudpickle.dumps(fn)

    def remote(self, *args):
        return _Future(self._blob, args)


def remote(*args, **options):
    """Supports both ``@ray.remote`` and ``@ray.remote(max_calls=1)``."""
    if args and callable(args[0]) and not options:
        return _RemoteFunction(args[0])

    def deco(fn):
        return _RemoteFunction(fn)

    return deco


def get(futures, timeout=None):
    single = isinstance(futures, _Future)
    futs = [futures] if single else list(futures)
    deadline = time.time() + (timeout if timeout else 3600)
    pending = set(range(len(futs)))
    while pending:
        for i in list(pending):
            rc = futs[i].proc.poll()
            if rc is None:
                continue
            pending.discard(i)
            if rc != 0:
                raise RayTaskError(
                    f"ray task {i} failed (exit {rc}):\n"
                    f"{futs[i]._error_tail()}")
        if pending and time.time() > deadline:
            raise TimeoutError(f"ray.get timed out after {timeout}s")
        time.sleep(0.02)
    results = []
    for f in futs:
        with open(f.out_path, "rb") as fh:
            results.append(cloudpickle.load(fh))
        f._cleanup()
    return results[0] if single else results


def cancel(fut, force=False):
    if fut.proc.poll() is None:
        fut.proc.kill() if force else fut.proc.terminate()


__version__ = "0.0-horovod-tpu-ci-shim"
