"""ray.util subset for the CI shim."""


def get_node_ip_address():
    return "127.0.0.1"
