"""numpy-backed NDArray subset for the CI mxnet shim."""
import numpy as np


class NDArray:
    def __init__(self, data, ctx=None, dtype=None):
        self._np = np.array(data, dtype=dtype)
        self.context = ctx

    def asnumpy(self):
        return self._np.copy()

    @property
    def shape(self):
        return self._np.shape

    @property
    def dtype(self):
        return self._np.dtype

    def __getitem__(self, idx):
        out = self._np[idx]
        return NDArray(out, ctx=self.context) if isinstance(out, np.ndarray) \
            else out

    def __setitem__(self, idx, value):
        self._np[idx] = value._np if isinstance(value, NDArray) else value

    def __len__(self):
        return len(self._np)

    def __repr__(self):
        return f"NDArray({self._np!r})"


def array(data, ctx=None, dtype=None):
    if isinstance(data, NDArray):
        data = data._np
    return NDArray(data, ctx=ctx, dtype=dtype)
