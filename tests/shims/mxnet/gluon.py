"""mx.gluon subset for the CI mxnet shim: Parameter + Trainer with the
kvstore-free update loop horovod_tpu.mxnet.DistributedTrainer overrides."""
import numpy as np

from . import optimizer as _opt
from .ndarray import NDArray


class Parameter:
    def __init__(self, name, data, grad_req="write"):
        self.name = name
        self.grad_req = grad_req
        self._data = data if isinstance(data, NDArray) else NDArray(data)
        self._grad = NDArray(np.zeros_like(self._data._np))

    def data(self):
        return self._data

    def grad(self):
        return self._grad

    def list_grad(self):
        return [self._grad]

    def list_data(self):
        return [self._data]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore=None):
        if hasattr(params, "values"):
            params = list(params.values())
        self._params = list(params)
        if isinstance(optimizer, str):
            optimizer = _opt.create(optimizer, **(optimizer_params or {}))
        self._optimizer = optimizer
        self._states = [self._optimizer.create_state(i, p.data())
                        for i, p in enumerate(self._params)]

    def _allreduce_grads(self):
        pass  # kvstore-backed in real gluon; subclasses override

    def step(self, batch_size, ignore_stale_grad=False):
        self._allreduce_grads()
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            self._optimizer.update(i, p.data(), p.grad(), self._states[i])
