"""CI-only mxnet conformance shim (NOT part of horovod_tpu).

Implements the exact API surface ``horovod_tpu.mxnet`` consumes —
``mxnet.ndarray.NDArray``/``array``, ``mx.optimizer.Optimizer`` (+ an SGD
for tests), ``mx.gluon.Trainer``/``Parameter`` — over plain numpy.
Upstream MXNet is archived (Apache attic, 2023) and not installable here;
the shim lets the binding's collectives, ``DistributedOptimizer`` and
``DistributedTrainer`` execute end-to-end in CI instead of only their
ImportError surface. Real-MXNet behavior (deferred init, contexts/GPU
streams, autograd) is explicitly NOT simulated. See README descope note.
"""
from . import gluon, ndarray, optimizer  # noqa: F401
from .ndarray import NDArray, array  # noqa: F401

nd = ndarray

__version__ = "0.0-horovod-tpu-ci-shim"
