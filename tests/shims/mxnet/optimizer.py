"""mx.optimizer subset for the CI mxnet shim."""


class Optimizer:
    def __init__(self, learning_rate=0.01):
        self.learning_rate = learning_rate

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self.learning_rate = lr


class SGD(Optimizer):
    def update(self, index, weight, grad, state):
        if isinstance(index, (tuple, list)):  # grouped form, like real mx
            for w, g in zip(weight, grad):
                w[:] = w.asnumpy() - self.learning_rate * g.asnumpy()
            return
        weight[:] = weight.asnumpy() - self.learning_rate * grad.asnumpy()


def create(name, **kwargs):
    if name.lower() == "sgd":
        return SGD(**kwargs)
    raise ValueError(f"shim knows only 'sgd', got {name!r}")
