"""ASAN and UBSAN tiers of the core sanitizer matrix (ISSUE 6 /
docs/static_analysis.md). Same worker matrix as test_tsan.py, same shared
harness (tests/util.py run_under_sanitizer), different instrumentation:

- ASAN (+LeakSanitizer): heap misuse and leaks — the handle table's
  core-owned output buffers (NewHandle/CompleteHandle/hvd_release) and the
  scatter-gather iovec path, which sends/recvs straight over user buffers,
  are the paths where a lifetime bug would hide.
- UBSAN: shift/overflow/alignment UB — the fp16/bf16 bit-twiddling block
  converters in reduce.h (mask-blend subnormal handling, unsigned-wrap
  exponent rebias) are the prime candidates; ring_pipeline_worker drives
  them across every dtype.

The collective-matrix tests run in tier-1; the deeper per-path runs are
`slow` (each is a full instrumented rebuild + multi-rank job). `make
check` (csrc/Makefile) builds every tier outside pytest.
"""
import pytest

from .util import assert_sanitizer_clean, run_under_sanitizer

pytestmark = pytest.mark.sanitizer


# --- ASAN ------------------------------------------------------------------

def test_core_collective_matrix_under_asan(tmp_path):
    p, core_reports = run_under_sanitizer(
        tmp_path, "collective_worker.py", 2, tier="asan")
    assert_sanitizer_clean(p, 2, core_reports, tier="asan")


@pytest.mark.slow
def test_zerocopy_sg_ring_under_asan(tmp_path):
    """The scatter-gather ring under ASAN: segmented iovecs over user
    buffers; an off-by-one in segment math is a heap-buffer-overflow here."""
    p, core_reports = run_under_sanitizer(
        tmp_path, "zerocopy_worker.py", 2, tier="asan",
        extra_env={"HVD_ZEROCOPY_THRESHOLD": "16384"})
    assert_sanitizer_clean(p, 2, core_reports, tier="asan")


@pytest.mark.slow
def test_reinit_under_asan(tmp_path):
    """Rapid init/shutdown cycles under LeakSanitizer: every cycle tears
    down sockets, the handle table, and core-owned gather outputs — the
    paths that would accrete if a release were missed."""
    import secrets

    p, core_reports = run_under_sanitizer(
        tmp_path, "reinit_worker.py", 4, tier="asan",
        extra_env={"HVD_RENDEZVOUS_SECRET": secrets.token_hex(16),
                   "REINIT_CYCLES": "2"})
    assert_sanitizer_clean(p, 4, core_reports, tier="asan")


# --- UBSAN -----------------------------------------------------------------

def test_core_collective_matrix_under_ubsan(tmp_path):
    p, core_reports = run_under_sanitizer(
        tmp_path, "collective_worker.py", 2, tier="ubsan")
    assert_sanitizer_clean(p, 2, core_reports, tier="ubsan")


@pytest.mark.slow
def test_fp16_bf16_converters_under_ubsan(tmp_path):
    """The streamed ring across every dtype under UBSAN: the branchless
    fp16/bf16 block converters shift and rebias exponent fields with
    mask arithmetic — exactly where an invalid-shift-exponent or signed
    overflow would sit."""
    p, core_reports = run_under_sanitizer(
        tmp_path, "ring_pipeline_worker.py", 2, tier="ubsan",
        extra_env={"HVD_RING_PIPELINE": "4",
                   "HVD_ZEROCOPY_THRESHOLD": "16384"})
    assert_sanitizer_clean(p, 2, core_reports, tier="ubsan")


@pytest.mark.slow
def test_zerocopy_sg_ring_under_ubsan(tmp_path):
    p, core_reports = run_under_sanitizer(
        tmp_path, "zerocopy_worker.py", 2, tier="ubsan",
        extra_env={"HVD_ZEROCOPY_THRESHOLD": "16384"})
    assert_sanitizer_clean(p, 2, core_reports, tier="ubsan")
