"""Cluster-integration code paths under CI shims (VERDICT r3 #4).

pyspark and ray are not installable here (no network), so tests/shims
vendors minimal conformance shims of exactly the API surface
horovod_tpu.spark.run and RayExecutor(backend="ray") consume, with
barrier tasks / remote tasks as real concurrent OS processes. These tests
make the previously never-executed code paths run end-to-end; what stays
untested is real-cluster behavior (scheduling, placement, retries) —
documented in the README descope note.

The workers run in subprocesses so the shim packages never enter the
pytest process's sys.modules (other tests probe for the real packages'
absence).
"""
import os

from .util import run_single, tpu_isolated_env

_SHIMS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "shims")
_PP = tpu_isolated_env(_SHIMS)


def test_spark_run_barrier_stage():
    """spark.run(): barrier tasks negotiate a fresh job through the
    driver's signed KV and return per-rank results ordered by rank."""
    run_single("spark_shim_worker.py", extra_env=_PP, timeout=300)


def test_ray_executor_ray_backend():
    """RayExecutor(backend='ray'): remote task fan-out, result collection,
    and the kill-survivors failure contract."""
    run_single("ray_shim_worker.py", extra_env=_PP, timeout=300)
