"""The graded benchmark examples run end-to-end tiny (BASELINE.json
configs: "ResNet-50 + DistributedGradientTape" and "BERT +
DistributedOptimizer (grad compression on)"). CI sizes are minimal; the
same scripts scale to the real configs via env."""
import os
import sys

import pytest

from .util import tpu_isolated_env

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = os.path.join(_REPO, "examples")


def _run_example(script, extra_env, timeout=420):
    from horovod_tpu.runner.local import run_local

    env = tpu_isolated_env()
    env.update({k: str(v) for k, v in extra_env.items()})
    # run_local (not a bare subprocess): on a hang it terminates the whole
    # rank group instead of orphaning spinning workers.
    codes = run_local(2, [sys.executable, os.path.join(_EXAMPLES, script)],
                      env=env, timeout=timeout)
    assert codes == [0, 0], codes


def test_tf2_resnet50_graded_config():
    pytest.importorskip("tensorflow")
    _run_example("tf2_synthetic_benchmark.py",
                 {"MODEL": "resnet50", "IMG": 32, "BATCH": 2, "STEPS": 2})


def test_torch_bert_compression_graded_config():
    pytest.importorskip("torch")
    pytest.importorskip("transformers")
    _run_example("torch_synthetic_benchmark.py",
                 {"MODEL": "bert", "FP16": 1, "NUM_GROUPS": 2,
                  "STEPS": 2, "BATCH": 2, "SEQ": 32})
