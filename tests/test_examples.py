"""The graded benchmark examples run end-to-end tiny (BASELINE.json
configs: "ResNet-50 + DistributedGradientTape" and "BERT +
DistributedOptimizer (grad compression on)"). CI sizes are minimal; the
same scripts scale to the real configs via env."""
import os

import pytest

from .util import run_worker_job
from .util import have_shard_map

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = os.path.join(_REPO, "examples")


def _run_example(script, extra_env, timeout=420):
    run_worker_job(2, os.path.join(_EXAMPLES, script),
                   extra_env=extra_env, timeout=timeout)


def test_tf2_resnet50_graded_config():
    pytest.importorskip("tensorflow")
    _run_example("tf2_synthetic_benchmark.py",
                 {"MODEL": "resnet50", "IMG": 32, "BATCH": 2, "STEPS": 2})


def test_torch_bert_compression_graded_config():
    pytest.importorskip("torch")
    pytest.importorskip("transformers")
    _run_example("torch_synthetic_benchmark.py",
                 {"MODEL": "bert", "FP16": 1, "NUM_GROUPS": 2,
                  "STEPS": 2, "BATCH": 2, "SEQ": 32})


def test_estimator_example_torch_and_lightning(tmp_path):
    """examples/estimator_train.py end-to-end tiny: TorchEstimator and
    LightningEstimator (protocol module, no pytorch_lightning import)
    both fit and transform. The script spawns its own ranks."""
    import subprocess
    import sys

    from .util import tpu_isolated_env

    pytest.importorskip("torch")
    pytest.importorskip("pandas")
    env = dict(os.environ)
    env.update(tpu_isolated_env())
    env.update({"ROWS": "64", "EPOCHS": "2", "NP": "2",
                "STORE": str(tmp_path / "store")})
    p = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, "estimator_train.py")],
        env=env, capture_output=True, text=True, timeout=420)
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr}"
    assert "estimator demo OK" in p.stdout
    assert "lightning loss" in p.stdout


def test_bn_sweep_driver_smoke():
    """examples/resnet_bn_sweep.py end-to-end on the CPU smoke path, one
    variant: guards the sweep's child-env plumbing (a PYTHONPATH clobber
    there once failed every variant with an opaque rc=1 — round 5) and
    the summary-table path."""
    import json
    import subprocess
    import sys

    from .util import tpu_isolated_env

    # Drop ambient HVD_* (a developer's exported bench tunables — e.g. a
    # TPU-only compiler option — would change or break the CPU child).
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("HVD_")}
    env.update(tpu_isolated_env())
    env.update({"SWEEP_ONLY": "baseline", "HVD_BENCH_BATCH": "4"})
    # Timeout must clear the child's own BENCH_DEADLINE=420 (the sweep
    # itself allows 600 s per variant for the same reason).
    p = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, "resnet_bn_sweep.py")],
        env=env, capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr}"
    lines = [json.loads(ln) for ln in p.stdout.splitlines()
             if ln.strip().startswith("{")]
    base = [d for d in lines if d.get("variant") == "baseline"]
    assert base and base[0].get("value", 0) > 0, lines
    assert "vs baseline" in p.stdout  # summary table printed


@pytest.mark.skipif(not have_shard_map(), reason="jax.shard_map unavailable (jax < 0.8): mesh workers cannot import horovod_tpu.parallel")
def test_pipeline_example():
    """examples/pipeline_train.py: 4 transformer-block GPipe stages x
    2-way dp on the virtual mesh, loss falls."""
    import subprocess
    import sys

    from .util import tpu_isolated_env

    env = dict(os.environ)
    env.update(tpu_isolated_env())
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["STEPS"] = "10"
    p = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, "pipeline_train.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr}"
    assert "pipeline demo OK" in p.stdout
