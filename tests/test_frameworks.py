"""Framework binding tests: torch and TF workers under the real 2-process
launcher (reference: test/parallel/test_torch.py, test_tensorflow.py run
via `horovodrun -np 2 pytest ...`)."""

import pytest

from .util import have_torch_native_ext, run_worker_job

_needs_torch_native = pytest.mark.skipif(
    not have_torch_native_ext(),
    reason="torch native extension does not build against the installed "
           "torch; the numpy-fallback matrix still runs below")


@_needs_torch_native
def test_torch_binding_2proc():
    pytest.importorskip("torch")
    run_worker_job(2, "torch_worker.py", timeout=240)


@_needs_torch_native
def test_torch_binding_4proc():
    pytest.importorskip("torch")
    run_worker_job(4, "torch_worker.py", timeout=240)


def test_torch_binding_numpy_fallback():
    """HVD_TORCH_NATIVE_OPS=0: the whole matrix must still pass through
    the numpy bridge (the no-toolchain fallback)."""
    pytest.importorskip("torch")
    run_worker_job(2, "torch_worker.py", timeout=240,
                   extra_env={"HVD_TORCH_NATIVE_OPS": "0"})


def test_tf_binding_2proc():
    """Default path: the native custom-op library (csrc/tf_ops.cc
    AsyncOpKernels, the reference's tensorflow/mpi_ops.cc analog) carries
    allreduce/allgather/broadcast; the worker asserts it loaded."""
    pytest.importorskip("tensorflow")
    run_worker_job(2, "tf_worker.py", timeout=300)


def test_tf_binding_pyfunc_fallback():
    """HVD_TF_NATIVE_OPS=0: the whole matrix must still pass through the
    tf.py_function bridge (the no-TF-headers fallback)."""
    pytest.importorskip("tensorflow")
    run_worker_job(2, "tf_worker.py", timeout=300,
                   extra_env={"HVD_TF_NATIVE_OPS": "0"})


def test_tf_xla_ops_2proc():
    """HVD_ENABLE_XLA_OPS=1: collectives compile INSIDE
    tf.function(jit_compile=True) via csrc/tf_xla_ops.cc (XlaOpKernel +
    CPU CustomCall riding the shared core — the reference's
    tensorflow/xla_mpi_ops.cc HVDAllreduceOp analog). The worker trains a
    DistributedGradientTape model in a fully XLA-compiled step."""
    pytest.importorskip("tensorflow")
    run_worker_job(2, "tf_xla_worker.py", timeout=300,
                   extra_env={"HVD_ENABLE_XLA_OPS": "1"})


def test_tf_xla_ops_fallback():
    """Without the gate, jit_compile=True must reject the graph (no silent
    wrong answers); eager/graph-mode remains the supported path."""
    pytest.importorskip("tensorflow")
    run_worker_job(2, "tf_xla_worker.py", timeout=300)


def test_tf_xla_ops_legacy_abi_2proc():
    """The legacy API_VERSION_STATUS_RETURNING ABI stays selectable
    (HVD_XLA_LEGACY_CUSTOM_CALL=1) behind the typed-FFI default — both
    ABIs share RunCollective, so the full worker matrix must pass under
    either emission."""
    pytest.importorskip("tensorflow")
    run_worker_job(2, "tf_xla_worker.py", timeout=300,
                   extra_env={"HVD_ENABLE_XLA_OPS": "1",
                              "HVD_XLA_LEGACY_CUSTOM_CALL": "1"})


def test_mxnet_binding_2proc():
    """The full mxnet surface (collectives, broadcast_parameters,
    DistributedOptimizer, DistributedTrainer) executes end-to-end over the
    CI mxnet shim (tests/shims/mxnet — upstream MXNet is archived and not
    installable here; see README descope note)."""
    import os

    from .util import tpu_isolated_env

    shims = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "shims")
    run_worker_job(2, "mxnet_worker.py", timeout=120,
                   extra_env=tpu_isolated_env(shims))


def test_mxnet_binding_import_surface():
    """MXNet is absent in this environment (README descope note): the
    binding must fail with a clear, actionable ImportError — and import
    cleanly when mxnet exists (reference: horovod/mxnet/__init__.py)."""
    try:
        import mxnet  # noqa: F401
    except ImportError:
        with pytest.raises(ImportError, match="mxnet.*not.*installed"):
            import horovod_tpu.mxnet  # noqa: F401
    else:
        import horovod_tpu.mxnet as hvd_mx

        assert hasattr(hvd_mx, "DistributedTrainer")
        assert hasattr(hvd_mx, "broadcast_parameters")


def test_rank_aware_checkpointing(tmp_path):
    """Orbax-delegated checkpoint/resume (SURVEY §5): the root writes +
    barrier; restore picks one step for ALL ranks; explicit-step and
    empty-dir paths covered."""
    pytest.importorskip("orbax.checkpoint")
    run_worker_job(2, "checkpoint_worker.py",
                   extra_env={"CKPT_DIR": str(tmp_path / "ck")},
                   timeout=240)
