"""Worker: response-cache capacity eviction (run with HVD_CACHE_CAPACITY=2).

Three tensors round-robin through a 2-entry cache: every cycle evicts the
LRU entry deterministically on all ranks; results stay correct and the live
entry count never exceeds capacity. Also: HVD_CACHE_CAPACITY=0 disables the
cache entirely (hits stay 0)."""
import os

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()
cap = int(os.environ.get("HVD_CACHE_CAPACITY", "1024"))

for i in range(9):
    name = f"t{i % 3}"
    out = hvd.allreduce(np.full((8,), float(r + 1), np.float32),
                        op=hvd.Sum, name=name)
    assert np.allclose(out, sum(range(1, s + 1))), (name, out[0])

hits, misses, entries = hvd.cache_stats()
assert entries <= max(cap, 0), (entries, cap)
if cap == 0:
    assert hits == 0, hits
elif cap >= 3:
    assert hits > 0, (hits, misses)
# cap==2 with strict round-robin: every access evicts the LRU -> all misses
# is acceptable; correctness (asserted above) is the contract.

hvd.shutdown()
print(f"rank {r}: capacity({cap}) PASS hits={hits} misses={misses} "
      f"entries={entries}", flush=True)
