"""Worker: tuned parameters must BEAT the (deliberately bad) defaults.

Phase 1: init with HVD_AUTOTUNE=1 under a pathological default cycle time
(set by the test: HVD_CYCLE_TIME_MS=25 paces the negotiation loop at
~40 Hz), drive the synthetic stream until the search locks, then time M
iterations at the tuned point. Phase 2: shutdown, re-init with autotune
OFF at the same defaults, time the same M iterations. The tuned
configuration must move more bytes/sec — the end-to-end "tuned >= default"
assertion VERDICT r3 #8 asks for (reference: parameter_manager.cc's whole
reason to exist).

Every rank runs identical iteration counts (collectives stay symmetric);
rank 0 asserts the win.
"""
import os
import time

# Fake multi-host topology (hier_worker.py convention) so the
# hierarchical arm is toggleable — see autotune_worker.py.
_L = os.environ.get("AT_LOCAL_SIZE")
if _L:
    _r = int(os.environ["HVD_RANK"])
    _s = int(os.environ["HVD_SIZE"])
    _L = int(_L)
    os.environ["HVD_LOCAL_RANK"] = str(_r % _L)
    os.environ["HVD_LOCAL_SIZE"] = str(_L)
    os.environ["HVD_CROSS_RANK"] = str(_r // _L)
    os.environ["HVD_CROSS_SIZE"] = str(_s // _L)

import numpy as np

import horovod_tpu as hvd


def stream(n_iters, tag):
    for i in range(n_iters):
        out = hvd.allreduce(np.full((512,), 1.0, np.float32), op=hvd.Sum,
                            name=f"{tag}{i % 4}")
        assert out[0] == hvd.size(), out[0]


M = int(os.environ.get("TEST_TIMED_ITERS", "60"))
max_samples = int(os.environ.get("HVD_AUTOTUNE_MAX_SAMPLES", "8"))

# -- phase 1: autotune on, search to lock, then timed window --------------
hvd.init()
r = hvd.rank()
assert hvd.autotune_state()[0] == "searching"
# Fixed iteration count on every rank (no status-dependent early exit: a
# rank observing "locked" one cycle before its peers would break first and
# strand their next allreduce).
stream(30 * max_samples, "warm")
status, fusion, cycle = hvd.autotune_state()
assert status == "locked", status
t0 = time.perf_counter()
stream(M, "tuned")
tuned_secs = time.perf_counter() - t0
# All ranks at the same point before tearing the mesh down, then stagger
# the re-init: rank 0 must bind the controller port strictly after every
# old socket closed and strictly before the workers' ConnectRetry window.
hvd.barrier(name="phase1.done")
hvd.shutdown()

# -- phase 2: same job, autotune off, same defaults -----------------------
os.environ["HVD_AUTOTUNE"] = "0"
# No stagger, no caller-side retry: re-forming a 32-rank mesh on the same
# port is raceable, and the library now absorbs the race itself (ListenRetry
# rebind backoff + worker rendezvous re-dial — VERDICT r4 weak #6;
# exercised directly by reinit_worker.py).
hvd.init()
t0 = time.perf_counter()
stream(M, "plain")
default_secs = time.perf_counter() - t0
hvd.shutdown()

if r == 0:
    speedup = default_secs / tuned_secs
    # The pathological 25 ms default cycle paces the stream at ~40
    # windows/sec; any sane tuned cycle beats it severalfold. >=1.5x keeps
    # the assertion meaningful yet robust to box noise.
    assert speedup >= 1.5, (
        f"tuned {tuned_secs:.2f}s vs default {default_secs:.2f}s "
        f"(speedup {speedup:.2f}) — autotune did not beat defaults")
    print(f"rank 0: autotune win {speedup:.1f}x "
          f"(fusion={fusion} cycle={cycle:.2f}ms)", flush=True)
print(f"rank {r}: autotune-win PASS", flush=True)
