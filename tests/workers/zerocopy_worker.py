"""Worker: scatter-gather zero-copy host plane (HVD_ZEROCOPY_THRESHOLD).

Run with a small HVD_ZEROCOPY_THRESHOLD so modest payloads route onto the
segmented-iovec ring (RingAllreduceSG): large single tensors and fused
groups above the threshold must perform ZERO staging memcpys (asserted via
hvd.zerocopy_stats()), small payloads must keep riding the fusion-buffer
staging path, and numerics must match the staged path exactly in both
regimes.
"""
import os

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()

enabled, threshold = hvd.zerocopy_state()
assert enabled, "zero-copy path should be live under HVD_ZEROCOPY=1"
want = int(os.environ["HVD_ZEROCOPY_THRESHOLD"])
assert threshold == want, (threshold, want)

big = threshold // 4 * 2  # float32 elems, 2x threshold in bytes
small = max(threshold // 16, 16)  # elems; ~threshold/4 bytes each


def stats():
    return hvd.zerocopy_stats()


# -- 1. large unfused allreduce: SG ring, zero staging bytes ---------------
zc_ops0, zc_b0, st_ops0, st_b0 = stats()
x = np.arange(big, dtype=np.float32) + r
out = hvd.allreduce(x, op=hvd.Sum, name="zc.big")
expected = np.arange(big, dtype=np.float32) * s + sum(range(s))
assert np.array_equal(out, expected), (out[:4], expected[:4])
zc_ops1, zc_b1, st_ops1, st_b1 = stats()
assert zc_ops1 == zc_ops0 + 1, (zc_ops0, zc_ops1)
assert zc_b1 == zc_b0 + big * 4, (zc_b0, zc_b1)
assert (st_ops1, st_b1) == (st_ops0, st_b0), "large allreduce staged!"

# -- 2. Average + Min + float64 through the SG accumulator -----------------
out = hvd.allreduce(np.full((big,), float(r + 1), np.float32),
                    op=hvd.Average, name="zc.avg")
assert np.allclose(out, (s + 1) / 2), out[:4]
out = hvd.allreduce(np.full((big,), float(r + 1), np.float32),
                    op=hvd.Min, name="zc.min")
assert np.array_equal(out, np.ones(big, np.float32)), out[:4]
out = hvd.allreduce(np.arange(big // 2, dtype=np.float64) * (r + 1),
                    op=hvd.Sum, name="zc.f64")
assert np.array_equal(
    out, np.arange(big // 2, dtype=np.float64) * sum(range(1, s + 1))), \
    out[:4]
zc_ops2, zc_b2, st_ops2, st_b2 = stats()
assert zc_ops2 == zc_ops1 + 3, (zc_ops1, zc_ops2)
assert (st_ops2, st_b2) == (st_ops1, st_b1)

# -- 3. fused group STRADDLING the threshold: each tensor is below it, the
# fused payload is above -> one SG op over per-tensor segments, still zero
# staging memcpys (ISSUE 4 acceptance: fused allreduce above threshold
# performs no staging memcpy).
parts = [np.full((small,), float(r + 1 + i), np.float32) for i in range(8)]
assert small * 4 < threshold < sum(p.nbytes for p in parts)
outs = hvd.grouped_allreduce(parts, op=hvd.Sum, name="zc.fused")
for i, o in enumerate(outs):
    want_v = sum(range(1 + i, s + 1 + i))
    assert np.allclose(o, want_v), (i, o[0], want_v)
zc_ops3, zc_b3, st_ops3, st_b3 = stats()
assert zc_ops3 == zc_ops2 + 1, "fused group did not take the SG path"
assert zc_b3 == zc_b2 + small * 4 * 8
assert (st_ops3, st_b3) == (st_ops2, st_b2), "fused group staged!"

# -- 4. below threshold: stays on the staging path, same numerics ----------
out = hvd.allreduce(np.full((small,), float(r + 1), np.float32),
                    op=hvd.Sum, name="zc.small")
assert np.allclose(out, sum(range(1, s + 1))), out[:4]
zc_ops4, zc_b4, st_ops4, st_b4 = stats()
assert zc_ops4 == zc_ops3, "small allreduce took the SG path"
assert st_ops4 == st_ops3 + 1
assert st_b4 > st_b3

# -- 5. non-contiguous input: the BRIDGE falls back to a counted copy
# (contiguity is a wire requirement), and the now-contiguous staging copy
# still rides the SG ring above threshold — numerics unchanged.
bs0 = hvd.bridge.stats()
strided = (np.arange(big * 2, dtype=np.float32) + r)[::2]
assert not strided.flags["C_CONTIGUOUS"]
out = hvd.allreduce(strided, op=hvd.Sum, name="zc.strided")
assert np.array_equal(
    out, np.arange(big * 2, dtype=np.float32)[::2] * s + sum(range(s))), \
    out[:4]
bs1 = hvd.bridge.stats()
assert bs1["copy_ops"] == bs0["copy_ops"] + 1, (bs0, bs1)
assert bs1["fallback_reasons"].get("non-contiguous", 0) >= 1, bs1
zc_ops5 = stats()[0]
assert zc_ops5 == zc_ops4 + 1, "strided copy did not reach the SG ring"

hvd.barrier(name="zc.done")
hvd.shutdown()
print(f"rank {r}: zerocopy PASS zc_ops={zc_ops4} staged_ops={st_ops4}",
      flush=True)
