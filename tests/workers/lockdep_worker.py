"""Lockdep worker (csrc/debug_lock.h): runs a real multi-rank job with the
checker on and asserts, per rank:

1. the real lock graph is CLEAN — a training step's acquisitions build
   order edges but no cycle and no lock held across a blocking TCP syscall;
2. the seeded AB-BA inversion (hvd.lockdep_selftest()) IS detected and
   surfaces through hvd.lockdep_stats() / hvd.lockdep_report() — the
   negative test proving detection isn't vacuously green.

Launched by tests/test_lockdep.py with HVD_LIB pointing at the `make
debug` core (lockdep defaults on there) or any core with HVD_LOCKDEP=1.
"""
import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init()
    try:
        enabled, cycles, blocking, edges, acq = hvd.lockdep_stats()
        assert enabled, "lockdep not enabled — wrong HVD_LIB / env?"

        # Drive the core across the paths whose locks are instrumented:
        # handle table + tensor queue (allreduce), process sets, timeline
        # control, and the TCP data plane under the syscall hooks.
        for i in range(4):
            x = np.arange(1024, dtype=np.float32) + hvd.rank() + i
            out = hvd.allreduce(x, op=hvd.Sum)
            assert out.shape == x.shape

        enabled, cycles, blocking, edges, acq = hvd.lockdep_stats()
        assert acq > 0, "no instrumented acquisitions recorded"
        # A clean steady-state run holds each core lock in a tight leaf
        # scope, so zero order EDGES is the healthy baseline (nesting only
        # appears on error paths like hvd_wait's handle_mu -> error_mu).
        assert cycles == 0, "unexpected inversion:\n" + hvd.lockdep_report()
        assert blocking == 0, \
            "lock held across blocking syscall:\n" + hvd.lockdep_report()

        # Negative test: the seeded inversion must be detected ...
        seeded = hvd.lockdep_selftest()
        assert seeded >= 1, "seeded AB-BA inversion not detected"
        enabled, cycles, blocking, edges, acq = hvd.lockdep_stats()
        assert cycles == seeded
        assert edges >= 1, "selftest's ordered A->B edge not recorded"
        report = hvd.lockdep_report()
        assert "lock-order inversion" in report, report
        assert "selftest_a" in report and "selftest_b" in report, report
        print("rank %d: PASS" % hvd.rank())
    finally:
        hvd.shutdown()


if __name__ == "__main__":
    main()
