"""Worker: in-mesh (XLA/ICI) and core-bridged (TCP ring) collectives
interleaved in ONE program, several rounds — the two data planes must
compose without wedging each other (VERDICT r2 weak #3: "no mixed in-mesh
+ core-bridged program" was tested). Reference analog: NCCL ops and MPI
ops coexisting under one OperationManager (horovod/common/ops/
operation_manager.cc priority list).
"""
from horovod_tpu.jax.distributed import force_cpu_platform

force_cpu_platform(2)

import functools  # noqa: E402

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import shard_map  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu.jax as hvd  # noqa: E402
from horovod_tpu.ops import jax_ops  # noqa: E402

hvd.init()
r, s = hvd.rank(), hvd.size()
assert hvd.is_multiprocess()
mesh = hvd.global_mesh()
n_local = len(jax.local_devices())
n = mesh.shape["data"]


@jax.jit
@functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                   out_specs=P("data"), check_vma=False)
def mesh_sum(x):
    return jax_ops.allreduce(x, "data", op=jax_ops.Sum)


@jax.jit
def core_sum_in_jit(x):
    # Core-bridged allreduce INSIDE jit: io_callback yields to the native
    # background thread (the xla_mpi_ops.cc CustomCall analog).
    return jax_ops.hvd_allreduce(x, op=jax_ops.Sum, name="mixed.injit")


for round_ in range(3):
    # 1) in-mesh psum across all processes' devices
    local = np.full((n_local, 2), float(r + 1), np.float32)
    out = mesh_sum(hvd.shard_local_batch(local, mesh))
    got = np.asarray(out.addressable_shards[0].data)
    assert np.allclose(got, n_local * sum(range(1, s + 1))), (round_, got)

    # 2) core-bridged eager allreduce on a jnp array
    y = hvd.allreduce(jnp.full((4,), float(r + 1)),
                      op=hvd.Sum, name=f"mixed.eager.{round_}")
    assert np.allclose(np.asarray(y), sum(range(1, s + 1))), (round_, y)

    # 3) core-bridged allreduce inside jit (io_callback)
    z = core_sum_in_jit(jnp.full((3,), float(r + 1), jnp.float32))
    assert np.allclose(np.asarray(z), sum(range(1, s + 1))), (round_, z)

    # 4) in-mesh again — the mesh plane survived the core round-trips
    out = mesh_sum(hvd.shard_local_batch(local * 2.0, mesh))
    got = np.asarray(out.addressable_shards[0].data)
    assert np.allclose(got, 2 * n_local * sum(range(1, s + 1))), (round_, got)

hvd.shutdown()
print(f"rank {r}: mixed planes PASS", flush=True)
