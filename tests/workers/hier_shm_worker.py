"""Worker: hierarchical allreduce over the intra-host shm plane (ISSUE 7).

Fake-pod topology via HIER_LOCAL_SIZE (default: all ranks on one "host"),
set before init like hier_worker.py. Runs the ring_pipeline_worker-style
parity sweep (all dtypes, Sum/Min/Max, fused pair, odd length, tiny
fallback, one pool-sized tensor) and then grades the shm/pool counters:

* EXPECT_SHM=1: shm_stats() ops/bytes must move and staged copies stay 0
  (the pointer-handoff proof); =0: the plane must stay silent.
* EXPECT_FALLBACK=1: the plane covered collectives but routing declined
  (HVD_SHM_THRESHOLD) — the fallback counter must move, ops must not.
* POOL_EXPECT_JOBS=1: the reduce worker pool (HVD_REDUCE_THREADS) must
  have fanned at least one reduction across its lanes.

With HVD_TIMELINE set and shm expected, rank 0 asserts the core timeline
recorded TCP_SHM_EXCHANGE sub-spans after shutdown.
"""
import os

r = int(os.environ["HVD_RANK"])
s = int(os.environ["HVD_SIZE"])
# Fake topology (SURVEY.md §4 / hier_worker.py convention): host-major
# blocks of L ranks. Default L = s — the single-host case, where the
# hierarchical decomposition's cross phase degenerates and the local
# phase rides the shm plane.
L = int(os.environ.get("HIER_LOCAL_SIZE", str(s)))
assert s % L == 0, (s, L)
os.environ["HVD_LOCAL_RANK"] = str(r % L)
os.environ["HVD_LOCAL_SIZE"] = str(L)
os.environ["HVD_CROSS_RANK"] = str(r // L)
os.environ["HVD_CROSS_SIZE"] = str(s // L)

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402

try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

hvd.init()

expect_shm = os.environ.get("EXPECT_SHM", "1") == "1"
expect_fallback = os.environ.get("EXPECT_FALLBACK", "0") == "1"
hier_on = os.environ.get("HVD_HIERARCHICAL_ALLREDUCE") == "1"
shm_allowed = os.environ.get("HVD_SHM", "1") != "0"

# Plane state: mapped iff same-host peers exist and HVD_SHM didn't kill
# it; the routing threshold echoes HVD_SHM_THRESHOLD.
enabled, threshold = hvd.shm_state()
assert enabled == (shm_allowed and L > 1), (enabled, shm_allowed, L)
assert threshold == int(os.environ.get("HVD_SHM_THRESHOLD", "0")), threshold

threads, jobs0, spans0 = hvd.reduce_pool_stats()
if "HVD_REDUCE_THREADS" in os.environ:
    assert threads == int(os.environ["HVD_REDUCE_THREADS"]), threads

ops0, bytes0, fb0, staged0 = hvd.shm_stats()

# Large enough that every dtype's per-rank chunk is a real shm payload at
# up to 8 ranks; POOL_N additionally clears the reduce pool's 128 KiB
# fan-out floor per span on the shm slot path.
N = 65536
POOL_N = 1 << 21  # 8 MiB float32


def rank_array(dtype, rk, n=N):
    # Small integers: exactly representable in every dtype here.
    return ((np.arange(n) % 13) + rk).astype(dtype)


OPS = [(hvd.Sum, "sum"), (hvd.Min, "min"), (hvd.Max, "max")]
DTYPES = [np.float32, np.float64, np.int32, np.int64, np.float16]
if _BF16 is not None:
    DTYPES.append(_BF16)

for dtype in DTYPES:
    dt = np.dtype(dtype)
    all_ranks = np.stack(
        [rank_array(dtype, rk).astype(np.float64) for rk in range(s)])
    for op, opname in OPS:
        out = hvd.allreduce(rank_array(dtype, r), op=op,
                            name=f"hs.{dt.name}.{opname}")
        if opname == "sum":
            expect = all_ranks.sum(axis=0)
        elif opname == "min":
            expect = all_ranks.min(axis=0)
        else:
            expect = all_ranks.max(axis=0)
        got = np.asarray(out).astype(np.float64)
        if dt.kind in "iu":
            assert np.array_equal(got, expect), \
                (dt.name, opname, got[:4], expect[:4])
        else:
            assert np.allclose(got, expect, rtol=1e-2, atol=1e-2), \
                (dt.name, opname, got[:4], expect[:4])

SUM = s * (s + 1) // 2  # sum over ranks of (r+1)
RSUM = s * (s - 1) // 2  # sum over ranks of r

# Average (postscale path on the hierarchical composition).
out = hvd.allreduce(np.full(N, float(r + 1), np.float32), op=hvd.Average,
                    name="hs.avg")
assert np.allclose(out, SUM / s), out[:4]

# Odd length with distinct per-element data (chunk-remainder spread).
M = (1 << 12) + 3
out = hvd.allreduce(np.arange(M, dtype=np.float32) + r * 1000.0,
                    op=hvd.Sum, name="hs.odd")
expect = s * np.arange(M, dtype=np.float32) + 1000.0 * RSUM
assert np.allclose(out, expect), (out[:4], expect[:4])

# Fused pair (two tensors in one cycle ride the fusion buffer).
ha = hvd.allreduce_async(np.full(257, float(r), np.float32), op=hvd.Sum,
                         name="hs.fa")
hb = hvd.allreduce_async(np.full(123, 2.0 * r, np.float32), op=hvd.Sum,
                         name="hs.fb")
from horovod_tpu.ops import collective_ops as cops  # noqa: E402

va, vb = cops.synchronize(ha), cops.synchronize(hb)
assert np.allclose(va, float(RSUM)), va[:4]
assert np.allclose(vb, 2.0 * RSUM), vb[:4]

# Tiny tensor (nelem < local_size): hierarchical falls back to the flat
# ring; on a multi-host topology that ring spans hosts, so it must route
# over TCP regardless of the plane.
out = hvd.allreduce(np.full(1, float(r + 1), np.float32), op=hvd.Sum,
                    name="hs.tiny")
assert np.allclose(out, float(SUM)), out

# Pool-sized tensor: each shm slot span clears the fan-out floor.
out = hvd.allreduce(np.full(POOL_N, float(r + 1), np.float32), op=hvd.Sum,
                    name="hs.pool")
assert np.allclose(out, float(SUM)), out[:4]

# --- Counter grading -------------------------------------------------------

ops1, bytes1, fb1, staged1 = hvd.shm_stats()
assert staged1 == staged0 == 0, \
    f"staged copies on the shm path: {staged0} -> {staged1}"
if expect_shm:
    assert ops1 > ops0 and bytes1 > bytes0, (ops0, ops1, bytes0, bytes1)
else:
    assert ops1 == ops0 and bytes1 == bytes0, (ops0, ops1, bytes0, bytes1)
if expect_fallback:
    assert fb1 > fb0, (fb0, fb1)

if os.environ.get("POOL_EXPECT_JOBS") == "1":
    _, jobs1, spans1 = hvd.reduce_pool_stats()
    assert jobs1 > jobs0, (jobs0, jobs1)
    assert spans1 > spans0, (spans0, spans1)

# Dispatch observability: HVD_HIERARCHICAL_ALLREDUCE must select the
# hierarchical backend for every allreduce, and never otherwise.
assert (hvd.backend_uses("hierarchical_allreduce") > 0) == hier_on
assert (hvd.backend_uses("ring_allreduce") == 0) == hier_on

if hier_on and expect_shm and L < s:
    # Multi-host: same-host traffic rides shm, so this rank's TCP bytes
    # to same-host peers stay far below its cross-plane shard traffic
    # (only sub-local_size fallbacks touch local TCP).
    host = r // L
    cross_tx = sum(hvd.peer_tx_bytes(q) for q in range(s) if q // L != host)
    local_tx = sum(hvd.peer_tx_bytes(q) for q in range(s)
                   if q // L == host and q != r)
    assert local_tx < cross_tx, (local_tx, cross_tx)

if os.environ.get("HVD_LOCKDEP") == "1":
    # Debug tier: the new shm/pool mutexes ("reduce_pool", the plane's
    # channel locks) and the shm-attach/shm-exchange blocking-syscall
    # annotations must leave the lock graph edge-clean.
    enabled, cycles, blocking, edges, acq = hvd.lockdep_stats()
    assert enabled
    assert cycles == 0 and blocking == 0, hvd.lockdep_report()
    # Real acquisitions were checked; zero EDGES is the ideal outcome
    # (the shm/pool paths never hold two core locks at once).
    assert acq > 0, (edges, acq)

hvd.barrier(name="hs.done")
hvd.shutdown()

tl = os.environ.get("HVD_TIMELINE")
if tl and r == 0 and expect_shm:
    text = open(tl).read()
    assert "TCP_SHM_EXCHANGE" in text, \
        "no TCP_SHM_EXCHANGE sub-events in the core timeline"

print(f"rank {r}: hier_shm PASS L={L} hier={int(hier_on)} "
      f"shm_ops={ops1 - ops0} shm_bytes={bytes1 - bytes0} "
      f"fallback={fb1 - fb0}", flush=True)
