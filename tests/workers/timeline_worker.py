"""Worker: dynamic start_timeline/stop_timeline (reference:
horovod_start_timeline/horovod_stop_timeline) — trace a window of
collectives at runtime, on top of / after the env-var timeline."""
import json
import os

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()
base = os.environ["TL_PATH"]

# Not yet started: stop is an error; untraced collectives run fine.
try:
    hvd.stop_timeline()
except RuntimeError:
    pass
else:
    raise SystemExit("stop before start should fail")
hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="untraced")

hvd.start_timeline(base, mark_cycles=True)
try:
    hvd.start_timeline(base)  # double start is an error
except RuntimeError:
    pass
else:
    raise SystemExit("double start should fail")
for i in range(3):
    hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name=f"traced.{i}")
hvd.stop_timeline()

# After stop: collectives keep working, new events aren't recorded.
hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="post.stop")

path = base if r == 0 else f"{base}.rank{r}"
events = json.load(open(path))
names = {e.get("tid") for e in events}
assert any("traced." in str(n) for n in names), names
assert not any("untraced" in str(n) or "post.stop" in str(n)
               for n in names), names
assert any(e.get("name") == "CYCLE_START" for e in events), \
    "mark_cycles did not take effect"

# Restart into a second window: the writer must be reusable.
hvd.start_timeline(base + ".2")
hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum, name="window2")
hvd.stop_timeline()
path2 = (base + ".2") if r == 0 else f"{base}.2.rank{r}"
ev2 = json.load(open(path2))
assert any("window2" in str(e.get("tid")) for e in ev2), ev2

print(f"rank {r}: timeline PASS", flush=True)
hvd.shutdown()
