"""MXNet binding worker over the CI mxnet shim (tests/shims).

Exercises the REAL horovod_tpu.mxnet code — every collective, both
broadcast_parameters forms, DistributedOptimizer, DistributedTrainer —
with the shim supplying the mxnet API over numpy. (Reference coverage
model: test/parallel/test_mxnet.py.)
"""
import mxnet as mx

assert "ci-shim" in mx.__version__, \
    "this worker must run against the CI shim, not a real mxnet"

import numpy as np  # noqa: E402
from mxnet import ndarray as nd  # noqa: E402

import horovod_tpu.mxnet as hvd  # noqa: E402

hvd.init()
r, s = hvd.rank(), hvd.size()

# -- collectives ------------------------------------------------------------
out = hvd.allreduce(nd.array(np.full(8, r + 1.0, np.float32)), op=hvd.Sum)
assert isinstance(out, nd.NDArray)
assert np.allclose(out.asnumpy(), s * (s + 1) / 2.0)

t = nd.array(np.full(4, float(r), np.float32))
hvd.allreduce_(t, op=hvd.Average)
assert np.allclose(t.asnumpy(), (s - 1) / 2.0)

outs = hvd.grouped_allreduce(
    [nd.array(np.full(3, r + 1.0, np.float32)),
     nd.array(np.full(5, 2.0 * r, np.float32))], op=hvd.Sum)
assert np.allclose(outs[0].asnumpy(), s * (s + 1) / 2.0)
assert np.allclose(outs[1].asnumpy(), 2.0 * sum(range(s)))

g = hvd.allgather(nd.array(np.full((2, 3), r, np.float32)))
assert g.shape == (2 * s, 3)

b = hvd.broadcast(nd.array(np.arange(4, dtype=np.float32) * (r + 1)),
                  root_rank=0)
assert np.allclose(b.asnumpy(), np.arange(4))

t2 = nd.array(np.arange(4).astype(np.float32) * (r + 1))
hvd.broadcast_(t2, root_rank=0)
assert np.allclose(t2.asnumpy(), np.arange(4))

a2a, rs_ = hvd.alltoall(nd.array(np.full(2 * s, float(r), np.float32)),
                        splits=[2] * s)
assert np.allclose(rs_.asnumpy(), 2)
assert np.allclose(a2a.asnumpy(),
                   np.repeat(np.arange(s, dtype=np.float32), 2))

rsc = hvd.reducescatter(nd.array(np.ones((2 * s, 3), np.float32) * (r + 1)),
                        op=hvd.Sum)
assert rsc.shape == (2, 3)
assert np.allclose(rsc.asnumpy(), s * (s + 1) / 2.0)

# -- broadcast_parameters ---------------------------------------------------
arg_params = {"w": nd.array(np.ones(3, np.float32) * (r + 10)),
              "b": nd.array(np.ones(2, np.float32) * (r + 20))}
hvd.broadcast_parameters(arg_params, root_rank=0, prefix="args")
assert np.allclose(arg_params["w"].asnumpy(), 10.0)
assert np.allclose(arg_params["b"].asnumpy(), 20.0)

gluon_params = {"w": mx.gluon.Parameter(
    "w", np.ones(3, np.float32) * (r + 5))}
hvd.broadcast_parameters(gluon_params, root_rank=0, prefix="gluon")
assert np.allclose(gluon_params["w"].data().asnumpy(), 5.0)

# -- DistributedOptimizer ---------------------------------------------------
opt = hvd.DistributedOptimizer(mx.optimizer.SGD(learning_rate=1.0),
                               op=hvd.Average)
w = nd.array(np.zeros(3, np.float32))
gr = nd.array(np.full(3, float(r + 1), np.float32))
opt.update(0, w, gr, opt.create_state(0, w))
# averaged grad = (s+1)/2, lr 1.0
assert np.allclose(w.asnumpy(), -(s + 1) / 2.0), w.asnumpy()
# grouped update path (list index)
w1, w2 = (nd.array(np.zeros(2, np.float32)) for _ in range(2))
g1 = nd.array(np.full(2, float(r + 1), np.float32))
g2 = nd.array(np.full(2, 2.0 * (r + 1), np.float32))
opt.update([1, 2], [w1, w2], [g1, g2], [None, None])
assert np.allclose(g1.asnumpy(), (s + 1) / 2.0), g1.asnumpy()
assert np.allclose(g2.asnumpy(), (s + 1) * 1.0), g2.asnumpy()

# -- DistributedTrainer -----------------------------------------------------
params = [mx.gluon.Parameter("w0", np.zeros(4, np.float32)),
          mx.gluon.Parameter("w1", np.zeros(2, np.float32))]
trainer = hvd.DistributedTrainer(params, "sgd",
                                 {"learning_rate": 0.5}, op=hvd.Average)
params[0].grad()[:] = np.full(4, float(r + 1), np.float32)
params[1].grad()[:] = np.full(2, 4.0 * (r + 1), np.float32)
trainer.step(batch_size=1)
assert np.allclose(params[0].data().asnumpy(), -0.5 * (s + 1) / 2.0)
assert np.allclose(params[1].data().asnumpy(), -2.0 * (s + 1) / 2.0)

print(f"rank {r}: MXNET PASS", flush=True)
hvd.shutdown()
