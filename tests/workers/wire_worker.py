"""Worker: syscall-minimal wire plane (csrc/wire.{h,cc}, collectives.cc
UringDuplex / WireSend, ISSUE 12). WIRE_MODE selects the scenario; every
rank asserts numeric parity against an exact f64 reference it recomputes
locally from seeded per-rank data, cross-rank bit-identity through digest
allgather, and the wire_state()/wire_stats() counters the scenario
promises. Rank 0 optionally dumps {digest, ops, syscalls} to
WIRE_STATS_OUT so the test can compare jobs run on different tiers.
"""
import hashlib
import json
import os

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()
mode = os.environ.get("WIRE_MODE", "parity")
expect = os.environ.get("WIRE_EXPECT")  # tier every rank should land on
N = int(os.environ.get("WIRE_N", "65536"))


def rank_data(rank, step=0, n=N):
    """Deterministic per-rank gradient in [-1, 1]; every rank can
    regenerate every peer's tensor, so the exact reference sum needs no
    second collective. Seeds match across tiers, so output digests from
    jobs forced onto different tiers must also match (the wire moves
    bytes, it never rounds)."""
    rng = np.random.RandomState(4321 + 97 * rank + step)
    return (rng.rand(n).astype(np.float32) * 2.0 - 1.0)


def reference(op, step=0, n=N):
    ref = np.zeros(n, np.float64)
    for peer in range(s):
        ref += rank_data(peer, step, n)
    if op is hvd.Average:
        ref /= s
    return ref


def assert_identical_across_ranks(out, tag):
    digest = hashlib.sha256(np.ascontiguousarray(out).tobytes()).hexdigest()
    digests = hvd.allgather_object(digest)
    assert len(set(digests)) == 1, (tag, digests)
    return digest


def parity_sweep():
    """Sum/Average over steps, plus a small tensor riding the fused path;
    returns the digest of the final output for cross-tier comparison."""
    digest = None
    for step, op in enumerate([hvd.Sum, hvd.Average, hvd.Sum, hvd.Average]):
        out = hvd.allreduce(rank_data(r, step), op=op, name=f"wire.{step}")
        ref = reference(op, step)
        err = np.abs(np.asarray(out, np.float64) - ref).max()
        # f32 ring reduction: rounding only in the adds, identical on
        # every tier — tolerance covers accumulation order, not the wire.
        assert err <= 1e-3 * s, (mode, step, err)
        digest = assert_identical_across_ranks(out, (mode, step))
    small = hvd.allreduce(rank_data(r, 9, 64), op=hvd.Sum, name="wire.small")
    assert np.abs(np.asarray(small, np.float64)
                  - reference(hvd.Sum, 9, 64)).max() <= 1e-4 * s
    return digest


live, probed, agreed, probe_failures, pinned = hvd.wire_state()

if mode == "parity":
    # Tier forced by HVD_WIRE: probe either lands on it or init fails, so
    # local probe == mesh agreement == the live data-plane tier.
    assert expect, "parity mode needs WIRE_EXPECT"
    assert live == probed == agreed == expect, (live, probed, agreed, expect)
    digest = parity_sweep()
    st = hvd.wire_stats()
    assert st["ops"] > 0 and st["syscalls"] > 0, st
    if expect == "uring":
        # The batching anatomy: multi-SQE submits, every SQE completed.
        assert st["uring_submits"] > 0, st
        assert st["uring_sqes"] >= st["uring_submits"], st
        assert st["uring_cqes"] >= st["uring_sqes"], st
        assert st["zc_sends"] == 0, st
    elif expect == "zerocopy":
        assert st["zc_sends"] > 0, st
        # Every notification the error queue delivered was reaped before
        # its buffer could be reused.
        assert st["zc_completions"] <= st["zc_sends"], st
        assert st["uring_submits"] == 0, st
    else:  # basic: the kill switch leaves every batched counter at zero
        for k in ("uring_submits", "uring_sqes", "uring_cqes", "uring_us",
                  "zc_sends", "zc_completions", "zc_copied", "zc_us"):
            assert st[k] == 0, (k, st)
    out_path = os.environ.get("WIRE_STATS_OUT")
    if out_path and r == 0:
        with open(out_path, "w") as f:
            json.dump({"tier": live, "digest": digest, "ops": st["ops"],
                       "syscalls": st["syscalls"]}, f)
elif mode == "fallback":
    # HVD_WIRE_PROBE_FAIL denied the upper rung(s): the probe must have
    # degraded (recording each refused rung) and the mesh must agree on
    # the surviving tier — collectives still correct on it.
    assert expect and probed == agreed == live == expect, (
        live, probed, agreed, expect)
    assert probe_failures >= 1, probe_failures
    parity_sweep()
elif mode == "numa":
    # HVD_NUMA=1 forces pinning even on a single-node box: every reduce
    # lane sits on its node's cpuset and says so.
    assert pinned >= 1, pinned
    parity_sweep()
else:
    raise SystemExit(f"unknown WIRE_MODE={mode}")

hvd.barrier()
hvd.shutdown()
print(f"rank {r}: wire {mode} ({live}) PASS", flush=True)
