"""TF/Keras elastic training worker (reference:
test/integration/test_elastic_tensorflow.py): TensorFlowKerasState
captures model + optimizer variables, commit() each iteration,
restore-on-failure, sync-on-membership-change.

Env knobs (same contract as elastic_train_worker.py):
- TEST_ITERS / TEST_SLEEP / TEST_LOG
- TEST_FAIL_SLOT + TEST_MARKER: worker that os._exit(1)s once at iter 2
"""
import os
import time

import numpy as np

import horovod_tpu.tensorflow as hvd

hvd.init()
import tensorflow as tf  # noqa: E402

ITERS = int(os.environ.get("TEST_ITERS", "6"))
SLEEP = float(os.environ.get("TEST_SLEEP", "0.1"))
FAIL_SLOT = os.environ.get("TEST_FAIL_SLOT")
MARKER = os.environ.get("TEST_MARKER", "")
WID = os.environ.get("HVD_WORKER_ID", "?")


def _should_die(it):
    if FAIL_SLOT is None or not MARKER or os.path.exists(MARKER):
        return False
    return it == 2 and WID.startswith(f"localhost-{FAIL_SLOT}-")


tf.random.set_seed(0)
model = tf.keras.Sequential([tf.keras.layers.Dense(1, use_bias=False)])
# momentum: the optimizer has SLOT variables, so restore/sync must carry
# them too or post-recovery updates diverge across ranks.
opt = tf.keras.optimizers.SGD(0.05, momentum=0.9)
model(tf.zeros((1, 6)))  # build variables

X = np.random.default_rng(0).normal(size=(32, 6)).astype(np.float32)
Y = (X @ np.ones((6, 1), np.float32))

state = hvd.elastic.TensorFlowKerasState(model, opt, iteration=0)


@hvd.elastic.run
def train(state):
    while state.iteration < ITERS:
        r, s = hvd.rank(), hvd.size()
        if _should_die(state.iteration):
            open(MARKER, "w").write("died\n")
            os._exit(1)
        xb, yb = tf.constant(X[r::s]), tf.constant(Y[r::s])
        with tf.GradientTape() as t:
            loss = tf.reduce_mean((model(xb) - yb) ** 2)
        tape = hvd.DistributedGradientTape(t)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        # A standalone collective rides the NATIVE custom-op path
        # (csrc/tf_ops.cc): when a peer dies here, the failure surfaces as
        # tf.errors.InternalError, and elastic.run must map it back to the
        # restore-and-rendezvous flow (not crash this worker).
        metric = hvd.allreduce(loss, op=hvd.Average,
                               name=f"elastic.metric.{state.iteration}")
        assert np.isfinite(float(metric))
        state.iteration += 1
        state.commit()
        time.sleep(SLEEP)


train(state)

w = model.trainable_variables[0].numpy()
gathered = hvd.allgather(tf.constant(w.reshape(1, -1)), name="final.w")
gw = np.asarray(gathered)
assert np.allclose(gw, gw[0], atol=1e-6), gw

log = os.environ.get("TEST_LOG")
if log:
    with open(log, "a") as f:
        f.write(f"final rank={hvd.rank()} size={hvd.size()} "
                f"iter={state.iteration}\n")
print(f"rank {hvd.rank()}: tf elastic PASS", flush=True)
hvd.shutdown()
