"""Negotiation-cycle latency probe: N back-to-back small allreduces.

Each blocking allreduce of a tiny tensor costs ~one negotiation cycle
(request gather -> coordinate -> response bcast -> ring on 256 bytes), so
mean seconds/op ~= cycle latency. Rank 0 writes the mean to $STRESS_OUT.
Used by the 8-vs-32-rank control-plane scaling test (reference concern:
Controller::ComputeResponseList gather semantics — a serial per-worker
recv makes the cycle O(N) sequential round-trips).
"""
import os
import time

import numpy as np

import horovod_tpu as hvd

hvd.init()
rounds = int(os.environ.get("STRESS_ROUNDS", "40"))
x = np.ones(64, dtype=np.float32)
for _ in range(5):  # warmup: mesh formed, code paths hot
    hvd.allreduce(x, op=hvd.Sum)
t0 = time.perf_counter()
for _ in range(rounds):
    y = hvd.allreduce(x, op=hvd.Sum)
dt = (time.perf_counter() - t0) / rounds
assert np.allclose(y, hvd.size()), y[:4]
if hvd.rank() == 0 and os.environ.get("STRESS_OUT"):
    with open(os.environ["STRESS_OUT"], "w") as f:
        f.write(f"{dt:.6f}\n")
hvd.shutdown()
