"""Worker: response-cache behavior across 2 ranks (reference:
horovod/common/response_cache.cc — bit-vector coordination, capacity,
invalidation on signature change).

Covers: steady-state hits (repeated same-name collectives negotiate as bit
positions), invalidation (shape change forces full renegotiation, then
re-caches), capacity-LRU eviction, and correctness of every cached result.
"""
import os
import sys

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()

# --- steady-state hits: same tensor name, many iterations
for i in range(12):
    x = np.full((16,), float(r + 1 + i), np.float32)
    out = hvd.allreduce(x, op=hvd.Sum, name="cached.grad")
    expect = sum(range(1 + i, s + 1 + i))
    assert np.allclose(out, expect), (i, out[0], expect)

hits, misses, entries = hvd.cache_stats()
# First iteration is a miss; the rest should ride the bit-vector path.
assert hits >= 8, (hits, misses, entries)
assert entries >= 1, entries

# --- grouped allreduce BYPASSES the cache by design: a cache hit skips
# the controller's group table, so an LRU eviction of SOME members would
# strand the rest in pending_groups_ forever (group count never reached
# -> stall shutdown). Full negotiation per cycle costs ~100B/tensor on a
# control plane that gathers concurrently — noise next to the gradient
# bytes. Results must stay correct and hit/entry counts must NOT grow.
entries_before = hvd.cache_stats()[2]
for i in range(6):
    tensors = [np.full((4,), float(r + i), np.float32),
               np.full((8,), float(r + 2 * i), np.float32)]
    outs = hvd.grouped_allreduce(tensors, op=hvd.Average, name="cached.group")
    assert np.allclose(outs[0], np.mean(np.arange(s)) + i)
    assert np.allclose(outs[1], np.mean(np.arange(s)) + 2 * i)

h2, _, entries_after = hvd.cache_stats()
assert h2 == hits, (h2, hits)          # no grouped hits
assert entries_after == entries_before  # no grouped insertions

# --- invalidation: same name, new shape -> full renegotiation, right answer
for shape in [(16,), (32,), (32,), (8, 2)]:
    x = np.full(shape, float(r + 1), np.float32)
    out = hvd.allreduce(x, op=hvd.Sum, name="cached.grad")
    assert out.shape == shape
    assert np.allclose(out, sum(range(1, s + 1))), out

# dtype change invalidates too
out = hvd.allreduce(np.full((16,), float(r + 1), np.float64),
                    op=hvd.Sum, name="cached.grad")
assert out.dtype == np.float64
assert np.allclose(out, sum(range(1, s + 1)))

# --- other cacheable op types keep working through the cache
for i in range(3):
    g = hvd.allgather(np.full((r + 1, 2), float(r), np.float32),
                      name="cached.gather")
    assert g.shape[0] == sum(range(1, s + 1))
    b = hvd.broadcast(np.full((4,), float(r), np.float32), root_rank=0,
                      name="cached.bcast")
    assert np.allclose(b, 0.0)
    rs = hvd.reducescatter(np.arange(s * 2, dtype=np.float32),
                           op=hvd.Sum, name="cached.rs")
    assert np.allclose(rs, np.arange(r * 2, r * 2 + 2) * s)

final_hits, final_misses, final_entries = hvd.cache_stats()
assert final_hits > h2
cap = int(os.environ.get("HVD_CACHE_CAPACITY", "1024"))
assert final_entries <= cap, (final_entries, cap)

hvd.shutdown()
print(f"rank {r}: cache PASS hits={final_hits} misses={final_misses} "
      f"entries={final_entries}", flush=True)
sys.exit(0)
