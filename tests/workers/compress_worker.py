"""Worker: compressed collectives (csrc/core.cc Int8RingKernel /
TopKKernel, ISSUE 11). COMPRESS_MODE selects the scenario; every rank
asserts numeric parity (or the error-feedback convergence bound) against
an exact f32 reference it recomputes locally from the seeded per-rank
data, then checks the compress_stats() counters the scenario promises.
"""
import hashlib
import os

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()
mode = os.environ.get("COMPRESS_MODE", "parity")
N = int(os.environ.get("COMPRESS_N", "4096"))


def rank_data(rank, step=0, n=N):
    """Deterministic per-rank gradient in [-1, 1]; every rank can
    regenerate every peer's tensor, so the exact f32 reference sum needs
    no second (uncompressed) collective."""
    rng = np.random.RandomState(1234 + 97 * rank + step)
    return (rng.rand(n).astype(np.float32) * 2.0 - 1.0)


def reference(op, step=0, n=N):
    ref = np.zeros(n, np.float64)
    for peer in range(s):
        ref += rank_data(peer, step, n)
    if op is hvd.Average:
        ref /= s
    return ref


def assert_identical_across_ranks(out, tag):
    """Both codecs promise bit-identical outputs on every rank (int8:
    every rank adopts the chunk owner's decode; topk: exact f32 densify
    in member order) — compare byte digests through allgather_object."""
    digest = hashlib.sha256(np.ascontiguousarray(out).tobytes()).hexdigest()
    digests = hvd.allgather_object(digest)
    assert len(set(digests)) == 1, (tag, digests)


if mode == "parity":
    # Codec comes from HVD_COMPRESS (int8, or topk with
    # HVD_COMPRESS_TOPK_FRAC=1.0 so sparsification drops nothing and the
    # exchange must be numerically faithful on its own).
    codec = os.environ["HVD_COMPRESS"]
    live, configured, frac = hvd.compress_state()
    assert live == configured == codec, (live, configured, codec)
    # int8 error bound: each of the m quantize hops rounds a partial sum
    # whose |max| <= s, at step <= s/127, error <= step/2 per element.
    tol = s * (s / 127.0) if codec == "int8" else 1e-5
    for step, op in enumerate([hvd.Sum, hvd.Average, hvd.Sum, hvd.Average]):
        out = hvd.allreduce(rank_data(r, step), op=op,
                            name=f"parity.{step}")
        ref = reference(op, step)
        err = np.abs(np.asarray(out, np.float64) - ref).max()
        assert err <= tol, (codec, step, err, tol)
        assert_identical_across_ranks(out, (codec, step))
    st = hvd.compress_stats()
    key = "int8_ops" if codec == "int8" else "topk_ops"
    assert st[key] >= 4, st
    assert st["wire_bytes"] > 0 and st["raw_bytes"] > 0, st
    if codec == "int8":
        # ~4x: int8 payload + one 4-byte scale per hop vs f32 payload.
        assert st["raw_bytes"] / st["wire_bytes"] >= 3.5, st
elif mode == "fp16" or mode == "bf16":
    # Binding-level cast compressors: compress -> (half-width wire dtype)
    # core allreduce -> decompress. Parity within the wire dtype's
    # precision; reduce.h converts per element so Sum/Average both hold.
    from horovod_tpu.compression import Compression

    comp = Compression.fp16 if mode == "fp16" else Compression.bf16
    if mode == "bf16":
        try:
            import ml_dtypes  # noqa: F401
        except ImportError:
            hvd.barrier()
            hvd.shutdown()
            print(f"rank {r}: compress[{mode}] PASS (ml_dtypes absent, "
                  "cast skipped)", flush=True)
            raise SystemExit(0)
    # fp16 sums: ~2^-11 relative per element, s terms; bf16: ~2^-8.
    tol = s * (2.0 ** -8 if mode == "bf16" else 2.0 ** -10)
    for step, op in enumerate([hvd.Sum, hvd.Average]):
        wire, ctx = comp.compress(rank_data(r, step))
        out = comp.decompress(
            np.asarray(hvd.allreduce(wire, op=op, name=f"{mode}.{step}")),
            ctx)
        ref = reference(op, step)
        err = np.abs(np.asarray(out, np.float64) - ref).max()
        assert err <= tol * max(1.0, np.abs(ref).max()), (mode, step, err)
    # The cast compressors ride the normal wire — no core codec engages.
    st = hvd.compress_stats()
    assert st["int8_ops"] == 0 and st["topk_ops"] == 0, st
elif mode == "ef":
    # Error-feedback convergence: a FIXED per-rank gradient allreduced T
    # times under a lossy codec. EF telescopes — each rank's encoded
    # stream sums to T*g - r_T with r_T bounded once every coordinate has
    # cycled through selection (~1/frac steps for topk) — so the running
    # mean of the outputs converges to the exact sum at rate ~1/T, while
    # a feedback-free codec would keep a constant per-step bias forever.
    T = int(os.environ.get("COMPRESS_EF_STEPS", "64"))
    g = rank_data(r)
    ref = reference(hvd.Sum)
    acc = np.zeros(N, np.float64)
    err1 = err_half = None
    norms = []
    for t in range(T):
        out = np.asarray(
            hvd.allreduce(g.copy(), op=hvd.Sum, name="ef.grad"), np.float64)
        if err1 is None:
            err1 = np.abs(out - ref).max()
        acc += out
        if t + 1 == T // 2:
            err_half = np.abs(acc / (t + 1) - ref).max()
        norms.append(hvd.compress_stats()["residual_norm"])
    errT = np.abs(acc / T - ref).max()
    # The single step must be measurably lossy (else convergence is
    # vacuous), the T-step mean must beat it 4x, and the trajectory must
    # still be descending at T/2 -> T (rules out a constant bias).
    assert err1 > 1e-3, f"codec not lossy enough to test EF: {err1}"
    assert errT <= err1 / 4.0, (err1, errT, T)
    assert errT < err_half, (err_half, errT)
    # Residuals stay bounded: the tail of the trajectory doesn't grow.
    assert norms[-1] <= 2.0 * max(norms[: T // 2]) + 1e-9, norms[-5:]
    assert hvd.compress_stats()["residual_buckets"] >= 1
elif mode == "ratio":
    # Bytes-on-wire accounting under a lossy codec. topk(frac) at s
    # ranks ships 8*k*(s-1) bytes of the 4*n*(s-1)*2/s an uncompressed
    # f32 ring would move: ratio n/(k*s) — 4096/(41*4) ~ 25x at 1%.
    expect = float(os.environ["COMPRESS_EXPECT_RATIO"])
    for step in range(4):
        hvd.allreduce(rank_data(r, step), op=hvd.Sum, name=f"ratio.{step}")
    st = hvd.compress_stats()
    assert st["int8_ops"] + st["topk_ops"] >= 4, st
    ratio = st["raw_bytes"] / st["wire_bytes"]
    assert ratio >= expect, (ratio, expect, st)
elif mode == "off":
    # Kill switch: no HVD_COMPRESS -> no codec backend runs, every
    # counter stays zero, and the merged compression_stats() proves total
    # disengagement (the wire-byte-identical claim, counter-proven).
    live, configured, frac = hvd.compress_state()
    assert live is None and configured is None, (live, configured)
    for step in range(4):
        out = hvd.allreduce(rank_data(r, step), op=hvd.Sum,
                            name=f"off.{step}")
        assert np.allclose(np.asarray(out, np.float64),
                           reference(hvd.Sum, step), atol=1e-4), step
    assert hvd.compress_stats() == {
        "int8_ops": 0, "topk_ops": 0, "raw_bytes": 0, "wire_bytes": 0,
        "residual_norm": 0.0, "residual_buckets": 0}, hvd.compress_stats()
    assert hvd.backend_uses("int8_ring_allreduce") == 0
    assert hvd.backend_uses("topk_allreduce") == 0
    merged = hvd.compression_stats()
    assert merged["engagements"] == 0 and merged["bytes_saved"] == 0, merged
elif mode == "runtime":
    # hvd.set_compression mid-run: starts off, every rank flips int8 on
    # (codec engages), then off again (counters freeze). The negotiation
    # is self-synchronizing, so the flip needs no barrier to be safe —
    # the barrier here only makes the counter assertions deterministic.
    assert hvd.compress_state()[0] is None
    out = hvd.allreduce(rank_data(r), op=hvd.Sum, name="rt.pre")
    assert hvd.compress_stats()["int8_ops"] == 0
    hvd.set_compression("int8")
    hvd.barrier()
    for step in range(3):
        out = hvd.allreduce(rank_data(r, step), op=hvd.Sum, name="rt.on")
        err = np.abs(np.asarray(out, np.float64)
                     - reference(hvd.Sum, step)).max()
        assert err <= s * (s / 127.0), (step, err)
    ops_on = hvd.compress_stats()["int8_ops"]
    assert ops_on >= 3, ops_on
    hvd.set_compression(None)
    hvd.barrier()
    out = hvd.allreduce(rank_data(r), op=hvd.Sum, name="rt.post")
    assert np.allclose(np.asarray(out, np.float64), reference(hvd.Sum),
                       atol=1e-4)
    assert hvd.compress_stats()["int8_ops"] == ops_on
else:
    raise SystemExit(f"unknown COMPRESS_MODE {mode!r}")

hvd.barrier()
hvd.shutdown()
print(f"rank {r}: compress[{mode}] PASS", flush=True)
