"""Worker: HVD_ZEROCOPY=0 disables the scatter-gather path entirely —
state reports disabled, large allreduces ride the staging path, and the
zero-copy counters stay flat (single rank: the m<=1 path would skip SG
anyway, so the state+counter assertions are the point here)."""
import numpy as np

import horovod_tpu as hvd

hvd.init()

enabled, threshold = hvd.zerocopy_state()
assert not enabled, "HVD_ZEROCOPY=0 must report the path disabled"
assert threshold == 4096, threshold

n = 8192  # 32 KB of f32, far above the 4 KB threshold
out = hvd.allreduce(np.arange(n, dtype=np.float32), op=hvd.Sum,
                    name="off.big")
assert np.array_equal(out, np.arange(n, dtype=np.float32)), out[:4]
zc_ops, zc_bytes, st_ops, st_bytes = hvd.zerocopy_stats()
assert zc_ops == 0 and zc_bytes == 0, (zc_ops, zc_bytes)
assert st_ops >= 1, st_ops

hvd.shutdown()
print("zerocopy-off PASS", flush=True)
