"""Worker: the five in-mesh collectives x dtypes through the GLOBAL
(multi-process) device mesh — the ICI-plane analog of the host path's
op x dtype matrix in collective_worker.py (reference:
test/parallel/test_tensorflow.py collective coverage; VERDICT r2 weak #3).

Launched by tpurun with a jax.distributed coordinator; every process
contributes n_local virtual CPU devices to one global mesh, and each
collective below executes as a single XLA op whose communication crosses
process boundaries on device.
"""
from horovod_tpu.jax.distributed import force_cpu_platform

force_cpu_platform(2)

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import shard_map  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu.jax as hvd  # noqa: E402
from horovod_tpu.ops import jax_ops  # noqa: E402

hvd.init()
r, s = hvd.rank(), hvd.size()
assert hvd.is_multiprocess(), "global mesh did not form"
mesh = hvd.global_mesh()
n_local = len(jax.local_devices())
n = mesh.shape["data"]
assert n == s * n_local, (n, s, n_local)


def run(fn, local_in):
    f = jax.jit(shard_map(fn, mesh=mesh, in_specs=P("data"),
                          out_specs=P("data"), check_vma=False))
    return f(hvd.shard_local_batch(local_in, mesh))


def check(out, expected_global):
    """Verify this process's addressable shards against the full expected
    global array (each shard knows its own slice via .index)."""
    for sh in out.addressable_shards:
        got = np.asarray(sh.data)
        want = expected_global[sh.index]
        assert got.shape == want.shape, (got.shape, want.shape)
        np.testing.assert_allclose(
            np.asarray(got, np.float64), np.asarray(want, np.float64),
            rtol=2e-2 if got.dtype == jnp.bfloat16 else 1e-5)


k, d = 3, 2  # rows per device, features
G = np.arange(n * k * d, dtype=np.float32).reshape(n * k, d)
blocks = G.reshape(n, k, d)
mine = G[r * n_local * k:(r + 1) * n_local * k]  # this process's rows

# -- allreduce: Sum / Average / Min / Max, f32 + bf16 + i32
for dtype in (np.float32, jnp.bfloat16, np.int32):
    x = mine.astype(dtype)
    out = run(lambda v: jax_ops.allreduce(v, "data", op=jax_ops.Sum), x)
    check(out, np.tile(blocks.sum(0), (n, 1)).astype(np.float64))
out = run(lambda v: jax_ops.allreduce(v, "data", op=jax_ops.Average), mine)
check(out, np.tile(blocks.mean(0), (n, 1)))
out = run(lambda v: jax_ops.allreduce(v, "data", op=jax_ops.Min), mine)
check(out, np.tile(blocks.min(0), (n, 1)))
out = run(lambda v: jax_ops.allreduce(v, "data", op=jax_ops.Max), mine)
check(out, np.tile(blocks.max(0), (n, 1)))

# -- allgather: every device receives the full G
out = run(lambda v: jax_ops.allgather(v, "data"), mine)
check(out, np.tile(G, (n, 1)))

# -- broadcast from a non-zero root index
root = min(2, n - 1)
out = run(lambda v: jax_ops.broadcast(v, "data", root_index=root), mine)
check(out, np.tile(blocks[root], (n, 1)))

# -- alltoall: device i's row j goes to device j's position i
m = 2
A = np.arange(n * n * m, dtype=np.float32).reshape(n * n, m)
a_mine = A[r * n_local * n:(r + 1) * n_local * n]
out = run(lambda v: jax_ops.alltoall(v, "data"), a_mine)
expect = np.empty_like(A)
for i in range(n):
    for j in range(n):
        expect[i * n + j] = A[j * n + i]
check(out, expect)

# -- reducescatter: sum across devices, scatter dim0
q = 2
Z = np.arange(n * n * q * d, dtype=np.float32).reshape(n * n * q, d)
z_mine = Z[r * n_local * n * q:(r + 1) * n_local * n * q]
out = run(lambda v: jax_ops.reducescatter(v, "data", op=jax_ops.Sum), z_mine)
zb = Z.reshape(n, n, q, d)  # [device, block, q, d]
expect = zb.sum(0).reshape(n * q, d)  # block i lands on device i
check(out, expect)

hvd.shutdown()
print(f"rank {r}: mesh matrix PASS", flush=True)
