"""Eviction-under-load worker for the sanitizer tiers (docs/elastic.md).

2-rank static job exercising the peer-liveness/eviction machinery's
concurrency surface: rank 1 arms the in-core blackhole fault hook
mid-run (its background thread parks holding every socket open — the
wedge), while rank 0 keeps issuing collectives and a frontend thread on
BOTH ranks polls hvd.elastic_stats() — the frontend reads of the
heartbeat-miss/eviction counters the coordinator thread is concurrently
bumping are exactly what TSAN validates here.

Rank 0 must observe the wedge as missed control-plane deadlines, evict
rank 1 by name (RankEvictedError), and record the eviction in its
counters. Rank 1's Python side stays live (only its core is parked); it
waits for rank 0's sync file, prints PASS, and _exits. Both ranks PASS.

Env: EVICT_SYNC (sync file path), HVD_FAULT_INJECT=1,
HVD_PEER_TIMEOUT_MS / HVD_PEER_EVICT_MISSES set by the test.
"""

import os
import sys
import threading
import time

import numpy as np

import horovod_tpu as hvd

SYNC = os.environ["EVICT_SYNC"]

hvd.init()
rank = hvd.rank()
assert hvd.size() == 2, hvd.size()

stop = threading.Event()


def _poll_stats():
    # Frontend reads racing the coordinator's counter updates.
    while not stop.is_set():
        hvd.elastic_stats()
        time.sleep(0.002)


poller = threading.Thread(target=_poll_stats, daemon=True)
poller.start()

for it in range(10):
    hvd.allreduce(np.ones(1024, np.float32), op=hvd.Sum, name=f"warm.{it}")

if rank == 1:
    assert hvd.fault_trigger("blackhole"), "fault hook not armed"
    # The core is now parked; this thread is not. Wait for rank 0 to
    # confirm the eviction, then vanish (os._exit: no core shutdown —
    # the parked background thread would never join).
    deadline = time.time() + 300
    while not os.path.exists(SYNC):
        if time.time() > deadline:
            print("FAIL: rank 0 never confirmed eviction", flush=True)
            os._exit(1)
        time.sleep(0.1)
    stop.set()
    print("PASS", flush=True)
    os._exit(0)

# rank 0: keep the load up until the miss escalation names the wedge.
err = None
deadline = time.time() + 300
it = 0
try:
    while time.time() < deadline:
        hvd.allreduce(np.ones(1024, np.float32), op=hvd.Sum,
                      name=f"post.{it}")
        it += 1
except hvd.RankEvictedError as e:
    err = e
assert err is not None, "no eviction within the deadline"
assert err.rank == 1, err
stats = hvd.elastic_stats()
assert stats["evictions"] >= 1, stats
assert stats["last_evicted_rank"] == 1, stats
assert stats["heartbeat_misses"] >= 1, stats
stop.set()
with open(SYNC, "w") as f:
    f.write("evicted")
print("PASS", flush=True)
sys.stdout.flush()
os._exit(0)
