"""Worker: a stalled collective past HVD_STALL_SHUTDOWN_TIME_SECONDS must
abort the whole job with HorovodInternalError instead of hanging — even when
stall WARNINGS are disabled (HVD_STALL_CHECK_TIME_SECONDS=0), the explicitly
configured shutdown threshold still fires (reference: stall-check shutdown
semantics in horovod docs/troubleshooting)."""
import os
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.exceptions import HorovodInternalError

hvd.init()
r = hvd.rank()

if r == 1:
    # Never submit the collective: rank 0's request ages past the shutdown
    # threshold on the coordinator.
    time.sleep(6.0)
    print(f"rank {r}: slept through the stall shutdown", flush=True)
    os._exit(0)

try:
    hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="stall.shutdown")
    raise SystemExit(f"rank {r}: allreduce unexpectedly succeeded")
except HorovodInternalError:
    print(f"rank {r}: stall shutdown raised HorovodInternalError as expected",
          flush=True)
