import os, numpy as np
import horovod_tpu as hvd
hvd.init()
r, s = hvd.rank(), hvd.size()
# allreduce
x = np.full(1000, float(r + 1), dtype=np.float32)
y = hvd.allreduce(x, op=hvd.Sum)
assert np.allclose(y, sum(range(1, s + 1))), y[:4]
# average
y = hvd.allreduce(x, op=hvd.Average)
assert np.allclose(y, sum(range(1, s + 1)) / s), y[:4]
# allgather (uneven)
g = hvd.allgather(np.full((r + 1, 2), r, dtype=np.int32))
assert g.shape == (s * (s + 1) // 2, 2), g.shape
exp = np.concatenate([np.full((i + 1, 2), i) for i in range(s)])
assert (g == exp).all()
# broadcast
b = hvd.broadcast(np.arange(5, dtype=np.float64) * (r + 1), root_rank=2 % s)
assert np.allclose(b, np.arange(5) * (2 % s + 1))
# alltoall with splits
t = np.arange(s * 3, dtype=np.float32).reshape(s * 3) + 100 * r
out, rs = hvd.alltoall(t, splits=[3] * s)
assert out.shape == (3 * s,)
assert (rs == 3).all()
# reducescatter
m = np.ones((s * 2 + 1, 4), dtype=np.float32) * (r + 1)
rsout = hvd.reducescatter(m, op=hvd.Sum)
assert np.allclose(rsout, sum(range(1, s + 1)))
# allgather_object: ragged picklable objects, ordered by rank
objs = hvd.allgather_object({"rank": r, "data": list(range(r + 1))})
assert [o["rank"] for o in objs] == list(range(s)), objs
assert objs[-1]["data"] == list(range(s)), objs
# grouped allgather + grouped reducescatter (atomic group negotiation)
gouts = hvd.grouped_allgather([np.full((r + 1, 2), r, np.float32),
                               np.full((2,), float(r), np.float32)])
assert gouts[0].shape == (s * (s + 1) // 2, 2)
assert gouts[1].shape == (2 * s,)
routs = hvd.grouped_reducescatter(
    [np.ones((s * 2, 3), np.float32) * (r + 1),
     np.ones((s, 1), np.float32) * (r + 1)], op=hvd.Sum)
assert routs[0].shape == (2, 3) and np.allclose(routs[0], sum(range(1, s + 1)))
assert routs[1].shape == (1, 1) and np.allclose(routs[1], sum(range(1, s + 1)))
# grouped allreduce (fusion)
outs = hvd.grouped_allreduce([np.full(10, float(r), np.float32), np.full(20, 2.0 * r, np.float32)], op=hvd.Sum)
assert np.allclose(outs[0], sum(range(s)))
assert np.allclose(outs[1], 2 * sum(range(s)))
# fp16 + bf16
h = hvd.allreduce(np.full(7, 1.0, dtype=np.float16), op=hvd.Sum)
assert np.allclose(h.astype(np.float32), s)
# 0-d scalars keep their shape (regression: ascontiguousarray promotes to 1-d)
sc = hvd.allreduce(np.float32(r + 1), op=hvd.Sum)
assert np.shape(sc) == () and float(sc) == s * (s + 1) / 2, sc
sb = hvd.broadcast(np.float64(r), root_rank=0)
assert np.shape(sb) == () and float(sb) == 0.0, sb
# adasum (power of 2 sizes only)
if s & (s - 1) == 0:
    a = hvd.allreduce(np.full(9, float(r + 1), np.float32), op=hvd.Adasum)
    assert a.shape == (9,)
hvd.barrier()
hvd.shutdown()
print(f"rank {r}: PASS", flush=True)
