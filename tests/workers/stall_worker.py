"""Worker: rank 1 delays a collective so the stall inspector (on the
coordinator) should warn iff HVD_STALL_CHECK_TIME_SECONDS > 0."""
import time

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()

if r == 1:
    time.sleep(2.5)
out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="slow.x")
assert np.allclose(out, s)

hvd.shutdown()
print(f"rank {r}: stall worker done", flush=True)
