import numpy as np
import horovod_tpu as hvd
hvd.init()
r, s = hvd.rank(), hvd.size()
# identical vectors -> adasum == average == the vector itself
v = np.array([1.0, 2.0, 3.0, 4.0], dtype=np.float32)
out = hvd.allreduce(v.copy(), op=hvd.Adasum)
assert np.allclose(out, v, atol=1e-5), (r, out)
# orthogonal vectors (2 ranks): adasum == sum
if s == 2:
    v2 = np.zeros(4, dtype=np.float32); v2[r] = 1.0
    out2 = hvd.allreduce(v2, op=hvd.Adasum)
    exp = np.zeros(4); exp[0] = 1; exp[1] = 1
    assert np.allclose(out2, exp, atol=1e-5), (r, out2)
hvd.shutdown()
print(f"rank {r}: ADASUM PASS", flush=True)
