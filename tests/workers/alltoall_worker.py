"""Worker: tiered alltoallv (csrc collectives.cc AlltoAllv, ISSUE 19).

A2A_MODE selects the scenario. `parity` sweeps even splits over every
dtype, uneven splits with zero-length chunks, and one large op that
engages the tier under test (A2A_EXPECT: basic | shm | sg), asserting
exact provenance on every received chunk plus the alltoall_stats()
counters the tier promises. Rank 0 optionally dumps the rank-ordered
output digests and counter deltas to A2A_STATS_OUT so the test can
prove bit-identity across jobs forced onto different tiers. `compress`
exercises the HVD_ALLTOALL_COMPRESS int8 wire codec: f32 parity within
one quantization step, non-f32 exempt (bit-exact), and the >= 3.5x
raw/wire byte ratio via compress_stats().
"""
import hashlib
import json
import os

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()
mode = os.environ.get("A2A_MODE", "parity")
expect = os.environ.get("A2A_EXPECT")  # tier the big op must ride
N = int(os.environ.get("A2A_N", "65536"))  # rows per peer in the big op

DTYPES = (np.float32, np.float64, np.float16,
          np.int32, np.int64, np.uint8)


def chunk(src, dst, rows, d=4, dtype=np.float32):
    """Deterministic provenance block for the src->dst chunk: every cell
    is unique per (src, dst, slot) and exactly representable in every
    swept dtype (values stay < 120, integral)."""
    base = np.arange(rows * d, dtype=np.int64) * 31 + src * 101 + dst * 7
    return (base % 120).astype(dtype).reshape(rows, d)


def big_data(src, dst, rows=None):
    """Large f32 chunk in [-1, 1): seeds depend only on (src, dst), so
    the receiver regenerates its exact expectation locally and digests
    from jobs forced onto different tiers must match bit-for-bit (the
    tiers move bytes, they never round)."""
    rng = np.random.RandomState(977 * src + 13 * dst + 5)
    return (rng.rand(N if rows is None else rows)
            .astype(np.float32) * 2.0 - 1.0)


def even_sweep():
    """Every dtype, uniform splits: peer p's chunk lands in slot p
    bit-exactly."""
    rows = 3
    for dtype in DTYPES:
        t = np.concatenate([chunk(r, j, rows, 4, dtype) for j in range(s)])
        out = hvd.alltoall(t, name=f"a2a.even.{np.dtype(dtype).name}")
        assert out.shape == (rows * s, 4), (dtype, out.shape)
        for p in range(s):
            got = out[p * rows:(p + 1) * rows]
            want = chunk(p, r, rows, 4, dtype)
            assert got.dtype == want.dtype, (dtype, got.dtype)
            assert np.array_equal(got, want), (np.dtype(dtype).name, p)


def uneven_sweep():
    """Ragged splits including zero-length chunks: recv_splits mirror the
    senders' row counts and payloads keep provenance."""
    splits = [(r + j) % 4 for j in range(s)]
    t = np.concatenate([chunk(r, j, splits[j], 4) for j in range(s)])
    out, rcounts = hvd.alltoall(t, splits=splits, name="a2a.uneven")
    off = 0
    for p in range(s):
        n = (p + r) % 4
        assert rcounts[p] == n, (p, rcounts)
        assert np.array_equal(out[off:off + n], chunk(p, r, n, 4)), p
        off += n
    assert out.shape[0] == off, (out.shape, off)


def big_op(tag="big"):
    """One op large enough to engage the shm / SG tier; returns the
    output digest for cross-tier bit-identity comparison."""
    t = np.concatenate([big_data(r, j) for j in range(s)])
    out = hvd.alltoall(t, name=f"a2a.{tag}")
    for p in range(s):
        assert np.array_equal(out[p * N:(p + 1) * N], big_data(p, r)), p
    return hashlib.sha256(np.ascontiguousarray(out).tobytes()).hexdigest()


if mode == "parity":
    assert expect in ("basic", "shm", "sg"), expect
    tiered, copt = hvd.alltoall_state()
    assert tiered == (os.environ.get("HVD_ALLTOALL", "auto") != "basic"), (
        tiered, os.environ.get("HVD_ALLTOALL"))
    # The opt-in flag mirrors the env; with no codec live it is inert
    # and every f32 op below still lands bit-exact.
    assert copt == (os.environ.get("HVD_ALLTOALL_COMPRESS") == "1"), copt
    ops0, bytes0, shm0, sg0 = hvd.alltoall_stats()
    even_sweep()
    uneven_sweep()
    digest = big_op()
    ops1, bytes1, shm1, sg1 = hvd.alltoall_stats()
    n_ops = len(DTYPES) + 2
    assert ops1 - ops0 == n_ops, (ops0, ops1, n_ops)
    assert bytes1 - bytes0 > 0, (bytes0, bytes1)
    if expect == "shm":
        # Threshold 0: every exchange's whole pairwise schedule rides shm.
        assert shm1 - shm0 == n_ops, (shm0, shm1, n_ops)
        assert sg1 == sg0, (sg0, sg1)
    elif expect == "sg":
        # Only the big op clears HVD_ZEROCOPY_THRESHOLD: its s-1 pairwise
        # rounds all take the UringDuplex linked-wave path.
        assert sg1 - sg0 == s - 1, (sg0, sg1, s)
        assert shm1 == shm0, (shm0, shm1)
    else:  # basic (or the HVD_ALLTOALL kill switch): tiers stay dark
        assert shm1 == shm0 and sg1 == sg0, (shm0, shm1, sg0, sg1)
    # EP capacity gauges ride the same plane: publish one raw report and
    # one through the parallel-package helper, read both back, and prove
    # the validation rejects an impossible report.
    r0 = hvd.ep_stats()[0]
    hvd.ep_report(0.125, 64, 8)
    try:  # the mesh package needs jax >= 0.8; fall back to the raw gauge
        from horovod_tpu.parallel import report_dispatch
    except ImportError:
        report_dispatch = None
    if report_dispatch is not None:
        assert report_dispatch(0.25, 16) is True
    else:
        hvd.ep_report(0.25, 16, 4)
    reports, tokens, dropped, last = hvd.ep_stats()
    assert reports == r0 + 2, (r0, reports)
    assert tokens >= 64 + 16 and dropped >= 8 + 4, (tokens, dropped)
    assert abs(last - 0.25) < 1e-6, last
    try:
        hvd.ep_report(0.5, 4, 8)  # dropped > tokens
    except ValueError:
        pass
    else:
        raise SystemExit("ep_report accepted dropped > tokens")
    digests = hvd.allgather_object(digest)
    out_path = os.environ.get("A2A_STATS_OUT")
    if out_path and r == 0:
        with open(out_path, "w") as f:
            json.dump({"expect": expect, "digests": digests,
                       "ops": ops1 - ops0, "bytes": bytes1 - bytes0,
                       "shm_ops": shm1 - shm0, "sg_rounds": sg1 - sg0}, f)
elif mode == "compress":
    tiered, copt = hvd.alltoall_state()
    assert copt, "HVD_ALLTOALL_COMPRESS=1 must report the opt-in"
    c0 = hvd.compress_stats()
    # f32 rides the int8 wire: per-peer scale = chunk maxabs / 127, so
    # each element lands within half a quantization step of the truth.
    t = np.concatenate([big_data(r, j) for j in range(s)])
    out = hvd.alltoall(t, name="a2a.int8")
    assert out.shape == (N * s,), out.shape
    for p in range(s):
        want = big_data(p, r)
        step = np.abs(want).max() / 127.0
        err = np.abs(np.asarray(out[p * N:(p + 1) * N], np.float64)
                     - want.astype(np.float64)).max()
        assert err <= step * 0.5 + 1e-7, (p, err, step)
    # Ragged splits with zero chunks keep the constant scale-header
    # geometry (4 bytes ride even on empty chunks).
    splits = [(r + j) % 3 for j in range(s)]
    tu = np.concatenate([big_data(r, j, splits[j]) for j in range(s)])
    ou, rcounts = hvd.alltoall(tu, splits=splits, name="a2a.int8.uneven")
    off = 0
    for p in range(s):
        n = (p + r) % 3
        assert rcounts[p] == n, (p, rcounts)
        want = big_data(p, r, n)
        if n:
            step = max(np.abs(want).max(), 1e-30) / 127.0
            err = np.abs(ou[off:off + n] - want).max()
            assert err <= step * 0.5 + 1e-7, (p, err, step)
        off += n
    # Non-f32 is exempt from the codec — moved bit-exactly.
    ti = np.concatenate([chunk(r, j, 3, 4, np.int64) for j in range(s)])
    oi = hvd.alltoall(ti, name="a2a.int8.exempt")
    for p in range(s):
        assert np.array_equal(oi[p * 3:(p + 1) * 3],
                              chunk(p, r, 3, 4, np.int64)), p
    c1 = hvd.compress_stats()
    assert c1["int8_ops"] - c0["int8_ops"] == 2, (c0, c1)
    raw = c1["raw_bytes"] - c0["raw_bytes"]
    wire = c1["wire_bytes"] - c0["wire_bytes"]
    assert raw > 0 and wire > 0, (raw, wire)
    assert raw / wire >= 3.5, (raw, wire, raw / wire)
else:
    raise SystemExit(f"unknown A2A_MODE={mode}")

hvd.barrier()
hvd.shutdown()
print(f"rank {r}: alltoall {mode} PASS", flush=True)
