"""Worker: hierarchical allreduce on a fake 2x2 pod (2 "hosts" x 2 local
ranks, all localhost — SURVEY.md §4 fake-pod convention). Reference:
NCCLHierarchicalAllreduce (local reduce-scatter → cross-plane allreduce of
the owned shard → local allgather), gated by HVD_HIERARCHICAL_ALLREDUCE.

Asserts correctness (hierarchical result == flat expectation, for Sum and
Average, fused pairs, odd lengths for chunk remainders) and prints this
rank's cross-plane tx bytes so the test can compare hierarchical vs flat
wire traffic (expected drop: ~1/local_size per rank).
"""
import os

r = int(os.environ["HVD_RANK"])
_s = int(os.environ["HVD_SIZE"])
# Fake multi-host topology: ranks are host-major (first L on "host0",
# next L on "host1", ...), matching the launcher's host-major slot
# assignment. L via HIER_LOCAL_SIZE (default 2: the 2x2 pod).
L = int(os.environ.get("HIER_LOCAL_SIZE", "2"))
assert _s % L == 0, (_s, L)
os.environ["HVD_LOCAL_RANK"] = str(r % L)
os.environ["HVD_LOCAL_SIZE"] = str(L)
os.environ["HVD_CROSS_RANK"] = str(r // L)
os.environ["HVD_CROSS_SIZE"] = str(_s // L)

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402

hvd.init()
s = hvd.size()
host = r // L
SUM = s * (s + 1) // 2  # sum over ranks of (r+1)
RSUM = s * (s - 1) // 2  # sum over ranks of r

N = 1 << 15  # 32k floats = 128 KiB per tensor

# Sum, several steps (steady-state cache path included).
for it in range(3):
    out = hvd.allreduce(np.full(N, float(r + 1), np.float32), op=hvd.Sum,
                        name="h.sum")
    assert np.allclose(out, float(SUM)), out[:4]

# Average.
out = hvd.allreduce(np.full(N, float(r + 1), np.float32), op=hvd.Average,
                    name="h.avg")
assert np.allclose(out, SUM / s), out[:4]

# Odd length (chunk remainder spread) + distinct per-element data.
M = (1 << 12) + 3
x = (np.arange(M, dtype=np.float32) + r * 1000.0)
out = hvd.allreduce(x, op=hvd.Sum, name="h.odd")
expect = s * np.arange(M, dtype=np.float32) + 1000.0 * RSUM
assert np.allclose(out, expect), (out[:4], expect[:4])

# Fused pair (two tensors in one cycle ride the fusion buffer).
ha = hvd.allreduce_async(np.full(257, float(r), np.float32), op=hvd.Sum,
                         name="h.fa")
hb = hvd.allreduce_async(np.full(123, 2.0 * r, np.float32), op=hvd.Sum,
                         name="h.fb")
from horovod_tpu.ops import collective_ops as ops  # noqa: E402

va, vb = ops.synchronize(ha), ops.synchronize(hb)
assert np.allclose(va, float(RSUM)), va[:4]
assert np.allclose(vb, 2.0 * RSUM), vb[:4]

# Tiny tensor (nelem < local_size falls back to the flat ring).
out = hvd.allreduce(np.full(1, float(r + 1), np.float32), op=hvd.Sum,
                    name="h.tiny")
assert np.allclose(out, float(SUM)), out

# Dispatch observability: with HVD_HIERARCHICAL_ALLREDUCE the operation
# manager must have selected the hierarchical backend for every allreduce,
# and never otherwise (reference: operation_manager.cc priority order).
hier_on = os.environ.get("HVD_HIERARCHICAL_ALLREDUCE") == "1"
assert (hvd.backend_uses("hierarchical_allreduce") > 0) == hier_on
assert (hvd.backend_uses("ring_allreduce") == 0) == hier_on

cross_tx = sum(hvd.peer_tx_bytes(q) for q in range(s) if q // L != host)
local_tx = sum(hvd.peer_tx_bytes(q) for q in range(s) if q // L == host
               and q != r)
hvd.shutdown()
print(f"HIERTX rank={r} cross={cross_tx} local={local_tx}", flush=True)
