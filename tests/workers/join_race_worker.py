"""Worker: join negotiation edge cases (reference: HorovodJoinOp +
Controller::ComputeResponseList, which keeps joined state live for the whole
response pass).

Case 1 — same-RequestList drain: the last survivor's async allreduce and its
join() land in ONE negotiation cycle. The join key predates the allreduce key
in arrival order, so the coordinator examines it first; joined state must
survive the rest of the pass or the allreduce loses its zero-fill stand-ins
and stalls forever.

Case 2 — fully-submitted non-allreduce overlapping a join: a broadcast every
member has already submitted needs no stand-ins and must complete normally
even while some ranks sit in join(); only an INCOMPLETE non-allreduce whose
missing members have joined is a usage error.

Run with HVD_CACHE_CAPACITY=0 (steady-state cache hits would bypass the
negotiation table) and a long cycle so back-to-back enqueues share a cycle.
"""
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.ops import collective_ops as ops

hvd.init()
r, s = hvd.rank(), hvd.size()
assert s == 2, "worker is written for 2 ranks"

# --- Case 1: allreduce + join in the same RequestList on the last survivor.
if r == 0:
    out = hvd.allreduce(np.full((4,), 1.0, np.float32), op=hvd.Sum,
                        name="race.g")
    assert np.allclose(out, 3.0), out  # step 1: both ranks active
    last = hvd.join()  # join key now sits in arrival order, pending rank 1
else:
    out = hvd.allreduce(np.full((4,), 2.0, np.float32), op=hvd.Sum,
                        name="race.g")
    assert np.allclose(out, 3.0), out
    time.sleep(0.5)  # let rank 0's join arrive cycles before our drain
    h = ops.allreduce_async(np.full((4,), 5.0, np.float32), op=hvd.Sum,
                            name="race.g")
    last = hvd.join()  # drains into the same cycle as the allreduce above
    out2 = ops.synchronize(h)
    # Rank 0 already joined: its contribution is a zero-filled stand-in.
    assert np.allclose(out2, 5.0), out2
assert last == 1, last  # rank 1 joins last

# --- Case 2: fully-submitted broadcast while rank 0 waits in join().
if r == 0:
    h = ops.broadcast_async(np.zeros((3,), np.float32), root_rank=1,
                            name="race.b")
    last = hvd.join()
    out = ops.synchronize(h)
else:
    time.sleep(0.5)  # rank 0's broadcast AND join are already pending
    out = hvd.broadcast(np.full((3,), 7.0, np.float32), root_rank=1,
                        name="race.b")
    last = hvd.join()
assert np.allclose(out, 7.0), out
assert last == 1, last

hvd.shutdown()
print(f"rank {r}: join race PASS", flush=True)
