"""Worker: restore-with-reshard across world sizes (ISSUE 15 satellite).

Run once with CKPT_PHASE=save at world size N, then again with
CKPT_PHASE=restore at world size M (N != M): the restore job reads the
global manifest and assembles each rank's target shards from only the
overlapping fragments — bit-exact, mixed dtypes, TP-sharded AND
replicated leaves. The 8-device CPU mesh is the same in both jobs
(force_cpu_platform(8 // np)), only the process count changes, so shard
boundaries genuinely move between save and restore.

The tree crosses the format's cases on purpose:
- "tp"    f32 (8, 4)  P("model")   8-way sharded both sides
- "tp16"  f16 (8, 6)  P("model")   half precision, bit-exact
- "rep"   i32 (3, 5)  plain numpy  root-written single shard, restored
                                   whole on host
- "repf"  f32 (8, 4)  plain numpy at save, P("model") at restore — each
                      device reads a SUB-REGION of the one stored
                      fragment (boundaries genuinely misaligned)
- "count" i64 ()      scalar       the empty-index edge case
"""
import os

import numpy as np

from horovod_tpu.jax.distributed import force_cpu_platform

phase = os.environ["CKPT_PHASE"]
np_ = int(os.environ.get("HVD_SIZE", "1"))
assert 8 % np_ == 0, np_
force_cpu_platform(8 // np_)

import jax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import checkpoint  # noqa: E402

if np_ > 1:
    from horovod_tpu.jax import distributed as jd

    assert jd.initialize_from_env(), "no HVD_JAX_COORD_ADDR in env"

hvd.init()
r = hvd.rank()
ckdir = os.environ["CKPT_DIR"]

mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("model",))
shd = NamedSharding(mesh, P("model"))

TP = np.arange(32.0, dtype=np.float32).reshape(8, 4) * 1.5
TP16 = (np.arange(48.0, dtype=np.float16) / 3.0).reshape(8, 6)
REP = np.arange(15, dtype=np.int32).reshape(3, 5) * 7
REPF = np.arange(32.0, dtype=np.float32).reshape(8, 4) - 11.0
COUNT = np.asarray(12345, np.int64)


def _mk(full):
    return jax.make_array_from_callback(
        full.shape, shd, lambda idx, _f=full: _f[idx])


if phase == "save":
    tree = {"tp": _mk(TP), "tp16": _mk(TP16), "rep": REP, "repf": REPF,
            "count": COUNT}
    checkpoint.save(ckdir, 2, tree)
    assert checkpoint.latest_step(ckdir) == 2
elif phase == "restore":
    like = {
        "tp": _mk(np.zeros_like(TP)),
        "tp16": _mk(np.zeros_like(TP16)),
        "rep": np.zeros_like(REP),
        # Saved as ONE root-written fragment; the sharded like makes
        # every device fetch only its sub-region of it.
        "repf": _mk(np.zeros_like(REPF)),
        "count": np.zeros_like(COUNT),
    }
    out, step = checkpoint.restore(ckdir, like)
    assert step == 2, step
    for name, want in (("tp", TP), ("tp16", TP16), ("repf", REPF)):
        got = out[name]
        assert isinstance(got, jax.Array), (name, type(got))
        assert got.dtype == want.dtype, (name, got.dtype)
        for sh in got.addressable_shards:
            assert np.array_equal(np.asarray(sh.data), want[sh.index]), name
    assert out["rep"].dtype == REP.dtype
    assert np.array_equal(out["rep"], REP)
    assert out["count"].dtype == np.int64 and int(out["count"]) == 12345
    st = hvd.checkpoint_stats()
    assert st["restores"] == 1 and st["fragments_fetched"] > 0, st
else:
    raise SystemExit(f"unknown CKPT_PHASE {phase!r}")

print(f"rank {r}: reshard-ckpt[{phase}@{np_}] PASS", flush=True)
hvd.shutdown()
