"""RayExecutor(backend="ray") end-to-end through the CI ray shim
(tests/shims).

Exercises the REAL horovod_tpu.ray._run_ray code path — ray.init, remote
task fan-out, KV rendezvous with the driver's advertised node IP,
negotiation, ray.get collection, cancel-on-failure — with the shim
supplying only the ray API surface (concurrent tasks in separate
processes). Reference analog: horovod/ray/runner.py RayExecutor actors.
"""
import ray

assert "ci-shim" in ray.__version__, \
    "this worker must run against the CI shim, not a real ray"

from horovod_tpu.ray import RayExecutor  # noqa: E402


def train():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r, s = hvd.rank(), hvd.size()
    out = hvd.allreduce(np.ones(3, np.float32) * (r + 1), op=hvd.Sum)
    hvd.shutdown()
    return r, s, float(out[0])


# backend auto-detection must pick ray when importable
ex = RayExecutor(num_workers=3)
assert ex.backend == "ray", ex.backend
ex.start()
results = ex.run(train)
ex.shutdown()
assert len(results) == 3, results
for rank, (r, s, val) in enumerate(results):
    assert r == rank and s == 3, results
    assert val == 6.0, results

# failure contract: a dying rank surfaces as ONE RuntimeError, survivors
# are cancelled (reference: RayExecutor kills the worker group)
def die():
    import os

    import horovod_tpu as hvd

    hvd.init()
    if hvd.rank() == 1:
        os._exit(17)
    import numpy as np

    hvd.allreduce(np.ones(2, np.float32))  # blocks until peer death fails it
    hvd.shutdown()


ex2 = RayExecutor(num_workers=2, backend="ray", timeout=120).start()
try:
    ex2.run(die)
    raise SystemExit("expected RuntimeError from dying rank")
except RuntimeError as e:
    assert "ray worker failed" in str(e), e
ex2.shutdown()

print("ray shim run PASS", flush=True)
