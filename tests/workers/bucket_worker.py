"""Worker: backprop-ordered gradient bucketing (csrc/tensor_queue.h
ordered bucket assembler, ISSUE 8). BUCKET_MODE selects the scenario;
every rank asserts the correctness of every collective while the
assembler learns/replays/flushes underneath, then checks the
bucket_stats() counters the scenario promises.
"""
import os

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()
mode = os.environ.get("BUCKET_MODE", "early")


def burst(names, count=1024, dtype=np.float32, steps=1):
    """One fake backward pass per step: async submit every gradient in
    order (like the torch hook path), then synchronize in order."""
    for _ in range(steps):
        hs = [hvd.allreduce_async(
                  np.full(count, dtype(r + 1 + i), dtype),
                  op=hvd.Sum, name=n)
              for i, n in enumerate(names)]
        for i, h in enumerate(hs):
            out = hvd.synchronize(h)
            expect = sum(range(1 + i, s + 1 + i))
            assert np.allclose(np.asarray(out, np.float64), expect), \
                (names[i], out[:2], expect)


if mode == "early":
    # 4 gradients of 4 KB under an 8 KB bound -> a 2-bucket plan learned
    # on step 0 and replayed; the first bucket of every replayed step
    # launches while grads 2/3 are still outstanding (early > 0 is the
    # backward/comms overlap claim).
    on, bb = hvd.bucket_state()
    assert on and bb == 8192, (on, bb)
    burst([f"grad.{i}" for i in range(4)], steps=6)
    launched, early, assembled, flushes, invalid, plan = hvd.bucket_stats()
    assert plan == 2, plan
    assert launched >= 10 and assembled >= 20, (launched, assembled)
    assert early >= 5, f"no early launches: {early}"
    assert flushes == 0 and invalid == 0, (flushes, invalid)
elif mode == "dtypes":
    # Mixed-dtype plans: members keep their own dtype through the grouped
    # release (the wire serializes per tensor); results stay exact.
    names = ["g.f32", "g.f64", "g.i32", "g.i64"]
    dtypes = [np.float32, np.float64, np.int32, np.int64]
    for _ in range(5):
        hs = [hvd.allreduce_async(
                  np.full(512, dt(r + 1 + i), dt), op=hvd.Sum, name=n)
              for i, (n, dt) in enumerate(zip(names, dtypes))]
        for i, h in enumerate(hs):
            out = hvd.synchronize(h)
            expect = sum(range(1 + i, s + 1 + i))
            assert np.allclose(np.asarray(out, np.float64), expect), \
                (names[i], out[:2])
    launched, early, assembled, flushes, invalid, plan = hvd.bucket_stats()
    assert launched > 0 and assembled > 0, (launched, assembled)
    assert flushes == 0 and invalid == 0, (flushes, invalid)
elif mode == "invalidate":
    # Graph change: a new name (and later a resized member) mid-run drops
    # the plan, releases held members ungrouped, and relearns — counted,
    # never wrong.
    base = [f"grad.{i}" for i in range(4)]
    burst(base, steps=3)
    burst(base + ["grad.extra"], steps=3)  # unknown name -> invalidate
    burst(base, count=2048, steps=3)       # resized members -> invalidate
    launched, early, assembled, flushes, invalid, plan = hvd.bucket_stats()
    assert invalid >= 2, invalid
    assert launched > 0, launched
elif mode == "flush":
    # A blocking sync loop submits bucket members one at a time: the
    # assembler must flush held members at the deadline (bounded stall),
    # then self-disable after a few streaks instead of taxing every step.
    # Each flush drops the plan and relearns (~5 calls per cycle with 4
    # names), so 30 calls cover the 4 flushes the latch needs.
    for i in range(30):
        out = hvd.allreduce(np.full(1024, float(r + 1), np.float32),
                            op=hvd.Sum, name=f"sync.{i % 4}")
        assert np.allclose(out, s * (s + 1) / 2.0), out[:2]
    launched, early, assembled, flushes, invalid, plan = hvd.bucket_stats()
    assert flushes >= 1, flushes
    on, _ = hvd.bucket_state()
    assert not on, "self-disable should have parked the assembler"
elif mode == "off":
    assert hvd.bucket_state() == (False, 32 << 20), hvd.bucket_state()
    burst([f"grad.{i}" for i in range(4)], steps=3)
    assert hvd.bucket_stats() == (0, 0, 0, 0, 0, 0), hvd.bucket_stats()
elif mode == "coexist":
    # Bucketing + scatter-gather zero-copy in one job: the fused bucket
    # payload crosses HVD_ZEROCOPY_THRESHOLD, so grouped buckets ride the
    # SG ring while the assembler keeps launching early.
    burst([f"grad.{i}" for i in range(4)], count=2048, steps=6)
    launched, early, assembled, flushes, invalid, plan = hvd.bucket_stats()
    assert launched >= 10 and early >= 5, (launched, early)
    zc_ops, zc_bytes, st_ops, st_bytes = hvd.zerocopy_stats()
    assert zc_ops > 0, (zc_ops, zc_bytes)
else:
    raise SystemExit(f"unknown BUCKET_MODE {mode!r}")

hvd.barrier()
hvd.shutdown()
print(f"rank {r}: bucket[{mode}] PASS", flush=True)
