"""Worker: traced bridge ops must fail LOUDLY when an elastic resize
invalidates their trace-time size hoists (VERDICT r5 #8).

hvd_allgather / hvd_alltoall / hvd_reducescatter hoist the process-set
size (and rank) at TRACE time to compute static output shapes — alltoall
additionally derives its uniform per-peer split from the traced size, the
same hazard the TF binding guards with its traced-world check. A resize
between trace and execution makes the compiled program's output buffer
silently wrong-sized. Single rank: trace the ops under jit, run them
once, then fake a resize by monkeypatching the live size query and assert
the callback raises the staleness error instead of returning garbage.
"""
import jax
import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd
from horovod_tpu.ops import collective_ops as _core
from horovod_tpu.ops import jax_ops

hvd.init()
assert hvd.size() == 1


@jax.jit
def gather(x):
    return jax_ops.hvd_allgather(x, name="stale.ag")


@jax.jit
def scatter(x):
    return jax_ops.hvd_reducescatter(x, op=jax_ops.Sum, name="stale.rs")


@jax.jit
def shuffle(x):
    return jax_ops.hvd_alltoall(x, name="stale.a2a")


x = jnp.arange(4, dtype=jnp.float32)
assert np.array_equal(np.asarray(gather(x)), np.arange(4, dtype=np.float32))
assert np.array_equal(np.asarray(scatter(x)), np.arange(4, dtype=np.float32))
assert np.array_equal(np.asarray(shuffle(x)), np.arange(4, dtype=np.float32))

# Fake the resize: the library now reports one more member than the traces
# hoisted. CDLL instances accept python attribute overrides, so this
# shadows the ctypes entry point for every caller in this process.
real_size = _core._lib.hvd_process_set_size
_core._lib.hvd_process_set_size = lambda ps: int(real_size(int(ps))) + 1

for jitted, tag in ((gather, "allgather"), (scatter, "reducescatter"),
                    (shuffle, "alltoall")):
    try:
        jitted(x)
    except Exception as e:  # noqa: BLE001 — jax wraps the callback error
        msg = f"{e!r}\n{e}"
        assert "elastic resize" in msg, (tag, msg)
        print(f"stale {tag}: loud error OK", flush=True)
    else:
        raise SystemExit(f"stale traced {tag} did NOT fail loudly")

_core._lib.hvd_process_set_size = real_size
hvd.shutdown()
print("bridge-stale PASS", flush=True)
