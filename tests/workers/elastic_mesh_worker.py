"""Elastic JAX worker: every epoch trains IN-JIT over a global device mesh
whose size tracks membership (VERDICT r2 #1 — elastic × ICI composition;
reference analog: nccl_operations.cc communicator abort/rebuild per elastic
reset).

Each process pins 2 virtual CPU devices (the fake-pod convention), so a
size-S epoch must expose a 2*S-device global mesh; an in-jit psum of ones
over that mesh must equal 2*S. Each iteration also runs a core-bridged
allreduce first — the fast failure detector (a dead peer breaks the TCP
plane immediately, long before an in-mesh collective would time out).

Env knobs: TEST_ITERS, TEST_LOG, TEST_SLEEP, TEST_FAIL_SLOT, TEST_MARKER
(same contract as elastic_train_worker.py).
"""

import functools
import os
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic
from horovod_tpu.jax import distributed as jd

jd.force_cpu_platform(2)
hvd.init()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import shard_map  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

ITERS = int(os.environ.get("TEST_ITERS", "8"))
SLEEP = float(os.environ.get("TEST_SLEEP", "0.1"))
FAIL_SLOT = os.environ.get("TEST_FAIL_SLOT")
MARKER = os.environ.get("TEST_MARKER", "")
WID = os.environ.get("HVD_WORKER_ID", "?")

state = elastic.JaxState(iteration=0, w=jnp.zeros(4, jnp.float32),
                         max_ndev=0)


def _should_die(it):
    if FAIL_SLOT is None or not MARKER:
        return False
    if os.path.exists(MARKER):
        return False
    return it == 3 and WID.startswith(f"localhost-{FAIL_SLOT}-")


def mesh_psum_step(w):
    """One in-jit step over the CURRENT global mesh: psum of ones across
    every device of every process in this epoch. The input is created
    inside the jit (a process-local host array is not addressable on a
    multi-process mesh) and only this process's shard is fetched."""
    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("data",))

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=(), out_specs=P(),
                       check_vma=False)
    def f():
        return jax.lax.psum(jnp.ones(4, jnp.float32), "data")

    y = f()
    got = float(np.asarray(y.addressable_data(0)).ravel()[0])
    w = jnp.asarray(w) + got / len(devs)
    return w, got, len(devs)


@elastic.run
def train(state):
    while state.iteration < ITERS:
        if _should_die(state.iteration):
            with open(MARKER, "w") as f:
                f.write(WID)
            os._exit(1)
        # Core-bridged op first: fast failure detection via the TCP plane.
        hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                      name=f"hb.{state.iteration}")
        expect_ndev = 2 * hvd.size()
        state.w, got, ndev = mesh_psum_step(state.w)
        assert ndev == expect_ndev, (ndev, expect_ndev)
        assert got == expect_ndev, (got, expect_ndev)
        state.max_ndev = max(state.max_ndev, ndev)
        state.iteration += 1
        state.commit()
        # Progress beacon for tests that trigger membership changes only
        # after real in-mesh training happened at the current size.
        pf = os.environ.get("TEST_PROGRESS")
        if pf and hvd.rank() == 0:
            with open(pf, "a") as f:
                f.write(f"{state.iteration} {hvd.size()}\n")
        time.sleep(SLEEP)
    return hvd.rank(), hvd.size(), 2 * hvd.size()


rank, size, ndev = train(state)
if os.environ.get("TEST_LOG"):
    with open(os.environ["TEST_LOG"], "a") as f:
        f.write(f"final rank={rank} size={size} iter={state.iteration} "
                f"ndev={ndev} maxndev={state.max_ndev}\n")
hvd.shutdown()
