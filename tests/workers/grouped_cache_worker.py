"""Worker: grouped collectives under response-cache eviction pressure.

Regression for the group x cache interaction: group members bypass the
cache entirely (CacheFilterRequests skips group_id >= 0; the coordinator
marks responses `grouped` so no replica inserts them). Before that fix, a
repeated EXPLICITLY-NAMED group under LRU pressure could have some
members bit-signaled as hits while others went through the group table —
the group count never completed and the job stalled to shutdown.

Run with HVD_CACHE_CAPACITY=1 so every cacheable tensor fights for one
slot (max eviction churn).
"""
import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()

for step in range(6):
    # same names every step: cacheable if groups ever entered the cache
    gouts = hvd.grouped_allgather(
        [np.full((2, 2), float(r), np.float32),
         np.full((3,), float(r), np.float32)], name="w")
    assert gouts[0].shape == (2 * s, 2)
    routs = hvd.grouped_allreduce(
        [np.ones(4, np.float32) * (r + 1), np.ones(2, np.float32)],
        op=hvd.Sum, name="g")
    assert np.allclose(routs[0], sum(range(1, s + 1)))
    # interleave a plain cached tensor to churn the 1-slot LRU
    y = hvd.allreduce(np.ones(3, np.float32), op=hvd.Sum, name="plain")
    assert np.allclose(y, s)

stats = hvd.cache_stats()
print(f"rank {r}: grouped-cache PASS {stats}", flush=True)
hvd.shutdown()
