"""Worker: torn-checkpoint fuzz (ISSUE 15 satellite).

Save a TP-sharded checkpoint (async, to exercise the writer thread +
wait() path), then corrupt it in every way a crashed writer or bad disk
could, and assert each restore fails LOUDLY with a CheckpointError
naming the offending piece — a partial restore must never be silently
wrong. Each corruption is undone before the next so the cases are
independent; the last one (deleted rank dir) is destructive and runs
last.
"""
import json
import os
import shutil

import numpy as np

from horovod_tpu.jax.distributed import force_cpu_platform

force_cpu_platform(8)

import jax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import checkpoint  # noqa: E402
from horovod_tpu.exceptions import CheckpointError  # noqa: E402

hvd.init()
ckdir = os.environ["CKPT_DIR"]

mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("model",))
full = np.arange(64.0, dtype=np.float32).reshape(8, 8)
w = jax.device_put(full, NamedSharding(mesh, P("model")))
checkpoint.save(ckdir, 5, {"w": w, "b": np.ones(3, np.float32)},
                async_=True)
checkpoint.wait()
st = hvd.checkpoint_stats()
assert st["saves"] == 1 and st["commits"] == 1, st

like = {"w": np.zeros((8, 8), np.float32), "b": np.zeros(3, np.float32)}
out, step = checkpoint.restore(ckdir, like)
assert step == 5 and np.array_equal(out["w"], full), step

step_dir = os.path.join(ckdir, "5")
mpath = os.path.join(step_dir, checkpoint.MANIFEST)
with open(mpath) as f:
    manifest_text = f.read()


def expect(frag):
    try:
        checkpoint.restore(ckdir, like, step=5)
    except CheckpointError as e:
        assert frag in str(e), (frag, str(e))
    else:
        raise AssertionError(f"restore survived corruption ({frag!r})")


# 1. Truncated MANIFEST.json — the classic torn write.
with open(mpath, "w") as f:
    f.write(manifest_text[: len(manifest_text) // 2])
expect("torn manifest")

# 2. Wrong format tag — a future/foreign layout must not half-parse.
with open(mpath, "w") as f:
    json.dump({"format": "bogus-v9"}, f)
expect("unknown format")
with open(mpath, "w") as f:
    f.write(manifest_text)

# 3. Flipped byte in a shard payload — crc must catch it and name it.
fpath = os.path.join(step_dir, "rank_0", "shard_0000.npy")
with open(fpath, "rb") as f:
    payload = f.read()
with open(fpath, "wb") as f:
    f.write(payload[:-1] + bytes([payload[-1] ^ 0xFF]))
expect("checksum mismatch in shard rank_0/shard_0000.npy")
with open(fpath, "wb") as f:
    f.write(payload)

# 4. tree_like asking for a tensor the checkpoint never had.
try:
    checkpoint.restore(ckdir, dict(like, extra=np.zeros(2)), step=5)
except CheckpointError as e:
    assert "extra" in str(e) and "no tensor" in str(e), str(e)
else:
    raise AssertionError("restore survived a tree mismatch")

# 5. Deleted rank dir — the error names the missing shard AND tensor.
shutil.rmtree(os.path.join(step_dir, "rank_0"))
expect("missing shard rank_0/")

# 6. No MANIFEST at all: the dir no longer counts as committed anywhere.
os.remove(mpath)
assert checkpoint.latest_step(ckdir) is None
try:
    checkpoint.restore(ckdir, like, step=5)
except CheckpointError as e:
    assert "no committed checkpoint" in str(e), str(e)
else:
    raise AssertionError("restore survived a missing manifest")

print("torn-ckpt PASS", flush=True)
hvd.shutdown()
