"""Worker: process-set collectives (reference parity:
test/parallel/test_*.py process-set coverage)."""
import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()
assert s >= 4, "needs 4 ranks"

evens = hvd.add_process_set([i for i in range(s) if i % 2 == 0])
odds = hvd.add_process_set([i for i in range(s) if i % 2 == 1])
assert evens.process_set_id > 0 and odds.process_set_id > 0
assert evens.process_set_id != odds.process_set_id

mine = evens if r % 2 == 0 else odds
members = [i for i in range(s) if i % 2 == r % 2]
assert mine.size() == len(members)
assert mine.rank() == members.index(r)

# Allreduce within my set only.
x = np.full(16, float(r), dtype=np.float32)
y = hvd.allreduce(x, op=hvd.Sum, process_set=mine.process_set_id)
assert np.allclose(y, sum(members)), (r, y[0], sum(members))

# Allgather within set.
g = hvd.allgather(np.array([r], dtype=np.int64), process_set=mine.process_set_id)
assert g.tolist() == members, (r, g)

# Broadcast within set: root is a global rank that must be a member.
b = hvd.broadcast(np.array([float(r)]), root_rank=members[0],
                  process_set=mine.process_set_id)
assert b[0] == members[0]

# Barrier on global set, then remove.
hvd.barrier()
hvd.remove_process_set(evens)
hvd.remove_process_set(odds)
hvd.shutdown()
print(f"rank {r}: PASS", flush=True)
