"""Worker: single-rank shutdown must not hang (VERDICT r1 weak #8).

Rank 1 calls hvd.shutdown() immediately while rank 0 keeps training; the
bounded-shutdown path (HVD_SHUTDOWN_TIMEOUT) interrupts the control plane,
rank 1's shutdown returns, and rank 0 observes HorovodInternalError — the
elastic recovery signal — instead of blocking forever."""
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import HorovodInternalError

hvd.init()
r, s = hvd.rank(), hvd.size()

if r == 1:
    t0 = time.time()
    hvd.shutdown()  # peers still active -> bounded by HVD_SHUTDOWN_TIMEOUT
    took = time.time() - t0
    assert took < 15.0, f"shutdown took {took:.1f}s"
    print(f"rank {r}: early shutdown returned in {took:.1f}s", flush=True)
else:
    got_internal_error = False
    try:
        for i in range(2000):
            hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name=f"t{i}")
    except HorovodInternalError:
        got_internal_error = True
    assert got_internal_error, "rank 0 never observed the peer's departure"
    print(f"rank {r}: got HorovodInternalError as expected", flush=True)
