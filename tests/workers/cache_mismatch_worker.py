"""Worker: per-rank HVD_CACHE_CAPACITY disagreement must not desynchronize
the response-cache replicas. Cache bit positions are implicit in insert and
eviction order, so mismatched capacities would make the same hit bit expand
to different tensors on different ranks once eviction starts. Rank 0's value
is broadcast during the mesh handshake and adopted everywhere (reference
analog: controller-coordinated cache bit assignment in response_cache.cc)."""
import os

r = int(os.environ["HVD_RANK"])
# Deliberately disagree: rank 0 (authoritative) tiny, others large.
os.environ["HVD_CACHE_CAPACITY"] = "2" if r == 0 else "64"

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402

hvd.init()
s = hvd.size()

# Three distinct steady-state tensors against an effective capacity of 2:
# every rank must evict in lockstep or values diverge / the job deadlocks.
for step in range(6):
    for t in range(3):
        out = hvd.allreduce(np.full((4,), float(r + 1 + t), np.float32),
                            op=hvd.Sum, name=f"mm.{t}")
        expect = sum(q + 1 + t for q in range(s))
        assert np.allclose(out, expect), (step, t, out[0], expect)

hits, misses, entries = hvd.cache_stats()
assert entries <= 2, entries  # coordinator's capacity was adopted
hvd.shutdown()
print(f"rank {r}: cache mismatch PASS entries={entries}", flush=True)
