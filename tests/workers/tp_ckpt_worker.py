"""Worker: hvd.checkpoint.save with a TP-sharded train state under the
sharded format (ISSUE 15 tentpole; updates the PR 7 pins). Two modes:

- CKPT_MODE=local: single process, params sharded over a model axis of
  local devices. Every shard is addressable, so one rank dir holds the
  whole state; restore into a plain-numpy like assembles full host
  arrays, restore into a sharded like ROUND-TRIPS the sharding (the
  reshard path, degenerate N==M case).
- CKPT_MODE=global: the model axis spans processes. The PR 7 pin made
  save fail loudly here; the sharded state plane's whole point is that
  it now SUCCEEDS — each rank writes only its own addressable shards,
  the root commits the global manifest, and restore hands every rank
  exactly its shards back, bit-exact, with no full-array gather on any
  host.
"""
import os

import numpy as np

from horovod_tpu.jax.distributed import force_cpu_platform

mode = os.environ.get("CKPT_MODE", "local")
force_cpu_platform(8 if mode == "local" else 4)

import jax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import checkpoint  # noqa: E402

if mode == "global":
    from horovod_tpu.jax import distributed as jd

    assert jd.initialize_from_env(), "no HVD_JAX_COORD_ADDR in env"

hvd.init()
r = hvd.rank()
ckdir = os.environ["CKPT_DIR"]
full = np.arange(32.0, dtype=np.float32).reshape(8, 4)

mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("model",))
sharding = NamedSharding(mesh, P("model"))

if mode == "local":
    w = jax.device_put(full, sharding)
    assert len(w.sharding.device_set) == 8  # really TP-sharded
    tree = {"w": w, "b": np.ones(4, np.float32)}
    checkpoint.save(ckdir, 1, tree)
    like = {"w": np.zeros((8, 4), np.float32),
            "b": np.zeros(4, np.float32)}
    out, step = checkpoint.restore(ckdir, like)
    assert step == 1, step
    # Plain-numpy like: the shard fragments assemble to the FULL array.
    assert np.array_equal(out["w"], full), out["w"]
    assert isinstance(out["w"], np.ndarray), type(out["w"])
    # Sharded like: the TP layout round-trips (what the PR 7 pin said a
    # sharded-checkpoint refactor should change — it did).
    wl = jax.device_put(np.zeros((8, 4), np.float32), sharding)
    out2, _ = checkpoint.restore(ckdir, {"w": wl, "b": like["b"]})
    assert isinstance(out2["w"], jax.Array), type(out2["w"])
    assert out2["w"].sharding == sharding
    assert np.array_equal(np.asarray(out2["w"]), full)
elif mode == "global":
    w = jax.make_array_from_callback(full.shape, sharding,
                                     lambda idx: full[idx])
    assert not w.is_fully_addressable
    # PR 7 pinned save() raising here; the sharded format writes it.
    tree = {"w": w, "b": np.full(4, float(r + 1), np.float32)}
    checkpoint.save(ckdir, 1, tree)
    assert checkpoint.latest_step(ckdir) == 1
    # Each rank wrote ONLY its own shards into its own rank dir.
    assert os.path.isdir(os.path.join(ckdir, "1", f"rank_{r}"))
    like_w = jax.make_array_from_callback(
        full.shape, sharding, lambda idx: np.zeros_like(full[idx]))
    out, step = checkpoint.restore(
        ckdir, {"w": like_w, "b": np.zeros(4, np.float32)})
    assert step == 1, step
    for sh in out["w"].addressable_shards:
        assert np.array_equal(np.asarray(sh.data), full[sh.index])
    # Unsharded leaves keep the restore-returns-the-root's-values
    # contract: rank 0 wrote b.
    assert np.allclose(out["b"], 1.0), out["b"]
else:
    raise SystemExit(f"unknown CKPT_MODE {mode!r}")

print(f"rank {r}: tp-ckpt[{mode}] PASS", flush=True)
hvd.shutdown()
