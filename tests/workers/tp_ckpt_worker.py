"""Worker: what hvd.checkpoint.save does with a TP-sharded train state
(ISSUE 8 satellite; ROADMAP item 5 prep). Two modes:

- CKPT_MODE=local: single process, params sharded over a model axis of
  local devices. Pinned behavior: the root's host pull (checkpoint.py
  _to_host) GATHERS each fully-addressable sharded leaf, so the written
  checkpoint holds FULL arrays; restore returns plain replicated host
  arrays — sharding metadata is NOT round-tripped.
- CKPT_MODE=global: the model axis spans processes, so the root holds
  only its own shards. Pinned behavior: save FAILS LOUDLY on the root's
  host pull (np.asarray of a non-fully-addressable jax.Array) before
  anything is written — not a silently-truncated checkpoint.
"""
import os

import numpy as np

from horovod_tpu.jax.distributed import force_cpu_platform

mode = os.environ.get("CKPT_MODE", "local")
force_cpu_platform(8 if mode == "local" else 4)

import jax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import checkpoint  # noqa: E402

if mode == "global":
    from horovod_tpu.jax import distributed as jd

    assert jd.initialize_from_env(), "no HVD_JAX_COORD_ADDR in env"

hvd.init()
r = hvd.rank()
ckdir = os.environ["CKPT_DIR"]
full = np.arange(32.0, dtype=np.float32).reshape(8, 4)

mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("model",))
sharding = NamedSharding(mesh, P("model"))

if mode == "local":
    w = jax.device_put(full, sharding)
    assert len(w.sharding.device_set) == 8  # really TP-sharded
    tree = {"w": w, "b": np.ones(4, np.float32)}
    checkpoint.save(ckdir, 1, tree)
    like = {"w": np.zeros((8, 4), np.float32),
            "b": np.zeros(4, np.float32)}
    out, step = checkpoint.restore(ckdir, like)
    assert step == 1, step
    # The sharded leaf was gathered: the checkpoint holds the FULL array.
    assert np.allclose(out["w"], full), out["w"]
    # ...and comes back as a plain host array — the TP layout is gone.
    # A later refactor that round-trips shardings should break THIS line.
    assert isinstance(out["w"], np.ndarray), type(out["w"])
elif mode == "global":
    w = jax.make_array_from_callback(full.shape, sharding,
                                     lambda idx: full[idx])
    assert not w.is_fully_addressable
    if r == 0:
        err = None
        try:
            checkpoint.save(ckdir, 1, {"w": w})
        except Exception as e:  # noqa: BLE001 — the pin IS the exception
            err = e
        assert err is not None, \
            "save silently accepted a non-addressable sharded state"
        assert "addressable" in str(err).lower(), err
        # Failed BEFORE writing: no half checkpoint on disk.
        assert checkpoint.latest_step(ckdir) is None
    hvd.barrier()
else:
    raise SystemExit(f"unknown CKPT_MODE {mode!r}")

print(f"rank {r}: tp-ckpt[{mode}] PASS", flush=True)
hvd.shutdown()
