"""Chaos worker: SIGKILL the checkpoint WRITER mid-save (ISSUE 15
crash-window satellite).

Flow (elastic job, 4 ranks, HVD_PEER_TIMEOUT_MS armed by the test):

1. iter 1 — every rank saves step 1 (sync). Committed.
2. iter 3 — the current writer (rank 0, the set root) arms
   HVD_CKPT_TEST_CRASH=2 and writes the marker; checkpoint.py's chaos
   hook then SIGKILLs it AFTER its shards are durable but BEFORE the
   shards barrier — exactly the window that used to wedge survivors in
   the ``ckpt.shards.<step>`` barrier forever. Survivors must get RankEvictedError out
   of the barrier via the PR 8 liveness/eviction path, roll back, and
   re-rendezvous.
3. On every (re)entry into the elastic fn, ranks restore via the
   manifest path (elastic.restore_from_checkpoint — coordinate-free, so
   joiners can run it): after the fault this must resolve step 1, the
   last COMMITTED step, with step 1's exact values — the torn step-2
   staging dir must never be resolvable as latest. The restored step
   also catches the replacement writer up, proving the driver's
   ckpt_step assignment plumbing.
4. The retried save of step 2 succeeds (the marker keeps the new writer
   from re-arming), the loop finishes, and every finisher logs
   ``final rank=R size=S iter=I ckpt=1 parity=ok``.
"""

import os
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import checkpoint, elastic

hvd.init()

ITERS = int(os.environ.get("TEST_ITERS", "6"))
SLEEP = float(os.environ.get("TEST_SLEEP", "0.15"))
MARKER = os.environ["TEST_MARKER"]
CKDIR = os.environ["CKPT_DIR"]
WID = os.environ.get("HVD_WORKER_ID", "?")
SAVE_ITER, CRASH_ITER = 1, 3

last_restored = [None]
state = elastic.ObjectState(iteration=0)


def _tree(step):
    return {"w": np.full(4, float(step), np.float32),
            "iteration": np.asarray(int(state.iteration), np.int64)}


@elastic.run
def train(state):
    like = {"w": np.zeros(4, np.float32),
            "iteration": np.asarray(0, np.int64)}
    out, st = elastic.restore_from_checkpoint(like, directory=CKDIR)
    last_restored[0] = st
    if st is not None:
        # Bit-exact: step s was saved with w == s everywhere.
        assert np.array_equal(out["w"],
                              np.full(4, float(st), np.float32)), \
            (st, out["w"])
        # Manifest-path catch-up: a freshly promoted/respawned rank 0
        # adopts the checkpoint's progress BEFORE state.sync() broadcasts
        # its dict, so the fleet never rewinds past the committed step.
        state.iteration = max(int(state.iteration), int(out["iteration"]))
    while state.iteration < ITERS:
        it = int(state.iteration)
        if it == SAVE_ITER:
            checkpoint.save(CKDIR, 1, _tree(1))
        if it == CRASH_ITER:
            if not os.path.exists(MARKER) and hvd.rank() == 0:
                # Arm the writer-crash hook ONCE: checkpoint.py SIGKILLs
                # this process mid-save of step 2, before the commit.
                with open(MARKER, "w") as f:
                    f.write(WID)
                os.environ["HVD_CKPT_TEST_CRASH"] = "2"
            elif os.path.exists(MARKER):
                # Post-fault retry: the torn step-2 attempt must have
                # left step 1 as the newest COMMITTED checkpoint.
                assert checkpoint.latest_step(CKDIR) == 1, \
                    checkpoint.latest_step(CKDIR)
            checkpoint.save(CKDIR, 2, _tree(2))
        out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                            name=f"it.{it}")
        state.iteration += 1
        state.commit()
        time.sleep(SLEEP)
    return hvd.rank(), hvd.size()


rank, size = train(state)
# Post-recovery parity: the repaired mesh must still reduce correctly.
check = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum, name="parity")
parity = "ok" if np.allclose(check, float(size)) else f"BAD({check[0]})"
if os.environ.get("TEST_LOG"):
    with open(os.environ["TEST_LOG"], "a") as f:
        f.write(f"final rank={rank} size={size} iter={state.iteration} "
                f"ckpt={last_restored[0]} parity={parity}\n")
hvd.shutdown()
