"""Torch binding worker: collectives, DistributedOptimizer training-step
convergence across ranks, broadcast_parameters/optimizer_state, SyncBN.
(Reference coverage model: test/parallel/test_torch.py.)"""
import os

import numpy as np
import torch

import horovod_tpu.torch as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()
torch.manual_seed(1234 + r)  # intentionally different per rank

# The native extension (csrc/torch_ops.cc) must carry the collectives in
# this environment unless the fallback was requested
# (HVD_TORCH_NATIVE_OPS=0 — test_torch_binding_numpy_fallback).
from horovod_tpu.torch import native_ext  # noqa: E402

expect_native = os.environ.get("HVD_TORCH_NATIVE_OPS", "1") == "1"
assert (native_ext.lib() is not None) == expect_native, "native ext state"

# collectives
t = torch.full((10,), float(r + 1))
out = hvd.allreduce(t, op=hvd.Sum)
assert torch.allclose(out, torch.full((10,), s * (s + 1) / 2.0)), out
g = hvd.allgather(torch.full((2, 2), float(r)))
assert g.shape == (2 * s, 2)
b = hvd.broadcast(torch.arange(4, dtype=torch.float32) * (r + 1),
                  root_rank=0)
assert torch.allclose(b, torch.arange(4, dtype=torch.float32))

# alltoall with splits + reducescatter (native kernels when loaded)
a2a, rs = hvd.alltoall(torch.full((2 * s,), float(r)), splits=[2] * s)
assert torch.allclose(a2a, torch.arange(s, dtype=torch.float32)
                      .repeat_interleave(2)), a2a
assert torch.all(rs == 2), rs
rsc = hvd.reducescatter(torch.ones(2 * s, 3) * float(r + 1), op=hvd.Sum)
assert rsc.shape == (2, 3)
assert torch.allclose(rsc, torch.full((2, 3), s * (s + 1) / 2.0)), rsc
ravg = hvd.reducescatter(torch.ones(2 * s, 3) * float(r + 1),
                         op=hvd.Average)
assert torch.allclose(ravg, torch.full((2, 3), (s + 1) / 2.0)), ravg

# 0-d scalars keep their shape (they ride the bridge, which promotes to
# 1-d for the wire and restores — native submits true shapes only)
sc = hvd.allreduce(torch.tensor(float(r + 1)), op=hvd.Sum)
assert sc.shape == () and float(sc) == s * (s + 1) / 2.0, sc

# non-contiguous input is handled (native path copies to contiguous;
# in-place variants fall back to the bridge)
nc = (torch.arange(16, dtype=torch.float32).reshape(4, 4).T)[1:3]
assert not nc.is_contiguous()
out_nc = hvd.allreduce(nc, op=hvd.Sum)
assert torch.allclose(out_nc, nc * s), out_nc

# model sync + hook-based DistributedOptimizer
model = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.ReLU(),
                            torch.nn.Linear(8, 1))
hvd.broadcast_parameters(model.state_dict(), root_rank=0)
w0 = [p.detach().clone() for p in model.parameters()]
opt = torch.optim.SGD(model.parameters(), lr=0.05)
opt = hvd.DistributedOptimizer(
    opt, named_parameters=model.named_parameters())
hvd.broadcast_optimizer_state(opt, root_rank=0)

xs = torch.randn(16, 4)  # different data per rank (different seed)
ys = torch.randn(16, 1)
for step in range(3):
    opt.zero_grad()
    loss = torch.nn.functional.mse_loss(model(xs), ys)
    loss.backward()
    opt.step()

# after synced init + averaged grads, params must be identical across ranks
for i, p in enumerate(model.parameters()):
    arr = p.detach().numpy()
    ref = hvd.broadcast(p.detach(), root_rank=0).numpy()
    assert np.allclose(arr, ref, atol=1e-6), f"param {i} diverged"
    assert not torch.allclose(p, w0[i]), f"param {i} did not train"

# sync batch norm: stats averaged over ALL ranks' samples. Rank r feeds a
# constant r, so global mean = mean(r) and var = E[r^2]-mean^2; each rank's
# normalized output must use the GLOBAL stats, not its local (zero) var.
bn = hvd.SyncBatchNorm(3)
bn.train()
x = torch.full((4, 3, 2), float(r))
y = bn(x)
gmean = sum(range(s)) / s
gvar = sum(i * i for i in range(s)) / s - gmean ** 2
expect = (r - gmean) / np.sqrt(gvar + bn.eps)
assert torch.allclose(y, torch.full_like(y, expect), atol=1e-4), \
    (y.flatten()[0].item(), expect)
assert np.allclose(bn.running_mean.numpy(), 0.9 * 0 + 0.1 * gmean,
                   atol=1e-5)

# the wrapper must be a full torch Optimizer (defaults, add_param_group)
extra_param = torch.nn.Parameter(torch.zeros(2))
opt.add_param_group({"params": [extra_param]})
assert isinstance(opt, torch.optim.Optimizer)
assert "lr" in opt.defaults

# SyncBN backward: grads must match full-batch BatchNorm (stats are
# differentiated through the local contribution)
full = torch.arange(2 * s * 3 * 2, dtype=torch.float32).reshape(2 * s, 3, 2)
full = full / full.numel()
local = full[2 * r:2 * (r + 1)].clone().requires_grad_(True)
bn_sync = hvd.SyncBatchNorm(3, affine=False)
bn_sync.train()
(bn_sync(local) ** 3).sum().backward()
ref_in = full.clone().requires_grad_(True)
bn_ref = torch.nn.BatchNorm1d(3, affine=False)
bn_ref.train()
(bn_ref(ref_in) ** 3).sum().backward()
assert np.allclose(local.grad.numpy(),
                   ref_in.grad[2 * r:2 * (r + 1)].numpy(), atol=1e-4), \
    np.abs(local.grad.numpy()
           - ref_in.grad[2 * r:2 * (r + 1)].numpy()).max()

# metric average
m = hvd.metric_average(float(r), name="m")
assert abs(m - (s - 1) / 2.0) < 1e-9

# gradient_predivide_factor: (1/f)*sum*(f/size) must equal plain Average
model_pd = torch.nn.Linear(4, 1, bias=False)
for q in model_pd.parameters():
    q.data.fill_(0.5)
opt_pd = hvd.DistributedOptimizer(torch.optim.SGD(model_pd.parameters(), lr=0.1),
                                  gradient_predivide_factor=2.0)
x_pd = torch.full((2, 4), float(r + 1))
model_pd(x_pd).sum().backward()
opt_pd.synchronize()
g = model_pd.weight.grad.numpy()
expect = np.mean([2 * (i + 1) for i in range(s)])  # avg over ranks of sum_b x
assert np.allclose(g, expect, atol=1e-5), (g, expect)

# grouped allreduce: atomic group through ONE native crossing (reference:
# horovod_torch_grouped_allreduce_async_); in-place, out-of-place, and
# fp16 wire compression inside the extension
g1 = torch.full((4,), float(r + 1))
g2 = torch.full((2, 3), 2.0 * (r + 1))
outs = hvd.grouped_allreduce([g1, g2], op=hvd.Sum)
assert np.allclose(outs[0].numpy(), s * (s + 1) / 2.0)
assert np.allclose(outs[1].numpy(), s * (s + 1))
t1, t2 = g1.clone(), g2.clone()
hvd.grouped_allreduce_([t1, t2], op=hvd.Average,
                       compression=hvd.Compression.fp16)
assert np.allclose(t1.numpy(), (s + 1) / 2.0, atol=1e-2), t1.numpy()
assert np.allclose(t2.numpy(), s + 1.0, atol=1e-2), t2.numpy()

# num_groups + fp16 compression on the optimizer: the hook path must stay
# native (wire cast in csrc/torch_ops.cc), never the numpy bridge
model_ng = torch.nn.Sequential(torch.nn.Linear(4, 8),
                               torch.nn.Linear(8, 1))
for q in model_ng.parameters():
    q.data.fill_(0.25)
opt_ng = hvd.DistributedOptimizer(
    torch.optim.SGD(model_ng.parameters(), lr=0.05),
    compression=hvd.Compression.fp16, num_groups=2)
x_ng = torch.full((4, 4), float(r + 1))
for _ in range(2):
    opt_ng.zero_grad()
    model_ng(x_ng).sum().backward()
    opt_ng.step()
if expect_native:
    assert opt_ng._hvd_stats["native"] > 0, opt_ng._hvd_stats
    assert opt_ng._hvd_stats["bridge"] == 0, opt_ng._hvd_stats
else:
    assert opt_ng._hvd_stats["native"] == 0, opt_ng._hvd_stats
for i, q in enumerate(model_ng.parameters()):
    ref = hvd.broadcast(q.data, root_rank=0)
    assert np.allclose(q.data.numpy(), ref.numpy(), atol=1e-6), \
        f"num_groups param {i} diverged"

# a custom compressor must take the bridge (the native wire cast would
# silently skip its compress/decompress)
class _Doubling(hvd.Compression.fp16):
    @staticmethod
    def compress(tensor):
        out, ctx = hvd.Compression.fp16.compress(tensor)
        return out, ctx

opt_cc = hvd.DistributedOptimizer(
    torch.optim.SGD([torch.nn.Parameter(torch.ones(3))], lr=0.1),
    compression=_Doubling)
p_cc = opt_cc.param_groups[0]["params"][0]
p_cc.grad = torch.full((3,), float(r + 1))
opt_cc._hvd_hook(p_cc)
opt_cc.synchronize()
assert opt_cc._hvd_stats["bridge"] == 1, opt_cc._hvd_stats
assert np.allclose(p_cc.grad.numpy(), (s + 1) / 2.0, atol=1e-2)

# sparse gradients (reference: sparse_as_dense): an Embedding(sparse=True)
# grad is densified before the dense allreduce; without the flag it must
# fail loudly, never feed a sparse layout to the wire.
emb = torch.nn.Embedding(6, 4, sparse=True)
with torch.no_grad():
    emb.weight.fill_(0.0)
opt_sp = hvd.DistributedOptimizer(
    torch.optim.SGD(emb.parameters(), lr=1.0), sparse_as_dense=True)
idx = torch.tensor([r, r + 1])  # rank-dependent rows
emb(idx).sum().backward()
opt_sp.synchronize()
g = emb.weight.grad
assert not g.is_sparse
# row k's dense grad on rank r is 1 iff k in {r, r+1}; averaged over
# ranks it is count(k in {r, r+1} for r in ranks) / s.
expect = np.zeros((6, 4), np.float32)
for q in range(s):
    expect[q] += 1.0
    expect[q + 1] += 1.0
expect /= s
assert np.allclose(g.numpy(), expect, atol=1e-6), g.numpy()

opt_sp2 = hvd.DistributedOptimizer(
    torch.optim.SGD([torch.nn.Parameter(torch.zeros(6, 4))], lr=1.0))
p_sp = opt_sp2.param_groups[0]["params"][0]
p_sp.grad = torch.sparse_coo_tensor(
    torch.tensor([[0], [0]]), torch.ones(1), (6, 4))
try:
    opt_sp2._hvd_hook(p_sp)
    raise SystemExit("sparse grad without sparse_as_dense must raise")
except ValueError as e:
    assert "sparse_as_dense" in str(e), e

print(f"rank {r}: TORCH PASS", flush=True)
hvd.shutdown()
