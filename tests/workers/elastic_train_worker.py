"""Elastic training worker driven by `tpurun --min-np/--max-np`.

Exercises the full elastic loop (reference: test/integration/data/ elastic
driver scripts): ObjectState commit/restore/sync, scale-up via
HostsUpdatedInterrupt, failure recovery via HorovodInternalError.

Env knobs (set by the test):
- TEST_ITERS: iterations to run
- TEST_LOG: file to append "final rank=R size=S iter=I" on completion
- TEST_SLEEP: per-iteration sleep seconds
- TEST_FAIL_SLOT: slot index that dies once at iteration 3
- TEST_MARKER: marker file recording that the death already happened
"""

import os
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic

hvd.init()

ITERS = int(os.environ.get("TEST_ITERS", "10"))
SLEEP = float(os.environ.get("TEST_SLEEP", "0.1"))
FAIL_SLOT = os.environ.get("TEST_FAIL_SLOT")
INTERNAL_SLOT = os.environ.get("TEST_INTERNAL_SLOT")
MARKER = os.environ.get("TEST_MARKER", "")
WID = os.environ.get("HVD_WORKER_ID", "?")

state = elastic.ObjectState(iteration=0, total=np.zeros(4, np.float32))


def _should_die(it):
    if FAIL_SLOT is None or not MARKER:
        return False
    if os.path.exists(MARKER):
        return False
    return it == 3 and WID.startswith(f"localhost-{FAIL_SLOT}-")


def _should_raise_internal(it):
    """Transient failure with every process alive (e.g. a flaky link):
    needs the worker→driver reset push to re-rendezvous promptly."""
    if INTERNAL_SLOT is None or not MARKER:
        return False
    if os.path.exists(MARKER):
        return False
    return it == 3 and WID.startswith(f"localhost-{INTERNAL_SLOT}-")


@elastic.run
def train(state):
    while state.iteration < ITERS:
        if _should_die(state.iteration):
            with open(MARKER, "w") as f:
                f.write(WID)
            os._exit(1)
        if _should_raise_internal(state.iteration):
            with open(MARKER, "w") as f:
                f.write(WID)
            raise hvd.HorovodInternalError("injected transient failure")
        out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                            name=f"it.{state.iteration}")
        state.total = state.total + out
        state.iteration += 1
        state.commit()
        time.sleep(SLEEP)
    return hvd.rank(), hvd.size()


rank, size = train(state)
if os.environ.get("TEST_LOG"):
    with open(os.environ["TEST_LOG"], "a") as f:
        f.write(f"final rank={rank} size={size} iter={state.iteration}\n")
hvd.shutdown()
