"""spark.run() end-to-end through the CI pyspark shim (tests/shims).

Exercises the REAL horovod_tpu.spark.run code path — barrier stage, HMAC
KV rendezvous, per-rank controller negotiation, payload execution,
result collection — with the shim supplying only the pyspark API surface
(concurrent barrier tasks in separate processes). Reference analog:
horovod/spark/__init__.py `run` over real executors.
"""
import pyspark

assert "ci-shim" in pyspark.__version__, \
    "this worker must run against the CI shim, not a real pyspark"

import horovod_tpu.spark as spark  # noqa: E402


def train(mult):
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r, s = hvd.rank(), hvd.size()
    out = hvd.allreduce(np.ones(4, np.float32) * (r + 1), op=hvd.Sum)
    val = float(out[0]) * mult
    # barrier API parity: reachable from inside a task
    ctx = pyspark.BarrierTaskContext.get()
    assert ctx.partitionId() == r
    ctx.barrier()
    hvd.shutdown()
    return r, s, val


N = 3
results = spark.run(train, args=(2.0,), num_proc=N)
assert len(results) == N, results
for rank, (r, s, val) in enumerate(results):
    assert r == rank, results          # ordered by rank
    assert s == N, results
    assert val == sum(range(1, N + 1)) * 2.0, results

print("spark shim run PASS", flush=True)
