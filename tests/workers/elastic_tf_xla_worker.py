"""Elastic resize under a fully XLA-compiled predivide step.

The live version of the ADVICE r4 medium contract: a
`tf.function(jit_compile=True)` train step with
``gradient_predivide_factor`` is traced once (at the starting world
size), a rank dies AND the discovery output shrinks, and THE SAME
compiled program keeps producing exact averages at the new size — the
trace bakes only the size-free ``(1/f, f)`` pair; Average's 1/members
comes from the core at collective-execution time
(csrc/core.cc `EffectivePostscale`). Also exercises the typed-FFI
error path end-to-end: the peer death surfaces from INSIDE the compiled
program as tf.errors with the core's failure markers, which
elastic._is_native_op_failure must map to restore-and-rendezvous.
"""
import os
import time

import numpy as np

import horovod_tpu.tensorflow as hvd

hvd.init()
import tensorflow as tf  # noqa: E402

from horovod_tpu.tensorflow import native_ops  # noqa: E402

assert native_ops.xla_enabled(), "worker requires HVD_ENABLE_XLA_OPS=1"

ITERS = int(os.environ.get("TEST_ITERS", "8"))
SLEEP = float(os.environ.get("TEST_SLEEP", "0.2"))
FAIL_SLOT = os.environ.get("TEST_FAIL_SLOT")
MARKER = os.environ.get("TEST_MARKER", "")
WID = os.environ.get("HVD_WORKER_ID", "?")

w = tf.Variable(tf.ones([4]))


@tf.function(jit_compile=True)
def grad_step(x):
    with tf.GradientTape() as t:
        loss = tf.reduce_sum(w * x)
    dtape = hvd.DistributedGradientTape(t, gradient_predivide_factor=4.0)
    (g,) = dtape.gradient(loss, [w])
    return g


def _should_die(it):
    if FAIL_SLOT is None or not MARKER or os.path.exists(MARKER):
        return False
    return it == 2 and WID.startswith(f"localhost-{FAIL_SLOT}-")


state = hvd.elastic.ObjectState(iteration=0, sizes=[])


@hvd.elastic.run
def train(state):
    while state.iteration < ITERS:
        r, s = hvd.rank(), hvd.size()
        if _should_die(state.iteration):
            open(MARKER, "w").write("died\n")
            os._exit(1)
        g = grad_step(tf.fill([4], float(r + 1)))
        # d(loss)/dw = x = r+1 on rank r; Average over the CURRENT
        # members = mean(1..s) = (s+1)/2, independent of f=4. A stale
        # size baked at trace time would break this after the resize.
        assert np.allclose(g.numpy(), (s + 1) / 2.0), (g.numpy(), s)
        state.sizes = state.sizes + [s]
        state.iteration += 1
        state.commit()
        time.sleep(SLEEP)


train(state)

# The central claim — no stale size in the trace — requires that the
# SAME compiled program served both world sizes: a silent retrace after
# the resize would re-bake factors and pass the numeric asserts
# vacuously.
assert grad_step.experimental_get_tracing_count() == 1, \
    grad_step.experimental_get_tracing_count()

log = os.environ.get("TEST_LOG")
if log:
    with open(log, "a") as f:
        f.write(f"final iter={state.iteration} "
                f"sizes={','.join(map(str, state.sizes))}\n")
print(f"rank {hvd.rank()}: elastic-xla PASS sizes={state.sizes}",
      flush=True)
hvd.shutdown()
