"""Worker: negotiation error handling — mismatched shapes must produce a
clean per-tensor error on every rank, not a hang or a crash (reference:
controller.cc ConstructResponse error paths)."""
import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()

# Mismatched allreduce shapes.
x = np.ones(4 + r, dtype=np.float32)  # different shape per rank
try:
    hvd.allreduce(x, op=hvd.Sum, name="bad.shape")
    raise SystemExit(f"rank {r}: expected an error for mismatched shapes")
except RuntimeError as e:
    assert "mismatched shape" in str(e), e

# Mismatched dtypes.
y = np.ones(4, dtype=np.float32 if r == 0 else np.float64)
try:
    hvd.allreduce(y, op=hvd.Sum, name="bad.dtype")
    raise SystemExit(f"rank {r}: expected an error for mismatched dtypes")
except RuntimeError as e:
    assert "mismatched dtype" in str(e), e

# The core must still work after errors.
z = hvd.allreduce(np.ones(4, dtype=np.float32), op=hvd.Sum)
assert np.allclose(z, s)
hvd.shutdown()
print(f"rank {r}: PASS", flush=True)
