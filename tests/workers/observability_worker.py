"""2-rank observability acceptance worker (tests/test_observability.py).

Runs with HVD_METRICS=1 and HVD_TIMELINE set: real allreduces must show
up as nonzero byte/latency series both in the registry snapshot and at a
live /metrics endpoint, and rank 0 must be able to merge its Python
spans with the core timeline into one valid Chrome-trace JSON.
"""
import json
import os
import urllib.request

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import observability as obs
from horovod_tpu.observability import metrics, spans

assert metrics.enabled(), "worker requires HVD_METRICS=1"
hvd.init()
r, s = hvd.rank(), hvd.size()

x = np.ones(1024, dtype=np.float32) * (r + 1)
for step in range(3):
    with spans.span("train.step", step=step):
        y = hvd.allreduce(x, op=hvd.Sum)
assert np.allclose(y, sum(range(1, s + 1))), y[:4]

# Registry: the acceptance criterion — nonzero allreduce bytes/latency.
snap = metrics.snapshot()
ar_bytes = [sm for sm in snap["hvd_op_bytes_total"]["samples"]
            if sm["labels"]["op"] == "allreduce"]
assert ar_bytes and ar_bytes[0]["value"] >= 3 * x.nbytes, ar_bytes
ar_lat = [sm for sm in snap["hvd_op_latency_seconds"]["samples"]
          if sm["labels"]["op"] == "allreduce"]
assert ar_lat and ar_lat[0]["count"] >= 3 and ar_lat[0]["sum"] > 0, ar_lat
# The sync wrapper's completion wait is a distinct series.
assert any(sm["labels"]["op"] == "allreduce.wait"
           for sm in snap["hvd_op_latency_seconds"]["samples"])

# Live scrape: every rank serves its own registry.
port = obs.start_endpoint(0, addr="127.0.0.1")
with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                            timeout=10) as resp:
    assert resp.status == 200
    assert "text/plain" in resp.headers["Content-Type"]
    text = resp.read().decode()
lines = [ln for ln in text.splitlines()
         if ln.startswith("hvd_op_bytes_total{") and 'op="allreduce"' in ln]
assert lines and float(lines[0].rsplit(" ", 1)[1]) > 0, lines
obs.stop_endpoint()

hvd.barrier()
hvd.shutdown()  # closes the core timeline (writes the trailing ])

if r == 0:
    out_dir = os.environ["OBS_TEST_DIR"]
    core_tl = os.environ["HVD_TIMELINE"]  # rank 0 writes the bare path
    py_tl = spans.dump(os.path.join(out_dir, "py_spans.json"))
    merged = obs.merge_traces(os.path.join(out_dir, "merged.json"),
                              core_tl, py_tl)
    with open(merged) as f:
        events = json.load(f)["traceEvents"]
    names = {e.get("name") for e in events}
    assert "train.step" in names, sorted(names)[:20]
    # Core timeline rows use the rank as pid (csrc/timeline.cc); Python
    # spans use the OS pid — both sources must be present.
    assert any(e.get("pid") == 0 for e in events), "no core events merged"
    assert any(e.get("pid") == os.getpid() for e in events)
    ts = [e.get("ts", 0) for e in events]
    assert ts == sorted(ts), "merged events not time-sorted"

print(f"rank {r}: PASS", flush=True)
