"""Worker: multi-process global mesh — the cross-process ICI data plane
(SURVEY.md §7 stage 5; reference analog: NCCLAllreduce in
horovod/common/ops/nccl_operations.cc where one process per device joins a
NCCL communicator).

tpurun's slot env provisions a jax.distributed coordinator
(HVD_JAX_COORD_ADDR); hvd.init() joins it, so jax.devices() spans every
process and in-jit collectives (psum / pmean) cross process boundaries ON
DEVICE, while the native TCP core still carries the control-plane
collectives in the same process.
"""
import os  # noqa: F401

# Per-process "chips": 2 virtual CPU devices each (the fake pod, SURVEY §4).
# force_cpu_platform also overrides any site hook that force-selected a TPU
# plugin platform via config.update (which beats env vars).
from horovod_tpu.jax.distributed import force_cpu_platform

force_cpu_platform(2)

import functools  # noqa: E402

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax import shard_map  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu.jax as hvd  # noqa: E402
from horovod_tpu import parallel  # noqa: E402

hvd.init()
r, s = hvd.rank(), hvd.size()

# --- the mesh spans processes
assert hvd.is_multiprocess(), "jax.distributed mesh did not form"
assert jax.process_count() == s, (jax.process_count(), s)
n_local = len(jax.local_devices())
assert len(jax.devices()) == s * n_local, jax.devices()

mesh = hvd.global_mesh()  # one 'data' axis over every chip in the job
assert mesh.shape["data"] == s * n_local

# --- in-jit psum crosses process boundaries on device
@jax.jit
@functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                   out_specs=P("data"), check_vma=False)
def summed(x):
    return jax.lax.psum(x, "data") * jnp.ones_like(x)

local = np.full((n_local, 1), float(r + 1), np.float32)
out = summed(hvd.shard_local_batch(local, mesh))
got = float(np.asarray(out.addressable_shards[0].data).ravel()[0])
expect = float(n_local * sum(range(1, s + 1)))
assert got == expect, (got, expect)

# --- full DP train step over the global mesh: gradient pmean on device
d, k = 5, 4  # features, rows per device
N = s * n_local * k  # global batch

rng = np.random.default_rng(0)  # every process can reconstruct the full set
X = rng.normal(size=(N, d)).astype(np.float32)
Y = (X @ np.arange(d).astype(np.float32))[:, None]
lo, hi = r * n_local * k, (r + 1) * n_local * k  # this process's shard

w0 = {"w": jnp.zeros((d, 1), jnp.float32)}
tx = optax.sgd(0.1)

def loss_fn(params, batch):
    x, y = batch
    return jnp.mean((x @ params["w"] - y) ** 2)

step = parallel.make_train_step(loss_fn, tx, mesh)
params = parallel.data_parallel.replicate(w0, mesh)
opt_state = parallel.data_parallel.replicate(tx.init(w0), mesh)

batch = hvd.shard_local_batch((X[lo:hi], Y[lo:hi]), mesh)
params, opt_state, loss = step(params, opt_state, batch)

# Expected: one SGD step with the gradient of the mean loss over the GLOBAL
# batch (pmean of per-shard grads == global mean for equal shard sizes).
w = np.zeros((d, 1), np.float32)
g = np.zeros_like(w)
for i in range(s * n_local):
    xs, ys = X[i * k:(i + 1) * k], Y[i * k:(i + 1) * k]
    g += 2.0 * xs.T @ (xs @ w - ys) / k
g /= s * n_local
w_expect = w - 0.1 * g

w_got = np.asarray(
    jax.tree.map(lambda a: a.addressable_shards[0].data, params)["w"])
assert np.allclose(w_got, w_expect, atol=1e-5), (w_got.ravel(),
                                                 w_expect.ravel())

# --- host metadata sync helper
ranks = hvd.process_allgather(np.asarray([r], np.int32))
assert sorted(ranks.ravel().tolist()) == list(range(s)), ranks

# --- the TCP core control plane composes in the same process
y = hvd.allreduce(jnp.full((4,), float(r + 1)), op=hvd.Sum, name="core.x")
assert np.allclose(np.asarray(y), sum(range(1, s + 1))), y

hvd.shutdown()
print(f"rank {r}: multiprocess mesh PASS", flush=True)
