"""Torch elastic training worker (reference:
test/integration/test_elastic_torch.py + data driver scripts): TorchState
captures model + optimizer, commit() each iteration, restore-on-failure,
sync-on-membership-change — the torch binding's full elastic loop over
the shared core.

Env knobs (same contract as elastic_train_worker.py):
- TEST_ITERS / TEST_SLEEP / TEST_LOG
- TEST_FAIL_SLOT + TEST_MARKER: slot that os._exit(1)s once at iter 3
"""
import os
import time

import numpy as np
import torch

import horovod_tpu.torch as hvd
from horovod_tpu import elastic

hvd.init()

ITERS = int(os.environ.get("TEST_ITERS", "8"))
SLEEP = float(os.environ.get("TEST_SLEEP", "0.1"))
FAIL_SLOT = os.environ.get("TEST_FAIL_SLOT")
MARKER = os.environ.get("TEST_MARKER", "")
WID = os.environ.get("HVD_WORKER_ID", "?")


def _should_die(it):
    """Key off the STABLE worker id (sibling-worker convention):
    HVD_LOCAL_RANK is rewritten every rendezvous epoch and could target
    the wrong process after a membership change."""
    if FAIL_SLOT is None or not MARKER or os.path.exists(MARKER):
        return False
    return it == 3 and WID.startswith(f"localhost-{FAIL_SLOT}-")

torch.manual_seed(0)
model = torch.nn.Linear(6, 1, bias=False)
opt = torch.optim.SGD(model.parameters(), lr=0.05)
state = hvd.elastic.TorchState(model, opt, iteration=0)

X = np.random.default_rng(0).normal(size=(32, 6)).astype(np.float32)
Y = (X @ np.ones((6, 1), np.float32))


@elastic.run
def train(state):
    while state.iteration < ITERS:
        r, s = hvd.rank(), hvd.size()
        if _should_die(state.iteration):
            open(MARKER, "w").write("died\n")
            os._exit(1)
        xb = torch.from_numpy(X[r::s])
        yb = torch.from_numpy(Y[r::s])
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(xb), yb)
        loss.backward()
        # average grads across the CURRENT membership through the core
        for p in model.parameters():
            hvd.allreduce_(p.grad, op=hvd.Average,
                           name=f"g.{state.iteration}")
        opt.step()
        state.iteration += 1
        state.commit()
        time.sleep(SLEEP)


train(state)

# All survivors end with identical weights (restore/sync kept them lockstep).
w = model.weight.detach().numpy()
gathered = hvd.allgather(torch.from_numpy(w.reshape(1, -1)).contiguous(),
                         name="final.w")
gw = np.asarray(gathered)
assert np.allclose(gw, gw[0], atol=1e-6), gw

log = os.environ.get("TEST_LOG")
if log:
    with open(log, "a") as f:
        f.write(f"final rank={hvd.rank()} size={hvd.size()} "
                f"iter={state.iteration}\n")
print(f"rank {hvd.rank()}: torch elastic PASS", flush=True)
hvd.shutdown()
