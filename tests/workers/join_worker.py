"""Worker: join with zero-fill participation (reference: HorovodJoinOp —
test pattern: ranks run different step counts; joined ranks contribute
zero-filled stand-ins; the average divides by the full member count).

Rank r runs 4 + 3*r steps. After a rank joins, survivors' allreduces must
still complete, with the joined rank's contribution = 0. join() returns the
last rank to join. Also covers the fused path (two tensors per step) and
the cache steady state (same names every step)."""
import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()

my_steps = 4 + 3 * r
max_steps = 4 + 3 * (s - 1)

for i in range(my_steps):
    # Ranks still active at step i (rank q runs 4+3q steps).
    active = [q for q in range(s) if i < 4 + 3 * q]
    va = hvd.allreduce(np.full((8,), float(r + 1), np.float32),
                       op=hvd.Average, name="grad.a")
    vb = hvd.allreduce(np.full((3,), float(10 * (r + 1)), np.float32),
                       op=hvd.Sum, name="grad.b")
    exp_a = sum(q + 1 for q in active) / s  # zero-dilated average
    exp_b = sum(10 * (q + 1) for q in active)
    assert np.allclose(va, exp_a), (i, va[0], exp_a, active)
    assert np.allclose(vb, exp_b), (i, vb[0], exp_b, active)

last = hvd.join()
assert last == s - 1, last  # rank s-1 runs longest, joins last

# Collectives work normally again after everyone rejoined.
out = hvd.allreduce(np.full((4,), float(r + 1), np.float32), op=hvd.Sum,
                    name="post.join")
assert np.allclose(out, sum(range(1, s + 1))), out

hits, misses, entries = hvd.cache_stats()
hvd.shutdown()
print(f"rank {r}: join PASS steps={my_steps} last={last} hits={hits}",
      flush=True)
