"""Worker: rank-aware orbax checkpointing across a 2-rank job — rank 0
writes, the barrier holds everyone until durable, restore agrees on the
step across ranks (SURVEY.md §5 checkpoint/resume)."""
import os

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import checkpoint

hvd.init()
r, s = hvd.rank(), hvd.size()
ckdir = os.environ["CKPT_DIR"]

tree = {"w": np.full((4, 2), float(r + 1), np.float32),
        "step_count": np.asarray(7, np.int64)}

# Save at steps 3 and 5; every rank may call save (only rank 0 writes).
checkpoint.save(ckdir, 3, tree)
tree2 = {"w": tree["w"] * 10.0, "step_count": np.asarray(9, np.int64)}
checkpoint.save(ckdir, 5, tree2)

assert checkpoint.latest_step(ckdir) == 5

# Restore latest: every rank gets rank 0's tree (it was the writer).
like = {"w": np.zeros((4, 2), np.float32),
        "step_count": np.asarray(0, np.int64)}
out, step = checkpoint.restore(ckdir, like)
assert step == 5, step
assert np.allclose(out["w"], 10.0), out["w"]  # rank 0 wrote (0+1)*10
assert int(out["step_count"]) == 9

# Restore an explicit earlier step.
out3, step3 = checkpoint.restore(ckdir, like, step=3)
assert step3 == 3 and np.allclose(out3["w"], 1.0)

# Empty dir: (None, None) on every rank.
none_out, none_step = checkpoint.restore(ckdir + "-empty", like)
assert none_out is None and none_step is None

print(f"rank {r}: checkpoint PASS", flush=True)
hvd.shutdown()
