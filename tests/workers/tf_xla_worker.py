"""In-XLA-graph TF collectives worker (csrc/tf_xla_ops.cc — the
`horovod/tensorflow/xla_mpi_ops.cc` analog, gated by HVD_ENABLE_XLA_OPS).

With the gate on: collectives compile inside tf.function(jit_compile=True)
and a DistributedGradientTape train step runs fully XLA-compiled across
ranks. With the gate off: XLA rejects the graph (the documented fallback —
run eager/graph-mode instead), which we assert raises.
"""
import os

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np  # noqa: E402
import tensorflow as tf  # noqa: E402

import horovod_tpu.tensorflow as hvd  # noqa: E402

hvd.init()
r, s = hvd.rank(), hvd.size()

from horovod_tpu.tensorflow import native_ops  # noqa: E402

assert native_ops.lib() is not None, "native ops must load for this worker"
xla_on = os.environ.get("HVD_ENABLE_XLA_OPS", "0") == "1"
assert native_ops.xla_enabled() == xla_on, \
    f"xla_enabled()={native_ops.xla_enabled()}, want {xla_on}"


@tf.function(jit_compile=True)
def compiled_allreduce(x):
    return hvd.allreduce(x, op=hvd.Sum, name="xla.ar") * 2.0


if not xla_on:
    # Fallback contract: without the XLA kernel library, jit_compile=True
    # must reject the graph instead of silently computing garbage.
    try:
        compiled_allreduce(tf.fill([4], float(r + 1)))
        raise SystemExit("expected XLA compilation to fail without the gate")
    except (tf.errors.InvalidArgumentError, tf.errors.UnimplementedError):
        pass
    print(f"rank {r}: TF XLA-fallback PASS", flush=True)
    hvd.shutdown()
    raise SystemExit(0)

# --- gate on: collectives ride the core from inside compiled programs ----
out = compiled_allreduce(tf.fill([8], float(r + 1)))
assert np.allclose(out.numpy(), 2.0 * s * (s + 1) / 2.0), out.numpy()


@tf.function(jit_compile=True)
def compiled_bcast(x):
    return hvd.broadcast(x, root_rank=0, name="xla.bc") + 1.0


b = compiled_bcast(tf.range(4, dtype=tf.float32) * float(r + 1))
assert np.allclose(b.numpy(), np.arange(4) + 1.0), b.numpy()

# Average + prescale must agree with the eager path bit-for-bit targets
@tf.function(jit_compile=True)
def compiled_avg(x):
    return hvd.allreduce(x, op=hvd.Average, name="xla.avg",
                         prescale_factor=0.5)


a = compiled_avg(tf.fill([6], float(r)))
assert np.allclose(a.numpy(), 0.5 * (s - 1) / 2.0), a.numpy()

# allgather + reducescatter also compile (beyond the reference, whose
# xla_mpi_ops.cc covers allreduce only): static shapes come from the
# process-set size at trace time; the call target validates the actual
# result shape against the compiled one.
@tf.function(jit_compile=True)
def compiled_gather_scatter(x):
    g = hvd.allgather(x, name="xla.ag")              # [s*2, 3]
    rs = hvd.reducescatter(g, op=hvd.Sum, name="xla.rs")  # [2, 3]
    return g, rs


gx, rsx = compiled_gather_scatter(tf.fill([2, 3], float(r + 1)))
assert gx.shape == (2 * s, 3), gx.shape
expect_g = np.repeat(np.arange(1, s + 1, dtype=np.float32), 2)[:, None]
assert np.allclose(gx.numpy(), np.broadcast_to(expect_g, (2 * s, 3))), \
    gx.numpy()
# reducescatter of the gathered tensor: every rank contributed the same
# gathered value, so shard r holds s * gathered[2r:2r+2]
assert rsx.shape == (2, 3), rsx.shape
assert np.allclose(rsx.numpy(), s * gx.numpy()[2 * r:2 * r + 2]), \
    rsx.numpy()


# Process-set collectives compile too: even/odd singleton sets at s=2 —
# the metadata blob carries the set id, the gather family's static shape
# derives from the SET size (not world size), and the reduction runs
# over set members only.
evens = hvd.add_process_set([i for i in range(s) if i % 2 == 0])
odds = hvd.add_process_set([i for i in range(s) if i % 2 == 1])
mine = evens if r % 2 == 0 else odds
members = [i for i in range(s) if i % 2 == r % 2]


@tf.function(jit_compile=True)
def compiled_ps(x):
    y = hvd.allreduce(x, op=hvd.Sum, name="xla.ps",
                      process_set=mine.process_set_id)
    g = hvd.allgather(tf.reshape(x, [1, -1]), name="xla.psg",
                      process_set=mine.process_set_id)
    return y, g


yps, gps = compiled_ps(tf.fill([4], float(r + 1)))
assert np.allclose(yps.numpy(), sum(m + 1 for m in members)), yps.numpy()
assert gps.shape == (len(members), 4), gps.shape
hvd.remove_process_set(evens)
hvd.remove_process_set(odds)


# gradient_predivide_factor through the XLA per-tensor path (ADVICE r4):
# the compiled graph bakes only the size-free (1/f, f) pair; Average's
# 1/member_count is applied by the core at collective-execution time
# (csrc/core.cc EffectivePostscale), so the traced function stays correct
# across elastic resizes. Assert exact averaging here so any future
# size-dependent factor would fail the 2-proc matrix.
@tf.function(jit_compile=True)
def predivide_step(w, x):
    with tf.GradientTape() as tape:
        tape.watch(w)
        loss = tf.reduce_sum(w * x)
    dtape = hvd.DistributedGradientTape(tape, gradient_predivide_factor=4.0)
    (g,) = dtape.gradient(loss, [w])
    return g


gpre = predivide_step(tf.ones([5]), tf.fill([5], float(r + 1)))
# d(loss)/dw = x = r+1 per rank; averaged over ranks = (s+1)/2 exactly,
# independent of f.
assert np.allclose(gpre.numpy(), (s + 1) / 2.0), gpre.numpy()


# --- fully compiled DistributedGradientTape train step -------------------
tf.random.set_seed(42)  # same init everywhere; bcast still exercised
model = tf.keras.Sequential([
    tf.keras.layers.Dense(8, activation="relu"),
    tf.keras.layers.Dense(1),
])
model.build((None, 4))
hvd.broadcast_variables(model.variables, root_rank=0)
opt = tf.keras.optimizers.SGD(0.05)

rng = np.random.default_rng(100 + r)  # different data per rank
x = tf.constant(rng.normal(size=(16, 4)), dtype=tf.float32)
y = tf.constant(rng.normal(size=(16, 1)), dtype=tf.float32)


@tf.function(jit_compile=True)
def train_step(x, y):
    with tf.GradientTape() as tape:
        tape = hvd.DistributedGradientTape(tape)
        loss = tf.reduce_mean((model(x) - y) ** 2)
    grads = tape.gradient(loss, model.trainable_variables)
    opt.apply_gradients(zip(grads, model.trainable_variables))
    return loss


for _ in range(3):
    train_step(x, y)

for i, v in enumerate(model.variables):
    ref = hvd.broadcast(tf.identity(v), root_rank=0)
    assert np.allclose(v.numpy(), ref.numpy(), atol=1e-6), \
        f"var {i} diverged under XLA training"

print(f"rank {r}: TF XLA PASS", flush=True)
hvd.shutdown()
