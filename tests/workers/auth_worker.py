"""Worker for the socket-auth test (csrc/auth.cc): rank 1 delays its
init so the coordinator's control listener sits in its accepting window
long enough for the test process to poke it with an unauthenticated
connect. The job must complete normally regardless — a rogue connect is
dropped, never fatal."""
import os
import time

import numpy as np

import horovod_tpu as hvd

r = int(os.environ["HVD_RANK"])
if r == 1:
    time.sleep(float(os.environ.get("AUTH_RANK1_DELAY", "5")))

hvd.init()
out = hvd.allreduce(np.ones(32, np.float32), op=hvd.Sum, name="auth.ar")
assert np.allclose(out, float(hvd.size())), out[:4]
hvd.barrier()
hvd.shutdown()
print(f"rank {r}: auth-job PASS", flush=True)
