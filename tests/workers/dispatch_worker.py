"""Worker: OperationManager priority dispatch (reference:
ops/operation_manager.cc — ordered op lists, first Enabled() executes).

Asserts the registered priority order for every collective and that
selection is response-driven: a Sum allreduce rides the terminal ring
backend while an Adasum allreduce in the same process picks the
higher-priority adasum backend.
"""
import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()

assert hvd.op_backends(0) == [
    "adasum_allreduce", "int8_ring_allreduce", "topk_allreduce",
    "hierarchical_allreduce", "ring_allreduce"]
assert hvd.op_backends(1) == ["ring_allgatherv"]
assert hvd.op_backends(2) == ["binomial_broadcast"]
assert hvd.op_backends(3) == ["int8_alltoallv", "pairwise_alltoallv"]
assert hvd.op_backends(4) == ["ring_reducescatter"]

assert hvd.backend_uses("ring_allreduce") == 0
out = hvd.allreduce(np.full(64, float(r + 1), np.float32), op=hvd.Sum)
assert np.allclose(out, s * (s + 1) / 2)
assert hvd.backend_uses("ring_allreduce") == 1
assert hvd.backend_uses("adasum_allreduce") == 0
assert hvd.backend_uses("hierarchical_allreduce") == 0

if s & (s - 1) == 0:  # adasum needs a power-of-two member count
    hvd.allreduce(np.full(16, float(r + 1), np.float32), op=hvd.Adasum)
    assert hvd.backend_uses("adasum_allreduce") == 1
    assert hvd.backend_uses("ring_allreduce") == 1

hvd.allgather(np.full((r + 1, 2), r, np.int32))
assert hvd.backend_uses("ring_allgatherv") == 1
hvd.broadcast(np.arange(4.0), root_rank=0)
assert hvd.backend_uses("binomial_broadcast") == 1
hvd.alltoall(np.zeros(s, np.float32), splits=[1] * s)
assert hvd.backend_uses("pairwise_alltoallv") == 1
hvd.reducescatter(np.ones((s, 2), np.float32), op=hvd.Sum)
assert hvd.backend_uses("ring_reducescatter") == 1

hvd.barrier()
hvd.shutdown()
print(f"DISPATCH rank={r} OK", flush=True)
