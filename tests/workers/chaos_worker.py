"""Chaos-injection elastic worker (docs/elastic.md methodology).

Same elastic loop as elastic_train_worker.py, but the configured victim
slot injects one of three fault types at a fixed iteration:

- ``kill``      — SIGKILL self: clean death, sockets close, the
                  coordinator evicts by name on the dead control socket.
- ``stop``      — SIGSTOP self: the classic wedge. The process stays
                  alive holding every socket open; detection must come
                  from missed control-plane heartbeats
                  (HVD_PEER_TIMEOUT_MS) or the driver's KV liveness
                  backstop, which SIGKILLs the stopped process.
- ``partition`` — arm the in-core fault hook (HVD_FAULT_INJECT=1 in the
                  job env) and trigger ``blackhole``: every core TCP
                  send/recv parks forever, simulating a network
                  partition of the control+data planes while the Python
                  side (KV heartbeats) stays reachable.

Env knobs (set by the test):
- TEST_ITERS / TEST_SLEEP / TEST_LOG: as elastic_train_worker.py
- TEST_CHAOS_FAULT: kill | stop | partition (default: no fault)
- TEST_CHAOS_SLOT:  slot index of the victim (default 1)
- TEST_CHAOS_ITER:  iteration the fault fires at (default 3)
- TEST_MARKER:      marker file recording the fault already fired

On completion every survivor runs a post-recovery parity check — a
fresh allreduce of ones must equal the final world size — and logs
``final rank=R size=S iter=I parity=ok``.
"""

import os
import signal
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic

hvd.init()

ITERS = int(os.environ.get("TEST_ITERS", "8"))
SLEEP = float(os.environ.get("TEST_SLEEP", "0.1"))
FAULT = os.environ.get("TEST_CHAOS_FAULT", "")
SLOT = os.environ.get("TEST_CHAOS_SLOT", "1")
FAULT_ITER = int(os.environ.get("TEST_CHAOS_ITER", "3"))
MARKER = os.environ.get("TEST_MARKER", "")
WID = os.environ.get("HVD_WORKER_ID", "?")


def _is_victim(it):
    if not FAULT or not MARKER or os.path.exists(MARKER):
        return False
    return it == FAULT_ITER and WID.startswith(f"localhost-{SLOT}-")


def _inject():
    with open(MARKER, "w") as f:
        f.write(f"{FAULT} {WID}")
    if FAULT == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    elif FAULT == "stop":
        # The wedge: stopped, not dead. Sockets stay open; only a
        # heartbeat deadline or the driver liveness backstop can tell
        # this apart from a slow rank (and SIGKILL works on a stopped
        # process where SIGTERM stays pending).
        os.kill(os.getpid(), signal.SIGSTOP)
    elif FAULT == "partition":
        assert hvd.fault_trigger("blackhole"), \
            "fault hook not armed (HVD_FAULT_INJECT missing from job env?)"
        # The next collective parks forever inside the core; the driver
        # must SIGKILL this process once a survivor names the rank.
    else:
        raise RuntimeError(f"unknown TEST_CHAOS_FAULT={FAULT!r}")


state = elastic.ObjectState(iteration=0, total=np.zeros(4, np.float32))


@elastic.run
def train(state):
    while state.iteration < ITERS:
        if _is_victim(state.iteration):
            _inject()
        out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                            name=f"it.{state.iteration}")
        state.total = state.total + out
        state.iteration += 1
        state.commit()
        time.sleep(SLEEP)
    return hvd.rank(), hvd.size()


rank, size = train(state)
# Post-recovery parity: the repaired mesh must still reduce correctly.
check = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum, name="parity")
parity = "ok" if np.allclose(check, float(size)) else f"BAD({check[0]})"
if os.environ.get("TEST_LOG"):
    with open(os.environ["TEST_LOG"], "a") as f:
        f.write(f"final rank={rank} size={size} iter={state.iteration} "
                f"parity={parity}\n")
hvd.shutdown()
