"""Worker: legacy orbax back-compat read (ISSUE 15 satellite).

Write a checkpoint with orbax directly — the exact layout the
pre-sharded revisions of horovod_tpu.checkpoint produced (StandardSave
into ``<dir>/<step>/`` with its ``_METADATA`` commit marker) — and
assert the new module still resolves it via ``latest_step`` and
restores it through the legacy orbax path, while a NEW save in the same
directory commits in the sharded format and shadows it as latest.
"""
import os

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import checkpoint

hvd.init()
ckdir = os.environ["CKPT_DIR"]

import orbax.checkpoint as ocp  # noqa: E402

tree = {"w": np.arange(12.0, dtype=np.float32).reshape(3, 4),
        "step": np.asarray(7, np.int64)}
with checkpoint._ckptr() as ck:
    ck.save(os.path.join(ckdir, "3"), args=ocp.args.StandardSave(tree))

# The orbax _METADATA marker counts as committed.
assert checkpoint.latest_step(ckdir) == 3

like = {"w": np.zeros((3, 4), np.float32), "step": np.asarray(0, np.int64)}
out, step = checkpoint.restore(ckdir, like)
assert step == 3, step
assert np.array_equal(np.asarray(out["w"]), tree["w"]), out["w"]
assert int(out["step"]) == 7, out["step"]

# A sharded-format save alongside it becomes the new latest; the legacy
# step stays readable by explicit step=.
checkpoint.save(ckdir, 4, {"w": tree["w"] * 2.0, "step": tree["step"]})
assert checkpoint.latest_step(ckdir) == 4
out, step = checkpoint.restore(ckdir, like, step=3)
assert step == 3 and np.array_equal(np.asarray(out["w"]), tree["w"])
out, step = checkpoint.restore(ckdir, like)
assert step == 4 and np.array_equal(out["w"], tree["w"] * 2.0)

print("legacy-ckpt PASS", flush=True)
hvd.shutdown()
