"""Worker: the minimum end-to-end slice (SURVEY.md §7 stage 4) — JAX
gradients leave the device, ride the core's negotiation + fused TCP ring,
and come back averaged; DistributedOptimizer + broadcast_parameters drive a
real training loop across processes."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # workers must not grab the TPU tunnel

import numpy as np

import jax

cpu = jax.devices("cpu")[0]
jax.config.update("jax_default_device", cpu)

import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import horovod_tpu.jax as hvd  # noqa: E402

hvd.init()
r, s = hvd.rank(), hvd.size()

# --- eager allreduce of a jax array through the core
x = jnp.full((8,), float(r + 1))
y = hvd.allreduce(x, op=hvd.Sum, name="eager.x")
assert np.allclose(np.asarray(y), sum(range(1, s + 1))), y

# --- allreduce inside jit lowers to io_callback through the same core
@jax.jit
def jitted(v):
    return hvd.allreduce(v * 2.0, op=hvd.Average, name="jit.x") + 1.0

out = jitted(jnp.full((4,), float(r)))
expected = 2.0 * np.mean(np.arange(s)) + 1.0
assert np.allclose(np.asarray(out), expected), (out, expected)

# --- broadcast_parameters: rank-divergent params converge to rank 0's
params = {"w": jnp.full((3, 3), float(r)), "b": jnp.full((3,), float(r))}
params = hvd.broadcast_parameters(params, root_rank=0)
assert np.allclose(np.asarray(params["w"]), 0.0)

# --- full DP training loop: DistributedOptimizer averages grads
rng = np.random.default_rng(7)  # same data everywhere; shard by rank
X = rng.normal(size=(64, 5)).astype(np.float32)
Y = (X @ np.arange(5).astype(np.float32))[:, None]
Xr, Yr = jnp.asarray(X[r::s]), jnp.asarray(Y[r::s])

w0 = {"w": jnp.asarray(rng.normal(size=(5, 1)).astype(np.float32))}
w0 = hvd.broadcast_parameters(w0, root_rank=0)
tx = hvd.DistributedOptimizer(optax.sgd(0.05), name="dp.grads")
opt_state = tx.init(w0)


def loss_fn(p, xb, yb):
    return jnp.mean((xb @ p["w"] - yb) ** 2)


@jax.jit
def step(p, o, xb, yb):
    loss, g = jax.value_and_grad(loss_fn)(p, xb, yb)
    updates, o = tx.update(g, o, p)
    return optax.apply_updates(p, updates), o, loss


p, o = w0, opt_state
first = last = None
for i in range(20):
    p, o, loss = step(p, o, Xr, Yr)
    if first is None:
        first = float(loss)
    last = float(loss)
assert last < first * 0.2, (first, last)

# All ranks must hold identical weights (grads were averaged identically).
gathered = hvd.allgather(jnp.reshape(p["w"], (1, -1)), name="final.w")
gw = np.asarray(gathered)
assert gw.shape[0] == s
assert np.allclose(gw, gw[0], atol=1e-6), gw

# fp16 compression path (gradients cross the wire as float16)
tx2 = hvd.DistributedOptimizer(optax.sgd(0.05), name="fp16.grads",
                               compression=hvd.Compression.fp16)
loss, g = jax.value_and_grad(loss_fn)(p, Xr, Yr)
updates, _ = tx2.update(g, tx2.init(p), p)
assert jax.tree.all(jax.tree.map(lambda u: bool(jnp.all(jnp.isfinite(u))), updates))
assert updates["w"].dtype == jnp.float32  # decompressed back

# metric averaging
m = hvd.metric_average(float(r), name="metric.r")
assert abs(m - np.mean(np.arange(s))) < 1e-9

hvd.shutdown()
print(f"rank {r}: JAX DP PASS", flush=True)
