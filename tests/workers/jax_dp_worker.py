"""Worker: the minimum end-to-end slice (SURVEY.md §7 stage 4) — JAX
gradients leave the device, ride the core's negotiation + fused TCP ring,
and come back averaged; DistributedOptimizer + broadcast_parameters drive a
real training loop across processes."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # workers must not grab the TPU tunnel

import numpy as np

import jax

cpu = jax.devices("cpu")[0]
jax.config.update("jax_default_device", cpu)

import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import horovod_tpu.jax as hvd  # noqa: E402

hvd.init()
r, s = hvd.rank(), hvd.size()

# --- eager allreduce of a jax array through the core
x = jnp.full((8,), float(r + 1))
y = hvd.allreduce(x, op=hvd.Sum, name="eager.x")
assert np.allclose(np.asarray(y), sum(range(1, s + 1))), y

# --- allreduce inside jit lowers to io_callback through the same core
@jax.jit
def jitted(v):
    return hvd.allreduce(v * 2.0, op=hvd.Average, name="jit.x") + 1.0

out = jitted(jnp.full((4,), float(r)))
expected = 2.0 * np.mean(np.arange(s)) + 1.0
assert np.allclose(np.asarray(out), expected), (out, expected)

# --- the full core-bridged op set, eager AND in-jit (VERDICT r2 #10)
# allgather (eager, ragged dim0 allowed)
g = hvd.allgather(jnp.full((r + 1, 2), float(r)), name="core.ag")
assert np.asarray(g).shape == (s * (s + 1) // 2, 2)

# allgather in-jit (uniform dim0 declared at trace time)
@jax.jit
def jit_ag(v):
    return hvd.allgather(v, name="jit.ag")

ga = jit_ag(jnp.full((2, 3), float(r)))
assert np.asarray(ga).shape == (2 * s, 3)
exp = np.concatenate([np.full((2, 3), float(i)) for i in range(s)])
assert np.allclose(np.asarray(ga), exp)

# broadcast in-jit
@jax.jit
def jit_bc(v):
    return hvd.broadcast(v, root_rank=s - 1, name="jit.bc")

bc = jit_bc(jnp.full((4,), float(r + 1)))
assert np.allclose(np.asarray(bc), float(s))

# alltoall: eager ragged + in-jit uniform
out, rs = hvd.alltoall(jnp.arange(s * 2, dtype=jnp.float32) + 100 * r,
                       splits=[2] * s, name="core.a2a")
assert np.asarray(out).shape == (2 * s,) and (np.asarray(rs) == 2).all()

@jax.jit
def jit_a2a(v):
    # splits=None: bare tensor (same convention as the tf/torch bindings)
    return hvd.alltoall(v, name="jit.a2a")

o = np.asarray(jit_a2a(jnp.arange(s * 3, dtype=jnp.float32) + 100 * r))
# row block j of rank r's input lands at rank j, position r
for j in range(s):
    assert np.allclose(o[j * 3:(j + 1) * 3],
                       np.arange(r * 3, (r + 1) * 3) + 100 * j), (r, j, o)

# reducescatter: eager + in-jit with uneven dim0 (remainder to first ranks)
m = jnp.ones((s * 2 + 1, 3), jnp.float32) * (r + 1)
rsout = hvd.reducescatter(m, op=hvd.Sum, name="core.rs")
rows = (s * 2 + 1) // s + (1 if r < (s * 2 + 1) % s else 0)
assert np.asarray(rsout).shape == (rows, 3)
assert np.allclose(np.asarray(rsout), sum(range(1, s + 1)))

@jax.jit
def jit_rs(v):
    return hvd.reducescatter(v, op=hvd.Average, name="jit.rs")

rsj = jit_rs(jnp.ones((s * 2 + 1, 3), jnp.float32) * (r + 1))
assert np.asarray(rsj).shape == (rows, 3)
assert np.allclose(np.asarray(rsj), np.mean(np.arange(1, s + 1)))

# --- broadcast_parameters: rank-divergent params converge to rank 0's
params = {"w": jnp.full((3, 3), float(r)), "b": jnp.full((3,), float(r))}
params = hvd.broadcast_parameters(params, root_rank=0)
assert np.allclose(np.asarray(params["w"]), 0.0)

# --- full DP training loop: DistributedOptimizer averages grads
rng = np.random.default_rng(7)  # same data everywhere; shard by rank
X = rng.normal(size=(64, 5)).astype(np.float32)
Y = (X @ np.arange(5).astype(np.float32))[:, None]
Xr, Yr = jnp.asarray(X[r::s]), jnp.asarray(Y[r::s])

w0 = {"w": jnp.asarray(rng.normal(size=(5, 1)).astype(np.float32))}
w0 = hvd.broadcast_parameters(w0, root_rank=0)
tx = hvd.DistributedOptimizer(optax.sgd(0.05), name="dp.grads")
opt_state = tx.init(w0)


def loss_fn(p, xb, yb):
    return jnp.mean((xb @ p["w"] - yb) ** 2)


@jax.jit
def step(p, o, xb, yb):
    loss, g = jax.value_and_grad(loss_fn)(p, xb, yb)
    updates, o = tx.update(g, o, p)
    return optax.apply_updates(p, updates), o, loss


p, o = w0, opt_state
first = last = None
for i in range(20):
    p, o, loss = step(p, o, Xr, Yr)
    if first is None:
        first = float(loss)
    last = float(loss)
assert last < first * 0.2, (first, last)

# All ranks must hold identical weights (grads were averaged identically).
gathered = hvd.allgather(jnp.reshape(p["w"], (1, -1)), name="final.w")
gw = np.asarray(gathered)
assert gw.shape[0] == s
assert np.allclose(gw, gw[0], atol=1e-6), gw

# fp16 compression path (gradients cross the wire as float16)
tx2 = hvd.DistributedOptimizer(optax.sgd(0.05), name="fp16.grads",
                               compression=hvd.Compression.fp16)
loss, g = jax.value_and_grad(loss_fn)(p, Xr, Yr)
updates, _ = tx2.update(g, tx2.init(p), p)
assert jax.tree.all(jax.tree.map(lambda u: bool(jnp.all(jnp.isfinite(u))), updates))
assert updates["w"].dtype == jnp.float32  # decompressed back

# metric averaging
m = hvd.metric_average(float(r), name="metric.r")
assert abs(m - np.mean(np.arange(s))) < 1e-9

hvd.shutdown()
print(f"rank {r}: JAX DP PASS", flush=True)
