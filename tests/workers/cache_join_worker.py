"""Worker: a steady-state CACHED non-allreduce overlapping a join must fail
fast, not hang. Once a collective rides the response-cache bit path, a rank
calling join() never reports its bit; the coordinator must evict the bit so
the survivor reposts through negotiation and receives the
only-allreduce-may-overlap-join error (instead of the bit AND silently never
completing — which the stall inspector cannot see because it only watches
the negotiation table)."""
import time

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()
assert s == 2, "worker is written for 2 ranks"

# Warm the cache: two steady-state broadcasts of the same named tensor.
for _ in range(2):
    out = hvd.broadcast(np.full((4,), 9.0 if r == 1 else 0.0, np.float32),
                        root_rank=1, name="cj.b")
    assert np.allclose(out, 9.0), out
hits, misses, entries = hvd.cache_stats()
assert hits >= 1, (hits, misses)  # second round rode the bit path

if r == 0:
    last = hvd.join()
    assert last == 1, last
else:
    time.sleep(0.5)  # rank 0's join is registered before our bit report
    try:
        hvd.broadcast(np.full((4,), 9.0, np.float32), root_rank=1,
                      name="cj.b")
        raise SystemExit("cached broadcast overlapping join did not fail")
    except RuntimeError as e:
        assert "only allreduce may overlap join" in str(e), e
    last = hvd.join()
    assert last == 1, last

hvd.shutdown()
print(f"rank {r}: cache join PASS", flush=True)
