"""TF binding worker: collectives, DistributedGradientTape,
broadcast_variables, Keras callbacks. (Reference coverage model:
test/parallel/test_tensorflow.py.)"""
import os

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np  # noqa: E402
import tensorflow as tf  # noqa: E402

import horovod_tpu.tensorflow as hvd  # noqa: E402

hvd.init()
r, s = hvd.rank(), hvd.size()

# The native custom-op library (csrc/tf_ops.cc) must have built and
# loaded in this environment — otherwise everything below would silently
# test only the py_function fallback. HVD_TF_NATIVE_OPS=0 runs get the
# fallback on purpose (test_tf_binding_pyfunc_fallback).
from horovod_tpu.tensorflow import native_ops  # noqa: E402

expect_native = os.environ.get("HVD_TF_NATIVE_OPS", "1") == "1"
assert (native_ops.lib() is not None) == expect_native, "native ops state"

# collectives (eager)
out = hvd.allreduce(tf.fill([8], float(r + 1)), op=hvd.Sum)
assert np.allclose(out.numpy(), s * (s + 1) / 2.0)
g = hvd.allgather(tf.fill([2, 3], r))
assert g.shape == (2 * s, 3)
b = hvd.broadcast(tf.range(4, dtype=tf.float32) * float(r + 1),
                  root_rank=0)
assert np.allclose(b.numpy(), np.arange(4))

# alltoall with explicit splits + reducescatter (native kernels when the
# op library is loaded; bridge under HVD_TF_NATIVE_OPS=0)
a2a, rs = hvd.alltoall(tf.fill([s * 2], float(r)), splits=[2] * s)
assert np.allclose(rs.numpy(), 2), rs.numpy()
exp = np.repeat(np.arange(s, dtype=np.float32), 2)
assert np.allclose(a2a.numpy(), exp), a2a.numpy()
rsc = hvd.reducescatter(tf.ones([s * 2, 3]) * float(r + 1), op=hvd.Sum)
assert rsc.shape == (2, 3)
assert np.allclose(rsc.numpy(), s * (s + 1) / 2.0), rsc.numpy()

# grouped allreduce
outs = hvd.grouped_allreduce([tf.fill([4], float(r)),
                              tf.fill([6], 2.0 * r)], op=hvd.Sum)
assert np.allclose(outs[0].numpy(), sum(range(s)))
assert np.allclose(outs[1].numpy(), 2.0 * sum(range(s)))

# inside tf.function (the graph path)
@tf.function
def reduced(x):
    return hvd.allreduce(x, op=hvd.Average, name="infn")

out = reduced(tf.fill([5], float(r)))
assert np.allclose(out.numpy(), (s - 1) / 2.0), out.numpy()

# DistributedGradientTape on a small model; different data per rank
tf.random.set_seed(100 + r)
model = tf.keras.Sequential([
    tf.keras.layers.Dense(8, activation="relu"),
    tf.keras.layers.Dense(1),
])
model.build((None, 4))
hvd.broadcast_variables(model.variables, root_rank=0)
opt = tf.keras.optimizers.SGD(0.05)
x = tf.random.normal((16, 4))
y = tf.random.normal((16, 1))
for _ in range(3):
    with tf.GradientTape() as tape:
        tape = hvd.DistributedGradientTape(tape)
        loss = tf.reduce_mean((model(x) - y) ** 2)
    grads = tape.gradient(loss, model.trainable_variables)
    opt.apply_gradients(zip(grads, model.trainable_variables))

for i, v in enumerate(model.variables):
    ref = hvd.broadcast(tf.identity(v), root_rank=0)
    assert np.allclose(v.numpy(), ref.numpy(), atol=1e-6), \
        f"var {i} diverged"

# metric average
assert abs(hvd.metric_average(float(r)) - (s - 1) / 2.0) < 1e-9

# v1-compat alias exists and is a no-op under eager TF2 (empty v1
# global-variables collection)
hvd.broadcast_global_variables(0)

# DistributedOptimizer inside compiled model.fit (the graph path:
# apply_gradients runs under tf.function and lowers via tf.py_function)
tf.random.set_seed(200 + r)
fit_model = tf.keras.Sequential([tf.keras.layers.Dense(1)])
fit_model.build((None, 3))
hvd.broadcast_variables(fit_model.variables, root_rank=0)
dopt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
fit_model.compile(optimizer=dopt, loss="mse")  # run_eagerly NOT set
fx = tf.random.normal((8, 3))
fy = tf.random.normal((8, 1))
fit_model.fit(fx, fy, epochs=1, batch_size=4, verbose=0)
for i, v in enumerate(fit_model.variables):
    ref = hvd.broadcast(tf.identity(v), root_rank=0)
    assert np.allclose(v.numpy(), ref.numpy(), atol=1e-6), \
        f"fit var {i} diverged"

# backward_passes_per_step: local aggregation, one allreduce every Nth
# call, no variable update in between (reference:
# tensorflow/gradient_aggregation.py LocalGradientAggregationHelper)
agg_model = tf.keras.Sequential([tf.keras.layers.Dense(1, use_bias=False)])
agg_model.build((None, 2))
hvd.broadcast_variables(agg_model.variables, root_rank=0)
aopt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(1.0),
                                backward_passes_per_step=2)
w0 = agg_model.trainable_variables[0].numpy().copy()
gstep = [tf.fill([2, 1], float(r + 1))]
aopt.apply_gradients(zip(gstep, agg_model.trainable_variables))
assert np.allclose(agg_model.trainable_variables[0].numpy(), w0), \
    "variables must not move on a non-communicating step"
aopt.apply_gradients(zip(gstep, agg_model.trainable_variables))
# accumulated (r+1)*2, averaged over passes -> (r+1), over ranks -> (s+1)/2
expect = w0 - (s + 1) / 2.0
assert np.allclose(agg_model.trainable_variables[0].numpy(), expect,
                   atol=1e-6), (agg_model.trainable_variables[0].numpy(),
                                expect)

# same semantics under tf.function (the graph path: tf.Variable counter +
# tf.cond; slot creation lifted via init_scope on first trace)
g_model = tf.keras.Sequential([tf.keras.layers.Dense(1, use_bias=False)])
g_model.build((None, 2))
hvd.broadcast_variables(g_model.variables, root_rank=0)
gopt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(1.0),
                                backward_passes_per_step=2)


@tf.function
def graph_apply(g):
    gopt.apply_gradients(zip([g], g_model.trainable_variables))


gw0 = g_model.trainable_variables[0].numpy().copy()
graph_apply(tf.fill([2, 1], float(r + 1)))
assert np.allclose(g_model.trainable_variables[0].numpy(), gw0)
graph_apply(tf.fill([2, 1], float(r + 1)))
assert np.allclose(g_model.trainable_variables[0].numpy(),
                   gw0 - (s + 1) / 2.0, atol=1e-6)

# Keras callbacks (reference: horovod/_keras/callbacks.py)
import horovod_tpu.keras as hvd_keras  # noqa: E402

cb_model = tf.keras.Sequential([tf.keras.layers.Dense(2)])
cb_model.build((None, 3))
cb_model.optimizer = tf.keras.optimizers.SGD(0.4)
# desync weights, then BroadcastGlobalVariablesCallback resyncs
for v in cb_model.variables:
    v.assign(v + float(r))
bcast_cb = hvd_keras.BroadcastGlobalVariablesCallback(root_rank=0)
bcast_cb.set_model(cb_model)
bcast_cb.on_train_begin()
for v in cb_model.variables:
    ref = hvd.broadcast(tf.identity(v), root_rank=0)
    assert np.allclose(v.numpy(), ref.numpy())

avg_cb = hvd_keras.MetricAverageCallback()
avg_cb.set_model(cb_model)
logs = {"loss": float(r)}
avg_cb.on_epoch_end(0, logs)
assert abs(logs["loss"] - (s - 1) / 2.0) < 1e-9, logs

warm_cb = hvd_keras.LearningRateWarmupCallback(initial_lr=0.4,
                                               warmup_epochs=2)
warm_cb.set_model(cb_model)
warm_cb.on_epoch_begin(0)
lr0 = float(cb_model.optimizer.learning_rate.numpy())
assert lr0 < 0.4 or s == 1, lr0
warm_cb.on_epoch_begin(2)
assert abs(float(cb_model.optimizer.learning_rate.numpy()) - 0.4) < 1e-6

sched_cb = hvd_keras.LearningRateScheduleCallback(initial_lr=0.4,
                                                  multiplier=0.1,
                                                  start_epoch=5)
sched_cb.set_model(cb_model)
sched_cb.on_epoch_begin(5)
assert abs(float(cb_model.optimizer.learning_rate.numpy()) - 0.04) < 1e-6

# gradient_predivide_factor on the tape: must equal plain Average
v_pd = tf.Variable(tf.ones((3,)) * (r + 1.0))
with tf.GradientTape() as t_pd:
    loss_pd = tf.reduce_sum(v_pd * v_pd)
tape_pd = hvd.DistributedGradientTape(t_pd, gradient_predivide_factor=2.0)
g_pd = tape_pd.gradient(loss_pd, [v_pd])[0]
expect_pd = np.mean([2.0 * (i + 1) for i in range(s)])
assert np.allclose(g_pd.numpy(), expect_pd, atol=1e-5), g_pd.numpy()

# ...and through a tf.function trace (the py_function path): the factors
# must be computed at EXECUTION time, never baked into the trace.
v_pg = tf.Variable(tf.ones((3,)) * (r + 1.0))

@tf.function
def pd_graph_step():
    with tf.GradientTape() as t_g:
        loss_g = tf.reduce_sum(v_pg * v_pg)
    tape_g = hvd.DistributedGradientTape(t_g, gradient_predivide_factor=2.0)
    return tape_g.gradient(loss_g, [v_pg])[0]

g_pg = pd_graph_step()
assert np.allclose(g_pg.numpy(), expect_pd, atol=1e-5), g_pg.numpy()

# sparse gradients (tf.IndexedSlices from tf.gather): the default
# sparse_as_dense=False keeps them sparse — allgathered values/indices
# (reference mpi_ops.py IndexedSlices allreduce), never a silent densify;
# sparse_as_dense=True densifies and rides the fused dense group. Both
# must land on the same dense equivalent.
emb = tf.Variable(tf.ones((4, 3)) * (r + 1.0))
with tf.GradientTape() as t_sp:
    # Scale per rank so the averaged gradient actually mixes rank data.
    loss_sp = tf.reduce_sum(tf.gather(emb, [0, 2]) * (r + 1.0))
tape_sp = hvd.DistributedGradientTape(t_sp)
g_sp = tape_sp.gradient(loss_sp, [emb])[0]
assert isinstance(g_sp, tf.IndexedSlices), type(g_sp)
# s ranks x 2 rows gathered; every rank contributes rows {0, 2}.
assert int(tf.shape(g_sp.values)[0]) == 2 * s, g_sp.values.shape
g_sp_dense = tf.convert_to_tensor(g_sp).numpy()  # scatter-adds dup rows
expect_sparse = np.sum([(i + 1.0) for i in range(s)]) / s
assert np.allclose(g_sp_dense[0], expect_sparse, atol=1e-5), g_sp_dense
assert np.allclose(g_sp_dense[2], expect_sparse, atol=1e-5), g_sp_dense
assert np.allclose(g_sp_dense[1], 0.0), g_sp_dense
# Sparse Min has no gather-based form: still a loud error.
with tf.GradientTape() as t_sm:
    loss_sm = tf.reduce_sum(tf.gather(emb, [1]))
tape_sm = hvd.DistributedGradientTape(t_sm, op=hvd.Min)
try:
    tape_sm.gradient(loss_sm, [emb])
    raise SystemExit("expected ValueError (sparse Min)")
except ValueError as e:
    assert "sparse_as_dense=True" in str(e), e
with tf.GradientTape() as t_sd:
    loss_sd = tf.reduce_sum(tf.gather(emb, [0, 2]) * (r + 1.0))
tape_sd = hvd.DistributedGradientTape(t_sd, sparse_as_dense=True)
g_sd = tape_sd.gradient(loss_sd, [emb])[0]
expect_rows = np.mean([i + 1.0 for i in range(s)])
g_sd_np = g_sd.numpy() if not isinstance(g_sd, tf.IndexedSlices) \
    else tf.convert_to_tensor(g_sd).numpy()
assert np.allclose(g_sd_np[0], expect_rows, atol=1e-5), g_sd_np
assert np.allclose(g_sd_np[1], 0.0), g_sd_np

# invalid factors fail at construction, not mid-backward
try:
    hvd.DistributedGradientTape(tf.GradientTape(), op=hvd.Sum,
                                gradient_predivide_factor=2.0)
    raise SystemExit("expected ValueError (op=Sum)")
except ValueError:
    pass
try:
    hvd.DistributedGradientTape(tf.GradientTape(),
                                gradient_predivide_factor=0.0)
    raise SystemExit("expected ValueError (f=0)")
except ValueError:
    pass

# hvd.load_model (reference: horovod/_keras load_model): a saved model's
# optimizer deserializes straight into a DistributedOptimizer with its
# hyperparameters AND slot state (Adam moments) intact, and keeps
# training in sync.
import tempfile  # noqa: E402

lm_model = tf.keras.Sequential([tf.keras.layers.Dense(1)])
lm_model.build((None, 3))
hvd.broadcast_variables(lm_model.variables, root_rank=0)
lm_model.compile(optimizer=hvd.DistributedOptimizer(
    tf.keras.optimizers.Adam(0.037)), loss="mse")
lm_model.fit(fx, fy, epochs=1, batch_size=4, verbose=0)  # builds slots
slots_before = [v.numpy().copy() for v in lm_model.optimizer.variables]
with tempfile.TemporaryDirectory() as td:
    path = os.path.join(td, "m.keras")
    lm_model.save(path)
    loaded = hvd_keras.load_model(path)
    assert getattr(loaded.optimizer, "_hvd_wrapped", False), \
        type(loaded.optimizer)
    assert loaded.optimizer.__class__.__name__ == "Adam"
    assert abs(float(loaded.optimizer.learning_rate.numpy())
               - 0.037) < 1e-7, "learning rate lost in round trip"
    # Adam's moment slots must survive save -> load_model
    slots_after = [v.numpy() for v in loaded.optimizer.variables]
    assert len(slots_after) == len(slots_before) and len(slots_after) > 1
    for i, (a, b) in enumerate(zip(slots_before, slots_after)):
        assert np.allclose(a, b, atol=1e-6), f"slot {i} lost"
    loaded.fit(fx, fy, epochs=1, batch_size=4, verbose=0)
    for i, v in enumerate(loaded.variables):
        ref = hvd.broadcast(tf.identity(v), root_rank=0)
        assert np.allclose(v.numpy(), ref.numpy(), atol=1e-6), \
            f"loaded var {i} diverged"

print(f"rank {r}: TF PASS", flush=True)
hvd.shutdown()
