"""Worker: rapid re-init on the SAME controller port with NO caller-side
retries (VERDICT r4 weak #6 — the retry now lives in the library:
csrc/tcp.cc ListenRetry rebinds rank 0's fixed port with backoff, and
csrc/core.cc EstablishMesh re-dials the whole worker rendezvous exchange
on any mid-handshake failure). Every cycle tears the mesh down and
immediately rebuilds it; ranks deliberately do NOT stagger, so rank 0's
rebind and the workers' re-dials race exactly the way the old test lore
(autotune_win_worker's init-retry loop) was papering over.
"""
import os

import numpy as np

import horovod_tpu as hvd

r = int(os.environ["HVD_RANK"])
cycles = int(os.environ.get("REINIT_CYCLES", "3"))

for c in range(cycles):
    hvd.init()
    s = hvd.size()
    out = hvd.allreduce(np.full(64, float(hvd.rank() + 1), np.float32),
                        op=hvd.Sum, name=f"reinit.{c}")
    assert np.allclose(out, s * (s + 1) / 2.0), out[:4]
    hvd.barrier()
    hvd.shutdown()

print(f"rank {r}: reinit x{cycles} PASS", flush=True)
