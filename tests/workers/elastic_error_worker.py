"""Worker: a dying peer must surface HorovodInternalError on survivors —
the elastic recovery hook (reference: HorovodInternalError raised when a
collective fails; SURVEY.md §3.4)."""
import os
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.exceptions import HorovodInternalError

hvd.init()
r, s = hvd.rank(), hvd.size()

# A couple of healthy rounds first.
for i in range(3):
    out = hvd.allreduce(np.ones(8, dtype=np.float32), op=hvd.Sum, name=f"ok.{i}")
    assert np.allclose(out, s)

if r == s - 1:
    # Die abruptly mid-job (no shutdown handshake).
    os._exit(0)

try:
    hvd.allreduce(np.ones(8, dtype=np.float32), op=hvd.Sum, name="after.death")
    print(f"rank {r}: expected HorovodInternalError", flush=True)
    sys.exit(1)
except HorovodInternalError:
    pass

print(f"rank {r}: PASS", flush=True)
os._exit(0)  # skip shutdown handshake; the job is already degraded
