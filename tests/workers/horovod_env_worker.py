"""Worker: the reference's HOROVOD_* env spellings configure the core
(docs/migrating.md — core.cc EnvRaw fallback). Launched with
HOROVOD_FUSION_THRESHOLD / HOROVOD_CYCLE_TIME / HOROVOD_CACHE_CAPACITY
set and no HVD_* equivalents; asserts the live parameters took them, and
that HVD_* wins when both are present."""
import os

import numpy as np

import horovod_tpu as hvd

hvd.init()
r = hvd.rank()

_, fusion, cycle = hvd.autotune_state()
assert fusion == 8 * 1024 * 1024, fusion       # HOROVOD_FUSION_THRESHOLD
assert abs(cycle - 3.0) < 1e-9, cycle          # HOROVOD_CYCLE_TIME (ms)
out = hvd.allreduce(np.ones(4, np.float32) * (r + 1), op=hvd.Sum)
assert np.allclose(out, hvd.size() * (hvd.size() + 1) / 2.0)
hvd.shutdown()

# precedence: HVD_* beats the compat spelling
os.environ["HVD_CYCLE_TIME_MS"] = "7.0"
hvd.init()
_, _, cycle = hvd.autotune_state()
assert abs(cycle - 7.0) < 1e-9, cycle
hvd.shutdown()

print(f"rank {r}: HOROVOD_* env compat PASS", flush=True)
