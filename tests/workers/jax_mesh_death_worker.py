"""Worker: a process dies while the global mesh is live; survivors must
fail FAST via the core control plane (TCP close -> HorovodInternalError),
not hang toward a coordination-service timeout (VERDICT r2 weak #3:
"no process-death-while-meshed behavior" was tested; reference analog:
ncclCommAbort propagating a NCCL error into HorovodInternalError).

Design note: the core TCP plane is the failure DETECTOR — a dead peer
closes its sockets and every blocked rank unblocks immediately. In-mesh
XLA collectives after a death would wait out their own heartbeat timeout,
so recovery (the elastic path) always re-enters through the core.
"""
from horovod_tpu.jax.distributed import force_cpu_platform

force_cpu_platform(2)

import functools  # noqa: E402
import os  # noqa: E402
import sys  # noqa: E402

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import shard_map  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu.jax as hvd  # noqa: E402
from horovod_tpu.exceptions import HorovodInternalError  # noqa: E402

hvd.init()
r, s = hvd.rank(), hvd.size()
assert hvd.is_multiprocess()
mesh = hvd.global_mesh()
n_local = len(jax.local_devices())


@jax.jit
@functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                   out_specs=P("data"), check_vma=False)
def mesh_sum(x):
    return jax.lax.psum(x, "data") * jnp.ones_like(x)


# Healthy: both planes work with the mesh live.
local = np.full((n_local, 1), float(r + 1), np.float32)
out = mesh_sum(hvd.shard_local_batch(local, mesh))
assert np.allclose(np.asarray(out.addressable_shards[0].data),
                   n_local * sum(range(1, s + 1)))
y = hvd.allreduce(jnp.ones((4,)), op=hvd.Sum, name="pre.death")
assert np.allclose(np.asarray(y), s)

if r == s - 1:
    os._exit(0)  # die abruptly, mesh still formed, no shutdown handshake

import time  # noqa: E402

t0 = time.monotonic()
try:
    hvd.allreduce(jnp.ones((4,)), op=hvd.Sum, name="post.death")
    print(f"rank {r}: expected HorovodInternalError", flush=True)
    sys.exit(1)
except HorovodInternalError:
    detect_s = time.monotonic() - t0
# The bound on the DETECTION PATH itself: TCP close propagates in
# milliseconds; a heartbeat/rendezvous-timeout fallback would take 60s+.
assert detect_s < 10, f"death detection took {detect_s:.1f}s"

print(f"rank {r}: death detected in {detect_s:.3f}s PASS", flush=True)
os._exit(0)  # job is degraded; skip the shutdown handshake
