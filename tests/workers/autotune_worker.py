"""Worker: autotune drives fusion threshold + cycle time on a synthetic
gradient stream (reference: parameter_manager.cc GP+EI, HOROVOD_AUTOTUNE,
HOROVOD_AUTOTUNE_LOG). Run with HVD_AUTOTUNE=1 and fast sampling knobs.

Asserts: parameters measurably change from their defaults, the search
eventually locks, the CSV log on rank 0 records one row per sample, and
every collective result stays correct while parameters move underneath.
"""
import os

# Optional fake multi-host topology (hier_worker.py convention): makes the
# hierarchical-allreduce arm toggleable, so the categorical sweep covers
# all 16 (cache, hier, zerocopy, pipeline) combinations. Without it
# cross_size == 1 and the manager correctly skips the no-op hier arm.
_L = os.environ.get("AT_LOCAL_SIZE")
if _L:
    _r = int(os.environ["HVD_RANK"])
    _s = int(os.environ["HVD_SIZE"])
    _L = int(_L)
    os.environ["HVD_LOCAL_RANK"] = str(_r % _L)
    os.environ["HVD_LOCAL_SIZE"] = str(_L)
    os.environ["HVD_CROSS_RANK"] = str(_r // _L)
    os.environ["HVD_CROSS_SIZE"] = str(_s // _L)

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()

# Optional: register a pipeline-parallel schedule so the CSV's recorded
# `schedule` column carries its label instead of "-" (ISSUE 13).
_SCHED = os.environ.get("AT_PIPE_SCHEDULE", "")
if _SCHED:
    from horovod_tpu.basics import basics as _basics
    assert _basics.register_pipeline_workload(_SCHED)

status0, fusion0, cycle0 = hvd.autotune_state()
assert status0 == "searching", status0
default_fusion = 64 * 1024 * 1024

saw_change = False
max_samples = int(os.environ.get("HVD_AUTOTUNE_MAX_SAMPLES", "30"))
# Fixed iteration count on every rank: collectives must stay symmetric, so
# no data-dependent early exit (a rank breaking first would strand peers).
for i in range(30 * max_samples):
    out = hvd.allreduce(np.full((256,), float(r + 1), np.float32),
                        op=hvd.Sum, name=f"g{i % 4}")
    assert np.allclose(out, sum(range(1, s + 1))), out[0]
    status, fusion, cycle = hvd.autotune_state()
    if fusion != default_fusion or cycle != 1.0:
        saw_change = True

status, fusion, cycle = hvd.autotune_state()
assert saw_change, "autotune never changed the live parameters"
assert status == "locked", (status, fusion, cycle)

log_path = os.environ.get("HVD_AUTOTUNE_LOG", "")
if r == 0 and log_path:
    with open(log_path) as f:
        lines = [l for l in f.read().splitlines() if l]
    assert lines[0] == \
        "sample,fusion_kb,cycle_ms,cache,hier,zerocopy,pipeline,shm," \
        "bucket,compress,wire,affinity,schedule,score_mbps", \
        lines[:1]
    rows = [l for l in lines[1:] if not l.startswith("#")]
    assert len(rows) == max_samples, (len(rows), max_samples)
    assert any(l.startswith("# final") for l in lines), lines[-2:]
    # The schedule column is a recorded context field: "-" until a
    # pipeline workload registers, the registered label afterwards.
    want_sched = _SCHED or "-"
    assert all(l.split(",")[12] == want_sched for l in rows), \
        (want_sched, rows[:2])
    # More than one distinct numeric point was actually explored.
    points = {tuple(l.split(",")[1:3]) for l in rows}
    assert len(points) >= 3, points
    # The categorical sweep ran: the first rows walk every TOGGLEABLE
    # (cache, hier, zerocopy, pipeline, shm, bucket, compress, wire) arm
    # at a pinned numeric point (reference: parameter_manager.cc
    # categorical layers before numeric tuning). Up to 2^8 = 256 arms;
    # HVD_ZEROCOPY=0, HVD_RING_PIPELINE=1, HVD_SHM=0, HVD_BUCKET=0, no
    # HVD_COMPRESS codec, HVD_WIRE=basic (or a probe-refused kernel), an
    # invalid topology, or single-rank each remove a dimension.
    n_arms = int(os.environ.get("EXPECT_ARMS", "8"))
    arms = [tuple(l.split(",")[3:11]) for l in rows[:n_arms]]
    assert len(set(arms)) == n_arms, arms
    numeric_pts = {tuple(l.split(",")[1:3]) for l in rows[:n_arms]}
    assert len(numeric_pts) == 1, numeric_pts
    # ...and the numeric phase runs under ONE locked arm.
    tail_arms = {tuple(l.split(",")[3:11]) for l in rows[n_arms:]}
    assert len(tail_arms) == 1, tail_arms

hvd.shutdown()
print(f"rank {r}: autotune PASS fusion={fusion} cycle={cycle:.3f}",
      flush=True)
