"""Worker: autotune v2 drives the bandit arm search + GP numeric tuning on
a synthetic gradient stream (reference: parameter_manager.cc GP+EI,
HOROVOD_AUTOTUNE, HOROVOD_AUTOTUNE_LOG; docs/autotune.md "v2 search").

Asserts: parameters measurably change from their defaults, the search
eventually locks, and the rank-0 CSV log matches the shared schema
(observability/autotune_csv.py): d+1 probe rows walking every toggleable
dim once at a pinned numeric point, halving rounds, then the GP phase
under ONE locked arm — with collective results staying correct while
parameters move underneath.

Env contract (all optional):
  AT_LOCAL_SIZE        fake multi-host topology (hier arm toggleable)
  AT_PIPE_SCHEDULE     register a pipeline schedule (CSV `schedule` col)
  EXPECT_DIMS          exact toggleable-dim count to assert
  EXPECT_DIMS_MIN      lower bound instead (env-dependent dims, e.g. wire)
  AT_PROFILE_EXPECT    expected CSV/stats profile state ("fresh",
                       "adopted", "near", "corrupt"); "adopted" also
                       asserts 0 sweep samples and an empty sweep log
"""
import os

# Optional fake multi-host topology (hier_worker.py convention): makes the
# hierarchical-allreduce arm toggleable. Without it cross_size == 1 and
# the manager correctly skips the no-op hier arm.
_L = os.environ.get("AT_LOCAL_SIZE")
if _L:
    _r = int(os.environ["HVD_RANK"])
    _s = int(os.environ["HVD_SIZE"])
    _L = int(_L)
    os.environ["HVD_LOCAL_RANK"] = str(_r % _L)
    os.environ["HVD_LOCAL_SIZE"] = str(_L)
    os.environ["HVD_CROSS_RANK"] = str(_r // _L)
    os.environ["HVD_CROSS_SIZE"] = str(_s // _L)

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.observability import autotune_csv

hvd.init()
r, s = hvd.rank(), hvd.size()

# Optional: register a pipeline-parallel schedule so the CSV's recorded
# `schedule` column carries its label instead of "-" (ISSUE 13).
_SCHED = os.environ.get("AT_PIPE_SCHEDULE", "")
if _SCHED:
    from horovod_tpu.basics import basics as _basics
    assert _basics.register_pipeline_workload(_SCHED)

profile_expect = os.environ.get("AT_PROFILE_EXPECT", "")
status0, fusion0, cycle0 = hvd.autotune_state()
if profile_expect != "adopted":
    assert status0 == "searching", status0
default_fusion = 64 * 1024 * 1024

# The sample budget derives from the arm count when HVD_AUTOTUNE_MAX_SAMPLES
# is unset/0 (Configure is deterministic from env + topology, so every rank
# computes the same number — safe to drive loop bounds from it).
budget = hvd.autotune_stats()["budget"]
assert budget > 0, budget

# Chunked stream with a symmetric stop vote: collectives must stay
# symmetric, so no rank may data-dependently break first — instead every
# chunk ends with an allreduced "I'm locked" vote and all ranks exit
# together once unanimous. The cap covers the halving windows' geometric
# growth (cycles_per_sample << round) with generous slack.
saw_change = False
it = 0
for _chunk in range(20 * budget):
    for _ in range(8):
        out = hvd.allreduce(np.full((256,), float(r + 1), np.float32),
                            op=hvd.Sum, name=f"g{it % 4}")
        assert np.allclose(out, sum(range(1, s + 1))), out[0]
        it += 1
    status, fusion, cycle = hvd.autotune_state()
    if fusion != default_fusion or cycle != 1.0:
        saw_change = True
    locked = hvd.allreduce(
        np.full((1,), 1.0 if status == "locked" else 0.0, np.float32),
        op=hvd.Sum, name="at_locked_vote")
    if locked[0] >= s:
        break

status, fusion, cycle = hvd.autotune_state()
assert status == "locked", (status, fusion, cycle)
stats = hvd.autotune_stats()

if r == 0:
    # The search ran on this rank: cross-check the stats surface.
    assert stats["status"] == "locked", stats
    exp_dims = os.environ.get("EXPECT_DIMS")
    if exp_dims is not None:
        assert stats["dims"] == int(exp_dims), (stats, exp_dims)
    exp_dims_min = os.environ.get("EXPECT_DIMS_MIN")
    if exp_dims_min is not None:
        assert stats["dims"] >= int(exp_dims_min), (stats, exp_dims_min)
    assert stats["arms"] == 2 ** stats["dims"], stats
    if profile_expect:
        assert stats["profile"] == profile_expect, stats
    if profile_expect == "adopted":
        # Second identical job: the persisted profile was adopted with
        # ZERO sweep samples (the acceptance headline).
        assert stats["adopted_profile"] and stats["samples"] == 0, stats
    else:
        assert not stats["adopted_profile"], stats
        assert stats["samples"] == stats["budget"], stats
        assert saw_change, "autotune never changed the live parameters"

log_path = os.environ.get("HVD_AUTOTUNE_LOG", "")
if r == 0 and log_path:
    with open(log_path) as f:
        lines = [l for l in f.read().splitlines() if l]
    assert lines[0] == autotune_csv.HEADER, lines[:1]
    rows = [autotune_csv.split_row(l) for l in lines[1:]
            if not l.startswith("#")]
    assert any(l.startswith("# final") for l in lines), lines[-2:]
    want_profile = profile_expect or ("fresh" if os.environ.get(
        "HVD_AUTOTUNE_PROFILE_DIR") else "-")
    if profile_expect == "adopted":
        # No sweep rows at all; the log records the adoption + final only.
        assert not rows, rows[:2]
        assert any(l.startswith("# adopted") for l in lines), lines
    else:
        assert len(rows) == stats["budget"], (len(rows), stats)
        assert all(row["profile"] == want_profile for row in rows), \
            (want_profile, rows[0])
        # The schedule column is a recorded context field: "-" until a
        # pipeline workload registers, the registered label afterwards.
        want_sched = _SCHED or "-"
        assert all(row["schedule"] == want_sched for row in rows), \
            (want_sched, rows[:2])
        d = stats["dims"]

        def arm_of(row):
            return tuple(row[c] for c in autotune_csv.ARM_COLUMNS)

        def pt_of(row):
            return (row["fusion_kb"], row["cycle_ms"])

        # Probe phase: d+1 rows (baseline + each dim flipped alone), every
        # toggleable dim observed in both states, all at ONE pinned
        # numeric point so arm scores stay comparable.
        probes = rows[:d + 1]
        assert all(row["bracket"] == "probe" for row in probes), probes
        assert len({arm_of(row) for row in probes}) == d + 1, probes
        varying = sum(1 for c in autotune_csv.ARM_COLUMNS
                      if len({row[c] for row in probes}) == 2)
        assert varying == d, (varying, d, probes)
        assert len({pt_of(row) for row in probes}) == 1, probes
        # After the probes: halving rounds (h<r>), numerically pinned like
        # the probes, then the GP phase under ONE locked arm.
        tail = rows[d + 1:]
        assert all(row["bracket"][0] in "hg" for row in tail), tail[:2]
        halving = [row for row in tail if row["bracket"].startswith("h")]
        assert len({pt_of(row) for row in probes + halving}) == 1, halving
        gp = [row for row in tail if row["bracket"] == "gp"]
        assert gp, "numeric phase never ran"
        assert len({arm_of(row) for row in gp}) == 1, gp
        # More than one distinct numeric point was actually explored.
        assert len({pt_of(row) for row in rows}) >= 3, rows

hvd.shutdown()
print(f"rank {r}: autotune PASS fusion={fusion} cycle={cycle:.3f} "
      f"samples={stats['samples']} profile={stats['profile']}",
      flush=True)
