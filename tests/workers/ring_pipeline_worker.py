"""Worker: streamed ring reduce-scatter (HVD_RING_PIPELINE).

Parity sweep across dtypes (f32/f64/i32/i64/f16/bf16) and ops
(Sum/Min/Max) against locally computed expected values — exact for the
integer dtypes, tolerance for floats — then asserts the core's
pipeline_stats()/reduce_stats() counters prove which path ran:

* HVD_RING_PIPELINE unset/0/N>1: ring steps whose chunk clears the
  streaming floor must deliver sub-blocks into Accumulate while the
  socket drains (stream_steps/stream_blocks > 0, overlap_us > 0).
* HVD_RING_PIPELINE=1: forced serial — every step must take the
  recv-then-reduce path (stream_steps == 0, serial_steps > 0), and the
  same parity sweep proves the two paths compute identical results.

With HVD_TIMELINE set, rank 0 additionally asserts the core timeline
recorded TCP_REDUCE_OVERLAP sub-events sized by the overlapped reduce
time.
"""
import os

import numpy as np

import horovod_tpu as hvd

try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

hvd.init()
r, s = hvd.rank(), hvd.size()

cfg = int(os.environ.get("HVD_RING_PIPELINE", "0"))
enabled, depth = hvd.pipeline_state()
assert depth == cfg, (depth, cfg)
assert enabled == (cfg != 1), (enabled, cfg)

# Large enough that every dtype's per-rank ring chunk clears the 4 KiB
# streaming floor at up to 8 ranks (f16: 2 B * 65536 / 8 = 16 KiB).
N = 65536

fast0, _, scalar0, _ = hvd.reduce_stats()
steps0, blocks0, serial0, us0 = hvd.pipeline_stats()


def rank_array(dtype, rk):
    # Small integers: exactly representable in every dtype here (bf16 has
    # an 8-bit mantissa; sums stay < 256 so even bf16 sums are exact).
    return ((np.arange(N) % 13) + rk).astype(dtype)


OPS = [(hvd.Sum, "sum"), (hvd.Min, "min"), (hvd.Max, "max")]
DTYPES = [np.float32, np.float64, np.int32, np.int64, np.float16]
if _BF16 is not None:
    DTYPES.append(_BF16)

for dtype in DTYPES:
    dt = np.dtype(dtype)
    all_ranks = np.stack(
        [rank_array(dtype, rk).astype(np.float64) for rk in range(s)])
    for op, opname in OPS:
        x = rank_array(dtype, r)
        out = hvd.allreduce(x, op=op, name=f"rp.{dt.name}.{opname}")
        if opname == "sum":
            expect = all_ranks.sum(axis=0)
        elif opname == "min":
            expect = all_ranks.min(axis=0)
        else:
            expect = all_ranks.max(axis=0)
        got = np.asarray(out).astype(np.float64)
        if dt.kind in "iu":
            assert np.array_equal(got, expect), \
                (dt.name, opname, got[:4], expect[:4])
        else:
            # Values are exactly representable, so even the 16-bit floats
            # come back exact; keep a tolerance for safety.
            assert np.allclose(got, expect, rtol=1e-2, atol=1e-2), \
                (dt.name, opname, got[:4], expect[:4])

steps1, blocks1, serial1, us1 = hvd.pipeline_stats()
fast1, fast_el, scalar1, _ = hvd.reduce_stats()

if cfg == 1:
    assert steps1 == steps0, "forced-serial run streamed a ring step"
    assert blocks1 == blocks0
    assert serial1 > serial0, (serial0, serial1)
else:
    assert steps1 > steps0, "no ring step streamed (pipeline inert?)"
    # On loopback a whole chunk can land in one recv, so a streamed step
    # may deliver one large block; every streamed step delivers >= 1.
    assert blocks1 - blocks0 >= steps1 - steps0, \
        "streamed steps must deliver sub-blocks"
    assert us1 >= us0, (us0, us1)
    assert serial1 >= serial0

if os.environ.get("HVD_REDUCE_VECTOR", "1") != "0":
    assert fast1 > fast0, "vectorized reduce tier never dispatched"
    assert fast_el > 0
else:
    assert scalar1 > scalar0, "scalar tier forced but never dispatched"

hvd.barrier(name="rp.done")
hvd.shutdown()

tl = os.environ.get("HVD_TIMELINE")
if tl and r == 0 and cfg != 1:
    text = open(tl).read()
    assert "TCP_REDUCE_OVERLAP" in text, \
        "no TCP_REDUCE_OVERLAP sub-events in the core timeline"

print(f"rank {r}: ring_pipeline PASS cfg={cfg} "
      f"stream_steps={steps1 - steps0} blocks={blocks1 - blocks0} "
      f"serial={serial1 - serial0} overlap_us={us1 - us0}", flush=True)
