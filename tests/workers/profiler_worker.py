"""Worker: profiler op ranges + trace window (reference:
nvtx_op_range.h — ranges around user-facing op calls; TPU mapping is the
xplane trace via jax.profiler). HVD_PROFILER=1 in the env: every
collective call runs inside a TraceAnnotation, and rank 0 opens a trace
window around a few steps and asserts the xplane artifact lands."""
import glob
import os

import numpy as np

import horovod_tpu as hvd

hvd.init()
r, s = hvd.rank(), hvd.size()
assert hvd.profiler.enabled()

logdir = os.environ["PROFILE_DIR"] + f"/rank{r}"
hvd.profiler.start(logdir)
for it in range(3):
    out = hvd.allreduce(np.full(256, float(r + 1), np.float32), op=hvd.Sum,
                        name="prof.ar")
    assert np.allclose(out, s * (s + 1) / 2)
hvd.allgather(np.full((r + 1, 2), r, np.float32), name="prof.ag")
hvd.profiler.stop()

traces = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                   recursive=True)
assert traces, f"no xplane trace under {logdir}"

# Ops still work after the window closes (annotation is a cheap no-op
# relative to correctness).
out = hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name="prof.after")
assert np.allclose(out, s)
hvd.barrier()
hvd.shutdown()
print(f"PROFILER rank={r} traces={len(traces)} OK", flush=True)
