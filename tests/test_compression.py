"""Compressed collectives (ISSUE 11): the int8 error-feedback ring and
top-k sparsified allgather codecs in csrc/core.cc — numeric parity across
rank counts and reduce ops, the error-feedback convergence proof, the
kill switch counter-proven byte-silent, runtime codec flips, the
TCP_COMPRESS_* timeline family, the seventh autotune arm, and the
binding-level Compression surface (int8/topk compressors, the bf16
ImportError message, core_codec routing)."""

import json
import os
import re

import numpy as np
import pytest

from .util import assert_sanitizer_clean, run_under_sanitizer, \
    run_worker_job

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu", "csrc")


# --- the parity matrix: ranks x codec x {Sum, Average} ---------------------
# Each worker mode runs BOTH reduce ops against an exact f32 reference it
# regenerates locally; int8/topk additionally assert bit-identical outputs
# on every rank and their wire-byte ratios.

@pytest.mark.parametrize(
    "np_", [2, 4, pytest.param(8, marks=pytest.mark.slow)])
def test_parity_int8(np_):
    run_worker_job(np_, "compress_worker.py", timeout=240, extra_env={
        "HVD_COMPRESS": "int8",
        "COMPRESS_MODE": "parity",
    })


@pytest.mark.parametrize(
    "np_", [2, 4, pytest.param(8, marks=pytest.mark.slow)])
def test_parity_topk(np_):
    """frac=1.0 keeps everything, so the sparse exchange itself (index
    packing, allgather, member-order densify) must be numerically exact."""
    run_worker_job(np_, "compress_worker.py", timeout=240, extra_env={
        "HVD_COMPRESS": "topk",
        "HVD_COMPRESS_TOPK_FRAC": "1.0",
        "COMPRESS_MODE": "parity",
    })


@pytest.mark.parametrize(
    "np_", [2, 4, pytest.param(8, marks=pytest.mark.slow)])
def test_parity_fp16(np_):
    run_worker_job(np_, "compress_worker.py", timeout=240, extra_env={
        "COMPRESS_MODE": "fp16",
    })


@pytest.mark.parametrize(
    "np_", [2, 4, pytest.param(8, marks=pytest.mark.slow)])
def test_parity_bf16(np_):
    run_worker_job(np_, "compress_worker.py", timeout=240, extra_env={
        "COMPRESS_MODE": "bf16",
    })


# --- error feedback --------------------------------------------------------

def test_error_feedback_convergence_topk():
    """The EF-SGD telescoping proof: the T-step running mean of a fixed
    gradient under 5% sparsity converges toward the exact sum (4x under
    the single-step error by T=64, still descending at T/2->T), where a
    feedback-free top-k would hold a constant bias forever. 5%/n=1024 so
    coordinates cycle through selection well inside T (~1/frac steps)."""
    run_worker_job(4, "compress_worker.py", timeout=300, extra_env={
        "HVD_COMPRESS": "topk",
        "HVD_COMPRESS_TOPK_FRAC": "0.05",
        "COMPRESS_MODE": "ef",
        "COMPRESS_N": "1024",
        "COMPRESS_EF_STEPS": "64",
    })


def test_error_feedback_convergence_int8():
    run_worker_job(4, "compress_worker.py", timeout=300, extra_env={
        "HVD_COMPRESS": "int8",
        "COMPRESS_MODE": "ef",
        "COMPRESS_EF_STEPS": "24",
    })


def test_topk_one_percent_wire_ratio():
    """The headline acceptance bound: topk at 1% keeps k=41 of n=4096
    per rank, so 4 ranks move n/(k*s) ~ 25x fewer wire bytes than the
    uncompressed f32 ring — comfortably over the required 10x."""
    run_worker_job(4, "compress_worker.py", timeout=240, extra_env={
        "HVD_COMPRESS": "topk",
        "HVD_COMPRESS_TOPK_FRAC": "0.01",
        "COMPRESS_MODE": "ratio",
        "COMPRESS_EXPECT_RATIO": "10.0",
    })


def test_int8_wire_ratio():
    """int8's bound: quantized ring payloads (one 4-byte scale per hop)
    clear the required 3.5x over f32."""
    run_worker_job(4, "compress_worker.py", timeout=240, extra_env={
        "HVD_COMPRESS": "int8",
        "COMPRESS_MODE": "ratio",
        "COMPRESS_EXPECT_RATIO": "3.5",
    })


# --- kill switch + runtime flips -------------------------------------------

def test_kill_switch_counters_stay_zero():
    """Compression off (HVD_COMPRESS unset): no codec backend runs and
    every compression counter — core and binding — stays zero. This is
    the counter-proof that the off path left every wire byte alone."""
    run_worker_job(2, "compress_worker.py", timeout=180, extra_env={
        "COMPRESS_MODE": "off",
    })


def test_runtime_codec_flip():
    """set_compression('int8') on every rank engages mid-run without a
    restart; set_compression(None) disengages and the counters freeze.
    The all-ranks-agree negotiation makes the flip safe without a
    barrier."""
    run_worker_job(2, "compress_worker.py", timeout=180, extra_env={
        "COMPRESS_MODE": "runtime",
    })


# --- timeline ---------------------------------------------------------------

def test_timeline_compress_events(tmp_path):
    """The TCP_COMPRESS_* sub-event family: int8 emits QUANTIZE+EXCHANGE,
    topk emits SELECT+EXCHANGE+DENSIFY, all inside valid chrome-trace
    JSON."""
    tl = tmp_path / "compress_timeline.json"
    run_worker_job(2, "compress_worker.py", timeout=180, extra_env={
        "HVD_COMPRESS": "int8",
        "COMPRESS_MODE": "parity",
        "HVD_TIMELINE": str(tl),
    })
    events = json.loads(tl.read_text())
    phases = {e["name"] for e in events}
    assert "TCP_COMPRESS_QUANTIZE" in phases, phases
    assert "TCP_COMPRESS_EXCHANGE" in phases, phases

    tl2 = tmp_path / "compress_timeline_topk.json"
    run_worker_job(2, "compress_worker.py", timeout=180, extra_env={
        "HVD_COMPRESS": "topk",
        "HVD_COMPRESS_TOPK_FRAC": "1.0",
        "COMPRESS_MODE": "parity",
        "HVD_TIMELINE": str(tl2),
    })
    phases2 = {e["name"] for e in json.loads(tl2.read_text())}
    assert {"TCP_COMPRESS_SELECT", "TCP_COMPRESS_EXCHANGE",
            "TCP_COMPRESS_DENSIFY"} <= phases2, phases2


# --- the seventh autotune arm ----------------------------------------------

def test_autotune_compress_arm(tmp_path):
    """The compress toggle as the seventh categorical arm: with
    zerocopy/pipeline/shm/bucket pinned and int8 configured, a 2-rank
    job's (cache, compress) probe rows flip each dim once and the
    compress CSV column really takes both states."""
    log = tmp_path / "autotune_compress.csv"
    run_worker_job(2, "autotune_worker.py", extra_env={
        "HVD_AUTOTUNE": "1",
        "HVD_AUTOTUNE_LOG": str(log),
        "HVD_AUTOTUNE_CYCLES_PER_SAMPLE": "4",
        "HVD_AUTOTUNE_MAX_SAMPLES": "10",
        "HVD_ZEROCOPY": "0",
        "HVD_RING_PIPELINE": "1",
        "HVD_SHM": "0",
        "HVD_BUCKET": "0",
        "HVD_COMPRESS": "int8",
        # wire arm pinned off: covered by test_wire.py::test_autotune_wire_arm
        "HVD_WIRE": "basic",
        "EXPECT_DIMS": "2",
    }, timeout=240)
    # d+1 = 3 probe rows: baseline, cache flipped, compress flipped.
    rows = [l for l in log.read_text().splitlines()[1:4]
            if not l.startswith("#")]
    assert {l.split(",")[9] for l in rows} == {"0", "1"}, rows


def test_arm_space_is_two_to_the_ninth():
    """kMaxArms covers the full 2^9 categorical space: nine toggleable
    dimensions (cache, hier, zerocopy, pipeline, shm, bucket, compress,
    wire — ISSUE 12 — plus alltoall tiering, ISSUE 19) need 512 arm
    slots. v2 (ISSUE 18) replaces the exhaustive Configure nest with a
    bit-lattice the bandit searches: every dim must be an AutotuneDim
    enum bit with init_/can_toggle_ config fields, and the lattice size
    must be 2^dims."""
    src = open(os.path.join(_CSRC, "autotune.h")).read()
    m = re.search(r"kMaxArms\s*=\s*(\d+)", src)
    assert m and int(m.group(1)) == 512, m
    for dim in ("cache", "hier", "zerocopy", "pipeline", "shm", "bucket",
                "compress", "wire", "alltoall"):
        assert re.search(r"kDim%s\b" % dim.capitalize(), src), dim
        assert re.search(r"\binit_%s\b" % dim, src), dim
        assert re.search(r"\bcan_toggle_%s\b" % dim, src), dim
    cc = open(os.path.join(_CSRC, "autotune.cc")).read()
    assert re.search(r"arm_count_\s*=\s*1\s*<<\s*dim_count_", cc)
    # ...and the shared CSV schema carries one column per dim.
    from horovod_tpu.observability import autotune_csv
    assert len(autotune_csv.ARM_COLUMNS) == 9, autotune_csv.ARM_COLUMNS


# --- sanitizer tiers --------------------------------------------------------
# The codec kernels touch residual state from the background thread and
# run a new FullDuplex/RingAllgatherv exchange shape; both tiers run the
# full parity worker (slow: the .so rebuilds under instrumentation).

@pytest.mark.slow
def test_int8_ring_under_tsan(tmp_path):
    p, core_reports = run_under_sanitizer(
        tmp_path, "compress_worker.py", 4, tier="tsan", extra_env={
            "HVD_COMPRESS": "int8", "COMPRESS_MODE": "parity"})
    assert_sanitizer_clean(p, 4, core_reports, tier="tsan")


@pytest.mark.slow
def test_topk_under_tsan(tmp_path):
    p, core_reports = run_under_sanitizer(
        tmp_path, "compress_worker.py", 4, tier="tsan", extra_env={
            "HVD_COMPRESS": "topk", "HVD_COMPRESS_TOPK_FRAC": "1.0",
            "COMPRESS_MODE": "parity"})
    assert_sanitizer_clean(p, 4, core_reports, tier="tsan")


@pytest.mark.slow
def test_int8_ring_under_asan(tmp_path):
    p, core_reports = run_under_sanitizer(
        tmp_path, "compress_worker.py", 4, tier="asan", extra_env={
            "HVD_COMPRESS": "int8", "COMPRESS_MODE": "parity"})
    assert_sanitizer_clean(p, 4, core_reports, tier="asan")


@pytest.mark.slow
def test_topk_under_asan(tmp_path):
    p, core_reports = run_under_sanitizer(
        tmp_path, "compress_worker.py", 4, tier="asan", extra_env={
            "HVD_COMPRESS": "topk", "HVD_COMPRESS_TOPK_FRAC": "1.0",
            "COMPRESS_MODE": "parity"})
    assert_sanitizer_clean(p, 4, core_reports, tier="asan")


# --- binding-level Compression surface (no core, no ranks) ------------------

def test_bf16_importerror_is_actionable(monkeypatch):
    """When ml_dtypes is missing, Compression.bf16 re-raises an
    ImportError that names both the fix (pip install ml_dtypes) and the
    fallback (Compression.fp16) instead of a bare module-not-found."""
    import builtins

    from horovod_tpu.compression import Compression

    real_import = builtins.__import__

    def no_ml_dtypes(name, *a, **kw):
        if name == "ml_dtypes":
            raise ImportError("No module named 'ml_dtypes'")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", no_ml_dtypes)
    with pytest.raises(ImportError) as ei:
        Compression.bf16.compress(np.ones(4, np.float32))
    msg = str(ei.value)
    assert "pip install ml_dtypes" in msg, msg
    assert "Compression.fp16" in msg, msg


def test_int8_compressor_roundtrip():
    from horovod_tpu.compression import Compression

    x = np.linspace(-3.0, 3.0, 1001, dtype=np.float32)
    q, ctx = Compression.int8.compress(x)
    assert q.dtype == np.int8
    out = Compression.int8.decompress(q, ctx)
    assert out.dtype == np.float32
    # Symmetric per-tensor scale: error bounded by scale/2 = maxabs/254.
    assert np.abs(out - x).max() <= 3.0 / 254.0 + 1e-7
    # Non-float passthrough.
    i = np.arange(8, dtype=np.int32)
    q2, ctx2 = Compression.int8.compress(i)
    assert q2 is i and ctx2 is None


def test_topk_compressor_keeps_largest():
    from horovod_tpu.compression import Compression

    comp = Compression.topk(0.1)
    x = np.arange(100, dtype=np.float32) - 50.0
    out, ctx = comp.compress(x)
    nz = np.nonzero(out)[0]
    assert len(nz) == 10
    kept = set(np.abs(x).argsort()[-10:])
    assert set(nz) == kept, (nz, kept)
    assert comp.decompress(out, ctx) is out
    with pytest.raises(ValueError):
        Compression.topk(0.0)
    with pytest.raises(ValueError):
        Compression.topk(1.5)


def test_core_codec_routing():
    from horovod_tpu import compression

    assert compression.core_codec(None) == (0, 0.0)
    assert compression.core_codec(compression.Compression.fp16) == (0, 0.0)
    assert compression.core_codec(compression.Compression.int8) == (1, 0.0)
    assert compression.core_codec(
        compression.Compression.topk(0.05)) == (2, 0.05)

    class Custom(compression.Int8Compressor):
        pass

    # Exact-class match: a subclass may change semantics the core codec
    # wouldn't reproduce.
    assert compression.core_codec(Custom) == (0, 0.0)


def test_wire_cast_counters():
    from horovod_tpu import compression

    before = compression.stats()
    compression.record_wire_cast(True)
    compression.record_wire_cast(False)
    after = compression.stats()
    assert after["engaged"] == before["engaged"] + 1
    assert after["fallback"] == before["fallback"] + 1
