"""Serving-plane engine + loop (ISSUE 14) — the jax half.

What tier-1 pins here:

- **Decode parity**: the paged-KV incremental decode (prefill once, then
  one token per jit'd step through block-table indirection) produces the
  SAME logits and the same greedy chain as running the full
  ``transformer.forward`` over the growing sequence. This is the
  correctness contract of the whole serving plane — the cache layout,
  the position convention (token ``generated[-1]`` lands at position
  ``context_len - 1``, attending kv_pos <= position), and the trash-page
  masking all collapse into this one comparison.
- **Mixed lengths, one step**: requests at different context lengths
  share a single jit'd decode step (the point of the block table);
  each slot matches its own full-forward reference.
- **resolve_attn decode shapes**: the auto-resolver keys on KV length
  and causal mode (satellite: a q_len=1 decode step must pick "gather"
  regardless of cache length; a chunked prefill crosses to "flash" on
  live-score footprint; the pre-existing self-attention threshold is
  unchanged).
- **ServeLoop**: end-to-end continuous batching over Poisson arrivals —
  all requests finish, the continuous-vs-static batch-fill gap is
  scheduling (not timing), preemption replays losslessly.
- **Driver autoscale**: the elastic driver consumes /ctl/serve_load
  observations and folds them into a target world size.

The jax-free scheduling invariants live in
tests/test_serving_scheduler.py (numpy-only).
"""
import dataclasses
import json

import numpy as np
import pytest

pytest.importorskip("jax")
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from horovod_tpu.models import transformer as tfm  # noqa: E402
from horovod_tpu.serving import engine, kv_cache  # noqa: E402
from horovod_tpu.serving.loop import (ServeLoop,  # noqa: E402
                                      poisson_requests)
from horovod_tpu.serving.scheduler import Request  # noqa: E402

pytestmark = pytest.mark.serve


def _cfg(**kw):
    """float32 so logits parity is tight (tiny() is bf16)."""
    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                d_ff=64, max_seq_len=64, dtype="float32")
    base.update(kw)
    return tfm.TransformerConfig(**base)


def _ref_logits(params, cfg, seq):
    """Full-forward reference: logits for the NEXT token after `seq`."""
    return np.asarray(
        tfm.forward(params, np.asarray([seq], np.int32), cfg)[0, -1],
        np.float32)


# ---------------------------------------------------------------------------
# engine parity
# ---------------------------------------------------------------------------

def test_decode_parity_with_forward():
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=16, page_size=8, max_context=64)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    prefill = engine.make_prefill(cfg, geo)
    decode = engine.make_decode_step(cfg, geo, max_batch=1)
    cache = kv_cache.make_cache(cfg, geo)

    rng = np.random.default_rng(3)
    prompt = [int(x) for x in rng.integers(0, cfg.vocab_size, size=9)]
    n_new = 10
    pages = list(range(1, 1 + (len(prompt) + n_new + geo.page_size - 1)
                       // geo.page_size))
    bt = np.asarray(pages + [0] * (geo.max_blocks - len(pages)), np.int32)

    toks = np.zeros(geo.max_kv, np.int32)
    toks[:len(prompt)] = prompt
    cache, logits = prefill(params, cache, toks, np.int32(len(prompt)), bt)
    step_logits = [np.asarray(logits, np.float32)]
    seq = list(prompt) + [int(engine.greedy(logits))]

    for _ in range(n_new - 1):
        # the newest token goes in at position len(seq)-1 and predicts
        # the next one.
        cache, logits = decode(
            params, cache,
            np.asarray([seq[-1]], np.int32),
            np.asarray([len(seq) - 1], np.int32),
            bt[None, :], np.asarray([True]))
        step_logits.append(np.asarray(logits[0], np.float32))
        seq.append(int(engine.greedy(logits)[0]))

    # ONE full forward over the final sequence references every step:
    # causal attention makes logits[i] a function of seq[:i+1] alone, so
    # per-position agreement + argmax consistency proves (by induction)
    # the incremental chain equals full-recompute greedy decoding.
    ref_all = np.asarray(
        tfm.forward(params, np.asarray([seq], np.int32), cfg)[0],
        np.float32)
    for i, got in enumerate(step_logits):
        pos = len(prompt) + i - 1      # position that produced seq[pos+1]
        np.testing.assert_allclose(got, ref_all[pos],
                                   rtol=1e-4, atol=1e-5)
        assert seq[pos + 1] == int(np.argmax(ref_all[pos]))


def test_mixed_lengths_share_one_decode_step():
    """Two requests at different context lengths decode in ONE jit'd
    step via their block tables; each slot matches its own reference."""
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=16, page_size=8, max_context=64)
    params = tfm.init_params(jax.random.PRNGKey(1), cfg)
    prefill = engine.make_prefill(cfg, geo)
    decode = engine.make_decode_step(cfg, geo, max_batch=3)
    cache = kv_cache.make_cache(cfg, geo)

    rng = np.random.default_rng(5)
    seqs = [[int(x) for x in rng.integers(0, cfg.vocab_size, size=n)]
            for n in (5, 13)]
    tables = []
    next_page = 1
    for seq in seqs:
        n_pages = (len(seq) + 1 + geo.page_size - 1) // geo.page_size
        pages = list(range(next_page, next_page + n_pages))
        next_page += n_pages
        bt = np.asarray(pages + [0] * (geo.max_blocks - len(pages)),
                        np.int32)
        toks = np.zeros(geo.max_kv, np.int32)
        toks[:len(seq)] = seq
        cache, logits = prefill(params, cache, toks,
                                np.int32(len(seq)), bt)
        seq.append(int(engine.greedy(logits)))
        tables.append(bt)

    # slot 2 is INACTIVE garbage — its writes must route to trash page 0
    # and not perturb the live slots.
    cache, logits = decode(
        params, cache,
        np.asarray([seqs[0][-1], seqs[1][-1], 0], np.int32),
        np.asarray([len(seqs[0]) - 1, len(seqs[1]) - 1, 0], np.int32),
        np.stack([tables[0], tables[1],
                  np.zeros(geo.max_blocks, np.int32)]),
        np.asarray([True, True, False]))
    for slot, seq in enumerate(seqs):
        np.testing.assert_allclose(np.asarray(logits[slot], np.float32),
                                   _ref_logits(params, cfg, seq),
                                   rtol=1e-4, atol=1e-5)


def test_prefill_pad_validated():
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=16, page_size=8, max_context=64)
    with pytest.raises(ValueError):
        engine.make_prefill(cfg, geo, prefill_pad=13)   # not page-aligned
    with pytest.raises(ValueError):
        engine.make_prefill(cfg, geo, prefill_pad=128)  # > max_seq_len


# ---------------------------------------------------------------------------
# resolve_attn: serving shapes (satellite)
# ---------------------------------------------------------------------------

def test_resolve_attn_kv_len_and_causal(monkeypatch):
    cfg = dataclasses.replace(tfm.tiny(), attn_impl="auto")
    monkeypatch.setattr(tfm.jax, "default_backend", lambda: "tpu")
    # decode: q_len=1 against a long cache is ALWAYS gather — the score
    # row is linear in KV, flash's q-tiling has nothing to eliminate.
    assert tfm.resolve_attn(cfg, 1, None, kv_len=8192) == "gather"
    assert tfm.resolve_attn(cfg, 1, None, kv_len=128) == "gather"
    # chunked prefill: a 512-query block against an 8K cache has a 4M
    # live score footprint -> flash.
    assert tfm.resolve_attn(cfg, 512, None, kv_len=8192) == "flash"
    # pre-existing causal self-attention threshold unchanged: the live
    # triangle crosses the S=1024 measured crossover.
    assert tfm.resolve_attn(cfg, 1024, None) == "flash"
    assert tfm.resolve_attn(cfg, 1023, None) == "gather"
    # bidirectional squares materialize twice the logits -> earlier
    # crossover (724^2 < threshold <= 725^2).
    assert tfm.resolve_attn(cfg, 725, None, causal=False) == "flash"
    assert tfm.resolve_attn(cfg, 724, None, causal=False) == "gather"


def test_resolve_attn_ring_requires_self_attention(monkeypatch):
    """A sequence-sharded mesh resolves to ring ONLY for full
    self-attention — rotating K/V shards past a 1-token query against an
    external cache is meaningless (the pre-fix failure mode)."""
    cfg = dataclasses.replace(tfm.tiny(), attn_impl="auto")
    monkeypatch.setattr(tfm.jax, "default_backend", lambda: "tpu")

    class _SeqMesh:
        axis_names = (cfg.seq_axis,)
        shape = {cfg.seq_axis: 4}

    assert tfm.resolve_attn(cfg, 128, _SeqMesh()) == "ring"
    assert tfm.resolve_attn(cfg, 1, _SeqMesh(), kv_len=4096) == "gather"


def test_resolve_attn_cpu_backend_gathers():
    cfg = dataclasses.replace(tfm.tiny(), attn_impl="auto")
    if jax.default_backend() == "tpu":
        pytest.skip("CPU-backend branch")
    assert tfm.resolve_attn(cfg, 4096, None) == "gather"


# ---------------------------------------------------------------------------
# ServeLoop end to end
# ---------------------------------------------------------------------------

def _instant(reqs):
    """Open-loop arrivals collapsed to t=0: scheduling (not wall-clock
    arrival timing) decides every admission — deterministic A/B."""
    for r in reqs:
        r.arrival_t = 1e-9
    return reqs


def test_serve_loop_continuous_vs_static_fill():
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=32, page_size=8, max_context=64)
    params = tfm.init_params(jax.random.PRNGKey(2), cfg)
    summaries = {}
    for mode in ("continuous", "static"):
        rng = np.random.default_rng(9)
        reqs = _instant(poisson_requests(
            10, rate=1e6, rng=rng, prompt_len=(2, 6), max_new=(1, 12),
            vocab=cfg.vocab_size))
        sl = ServeLoop(params, cfg, geo=geo, max_batch=4, mode=mode)
        sl.warmup()
        summary, finished = sl.run(reqs)
        assert len(finished) == 10
        assert summary["tokens"] == sum(len(r.generated) for r in finished)
        assert all(r.finish_reason == "max_tokens" for r in finished)
        summaries[mode] = summary
    # the A/B gap the bench measures, isolated from timing: continuous
    # refills drained slots, static idles them until the batch empties.
    assert summaries["continuous"]["batch_fill_mean"] \
        > summaries["static"]["batch_fill_mean"]


def test_serve_loop_preemption_replays_losslessly():
    """A page-starved pool forces preemption; the re-prefill replays
    prompt + generated so every request still finishes with its full
    greedy chain (matching an uncontended run)."""
    cfg = _cfg()
    params = tfm.init_params(jax.random.PRNGKey(4), cfg)
    roomy = kv_cache.geometry(n_pages=32, page_size=4, max_context=32)
    tight = dataclasses.replace(roomy, n_pages=7)  # 6 usable pages

    def _run(geo):
        rng = np.random.default_rng(13)
        reqs = _instant(poisson_requests(
            4, rate=1e6, rng=rng, prompt_len=(3, 6), max_new=(8, 12),
            vocab=cfg.vocab_size))
        sl = ServeLoop(params, cfg, geo=geo, max_batch=2, mode="continuous")
        summary, finished = sl.run(reqs)
        assert len(finished) == 4
        return summary, {r.rid: list(r.generated) for r in finished}

    tight_summary, tight_chains = _run(tight)
    _, roomy_chains = _run(roomy)
    assert tight_summary["preemptions"] > 0
    assert tight_chains == roomy_chains


def test_serve_loop_rejects_oversized_prompt():
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=8, page_size=4, max_context=16)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    sl = ServeLoop(params, cfg, geo=geo, max_batch=1)
    with pytest.raises(ValueError):
        sl.run([Request(rid=0, prompt=list(range(16)), max_new_tokens=4)])


# ---------------------------------------------------------------------------
# serving v2 (ISSUE 16): chunked/batched prefill, prefix cache, speculation
# ---------------------------------------------------------------------------

def test_chunk_step_parity_with_forward():
    """The chunked prefill step (decode generalized to q_len > 1) writes
    window K/V through the block table and matches the full forward at
    every real position, including a ragged final chunk whose padding
    writes land beyond every compared position."""
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=16, page_size=8, max_context=64)
    params = tfm.init_params(jax.random.PRNGKey(7), cfg)
    chunk = engine.make_chunk_step(cfg, geo, q_len=8)
    cache = kv_cache.make_cache(cfg, geo)
    rng = np.random.default_rng(11)
    seq = [int(x) for x in rng.integers(0, cfg.vocab_size, size=20)]
    bt = np.asarray([1, 2, 3] + [0] * (geo.max_blocks - 3), np.int32)[None]
    ref_all = np.asarray(
        tfm.forward(params, np.asarray([seq], np.int32), cfg)[0],
        np.float32)
    for start in (0, 8, 16):
        end = min(start + 8, len(seq))
        toks = np.zeros((1, 8), np.int32)
        toks[0, :end - start] = seq[start:end]
        cache, logits = chunk(params, cache, toks,
                              np.asarray([start], np.int32), bt,
                              np.ones(1, bool))
        np.testing.assert_allclose(
            np.asarray(logits[0, :end - start], np.float32),
            ref_all[start:end], rtol=1e-4, atol=1e-5)


def test_chunk_step_validated():
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=16, page_size=8, max_context=64)
    with pytest.raises(ValueError):
        engine.make_chunk_step(cfg, geo, q_len=0)
    with pytest.raises(ValueError):   # cache wider than the pos table
        engine.make_chunk_step(
            cfg, kv_cache.geometry(32, 8, 128), q_len=8)


def test_batched_prefill_parity():
    """One padded call prefills rows of different lengths; each row's
    last-real-position logits match its own full-forward reference."""
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=16, page_size=8, max_context=64)
    params = tfm.init_params(jax.random.PRNGKey(8), cfg)
    bp = engine.make_batched_prefill(cfg, geo)
    cache = kv_cache.make_cache(cfg, geo)
    rng = np.random.default_rng(12)
    seqs = [[int(x) for x in rng.integers(0, cfg.vocab_size, size=n)]
            for n in (5, 13, 9)]
    B, mb, pad = 3, geo.max_blocks, geo.max_kv
    toks = np.zeros((B, pad), np.int32)
    lengths = np.ones(B, np.int32)
    tables = np.zeros((B, mb), np.int32)
    next_page = 1
    for row, seq in enumerate(seqs):
        toks[row, :len(seq)] = seq
        lengths[row] = len(seq)
        n_pages = -(-len(seq) // geo.page_size)
        tables[row, :n_pages] = range(next_page, next_page + n_pages)
        next_page += n_pages
    cache, logits = bp(params, cache, toks, lengths, tables,
                       np.ones(B, bool))
    for row, seq in enumerate(seqs):
        np.testing.assert_allclose(np.asarray(logits[row], np.float32),
                                   _ref_logits(params, cfg, seq),
                                   rtol=1e-4, atol=1e-5)


def test_batched_prefill_loop_parity_and_fallback_counters():
    """Satellite: same-boundary admissions prefill in ONE batched call;
    the counted per-request fallback produces identical chains."""
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=32, page_size=8, max_context=64)
    params = tfm.init_params(jax.random.PRNGKey(6), cfg)

    def _reqs():
        rng = np.random.default_rng(17)
        return _instant(poisson_requests(
            8, rate=1e6, rng=rng, prompt_len=(2, 10), max_new=(2, 10),
            vocab=cfg.vocab_size))

    on = ServeLoop(params, cfg, geo=geo, max_batch=4, prefix_cache=False,
                   batch_prefill=True)
    s_on, f_on = on.run(_reqs())
    off = ServeLoop(params, cfg, geo=geo, max_batch=4, prefix_cache=False,
                    batch_prefill=False)
    s_off, f_off = off.run(_reqs())
    assert off.bprefill_fn is None
    assert {r.rid: r.generated for r in f_on} \
        == {r.rid: r.generated for r in f_off}
    assert s_on["prefill_batch_calls"] >= 1 and s_on["prefill_batched"] >= 2
    assert s_off["prefill_batch_calls"] == 0
    assert s_off["prefill_single"] == 8


def test_prefix_cache_warm_second_request_hits():
    """A warm identical prefix admits with shared pages, chunk-fills
    only the novel tail, and still generates the exact cache-off chain —
    the cached K/V really is the prefill's K/V."""
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=32, page_size=8, max_context=64)
    params = tfm.init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.default_rng(21)
    prefix = [int(x) for x in rng.integers(0, cfg.vocab_size, size=24)]
    tails = [[int(x) for x in rng.integers(0, cfg.vocab_size, size=4)]
             for _ in range(2)]

    def _req(rid, tail):
        return Request(rid=rid, prompt=prefix + list(tail),
                       max_new_tokens=8)

    off = ServeLoop(params, cfg, geo=geo, max_batch=2, prefix_cache=False)
    _, ref0 = off.run(_instant([_req(0, tails[0])]))
    _, ref1 = off.run(_instant([_req(1, tails[1])]))
    sl = ServeLoop(params, cfg, geo=geo, max_batch=2, prefix_cache=True)
    _, cold = sl.run(_instant([_req(0, tails[0])]))
    assert cold[0].cached_tokens == 0            # nothing cached yet
    _, warm = sl.run(_instant([_req(1, tails[1])]))
    assert warm[0].cached_tokens == 24           # 3 shared pages
    assert cold[0].generated == ref0[0].generated
    assert warm[0].generated == ref1[0].generated
    assert sl.batcher.stats["prefix_hit_tokens"] == 24
    assert sl.loop_stats["chunk_fills"] >= 1     # only the tail was filled
    import horovod_tpu as hvd
    stats = hvd.serve_stats()
    assert stats["prefix_cache"] is True
    assert stats["prefix_hit_ratio"] > 0
    assert stats["prefix_nodes"] >= 3


class _OracleDrafter:
    """Drafts the exact reference continuation — pins the accept-side
    bookkeeping at (near-)full acceptance, no model luck involved."""

    def __init__(self, finished):
        self._chains = {tuple(r.prompt): list(r.generated)
                        for r in finished}

    def propose(self, context, k):
        for prompt, chain in self._chains.items():
            n = len(prompt)
            if tuple(context[:n]) == prompt and len(context) >= n:
                done = len(context) - n
                return chain[done:done + k]
        return []


def test_spec_decode_bit_identical_to_greedy():
    """The speculative path emits EXACTLY the plain greedy chain — with
    the self-drafting NGramDrafter and with a full-acceptance oracle —
    and the accept/reject counters add up."""
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=16, page_size=8, max_context=64)
    params = tfm.init_params(jax.random.PRNGKey(5), cfg)
    prompt = [1, 2, 3, 4] * 3

    def _reqs():
        return _instant([
            Request(rid=0, prompt=list(prompt), max_new_tokens=20),
            Request(rid=1, prompt=list(prompt[2:]), max_new_tokens=16)])

    base = ServeLoop(params, cfg, geo=geo, max_batch=2,
                     prefix_cache=False, spec_tokens=0)
    _, ref = base.run(_reqs())
    ref_chains = {r.rid: list(r.generated) for r in ref}

    spec = ServeLoop(params, cfg, geo=geo, max_batch=2,
                     prefix_cache=False, spec_tokens=3)
    summary, got = spec.run(_reqs())
    assert {r.rid: list(r.generated) for r in got} == ref_chains
    assert summary["spec_steps"] > 0
    st = spec.batcher.stats
    # every spec step emits accepted + 1 bonus; decode-side tokens are
    # total minus the two prefill-emitted first tokens.
    assert st["spec_accepted"] + st["spec_steps"] == st["tokens"] - 2

    oracle = ServeLoop(params, cfg, geo=geo, max_batch=2,
                       prefix_cache=False, spec_tokens=3,
                       drafter=_OracleDrafter(ref))
    o_summary, o_got = oracle.run(_reqs())
    assert {r.rid: list(r.generated) for r in o_got} == ref_chains
    assert o_summary["spec_accepted_per_step"] > 2.0   # near-full accept
    assert o_summary["spec_steps"] < summary["spec_steps"] \
        or summary["spec_accepted_per_step"] == o_summary[
            "spec_accepted_per_step"]


def test_serve_kill_switches_restore_baseline(monkeypatch):
    """HVD_SERVE_PREFIX_CACHE=0 + spec_tokens=0 is the PR 14 loop: no
    prefix/spec engine is built and the four new SERVE_* metric families
    record ZERO activity even with metrics enabled."""
    from horovod_tpu.observability import metrics as _metrics
    cfg = _cfg()
    geo = kv_cache.geometry(n_pages=16, page_size=8, max_context=64)
    params = tfm.init_params(jax.random.PRNGKey(2), cfg)
    monkeypatch.setenv("HVD_SERVE_PREFIX_CACHE", "0")
    monkeypatch.setenv("HVD_SERVE_SPEC_TOKENS", "0")
    sl = ServeLoop(params, cfg, geo=geo, max_batch=2)   # env-driven
    assert sl.prefix is None and sl.chunk_fn is None and sl.spec_fn is None
    _metrics.REGISTRY.clear()
    monkeypatch.setattr(_metrics, "_enabled", True)
    try:
        rng = np.random.default_rng(2)
        summary, finished = sl.run(_instant(poisson_requests(
            4, rate=1e6, rng=rng, prompt_len=(2, 6), max_new=(1, 6),
            vocab=cfg.vocab_size)))
        assert len(finished) == 4
        for m in (_metrics.SERVE_PREFIX_HIT_RATIO,
                  _metrics.SERVE_PREFIX_EVICTIONS,
                  _metrics.SERVE_SPEC_ACCEPTED_PER_STEP,
                  _metrics.SERVE_SPEC_REJECTED):
            assert m.collect() == []                 # zero activity
        assert _metrics.SERVE_BATCH_FILL.collect()   # baseline recorded
        assert summary["prefix_hit_ratio"] == 0.0
        assert summary["spec_steps"] == 0
        assert summary["chunk_fills"] == 0
    finally:
        _metrics.REGISTRY.clear()
    # the knobs plumb through when set the other way
    monkeypatch.setenv("HVD_SERVE_PREFIX_CACHE", "1")
    monkeypatch.setenv("HVD_SERVE_SPEC_TOKENS", "2")
    sl2 = ServeLoop(params, cfg, geo=geo, max_batch=2)
    assert sl2.prefix is not None and sl2.spec_tokens == 2
    assert sl2.chunk_fn is not None and sl2.spec_fn is not None


# ---------------------------------------------------------------------------
# driver autoscale plumbing
# ---------------------------------------------------------------------------

def test_driver_consumes_serve_load():
    """The elastic driver drains /ctl/serve_load through the autoscale
    policy: consumed keys leave the KV bounded, a sustained breach moves
    _target_np (the epoch active-set cap), malformed payloads are
    ignored."""
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.serving.autoscale import AutoscalePolicy

    d = ElasticDriver(["true"], FixedHosts({}), 1, 4)
    try:
        d.autoscale = AutoscalePolicy(1, 4, high_depth=8, patience=2)

        def _push(payload):
            d.rdv.put("/ctl/serve_load/w1", payload)

        _push(b"not json")                      # ignored, still consumed
        assert d._check_serve_load() is False
        assert d.rdv.scan("/ctl/serve_load") == {}

        _push(json.dumps({"queue_depth": 20, "batch_fill": 1.0}).encode())
        assert d._check_serve_load() is False   # streak 1 < patience
        _push(json.dumps({"queue_depth": 20, "batch_fill": 1.0}).encode())
        assert d._check_serve_load() is True    # streak 2 -> scale up
        assert d._target_np == 2
        assert d.stats["autoscale_events"] == 1
        assert d.stats["target_np"] == 2
        assert json.loads(d.rdv.get("/ctl/elastic_stats"))["target_np"] == 2

        # sustained idle walks the target back down to min_np
        for _ in range(2):
            _push(json.dumps({"queue_depth": 0,
                              "batch_fill": 0.1}).encode())
            d._check_serve_load()
        assert d._target_np == 1
    finally:
        d.stop()
