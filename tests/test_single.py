"""Single-process tier (reference: test/single/): API behavior with size=1,
launcher utilities, no cluster."""

import numpy as np
import pytest

import horovod_tpu as hvd


@pytest.fixture(scope="module", autouse=True)
def _init():
    import os

    # Slow the negotiation cycle so the duplicate-name test below can enqueue
    # its second tensor before the first leaves the queue.
    os.environ["HVD_CYCLE_TIME_MS"] = "30"
    hvd.init()
    yield
    hvd.shutdown()
    os.environ.pop("HVD_CYCLE_TIME_MS", None)


def test_rank_size():
    assert hvd.rank() == 0
    assert hvd.size() == 1
    assert hvd.local_rank() == 0
    assert hvd.is_initialized()


def test_allreduce_identity():
    x = np.random.default_rng(0).normal(size=(4, 5)).astype(np.float32)
    out = hvd.allreduce(x, op=hvd.Sum)
    assert np.allclose(out, x)
    out = hvd.allreduce(x, op=hvd.Average)
    assert np.allclose(out, x)


def test_dtypes():
    for dt in [np.uint8, np.int8, np.int32, np.int64, np.float16,
               np.float32, np.float64]:
        x = np.ones((3,), dtype=dt)
        assert hvd.allreduce(x, op=hvd.Sum).dtype == dt


def test_bfloat16():
    import ml_dtypes

    x = np.ones((5,), dtype=ml_dtypes.bfloat16)
    out = hvd.allreduce(x, op=hvd.Sum)
    assert out.dtype == ml_dtypes.bfloat16
    assert np.allclose(out.astype(np.float32), 1.0)


def test_allgather_single():
    x = np.arange(6, dtype=np.int32).reshape(2, 3)
    assert (hvd.allgather(x) == x).all()


def test_broadcast_object():
    obj = {"a": 1, "b": [1, 2, 3]}
    assert hvd.broadcast_object(obj, root_rank=0) == obj


def test_duplicate_name_rejected():
    x = np.ones(4, dtype=np.float32)
    h1 = hvd.allreduce_async(x, name="dup")
    with pytest.raises(ValueError, match="already pending"):
        # Enqueue a second in-flight tensor with the same name immediately.
        hvd.allreduce_async(x, name="dup")
    hvd.synchronize(h1)


def test_prescale_postscale():
    x = np.full(4, 2.0, dtype=np.float32)
    out = hvd.allreduce(x, op=hvd.Sum, prescale_factor=0.5,
                        postscale_factor=3.0)
    assert np.allclose(out, 3.0)


def test_keras_elastic_callbacks():
    """Elastic Keras callbacks mutate/commit state at the right hooks
    (reference: _keras/callbacks.py CommitStateCallback /
    UpdateBatchStateCallback / UpdateEpochStateCallback)."""
    pytest.importorskip("tensorflow")
    from horovod_tpu._keras.callbacks import (
        CommitStateCallback,
        UpdateBatchStateCallback,
        UpdateEpochStateCallback,
    )

    class _State:
        def __init__(self):
            self.commits = 0
            self.batch = None
            self.epoch = None

        def commit(self):
            self.commits += 1

    st = _State()
    commit_cb = CommitStateCallback(st, batches_per_commit=2)
    batch_cb = UpdateBatchStateCallback(st)
    epoch_cb = UpdateEpochStateCallback(st)
    for b in range(4):
        batch_cb.on_train_batch_end(b)
        commit_cb.on_train_batch_end(b)
    epoch_cb.on_epoch_end(3)
    assert st.commits == 2      # batches 1 and 3 (every 2nd)
    assert st.batch == 3
    assert st.epoch == 3
