"""Launcher unit tests (reference: test/single/test_run.py — arg parsing,
hostfile parsing, command assembly with NOTHING actually executed, plus KV
store round trips on localhost)."""

import json
import os
import textwrap

import pytest

from horovod_tpu.runner import config_parser, hosts, http_server, util
from horovod_tpu.runner.launch import get_remote_command, parse_args


# -- hosts ------------------------------------------------------------------

def test_parse_hosts():
    hs = hosts.parse_hosts("a:4,b:2,c")
    assert hs == [hosts.HostInfo("a", 4), hosts.HostInfo("b", 2),
                  hosts.HostInfo("c", 1)]
    with pytest.raises(ValueError):
        hosts.parse_hosts("")


def test_parse_hostfile(tmp_path):
    p = tmp_path / "hf"
    p.write_text(textwrap.dedent("""\
        # cluster
        node1 slots=4
        node2:2
        node3
    """))
    hs = hosts.parse_hostfile(str(p))
    assert hs == [hosts.HostInfo("node1", 4), hosts.HostInfo("node2", 2),
                  hosts.HostInfo("node3", 1)]


def test_host_assignments():
    hs = [hosts.HostInfo("a", 2), hosts.HostInfo("b", 2)]
    slots = hosts.get_host_assignments(hs, 3)
    assert [(s.hostname, s.rank, s.local_rank, s.local_size,
             s.cross_rank) for s in slots] == [
        ("a", 0, 0, 2, 0), ("a", 1, 1, 2, 0), ("b", 2, 0, 1, 1)]
    assert all(s.size == 3 for s in slots)
    with pytest.raises(ValueError):
        hosts.get_host_assignments(hs, 5)


# -- args / config ----------------------------------------------------------

def test_parse_args_basic():
    a = parse_args(["-np", "4", "--fusion-threshold-mb", "32",
                    "--timeline-filename", "/tmp/t.json",
                    "python", "train.py", "--lr", "0.1"])
    assert a.np == 4
    assert a.command == ["python", "train.py", "--lr", "0.1"]
    env = config_parser.args_to_env(a)
    assert env["HVD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
    assert env["HVD_TIMELINE"] == "/tmp/t.json"


def test_parse_args_no_stall_check():
    a = parse_args(["-np", "2", "--no-stall-check", "x"])
    env = config_parser.args_to_env(a)
    assert env["HVD_STALL_CHECK_TIME_SECONDS"] == "0"


def test_config_file(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(textwrap.dedent("""\
        params:
          fusion-threshold-mb: 16
          cycle-time-ms: 2.5
        timeline:
          filename: /tmp/tl.json
          mark-cycles: true
        autotune:
          enable: true
    """))
    a = parse_args(["-np", "2", "--config-file", str(cfg), "x"])
    env = config_parser.args_to_env(a)
    assert env["HVD_FUSION_THRESHOLD"] == str(16 * 1024 * 1024)
    assert env["HVD_CYCLE_TIME_MS"] == "2.5"
    assert env["HVD_TIMELINE"] == "/tmp/tl.json"
    assert env["HVD_TIMELINE_MARK_CYCLES"] == "1"
    assert env["HVD_AUTOTUNE"] == "1"


def test_cli_overrides_config_file(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("params:\n  fusion-threshold-mb: 16\n")
    a = parse_args(["-np", "2", "--fusion-threshold-mb", "8",
                    "--config-file", str(cfg), "x"])
    env = config_parser.args_to_env(a)
    assert env["HVD_FUSION_THRESHOLD"] == str(8 * 1024 * 1024)


# -- remote command assembly (nothing executed; reference mocks ssh) --------

def test_get_remote_command():
    s = hosts.SlotInfo("node7", 3, 8, 1, 2, 1, 2)
    cmd = get_remote_command(s, ["python", "train.py"],
                             {"HVD_RANK": "3", "HVD_SIZE": "8"},
                             ssh_port=2222)
    assert cmd.startswith("ssh ")
    assert "node7" in cmd and "-p 2222" in cmd
    assert "HVD_RANK=3" in cmd and "HVD_SIZE=8" in cmd
    assert "python train.py" in cmd


def test_remote_command_negotiated_endpoints_and_stdin_secret(monkeypatch):
    """Multi-host static launch (mocked ssh, reference style:
    test/single/test_run.py): the exact remote command carries the
    negotiate sentinel and rendezvous address, reads the HMAC secret from
    STDIN (never argv), and no remote port is guessed by the launcher."""
    import horovod_tpu.runner.launch as launch_mod

    spawned = []

    class _FakeProc:
        def __init__(self):
            import io

            self.stdin = io.BytesIO()
            self.stdin.flush = lambda: None
            self._closed = False
            orig_close = self.stdin.close

            def close():
                self._data = self.stdin.getvalue()
                orig_close()

            self.stdin.close = close

        def poll(self):
            return 0

    def fake_safe_exec(command, env=None, stdout=None, stderr=None,
                       stdin=None):
        p = _FakeProc()
        spawned.append((command, env, p))
        return p

    monkeypatch.setattr(launch_mod, "safe_exec", fake_safe_exec)
    monkeypatch.setattr(launch_mod, "terminate", lambda p: None)
    args = launch_mod.parse_args(
        ["-np", "2", "-H", "remote1:1,remote2:1", "python", "train.py"])
    rc = launch_mod._run_static(args)
    assert rc == 0
    assert len(spawned) == 2
    for command, env, proc in spawned:
        sh = command[2]  # ["/bin/sh", "-c", cmd]
        assert sh.startswith("ssh ")
        assert "HVD_CONTROLLER_ADDR=negotiate" in sh
        assert "HVD_JAX_COORD_ADDR=negotiate" in sh
        assert "HVD_RENDEZVOUS_ADDR=" in sh
        assert "TPU_VISIBLE_CHIPS=0" in sh  # chip pin reaches remote hosts
        # the secret must never appear on the command line...
        assert "HVD_RENDEZVOUS_SECRET=" not in sh.replace(
            "read -r HVD_RENDEZVOUS_SECRET", "")
        assert "read -r HVD_RENDEZVOUS_SECRET && "\
               "export HVD_RENDEZVOUS_SECRET" in sh
        # ...it rides stdin.
        secret_line = proc._data
        assert secret_line.endswith(b"\n") and len(secret_line) == 65
        bytes.fromhex(secret_line.strip().decode())  # valid hex key


def test_endpoint_negotiation_localhost():
    """runner/network.py: rank 0 probes a free port on its own host,
    discovers the interface routing to the driver (loopback here), and
    registers it; rank 1 reads the same address (reference:
    driver_service.py task registration)."""
    import threading

    from horovod_tpu.runner import network

    key = util.make_secret_key()
    srv = http_server.RendezvousServer(secret_key=key)
    port = srv.start()
    addr = f"127.0.0.1:{port}"
    results = {}
    try:
        def rank1():
            results[1] = network.negotiate(addr, key, 1, "svc-t",
                                           ["controller", "jax_coord"],
                                           timeout=10)

        t = threading.Thread(target=rank1)
        t.start()
        results[0] = network.negotiate(addr, key, 0, "svc-t",
                                       ["controller", "jax_coord"])
        t.join(timeout=15)
        assert results[0] == results[1]
        host, p = results[0]["controller"].rsplit(":", 1)
        assert host == "127.0.0.1"  # loopback iface selected toward driver
        assert 0 < int(p) < 65536
        assert results[0]["controller"] != results[0]["jax_coord"]
    finally:
        srv.stop()


# -- HTTP KV rendezvous -----------------------------------------------------

def test_kv_store_roundtrip():
    key = util.make_secret_key()
    srv = http_server.RendezvousServer(secret_key=key)
    port = srv.start()
    addr = f"127.0.0.1:{port}"
    try:
        http_server.put_kv(addr, "scope", "k1", b"hello", secret_key=key)
        assert http_server.read_kv(addr, "scope", "k1",
                                   secret_key=key) == b"hello"
        # missing key → 404
        import urllib.error
        with pytest.raises(urllib.error.HTTPError):
            http_server.read_kv(addr, "scope", "nope", secret_key=key)
        # bad signature → 403
        with pytest.raises(urllib.error.HTTPError):
            http_server.read_kv(addr, "scope", "k1",
                                secret_key=b"wrong-key-000")
    finally:
        srv.stop()


def test_kv_client_retries_transient_only(monkeypatch):
    """Bounded retry policy (docs/elastic.md): ECONNREFUSED against a dead
    port is retried HVD_KV_RETRIES times (counted in retry_count(), the
    kv_retries field of hvd.elastic_stats()); an HTTP status from a LIVE
    server (404 missing key) reached the server and is never retried."""
    import urllib.error

    from horovod_tpu.runner.local import find_free_port

    monkeypatch.setenv("HVD_KV_RETRIES", "2")
    # Squash the backoff sleeps; the schedule itself is what we count.
    monkeypatch.setattr(http_server.time, "sleep", lambda s: None)
    before = http_server.retry_count()
    dead = find_free_port()  # probed free, nothing listening
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        http_server.put_kv(f"127.0.0.1:{dead}", "scope", "k", b"v")
    assert http_server.retry_count() - before == 2

    srv = http_server.RendezvousServer()
    port = srv.start()
    try:
        before = http_server.retry_count()
        with pytest.raises(urllib.error.HTTPError) as ei:
            http_server.read_kv(f"127.0.0.1:{port}", "scope", "nope")
        assert ei.value.code == 404
        assert http_server.retry_count() == before  # 404 is not transient
    finally:
        srv.stop()


def test_kv_store_wait_rendezvous():
    import threading
    import time

    srv = http_server.RendezvousServer()
    port = srv.start()
    addr = f"127.0.0.1:{port}"
    try:
        def put_later():
            time.sleep(0.3)
            http_server.put_kv(addr, "rdv", "epoch", b"7")

        t = threading.Thread(target=put_later)
        t.start()
        v = http_server.read_kv(addr, "rdv", "epoch", wait=True, timeout=5)
        assert v == b"7"
        t.join()
    finally:
        srv.stop()


# -- end-to-end localhost launch -------------------------------------------

def _worker_pythonpath(monkeypatch):
    """Spawned launcher ranks must NOT inherit the session's site-hook
    PYTHONPATH (it would register the real TPU platform inside every
    worker — tests/util.tpu_isolated_env is the single policy)."""
    from .util import tpu_isolated_env

    for k, v in tpu_isolated_env().items():
        monkeypatch.setenv(k, v)


def test_tpurun_localhost(tmp_path, monkeypatch):
    """Full CLI path: tpurun -np 2 on localhost, real collective."""
    from horovod_tpu.runner.launch import run_commandline

    _worker_pythonpath(monkeypatch)
    script = tmp_path / "w.py"
    script.write_text(textwrap.dedent("""\
        import numpy as np
        import horovod_tpu as hvd
        hvd.init()
        out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum)
        assert (out == hvd.size()).all()
        hvd.shutdown()
    """))
    rc = run_commandline(["-np", "2", "--no-stall-check",
                          "python", str(script)])
    assert rc == 0


def test_tpurun_failure_propagates(tmp_path):
    from horovod_tpu.runner.launch import run_commandline

    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)\n")
    rc = run_commandline(["-np", "2", "python", str(script)])
    assert rc != 0


def test_tpu_chip_binding(monkeypatch):
    """tpurun pins TPU_VISIBLE_CHIPS=local_rank per slot (one process =
    one chip, set before libtpu init); HVD_BIND_TPU_CHIPS=0 opts out."""
    import horovod_tpu.runner.launch as launch_mod

    def capture(np_):
        seen = []

        def fake_safe_exec(command, env=None, **kw):
            seen.append(env)

            class _P:
                def poll(self):
                    return 0
            return _P()

        monkeypatch.setattr(launch_mod, "safe_exec", fake_safe_exec)
        monkeypatch.setattr(launch_mod, "terminate", lambda p: None)
        args = launch_mod.parse_args(
            ["-np", str(np_), "python", "train.py"])
        assert launch_mod._run_static(args) == 0
        return seen

    envs = capture(2)
    assert [e.get("TPU_VISIBLE_CHIPS") for e in envs] == ["0", "1"]

    # an inherited launcher-level pin must be OVERWRITTEN per rank, not
    # kept (setdefault would bind every rank to the same chip)
    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "3")
    envs = capture(2)
    assert [e.get("TPU_VISIBLE_CHIPS") for e in envs] == ["0", "1"]
    monkeypatch.delenv("TPU_VISIBLE_CHIPS")

    monkeypatch.setenv("HVD_BIND_TPU_CHIPS", "0")
    envs = capture(2)
    assert all(e.get("TPU_VISIBLE_CHIPS") != "0" or
               e.get("TPU_VISIBLE_CHIPS") != "1" for e in envs)
    assert all("TPU_VISIBLE_CHIPS" not in e for e in envs)


# -- LSF integration (reference: runner/util/lsf.py + js_run.py) ------------

def test_lsf_host_parsing(tmp_path):
    """All three LSF env forms parse to (host, slots); the rankfile's
    first line (the launch node) is skipped unconditionally — reference
    semantics, no slot-count heuristics."""
    from horovod_tpu.runner import lsf

    # rankfile: launch node first, then one host per task slot
    rf = tmp_path / "rankfile"
    rf.write_text("mgmt01\nnode1\nnode1\nnode2\nnode2\n")
    env = {"LSB_JOBID": "7", "LSB_DJOB_RANKFILE": str(rf)}
    assert lsf.in_lsf(env)
    hs = lsf.host_slots(env)
    assert [(h.hostname, h.slots) for h in hs] == [("node1", 2),
                                                   ("node2", 2)]

    # launch node ALSO hosting tasks: its batch line is skipped, its
    # task lines are kept
    rf.write_text("node1\nnode1\nnode1\nnode2\nnode2\n")
    hs = lsf.host_slots(env)
    assert [(h.hostname, h.slots) for h in hs] == [("node1", 2),
                                                   ("node2", 2)]

    # MCPU pairs are execution hosts — used as-is (span[ptile=1] shape:
    # one slot per host must not lose its first host)
    env = {"LSB_JOBID": "7", "LSB_MCPU_HOSTS": "node1 1 node2 1"}
    hs = lsf.host_slots(env)
    assert [(h.hostname, h.slots) for h in hs] == [("node1", 1),
                                                   ("node2", 1)]

    # LSB_HOSTS per-slot list — used as-is
    env = {"LSB_JOBID": "7", "LSB_HOSTS": "node1 node1 node2 node2"}
    hs = lsf.host_slots(env)
    assert [(h.hostname, h.slots) for h in hs] == [("node1", 2),
                                                   ("node2", 2)]

    assert not lsf.in_lsf({})


def test_lsf_autodetect_runs_job(tmp_path, monkeypatch):
    """Inside a (faked) LSF allocation whose compute slots are localhost,
    `tpurun` with NO -H/-np runs the job end-to-end from the scheduler
    env alone."""
    import sys

    import horovod_tpu.runner.launch as launch_mod

    _worker_pythonpath(monkeypatch)
    rf = tmp_path / "rankfile"
    rf.write_text("mgmt01\nlocalhost\nlocalhost\n")
    monkeypatch.setenv("LSB_JOBID", "42")
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rf))
    out = tmp_path / "ranks.txt"
    script = tmp_path / "job.py"
    script.write_text(
        "import os\n"
        "import horovod_tpu as hvd\n"
        "import numpy as np\n"
        "hvd.init()\n"
        "s = float(hvd.allreduce(np.ones(2, np.float32),"
        " op=hvd.Sum)[0])\n"
        f"open({str(out)!r}, 'a').write("
        "f'{hvd.rank()}/{hvd.size()}:{s}\\n')\n"
        "hvd.shutdown()\n")
    rc = launch_mod.run_commandline(
        ["--verbose", "--no-stall-check", sys.executable, str(script)])
    assert rc == 0
    lines = sorted(out.read_text().split())
    assert lines == ["0/2:2.0", "1/2:2.0"], lines


def test_lsf_blaunch_remote_command(monkeypatch, tmp_path):
    """Remote slots under LSF spawn via blaunch (LSF's in-allocation
    remote shell), not ssh; auto-selected, overridable."""
    import horovod_tpu.runner.launch as launch_mod

    s = hosts.SlotInfo("node7", 1, 2, 0, 1, 1, 2)
    cmd = get_remote_command(s, ["python", "train.py"],
                             {"HVD_RANK": "1"}, remote_shell="blaunch")
    assert cmd.startswith("blaunch node7 ")
    assert "HVD_RANK=1" in cmd and "python train.py" in cmd

    rf = tmp_path / "rankfile"
    rf.write_text("mgmt01\nnodeA\nnodeB\n")
    monkeypatch.setenv("LSB_JOBID", "42")
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rf))

    spawned = []

    class _P:
        stdin = None

        def poll(self):
            return 0

    def fake_safe_exec(command, env=None, **kw):
        p = _P()

        class _Stdin:
            def write(self, b):
                pass

            def flush(self):
                pass

            def close(self):
                pass

        p.stdin = _Stdin()
        spawned.append((command, env or {}))
        return p

    monkeypatch.setattr(launch_mod, "safe_exec", fake_safe_exec)
    monkeypatch.setattr(launch_mod, "terminate", lambda p: None)
    monkeypatch.setattr(launch_mod.util, "send_stdin_line",
                        lambda p, b: None)
    rc = launch_mod.run_commandline(["python", "train.py"])
    assert rc == 0
    shells = [c[2] for c, _ in spawned]
    assert len(shells) == 2
    assert all(sh.startswith("blaunch node") for sh in shells), shells
    for sh, env in zip(shells, (e for _, e in spawned)):
        # no stdin protocol under blaunch, and the secret stays off argv:
        # it rides the propagated caller environment instead
        assert "read -r" not in sh, sh
        assert "HVD_RENDEZVOUS_SECRET" not in sh, sh
        assert env.get("HVD_RENDEZVOUS_SECRET"), "secret must ride env"


def test_check_build(capsys):
    """tpurun --check-build (reference: horovodrun --check-build) reports
    frameworks and native layers without needing a training command."""
    import horovod_tpu.runner.launch as launch_mod

    rc = launch_mod.run_commandline(["--check-build"])
    assert rc == 0
    out = capsys.readouterr().out
    # report SHAPE, not the host's package inventory: every row present
    for row in ("JAX", "TensorFlow", "PyTorch", "MXNet",
                "core runtime (libhvd_tpu.so)", "TF custom ops",
                "TF in-XLA-graph ops", "torch extension"):
        assert row in out, (row, out)
    assert out.count("[") >= 10
