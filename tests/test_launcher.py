"""Launcher unit tests (reference: test/single/test_run.py — arg parsing,
hostfile parsing, command assembly with NOTHING actually executed, plus KV
store round trips on localhost)."""

import json
import os
import textwrap

import pytest

from horovod_tpu.runner import config_parser, hosts, http_server, util
from horovod_tpu.runner.launch import get_remote_command, parse_args


# -- hosts ------------------------------------------------------------------

def test_parse_hosts():
    hs = hosts.parse_hosts("a:4,b:2,c")
    assert hs == [hosts.HostInfo("a", 4), hosts.HostInfo("b", 2),
                  hosts.HostInfo("c", 1)]
    with pytest.raises(ValueError):
        hosts.parse_hosts("")


def test_parse_hostfile(tmp_path):
    p = tmp_path / "hf"
    p.write_text(textwrap.dedent("""\
        # cluster
        node1 slots=4
        node2:2
        node3
    """))
    hs = hosts.parse_hostfile(str(p))
    assert hs == [hosts.HostInfo("node1", 4), hosts.HostInfo("node2", 2),
                  hosts.HostInfo("node3", 1)]


def test_host_assignments():
    hs = [hosts.HostInfo("a", 2), hosts.HostInfo("b", 2)]
    slots = hosts.get_host_assignments(hs, 3)
    assert [(s.hostname, s.rank, s.local_rank, s.local_size,
             s.cross_rank) for s in slots] == [
        ("a", 0, 0, 2, 0), ("a", 1, 1, 2, 0), ("b", 2, 0, 1, 1)]
    assert all(s.size == 3 for s in slots)
    with pytest.raises(ValueError):
        hosts.get_host_assignments(hs, 5)


# -- args / config ----------------------------------------------------------

def test_parse_args_basic():
    a = parse_args(["-np", "4", "--fusion-threshold-mb", "32",
                    "--timeline-filename", "/tmp/t.json",
                    "python", "train.py", "--lr", "0.1"])
    assert a.np == 4
    assert a.command == ["python", "train.py", "--lr", "0.1"]
    env = config_parser.args_to_env(a)
    assert env["HVD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
    assert env["HVD_TIMELINE"] == "/tmp/t.json"


def test_parse_args_no_stall_check():
    a = parse_args(["-np", "2", "--no-stall-check", "x"])
    env = config_parser.args_to_env(a)
    assert env["HVD_STALL_CHECK_TIME_SECONDS"] == "0"


def test_config_file(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(textwrap.dedent("""\
        params:
          fusion-threshold-mb: 16
          cycle-time-ms: 2.5
        timeline:
          filename: /tmp/tl.json
          mark-cycles: true
        autotune:
          enable: true
    """))
    a = parse_args(["-np", "2", "--config-file", str(cfg), "x"])
    env = config_parser.args_to_env(a)
    assert env["HVD_FUSION_THRESHOLD"] == str(16 * 1024 * 1024)
    assert env["HVD_CYCLE_TIME_MS"] == "2.5"
    assert env["HVD_TIMELINE"] == "/tmp/tl.json"
    assert env["HVD_TIMELINE_MARK_CYCLES"] == "1"
    assert env["HVD_AUTOTUNE"] == "1"


def test_cli_overrides_config_file(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("params:\n  fusion-threshold-mb: 16\n")
    a = parse_args(["-np", "2", "--fusion-threshold-mb", "8",
                    "--config-file", str(cfg), "x"])
    env = config_parser.args_to_env(a)
    assert env["HVD_FUSION_THRESHOLD"] == str(8 * 1024 * 1024)


# -- remote command assembly (nothing executed; reference mocks ssh) --------

def test_get_remote_command():
    s = hosts.SlotInfo("node7", 3, 8, 1, 2, 1, 2)
    cmd = get_remote_command(s, ["python", "train.py"],
                             {"HVD_RANK": "3", "HVD_SIZE": "8"},
                             ssh_port=2222)
    assert cmd.startswith("ssh ")
    assert "node7" in cmd and "-p 2222" in cmd
    assert "HVD_RANK=3" in cmd and "HVD_SIZE=8" in cmd
    assert "python train.py" in cmd


# -- HTTP KV rendezvous -----------------------------------------------------

def test_kv_store_roundtrip():
    key = util.make_secret_key()
    srv = http_server.RendezvousServer(secret_key=key)
    port = srv.start()
    addr = f"127.0.0.1:{port}"
    try:
        http_server.put_kv(addr, "scope", "k1", b"hello", secret_key=key)
        assert http_server.read_kv(addr, "scope", "k1",
                                   secret_key=key) == b"hello"
        # missing key → 404
        import urllib.error
        with pytest.raises(urllib.error.HTTPError):
            http_server.read_kv(addr, "scope", "nope", secret_key=key)
        # bad signature → 403
        with pytest.raises(urllib.error.HTTPError):
            http_server.read_kv(addr, "scope", "k1",
                                secret_key=b"wrong-key-000")
    finally:
        srv.stop()


def test_kv_store_wait_rendezvous():
    import threading
    import time

    srv = http_server.RendezvousServer()
    port = srv.start()
    addr = f"127.0.0.1:{port}"
    try:
        def put_later():
            time.sleep(0.3)
            http_server.put_kv(addr, "rdv", "epoch", b"7")

        t = threading.Thread(target=put_later)
        t.start()
        v = http_server.read_kv(addr, "rdv", "epoch", wait=True, timeout=5)
        assert v == b"7"
        t.join()
    finally:
        srv.stop()


# -- end-to-end localhost launch -------------------------------------------

def test_tpurun_localhost(tmp_path):
    """Full CLI path: tpurun -np 2 on localhost, real collective."""
    from horovod_tpu.runner.launch import run_commandline

    script = tmp_path / "w.py"
    script.write_text(textwrap.dedent("""\
        import numpy as np
        import horovod_tpu as hvd
        hvd.init()
        out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum)
        assert (out == hvd.size()).all()
        hvd.shutdown()
    """))
    rc = run_commandline(["-np", "2", "--no-stall-check",
                          "python", str(script)])
    assert rc == 0


def test_tpurun_failure_propagates(tmp_path):
    from horovod_tpu.runner.launch import run_commandline

    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)\n")
    rc = run_commandline(["-np", "2", "python", str(script)])
    assert rc != 0
