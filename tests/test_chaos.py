"""Chaos-injection harness for the elastic plane (docs/elastic.md).

Real local elastic jobs where a victim rank injects SIGKILL (clean
death), SIGSTOP (wedge — alive, sockets open, making no progress), or a
core-level network partition (HVD_FAULT_INJECT blackhole) mid-training.
The job must detect the fault within the configured heartbeat budget,
evict the rank by name, repair the epoch (respawn or hot-spare
promotion), and pass a post-recovery allreduce parity check — all inside
a bounded wall clock (the subprocess timeout IS the no-hang assertion).
"""

import os
import subprocess
import sys
import time

import pytest

from .util import tpu_isolated_env

WORKER = os.path.join(os.path.dirname(__file__), "workers",
                      "chaos_worker.py")

def _chaos_env(np_):
    """Heartbeat budget: 1.5 s deadline x 3 misses names a wedge within
    ~5 s at 4 ranks. Larger rank counts on an oversubscribed CPU test
    host get a wider budget — a rank descheduled for seconds by load is
    SLOW, not wedged, and must not be evicted (the distinction the
    escalation ladder exists for)."""
    if np_ >= 8:
        return {"HVD_PEER_TIMEOUT_MS": "3000", "HVD_PEER_EVICT_MISSES": "5"}
    return {"HVD_PEER_TIMEOUT_MS": "1500"}


def _run_chaos(tmp_path, np_, fault, extra_env=None, hot_spares=0,
               timeout=120, iters=8, worker=WORKER):
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text(f"localhost:{np_ + hot_spares}\n")
    log_file = tmp_path / "final.log"
    marker = tmp_path / "fault.marker"
    env = dict(os.environ)
    env.update(tpu_isolated_env())
    env.update(_chaos_env(np_))
    env["TEST_LOG"] = str(log_file)
    env["TEST_MARKER"] = str(marker)
    env["TEST_CHAOS_FAULT"] = fault
    env["TEST_ITERS"] = str(iters)
    env["TEST_SLEEP"] = "0.15"
    if fault == "partition":
        env["HVD_FAULT_INJECT"] = "1"
    env.update(extra_env or {})

    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
           "--min-np", "2", "--max-np", str(np_),
           "--host-discovery-script", f"cat {hosts_file}",
           # Short cooldowns: a loaded test host can fail several spawns
           # in a burst; the job must retry, not exhaust its only host.
           "--blacklist-cooldown-range", "2", "5",
           "--verbose"]
    if hot_spares:
        cmd += ["--hot-spares", str(hot_spares)]
    cmd += [sys.executable, worker]
    t0 = time.monotonic()
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        raise AssertionError(
            f"chaos job ({fault}, np={np_}) hung past {timeout}s "
            f"(detection/eviction never completed):\n{out}")
    elapsed = time.monotonic() - t0
    log = log_file.read_text() if log_file.exists() else ""
    return proc.returncode, log, out, marker, elapsed


def _assert_recovered(rc, log, out, marker, np_, iters=8):
    assert rc == 0, f"job failed rc={rc}\n{out}"
    assert marker.exists(), f"fault was never injected\n{out}"
    finals = [line for line in log.splitlines() if line.startswith("final")]
    # np_ finishers: the survivors plus the replacement/promoted spare
    # that took the evicted rank (the victim itself never logs).
    assert len(finals) == np_, \
        f"expected {np_} finishers, got {len(finals)}:\n{log}\n{out}"
    assert all(f"iter={iters}" in line for line in finals), log
    assert all("parity=ok" in line for line in finals), \
        f"post-recovery parity failed:\n{log}\n{out}"


def test_chaos_kill_smoke(tmp_path):
    """Tier-1 smoke: clean SIGKILL at 4 ranks — detect on the dead
    control socket, evict by name, respawn, finish with parity."""
    rc, log, out, marker, _ = _run_chaos(tmp_path, 4, "kill")
    _assert_recovered(rc, log, out, marker, 4)
    assert "RankEvictedError" in out or "FAILED" in out, out


def test_chaos_kill_writer_mid_save(tmp_path):
    """ISSUE 15 crash-window cell: SIGKILL the checkpoint WRITER (rank 0,
    the set root) after its shards are durable but BEFORE the commit —
    the window that used to wedge the other ranks in the
    ``ckpt.shards.<step>`` barrier forever. Survivors must surface RankEvictedError out of the commit
    barrier (the PR 8 liveness/eviction path), re-rendezvous, and every
    finisher must restore the last COMMITTED step (1) — the torn step-2
    staging dir can never be resolvable as latest."""
    ckdir = tmp_path / "ck"
    rc, log, out, marker, _ = _run_chaos(
        tmp_path, 4, "ckpt-writer", timeout=150, iters=6,
        extra_env={"CKPT_DIR": str(ckdir)},
        worker=os.path.join(os.path.dirname(__file__), "workers",
                            "ckpt_chaos_worker.py"))
    assert rc == 0, f"job failed rc={rc}\n{out}"
    assert marker.exists(), f"writer crash was never injected\n{out}"
    finals = [l for l in log.splitlines() if l.startswith("final")]
    assert len(finals) == 4, f"expected 4 finishers:\n{log}\n{out}"
    assert all("iter=6" in l and "parity=ok" in l for l in finals), log
    # Every finisher resolved the previous committed step on recovery.
    assert all("ckpt=1" in l for l in finals), log
    # A SIGKILLed writer surfaces on the dead control socket (the driver
    # names the rc=-9 failure) or, if the socket lingers, the liveness
    # timeout — either way the survivors must NOT hang in the barrier.
    assert ("RankEvictedError" in out or "evicting" in out
            or "liveness stale" in out or "FAILED" in out), \
        f"writer death never detected:\n{out}"
    # The aborted attempt's staging leftovers never count as a step.
    import horovod_tpu.checkpoint as _ck
    assert _ck.latest_step(ckdir) == 2  # the RETRIED step-2 commit


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("fault", ["kill", "stop", "partition"])
@pytest.mark.parametrize("np_", [4, 8])
def test_chaos_matrix(tmp_path, fault, np_):
    """The full fault matrix at 4 and 8 ranks: every fault type must be
    detected and repaired inside the harness timeout, and the repaired
    mesh must pass the parity check."""
    rc, log, out, marker, elapsed = _run_chaos(
        tmp_path, np_, fault, timeout=150)
    _assert_recovered(rc, log, out, marker, np_)
    # Wedge/partition recovery must come from the eviction machinery,
    # not a generic crash: the driver names the eviction.
    if fault in ("stop", "partition"):
        assert ("evicting" in out or "liveness stale" in out
                or "RankEvictedError" in out), \
            f"no eviction recorded for {fault}:\n{out}"


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_spare_promotion(tmp_path):
    """Hot-spare path: with --hot-spares 1 the evicted rank is repaired
    by promoting the parked spare (driver logs the promotion) and the
    job still finishes with parity."""
    rc, log, out, marker, _ = _run_chaos(
        tmp_path, 4, "kill", hot_spares=1, timeout=150)
    _assert_recovered(rc, log, out, marker, 4)
    assert "promoted" in out, f"no spare promotion in driver log:\n{out}"


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_wedge_with_spare(tmp_path):
    """The headline churn scenario: a SIGSTOP wedge repaired by spare
    promotion — detection via heartbeats, SIGKILL of the stopped
    process, promotion of the parked worker."""
    rc, log, out, marker, _ = _run_chaos(
        tmp_path, 4, "stop", hot_spares=1, timeout=150)
    _assert_recovered(rc, log, out, marker, 4)
    assert "promoted" in out, f"no spare promotion in driver log:\n{out}"
