"""Elastic integration tests (reference: test/integration/test_elastic_*.py
+ elastic_common.py BaseElasticTests): a REAL local elastic job on
localhost — fake discovery is a script cat-ing a hosts file the test
mutates mid-run; failure injection is a worker calling os._exit(1)."""

import os
import subprocess
import sys
import threading
import time

import pytest

from .util import tpu_isolated_env
from .util import have_shard_map

WORKER = os.path.join(os.path.dirname(__file__), "workers",
                      "elastic_train_worker.py")
MESH_WORKER = os.path.join(os.path.dirname(__file__), "workers",
                           "elastic_mesh_worker.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_elastic(tmp_path, hosts_initial, extra_env, min_np, max_np,
                 mutate=None, timeout=120, worker=WORKER):
    """Run tpurun elastic in-process-launched subprocess; returns (rc, log)."""
    hosts_file = tmp_path / "hosts.txt"
    hosts_file.write_text(hosts_initial + "\n")
    log_file = tmp_path / "final.log"
    env = dict(os.environ)
    # Repo-only PYTHONPATH + CPU jax: the single off-the-real-TPU policy
    # (tests/util.tpu_isolated_env) for every spawned test process.
    env.update(tpu_isolated_env())
    env["TEST_LOG"] = str(log_file)
    env.update(extra_env)

    cmd = [sys.executable, "-m", "horovod_tpu.runner.launch",
           "--min-np", str(min_np), "--max-np", str(max_np),
           "--host-discovery-script", f"cat {hosts_file}",
           "--verbose",
           sys.executable, worker]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    if mutate:
        t = threading.Thread(target=mutate, args=(hosts_file,), daemon=True)
        t.start()
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        raise AssertionError(f"elastic job timed out; output:\n{out}")
    log = log_file.read_text() if log_file.exists() else ""
    return proc.returncode, log, out


def test_elastic_scale_up(tmp_path):
    """Start with 2 slots, discovery adds a third mid-run; all workers
    (including the late joiner) finish at the full iteration count."""
    def mutate(hosts_file):
        time.sleep(2.0)
        hosts_file.write_text("localhost:3\n")

    rc, log, out = _run_elastic(
        tmp_path, "localhost:2",
        {"TEST_ITERS": "14", "TEST_SLEEP": "0.25"},
        min_np=2, max_np=4, mutate=mutate)
    assert rc == 0, f"job failed rc={rc}\n{out}"
    finals = [line for line in log.splitlines() if line.startswith("final")]
    assert len(finals) == 3, f"expected 3 finishers:\n{log}\n{out}"
    assert any("size=3" in line for line in finals), \
        f"no worker saw size=3 (scale-up never landed):\n{log}\n{out}"
    assert all("iter=14" in line for line in finals), log


def test_elastic_failure_recovery(tmp_path):
    """A worker dies mid-job; survivors restore from the last commit, the
    driver respawns a replacement, and the job completes."""
    marker = tmp_path / "died.marker"
    rc, log, out = _run_elastic(
        tmp_path, "localhost:2",
        {"TEST_ITERS": "10", "TEST_SLEEP": "0.1",
         "TEST_FAIL_SLOT": "1", "TEST_MARKER": str(marker)},
        min_np=2, max_np=2)
    assert rc == 0, f"job failed rc={rc}\n{out}"
    assert marker.exists(), "failure was never injected"
    finals = [line for line in log.splitlines() if line.startswith("final")]
    assert len(finals) == 2, f"expected 2 finishers:\n{log}\n{out}"
    assert all("iter=10" in line for line in finals), log


@pytest.mark.skipif(not have_shard_map(), reason="jax.shard_map unavailable (jax < 0.8): mesh workers cannot import horovod_tpu.parallel")
def test_elastic_mesh_scale_up(tmp_path):
    """Elastic × ICI composition (VERDICT r2 #1): each epoch trains in-jit
    over a global jax mesh sized to membership. Scale-up 2→3 procs (2
    virtual devices each): every epoch's in-mesh psum equals the device
    count, and the final epoch spans 6 devices."""
    def mutate(hosts_file):
        time.sleep(2.5)
        hosts_file.write_text("localhost:3\n")

    rc, log, out = _run_elastic(
        tmp_path, "localhost:2",
        {"TEST_ITERS": "12", "TEST_SLEEP": "0.25"},
        min_np=2, max_np=4, mutate=mutate, timeout=180, worker=MESH_WORKER)
    assert rc == 0, f"job failed rc={rc}\n{out}"
    finals = [line for line in log.splitlines() if line.startswith("final")]
    assert len(finals) == 3, f"expected 3 finishers:\n{log}\n{out}"
    assert any("size=3 " in line and "ndev=6" in line for line in finals), \
        f"no worker finished on the 6-device mesh:\n{log}\n{out}"
    assert all("iter=12" in line for line in finals), log


@pytest.mark.skipif(not have_shard_map(), reason="jax.shard_map unavailable (jax < 0.8): mesh workers cannot import horovod_tpu.parallel")
def test_elastic_mesh_failure_recovery(tmp_path):
    """A worker dies mid-job: survivors restore committed HOST state, the
    PJRT backend is rebuilt per epoch, and the respawned membership trains
    on a fresh 4-device mesh to completion."""
    marker = tmp_path / "died.marker"
    rc, log, out = _run_elastic(
        tmp_path, "localhost:2",
        {"TEST_ITERS": "8", "TEST_SLEEP": "0.1",
         "TEST_FAIL_SLOT": "1", "TEST_MARKER": str(marker)},
        min_np=2, max_np=2, timeout=180, worker=MESH_WORKER)
    assert rc == 0, f"job failed rc={rc}\n{out}"
    assert marker.exists(), "failure was never injected"
    finals = [line for line in log.splitlines() if line.startswith("final")]
    assert len(finals) == 2, f"expected 2 finishers:\n{log}\n{out}"
    assert all("iter=8" in line and "ndev=4" in line for line in finals), log


@pytest.mark.skipif(not have_shard_map(), reason="jax.shard_map unavailable (jax < 0.8): mesh workers cannot import horovod_tpu.parallel")
def test_elastic_mesh_scale_down(tmp_path):
    """Scale-down 3→2: the excess worker exits on the KV directive,
    survivors tear the 6-device mesh down and finish on a 4-device mesh
    (maxndev=6 proves they really trained in-mesh at size 3 first). The
    mutation is progress-gated: it fires only after rank 0 reports ≥2
    iterations at size 3, so slow jax startup cannot race the scale-down
    past the size-3 epochs."""
    progress = tmp_path / "progress.log"

    def mutate(hosts_file):
        deadline = time.time() + 90
        while time.time() < deadline:
            if progress.exists():
                lines = progress.read_text().splitlines()
                if any(int(ln.split()[0]) >= 2 and ln.split()[1] == "3"
                       for ln in lines if len(ln.split()) == 2):
                    break
            time.sleep(0.2)
        hosts_file.write_text("localhost:2\n")

    rc, log, out = _run_elastic(
        tmp_path, "localhost:3",
        {"TEST_ITERS": "16", "TEST_SLEEP": "0.4",
         "TEST_PROGRESS": str(progress)},
        min_np=2, max_np=3, mutate=mutate, timeout=180, worker=MESH_WORKER)
    assert rc == 0, f"job failed rc={rc}\n{out}"
    finals = [line for line in log.splitlines() if line.startswith("final")]
    assert len(finals) == 2, f"expected 2 finishers:\n{log}\n{out}"
    assert all("size=2 " in line and "ndev=4" in line for line in finals), \
        f"survivors should finish on the 4-device mesh:\n{log}\n{out}"
    assert any("maxndev=6" in line for line in finals), \
        f"no survivor saw the 6-device mesh before scale-down:\n{log}\n{out}"
    assert all("iter=16" in line for line in finals), log


def test_elastic_internal_error_reset_push(tmp_path):
    """A worker raises HorovodInternalError while every process is ALIVE
    (transient failure): its reset-request PUT makes the driver publish a
    new epoch promptly, so the job recovers in seconds instead of stalling
    toward the 600 s rendezvous timeout (r1 advisor finding: the reference
    pushes via WorkerNotificationService)."""
    marker = tmp_path / "raised.marker"
    rc, log, out = _run_elastic(
        tmp_path, "localhost:2",
        {"TEST_ITERS": "10", "TEST_SLEEP": "0.1",
         "TEST_INTERNAL_SLOT": "1", "TEST_MARKER": str(marker),
         "HVD_SHUTDOWN_TIMEOUT": "2"},
        min_np=2, max_np=2, timeout=90)
    assert rc == 0, f"job failed rc={rc}\n{out}"
    assert marker.exists(), "internal error was never injected"
    assert "reset requested by" in out, out
    finals = [line for line in log.splitlines() if line.startswith("final")]
    assert len(finals) == 2, f"expected 2 finishers:\n{log}\n{out}"
    assert all("iter=10" in line for line in finals), log


def test_elastic_kv_rejects_unsigned_requests():
    """The elastic KV store binds 0.0.0.0 with a per-job HMAC secret:
    unsigned PUTs (e.g. a hostile /ctl/epoch resize) are rejected with 403,
    signed ones accepted (r1 advisor finding)."""
    import urllib.error
    import urllib.request

    from horovod_tpu.runner import http_server
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver

    d = ElasticDriver(["true"], FixedHosts({}), 1, 1)
    try:
        assert d.secret and d.rdv.secret_key == d.secret
        url = f"http://127.0.0.1:{d.rdv_port}/ctl/epoch"
        req = urllib.request.Request(url, data=b"999", method="PUT")
        try:
            urllib.request.urlopen(req, timeout=5)
            raise AssertionError("unsigned PUT was accepted")
        except urllib.error.HTTPError as e:
            assert e.code == 403, e.code
        http_server.put_kv(f"127.0.0.1:{d.rdv_port}", "ctl", "x", b"1",
                           secret_key=d.secret)
        assert d.rdv.get("/ctl/x") == b"1"
    finally:
        d.stop()


def test_blacklist_transient_decay():
    """A blacklist earned entirely by transient evictions (driver kills of
    wedged workers) lifts early once those records age out of
    TRANSIENT_DECAY_S; any hard crash in the mix pins the full cooldown."""
    from horovod_tpu.runner.elastic import driver as drv
    from horovod_tpu.runner.elastic.discovery import FixedHosts

    d = drv.ElasticDriver(["true"], FixedHosts({}), 1, 1,
                          cooldown_range=(30.0, 60.0))
    try:
        t0 = 1000.0
        for i in range(drv.FAILURES_TO_BLACKLIST):
            d._record_failure("hostA", transient=True, now=t0 + i)
        assert d._blacklisted("hostA", t0 + 3)
        # All-transient: lifts as soon as the records decay, well before
        # the 30 s cooldown would expire.
        assert not d._blacklisted("hostA", t0 + drv.TRANSIENT_DECAY_S + 3)

        for i in range(drv.FAILURES_TO_BLACKLIST - 1):
            d._record_failure("hostB", transient=True, now=t0 + i)
        d._record_failure("hostB", transient=False, now=t0 + 2.0)
        assert d._blacklisted("hostB", t0 + 3)
        # The hard crash pins the cooldown past the transient decay point…
        assert d._blacklisted("hostB", t0 + drv.TRANSIENT_DECAY_S + 3)
        # …and only the cooldown itself lifts it.
        assert not d._blacklisted("hostB", t0 + 2.0 + 30.0 + 1)
    finally:
        d.stop()


def test_incremental_epoch_preserves_survivor_ranks():
    """Eviction repair keeps survivor ranks: the newcomer slots into the
    freed rank (incremental epoch) instead of forcing a full re-rank, and
    a size change still falls back to None (full path)."""
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver

    class W:
        def __init__(self, wid, host, slot):
            self.id, self.hostname, self.slot = wid, host, slot

    d = ElasticDriver(["true"], FixedHosts({}), 1, 4)
    try:
        a = W("a", "localhost", 0)
        c = W("c", "localhost", 2)
        s = W("spare", "localhost", 3)
        prev = {"a": 0, "b": 1, "c": 2}
        d._rank_hosts = {0: "localhost", 1: "localhost", 2: "localhost"}
        order = d._incremental_order([a, s, c], prev)
        assert order is not None
        assert [w.id for w in order] == ["a", "spare", "c"]
        # identity membership is also incremental (rank stability)
        b = W("b", "localhost", 1)
        assert [w.id for w in d._incremental_order([c, a, b], prev)] \
            == ["a", "b", "c"]
        # size change -> full re-rank
        assert d._incremental_order([a, c], prev) is None
        # all-fresh membership has nothing to preserve
        assert d._incremental_order(
            [W("x", "localhost", 0), W("y", "localhost", 1),
             W("z", "localhost", 2)], prev) is None
    finally:
        d.stop()


def test_elastic_scale_down(tmp_path):
    """Discovery removes a slot mid-run: the excess worker is told to exit
    via the KV directive, the rest re-rendezvous at size=2 and finish."""
    def mutate(hosts_file):
        time.sleep(2.0)
        hosts_file.write_text("localhost:2\n")

    rc, log, out = _run_elastic(
        tmp_path, "localhost:3",
        {"TEST_ITERS": "14", "TEST_SLEEP": "0.25"},
        min_np=2, max_np=3, mutate=mutate)
    assert rc == 0, f"job failed rc={rc}\n{out}"
    finals = [line for line in log.splitlines() if line.startswith("final")]
    assert len(finals) == 2, f"expected 2 finishers:\n{log}\n{out}"
    assert all("size=2" in line for line in finals), \
        f"survivors should finish at size=2:\n{log}\n{out}"
    assert all("iter=14" in line for line in finals), log


TORCH_WORKER = os.path.join(os.path.dirname(__file__), "workers",
                            "elastic_torch_worker.py")


def test_elastic_torch_failure_recovery(tmp_path):
    """Torch binding end-to-end elastic (reference:
    test/integration/test_elastic_torch.py): a rank dies mid-job;
    TorchState restores model+optimizer from the last commit, the driver
    respawns, and every finisher holds identical weights."""
    marker = tmp_path / "torch-died.marker"
    rc, log, out = _run_elastic(
        tmp_path, "localhost:2",
        {"TEST_ITERS": "8", "TEST_SLEEP": "0.1",
         "TEST_FAIL_SLOT": "1", "TEST_MARKER": str(marker),
         "JAX_PLATFORMS": "cpu"},
        min_np=2, max_np=2, worker=TORCH_WORKER)
    assert rc == 0, f"job failed rc={rc}\n{out}"
    assert marker.exists(), "failure was never injected"
    finals = [line for line in log.splitlines() if line.startswith("final")]
    assert len(finals) == 2, f"expected 2 finishers:\n{log}\n{out}"
    assert all("iter=8" in line for line in finals), log


TF_WORKER = os.path.join(os.path.dirname(__file__), "workers",
                         "elastic_tf_worker.py")


def test_elastic_tf_failure_recovery(tmp_path):
    """TF/Keras binding end-to-end elastic (reference:
    test/integration/test_elastic_tensorflow.py): a rank dies mid-job;
    TensorFlowKerasState restores from the last commit, the driver
    respawns, and every finisher holds identical weights."""
    import pytest

    pytest.importorskip("tensorflow")
    marker = tmp_path / "tf-died.marker"
    rc, log, out = _run_elastic(
        tmp_path, "localhost:2",
        {"TEST_ITERS": "6", "TEST_SLEEP": "0.1",
         "TEST_FAIL_SLOT": "1", "TEST_MARKER": str(marker),
         "JAX_PLATFORMS": "cpu"},
        min_np=2, max_np=2, worker=TF_WORKER, timeout=240)
    assert rc == 0, f"job failed rc={rc}\n{out}"
    assert marker.exists(), "failure was never injected"
    finals = [line for line in log.splitlines() if line.startswith("final")]
    assert len(finals) == 2, f"expected 2 finishers:\n{log}\n{out}"
    assert all("iter=6" in line for line in finals), log


TF_XLA_WORKER = os.path.join(os.path.dirname(__file__), "workers",
                             "elastic_tf_xla_worker.py")


def test_elastic_resize_under_compiled_xla_predivide(tmp_path):
    """ADVICE r4 medium, live: a jit_compile=True step with
    gradient_predivide_factor traced at size 2 must keep producing exact
    averages after the world SHRINKS to 1 (no stale size in the trace —
    the core divides by the negotiated member count at execution time).
    The rank death also drives the typed-FFI error path through
    elastic._is_native_op_failure."""
    import pytest

    pytest.importorskip("tensorflow")
    marker = tmp_path / "xla-died.marker"

    def shrink(hosts_file):
        # Once the injected death happened, take the slot out of
        # discovery so the driver re-meshes at size 1 instead of
        # respawning back to 2. The wait must sit INSIDE the test's own
        # 300 s timeout but comfortably above worker startup: under full
        # machine load the TF import + jit_compile trace can take >90 s
        # to reach the injection point, and shrinking before the death
        # skips the injection entirely (observed flake, round 5).
        deadline = time.time() + 240
        while time.time() < deadline and not marker.exists():
            time.sleep(0.1)
        hosts_file.write_text("localhost:1\n")

    rc, log, out = _run_elastic(
        tmp_path, "localhost:2",
        {"TEST_ITERS": "6", "TEST_SLEEP": "0.2",
         "TEST_FAIL_SLOT": "1", "TEST_MARKER": str(marker),
         "HVD_ENABLE_XLA_OPS": "1", "JAX_PLATFORMS": "cpu"},
        min_np=1, max_np=2, worker=TF_XLA_WORKER, timeout=300,
        mutate=shrink)
    assert rc == 0, f"job failed rc={rc}\n{out}"
    assert marker.exists(), "failure was never injected"
    finals = [line for line in log.splitlines() if line.startswith("final")]
    assert len(finals) >= 1, f"no finisher:\n{log}\n{out}"
    sizes = finals[0].split("sizes=")[1].split(",")
    # The same compiled function ran (asserted in-worker) at BOTH sizes.
    assert "2" in sizes and "1" in sizes, finals[0]
