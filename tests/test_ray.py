"""RayExecutor tests (reference: test/single/test_ray.py — but ray is not
installed in this environment, so these exercise the local backend, which
is the same start/run/shutdown surface over tpurun-style local processes)."""

import numpy as np
import pytest

from horovod_tpu.ray import RayExecutor


def test_executor_runs_collectives_and_collects_results():
    ex = RayExecutor(num_workers=3, env={"JAX_PLATFORMS": "cpu"})
    ex.start()

    def train(scale):
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        out = hvd.allreduce(np.ones(4, np.float32) * scale, op=hvd.Sum)
        r = hvd.rank()
        hvd.shutdown()
        return r, float(out[0])

    results = ex.run(train, args=(2.0,))
    ex.shutdown()
    assert [r for r, _ in results] == [0, 1, 2]
    assert all(v == 6.0 for _, v in results)


def test_executor_failure_surfaces_and_kills_job():
    ex = RayExecutor(num_workers=2, timeout=120,
                     env={"JAX_PLATFORMS": "cpu"})
    ex.start()

    def bad():
        import horovod_tpu as hvd

        hvd.init()
        if hvd.rank() == 1:
            raise RuntimeError("boom on rank 1")
        # rank 0 would block forever on a collective without the kill
        hvd.allreduce(np.ones(2, np.float32), name="never.completes")

    with pytest.raises(RuntimeError, match="rank 1 failed"):
        ex.run(bad)
    ex.shutdown()


def test_executor_requires_start():
    ex = RayExecutor(num_workers=1)
    with pytest.raises(RuntimeError, match="start"):
        ex.run(lambda: None)


def test_ray_backend_unavailable_raises():
    with pytest.raises(RuntimeError, match="ray"):
        RayExecutor(num_workers=1, backend="ray")
