"""In-core lockdep (csrc/debug_lock.h): runtime lock-order and
blocking-syscall checking over the core's instrumented mutexes
(handle_table, error_state, join_state, tensor_queue, process_sets,
timeline, timeline_ctl, op_uses), gated by HVD_LOCKDEP=1 / the `make
debug` tier. docs/static_analysis.md documents the workflow.

Two live checks (lockdep_worker.py, per rank): the REAL lock graph of a
2-rank collective job stays clean, and a seeded AB-BA inversion IS
detected via hvd.lockdep_stats()/lockdep_report() — the negative test
the tentpole requires. Plus an in-process check that the release core
keeps the checker off (and free) by default.
"""
import os

import pytest

from .util import assert_sanitizer_clean, run_under_sanitizer

pytestmark = pytest.mark.sanitizer


def test_lockdep_off_by_default():
    """The release core must not pay for (or report) lockdep unless asked:
    stats work uninitialized, report enabled=False and no recorded state."""
    if os.environ.get("HVD_LOCKDEP") == "1" or "debug" in \
            os.environ.get("HVD_LIB", ""):
        pytest.skip("ambient env forces lockdep on")
    import horovod_tpu as hvd

    enabled, cycles, blocking, edges, acq = hvd.lockdep_stats()
    assert not enabled
    assert (cycles, blocking, edges, acq) == (0, 0, 0, 0)
    # With the checker off, seeding the inversion records nothing.
    assert hvd.lockdep_selftest() == 0
    assert hvd.lockdep_report() == ""


def test_lockdep_clean_graph_and_seeded_inversion(tmp_path):
    """2-rank job on the debug tier: every rank asserts its real lock
    graph is clean (edges observed, zero cycles, zero blocking-syscall
    holds), then seeds the AB-BA inversion and asserts detection."""
    p, _ = run_under_sanitizer(
        tmp_path, "lockdep_worker.py", 2, tier="debug",
        extra_env={"HVD_LOCKDEP": "1"})
    assert_sanitizer_clean(p, 2, [], tier="lockdep")
    # The seeded inversion must have been reported on stderr by the
    # checker itself (debug_lock.h prints as it records).
    assert "lock-order inversion" in p.stderr, p.stderr[-2000:]


def test_lockdep_shm_pool_mutexes_edge_clean(tmp_path):
    """Debug tier over the hierarchical shm path: the reduce pool's
    "reduce_pool" DebugMutex and the shm plane's attach/exchange
    blocking-syscall annotations must add only clean edges — every rank
    asserts zero cycles and zero locks held across blocking syscalls
    after the full parity sweep (HVD_LOCKDEP grade inside the worker)."""
    p, _ = run_under_sanitizer(
        tmp_path, "hier_shm_worker.py", 2, tier="debug",
        extra_env={"HVD_LOCKDEP": "1",
                   "HVD_HIERARCHICAL_ALLREDUCE": "1",
                   "HVD_REDUCE_THREADS": "2",
                   "EXPECT_SHM": "1"})
    assert_sanitizer_clean(p, 2, [], tier="lockdep")
