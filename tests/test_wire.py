"""Syscall-minimal wire plane (ISSUE 12): csrc/wire.{h,cc} and the
collectives.cc UringDuplex / WireSend tiers — forced-tier numeric parity
across rank counts, cross-tier bit-identity of the same job on every
tier, the measured syscalls/op reduction of the batched tier, the probe
fallback ladder, NUMA lane pinning, the kill switch counter-proven
inert, and TSAN/lockdep over the chained-wave engine.

Every job here sets HVD_SHM=0: the intra-host shm plane would otherwise
swallow all same-host peer traffic and the TCP wire under test would
never carry a byte.
"""

import json
import os

import pytest

from .util import assert_sanitizer_clean, run_under_sanitizer, \
    run_worker_job

# 4 Mi floats = 16 MiB tensors: chunks stay >= 2 MiB up to 8 ranks, so
# the streamed (block-pipelined) path — and with it the uring chained
# wave — is exercised, not just the serial small-chunk fallback.
_STREAMED_N = "4194304"


def _wire_env(tier, n=_STREAMED_N, **extra):
    env = {
        "HVD_SHM": "0",
        "HVD_WIRE": tier,
        "WIRE_MODE": "parity",
        "WIRE_EXPECT": tier,
        "WIRE_N": n,
        "HVD_DATA_TIMEOUT_SECONDS": "60",
    }
    env.update(extra)
    return env


# --- forced-tier parity: ranks x tier --------------------------------------
# The worker asserts probe == mesh agreement == live tier, numeric parity
# against an exact local reference, cross-rank digest bit-identity, and
# the tier's counter anatomy (submits/sqes/cqes on uring, error-queue
# reaps on zerocopy, everything zero on basic).

@pytest.mark.parametrize(
    "np_", [2, 4, pytest.param(8, marks=pytest.mark.slow)])
def test_parity_uring(np_):
    run_worker_job(np_, "wire_worker.py", timeout=240,
                   extra_env=_wire_env("uring"))


@pytest.mark.parametrize(
    "np_", [2, 4, pytest.param(8, marks=pytest.mark.slow)])
def test_parity_zerocopy(np_):
    """Low threshold so even the 64-element fused op's send carries
    MSG_ZEROCOPY and the error-queue reap path runs."""
    run_worker_job(np_, "wire_worker.py", timeout=240,
                   extra_env=_wire_env("zerocopy",
                                       HVD_WIRE_ZC_THRESHOLD="4096"))


@pytest.mark.parametrize(
    "np_", [2, 4, pytest.param(8, marks=pytest.mark.slow)])
def test_parity_basic(np_):
    """The kill switch: HVD_WIRE=basic must leave every uring_*/zc_*
    counter at zero (asserted in the worker) while syscalls keep counting
    — the legacy baseline is still the legacy baseline."""
    run_worker_job(np_, "wire_worker.py", timeout=240,
                   extra_env=_wire_env("basic"))


# --- cross-tier bit-identity + the syscall reduction -----------------------
# The same seeded job forced onto each tier: the wire moves bytes, it
# never rounds, so the rank-0 output digests must match bit-for-bit —
# and the batched tier must do it in measurably fewer syscalls.

def _run_tier(tmp_path, np_, tier, n, **extra):
    out = str(tmp_path / ("wire_%s.json" % tier))
    run_worker_job(np_, "wire_worker.py", timeout=360,
                   extra_env=_wire_env(tier, n=n, WIRE_STATS_OUT=out,
                                       **extra))
    with open(out) as f:
        return json.load(f)


def test_cross_tier_bit_identity_and_reduction(tmp_path):
    stats = {t: _run_tier(tmp_path, 4, t, _STREAMED_N)
             for t in ("basic", "zerocopy", "uring")}
    assert len({s["digest"] for s in stats.values()}) == 1, stats
    # Same collective schedule on every tier.
    assert len({s["ops"] for s in stats.values()}) == 1, stats
    basic = stats["basic"]["syscalls"] / stats["basic"]["ops"]
    uring = stats["uring"]["syscalls"] / stats["uring"]["ops"]
    # Conservative floor at 4 ranks / 16 MiB; the hostplane bench proves
    # the >= 5x acceptance number at 8 ranks / 64 MiB.
    assert basic / uring >= 2.5, stats


@pytest.mark.slow
def test_syscall_reduction_8rank(tmp_path):
    """The acceptance measurement itself: >= 5x fewer syscalls/op on the
    batched tier at 8 ranks, same digest."""
    basic = _run_tier(tmp_path, 8, "basic", "16777216")
    uring = _run_tier(tmp_path, 8, "uring", "16777216")
    assert basic["digest"] == uring["digest"]
    assert basic["ops"] == uring["ops"]
    ratio = (basic["syscalls"] / basic["ops"]) / \
        (uring["syscalls"] / uring["ops"])
    assert ratio >= 5.0, (basic, uring)


# --- probe fallback ladder -------------------------------------------------
# HVD_WIRE_PROBE_FAIL is a bitmask of rungs that pretend to fail
# (1 << tier): the probe must degrade coherently, count each refused
# rung, and the mesh must agree on the surviving tier.

def test_fallback_uring_denied():
    run_worker_job(2, "wire_worker.py", timeout=240, extra_env={
        "HVD_SHM": "0",
        "HVD_WIRE": "auto",
        "HVD_WIRE_PROBE_FAIL": "4",  # 1 << kUring
        "WIRE_MODE": "fallback",
        "WIRE_EXPECT": "zerocopy",
        "WIRE_N": _STREAMED_N,
        "HVD_DATA_TIMEOUT_SECONDS": "60",
    })


def test_fallback_all_denied():
    run_worker_job(2, "wire_worker.py", timeout=240, extra_env={
        "HVD_SHM": "0",
        "HVD_WIRE": "auto",
        "HVD_WIRE_PROBE_FAIL": "6",  # uring AND zerocopy rungs
        "WIRE_MODE": "fallback",
        "WIRE_EXPECT": "basic",
        "WIRE_N": _STREAMED_N,
        "HVD_DATA_TIMEOUT_SECONDS": "60",
    })


# --- NUMA lane pinning -----------------------------------------------------

def test_numa_pinned_lanes():
    """HVD_NUMA=1 forces pinning even on a single-node box; the pool
    needs >= 2 threads for a worker lane to exist at all (1 = inline)."""
    run_worker_job(2, "wire_worker.py", timeout=240, extra_env={
        "HVD_SHM": "0",
        "HVD_NUMA": "1",
        "HVD_REDUCE_THREADS": "2",
        "WIRE_MODE": "numa",
        "WIRE_N": _STREAMED_N,
        "HVD_DATA_TIMEOUT_SECONDS": "60",
    })


# --- the eighth autotune arm -----------------------------------------------

_AUTOTUNE_ENV = {
    "HVD_AUTOTUNE": "1",
    "HVD_AUTOTUNE_CYCLES_PER_SAMPLE": "4",
    "HVD_AUTOTUNE_MAX_SAMPLES": "10",
    # Pin the other seven dimensions so only (cache, wire) sweep.
    "HVD_ZEROCOPY": "0",
    "HVD_RING_PIPELINE": "1",
    "HVD_SHM": "0",
    "HVD_BUCKET": "0",
}


def test_autotune_wire_arm(tmp_path):
    """The wire tier as the eighth categorical arm: when the probe
    succeeds, the (cache, wire) lattice's probe rows flip the wire dim
    and the wire CSV column really takes both states."""
    log = tmp_path / "autotune_wire.csv"
    run_worker_job(2, "autotune_worker.py", timeout=240,
                   extra_env=dict(_AUTOTUNE_ENV, HVD_AUTOTUNE_LOG=str(log),
                                  EXPECT_DIMS="3"))
    # d+1 = 4 probe rows: baseline, cache flipped, wire flipped, alltoall
    # flipped (the ninth dim rides along once the uring tier is up).
    rows = [l for l in log.read_text().splitlines()[1:5]
            if not l.startswith("#")]
    assert {l.split(",")[10] for l in rows} == {"0", "1"}, rows
    assert {l.split(",")[11] for l in rows} == {"0", "1"}, rows


def test_autotune_wire_arm_absent_when_probe_fails(tmp_path):
    """The acceptance guard: the arm exists ONLY where the probe
    succeeded. With every rung denied the mesh lands on basic, both arm
    settings would measure the identical sendmsg path, and the sweep
    must not waste samples on it — one dim (cache only), wire pinned 0."""
    log = tmp_path / "autotune_wire_denied.csv"
    run_worker_job(2, "autotune_worker.py", timeout=240,
                   extra_env=dict(_AUTOTUNE_ENV, HVD_AUTOTUNE_LOG=str(log),
                                  HVD_WIRE_PROBE_FAIL="6",
                                  EXPECT_DIMS="1"))
    rows = [l for l in log.read_text().splitlines()[1:]
            if not l.startswith("#") and l]
    assert {l.split(",")[10] for l in rows} == {"0"}, rows


# --- sanitizers over the chained-wave engine --------------------------------
# 2 Mi floats keeps chunks streamed (4 MiB at 2 ranks) without pushing
# the instrumented builds past their timeout.

def test_uring_tsan(tmp_path):
    p, reports = run_under_sanitizer(
        tmp_path, "wire_worker.py", 2, tier="tsan",
        extra_env=_wire_env("uring", n="2097152"))
    assert_sanitizer_clean(p, 2, reports, "tsan")


def test_uring_lockdep(tmp_path):
    p, reports = run_under_sanitizer(
        tmp_path, "wire_worker.py", 2, tier="debug",
        extra_env=_wire_env("uring", n="2097152"))
    assert_sanitizer_clean(p, 2, reports, "lockdep")
