"""Serving-plane scheduling invariants (ISSUE 14) — pure-numpy tier-1.

The control half of the serving plane (horovod_tpu/serving/scheduler.py
and autoscale.py) is deliberately jax-free, so the invariants that keep
the paged KV cache sound — page conservation, no double-allocation,
strict-ownership frees, admission/eviction at token boundaries,
batch-fill monotonicity under backlog — are all testable without an
accelerator stack. Modules are loaded standalone (the serving package
lazy-imports, but standalone load keeps parity with how bench.py's
jax-free parent would read them), the test_pipeline_schedules.py idiom.

Engine-side coverage (prefill/decode parity against forward(), the
mixed-length jit'd step, the ServeLoop A/B) lives in
tests/test_serving.py, which needs jax.
"""
import importlib.util
import os

import pytest

from .util import _REPO

pytestmark = pytest.mark.serve


def _load(name):
    path = os.path.join(_REPO, "horovod_tpu", "serving", name + ".py")
    spec = importlib.util.spec_from_file_location(name + "_under_test",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


sched = _load("scheduler")
autoscale = _load("autoscale")


def _mk(n_pages=32, page_size=4, max_batch=4, mode="continuous"):
    alloc = sched.PageAllocator(n_pages, page_size)
    return alloc, sched.ContinuousBatcher(alloc, max_batch, mode)


def _req(rid, prompt_len=4, max_new=8, eos=-1):
    return sched.Request(rid=rid, prompt=list(range(prompt_len)),
                         max_new_tokens=max_new, eos_id=eos)


def _conserved(b):
    """The page-accounting contract: free + owned == usable, and every
    running request's pages are disjoint."""
    owned = [p for r in b.running.values() for p in r.pages]
    assert len(owned) == len(set(owned)), "page owned twice"
    assert 0 not in owned, "trash page 0 handed out"
    assert b.alloc.free_pages() + b.alloc.used_pages() \
        == b.alloc.usable_pages
    assert b.alloc.used_pages() == len(owned)


# ---------------------------------------------------------------------------
# PageAllocator
# ---------------------------------------------------------------------------

def test_allocator_reserves_trash_page():
    a = sched.PageAllocator(8, 4)
    assert a.usable_pages == 7
    got = a.alloc(7)
    assert got is not None and 0 not in got
    assert a.alloc(1) is None  # page 0 is never the fallback


def test_allocator_all_or_nothing():
    a = sched.PageAllocator(5, 4)
    assert a.alloc(5) is None          # only 4 usable
    assert a.free_pages() == 4         # failed alloc took nothing
    assert a.alloc(4) is not None
    assert a.free_pages() == 0


def test_allocator_double_free_raises_before_mutation():
    a = sched.PageAllocator(8, 4)
    pages = a.alloc(3)
    a.free(pages[:1])
    with pytest.raises(sched.PageError):
        a.free(pages)                  # pages[0] no longer owned
    # the failed free must not have returned pages[1:] either
    assert a.used_pages() == 2
    assert a.free_pages() == 5


def test_allocator_foreign_page_raises():
    a = sched.PageAllocator(8, 4)
    a.alloc(2)
    with pytest.raises(sched.PageError):
        a.free([6])                    # never allocated
    with pytest.raises(sched.PageError):
        a.free([0])                    # the trash page


def test_allocator_rejects_degenerate_pools():
    with pytest.raises(ValueError):
        sched.PageAllocator(1, 4)      # only the trash page
    with pytest.raises(ValueError):
        sched.PageAllocator(8, 0)


def test_allocator_occupancy():
    a = sched.PageAllocator(9, 4)
    assert a.occupancy() == 0.0
    a.alloc(4)
    assert a.occupancy() == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def test_serve_knobs_defaults(monkeypatch):
    for k in ("HVD_SERVE_PAGE_SIZE", "HVD_SERVE_KV_PAGES",
              "HVD_SERVE_MAX_BATCH", "HVD_SERVE_MODE"):
        monkeypatch.delenv(k, raising=False)
    k = sched.serve_knobs()
    assert k == {"page_size": sched.DEFAULT_PAGE_SIZE,
                 "kv_pages": sched.DEFAULT_KV_PAGES,
                 "max_batch": sched.DEFAULT_MAX_BATCH,
                 "mode": "continuous"}


def test_serve_knobs_env_overrides(monkeypatch):
    monkeypatch.setenv("HVD_SERVE_PAGE_SIZE", "32")
    monkeypatch.setenv("HVD_SERVE_KV_PAGES", "512")
    monkeypatch.setenv("HVD_SERVE_MAX_BATCH", "not-a-number")
    monkeypatch.setenv("HVD_SERVE_MODE", "static")
    k = sched.serve_knobs()
    assert k["page_size"] == 32 and k["kv_pages"] == 512
    assert k["max_batch"] == sched.DEFAULT_MAX_BATCH  # garbage -> default
    assert k["mode"] == "static"


# ---------------------------------------------------------------------------
# admission / eviction
# ---------------------------------------------------------------------------

def test_admission_fills_free_slots_lowest_first():
    _, b = _mk(max_batch=4)
    for i in range(6):
        b.submit(_req(i))
    got = b.admit()
    assert [r.rid for r in got] == [0, 1, 2, 3]
    assert sorted(b.running) == [0, 1, 2, 3]
    assert b.queue_depth() == 2
    assert b.batch_fill() == 1.0
    _conserved(b)


def test_admission_reserves_first_decode_slot():
    # prompt 4 + 1 upcoming decode position at page_size 4 -> 2 pages.
    _, b = _mk(n_pages=3, page_size=4)  # 2 usable
    b.submit(_req(0, prompt_len=4))
    assert len(b.admit()) == 1
    assert len(b.running[0].pages) == 2
    _conserved(b)


def test_admission_head_of_line_keeps_arrival_order():
    _, b = _mk(n_pages=4, page_size=4)  # 3 usable
    b.submit(_req(0, prompt_len=8))     # needs 3 pages
    b.submit(_req(1, prompt_len=1))     # would fit, but is behind rid 0
    assert len(b.admit()) == 1
    b.submit(_req(2, prompt_len=1))
    assert b.admit() == []              # rid 1 blocked -> rid 2 waits too
    assert [r.rid for r in b.waiting] == [1, 2]


def test_eviction_on_eos_and_max_tokens_frees_pages():
    _, b = _mk()
    b.submit(_req(0, max_new=8, eos=7))
    b.submit(_req(1, max_new=2))
    b.admit()
    done = b.on_tokens({0: 7, 1: 5})    # rid 0 hits EOS immediately
    assert [r.rid for r in done] == [0]
    assert done[0].finish_reason == "eos" and done[0].pages == []
    done = b.on_tokens({1: 5})          # rid 1 reaches max_new=2
    assert [r.rid for r in done] == [1]
    assert done[0].finish_reason == "max_tokens"
    assert b.idle()
    assert b.alloc.used_pages() == 0
    _conserved(b)


def test_eviction_readmits_in_same_boundary():
    _, b = _mk(max_batch=1)
    b.submit(_req(0, max_new=1))
    b.submit(_req(1))
    b.admit()
    assert b.queue_depth() == 1
    done = b.on_tokens({0: 3})
    # rid 0 finished AND rid 1 took its slot within one boundary — the
    # continuous-batching property itself.
    assert [r.rid for r in done] == [0]
    assert b.running[0].rid == 1
    _conserved(b)


def test_static_mode_admits_only_into_empty_batch():
    _, b = _mk(max_batch=2, mode="static")
    for i in range(4):
        b.submit(_req(i, max_new=2 + i))
    b.admit()
    assert sorted(r.rid for r in b.running.values()) == [0, 1]
    done = b.on_tokens({0: 1, 1: 1})
    assert not done
    done = b.on_tokens({0: 1, 1: 1})    # rid 0 done (max_new=2)...
    assert [r.rid for r in done] == [0]
    assert [r.rid for r in b.running.values()] == [1]  # slot idles
    done = b.on_tokens({1: 1})          # rid 1 done -> batch empty
    assert [r.rid for r in done] == [1]
    assert sorted(r.rid for r in b.running.values()) == [2, 3]
    _conserved(b)


def test_batch_fill_monotone_under_backlog():
    """With a standing queue and ample pages, continuous batching keeps
    every slot busy at every boundary — fill never drops below 1.0 until
    the backlog drains (the quantity the bench A/B measures)."""
    _, b = _mk(n_pages=128, page_size=4, max_batch=4)
    for i in range(12):
        b.submit(_req(i, prompt_len=2, max_new=1 + (i % 4)))
    b.admit()
    fills = []
    while not b.idle():
        b.on_tokens({s: 1 for s in list(b.running)})
        if b.queue_depth() > 0 or b.batch_fill() == 1.0:
            fills.append(b.batch_fill())
        _conserved(b)
    assert fills and all(f == 1.0 for f in fills)
    assert len(b.done) == 12


def test_no_double_free_over_random_workload():
    """Fuzz the full lifecycle (admit/evict/grow/preempt) against the
    conservation invariant; any double-free raises PageError."""
    import numpy as np
    rng = np.random.default_rng(7)
    _, b = _mk(n_pages=12, page_size=2, max_batch=3)
    for i in range(40):
        b.submit(_req(i, prompt_len=int(rng.integers(1, 5)),
                      max_new=int(rng.integers(1, 9))))
    b.admit()
    steps = 0
    while not b.idle():
        b.on_tokens({s: int(rng.integers(0, 9)) for s in list(b.running)})
        _conserved(b)
        steps += 1
        assert steps < 2000, "scheduler wedged"
    assert len(b.done) == 40
    assert b.alloc.used_pages() == 0


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------

def test_preemption_youngest_victim_keeps_generated():
    # 4 usable pages, page_size 2: two requests of prompt 2 own 2 pages
    # each (context + 1 reserved) and the pool is exhausted. The elder's
    # growth across the page boundary starves -> the YOUNGER is
    # preempted, keeps its generated prefix, and lands at the FRONT of
    # the waiting queue.
    _, b = _mk(n_pages=5, page_size=2, max_batch=2)
    b.submit(_req(0, prompt_len=2, max_new=8))
    b.admit()
    b.submit(_req(1, prompt_len=2, max_new=8))
    b.submit(_req(2, prompt_len=2, max_new=8))   # queued behind
    done = b.on_tokens({0: 5})                   # admits rid 1 (pool now full)
    assert not done and sorted(b.running) == [0, 1]
    b.on_tokens({0: 5, 1: 5})    # rid 0 ctx 4 -> needs a 3rd page: starved
    victim = [r for r in b.waiting if r.rid == 1]
    assert victim and victim[0] is b.waiting[0]  # front, ahead of rid 2
    assert victim[0].preemptions == 1
    assert victim[0].generated == [5]            # prefix kept for replay
    assert victim[0].pages == [] and victim[0].slot == -1
    _conserved(b)


def test_preemption_self_when_youngest():
    _, b = _mk(n_pages=3, page_size=1, max_batch=1)  # 2 usable
    b.submit(_req(0, prompt_len=1, max_new=8))
    b.admit()
    assert len(b.running[0].pages) == 2
    b.on_tokens({0: 5})                 # needs a 3rd page -> none left
    assert not b.running                # preempted itself, no deadlock
    assert b.waiting[0].rid == 0 and b.waiting[0].preemptions == 1
    assert b.alloc.used_pages() == 0


def test_block_table_pads_with_trash_and_bounds():
    _, b = _mk()
    b.submit(_req(0))
    b.admit()
    req = b.running[0]
    bt = b.block_table(req, 6)
    assert len(bt) == 6
    assert bt[:len(req.pages)] == req.pages
    assert all(p == 0 for p in bt[len(req.pages):])
    with pytest.raises(ValueError):
        b.block_table(req, len(req.pages) - 1)


def test_mode_validated():
    alloc = sched.PageAllocator(8, 4)
    with pytest.raises(ValueError):
        sched.ContinuousBatcher(alloc, 4, mode="dynamic")


# ---------------------------------------------------------------------------
# AutoscalePolicy
# ---------------------------------------------------------------------------

def test_autoscale_scale_up_needs_patience():
    p = autoscale.AutoscalePolicy(1, 4, high_depth=8, patience=3)
    assert p.observe(20, 1.0) is None
    assert p.observe(20, 1.0) is None
    assert p.observe(20, 1.0) == 2      # third consecutive breach
    assert p.observe(20, 1.0) is None   # streak reset after acting
    assert p.observe(20, 1.0) is None
    assert p.observe(20, 1.0) == 3


def test_autoscale_breach_streak_resets_in_band():
    p = autoscale.AutoscalePolicy(1, 4, high_depth=8, patience=3)
    p.observe(20, 1.0)
    p.observe(20, 1.0)
    assert p.observe(4, 1.0) is None    # in band: streak dies
    assert p.observe(20, 1.0) is None
    assert p.observe(20, 1.0) is None
    assert p.observe(20, 1.0) == 2


def test_autoscale_scale_down_needs_idle_batch_too():
    p = autoscale.AutoscalePolicy(1, 4, low_depth=1, low_fill=0.5,
                                  patience=2)
    p.target = 3
    assert p.observe(0, 0.9) is None    # queue empty but batch busy
    assert p.observe(0, 0.9) is None    # ...never scales down
    assert p.observe(0, 0.2) is None
    assert p.observe(0, 0.2) == 2       # empty AND half-idle: down


def test_autoscale_clamps_to_bounds():
    p = autoscale.AutoscalePolicy(2, 3, patience=1)
    assert p.observe(0, 0.0) is None    # already at min_np
    assert p.observe(99, 1.0) == 3
    assert p.observe(99, 1.0) is None   # at max_np: hold
    assert p.observe(0, 0.0) == 2
    assert p.observe(0, 0.0) is None    # back at min_np


def test_autoscale_validates_band_and_bounds():
    with pytest.raises(ValueError):
        autoscale.AutoscalePolicy(4, 2)
    with pytest.raises(ValueError):
        autoscale.AutoscalePolicy(1, 4, high_depth=1, low_depth=1)
