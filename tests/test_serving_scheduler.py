"""Serving-plane scheduling invariants (ISSUE 14) — pure-numpy tier-1.

The control half of the serving plane (horovod_tpu/serving/scheduler.py
and autoscale.py) is deliberately jax-free, so the invariants that keep
the paged KV cache sound — page conservation, no double-allocation,
strict-ownership frees, admission/eviction at token boundaries,
batch-fill monotonicity under backlog — are all testable without an
accelerator stack. Modules are loaded standalone (the serving package
lazy-imports, but standalone load keeps parity with how bench.py's
jax-free parent would read them), the test_pipeline_schedules.py idiom.

Engine-side coverage (prefill/decode parity against forward(), the
mixed-length jit'd step, the ServeLoop A/B) lives in
tests/test_serving.py, which needs jax.
"""
import importlib.util
import os

import pytest

from .util import _REPO

pytestmark = pytest.mark.serve


def _load(name):
    path = os.path.join(_REPO, "horovod_tpu", "serving", name + ".py")
    spec = importlib.util.spec_from_file_location(name + "_under_test",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


sched = _load("scheduler")
autoscale = _load("autoscale")
prefix_cache = _load("prefix_cache")
speculate = _load("speculate")


def _mk(n_pages=32, page_size=4, max_batch=4, mode="continuous"):
    alloc = sched.PageAllocator(n_pages, page_size)
    return alloc, sched.ContinuousBatcher(alloc, max_batch, mode)


def _req(rid, prompt_len=4, max_new=8, eos=-1):
    return sched.Request(rid=rid, prompt=list(range(prompt_len)),
                         max_new_tokens=max_new, eos_id=eos)


def _conserved(b):
    """The page-accounting contract: free + owned == usable, and every
    running request's pages are disjoint."""
    owned = [p for r in b.running.values() for p in r.pages]
    assert len(owned) == len(set(owned)), "page owned twice"
    assert 0 not in owned, "trash page 0 handed out"
    assert b.alloc.free_pages() + b.alloc.used_pages() \
        == b.alloc.usable_pages
    assert b.alloc.used_pages() == len(owned)


# ---------------------------------------------------------------------------
# PageAllocator
# ---------------------------------------------------------------------------

def test_allocator_reserves_trash_page():
    a = sched.PageAllocator(8, 4)
    assert a.usable_pages == 7
    got = a.alloc(7)
    assert got is not None and 0 not in got
    assert a.alloc(1) is None  # page 0 is never the fallback


def test_allocator_all_or_nothing():
    a = sched.PageAllocator(5, 4)
    assert a.alloc(5) is None          # only 4 usable
    assert a.free_pages() == 4         # failed alloc took nothing
    assert a.alloc(4) is not None
    assert a.free_pages() == 0


def test_allocator_double_free_raises_before_mutation():
    a = sched.PageAllocator(8, 4)
    pages = a.alloc(3)
    a.free(pages[:1])
    with pytest.raises(sched.PageError):
        a.free(pages)                  # pages[0] no longer owned
    # the failed free must not have returned pages[1:] either
    assert a.used_pages() == 2
    assert a.free_pages() == 5


def test_allocator_foreign_page_raises():
    a = sched.PageAllocator(8, 4)
    a.alloc(2)
    with pytest.raises(sched.PageError):
        a.free([6])                    # never allocated
    with pytest.raises(sched.PageError):
        a.free([0])                    # the trash page


def test_allocator_rejects_degenerate_pools():
    with pytest.raises(ValueError):
        sched.PageAllocator(1, 4)      # only the trash page
    with pytest.raises(ValueError):
        sched.PageAllocator(8, 0)


def test_allocator_occupancy():
    a = sched.PageAllocator(9, 4)
    assert a.occupancy() == 0.0
    a.alloc(4)
    assert a.occupancy() == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def test_serve_knobs_defaults(monkeypatch):
    for k in ("HVD_SERVE_PAGE_SIZE", "HVD_SERVE_KV_PAGES",
              "HVD_SERVE_MAX_BATCH", "HVD_SERVE_MODE",
              "HVD_SERVE_PREFIX_CACHE", "HVD_SERVE_SPEC_TOKENS"):
        monkeypatch.delenv(k, raising=False)
    k = sched.serve_knobs()
    assert k == {"page_size": sched.DEFAULT_PAGE_SIZE,
                 "kv_pages": sched.DEFAULT_KV_PAGES,
                 "max_batch": sched.DEFAULT_MAX_BATCH,
                 "mode": "continuous",
                 "prefix_cache": sched.DEFAULT_PREFIX_CACHE,
                 "spec_tokens": sched.DEFAULT_SPEC_TOKENS}
    assert k["prefix_cache"] == 1 and k["spec_tokens"] == 0


def test_serve_knobs_env_overrides(monkeypatch):
    monkeypatch.setenv("HVD_SERVE_PAGE_SIZE", "32")
    monkeypatch.setenv("HVD_SERVE_KV_PAGES", "512")
    monkeypatch.setenv("HVD_SERVE_MAX_BATCH", "not-a-number")
    monkeypatch.setenv("HVD_SERVE_MODE", "static")
    monkeypatch.setenv("HVD_SERVE_PREFIX_CACHE", "0")
    monkeypatch.setenv("HVD_SERVE_SPEC_TOKENS", "4")
    k = sched.serve_knobs()
    assert k["page_size"] == 32 and k["kv_pages"] == 512
    assert k["max_batch"] == sched.DEFAULT_MAX_BATCH  # garbage -> default
    assert k["mode"] == "static"
    assert k["prefix_cache"] == 0 and k["spec_tokens"] == 4


# ---------------------------------------------------------------------------
# admission / eviction
# ---------------------------------------------------------------------------

def test_admission_fills_free_slots_lowest_first():
    _, b = _mk(max_batch=4)
    for i in range(6):
        b.submit(_req(i))
    got = b.admit()
    assert [r.rid for r in got] == [0, 1, 2, 3]
    assert sorted(b.running) == [0, 1, 2, 3]
    assert b.queue_depth() == 2
    assert b.batch_fill() == 1.0
    _conserved(b)


def test_admission_reserves_first_decode_slot():
    # prompt 4 + 1 upcoming decode position at page_size 4 -> 2 pages.
    _, b = _mk(n_pages=3, page_size=4)  # 2 usable
    b.submit(_req(0, prompt_len=4))
    assert len(b.admit()) == 1
    assert len(b.running[0].pages) == 2
    _conserved(b)


def test_admission_head_of_line_keeps_arrival_order():
    _, b = _mk(n_pages=4, page_size=4)  # 3 usable
    b.submit(_req(0, prompt_len=8))     # needs 3 pages
    b.submit(_req(1, prompt_len=1))     # would fit, but is behind rid 0
    assert len(b.admit()) == 1
    b.submit(_req(2, prompt_len=1))
    assert b.admit() == []              # rid 1 blocked -> rid 2 waits too
    assert [r.rid for r in b.waiting] == [1, 2]


def test_eviction_on_eos_and_max_tokens_frees_pages():
    _, b = _mk()
    b.submit(_req(0, max_new=8, eos=7))
    b.submit(_req(1, max_new=2))
    b.admit()
    done = b.on_tokens({0: 7, 1: 5})    # rid 0 hits EOS immediately
    assert [r.rid for r in done] == [0]
    assert done[0].finish_reason == "eos" and done[0].pages == []
    done = b.on_tokens({1: 5})          # rid 1 reaches max_new=2
    assert [r.rid for r in done] == [1]
    assert done[0].finish_reason == "max_tokens"
    assert b.idle()
    assert b.alloc.used_pages() == 0
    _conserved(b)


def test_eviction_readmits_in_same_boundary():
    _, b = _mk(max_batch=1)
    b.submit(_req(0, max_new=1))
    b.submit(_req(1))
    b.admit()
    assert b.queue_depth() == 1
    done = b.on_tokens({0: 3})
    # rid 0 finished AND rid 1 took its slot within one boundary — the
    # continuous-batching property itself.
    assert [r.rid for r in done] == [0]
    assert b.running[0].rid == 1
    _conserved(b)


def test_static_mode_admits_only_into_empty_batch():
    _, b = _mk(max_batch=2, mode="static")
    for i in range(4):
        b.submit(_req(i, max_new=2 + i))
    b.admit()
    assert sorted(r.rid for r in b.running.values()) == [0, 1]
    done = b.on_tokens({0: 1, 1: 1})
    assert not done
    done = b.on_tokens({0: 1, 1: 1})    # rid 0 done (max_new=2)...
    assert [r.rid for r in done] == [0]
    assert [r.rid for r in b.running.values()] == [1]  # slot idles
    done = b.on_tokens({1: 1})          # rid 1 done -> batch empty
    assert [r.rid for r in done] == [1]
    assert sorted(r.rid for r in b.running.values()) == [2, 3]
    _conserved(b)


def test_batch_fill_monotone_under_backlog():
    """With a standing queue and ample pages, continuous batching keeps
    every slot busy at every boundary — fill never drops below 1.0 until
    the backlog drains (the quantity the bench A/B measures)."""
    _, b = _mk(n_pages=128, page_size=4, max_batch=4)
    for i in range(12):
        b.submit(_req(i, prompt_len=2, max_new=1 + (i % 4)))
    b.admit()
    fills = []
    while not b.idle():
        b.on_tokens({s: 1 for s in list(b.running)})
        if b.queue_depth() > 0 or b.batch_fill() == 1.0:
            fills.append(b.batch_fill())
        _conserved(b)
    assert fills and all(f == 1.0 for f in fills)
    assert len(b.done) == 12


def test_no_double_free_over_random_workload():
    """Fuzz the full lifecycle (admit/evict/grow/preempt) against the
    conservation invariant; any double-free raises PageError."""
    import numpy as np
    rng = np.random.default_rng(7)
    _, b = _mk(n_pages=12, page_size=2, max_batch=3)
    for i in range(40):
        b.submit(_req(i, prompt_len=int(rng.integers(1, 5)),
                      max_new=int(rng.integers(1, 9))))
    b.admit()
    steps = 0
    while not b.idle():
        b.on_tokens({s: int(rng.integers(0, 9)) for s in list(b.running)})
        _conserved(b)
        steps += 1
        assert steps < 2000, "scheduler wedged"
    assert len(b.done) == 40
    assert b.alloc.used_pages() == 0


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------

def test_preemption_youngest_victim_keeps_generated():
    # 4 usable pages, page_size 2: two requests of prompt 2 own 2 pages
    # each (context + 1 reserved) and the pool is exhausted. The elder's
    # growth across the page boundary starves -> the YOUNGER is
    # preempted, keeps its generated prefix, and lands at the FRONT of
    # the waiting queue.
    _, b = _mk(n_pages=5, page_size=2, max_batch=2)
    b.submit(_req(0, prompt_len=2, max_new=8))
    b.admit()
    b.submit(_req(1, prompt_len=2, max_new=8))
    b.submit(_req(2, prompt_len=2, max_new=8))   # queued behind
    done = b.on_tokens({0: 5})                   # admits rid 1 (pool now full)
    assert not done and sorted(b.running) == [0, 1]
    b.on_tokens({0: 5, 1: 5})    # rid 0 ctx 4 -> needs a 3rd page: starved
    victim = [r for r in b.waiting if r.rid == 1]
    assert victim and victim[0] is b.waiting[0]  # front, ahead of rid 2
    assert victim[0].preemptions == 1
    assert victim[0].generated == [5]            # prefix kept for replay
    assert victim[0].pages == [] and victim[0].slot == -1
    _conserved(b)


def test_preemption_self_when_youngest():
    _, b = _mk(n_pages=3, page_size=1, max_batch=1)  # 2 usable
    b.submit(_req(0, prompt_len=1, max_new=8))
    b.admit()
    assert len(b.running[0].pages) == 2
    b.on_tokens({0: 5})                 # needs a 3rd page -> none left
    assert not b.running                # preempted itself, no deadlock
    assert b.waiting[0].rid == 0 and b.waiting[0].preemptions == 1
    assert b.alloc.used_pages() == 0


def test_block_table_pads_with_trash_and_bounds():
    _, b = _mk()
    b.submit(_req(0))
    b.admit()
    req = b.running[0]
    bt = b.block_table(req, 6)
    assert len(bt) == 6
    assert bt[:len(req.pages)] == req.pages
    assert all(p == 0 for p in bt[len(req.pages):])
    with pytest.raises(ValueError):
        b.block_table(req, len(req.pages) - 1)


def test_mode_validated():
    alloc = sched.PageAllocator(8, 4)
    with pytest.raises(ValueError):
        sched.ContinuousBatcher(alloc, 4, mode="dynamic")
    with pytest.raises(ValueError):
        sched.ContinuousBatcher(alloc, 4, spec_tokens=-1)


# ---------------------------------------------------------------------------
# refcounted PageAllocator (ISSUE 16 — copy-on-write sharing)
# ---------------------------------------------------------------------------

def _conserved_shared(b, cache=None):
    """The refcounted contract: free + DISTINCT-owned == usable, and
    every page's refcount equals its holder count (running requests
    plus at most one prefix-cache reference)."""
    import collections
    holders = collections.Counter()
    for r in b.running.values():
        for p in r.pages:
            holders[p] += 1
    if cache is not None:
        for p in cache.cached_pages():
            holders[p] += 1
    assert 0 not in holders, "trash page 0 held"
    assert b.alloc.free_pages() + b.alloc.used_pages() \
        == b.alloc.usable_pages
    assert b.alloc.used_pages() == len(holders)
    for p, n in holders.items():
        assert b.alloc.refcount(p) == n, (p, n, b.alloc.refcount(p))


def test_share_bumps_refcount_and_free_decrements():
    a = sched.PageAllocator(8, 4)
    pages = a.alloc(2)
    a.share(pages)
    assert [a.refcount(p) for p in pages] == [2, 2]
    assert a.used_pages() == 2           # distinct pages, not references
    a.free(pages)                        # one holder drops
    assert [a.refcount(p) for p in pages] == [1, 1]
    assert a.free_pages() == 5           # nothing returned to the pool yet
    a.free(pages)                        # last holder drops
    assert a.free_pages() == 7 and a.used_pages() == 0


def test_share_unowned_raises_before_mutation():
    a = sched.PageAllocator(8, 4)
    pages = a.alloc(1)
    with pytest.raises(sched.PageError):
        a.share(pages + [5])             # 5 was never allocated
    assert a.refcount(pages[0]) == 1     # the valid page was NOT bumped


def test_refcount_underflow_raises_before_mutation():
    a = sched.PageAllocator(8, 4)
    (p,) = a.alloc(1)
    a.share([p])                         # refcount 2
    with pytest.raises(sched.PageError):
        a.free([p, p, p])                # 3 drops > 2 refs, atomically
    assert a.refcount(p) == 2            # untouched — checked BEFORE
    a.free([p, p])                       # exactly the refcount is fine
    assert a.refcount(p) == 0 and a.free_pages() == 7


def test_cow_fork_free_conservation():
    """A 'fork' (two holders of one prefix) then both frees, in either
    order, conserves pages and never double-returns."""
    a = sched.PageAllocator(10, 4)
    shared = a.alloc(3)                  # the cached prefix
    a.share(shared)                      # the forked request's reference
    own = a.alloc(2)                     # its private suffix pages
    assert a.used_pages() == 5
    a.free(shared + own)                 # request exits
    assert a.used_pages() == 3           # prefix still owned by the cache
    assert a.free_pages() == 6
    a.free(shared)                       # cache drops it too
    assert a.free_pages() == 9 and a.used_pages() == 0


# ---------------------------------------------------------------------------
# PrefixCache (radix tree)
# ---------------------------------------------------------------------------

def _cache(n_pages=32, page_size=4):
    a = sched.PageAllocator(n_pages, page_size)
    return a, prefix_cache.PrefixCache(a)


def test_prefix_insert_then_lookup_shares_pages():
    a, pc = _cache()
    pages = a.alloc(3)
    prompt = list(range(10))             # 2 full pages + 2-token tail
    assert pc.insert(prompt, pages) == 2   # only full pages are cached
    assert a.refcount(pages[0]) == 2 and a.refcount(pages[2]) == 1
    hit, n = pc.lookup(prompt)
    assert hit == pages[:2] and n == 8
    # lookup takes NO references — sharing is the caller's decision
    assert a.refcount(pages[0]) == 2


def test_prefix_lookup_is_strict():
    """An exactly-page-aligned prompt must keep >= 1 novel token: the
    match is capped one page short so the first-token logits always
    come from a real prefill."""
    a, pc = _cache(page_size=4)
    pages = a.alloc(2)
    pc.insert(list(range(8)), pages)
    hit, n = pc.lookup(list(range(8)))
    assert hit == pages[:1] and n == 4   # NOT both pages
    hit, n = pc.lookup(list(range(9)))
    assert hit == pages[:2] and n == 8   # one tail token -> full match
    assert pc.lookup(list(range(3)))[1] == 0   # sub-page prompt: miss


def test_prefix_radix_shares_common_nodes():
    a, pc = _cache(page_size=4)
    p1 = a.alloc(2)
    pc.insert(list(range(8)) + [99], p1)
    # Same first page, different second page -> ONE new node only.
    p2 = [p1[0]] + a.alloc(1)
    added = pc.insert(list(range(4)) + [50, 51, 52, 53, 99], p2)
    assert added == 1
    assert len(pc) == 3
    assert a.refcount(p1[0]) == 2        # one cache ref despite two inserts


def test_prefix_lru_eviction_order():
    a, pc = _cache(page_size=4)
    pa, pb = a.alloc(1), a.alloc(1)
    pc.insert([1, 1, 1, 1, 9], pa)
    pc.insert([2, 2, 2, 2, 9], pb)
    a.free(pa + pb)                      # cache is now the only holder
    pc.lookup([1, 1, 1, 1, 9])           # touch A — B becomes LRU
    assert pc.evict(1) == 1
    assert pc.lookup([2, 2, 2, 2, 9])[1] == 0   # B gone
    assert pc.lookup([1, 1, 1, 1, 9])[1] == 4   # A survives
    assert a.refcount(pb[0]) == 0


def test_prefix_evict_skips_shared_and_interior_pages():
    a, pc = _cache(page_size=4)
    pages = a.alloc(2)
    pc.insert(list(range(8)) + [9], pages)   # chain: interior -> leaf
    # A live request still shares the LEAF page: nothing is evictable
    # (the interior page is protected by its child).
    assert pc.evict(5) == 0
    a.free([pages[0]])                   # request drops the interior page
    assert pc.evict(5) == 0              # leaf still shared by request
    a.free([pages[1]])                   # request exits fully
    assert pc.evict(5) == 2              # leaf first, then the exposed parent
    assert len(pc) == 0
    assert a.free_pages() == a.usable_pages


# ---------------------------------------------------------------------------
# batcher x prefix cache (COW admission / preemption / reclaim)
# ---------------------------------------------------------------------------

def _mk_cached(n_pages=32, page_size=4, max_batch=4, spec_tokens=0):
    a = sched.PageAllocator(n_pages, page_size)
    pc = prefix_cache.PrefixCache(a)
    b = sched.ContinuousBatcher(a, max_batch, "continuous",
                                prefix_cache=pc, spec_tokens=spec_tokens)
    return a, pc, b


def _preq(rid, prompt, max_new=8, eos=-1):
    return sched.Request(rid=rid, prompt=list(prompt),
                         max_new_tokens=max_new, eos_id=eos)


def test_admission_shares_cached_prefix():
    a, pc, b = _mk_cached()
    b.submit(_preq(0, range(9)))
    b.admit()
    first = b.running[0]
    assert first.cached_tokens == 0      # cold cache: full miss
    b.register_prefilled(first)          # prompt pages published
    shared_pages = first.pages[:2]
    b.on_tokens({0: 99}, 0.0)
    _conserved_shared(b, pc)
    b.submit(_preq(1, range(9)))         # identical prompt
    b.admit()
    second = b.running[1]
    assert second.cached_tokens == 8
    assert second.pages[:2] == shared_pages    # the SAME physical pages
    assert a.refcount(shared_pages[0]) == 3    # req0 + req1 + cache
    assert b.stats["prefix_hit_tokens"] == 8
    assert b.prefix_hit_ratio() == pytest.approx(8 / 18)
    _conserved_shared(b, pc)


def test_preemption_of_request_holding_shared_pages():
    a, pc, b = _mk_cached(n_pages=32, page_size=2)
    b.submit(_preq(0, range(5), max_new=16))
    b.admit()
    b.register_prefilled(b.running[0])
    b.submit(_preq(1, range(5), max_new=16))
    b.on_tokens({0: 7}, 0.0)             # admits rid 1 with a prefix hit
    second = b.running[1]
    assert second.cached_tokens == 4
    shared = list(second.pages[:2])
    assert a.refcount(shared[0]) == 3    # rid0 + rid1 + cache
    b._preempt(second, 0.0)
    # One reference dropped per shared page; the other holders survive.
    assert second.pages == [] and second.cached_tokens == 0
    assert b.waiting[0] is second        # preempted -> FRONT of the queue
    assert a.refcount(shared[0]) == 2
    _conserved_shared(b, pc)
    b.admit()                            # readmits, re-hitting the cache
    assert second.state == "running"
    assert second.cached_tokens == 4     # re-resolved at readmission
    assert a.refcount(shared[0]) == 3
    _conserved_shared(b, pc)


def test_page_pressure_evicts_cold_prefixes_before_preempting():
    a, pc, b = _mk_cached(n_pages=8, page_size=2, max_batch=2)
    b.submit(_preq(0, range(4), max_new=2))
    b.admit()
    b.register_prefilled(b.running[0])
    cached = list(b.running[0].pages[:2])
    b.on_tokens({0: 9}, 0.0)
    b.on_tokens({0: 9}, 0.0)             # rid 0 finishes (max_new=2)
    assert not b.running
    assert a.used_pages() == 2           # only the cached prefix remains
    # A fat unrelated request needs more than the free pool: the cold
    # cached prefix is LRU-evicted to make room instead of stalling.
    b.submit(_preq(1, list(range(50, 61)), max_new=4))
    b.admit()
    assert 0 in b.running and b.running[0].rid == 1
    assert pc.stats["evictions"] >= 1
    assert cached[1] not in pc.cached_pages()   # evicted leaf left the tree
    _conserved_shared(b, pc)


def test_grow_reserves_spec_lookahead():
    a = sched.PageAllocator(32, 2)
    bs = sched.ContinuousBatcher(a, 4, "continuous", spec_tokens=3)
    bs.submit(_req(0, prompt_len=2, max_new=16))
    bs.admit()
    # context 2 + lookahead (1 + 3 drafts) = 6 positions -> 3 pages.
    assert len(bs.running[0].pages) == 3
    bs.on_tokens({0: 5}, 0.0)            # context 3, window to 7 -> 4 pages
    assert len(bs.running[0].pages) == 4


def test_on_tokens_list_truncates_at_finish():
    _, b = _mk()
    b.submit(_req(0, prompt_len=2, max_new=8, eos=42))
    b.admit()
    done = b.on_tokens({0: [1, 2, 42, 3, 4]}, 0.0)   # EOS mid-burst
    assert len(done) == 1 and done[0].finish_reason == "eos"
    assert done[0].generated == [1, 2, 42]           # trailing drafts dropped
    assert b.stats["tokens"] == 3
    b.submit(_req(1, prompt_len=2, max_new=2))
    b.admit()
    done = b.on_tokens({0: [7, 8, 9]}, 0.0)
    assert done[0].finish_reason == "max_tokens"
    assert done[0].generated == [7, 8]               # capped at max_new


# ---------------------------------------------------------------------------
# speculate (accept/reject arithmetic)
# ---------------------------------------------------------------------------

def test_accept_drafts_prefix_rule():
    em, acc, rej = speculate.accept_drafts([3, 4, 1], [3, 4, 9, 7])
    assert (em, acc, rej) == ([3, 4, 9], 2, 1)   # 2 accepted + bonus
    em, acc, rej = speculate.accept_drafts([5, 6], [7, 8, 9])
    assert (em, acc, rej) == ([7], 0, 2)         # full reject still emits 1
    em, acc, rej = speculate.accept_drafts([1, 2], [1, 2, 3])
    assert (em, acc, rej) == ([1, 2, 3], 2, 0)   # clean sweep: k+1 tokens
    with pytest.raises(ValueError):
        speculate.accept_drafts([1, 2], [1, 2])  # k+1 positions required


def test_ngram_drafter_prefers_full_continuations():
    d = speculate.NGramDrafter(2)
    ctx = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2]
    # The trailing (1, 2) also matches at the END (truncated): the
    # earlier FULL continuation must win.
    assert d.propose(ctx, 3) == [3, 4, 1]
    assert d.propose(ctx, 8) == [3, 4, 1, 2, 3, 4, 1, 2]
    assert d.propose([9, 9], 4) == []            # no earlier occurrence
    assert d.propose(ctx, 0) == []
    with pytest.raises(ValueError):
        speculate.NGramDrafter(0)


def test_fixed_drafter_truncates():
    d = speculate.FixedDrafter([5, 6, 7])
    assert d.propose([1, 2], 2) == [5, 6]


# ---------------------------------------------------------------------------
# fuzz: shared prefixes + speculation bursts against conservation
# ---------------------------------------------------------------------------

def test_no_double_free_with_shared_prefixes_over_random_workload():
    """The ISSUE-16 extension of the lifecycle fuzz: prompts drawn from
    a handful of shared templates (so admissions constantly fork cached
    prefix pages), multi-token speculative bursts at boundaries, and
    periodic cache eviction pressure — the refcounted conservation
    invariant must hold at every step."""
    import numpy as np
    rng = np.random.default_rng(16)
    a, pc, b = _mk_cached(n_pages=14, page_size=2, max_batch=3,
                          spec_tokens=2)
    templates = [list(rng.integers(0, 50, size=6)) for _ in range(3)]
    for i in range(40):
        t = templates[int(rng.integers(0, 3))]
        tail = [int(x) for x in
                rng.integers(50, 99, size=int(rng.integers(1, 4)))]
        b.submit(sched.Request(rid=i, prompt=list(t) + tail,
                               max_new_tokens=int(rng.integers(1, 9))))
    b.admit()
    steps = 0
    prefill_seen = set()
    while not b.idle():
        # Publish "prefilled" prompts like the serve loop would.
        for r in list(b.running.values()):
            key = (r.rid, r.admit_seq)
            if key not in prefill_seen:
                prefill_seen.add(key)
                b.register_prefilled(r)
        burst = {s: [int(x) for x in
                     rng.integers(0, 9, size=int(rng.integers(1, 4)))]
                 for s in list(b.running)}
        b.on_tokens(burst, 0.0)
        _conserved_shared(b, pc)
        steps += 1
        assert steps < 2000, "scheduler wedged"
    assert len(b.done) == 40
    # Every page still owned is owned by the cache alone.
    for p in pc.cached_pages():
        assert a.refcount(p) == 1
    pc.evict(a.usable_pages)
    assert a.used_pages() == 0 and a.free_pages() == a.usable_pages


# ---------------------------------------------------------------------------
# AutoscalePolicy
# ---------------------------------------------------------------------------

def test_autoscale_scale_up_needs_patience():
    p = autoscale.AutoscalePolicy(1, 4, high_depth=8, patience=3)
    assert p.observe(20, 1.0) is None
    assert p.observe(20, 1.0) is None
    assert p.observe(20, 1.0) == 2      # third consecutive breach
    assert p.observe(20, 1.0) is None   # streak reset after acting
    assert p.observe(20, 1.0) is None
    assert p.observe(20, 1.0) == 3


def test_autoscale_breach_streak_resets_in_band():
    p = autoscale.AutoscalePolicy(1, 4, high_depth=8, patience=3)
    p.observe(20, 1.0)
    p.observe(20, 1.0)
    assert p.observe(4, 1.0) is None    # in band: streak dies
    assert p.observe(20, 1.0) is None
    assert p.observe(20, 1.0) is None
    assert p.observe(20, 1.0) == 2


def test_autoscale_scale_down_needs_idle_batch_too():
    p = autoscale.AutoscalePolicy(1, 4, low_depth=1, low_fill=0.5,
                                  patience=2)
    p.target = 3
    assert p.observe(0, 0.9) is None    # queue empty but batch busy
    assert p.observe(0, 0.9) is None    # ...never scales down
    assert p.observe(0, 0.2) is None
    assert p.observe(0, 0.2) == 2       # empty AND half-idle: down


def test_autoscale_clamps_to_bounds():
    p = autoscale.AutoscalePolicy(2, 3, patience=1)
    assert p.observe(0, 0.0) is None    # already at min_np
    assert p.observe(99, 1.0) == 3
    assert p.observe(99, 1.0) is None   # at max_np: hold
    assert p.observe(0, 0.0) == 2
    assert p.observe(0, 0.0) is None    # back at min_np


def test_autoscale_validates_band_and_bounds():
    with pytest.raises(ValueError):
        autoscale.AutoscalePolicy(4, 2)
    with pytest.raises(ValueError):
        autoscale.AutoscalePolicy(1, 4, high_depth=1, low_depth=1)
