"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (the "fake pod" — SURVEY.md §4:
multi-node is simulated as multi-device/multi-process on one host). These env
vars must be set before the first `import jax` anywhere in the test process.
"""

import os
import sys

# Force CPU for tests even when the session env selects a TPU platform
# (bench.py and __graft_entry__.py are the TPU surfaces, not the test suite).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Make the repo importable for spawned worker subprocesses too.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)
os.environ["PYTHONPATH"] = _REPO + os.pathsep + os.environ.get("PYTHONPATH", "")
