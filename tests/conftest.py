"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (the "fake pod" — SURVEY.md §4:
multi-node is simulated as multi-device/multi-process on one host). These env
vars must be set before the first `import jax` anywhere in the test process.
"""

import os
import sys

# Force CPU for tests even when the session env selects a TPU platform
# (bench.py and __graft_entry__.py are the TPU surfaces, not the test suite).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

# A site hook may have pre-imported jax and pinned jax_platforms to a TPU
# plugin; env vars alone are then ignored. Override the live config too.
# Best-effort: pure-core tests must still run without jax / with a stuck
# backend (jax-dependent test modules importorskip and assert devices
# themselves).
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

# Make the repo importable for spawned worker subprocesses too.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)
os.environ["PYTHONPATH"] = _REPO + os.pathsep + os.environ.get("PYTHONPATH", "")
