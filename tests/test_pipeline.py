"""Pipeline parallelism (parallel/pipeline.py — beyond reference: the
reference has no PP or p2p send/recv at all). Correctness bar: the GPipe
schedule must match the sequential composition, forward AND gradients,
on the virtual CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

try:  # the whole parallel package needs jax >= 0.8's jax.shard_map
    from jax import shard_map as _shard_map  # noqa: F401
    _HAVE_SHARD_MAP = True
except ImportError:
    _HAVE_SHARD_MAP = False

pytestmark = pytest.mark.skipif(
    not _HAVE_SHARD_MAP,
    reason="jax.shard_map unavailable (jax < 0.8): "
           "horovod_tpu.parallel cannot import here")


def test_pipeline_forward_matches_sequential():
    """parallel/pipeline.py (beyond reference — the reference has no PP
    or p2p at all): a 4-stage GPipe schedule over a 'pipe' mesh axis
    must reproduce running the same 4 layers sequentially on one
    device, for several microbatch counts (bubble masking correct at
    M == S and M > S)."""
    from horovod_tpu.parallel.pipeline import (pipeline_apply,
                                               shard_stage_params)

    S, D = 4, 16
    cpus = jax.devices("cpu")
    assert len(cpus) >= S
    mesh = Mesh(np.asarray(cpus[:S]), ("pipe",))

    rng = np.random.default_rng(0)
    W = rng.normal(size=(S, D, D)).astype(np.float32) / np.sqrt(D)
    b = rng.normal(size=(S, D)).astype(np.float32) * 0.1
    x = rng.normal(size=(8, D)).astype(np.float32)

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    def sequential(x):
        h = x
        for s in range(S):
            h = np.tanh(h @ W[s] + b[s])
        return h

    params = shard_stage_params({"w": W, "b": b}, mesh, "pipe")
    for M in (4, 8):
        out = np.asarray(pipeline_apply(stage_fn, params, jnp.asarray(x),
                                        mesh, "pipe", n_microbatches=M))
        assert np.allclose(out, sequential(x), atol=1e-5), (M, out[0][:4])


def test_pipeline_train_step_learns():
    """Gradients flow through the scan+ppermute schedule: jax.grad of a
    loss on pipeline outputs trains all four stages (loss falls 10x),
    and the per-stage grads match the sequential model's grads."""
    import optax

    from horovod_tpu.parallel.pipeline import (make_pipeline_train_step,
                                               pipeline_apply,
                                               shard_stage_params)

    S, D = 4, 8
    cpus = jax.devices("cpu")
    mesh = Mesh(np.asarray(cpus[:S]), ("pipe",))
    rng = np.random.default_rng(1)
    W = (rng.normal(size=(S, D, D)).astype(np.float32) / np.sqrt(D))
    x = rng.normal(size=(16, D)).astype(np.float32)
    y = np.roll(x, 1, axis=1) * 0.5  # a learnable linear-ish target

    def stage_fn(p, h):
        return h @ p["w"]

    def loss_fn(out, batch):
        return jnp.mean((out - batch["y"]) ** 2)

    # Grad parity vs the sequential composition, same loss.
    def seq_loss(Wflat):
        h = jnp.asarray(x)
        for s in range(S):
            h = h @ Wflat[s]
        return jnp.mean((h - jnp.asarray(y)) ** 2)

    params = shard_stage_params({"w": W}, mesh)
    def pipe_loss(p):
        out = pipeline_apply(stage_fn, p, jnp.asarray(x), mesh,
                             n_microbatches=4)
        return jnp.mean((out - jnp.asarray(y)) ** 2)

    g_pipe = jax.grad(pipe_loss)(params)["w"]
    g_seq = jax.grad(seq_loss)(jnp.asarray(W))
    assert np.allclose(np.asarray(g_pipe), np.asarray(g_seq),
                       atol=1e-5), np.abs(
        np.asarray(g_pipe) - np.asarray(g_seq)).max()

    # End-to-end training through make_pipeline_train_step.
    tx = optax.adam(3e-3)
    step = make_pipeline_train_step(stage_fn, loss_fn, tx, mesh,
                                    n_microbatches=4)
    opt_state = tx.init(params)
    batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
    losses = []
    for _ in range(200):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_pipeline_stage_count_mismatch_rejected():
    """A stage stack whose leading dim disagrees with the mesh axis must
    fail LOUDLY — shard_map would otherwise hand each device a slice of
    stages and silently compute the wrong (e.g. even-stages-only)
    composition."""
    import pytest

    from horovod_tpu.parallel.pipeline import (pipeline_apply,
                                               shard_stage_params)

    cpus = jax.devices("cpu")
    mesh = Mesh(np.asarray(cpus[:4]), ("pipe",))
    W8 = np.zeros((8, 4, 4), np.float32)
    with pytest.raises(ValueError, match="stage"):
        shard_stage_params({"w": W8}, mesh)
    with pytest.raises(ValueError, match="stage"):
        pipeline_apply(lambda p, h: h, {"w": jnp.zeros((8, 4, 4))},
                       jnp.zeros((8, 4)), mesh, n_microbatches=4)


def test_pipelined_transformer_matches_forward():
    """The REAL model through the pipeline: 4 transformer blocks
    (models/transformer.py apply_block) as 4 pipeline stages must
    reproduce tfm.forward exactly — embedding and head handled outside,
    per-layer params stacked on the stage dim."""
    import dataclasses

    from horovod_tpu.models import transformer as tfm
    from horovod_tpu.parallel.pipeline import (pipeline_apply,
                                               shard_stage_params)

    # f32 compute: exact parity (bf16 would differ by rounding order
    # between the scanned pipeline and the unrolled forward).
    cfg = dataclasses.replace(tfm.tiny(), n_layers=4, dtype="float32")
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    cpus = jax.devices("cpu")
    mesh = Mesh(np.asarray(cpus[:4]), ("pipe",))

    B, S = 4, 16
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S)), jnp.int32)

    want = np.asarray(tfm.forward(params, tokens, cfg))

    # Embed outside the pipeline (stage 0's input), blocks inside,
    # final-ln + head outside.
    dt = cfg.compute_dtype
    x = params["embed"].astype(dt)[tokens]
    x = x + params["pos_embed"].astype(dt)[:S][None]

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params["layers"])
    stage_params = shard_stage_params(
        jax.tree.map(np.asarray, stacked), mesh)

    def stage_fn(layer, h):
        return tfm.apply_block(layer, h, cfg)

    h = pipeline_apply(stage_fn, stage_params, x, mesh, n_microbatches=4)
    h = tfm._layer_norm(h, params["final_ln"])
    got = np.asarray(jnp.einsum("bsd,vd->bsv", h,
                                params["embed"].astype(dt)))
    assert np.allclose(got, want, atol=2e-4), np.abs(got - want).max()


def test_pipeline_composes_with_data_parallel():
    """pp x dp on one 4x2 mesh: microbatch rows shard over 'data', each
    replica runs the pipeline schedule on its shard, outputs match the
    sequential composition on the full batch, and per-replica grads
    psum'd over 'data' equal the full-batch sequential grads."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.parallel.pipeline import (pipeline_apply,
                                               shard_stage_params)

    S, D = 4, 8
    cpus = jax.devices("cpu")
    assert len(cpus) >= 8
    mesh = Mesh(np.asarray(cpus[:8]).reshape(4, 2), ("pipe", "data"))

    rng = np.random.default_rng(2)
    W = rng.normal(size=(S, D, D)).astype(np.float32) / np.sqrt(D)
    x = rng.normal(size=(16, D)).astype(np.float32)
    y = np.roll(x, 1, axis=1) * 0.5

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    params = shard_stage_params({"w": W}, mesh, "pipe")
    xd = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data")))

    out = np.asarray(pipeline_apply(stage_fn, params, xd, mesh,
                                    n_microbatches=4, batch_axis="data"))
    ref = x
    for s in range(S):
        ref = np.tanh(ref @ W[s])
    assert np.allclose(out, ref, atol=1e-5)

    # Gradient parity: mean loss over the FULL batch — per-shard mean
    # losses averaged over 'data' equal the full mean, so psum(grad)/2
    # must equal the sequential full-batch grad.
    def pipe_loss(p):
        o = pipeline_apply(stage_fn, p, xd, mesh, n_microbatches=4,
                           batch_axis="data")
        return jnp.mean((o - jnp.asarray(y)) ** 2)

    def seq_loss(Wf):
        h = jnp.asarray(x)
        for s in range(S):
            h = jnp.tanh(h @ Wf[s])
        return jnp.mean((h - jnp.asarray(y)) ** 2)

    g_pipe = np.asarray(jax.grad(pipe_loss)(params)["w"])
    g_seq = np.asarray(jax.grad(seq_loss)(jnp.asarray(W)))
    assert np.allclose(g_pipe, g_seq, atol=1e-5), np.abs(
        g_pipe - g_seq).max()


def test_pipeline_with_remat_stage():
    """jax.checkpoint around the stage function composes with the
    scan+ppermute schedule (the long-context recipe: rematerialized
    blocks inside pipeline stages) — gradients still match sequential."""
    from horovod_tpu.parallel.pipeline import (pipeline_apply,
                                               shard_stage_params)

    S, D = 4, 8
    cpus = jax.devices("cpu")
    mesh = Mesh(np.asarray(cpus[:S]), ("pipe",))
    rng = np.random.default_rng(3)
    W = rng.normal(size=(S, D, D)).astype(np.float32) / np.sqrt(D)
    x = rng.normal(size=(8, D)).astype(np.float32)

    stage_fn = jax.checkpoint(lambda p, h: jnp.tanh(h @ p["w"]))
    params = shard_stage_params({"w": W}, mesh)

    def pipe_loss(p):
        out = pipeline_apply(stage_fn, p, jnp.asarray(x), mesh,
                             n_microbatches=4)
        return jnp.sum(out ** 2)

    def seq_loss(Wf):
        h = jnp.asarray(x)
        for s in range(S):
            h = jnp.tanh(h @ Wf[s])
        return jnp.sum(h ** 2)

    g_pipe = np.asarray(jax.grad(pipe_loss)(params)["w"])
    g_seq = np.asarray(jax.grad(seq_loss)(jnp.asarray(W)))
    assert np.allclose(g_pipe, g_seq, atol=1e-5), np.abs(
        g_pipe - g_seq).max()


# ---------------------------------------------------------------------------
# Schedule parity (ISSUE 13): every schedule is a different ORDER of the
# same math — loss and grads must match the single-device sequential
# reference, across stage counts and composed with data parallelism.
# ---------------------------------------------------------------------------


def _schedule_parity_setup(S, dp, n_slices):
    """Mesh + params + batch + the sequential reference for one case."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    D, B = 8, 16
    cpus = jax.devices("cpu")
    assert len(cpus) >= S * dp
    if dp > 1:
        mesh = Mesh(np.asarray(cpus[:S * dp]).reshape(S, dp),
                    ("pipe", "data"))
    else:
        mesh = Mesh(np.asarray(cpus[:S]), ("pipe",))

    rng = np.random.default_rng(7)
    W = (rng.normal(size=(n_slices, D, D)).astype(np.float32)
         / np.sqrt(D))
    x = rng.normal(size=(B, D)).astype(np.float32)
    y = np.roll(x, 1, axis=1) * 0.5

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    def loss_fn(out, batch):
        return jnp.mean((out - batch["y"]) ** 2)

    def seq_loss(Wf):
        h = jnp.asarray(x)
        for j in range(n_slices):
            h = jnp.tanh(h @ Wf[j])
        return jnp.mean((h - jnp.asarray(y)) ** 2)

    xs = jnp.asarray(x)
    if dp > 1:
        xs = jax.device_put(xs, NamedSharding(mesh, P("data")))
    batch = {"x": xs, "y": jnp.asarray(y)}
    return mesh, W, batch, stage_fn, loss_fn, seq_loss


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b", "interleaved", "zb"])
@pytest.mark.parametrize("S,dp", [(2, 1), (4, 1), (8, 1), (2, 2), (4, 2)])
def test_schedule_parity_vs_reference(schedule, S, dp):
    """Outputs AND gradients: each schedule x {2,4,8} stages (dp=1) and
    x {2,4} stages (dp=2) must allclose the single-device sequential
    composition — schedules change timing, not math."""
    from horovod_tpu.parallel.pipeline import (make_pipeline_value_and_grad,
                                               shard_stage_params)

    V = 2 if schedule == "interleaved" else None
    n_slices = S * (V or 1)
    mesh, W, batch, stage_fn, loss_fn, seq_loss = _schedule_parity_setup(
        S, dp, n_slices)

    params = shard_stage_params({"w": W}, mesh, "pipe",
                                virtual_stages=V or 1)
    vg = make_pipeline_value_and_grad(
        stage_fn, loss_fn, mesh, n_microbatches=S,
        batch_axis="data" if dp > 1 else None,
        schedule=schedule, virtual_stages=V)
    loss, grads = vg(params, batch)

    ref_loss, ref_grad = jax.value_and_grad(seq_loss)(jnp.asarray(W))
    assert np.isclose(float(loss), float(ref_loss), atol=1e-5), (
        schedule, S, dp, float(loss), float(ref_loss))
    g = np.asarray(grads["w"])
    assert g.shape == np.asarray(ref_grad).shape
    assert np.allclose(g, np.asarray(ref_grad), atol=1e-4), (
        schedule, S, dp, np.abs(g - np.asarray(ref_grad)).max())


def test_divisibility_error_suggests_nearest():
    """The divisibility error must hand the user the nearest valid
    n_microbatches instead of a bare modulo complaint."""
    from horovod_tpu.parallel.pipeline import (pipeline_apply,
                                               shard_stage_params)

    cpus = jax.devices("cpu")
    mesh = Mesh(np.asarray(cpus[:4]), ("pipe",))
    W = np.zeros((4, 4, 4), np.float32)
    params = shard_stage_params({"w": W}, mesh)
    with pytest.raises(ValueError,
                       match="nearest valid n_microbatches is 4"):
        pipeline_apply(lambda p, h: h @ p["w"], params,
                       jnp.zeros((16, 4)), mesh, n_microbatches=5)


def test_stage_dim_error_mentions_virtual_slices():
    """With virtual_stages > 1 the stage-dim validator must explain the
    S*V expectation — '6 != 4' alone would send the user hunting."""
    from horovod_tpu.parallel.pipeline import shard_stage_params

    cpus = jax.devices("cpu")
    mesh = Mesh(np.asarray(cpus[:4]), ("pipe",))
    with pytest.raises(ValueError, match="virtual slices"):
        shard_stage_params({"w": np.zeros((6, 4, 4), np.float32)}, mesh,
                           virtual_stages=2)


def test_zb_single_stage_falls_back_and_stays_correct():
    """S=1 can't split the backward (nothing to overlap) — zb must fall
    back to the fused 1F1B path, count the fallback when metrics are on,
    and still produce the exact sequential loss/grads."""
    from horovod_tpu.observability import metrics
    from horovod_tpu.parallel.pipeline import (make_pipeline_value_and_grad,
                                               shard_stage_params)

    D, B = 8, 16
    cpus = jax.devices("cpu")
    mesh = Mesh(np.asarray(cpus[:1]), ("pipe",))
    rng = np.random.default_rng(9)
    W = (rng.normal(size=(1, D, D)).astype(np.float32) / np.sqrt(D))
    x = rng.normal(size=(B, D)).astype(np.float32)
    y = np.roll(x, 1, axis=1) * 0.5

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    def loss_fn(out, batch):
        return jnp.mean((out - batch["y"]) ** 2)

    was_enabled = metrics.enabled()
    metrics.enable()
    try:
        vg = make_pipeline_value_and_grad(stage_fn, loss_fn, mesh,
                                          n_microbatches=4, schedule="zb")
        snap = metrics.snapshot()["hvd_pipeline_zb_fallbacks_total"]
        reasons = {s["labels"]["reason"]: s["value"]
                   for s in snap["samples"]}
        assert reasons.get("single_stage", 0) >= 1, snap
    finally:
        if not was_enabled:
            metrics.disable()

    assert vg.schedule_label == "1f1b"
    params = shard_stage_params({"w": W}, mesh)
    loss, grads = vg(params, {"x": jnp.asarray(x), "y": jnp.asarray(y)})

    def seq_loss(Wf):
        h = jnp.tanh(jnp.asarray(x) @ Wf[0])
        return jnp.mean((h - jnp.asarray(y)) ** 2)

    ref_loss, ref_grad = jax.value_and_grad(seq_loss)(jnp.asarray(W))
    assert np.isclose(float(loss), float(ref_loss), atol=1e-5)
    assert np.allclose(np.asarray(grads["w"]), np.asarray(ref_grad),
                       atol=1e-4)
