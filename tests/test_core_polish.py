"""Core-polish coverage (VERDICT r1 item #9 + ADVICE #1): NEGOTIATE timeline
phase, stall-inspector disable semantics, bounded single-rank shutdown,
negotiation frame-size sanity cap, and HVD_LOG_LEVEL consumption."""

import json
import os
import socket
import struct
import subprocess
import sys
import time

from .util import WORKERS, _REPO


def _run_job(np_, worker, extra_env=None, timeout=90, controller_port=None):
    """run_local with captured combined output (for stderr assertions)."""
    from horovod_tpu.runner.local import run_local

    env = {"PYTHONPATH": _REPO, "JAX_PLATFORMS": "cpu"}
    env.update(extra_env or {})
    out_path = os.path.join("/tmp", f"job_out_{os.getpid()}_{worker}.log")
    with open(out_path, "w") as f:
        codes = run_local(np_, [sys.executable, os.path.join(WORKERS, worker)],
                          env=env, timeout=timeout, stdout=f,
                          controller_port=controller_port)
    with open(out_path) as f:
        output = f.read()
    os.unlink(out_path)
    return codes, output


def test_timeline_negotiate_phase(tmp_path):
    """The timeline records the QUEUE -> NEGOTIATE_* -> TCP_* lifecycle
    (reference: NEGOTIATE_ALLREDUCE / WAIT_FOR_OTHER_TENSOR_DATA phases in
    docs/timeline.rst)."""
    tl = tmp_path / "tl.json"
    codes, out = _run_job(2, "stall_worker.py",
                          extra_env={"HVD_TIMELINE": str(tl)})
    assert codes == [0, 0], out
    events = json.loads(tl.read_text())
    phases = {e["name"] for e in events if e.get("ph") == "X"}
    assert "QUEUE" in phases, phases
    assert "NEGOTIATE_ALLREDUCE" in phases, phases
    assert "TCP_ALLREDUCE" in phases, phases
    # rank 1 announced ~2.5s late; the coordinator's NEGOTIATE phase for the
    # early rank must span that wait.
    neg = [e for e in events if e["name"] == "NEGOTIATE_ALLREDUCE"]
    assert max(e["dur"] for e in neg) > 1_000_000, neg


def test_stall_warning_fires():
    codes, out = _run_job(2, "stall_worker.py",
                          extra_env={"HVD_STALL_CHECK_TIME_SECONDS": "1"})
    assert codes == [0, 0], out
    assert "potential stall" in out, out
    assert "NOT by ranks [ 1 ]" in out, out


def test_stall_check_disabled():
    """--no-stall-check maps to HVD_STALL_CHECK_TIME_SECONDS=0, which now
    disables the inspector instead of warning every cycle (ADVICE r1 #1)."""
    codes, out = _run_job(2, "stall_worker.py",
                          extra_env={"HVD_STALL_CHECK_TIME_SECONDS": "0"})
    assert codes == [0, 0], out
    assert "potential stall" not in out, out


def test_stall_shutdown_fires_even_with_warnings_disabled():
    """HVD_STALL_SHUTDOWN_TIME_SECONDS aborts a stalled job with
    HorovodInternalError, and silencing warnings with
    HVD_STALL_CHECK_TIME_SECONDS=0 does NOT disable the explicitly
    configured shutdown threshold (ADVICE r2 #3)."""
    codes, out = _run_job(2, "stall_shutdown_worker.py",
                          extra_env={"HVD_STALL_CHECK_TIME_SECONDS": "0",
                                     "HVD_STALL_SHUTDOWN_TIME_SECONDS": "1"},
                          timeout=60)
    assert codes == [0, 0], out
    assert "HorovodInternalError as expected" in out, out
    assert "potential stall" not in out, out  # warnings stayed silenced


def test_cache_capacity_mismatch_reconciled():
    """Per-rank HVD_CACHE_CAPACITY disagreement is reconciled during the
    mesh handshake (rank 0 authoritative) instead of silently
    desynchronizing replica bit positions once eviction starts
    (ADVICE r2 #5)."""
    codes, out = _run_job(2, "cache_mismatch_worker.py", timeout=60)
    assert codes == [0, 0], out
    assert "HVD_CACHE_CAPACITY mismatch" in out, out


def test_single_rank_shutdown_does_not_hang():
    codes, out = _run_job(2, "early_shutdown_worker.py",
                          extra_env={"HVD_SHUTDOWN_TIMEOUT": "2"},
                          timeout=60)
    assert codes == [0, 0], out
    assert "HorovodInternalError as expected" in out, out


def test_profiler_op_ranges_and_trace_window(tmp_path):
    """Profiler parity (reference: nvtx_op_range.h → TPU xplane mapping,
    SURVEY §5): with HVD_PROFILER=1 collectives run inside TraceAnnotation
    ranges, start/stop opens a trace window, and the xplane artifact is
    written. Off by default: op_range is a shared no-op context."""
    from horovod_tpu import profiler

    assert not profiler.enabled()
    import contextlib

    assert isinstance(profiler.op_range("x"), contextlib.nullcontext)

    codes, out = _run_job(2, "profiler_worker.py",
                          extra_env={"HVD_PROFILER": "1",
                                     "PROFILE_DIR": str(tmp_path)})
    assert codes == [0, 0], out
    assert out.count("OK") == 2, out


def test_log_level_consumed():
    """HVD_LOG_LEVEL=info surfaces core init/shutdown logs; the default
    (warn) keeps them silent (reference: logging.cc HOROVOD_LOG_LEVEL)."""
    codes, out = _run_job(2, "stall_worker.py",
                          extra_env={"HVD_LOG_LEVEL": "info"})
    assert codes == [0, 0], out
    assert "[hvd info]" in out and "init: size=2" in out, out

    codes, out = _run_job(2, "stall_worker.py", extra_env={})
    assert codes == [0, 0], out
    assert "[hvd info]" not in out, out


def test_frame_size_sanity_cap():
    """A hostile/corrupt peer announcing a huge frame length must not OOM
    the coordinator. Since the resilient-rendezvous change (VERDICT r4
    weak #6) the hostile connection is DROPPED (CheckFrameLen throws, the
    accept loop closes the socket and keeps going) and the real job
    completes — previously the cap surfaced as an init failure."""
    port = _free_port()
    rogue_done = {}

    def rogue():
        # Dial the controller like a worker would, then claim a 3 GiB
        # frame. The coordinator must close the connection on us.
        deadline = time.time() + 10
        s = None
        while time.time() < deadline:
            try:
                s = socket.create_connection(("127.0.0.1", port), timeout=1)
                break
            except OSError:
                time.sleep(0.05)
        assert s is not None, "controller never listened"
        s.sendall(struct.pack("<I", 3 << 30))
        s.settimeout(20)
        try:
            rogue_done["closed"] = s.recv(1) == b""
        except OSError:
            rogue_done["closed"] = True  # reset also proves the drop
        s.close()

    import threading
    t = threading.Thread(target=rogue)
    t.start()
    # Explicit empty secret: auth off, so the rogue's frame-length claim
    # reaches RecvFrame (the cap under test) rather than the auth gate.
    codes, out = _run_job(2, "auth_worker.py",
                          extra_env={"AUTH_RANK1_DELAY": "4",
                                     "HVD_RENDEZVOUS_SECRET": ""},
                          timeout=90, controller_port=port)
    t.join(timeout=30)
    assert codes == [0, 0], out
    assert rogue_done.get("closed"), "coordinator never dropped the rogue"


def test_unauthenticated_connect_refused():
    """csrc/auth.cc (VERDICT r4 weak #7): with a job secret in the
    environment, every negotiated socket demands an HMAC-SHA256
    challenge-response on connect. A connector without the secret is
    refused (socket closed after a bad MAC) and the job completes
    undisturbed. This exceeds the reference: its Gloo pairs accept raw
    connects."""
    import secrets as pysecrets
    import threading

    port = _free_port()
    secret = pysecrets.token_hex(16)
    rogue_state = {}

    def rogue():
        deadline = time.time() + 10
        s = None
        while time.time() < deadline:
            try:
                s = socket.create_connection(("127.0.0.1", port), timeout=1)
                break
            except OSError:
                time.sleep(0.05)
        assert s is not None, "controller never listened"
        s.settimeout(20)
        try:
            challenge = b""
            while len(challenge) < 16:
                chunk = s.recv(16 - len(challenge))
                if not chunk:
                    break
                challenge += chunk
            rogue_state["challenged"] = len(challenge) == 16
            s.sendall(b"\x00" * 32)  # a MAC we cannot compute
            rogue_state["refused"] = s.recv(1) == b""
        except OSError:
            rogue_state["refused"] = True
        s.close()

    t = threading.Thread(target=rogue)
    t.start()
    codes, out = _run_job(
        2, "auth_worker.py",
        extra_env={"HVD_RENDEZVOUS_SECRET": secret,
                   "AUTH_RANK1_DELAY": "4"},
        timeout=90, controller_port=port)
    t.join(timeout=30)
    assert codes == [0, 0], out
    assert rogue_state.get("challenged"), "no challenge was issued"
    assert rogue_state.get("refused"), \
        "coordinator accepted an unauthenticated peer"


def test_silent_rogue_does_not_wedge_rendezvous():
    """A half-open connection that never sends a byte must not wedge the
    single-threaded accept loop: the handshake recv is bounded
    (Socket::SetRecvTimeout in EstablishMesh), after which the rogue is
    dropped and the real worker registers."""
    import threading

    import secrets as pysecrets

    port = _free_port()
    state = {}

    def rogue():
        deadline = time.time() + 10
        s = None
        while time.time() < deadline:
            try:
                s = socket.create_connection(("127.0.0.1", port), timeout=1)
                break
            except OSError:
                time.sleep(0.05)
        assert s is not None, "controller never listened"
        # Say nothing. The coordinator must give up on us by itself.
        s.settimeout(30)
        try:
            while s.recv(64):
                pass  # drain the challenge; still never answer
            state["dropped"] = True
        except OSError:
            state["dropped"] = True
        s.close()

    t = threading.Thread(target=rogue)
    t.start()
    codes, out = _run_job(
        2, "auth_worker.py",
        extra_env={"HVD_RENDEZVOUS_SECRET": pysecrets.token_hex(16),
                   "AUTH_RANK1_DELAY": "4"},
        timeout=90, controller_port=port)
    t.join(timeout=40)
    assert codes == [0, 0], out
    assert state.get("dropped"), "coordinator never dropped the silent peer"


def test_hmac_matches_hashlib():
    """Known-answer check of the core's hand-rolled HMAC-SHA256
    (csrc/auth.cc) against Python's hashlib — a SHA that merely
    self-agrees across ranks would still pass the handshake tests."""
    import ctypes
    import hashlib
    import hmac as pyhmac

    lib = ctypes.CDLL(os.path.join(_REPO, "horovod_tpu", "lib",
                                   "libhvd_tpu.so"))
    fn = lib.hvd_hmac_sha256
    fn.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
                   ctypes.c_int, ctypes.c_char_p]
    cases = [(b"k", b"m"), (b"x" * 65, b"data" * 100), (b"", b""),
             (bytes(range(32)), bytes(range(256)) * 3),
             (b"secret", b"a" * 55), (b"secret", b"a" * 56),
             (b"secret", b"a" * 64)]
    for key, msg in cases:
        out = ctypes.create_string_buffer(32)
        fn(key, len(key), msg, len(msg), out)
        want = pyhmac.new(key, msg, hashlib.sha256).digest()
        assert out.raw == want, (key, msg)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_dynamic_timeline(tmp_path):
    """start_timeline/stop_timeline at runtime (reference:
    horovod_start_timeline): traced window captured with cycle marks,
    untraced ops absent, restartable into a second file, error on double
    start / stop-before-start."""
    from .util import run_worker_job

    run_worker_job(2, "timeline_worker.py",
                   extra_env={"TL_PATH": str(tmp_path / "tl.json")})
