"""The sharded state plane (ISSUE 15; docs/checkpoint.md): per-rank
shard writes, two-barrier atomic commit, restore-with-reshard across
world sizes, torn-checkpoint loudness, and the legacy orbax read path.

These update the PR 7 pins: fully-addressable sharded leaves now
round-trip their sharding, and a cross-process sharded save — which the
orbax-backed revision pinned as raising loudly — now SUCCEEDS with each
rank writing only its own addressable shards (tp_ckpt_worker.py asserts
both rank-side). The kill-the-writer-mid-save crash cell lives in
tests/test_chaos.py next to the rest of the fault matrix.
"""

import pytest

pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")

from .util import run_single, run_worker_job


def test_tp_sharded_save_roundtrips_sharding(tmp_path):
    """Single process, 8-device model axis: restore into a numpy like
    assembles the full array; restore into a sharded like round-trips
    the TP layout (the degenerate N == M reshard)."""
    run_worker_job(1, "tp_ckpt_worker.py", timeout=180, extra_env={
        "CKPT_MODE": "local",
        "CKPT_DIR": str(tmp_path / "ck"),
    })


def test_cross_process_sharded_save_succeeds(tmp_path):
    """Model axis spanning 2 processes: the non-fully-addressable case
    the orbax revision refused — now each rank writes its own shards and
    restore hands them back bit-exact with no full-array gather."""
    run_worker_job(2, "tp_ckpt_worker.py", timeout=240, jax_coord=True,
                   extra_env={
                       "CKPT_MODE": "global",
                       "CKPT_DIR": str(tmp_path / "ck"),
                   })


def _reshard(tmp_path, n, m):
    """Save at world size n, restore at world size m, same 8-device CPU
    mesh both times (so the per-process shard boundaries really move)."""
    ckdir = str(tmp_path / "ck")
    run_worker_job(n, "reshard_ckpt_worker.py", timeout=240,
                   jax_coord=n > 1,
                   extra_env={"CKPT_PHASE": "save", "CKPT_DIR": ckdir})
    run_worker_job(m, "reshard_ckpt_worker.py", timeout=240,
                   jax_coord=m > 1,
                   extra_env={"CKPT_PHASE": "restore", "CKPT_DIR": ckdir})


@pytest.mark.parametrize("n,m", [(1, 4), (4, 1)])
def test_restore_with_reshard(tmp_path, n, m):
    """The headline elastic resize, both directions: N writer processes,
    M reader processes, bit-exact across mixed dtypes, TP-sharded AND
    replicated leaves (reshard_ckpt_worker.py asserts rank-side)."""
    _reshard(tmp_path, n, m)


@pytest.mark.slow
@pytest.mark.parametrize("n,m", [(2, 4), (4, 2), (1, 2), (2, 1)])
def test_restore_with_reshard_matrix(tmp_path, n, m):
    """The rest of the {1,2,4} -> {4,2,1} resize matrix."""
    _reshard(tmp_path, n, m)


def test_torn_checkpoint_fails_loudly(tmp_path):
    """Truncated manifest, wrong format tag, bit-flipped shard, missing
    rank dir, tree mismatch: every corruption raises a CheckpointError
    naming the offending piece (torn_ckpt_worker.py)."""
    run_single("torn_ckpt_worker.py", timeout=180, extra_env={
        "CKPT_DIR": str(tmp_path / "ck"),
        "JAX_PLATFORMS": "cpu",
    })


def test_orbax_backcompat_restore(tmp_path):
    """Checkpoints written by the pre-sharded orbax revisions still
    resolve and restore; a sharded save alongside shadows them as
    latest (legacy_ckpt_worker.py)."""
    run_single("legacy_ckpt_worker.py", timeout=240, extra_env={
        "CKPT_DIR": str(tmp_path / "ck"),
        "JAX_PLATFORMS": "cpu",
    })


def test_latest_step_ignores_uncommitted(tmp_path):
    """latest_step resolves only COMMITTED steps: ``.tmp`` staging dirs,
    bare integer dirs without a commit marker, non-integer names, and
    plain files are all ignored; the sharded MANIFEST.json and both
    legacy orbax ``_METADATA`` placements count."""
    from horovod_tpu import checkpoint

    d = tmp_path / "ck"
    (d / "7.tmp" / "rank_0").mkdir(parents=True)  # crashed writer staging
    (d / "5").mkdir()                             # no commit marker
    (d / "junk").mkdir()                          # non-integer name
    (d / "8").write_text("x")                     # a FILE, not a step dir
    (d / "3").mkdir()
    (d / "3" / "MANIFEST.json").write_text("{}")
    assert checkpoint.latest_step(d) == 3
    (d / "4").mkdir()
    (d / "4" / "_METADATA").write_text("")        # legacy orbax
    assert checkpoint.latest_step(d) == 4
    (d / "6" / "default").mkdir(parents=True)
    (d / "6" / "default" / "_METADATA").write_text("")  # older nesting
    assert checkpoint.latest_step(d) == 6
    assert checkpoint.latest_step(d / "absent") is None
