"""Pins for hvd.checkpoint.save with TP-sharded train state (ISSUE 8
satellite / ROADMAP item 5 prep): what the orbax-backed save/restore
actually does today, BEFORE any sharded-checkpoint refactor.

Today's contract (tp_ckpt_worker.py asserts it rank-side):

- Fully-addressable sharded leaves (model axis within one process) are
  gathered by the root's host pull and written as FULL arrays; restore
  hands back plain replicated numpy — sharding is not round-tripped.
- Non-fully-addressable leaves (model axis spanning processes) make
  save raise on the root before anything hits disk — a loud failure,
  not a silently-wrong partial checkpoint.
"""

import pytest

pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")

from .util import run_worker_job


def test_tp_sharded_save_gathers_full_arrays(tmp_path):
    run_worker_job(1, "tp_ckpt_worker.py", timeout=180, extra_env={
        "CKPT_MODE": "local",
        "CKPT_DIR": str(tmp_path / "ck"),
    })


def test_cross_process_sharded_save_fails_loudly(tmp_path):
    run_worker_job(2, "tp_ckpt_worker.py", timeout=240, jax_coord=True,
                   extra_env={
                       "CKPT_MODE": "global",
                       "CKPT_DIR": str(tmp_path / "ck"),
                   })
