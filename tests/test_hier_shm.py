"""Hierarchical host plane (ISSUE 7): the intra-host shared-memory
transport (csrc/shm.cc), the reduce worker pool (csrc/reduce.h,
HVD_REDUCE_THREADS), and hierarchical allreduce riding both.

Every multi-rank case drives workers/hier_shm_worker.py, which runs the
full parity sweep (all dtypes, Sum/Min/Max/Average, fused pair, odd
length, tiny fallback, one pool-sized tensor) and grades the shm/pool
counters — shm ops/bytes must move exactly when expected and the
staged-copy counter must stay 0 (the pointer-handoff proof).
"""
import pytest

import horovod_tpu as hvd

from .util import run_worker_job


def test_shm_stats_require_init():
    if hvd.is_initialized():  # pragma: no cover - ordering guard
        pytest.skip("core already initialized in this process")
    with pytest.raises(ValueError):
        hvd.shm_stats()
    with pytest.raises(ValueError):
        hvd.shm_state()


def test_reduce_pool_stats_without_init():
    # The pool is process-global (configured at init, queried any time).
    threads, jobs, spans = hvd.reduce_pool_stats()
    assert threads >= 1
    assert jobs >= 0 and spans >= 0


def test_hier_shm_2rank_timeline(tmp_path):
    """Single-host hierarchical parity; rank 0 checks TCP_SHM_EXCHANGE
    sub-spans land in the core timeline."""
    run_worker_job(2, "hier_shm_worker.py", timeout=300, extra_env={
        "HVD_HIERARCHICAL_ALLREDUCE": "1",
        "EXPECT_SHM": "1",
        "HVD_TIMELINE": str(tmp_path / "shm_tl.json"),
    })


def test_hier_shm_pool_4rank():
    """Single-host hierarchical parity with a 3-lane reduce pool; the
    pool's job/span counters must move on the 8 MiB tensor."""
    run_worker_job(4, "hier_shm_worker.py", timeout=360, extra_env={
        "HVD_HIERARCHICAL_ALLREDUCE": "1",
        "EXPECT_SHM": "1",
        "HVD_REDUCE_THREADS": "3",
        "POOL_EXPECT_JOBS": "1",
    })


@pytest.mark.slow
def test_hier_shm_multihost_8rank():
    """Two fake hosts x 4 local ranks: local phases ride shm, the cross
    ring stays on TCP (worker asserts local TCP bytes < cross bytes)."""
    run_worker_job(8, "hier_shm_worker.py", timeout=480, extra_env={
        "HIER_LOCAL_SIZE": "4",
        "HVD_HIERARCHICAL_ALLREDUCE": "1",
        "EXPECT_SHM": "1",
    })


def test_flat_ring_rides_shm_2rank():
    """Without the hierarchical arm the flat staged ring still routes
    same-host exchanges over the plane."""
    run_worker_job(2, "hier_shm_worker.py", timeout=300, extra_env={
        "EXPECT_SHM": "1",
    })


def test_shm_kill_switch_4rank():
    """HVD_SHM=0: the identical sweep over pure TCP — the plane must not
    even map, and parity must hold bit-for-bit with the shm runs."""
    run_worker_job(4, "hier_shm_worker.py", timeout=360, extra_env={
        "HVD_SHM": "0",
        "HVD_HIERARCHICAL_ALLREDUCE": "1",
        "EXPECT_SHM": "0",
    })


def test_ranks_spanning_hosts_fall_back_2rank():
    """One rank per fake host: no same-host peers, so the plane never
    maps and the hierarchical topology never validates."""
    run_worker_job(2, "hier_shm_worker.py", timeout=300, extra_env={
        "HIER_LOCAL_SIZE": "1",
        "EXPECT_SHM": "0",
    })


def test_shm_threshold_fallback_2rank():
    """A 1 GiB routing threshold declines every message: the fallback
    counter must move while ops stay 0."""
    run_worker_job(2, "hier_shm_worker.py", timeout=300, extra_env={
        "HVD_SHM_THRESHOLD": str(1 << 30),
        "EXPECT_SHM": "0",
        "EXPECT_FALLBACK": "1",
    })


def test_autotune_shm_arm(tmp_path):
    """The shm routing toggle as an autotune categorical arm: on a
    2-rank single-host pod with zerocopy and ring-pipeline pinned off,
    the (cache, hier, shm) probe rows flip each dim once, the bandit
    locks a winner, and ships it in the ResponseList (autotune_worker.py
    asserts the CSV phase walk and the lock)."""
    log = tmp_path / "autotune_shm.csv"
    run_worker_job(2, "autotune_worker.py", extra_env={
        "HVD_AUTOTUNE": "1",
        "HVD_AUTOTUNE_LOG": str(log),
        "HVD_AUTOTUNE_CYCLES_PER_SAMPLE": "4",
        "HVD_AUTOTUNE_MAX_SAMPLES": "12",
        "HVD_ZEROCOPY": "0",
        "HVD_RING_PIPELINE": "1",
        # bucket arm off: covered by test_bucket.py::test_autotune_bucket_arm
        "HVD_BUCKET": "0",
        # wire arm pinned off: covered by test_wire.py::test_autotune_wire_arm
        "HVD_WIRE": "basic",
        # shm active => the alltoall tier arm (ISSUE 19) joins the sweep.
        "EXPECT_DIMS": "4",
    }, timeout=240)
    # The shm column really swept both states (d+1 = 5 probe rows).
    rows = [l for l in log.read_text().splitlines()[1:6]
            if not l.startswith("#")]
    assert {l.split(",")[7] for l in rows} == {"0", "1"}, rows


def test_shm_and_scatter_gather_coexist_2rank():
    """A low zerocopy threshold sends large tensors down the TCP
    scatter-gather ring while small fused cycles still ride shm — both
    transports in one job without cross-talk."""
    run_worker_job(2, "hier_shm_worker.py", timeout=300, extra_env={
        "HVD_ZEROCOPY_THRESHOLD": "16384",
        "EXPECT_SHM": "1",
    })
