"""Pallas flash-attention numerics (interpret mode on CPU): forward and
gradients must match the naive XLA attention that models/transformer.py
uses, causal and non-causal, f32 and bf16 inputs."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

try:  # the kernels target jax >= 0.8's pltpu.CompilerParams API
    from jax.experimental.pallas import tpu as _pltpu
    _HAVE_PALLAS = hasattr(_pltpu, "CompilerParams")
except Exception:  # noqa: BLE001 — any import failure means no pallas
    _HAVE_PALLAS = False

pytestmark = pytest.mark.skipif(
    not _HAVE_PALLAS,
    reason="pltpu.CompilerParams unavailable (jax < 0.8): the pallas "
           "kernels cannot build here")

if _HAVE_PALLAS:
    from horovod_tpu.ops.pallas_attention import flash_attention


def _naive(q, k, v, causal):
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bshk,bthk->bhst",
                        q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, -1)
    return jnp.einsum("bhst,bthk->bshk", probs,
                      v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_naive(causal):
    rng = np.random.default_rng(0)
    B, S, H, D = 2, 256, 3, 64
    q, k, v = (jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
               for _ in range(3))
    out = flash_attention(q, k, v, causal=causal, block=128, interpret=True)
    ref = _naive(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_gradients_match_naive():
    rng = np.random.default_rng(1)
    B, S, H, D = 1, 256, 2, 64
    q, k, v = (jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
               for _ in range(3))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block=128, interpret=True)
        return jnp.sum(jnp.sin(o.astype(jnp.float32)))

    def loss_naive(q, k, v):
        return jnp.sum(jnp.sin(_naive(q, k, v, True).astype(jnp.float32)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_naive = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for gf, gn, name in zip(g_flash, g_naive, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gn),
                                   atol=3e-4, rtol=3e-4,
                                   err_msg=f"d{name} mismatch")


def test_bf16_inputs_and_partial_block():
    rng = np.random.default_rng(2)
    B, S, H, D = 1, 128, 2, 64
    q, k, v = (jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.bfloat16)
               for _ in range(3))
    out = flash_attention(q, k, v, causal=True, block=128, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = _naive(q, k, v, True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2, rtol=3e-2)
    # block > S clamps to S; non-divisible S rejected clearly.
    out2 = flash_attention(q, k, v, causal=True, block=256, interpret=True)
    np.testing.assert_allclose(np.asarray(out2, np.float32),
                               np.asarray(out, np.float32), atol=1e-6)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q[:, :100], k[:, :100], v[:, :100], block=64,
                        interpret=True)


def test_transformer_flash_impl_matches_gather():
    """attn_impl='flash' in the transformer produces the same logits as the
    XLA 'gather' path — single device and on a dp x tp mesh (shard_map)."""
    import dataclasses

    from jax.sharding import Mesh, PartitionSpec as P  # noqa: F401

    from horovod_tpu.models import transformer as tfm

    cfg_g = tfm.tiny()
    cfg_f = dataclasses.replace(cfg_g, attn_impl="flash")
    params = tfm.init_params(jax.random.PRNGKey(0), cfg_g)
    rng = np.random.default_rng(4)
    tokens = jnp.asarray(rng.integers(0, cfg_g.vocab_size, (2, 32)),
                         jnp.int32)
    out_g = tfm.forward(params, tokens, cfg_g)
    out_f = tfm.forward(params, tokens, cfg_f)
    np.testing.assert_allclose(np.asarray(out_g, np.float32),
                               np.asarray(out_f, np.float32),
                               atol=2e-2, rtol=2e-2)

    devs = jax.devices()[:4]
    if len(devs) < 4:  # conftest forces 8 virtual CPU devices in CI
        pytest.skip("needs >=4 devices for the dp x tp shard_map branch")
    mesh = Mesh(np.asarray(devs).reshape(2, 2), ("data", "model"))
    out_m = jax.jit(lambda p, t: tfm.forward(p, t, cfg_f, mesh=mesh))(
        params, tokens)
    np.testing.assert_allclose(np.asarray(out_m, np.float32),
                               np.asarray(out_f, np.float32),
                               atol=2e-2, rtol=2e-2)


def test_strict_mode_and_masked_rows():
    """mode="strict" (q > k, ring striped cross-shard mask): row 0 is
    fully masked and must return o = 0, lse = sentinel, and ZERO
    gradients — the -1e30 sentinel must not cancel in exp(s - m)."""
    from horovod_tpu.ops.pallas_attention import flash_attention_lse

    rng = np.random.default_rng(6)
    B, S, H, D = 1, 128, 2, 32
    q, k, v = (jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
               for _ in range(3))
    o, lse = flash_attention_lse(q, k, v, mode="strict", block=64,
                                 interpret=True)
    # reference: strict lower-triangular mask
    scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("bshk,bthk->bhst", q, k) * scale
    mask = jnp.tril(jnp.ones((S, S), bool), k=-1)
    s = jnp.where(mask[None, None], s, -np.inf)
    w = jax.nn.softmax(s, -1)
    ref = jnp.einsum("bhst,bthk->bshk", jnp.where(jnp.isnan(w), 0, w), v)
    assert np.allclose(np.asarray(o[:, 0]), 0.0), o[:, 0]
    assert np.all(np.asarray(lse[:, :, 0]) < -1e29)
    np.testing.assert_allclose(np.asarray(o[:, 1:]),
                               np.asarray(ref[:, 1:]), atol=2e-5, rtol=2e-5)

    # gradients of a loss touching every row: row 0 contributes nothing.
    g = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention_lse(q, k, v, mode="strict", block=64,
                            interpret=True)[0] ** 2),
        argnums=(0, 1, 2))(q, k, v)
    assert np.allclose(np.asarray(g[0][:, 0]), 0.0), g[0][:, 0]
    assert np.all(np.isfinite(np.asarray(g[1]))) \
        and np.all(np.isfinite(np.asarray(g[2])))


def test_chunked_loss_matches_full():
    """cfg.loss_chunk computes the identical cross-entropy without ever
    materializing the [S, vocab] float32 tensor (value and gradients)."""
    import dataclasses

    from horovod_tpu.models import transformer as tfm

    cfg = tfm.tiny()
    cfg_c = dataclasses.replace(cfg, loss_chunk=8)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(5)
    batch = {"tokens": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (2, 33)), jnp.int32)}
    l_full, g_full = jax.value_and_grad(tfm.loss_fn)(params, batch, cfg)
    l_chunk, g_chunk = jax.value_and_grad(tfm.loss_fn)(params, batch, cfg_c)
    np.testing.assert_allclose(float(l_full), float(l_chunk), rtol=1e-5)
    # bf16 compute: chunked summation reassociates, so grads agree to bf16
    # rounding, not bitwise.
    for a, b in zip(jax.tree.leaves(g_full), jax.tree.leaves(g_chunk)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=1e-3, rtol=1e-2)


def test_flash_under_jit_and_vmapless_shapes():
    """The kernel composes with jit (the transformer uses it inside one)."""
    rng = np.random.default_rng(3)
    B, S, H, D = 2, 128, 2, 64
    q, k, v = (jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
               for _ in range(3))
    f = jax.jit(functools.partial(flash_attention, causal=True,
                                  interpret=True))
    np.testing.assert_allclose(np.asarray(f(q, k, v)),
                               np.asarray(_naive(q, k, v, True)),
                               atol=2e-5, rtol=2e-5)
