"""Sequence/context/expert parallelism tests on the virtual 8-device mesh.

Correctness bar: sharded implementations must match a single-device
reference computed on the gathered arrays.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # the whole parallel package needs jax >= 0.8's jax.shard_map
    from jax import shard_map as _shard_map  # noqa: F401
    _HAVE_SHARD_MAP = True
except ImportError:
    _HAVE_SHARD_MAP = False

pytestmark = pytest.mark.skipif(
    not _HAVE_SHARD_MAP,
    reason="jax.shard_map unavailable (jax < 0.8): "
           "horovod_tpu.parallel cannot import here")

if _HAVE_SHARD_MAP:
    from horovod_tpu.parallel import (
        make_moe_layer,
        make_ring_attention,
        make_ulysses_attention,
    )


def _ref_attention(q, k, v, causal):
    q32, k32, v32 = (np.asarray(t, np.float32) for t in (q, k, v))
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = np.einsum("bqhd,bkhd->bhqk", q32, k32) * scale
    if causal:
        S = s.shape[-1]
        mask = np.tril(np.ones((S, S), bool))
        s = np.where(mask[None, None], s, -np.inf)
    s = s - s.max(-1, keepdims=True)
    w = np.exp(s)
    w = w / w.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", w, v32)


def _qkv(B=2, S=32, H=4, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def seq_mesh():
    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, ("data", "seq"))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(seq_mesh, causal):
    q, k, v = _qkv()
    fn = make_ring_attention(seq_mesh, axis="seq", causal=causal,
                             batch_axis="data")
    out = fn(q, k, v)
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("layout", ["contiguous", "striped"])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_flash_inner_matches_reference(seq_mesh, causal,
                                                      layout):
    """inner="flash" runs the fused pallas kernel per block pair and
    merges partials by log-sum-exp; must equal the dense reference for
    both layouts (striped exercises the kernel's "strict" mode)."""
    q, k, v = _qkv(S=64, D=16, seed=11)
    fn = make_ring_attention(seq_mesh, axis="seq", causal=causal,
                             batch_axis="data", layout=layout,
                             inner="flash")
    out = fn(q, k, v)
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize("layout", ["contiguous", "striped"])
def test_ring_attention_flash_inner_gradients(seq_mesh, layout):
    """Gradients flow through the kernel's custom vjp AND the lse-based
    partial merge (the lse cotangent path): must match the einsum ring.
    striped exercises the "strict" mode backward (masked-row hazard)."""
    q, k, v = _qkv(B=2, S=32, H=2, D=8, seed=12)
    fns = {inner: make_ring_attention(seq_mesh, axis="seq", causal=True,
                                      batch_axis="data", layout=layout,
                                      inner=inner)
           for inner in ("einsum", "flash")}

    grads = {}
    for inner, fn in fns.items():
        grads[inner] = jax.grad(
            lambda q, k, v, fn=fn: jnp.sum(fn(q, k, v) ** 2),
            argnums=(0, 1, 2))(q, k, v)
    for ge, gf, name in zip(grads["einsum"], grads["flash"], "qkv"):
        np.testing.assert_allclose(np.asarray(ge), np.asarray(gf),
                                   rtol=1e-3, atol=1e-3,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_reference(seq_mesh, causal):
    q, k, v = _qkv()
    fn = make_ulysses_attention(seq_mesh, axis="seq", causal=causal,
                                batch_axis="data")
    out = fn(q, k, v)
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_ring_matches_ulysses_long_seq(seq_mesh):
    """Cross-check the two SP schemes against each other at longer S."""
    q, k, v = _qkv(B=2, S=128, H=8, D=16, seed=3)
    ring = make_ring_attention(seq_mesh, axis="seq", batch_axis="data")
    uly = make_ulysses_attention(seq_mesh, axis="seq", batch_axis="data")
    np.testing.assert_allclose(np.asarray(ring(q, k, v)),
                               np.asarray(uly(q, k, v)),
                               rtol=2e-4, atol=2e-4)


def test_moe_dispatch_matches_dense():
    """alltoall dispatch/combine == dense one-hot routing when capacity is
    generous (no drops)."""
    rng = np.random.default_rng(7)
    E, D, F, T = 8, 16, 32, 64          # tokens per expert shard
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("expert",))
    w_in = jnp.asarray(rng.standard_normal((E, D, F)) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.standard_normal((E, F, D)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((8 * T, D)), jnp.float32)
    logits = jnp.asarray(rng.standard_normal((8 * T, E)), jnp.float32)

    layer = make_moe_layer(mesh, "expert", w_in, w_out,
                           capacity_factor=float(E))  # capacity = T: no drop
    out = layer(x, logits)

    # dense reference: every token through its argmax expert, gate-weighted
    probs = jax.nn.softmax(np.asarray(logits, np.float32), axis=-1)
    eidx = np.argmax(probs, -1)
    gate = probs[np.arange(len(eidx)), eidx]
    h = np.einsum("td,edf->tef", np.asarray(x), np.asarray(w_in))
    h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
    y = np.einsum("tef,efd->ted", h, np.asarray(w_out))
    ref = y[np.arange(len(eidx)), eidx] * gate[:, None]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)


def test_moe_drops_overflow():
    """With capacity 1 and all tokens routed to one expert, all but one
    token per shard-queue are dropped (output zeros)."""
    E, D, T = 8, 4, 16
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("expert",))
    w_in = jnp.zeros((E, D, D), jnp.float32) + jnp.eye(D)
    w_out = jnp.zeros((E, D, D), jnp.float32) + jnp.eye(D)
    x = jnp.ones((8 * T, D), jnp.float32)
    logits = jnp.zeros((8 * T, E), jnp.float32).at[:, 0].set(10.0)
    layer = make_moe_layer(mesh, "expert", w_in, w_out, capacity_factor=0.0)
    out = np.asarray(layer(x, logits))
    # capacity clamps to >=1: exactly one token per shard survives
    nonzero_rows = (np.abs(out).sum(-1) > 1e-6).sum()
    assert nonzero_rows == 8, nonzero_rows


def test_ragged_alltoall_uneven_splits():
    """ragged_alltoall (the ICI alltoallv — VERDICT r3 #7): every shard
    sends a DIFFERENT number of rows to each peer; receivers must see
    exactly the sent rows, tagged with correct counts, zero-padded."""
    import functools

    from jax import shard_map

    from horovod_tpu.ops.jax_ops import ragged_alltoall

    Pn, D, cap = 8, 4, 6
    mesh = Mesh(np.asarray(jax.devices()[:Pn]), ("x",))
    # shard i sends (i + j) % 4 rows to peer j; row values encode
    # (src, dst, slot) so the receiver can verify provenance exactly.
    counts = np.array([[(i + j) % 4 for j in range(Pn)]
                      for i in range(Pn)], np.int32)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=(),
                       out_specs=(P("x", None, None, None), P("x", None)),
                       check_vma=False)
    def go():
        i = jax.lax.axis_index("x")
        my_counts = jnp.asarray(counts)[i]                       # [P]
        starts = jnp.cumsum(my_counts) - my_counts
        T = int(counts.sum(1).max())
        row = jnp.arange(T, dtype=jnp.int32)
        # destination of each row under the grouped layout
        dst = jnp.sum((row[:, None] >= (starts + my_counts)[None, :])
                      .astype(jnp.int32), axis=1)
        slot = row - starts[dst]
        x = (i * 10000 + dst * 100 + slot).astype(jnp.float32)[:, None] \
            * jnp.ones((1, D), jnp.float32)
        recv, rcounts = ragged_alltoall(x, my_counts, "x", cap)
        return recv[None], rcounts[None]

    recv, rcounts = go()
    recv, rcounts = np.asarray(recv), np.asarray(rcounts)
    for dst in range(Pn):
        for src in range(Pn):
            n = counts[src, dst]
            assert rcounts[dst, src] == n, (dst, src, rcounts[dst])
            for s in range(cap):
                expect = (src * 10000 + dst * 100 + s) if s < n else 0.0
                np.testing.assert_allclose(
                    recv[dst, src, s], expect,
                    err_msg=f"dst={dst} src={src} slot={s}")


def test_ragged_alltoall_overflow_truncates_cleanly():
    """Counts EXCEEDING ``capacity`` (ISSUE 19 satellite): the sender
    ships only the first ``capacity`` rows of an overflowing block,
    recv_counts clamp to ``capacity`` (never point past the drop), and
    the overflow must not corrupt adjacent (src, dst) slots — every
    non-overflowing block still arrives byte-exact, padding stays zero."""
    import functools

    from jax import shard_map

    from horovod_tpu.ops.jax_ops import ragged_alltoall

    Pn, D, cap = 8, 4, 2
    mesh = Mesh(np.asarray(jax.devices()[:Pn]), ("x",))
    # Counts 0..4 against cap=2: pairs with (i + 2j) % 5 > 2 overflow.
    counts = np.array([[(i + 2 * j) % 5 for j in range(Pn)]
                      for i in range(Pn)], np.int32)
    assert (counts > cap).any() and (counts <= cap).any()

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=(),
                       out_specs=(P("x", None, None, None), P("x", None)),
                       check_vma=False)
    def go():
        i = jax.lax.axis_index("x")
        my_counts = jnp.asarray(counts)[i]                       # [P]
        starts = jnp.cumsum(my_counts) - my_counts
        T = int(counts.sum(1).max())
        row = jnp.arange(T, dtype=jnp.int32)
        dst = jnp.sum((row[:, None] >= (starts + my_counts)[None, :])
                      .astype(jnp.int32), axis=1)
        slot = row - starts[dst]
        x = (i * 10000 + dst * 100 + slot).astype(jnp.float32)[:, None] \
            * jnp.ones((1, D), jnp.float32)
        recv, rcounts = ragged_alltoall(x, my_counts, "x", cap)
        return recv[None], rcounts[None]

    recv, rcounts = go()
    recv, rcounts = np.asarray(recv), np.asarray(rcounts)
    for dst in range(Pn):
        for src in range(Pn):
            n = min(int(counts[src, dst]), cap)
            # clamp contract: counts never exceed the slots that exist
            assert rcounts[dst, src] == n, (dst, src, rcounts[dst])
            for s in range(cap):
                expect = (src * 10000 + dst * 100 + s) if s < n else 0.0
                np.testing.assert_allclose(
                    recv[dst, src, s], expect,
                    err_msg=f"dst={dst} src={src} slot={s}")


def _ragged_moe_fn(mesh, axis, **kw):
    """Jitted sharded ragged-MoE layer taking (x, logits, w_in, w_out) as
    traced arguments — usable both for forward parity and for
    differentiating w.r.t. the weights."""
    import functools

    from jax import shard_map

    from horovod_tpu.parallel import moe_dispatch_combine_ragged

    espec = P(axis, None, None)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), espec, espec),
        out_specs=P(axis, None), check_vma=False)
    def fn(x, logits, w_in_l, w_out_l):
        def expert_fn(buf):
            h = jnp.einsum("end,edf->enf", buf.astype(jnp.float32),
                           w_in_l.astype(jnp.float32))
            h = jax.nn.gelu(h)
            return jnp.einsum("enf,efd->end", h,
                              w_out_l.astype(jnp.float32)).astype(buf.dtype)

        out, _ = moe_dispatch_combine_ragged(x, logits, expert_fn, axis,
                                             **kw)
        return out

    return fn


def _ragged_moe_layer(mesh, axis, w_in, w_out, **kw):
    fn = _ragged_moe_fn(mesh, axis, **kw)
    return lambda x, logits: fn(x, logits, w_in, w_out)


def test_make_moe_layer_ragged_flag_matches_dense():
    """make_moe_layer(ragged=True) — the bench's entry point to the
    alltoallv wire format — agrees with the dense-slot layer when
    capacity is generous (same routing, same experts, different wire)."""
    rng = np.random.default_rng(13)
    E, D, F, T = 8, 16, 32, 64
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("expert",))
    w_in = jnp.asarray(rng.standard_normal((E, D, F)) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.standard_normal((E, F, D)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((8 * T, D)), jnp.float32)
    logits = jnp.asarray(rng.standard_normal((8 * T, E)), jnp.float32)

    dense = make_moe_layer(mesh, "expert", w_in, w_out,
                           capacity_factor=float(E))
    ragged = make_moe_layer(mesh, "expert", w_in, w_out,
                            capacity_factor=float(E), ragged=True)
    np.testing.assert_allclose(np.asarray(ragged(x, logits)),
                               np.asarray(dense(x, logits)),
                               rtol=2e-3, atol=2e-3)


def test_moe_ragged_matches_dense():
    """Ragged (wire-following) dispatch == dense one-hot routing when
    capacities are lossless — including under IMBALANCED routing."""
    rng = np.random.default_rng(11)
    E, D, F, T = 8, 16, 32, 64
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("expert",))
    w_in = jnp.asarray(rng.standard_normal((E, D, F)) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.standard_normal((E, F, D)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((8 * T, D)), jnp.float32)
    # skewed router: expert 0 drawn ~6x more often than the rest
    logits_np = rng.standard_normal((8 * T, E)).astype(np.float32)
    logits_np[:, 0] += 1.5
    logits = jnp.asarray(logits_np)

    layer = _ragged_moe_layer(mesh, "expert", w_in, w_out,
                              peer_capacity=T, expert_capacity=8 * T)
    out = layer(x, logits)

    probs = jax.nn.softmax(np.asarray(logits, np.float32), axis=-1)
    eidx = np.argmax(probs, -1)
    gate = probs[np.arange(len(eidx)), eidx]
    h = np.einsum("td,edf->tef", np.asarray(x), np.asarray(w_in))
    h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
    y = np.einsum("tef,efd->ted", h, np.asarray(w_out))
    ref = y[np.arange(len(eidx)), eidx] * gate[:, None]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)


def test_moe_ragged_gradients_match_dense():
    """Training flows through the ragged dispatch: grads of the sharded
    ragged MoE layer w.r.t. x and the expert weights == grads of the
    dense single-device reference (lossless capacities)."""
    rng = np.random.default_rng(13)
    E, D, F, T = 8, 8, 16, 32
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("expert",))
    w_in = jnp.asarray(rng.standard_normal((E, D, F)) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.standard_normal((E, F, D)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((8 * T, D)), jnp.float32)
    logits_np = rng.standard_normal((8 * T, E)).astype(np.float32)
    logits_np[:, 0] += 1.0  # imbalanced routing
    logits = jnp.asarray(logits_np)

    ragged = _ragged_moe_fn(mesh, "expert", peer_capacity=T,
                            expert_capacity=8 * T)

    def dense(x, logits, w_in, w_out):
        probs = jax.nn.softmax(logits, axis=-1)
        gate = jnp.max(probs, axis=-1)
        eidx = jnp.argmax(probs, axis=-1)
        h = jnp.einsum("td,edf->tef", x, w_in)
        h = jax.nn.gelu(h)
        y = jnp.einsum("tef,efd->ted", h, w_out)
        sel = jnp.take_along_axis(
            y, eidx[:, None, None].repeat(D, axis=2), axis=1)[:, 0]
        return sel * gate[:, None]

    w = jnp.asarray(rng.standard_normal((8 * T, D)), jnp.float32)

    def loss_ragged(x, w_in, w_out):
        return jnp.sum(ragged(x, logits, w_in, w_out) * w)

    def loss_dense(x, w_in, w_out):
        return jnp.sum(dense(x, logits, w_in, w_out) * w)

    gr = jax.grad(loss_ragged, (0, 1, 2))(x, w_in, w_out)
    gd = jax.grad(loss_dense, (0, 1, 2))(x, w_in, w_out)
    for a, b, n in zip(gr, gd, ("x", "w_in", "w_out")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"d{n} mismatch")


def test_moe_ragged_drops_overflow():
    """peer_capacity=1 with every token routed to shard 0's expert:
    exactly one token per source shard survives; dropped outputs are 0."""
    E, D, T = 8, 4, 16
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("expert",))
    eye = jnp.zeros((E, D, D), jnp.float32) + jnp.eye(D)
    x = jnp.ones((8 * T, D), jnp.float32)
    logits = jnp.zeros((8 * T, E), jnp.float32).at[:, 0].set(10.0)
    layer = _ragged_moe_layer(mesh, "expert", eye, eye,
                              peer_capacity=1, expert_capacity=16)
    out = np.asarray(layer(x, logits))
    nonzero_rows = (np.abs(out).sum(-1) > 1e-6).sum()
    assert nonzero_rows == 8, nonzero_rows


def test_ring_attention_gradients(seq_mesh):
    """Training must differentiate through the ring (scan + ppermute):
    grads of sharded ring attention == grads of the dense reference."""
    q, k, v = _qkv(B=2, S=16, H=2, D=4, seed=9)
    fn = make_ring_attention(seq_mesh, axis="seq", causal=True,
                             batch_axis="data")

    def loss_ring(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_ref(q, k, v):
        scale = 1.0 / np.sqrt(q.shape[-1])
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        S = s.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", w, v)
        return jnp.sum(out ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_striped_ring_attention_matches_reference(seq_mesh, causal):
    """layout='striped' (zig-zag): equal causal work per device; results
    must be identical to the dense reference on contiguous sequences
    (stripe/unstripe happen inside the wrapper)."""
    q, k, v = _qkv(seed=5)
    fn = make_ring_attention(seq_mesh, axis="seq", causal=causal,
                             batch_axis="data", layout="striped")
    out = fn(q, k, v)
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_striped_ring_attention_gradients(seq_mesh):
    """Gradients must flow through stripe -> ring -> unstripe identically
    to the contiguous path."""
    q, k, v = _qkv(B=2, S=32, H=2, D=8, seed=9)
    contig = make_ring_attention(seq_mesh, axis="seq", causal=True,
                                 batch_axis="data")
    striped = make_ring_attention(seq_mesh, axis="seq", causal=True,
                                  batch_axis="data", layout="striped")

    def loss(fn):
        return lambda a, b, c: jnp.sum(fn(a, b, c) ** 2)

    g_c = jax.grad(loss(contig), argnums=(0, 1, 2))(q, k, v)
    g_s = jax.grad(loss(striped), argnums=(0, 1, 2))(q, k, v)
    for gc, gs in zip(g_c, g_s):
        np.testing.assert_allclose(np.asarray(gc), np.asarray(gs),
                                   rtol=2e-4, atol=2e-4)


def test_stripe_unstripe_roundtrip():
    from horovod_tpu.parallel import (
        stripe_sequence,
        unstripe_sequence,
    )

    x = jnp.arange(2 * 12 * 3, dtype=jnp.float32).reshape(2, 12, 3)
    y = stripe_sequence(x, 4)
    # shard 0 of 4 (rows 0:3 of striped order) holds positions {0, 4, 8}
    np.testing.assert_array_equal(np.asarray(y[:, :3]),
                                  np.asarray(x[:, [0, 4, 8]]))
    np.testing.assert_array_equal(np.asarray(unstripe_sequence(y, 4)),
                                  np.asarray(x))
