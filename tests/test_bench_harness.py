"""The wedge-proof bench harness itself (VERDICT r4 #1 — the round-4
record was lost to a TPU hang that outlived the driver's timeout, so the
harness's survival properties need direct coverage):

- a config that hangs is SIGKILLed at its sub-deadline and becomes an
  explicit error line while every other config still measures and the
  final cumulative line lands last;
- a wedged relay probe produces the explicit error + the cached numbers
  from bench_cache.json instead of consuming the driver budget.

Both use bench.py's _BENCH_TEST_HANG injection hooks; configs run on the
CPU smoke path so the whole file is device-independent.
"""
import json
import os
import subprocess
import sys

import pytest

from .util import _REPO
from .util import have_shard_map

BENCH = os.path.join(_REPO, "bench.py")


def _run_bench(extra_env, timeout):
    from .util import tpu_isolated_env

    env = dict(os.environ)
    env.update(tpu_isolated_env())  # the one children-off-the-TPU policy
    env.update({k: str(v) for k, v in extra_env.items()})
    p = subprocess.run([sys.executable, BENCH], env=env,
                       capture_output=True, text=True, timeout=timeout)
    lines = [json.loads(ln) for ln in p.stdout.splitlines()
             if ln.strip().startswith("{")]
    return p, lines


@pytest.mark.skipif(not have_shard_map(), reason="jax.shard_map unavailable (jax < 0.8): the graded moe bench config cannot import horovod_tpu.parallel here")
def test_hung_config_is_killed_and_rest_still_measure():
    """transformer hangs forever; the parent must kill it at the (tiny)
    sub-deadline, emit its error line in sequence, and still deliver
    resnet50 + the remaining configs + the final cumulative line."""
    # Outer timeout must EXCEED the bench's own deadline — on a slow box
    # the graceful skip path needs its full budget before we'd SIGKILL.
    p, lines = _run_bench(
        {"_BENCH_TEST_HANG": "transformer",
         "BENCH_CAP_TRANSFORMER": "8",
         # elastic sheds its optional fault-matrix jobs under a tight
         # sub-budget; the headline recovery job alone proves the config.
         "BENCH_CAP_ELASTIC": "75",
         # 540 + the bucket config's 90 s cap + the pipeline config's
         # 150 s cap (both A/Bs are seconds warm; the headroom is for a
         # cold cache on a loaded box).
         "BENCH_DEADLINE": "780",
         # keep the CPU smoke run quick
         "HVD_BENCH_BATCH": "8"},
        timeout=850)
    assert p.returncode == 0, p.stderr[-2000:]
    by_metric = {d["metric"]: d for d in lines}
    tr = by_metric["bert_large_scale_train_throughput"]
    assert "sub-deadline" in tr.get("error", ""), tr
    rn = by_metric["resnet50_synthetic_train_throughput"]
    assert rn["value"] > 0, rn
    # Final cumulative line is LAST and carries the same error inside
    # extra, so the driver's tail always holds the newest full picture.
    final = lines[-1]
    assert "extra" in final, final
    assert "sub-deadline" in final["extra"]["transformer"].get("error", "")
    assert final["extra"]["hostplane"]["value"] > 0, final["extra"]
    # The BASELINE graded configs added in round 5 ride the same record:
    # MoE dispatch throughput and measured elastic recovery.
    assert final["extra"]["moe"]["value"] > 0, final["extra"]
    assert final["extra"]["elastic"]["value"] > 0, final["extra"]


def test_wedged_probe_emits_cached_fallback(tmp_path):
    """probe hang = the real round-4 failure mode. The bench must print
    ONE line: explicit error + the last recorded numbers from the cache,
    well inside the budget. A temp BENCH_CACHE_PATH is seeded so the
    assertion is deterministic and the repo's real record is untouched."""
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps(
        {"metric": "resnet50_synthetic_train_throughput", "value": 1234.5,
         "unit": "images/sec/chip", "vs_baseline": 0.16,
         "cached_note": "seeded by test"}))
    p, lines = _run_bench(
        {"_BENCH_TEST_HANG": "probe",
         "BENCH_PROBE_TIMEOUT": "6",
         "BENCH_CACHE_PATH": str(cache),
         "BENCH_DEADLINE": "120"},
        timeout=110)
    assert p.returncode == 0, p.stderr[-2000:]
    assert len(lines) == 1, lines
    d = lines[0]
    assert "relay wedged" in d.get("error", ""), d
    assert d.get("cached") is True, d
    assert d["value"] == 1234.5, d
    assert d["vs_baseline"] == 0.16, d
