"""Autotune v2 (ISSUE 18): bandit arm search + persisted workload-keyed
tuning profiles.

The sim tests drive the REAL in-core search policy (csrc/autotune.cc)
through the `AutotuneSim` harness — a caller-supplied score surface and
a fake clock, no pod — which makes an exhaustive 2^8 ground-truth
enumeration affordable. They pin the two acceptance headlines:

  * the bandit locks within 5% of the exhaustive best using <= 25% of
    the samples exhaustive enumeration needs, and
  * a persisted profile is adopted by an identical second job with ZERO
    sweep samples (mismatches seed priors, corrupt files fall back with
    a counted reason).

The pod test proves the same round-trip end-to-end: two sequential fake
pods share a profile dir; the second locks via the ResponseList wire
without sweeping.
"""
import itertools
import os

import pytest

from .util import run_worker_job

from horovod_tpu.basics import AutotuneSim
from horovod_tpu.observability import autotune_csv


# Deterministic synthetic score surface over the full 8-dim lattice:
# multiplicative per-dim effects plus pairwise interactions, so the best
# arm is NOT the greedy composition of the single-toggle winners and the
# halving rounds have real work to do.
_WEIGHTS = (1.30, 0.85, 1.15, 1.05, 0.92, 1.22, 0.80, 1.10)
_INTERACTIONS = {(0, 5): 1.06, (2, 3): 0.95, (1, 4): 1.04}


def _surface(arm):
    score = 100.0
    for i, w in enumerate(_WEIGHTS):
        if arm >> i & 1:
            score *= w
    for (a, b), w in _INTERACTIONS.items():
        if arm >> a & 1 and arm >> b & 1:
            score *= w
    return score


_EXHAUSTIVE_BEST = max(_surface(a) for a in range(256))


def test_bandit_within_5pct_in_25pct_samples(tmp_path):
    """The acceptance headline: on the full 256-arm surface the bandit's
    locked arm scores within 5% of the exhaustive best while measuring
    <= 25% of the 256 windows exhaustive enumeration costs."""
    sim = AutotuneSim(n_dims=8)
    try:
        locked_arm = sim.run(_surface)
        stats = sim.stats()
        locked, arm, fusion, cycle = sim.result()
    finally:
        sim.close()
    assert locked and arm == locked_arm, (locked, arm, locked_arm)
    assert stats["dims"] == 8 and stats["arms"] == 256, stats
    assert stats["samples"] == stats["budget"], stats
    assert stats["samples"] <= 256 * 0.25, stats
    gap = 1.0 - _surface(arm) / _EXHAUSTIVE_BEST
    assert gap <= 0.05, (bin(arm), gap, stats)
    assert fusion > 0 and cycle > 0, (fusion, cycle)


def test_bandit_budget_derivation():
    """Auto budget = (d+1) probes + (2B-2) halving + GP tail, derived
    from the dim count instead of the old MAX_SAMPLES=80 hardcode; an
    explicit cap shrinks the bracket to fit and is honored exactly."""
    sim = AutotuneSim(n_dims=8)
    try:
        auto = sim.stats()["budget"]
    finally:
        sim.close()
    assert 9 + 2 < auto <= 64, auto  # probes + a real bracket, yet <=25%
    sim = AutotuneSim(n_dims=8, max_samples=20)
    try:
        sim.run(_surface)
        stats = sim.stats()
    finally:
        sim.close()
    assert stats["budget"] == 20 and stats["samples"] == 20, stats


# The ninth dim (alltoall tiering, ISSUE 19): same multiplicative
# surface extended by one bit so the 512-arm lattice has a distinct
# exhaustive best the bandit must still approach within budget.
_WEIGHTS9 = _WEIGHTS + (1.18,)


def _surface9(arm):
    score = 100.0
    for i, w in enumerate(_WEIGHTS9):
        if arm >> i & 1:
            score *= w
    for (a, b), w in _INTERACTIONS.items():
        if arm >> a & 1 and arm >> b & 1:
            score *= w
    return score


_EXHAUSTIVE_BEST9 = max(_surface9(a) for a in range(512))


def test_bandit_scales_to_ninth_dim():
    """ISSUE 19 acceptance: with the alltoall tier as the ninth bit the
    lattice doubles to 512 arms, the auto budget grows with d (it is
    derived, not hardcoded), and the bandit still locks within 5% of the
    exhaustive best while spending <= 25% of exhaustive enumeration."""
    sim = AutotuneSim(n_dims=8)
    try:
        budget8 = sim.stats()["budget"]
    finally:
        sim.close()
    sim = AutotuneSim(n_dims=9)
    try:
        arm = sim.run(_surface9)
        stats = sim.stats()
    finally:
        sim.close()
    assert stats["dims"] == 9 and stats["arms"] == 512, stats
    assert stats["budget"] > budget8, (stats["budget"], budget8)
    assert stats["samples"] == stats["budget"] <= 512 * 0.25, stats
    gap = 1.0 - _surface9(arm) / _EXHAUSTIVE_BEST9
    assert gap <= 0.05, (bin(arm), gap, stats)


def test_profile_round_trip_adopts_with_zero_samples(tmp_path):
    """Job A converges and persists; identical job B adopts the profile
    with ZERO sweep samples and lands on the same configuration."""
    d = str(tmp_path)
    sim = AutotuneSim(n_dims=8, profile_dir=d, workload_id=7, world=4)
    try:
        sim.run(_surface)
        a_stats = sim.stats()
        _, a_arm, a_fusion, a_cycle = sim.result()
    finally:
        sim.close()
    assert a_stats["profile"] == "fresh", a_stats
    profiles = [f for f in os.listdir(d) if f.startswith("hvdtune-")]
    assert len(profiles) == 1 and profiles[0].endswith(".profile"), profiles
    assert "-w4-" in profiles[0], profiles

    sim = AutotuneSim(n_dims=8, profile_dir=d, workload_id=7, world=4)
    try:
        b_arm = sim.run(_surface)
        b_stats = sim.stats()
        b_locked, _, b_fusion, b_cycle = sim.result()
    finally:
        sim.close()
    assert b_locked, b_stats
    assert b_stats["profile"] == "adopted" and b_stats["adopted_profile"], \
        b_stats
    assert b_stats["samples"] == 0, b_stats  # the acceptance headline
    # cycle_ms round-trips through the profile's text serialization, so
    # compare it with float tolerance rather than bit-exactly.
    assert (b_arm, b_fusion) == (a_arm, a_fusion), \
        ((b_arm, b_fusion), (a_arm, a_fusion))
    assert b_cycle == pytest.approx(a_cycle, rel=1e-5), (b_cycle, a_cycle)


def test_profile_mismatch_refuses_but_seeds_priors(tmp_path):
    """A different workload on the same topology must NOT blind-adopt:
    the near-miss profile seeds the bracket priors and the numeric start
    point, and the search still runs its full budget."""
    d = str(tmp_path)
    sim = AutotuneSim(n_dims=8, profile_dir=d, workload_id=7, world=4)
    try:
        sim.run(_surface)
    finally:
        sim.close()
    sim = AutotuneSim(n_dims=8, profile_dir=d, workload_id=99, world=4)
    try:
        arm = sim.run(_surface)
        stats = sim.stats()
    finally:
        sim.close()
    assert stats["profile"] == "near" and stats["prior_seeded"], stats
    assert not stats["adopted_profile"], stats
    assert stats["samples"] == stats["budget"] > 0, stats
    assert 1.0 - _surface(arm) / _EXHAUSTIVE_BEST <= 0.05, bin(arm)
    # A different topology is not even a near-miss: fresh search.
    sim = AutotuneSim(n_dims=8, profile_dir=d, workload_id=7, world=8)
    try:
        sim.step(_surface(sim.arm))
        stats = sim.stats()
    finally:
        sim.close()
    assert stats["profile"] == "fresh" and not stats["prior_seeded"], stats


def test_profile_torn_or_corrupt_falls_back_counted(tmp_path):
    """An exact-key profile that fails its CRC must never be adopted:
    the job counts the reason (profile=corrupt) and searches fresh."""
    d = str(tmp_path)
    sim = AutotuneSim(n_dims=8, profile_dir=d, workload_id=7, world=4)
    try:
        sim.run(_surface)
        _, good_arm, _, _ = sim.result()
    finally:
        sim.close()
    (name,) = os.listdir(d)
    path = os.path.join(d, name)
    body = open(path, "rb").read()
    # Torn write: truncate mid-file (the atomic rename protocol should
    # make this impossible, but a crashed writer or a bad disk can't be
    # allowed to poison the next job either way).
    with open(path, "wb") as f:
        f.write(body[: len(body) // 2])
    sim = AutotuneSim(n_dims=8, profile_dir=d, workload_id=7, world=4)
    try:
        arm = sim.run(_surface)
        stats = sim.stats()
    finally:
        sim.close()
    assert stats["profile"] == "corrupt", stats
    assert not stats["adopted_profile"] and not stats["prior_seeded"], stats
    assert stats["samples"] == stats["budget"] > 0, stats
    assert arm == good_arm, (bin(arm), bin(good_arm))  # still finds it
    # Bit-rot (CRC mismatch on a full-length file) counts the same way.
    with open(path, "wb") as f:
        f.write(body.replace(b"arm", b"brm", 1))
    sim = AutotuneSim(n_dims=8, profile_dir=d, workload_id=7, world=4)
    try:
        sim.step(_surface(sim.arm))
        stats = sim.stats()
    finally:
        sim.close()
    assert stats["profile"] == "corrupt", stats


def test_profile_dir_unset_is_dead_code(tmp_path):
    """Kill switch: with no profile dir the ladder never runs — status
    stays '-' (v1-identical search, no filesystem access)."""
    sim = AutotuneSim(n_dims=8)
    try:
        sim.run(_surface)
        stats = sim.stats()
    finally:
        sim.close()
    assert stats["profile"] == "-", stats
    assert not stats["adopted_profile"] and not stats["prior_seeded"], stats


def test_profile_schema_constants():
    """The shared CSV schema table is internally consistent (every
    consumer slices through it, so pin its shape here)."""
    assert autotune_csv.HEADER.split(",") == list(autotune_csv.COLUMNS)
    assert len(set(autotune_csv.COLUMNS)) == len(autotune_csv.COLUMNS)
    assert autotune_csv.PROFILE_STATES[0] == "-"
    with pytest.raises(ValueError):
        autotune_csv.split_row("too,few,fields")


def test_pod_profile_adoption_round_trip(tmp_path):
    """End-to-end on two sequential fake pods sharing a profile dir: job
    A sweeps (profile=fresh) and persists on convergence; job B adopts
    over the ResponseList wire with zero sweep samples (the worker
    asserts stats, CSV `# adopted` marker, and collective correctness
    throughout)."""
    profiles = tmp_path / "profiles"
    profiles.mkdir()
    env = {
        "HVD_AUTOTUNE": "1",
        "HVD_AUTOTUNE_CYCLES_PER_SAMPLE": "4",
        "HVD_AUTOTUNE_MAX_SAMPLES": "12",
        "HVD_AUTOTUNE_PROFILE_DIR": str(profiles),
        # Two dims (cache x pipeline) keep the tiny budget valid and the
        # run fast; the full lattice is covered by the sim tests above.
        "HVD_ZEROCOPY": "0",
        "HVD_SHM": "0",
        "HVD_BUCKET": "0",
        "HVD_WIRE": "basic",
        "EXPECT_DIMS": "2",
    }
    log_a = tmp_path / "job_a.csv"
    run_worker_job(2, "autotune_worker.py", timeout=240, extra_env=dict(
        env, HVD_AUTOTUNE_LOG=str(log_a), AT_PROFILE_EXPECT="fresh"))
    written = [f for f in os.listdir(profiles) if f.endswith(".profile")]
    assert len(written) == 1, written
    log_b = tmp_path / "job_b.csv"
    run_worker_job(2, "autotune_worker.py", timeout=240, extra_env=dict(
        env, HVD_AUTOTUNE_LOG=str(log_b), AT_PROFILE_EXPECT="adopted"))
    # Job B's log carries the adoption marker and no sweep rows at all
    # (also asserted rank-side; re-checked here against the raw file).
    lines = [l for l in log_b.read_text().splitlines() if l]
    assert lines[0] == autotune_csv.HEADER, lines[:1]
    assert any(l.startswith("# adopted") for l in lines), lines
    assert all(l.startswith("#") for l in lines[1:]), lines[:4]
