"""Data-loader base + Spark store/shim tests (reference:
horovod/data/data_loader_base.py; horovod/spark/common/store.py;
test/single/test_spark.py local-mode pieces — pyspark is absent here, so
run() is tested for its gating only; see README descope note)."""

import time

import pytest

from horovod_tpu.data import AsyncDataLoaderMixin, BaseDataLoader
from horovod_tpu.spark.store import LocalStore, Store


class _RangeLoader(BaseDataLoader):
    def __init__(self, n, fail_at=None, delay=0.0):
        self.n, self.fail_at, self.delay = n, fail_at, delay

    def __len__(self):
        return self.n

    def _iterate(self):
        for i in range(self.n):
            if self.fail_at is not None and i == self.fail_at:
                raise RuntimeError("loader exploded")
            if self.delay:
                time.sleep(self.delay)
            yield i


class _AsyncRangeLoader(AsyncDataLoaderMixin, _RangeLoader):
    pass


def test_base_loader_iterates():
    assert list(_RangeLoader(5)) == [0, 1, 2, 3, 4]
    assert len(_RangeLoader(5)) == 5


def test_async_loader_matches_sync_and_overlaps():
    loader = _AsyncRangeLoader(8, delay=0.01, num_prefetch_batches=4)
    assert list(loader) == list(range(8))
    # sync fallback
    assert list(_AsyncRangeLoader(4, async_loading=False)) == [0, 1, 2, 3]


def test_async_loader_surfaces_producer_error():
    loader = _AsyncRangeLoader(8, fail_at=3)
    got = []
    with pytest.raises(RuntimeError, match="loader exploded"):
        for x in loader:
            got.append(x)
    assert got == [0, 1, 2]


def test_local_store_paths(tmp_path):
    store = Store.create(str(tmp_path / "artifacts"))
    assert isinstance(store, LocalStore)
    ckpt = store.get_checkpoint_path("run1")
    logs = store.get_logs_path("run1")
    assert store.exists(ckpt) and store.exists(logs)
    assert ckpt != logs
    assert store.get_train_data_path() != store.get_val_data_path()
    store.delete(ckpt)
    assert not store.exists(ckpt)


def test_remote_store_schemes_descoped(tmp_path):
    with pytest.raises(NotImplementedError, match="descoped"):
        Store.create("hdfs://nn/path")


def test_spark_run_gated_without_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError:
        import horovod_tpu.spark as hs

        with pytest.raises(ImportError, match="pyspark.*not.*installed"):
            hs.run(lambda: None, num_proc=1)
    else:
        pytest.skip("pyspark present; run() exercised elsewhere")


def test_async_loader_abandoned_consumer_stops_producer():
    """Breaking out of iteration must release the producer thread (it
    must not stay blocked on a full queue holding batches forever)."""
    import threading

    before = threading.active_count()
    loader = _AsyncRangeLoader(1000, num_prefetch_batches=1)
    for i, _ in enumerate(loader):
        if i == 2:
            break
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.02)
    assert threading.active_count() <= before, "producer thread leaked"


def test_local_store_indexed_paths_are_directories(tmp_path):
    store = LocalStore(str(tmp_path))
    p = store.get_train_data_path(0)
    assert store.exists(p)
