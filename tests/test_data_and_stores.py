"""Data-loader base + Spark store/shim tests (reference:
horovod/data/data_loader_base.py; horovod/spark/common/store.py;
test/single/test_spark.py local-mode pieces — pyspark is absent here, so
run() is tested for its gating only; see README descope note)."""

import time

import pytest

from horovod_tpu.data import AsyncDataLoaderMixin, BaseDataLoader
from horovod_tpu.spark.store import LocalStore, Store


class _RangeLoader(BaseDataLoader):
    def __init__(self, n, fail_at=None, delay=0.0):
        self.n, self.fail_at, self.delay = n, fail_at, delay

    def __len__(self):
        return self.n

    def _iterate(self):
        for i in range(self.n):
            if self.fail_at is not None and i == self.fail_at:
                raise RuntimeError("loader exploded")
            if self.delay:
                time.sleep(self.delay)
            yield i


class _AsyncRangeLoader(AsyncDataLoaderMixin, _RangeLoader):
    pass


def test_base_loader_iterates():
    assert list(_RangeLoader(5)) == [0, 1, 2, 3, 4]
    assert len(_RangeLoader(5)) == 5


def test_async_loader_matches_sync_and_overlaps():
    loader = _AsyncRangeLoader(8, delay=0.01, num_prefetch_batches=4)
    assert list(loader) == list(range(8))
    # sync fallback
    assert list(_AsyncRangeLoader(4, async_loading=False)) == [0, 1, 2, 3]


def test_async_loader_surfaces_producer_error():
    loader = _AsyncRangeLoader(8, fail_at=3)
    got = []
    with pytest.raises(RuntimeError, match="loader exploded"):
        for x in loader:
            got.append(x)
    assert got == [0, 1, 2]


def test_local_store_paths(tmp_path):
    store = Store.create(str(tmp_path / "artifacts"))
    assert isinstance(store, LocalStore)
    ckpt = store.get_checkpoint_path("run1")
    logs = store.get_logs_path("run1")
    assert store.exists(ckpt) and store.exists(logs)
    assert ckpt != logs
    assert store.get_train_data_path() != store.get_val_data_path()
    store.delete(ckpt)
    assert not store.exists(ckpt)


def test_remote_store_schemes_route_and_descope(tmp_path):
    """Store.create routes by scheme (reference parity). hdfs/gs/s3 need
    fsspec-family drivers that this zero-egress image lacks, so their
    constructors raise the documented descope error; dbfs:/ is the
    reference's fuse-mount special case and works as a LocalStore."""
    for url in ("hdfs://nn/path", "s3://bucket/path"):
        with pytest.raises(ImportError, match="descope"):
            Store.create(url)
    # gcsfs ships in this image, so the gs:// adapter builds for real
    # (zero egress forbids exercising actual bucket IO in this test, and
    # the store's first makedirs would be a network call — so build the
    # adapter directly and run the store against an injected fs).
    from horovod_tpu.spark.store import (GCSStore, InMemoryFilesystem,
                                         _fsspec_filesystem)

    adapter = _fsspec_filesystem("gs", "gcsfs")
    assert hasattr(adapter, "open") and hasattr(adapter, "makedirs")
    gcs = GCSStore("gs://bucket/path", fs=InMemoryFilesystem())
    assert gcs.get_checkpoint_path("r").startswith("gs://bucket/path")
    from horovod_tpu.spark.store import DBFSLocalStore

    # Path translation only: constructing would mkdir under /dbfs, which
    # doesn't exist in this container.
    assert DBFSLocalStore.translate("dbfs:/ml/store") == "/dbfs/ml/store"
    assert DBFSLocalStore.translate("/dbfs/ml/store") == "/dbfs/ml/store"


def test_filesystem_store_in_memory_conformance(tmp_path):
    """The whole estimator data path — path layout, shard materialization,
    shard reads, checkpoint write/read — must work through the pluggable
    filesystem adapter alone (VERDICT r4 missing #2: remote filesystems
    drop in behind one class). An in-memory adapter proves no bare open()
    sneaks in."""
    import os

    from horovod_tpu.spark.params import EstimatorParams, load_shard
    from horovod_tpu.spark.store import FilesystemStore, InMemoryFilesystem

    fs = InMemoryFilesystem()
    store = FilesystemStore("mem://root", fs)

    # Path layout + IO primitives.
    ckpt = store.get_checkpoint_path("r1")
    with store.open_write(ckpt + "/weights.bin") as f:
        f.write(b"\x01\x02\x03")
    assert store.exists(ckpt + "/weights.bin")
    with store.open_read(ckpt + "/weights.bin") as f:
        assert f.read() == b"\x01\x02\x03"

    # Estimator materialization + shard reads ride the adapter.
    df = _regression_frame()
    p = EstimatorParams(model=object(), loss="mse",
                        feature_cols=["x0", "x1"], label_cols=["y"],
                        validation=0.25, num_proc=2, store=store,
                        run_id="r1", shuffle=False)
    train_path, val_path, n_val = p._materialize(df, "r1")
    assert n_val > 0
    for r in range(2):
        X, Y = load_shard(train_path, r, store)
        assert len(X) == len(Y) > 0
        Xv, Yv = load_shard(val_path, r, store)
        assert len(Xv) == n_val
    # Nothing touched the real filesystem.
    assert not os.path.exists("mem:")

    store.delete(train_path)
    assert not store.exists(train_path + "/shard-0.npz")


def _regression_frame(n=32):
    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, 2)).astype(np.float32)
    df = pd.DataFrame(X, columns=["x0", "x1"])
    df["y"] = X @ np.array([1.0, 2.0], np.float32)
    return df


def test_spark_run_gated_without_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError:
        import horovod_tpu.spark as hs

        with pytest.raises(ImportError, match="pyspark.*not.*installed"):
            hs.run(lambda: None, num_proc=1)
    else:
        pytest.skip("pyspark present; run() exercised elsewhere")


def test_async_loader_abandoned_consumer_stops_producer():
    """Breaking out of iteration must release the producer thread (it
    must not stay blocked on a full queue holding batches forever)."""
    import threading

    before = threading.active_count()
    loader = _AsyncRangeLoader(1000, num_prefetch_batches=1)
    for i, _ in enumerate(loader):
        if i == 2:
            break
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.02)
    assert threading.active_count() <= before, "producer thread leaked"


def test_local_store_indexed_paths_are_directories(tmp_path):
    store = LocalStore(str(tmp_path))
    p = store.get_train_data_path(0)
    assert store.exists(p)
