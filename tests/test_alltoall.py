"""Tiered alltoallv as a first-class core collective (ISSUE 19): the
intra-host shm tier, the SG io_uring linked-wave tier above
HVD_ZEROCOPY_THRESHOLD, the HVD_ALLTOALL kill switch, and the
HVD_ALLTOALL_COMPRESS int8 expert-dispatch wire — parity, counters,
cross-tier bit-identity, and TSAN/lockdep over the new exchange shape.
The autotune arm itself is pinned by test_wire.py (uring-gated) and
test_hier_shm.py (shm-gated).
"""
import json

import pytest

from .util import (assert_sanitizer_clean, run_under_sanitizer,
                   run_worker_job)

# Tier forcing: shm keeps the default plane but routes every size
# through it; sg disables shm so the big op must take the UringDuplex
# path; basic leaves the tiered routing enabled but with nothing to
# ride (HVD_SHM=0 isolation per the test_wire.py pattern).
_TIER_ENV = {
    "basic": {"HVD_SHM": "0", "HVD_WIRE": "basic"},
    "shm": {"HVD_SHM_THRESHOLD": "0", "HVD_WIRE": "basic"},
    "sg": {"HVD_SHM": "0", "HVD_WIRE": "uring",
           "HVD_ZEROCOPY_THRESHOLD": "16384"},
}


def _a2a_env(tier, **extra):
    env = {
        "A2A_MODE": "parity",
        "A2A_EXPECT": tier,
        "HVD_DATA_TIMEOUT_SECONDS": "60",
    }
    env.update(_TIER_ENV[tier])
    env.update(extra)
    return env


@pytest.mark.parametrize("np_", [2, 4,
                                 pytest.param(8, marks=pytest.mark.slow)])
@pytest.mark.parametrize("tier", ["basic", "shm", "sg"])
def test_alltoallv_parity(tier, np_):
    """Every dtype, even + ragged (zero-chunk) splits, and a tier-
    engaging large op: exact provenance on every received chunk and the
    counter deltas the forced tier promises."""
    run_worker_job(np_, "alltoall_worker.py", timeout=240,
                   extra_env=_a2a_env(tier))


def test_tier_digests_bit_identical(tmp_path):
    """Acceptance: the tiers move bytes, they never round. The same
    seeded workload forced onto basic / shm / sg must produce identical
    rank-ordered output digests, while each job's counters prove it
    really took its tier."""
    stats = {}
    for tier in ("basic", "shm", "sg"):
        out = tmp_path / f"{tier}.json"
        run_worker_job(2, "alltoall_worker.py", timeout=240,
                       extra_env=_a2a_env(tier, A2A_STATS_OUT=str(out)))
        stats[tier] = json.loads(out.read_text())
    assert (stats["basic"]["digests"] == stats["shm"]["digests"]
            == stats["sg"]["digests"]), stats
    assert stats["shm"]["shm_ops"] > 0, stats["shm"]
    assert stats["sg"]["sg_rounds"] > 0, stats["sg"]
    assert stats["basic"]["shm_ops"] == 0, stats["basic"]
    assert stats["basic"]["sg_rounds"] == 0, stats["basic"]


def test_alltoall_kill_switch(tmp_path):
    """HVD_ALLTOALL=basic keeps both tier counters at zero even with the
    shm plane mapped and the uring wire up; the worker also asserts
    alltoall_state() reports untiered while parity holds."""
    out = tmp_path / "killswitch.json"
    run_worker_job(2, "alltoall_worker.py", timeout=240, extra_env={
        "A2A_MODE": "parity",
        "A2A_EXPECT": "basic",
        "HVD_ALLTOALL": "basic",
        "HVD_SHM_THRESHOLD": "0",
        "HVD_WIRE": "uring",
        "HVD_ZEROCOPY_THRESHOLD": "16384",
        "HVD_DATA_TIMEOUT_SECONDS": "60",
        "A2A_STATS_OUT": str(out),
    })
    st = json.loads(out.read_text())
    assert st["ops"] > 0, st
    assert st["shm_ops"] == 0 and st["sg_rounds"] == 0, st


@pytest.mark.parametrize("np_", [2, 4])
def test_alltoall_int8_compress(np_):
    """HVD_ALLTOALL_COMPRESS with the int8 codec live: f32 dispatch
    rides 4-byte-scale + int8 wire chunks (>= 3.5x byte reduction per
    compress_stats), ragged splits keep the constant header geometry,
    non-f32 stays bit-exact, parity within one quantization step."""
    run_worker_job(np_, "alltoall_worker.py", timeout=240, extra_env={
        "A2A_MODE": "compress",
        "HVD_COMPRESS": "int8",
        "HVD_ALLTOALL_COMPRESS": "1",
        "HVD_DATA_TIMEOUT_SECONDS": "60",
    })


def test_env_capacity_factor(monkeypatch):
    """HVD_EP_CAPACITY_FACTOR: default 1.25, numeric override honored,
    garbage falls back to the default instead of raising mid-layer."""
    ep = pytest.importorskip("horovod_tpu.parallel.expert_parallel",
                             reason="mesh package needs jax >= 0.8")
    monkeypatch.delenv("HVD_EP_CAPACITY_FACTOR", raising=False)
    assert ep.env_capacity_factor() == 1.25
    monkeypatch.setenv("HVD_EP_CAPACITY_FACTOR", "2.0")
    assert ep.env_capacity_factor() == 2.0
    monkeypatch.setenv("HVD_EP_CAPACITY_FACTOR", "bogus")
    assert ep.env_capacity_factor() == 1.25


def test_report_dispatch_without_core_is_noop():
    """The pure-XLA path has no gauge plane: report_dispatch returns
    False instead of raising when the core is uninitialized."""
    import horovod_tpu as hvd
    ep = pytest.importorskip("horovod_tpu.parallel.expert_parallel",
                             reason="mesh package needs jax >= 0.8")
    if hvd.is_initialized():
        pytest.skip("core initialized in-process by another module")
    assert ep.report_dispatch(0.1, 32) is False


def test_compress_without_codec_stays_uncompressed():
    """The opt-in alone is not enough: with no int8 codec live, Enqueue
    must not stamp compress onto alltoalls — the uncompressed parity
    worker runs clean with the flag set."""
    run_worker_job(2, "alltoall_worker.py", timeout=240,
                   extra_env=_a2a_env("shm", HVD_ALLTOALL_COMPRESS="1"))


# --- sanitizers over the new exchange shapes --------------------------------
# The shm pointer-handoff loop and the SG linked-wave rung both move
# background-thread state the ring collectives never exercised in this
# pairwise shape; run the full parity worker under each (test_wire.py
# pattern — HVD_SHM=0 isolation on the wire tier).

def test_alltoall_sg_tsan(tmp_path):
    p, reports = run_under_sanitizer(
        tmp_path, "alltoall_worker.py", 2, tier="tsan",
        extra_env=_a2a_env("sg", A2A_N="262144"))
    assert_sanitizer_clean(p, 2, reports, "tsan")


def test_alltoall_shm_lockdep(tmp_path):
    p, reports = run_under_sanitizer(
        tmp_path, "alltoall_worker.py", 2, tier="debug",
        extra_env=_a2a_env("shm"))
    assert_sanitizer_clean(p, 2, reports, "lockdep")
