"""Estimator layer (reference: horovod/spark/keras/estimator.py,
horovod/spark/torch/estimator.py + common/store.py): fit(df) materializes
shards to the store, trains num_proc negotiated local ranks data-parallel,
rank 0 checkpoints to the store, and the returned model transforms a
DataFrame by appending prediction columns."""

import os

import numpy as np
import pandas as pd
import pytest

from horovod_tpu.spark.store import LocalStore


def _regression_df(n=256, d=4, seed=0):
    """y = X @ w with a fixed w — learnable to near-zero loss by a linear
    model, so convergence is a real signal the distributed training ran."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = np.arange(1, d + 1, dtype=np.float32)
    y = X @ w
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(d)])
    df["y"] = y
    return df


def test_torch_estimator_end_to_end(tmp_path):
    import torch

    from horovod_tpu.spark.torch import TorchEstimator, TorchModel

    df = _regression_df()
    store = LocalStore(tmp_path / "store")
    model = torch.nn.Linear(4, 1)
    est = TorchEstimator(
        model=model, optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
        loss=torch.nn.MSELoss(), feature_cols=["x0", "x1", "x2", "x3"],
        label_cols=["y"], batch_size=32, epochs=8, validation=0.2,
        num_proc=2, store=store, run_id="t1", timeout=300)
    fitted = est.fit(df)

    # Trained: rank-averaged loss decreased by orders of magnitude, and the
    # learned weights recover w = [1, 2, 3, 4].
    assert fitted.history[-1] < fitted.history[0] * 0.05, fitted.history
    assert fitted.val_loss is not None and fitted.val_loss < 0.1
    w = fitted.model.weight.detach().numpy().ravel()
    assert np.allclose(w, [1, 2, 3, 4], atol=0.2), w

    # transform appends the output column.
    out = fitted.transform(df.head(16))
    assert "y__output" in out.columns
    assert np.allclose(out["y__output"], out["y"], atol=1.0)

    # Rank 0 checkpointed to the store; load() rebuilds the same model.
    ckpt = store.get_checkpoint_path("t1")
    assert os.path.exists(os.path.join(ckpt, "model.pt"))
    reloaded = TorchModel.load(torch.nn.Linear(4, 1), ckpt,
                               ["x0", "x1", "x2", "x3"], ["y"])
    out2 = reloaded.transform(df.head(16))
    assert np.allclose(out2["y__output"], out["y__output"])


def test_torch_estimator_uneven_rows_and_fresh_run_id(tmp_path):
    """65 rows / 2 ranks / batch 32 would give ranks different step counts
    without equal-shard materialization (gradient-allreduce deadlock); and a
    second fit() must mint a fresh run_id instead of overwriting the first
    run's checkpoint."""
    import torch

    from horovod_tpu.spark.torch import TorchEstimator

    df = _regression_df(n=65)
    model = torch.nn.Linear(4, 1)
    est = TorchEstimator(
        model=model, optimizer=torch.optim.SGD(model.parameters(), lr=0.05),
        loss=torch.nn.MSELoss(), feature_cols=["x0", "x1", "x2", "x3"],
        label_cols=["y"], batch_size=32, epochs=2, num_proc=2,
        store=LocalStore(tmp_path / "store"), timeout=300)
    m1 = est.fit(df)
    m2 = est.fit(df)
    assert m1.checkpoint_path != m2.checkpoint_path
    assert os.path.exists(os.path.join(m1.checkpoint_path, "model.pt"))
    assert os.path.exists(os.path.join(m2.checkpoint_path, "model.pt"))


def test_keras_estimator_end_to_end(tmp_path):
    tf = pytest.importorskip("tensorflow")

    from horovod_tpu.spark.keras import KerasEstimator, KerasModel

    df = _regression_df()
    store = LocalStore(tmp_path / "store")
    model = tf.keras.Sequential(
        [tf.keras.Input(shape=(4,)), tf.keras.layers.Dense(1)])
    est = KerasEstimator(
        model=model, optimizer=tf.keras.optimizers.SGD(0.1), loss="mse",
        feature_cols=["x0", "x1", "x2", "x3"], label_cols=["y"],
        batch_size=32, epochs=8, validation=0.2, num_proc=2, store=store,
        run_id="k1", timeout=300)
    fitted = est.fit(df)

    hist = fitted.history["loss"]
    assert hist[-1] < hist[0] * 0.05, hist
    assert fitted.val_scores and fitted.val_scores[0] < 0.1

    out = fitted.transform(df.head(16))
    assert "y__output" in out.columns
    assert np.allclose(out["y__output"], out["y"], atol=1.0)

    ckpt = store.get_checkpoint_path("k1")
    assert os.path.exists(os.path.join(ckpt, "model_weights.npz"))
    reloaded = KerasModel.load(fitted.model_json, ckpt,
                               ["x0", "x1", "x2", "x3"], ["y"])
    out2 = reloaded.transform(df.head(16))
    assert np.allclose(out2["y__output"], out["y__output"], atol=1e-5)


def test_materialize_validation_column_and_errors(tmp_path):
    from horovod_tpu.spark.params import EstimatorParams, load_shard

    df = _regression_df(n=64)
    df["is_val"] = (np.arange(64) % 4 == 0)
    p = EstimatorParams(model=object(), loss="mse",
                        feature_cols=["x0", "x1", "x2", "x3"],
                        label_cols=["y"], validation="is_val", num_proc=2,
                        store=LocalStore(tmp_path / "s"), run_id="m1",
                        shuffle=False)
    train_path, val_path, n_val = p._materialize(df, "m1")
    assert n_val == 8  # per-rank val rows
    rows = [len(load_shard(train_path, r)[0]) for r in range(2)]
    vrows = [len(load_shard(val_path, r)[0]) for r in range(2)]
    # Equal shards per rank (uneven remainders dropped): unequal row counts
    # would desynchronize the per-batch gradient allreduce.
    assert rows == [24, 24] and vrows == [8, 8]

    # Fewer val rows than ranks -> val is empty on EVERY rank (all-or-none,
    # so workers can gate the val metric_average on their own shard).
    p3 = EstimatorParams(model=object(), loss="mse",
                         feature_cols=["x0", "x1", "x2", "x3"],
                         label_cols=["y"], validation=0.01, num_proc=2,
                         store=LocalStore(tmp_path / "s3"), run_id="m3")
    _, vp3, nv3 = p3._materialize(df, "m3")
    assert nv3 == 0
    assert all(len(load_shard(vp3, r)[0]) == 0 for r in range(2))

    with pytest.raises(ValueError, match="columns not in DataFrame"):
        p2 = EstimatorParams(model=object(), feature_cols=["nope"],
                             label_cols=["y"], store=LocalStore(tmp_path))
        p2._materialize(df, "m2")

    with pytest.raises(TypeError, match="DataFrame"):
        from horovod_tpu.spark.params import _as_pandas

        _as_pandas([1, 2, 3])


def test_lightning_estimator_end_to_end(tmp_path):
    """LightningEstimator (reference: horovod/spark/lightning/estimator.py)
    drives a LightningModule-protocol module end-to-end: the module owns
    its loss (training_step) and optimizer (configure_optimizers); the
    estimator trains it data-parallel via the torch binding. The module
    comes from the pytorch_lightning conformance shim, subclassed exactly
    as user code subclasses pl.LightningModule."""
    import sys

    import torch

    shims = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "shims")
    sys.path.insert(0, shims)
    try:
        import pytorch_lightning as pl
    finally:
        sys.path.remove(shims)

    from horovod_tpu.spark.lightning import (LightningEstimator,
                                             LightningModel)

    class LinReg(pl.LightningModule):
        def __init__(self):
            super().__init__()
            self.lin = torch.nn.Linear(4, 1)

        def forward(self, x):
            return self.lin(x)

        def training_step(self, batch, batch_idx):
            x, y = batch
            loss = torch.nn.functional.mse_loss(self(x), y)
            self.log("train_loss", loss)
            return {"loss": loss}

        def validation_step(self, batch, batch_idx):
            x, y = batch
            return torch.nn.functional.mse_loss(self(x), y)

        def configure_optimizers(self):
            return torch.optim.SGD(self.parameters(), lr=0.1)

    from horovod_tpu.spark.params import LocalBackend

    class _ShimPathBackend(LocalBackend):
        """Worker ranks must also see the pytorch_lightning shim: the
        pickled module's base class is resolved by import at unpickle
        time (exactly as a real pl.LightningModule would need the real
        library installed on workers)."""

        def run(self, fn, args, num_proc, env, timeout):
            repo = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            env = dict(env)
            env["PYTHONPATH"] = os.pathsep.join((repo, shims))
            return super().run(fn, args, num_proc, env, timeout)

    df = _regression_df()
    store = LocalStore(tmp_path / "store")
    est = LightningEstimator(
        model=LinReg(), feature_cols=["x0", "x1", "x2", "x3"],
        label_cols=["y"], batch_size=32, epochs=8, validation=0.2,
        num_proc=2, store=store, run_id="l1", timeout=300,
        backend=_ShimPathBackend())
    fitted = est.fit(df)

    assert fitted.history[-1] < fitted.history[0] * 0.05, fitted.history
    assert fitted.val_loss is not None and fitted.val_loss < 0.1
    w = fitted.model.lin.weight.detach().numpy().ravel()
    assert np.allclose(w, [1, 2, 3, 4], atol=0.2), w

    out = fitted.transform(df.head(16))
    assert "y__output" in out.columns
    assert np.allclose(out["y__output"], out["y"], atol=1.0)

    ckpt = store.get_checkpoint_path("l1")
    assert os.path.exists(os.path.join(ckpt, "module.pt"))
    reloaded = LightningModel.load(LinReg(), ckpt,
                                   ["x0", "x1", "x2", "x3"], ["y"])
    out2 = reloaded.transform(df.head(16))
    assert np.allclose(out2["y__output"], out["y__output"])


def test_lightning_estimator_protocol_validation():
    """A model without the LightningModule core protocol is rejected with
    a message naming the missing hook; multi-optimizer modules are
    rejected at optimizer normalization."""
    import torch

    from horovod_tpu.spark.lightning import (LightningEstimator,
                                             _first_optimizer)

    est = LightningEstimator(model=torch.nn.Linear(2, 1),
                             feature_cols=["a"], label_cols=["b"])
    with pytest.raises(ValueError, match="training_step"):
        est._check_params()

    lin = torch.nn.Linear(2, 1)
    o1 = torch.optim.SGD(lin.parameters(), lr=0.1)
    o2 = torch.optim.SGD(lin.parameters(), lr=0.2)
    with pytest.raises(ValueError, match="multi-optimizer"):
        _first_optimizer([o1, o2])
    opt, sched = _first_optimizer({"optimizer": o1})
    assert opt is o1 and sched is None
    sch = torch.optim.lr_scheduler.StepLR(o1, step_size=1)
    opt, sched = _first_optimizer(([o1], [sch]))
    assert opt is o1 and sched is sch


def test_lightning_scheduler_config_dict_and_process_local_store_guard():
    """configure_optimizers may return Lightning's lr_scheduler CONFIG
    dict — only the scheduler inside is stepped; and an estimator fed a
    process-local (in-memory) store must refuse up front rather than
    silently discarding rank-0's checkpoint in a pickled fs copy."""
    import torch

    from horovod_tpu.spark.lightning import _first_optimizer
    from horovod_tpu.spark.params import EstimatorParams
    from horovod_tpu.spark.store import FilesystemStore, InMemoryFilesystem

    lin = torch.nn.Linear(2, 1)
    o = torch.optim.SGD(lin.parameters(), lr=0.1)
    sch = torch.optim.lr_scheduler.StepLR(o, step_size=1)
    opt, sched = _first_optimizer(
        {"optimizer": o,
         "lr_scheduler": {"scheduler": sch, "interval": "epoch"}})
    assert opt is o and sched is sch

    p = EstimatorParams(model=object(), loss="mse", feature_cols=["a"],
                        label_cols=["b"],
                        store=FilesystemStore("mem://x",
                                              InMemoryFilesystem()))
    with pytest.raises(ValueError, match="process-local"):
        p._prepare_store()
