"""In-mesh (SPMD) collective + DP train-step tests on the 8-device virtual
CPU mesh (SURVEY.md §4: the 'fake pod')."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

try:  # the mesh layer needs jax >= 0.8's jax.shard_map (PR 13 gate)
    from jax import shard_map  # noqa: E402
    _HAVE_SHARD_MAP = True
except ImportError:
    _HAVE_SHARD_MAP = False

pytestmark = pytest.mark.skipif(
    not _HAVE_SHARD_MAP,
    reason="jax.shard_map unavailable (jax < 0.8): "
           "horovod_tpu.parallel cannot import here")

if _HAVE_SHARD_MAP:
    from horovod_tpu.ops import jax_ops  # noqa: E402
    from horovod_tpu.parallel import create_mesh, make_train_step  # noqa: E402
    from horovod_tpu.parallel.data_parallel import (  # noqa: E402
        replicate, shard_batch)


@pytest.fixture(scope="module")
def mesh():
    # The session may expose a real TPU platform too; the test pod is the
    # 8-device virtual CPU backend (conftest sets the XLA flag).
    cpus = jax.devices("cpu")
    assert len(cpus) == 8, cpus
    return create_mesh({"data": 8}, devices=cpus)


def _smap(mesh, fn, in_spec, out_spec):
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_spec,
                             out_specs=out_spec, check_vma=False))


def test_allreduce_mean_sum(mesh):
    x = jnp.arange(8.0)

    out = _smap(mesh, lambda a: jax_ops.allreduce(a, "data", jax_ops.Sum),
                P("data"), P("data"))(x)
    assert np.allclose(out, np.full(8, x.sum()))

    out = _smap(mesh, lambda a: jax_ops.allreduce(a, "data", jax_ops.Average),
                P("data"), P("data"))(x)
    assert np.allclose(out, np.full(8, x.mean()))


def test_allgather(mesh):
    x = jnp.arange(16.0).reshape(8, 2)
    out = _smap(mesh, lambda a: jax_ops.allgather(a, "data"),
                P("data"), P("data"))(x)
    # Each shard gathers the full array; with out_spec P('data') the global
    # result is 8 stacked copies of rows.
    assert out.shape == (64, 2)
    got = np.asarray(out).reshape(8, 8, 2)
    exp = np.broadcast_to(np.arange(16.0).reshape(8, 2), (8, 8, 2))
    assert np.allclose(got, exp)


def test_broadcast(mesh):
    x = jnp.arange(8.0)
    out = _smap(mesh, lambda a: jax_ops.broadcast(a, "data", root_index=3),
                P("data"), P("data"))(x)
    assert np.allclose(out, np.full(8, 3.0))


def test_alltoall(mesh):
    # 8 shards each with 8 rows -> transpose blocks.
    x = jnp.arange(64.0).reshape(64, 1)
    out = _smap(mesh, lambda a: jax_ops.alltoall(a, "data"),
                P("data"), P("data"))(x)
    assert out.shape == (64, 1)
    got = np.asarray(out).reshape(8, 8)
    exp = np.arange(64).reshape(8, 8).T
    assert np.allclose(got, exp)


def test_reducescatter(mesh):
    # Global (64, 4) -> per-shard (8, 4) -> scattered to (1, 4) per shard.
    x = jnp.ones((64, 4))
    out = _smap(mesh, lambda a: jax_ops.reducescatter(a, "data", jax_ops.Sum),
                P("data"), P("data"))(x)
    assert out.shape == (8, 4)
    assert np.allclose(out, 8.0)


def test_dp_train_step_matches_single_device(mesh):
    """The sharded step must be numerically identical to the single-device
    step on the full batch (allreduce-mean == full-batch gradient)."""

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.normal(size=(4, 1)).astype(np.float32)),
              "b": jnp.zeros((1,), jnp.float32)}
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = rng.normal(size=(32, 1)).astype(np.float32)
    tx = optax.sgd(0.1)

    # Single-device reference.
    def ref_step(p, o, b):
        loss, g = jax.value_and_grad(loss_fn)(p, b)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    p1, o1, l1 = ref_step(params, tx.init(params), (x, y))

    # Sharded step.
    step = make_train_step(loss_fn, tx, mesh)
    p = replicate(params, mesh)
    o = replicate(tx.init(params), mesh)
    batch = shard_batch((x, y), mesh)
    p2, o2, l2 = step(p, o, batch)

    assert np.allclose(float(l1), float(l2), rtol=1e-5)
    assert np.allclose(np.asarray(p1["w"]), np.asarray(p2["w"]), rtol=1e-5)
    assert np.allclose(np.asarray(p1["b"]), np.asarray(p2["b"]), rtol=1e-5)


def test_train_step_loss_decreases(mesh):
    def loss_fn(params, batch):
        x, y = batch
        h = jnp.tanh(x @ params["w1"])
        pred = h @ params["w2"]
        return jnp.mean((pred - y) ** 2)

    rng = np.random.default_rng(1)
    params = {
        "w1": jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32) * 0.3),
        "w2": jnp.asarray(rng.normal(size=(16, 1)).astype(np.float32) * 0.3),
    }
    tx = optax.adam(1e-2)
    step = make_train_step(loss_fn, tx, mesh)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = (x[:, :1] * 2.0).astype(np.float32)

    p = replicate(params, mesh)
    o = replicate(tx.init(params), mesh)
    batch = shard_batch((x, y), mesh)
    losses = []
    for _ in range(20):
        p, o, loss = step(p, o, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses


def test_dp_train_step_gradient_accumulation(mesh):
    """accum_steps (the compiled-path backward_passes_per_step, VERDICT r2
    weak #7): microbatched scan accumulation must produce the SAME params
    as the full-shard step for a mean-type loss, and reject indivisible
    batches at trace time."""

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] - y) ** 2)

    rng = np.random.default_rng(1)
    params = {"w": jnp.asarray(rng.normal(size=(4, 1)).astype(np.float32))}
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = rng.normal(size=(32, 1)).astype(np.float32)
    tx = optax.sgd(0.1)

    # donate=False: this test reuses the same replicated inputs across
    # step variants, and replicate() of an already-placed array can alias
    # the buffer a donated call would delete.
    full = make_train_step(loss_fn, tx, mesh, donate=False)
    accum = make_train_step(loss_fn, tx, mesh, accum_steps=4, donate=False)
    p0 = replicate(params, mesh)
    o0 = replicate(tx.init(params), mesh)
    batch = shard_batch((x, y), mesh)
    p1, _, l1 = full(p0, o0, batch)
    p2, _, l2 = accum(replicate(params, mesh),
                      replicate(tx.init(params), mesh), batch)
    assert np.allclose(float(l1), float(l2), rtol=1e-5)
    assert np.allclose(np.asarray(p1["w"]), np.asarray(p2["w"]), rtol=1e-5)

    bad = make_train_step(loss_fn, tx, mesh, accum_steps=3, donate=False)
    with pytest.raises(ValueError, match="divisible"):
        bad(replicate(params, mesh), replicate(tx.init(params), mesh),
            batch)

    with pytest.raises(ValueError, match="accum_steps"):
        make_train_step(loss_fn, tx, mesh, accum_steps=0)


def test_adasum_device_plane_matches_vhdd_reference():
    """ops/jax_ops.adasum (device-plane Adasum, VERDICT r4 missing #5)
    must reproduce the host core's VHDD recursion (csrc/adasum.cc): at
    each doubling level, pair combines sa*a + sb*b with the dot products
    of the level's block aggregates. Checked against a numpy
    re-implementation of the recursion, plus the two analytic anchors:
    identical vectors pass through unchanged (sa=sb=1/2), mutually
    orthogonal vectors add exactly (sa=sb=1)."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from horovod_tpu.ops import jax_ops

    n, D = 8, 33
    cpus = jax.devices("cpu")
    assert len(cpus) >= n, cpus  # conftest forces 8 virtual CPU devices
    mesh = Mesh(np.asarray(cpus[:n]), ("data",))

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=P("data", None),
                       out_specs=P("data", None), check_vma=False)
    def run(stacked):
        return jax_ops.adasum(stacked[0], "data")[None]

    def np_adasum(vs):
        vs = [v.astype(np.float64) for v in vs]
        m = len(vs)
        dist = 1
        while dist < m:
            nxt = list(vs)
            for i in range(m):
                a, b = vs[i], vs[i ^ dist]
                ab, aa, bb = a @ b, a @ a, b @ b
                sa = 1.0 - ab / (2 * aa) if aa > 0 else 1.0
                sb = 1.0 - ab / (2 * bb) if bb > 0 else 1.0
                nxt[i] = sa * a + sb * b
            vs = nxt
            dist <<= 1
        return vs[0]

    rng = np.random.default_rng(7)
    vecs = rng.standard_normal((n, D)).astype(np.float32)
    x = jax.device_put(jnp.asarray(vecs),
                       NamedSharding(mesh, P("data", None)))
    out = np.asarray(run(x))
    want = np_adasum(list(vecs))
    # Every shard holds the same combined result.
    for r in range(n):
        assert np.allclose(out[r], want, atol=1e-4), (r, out[r][:4])

    # Identical vectors -> unchanged.
    same = np.broadcast_to(vecs[0], (n, D)).copy()
    out = np.asarray(run(jax.device_put(
        jnp.asarray(same), NamedSharding(mesh, P("data", None)))))
    assert np.allclose(out, same, atol=1e-5)

    # Orthogonal vectors -> exact sum.
    ortho = np.zeros((n, D), np.float32)
    for r in range(n):
        ortho[r, r] = float(r + 1)
    out = np.asarray(run(jax.device_put(
        jnp.asarray(ortho), NamedSharding(mesh, P("data", None)))))
    assert np.allclose(out, ortho.sum(0), atol=1e-5), out[0][:8]


def test_make_train_step_adasum_reduction():
    """make_train_step(grad_reduce='adasum'): the DP wrapper trains with
    the device-plane Adasum instead of pmean and the loss still falls."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from horovod_tpu.parallel.data_parallel import (make_train_step,
                                                    replicate, shard_batch)

    cpus = jax.devices("cpu")
    assert len(cpus) >= 8, cpus
    mesh = Mesh(np.asarray(cpus[:8]), ("data",))
    w_true = np.arange(1, 5, dtype=np.float32)

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    tx = optax.sgd(0.05)
    step = make_train_step(loss_fn, tx, mesh, grad_reduce="adasum")
    params = replicate({"w": jnp.zeros(4)}, mesh)
    opt_state = replicate(tx.init({"w": jnp.zeros(4)}), mesh)

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 4)).astype(np.float32)
    Y = X @ w_true
    batch = shard_batch({"x": jnp.asarray(X), "y": jnp.asarray(Y)}, mesh)
    losses = []
    for _ in range(40):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])
    assert np.isfinite(losses[-1])


def test_bridge_callback_relay_gate(monkeypatch):
    """The in-jit core-bridge ops must fail AT TRACE TIME on a
    remote-compile relay backend (io_callback programs hang forever in
    its compiler — measured round 5) instead of hanging, and the
    override knob must restore the normal lowering."""
    import pytest

    from horovod_tpu.ops import jax_ops as jo

    # Forced-error knob stands in for the relay (JAX_PLATFORMS can't be
    # changed after backend init in this process).
    monkeypatch.setenv("HVD_INJIT_CALLBACKS", "0")
    with pytest.raises(RuntimeError, match="io_callback"):
        jax.jit(lambda x: jo.hvd_allreduce(x))(jnp.ones(4))

    monkeypatch.setenv("JAX_PLATFORMS", "axon")  # relay signature
    monkeypatch.delenv("HVD_INJIT_CALLBACKS", raising=False)
    with pytest.raises(RuntimeError, match="remote-compile relay"):
        jax.jit(lambda x: jo.hvd_allreduce(x))(jnp.ones(4))

    # Override re-opens the gate: tracing/lowering succeeds again (the
    # gate fires at trace time; execution would need an initialized
    # core, which single-process pytest doesn't have).
    monkeypatch.setenv("HVD_INJIT_CALLBACKS", "1")
    jax.jit(lambda x: jo.hvd_allreduce(x, op=jo.Sum)).lower(jnp.ones(4))
