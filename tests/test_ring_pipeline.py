"""Pipelined host-plane ring (ISSUE 5): streamed sub-chunk reduction
overlap in the ring reduce-scatter (HVD_RING_PIPELINE) and the
vectorized reduce kernels (HVD_REDUCE_VECTOR / hvd.reduce_stats()).

The parity matrix runs the same worker at 2/4/8 ranks over all dtypes
and ops with streaming on, once with streaming forced serial
(HVD_RING_PIPELINE=1), and once with the scatter-gather ring disabled so
the staged fusion-buffer ring streams too. Expected values are computed
locally in each worker, so "pipelined == serial" follows from both
matching the same exact references.
"""
import numpy as np
import pytest

import horovod_tpu as hvd

from .util import run_worker_job


def test_pipelined_parity_2rank(tmp_path):
    """2-rank streamed parity + TCP_REDUCE_OVERLAP timeline sub-events."""
    run_worker_job(2, "ring_pipeline_worker.py", timeout=300, extra_env={
        "HVD_RING_PIPELINE": "4",
        "HVD_ZEROCOPY_THRESHOLD": "16384",
        "HVD_TIMELINE": str(tmp_path / "rp_timeline.json"),
    })


def test_pipelined_parity_4rank():
    run_worker_job(4, "ring_pipeline_worker.py", timeout=300, extra_env={
        "HVD_RING_PIPELINE": "4",
        "HVD_ZEROCOPY_THRESHOLD": "16384",
    })


def test_pipelined_parity_8rank():
    run_worker_job(8, "ring_pipeline_worker.py", timeout=420, extra_env={
        "HVD_RING_PIPELINE": "4",
        "HVD_ZEROCOPY_THRESHOLD": "16384",
    })


def test_forced_serial_equivalence_2rank():
    """HVD_RING_PIPELINE=1 pins every ring step to the serial
    recv-then-reduce path; the identical parity sweep proves the
    streamed and serial paths compute the same results."""
    run_worker_job(2, "ring_pipeline_worker.py", timeout=300, extra_env={
        "HVD_RING_PIPELINE": "1",
        "HVD_ZEROCOPY_THRESHOLD": "16384",
    })


def test_pipelined_staged_ring_2rank():
    """HVD_ZEROCOPY=0 routes everything through the fusion-buffer staging
    ring — its reduce-scatter must stream sub-chunks too. HVD_SHM=0: this
    test pins the TCP staging path specifically; with the intra-host shm
    plane on (the default for launcher-declared single-host jobs, ISSUE
    7) the staged ring becomes a pointer handoff and never streams —
    that routing is covered by test_hier_shm.py."""
    run_worker_job(2, "ring_pipeline_worker.py", timeout=300, extra_env={
        "HVD_RING_PIPELINE": "4",
        "HVD_ZEROCOPY": "0",
        "HVD_SHM": "0",
    })


def test_scalar_tier_forced_2rank():
    """HVD_REDUCE_VECTOR=0 pins Accumulate to the non-vectorized scalar
    baseline; parity must hold and the scalar counters must move."""
    run_worker_job(2, "ring_pipeline_worker.py", timeout=300, extra_env={
        "HVD_RING_PIPELINE": "4",
        "HVD_ZEROCOPY_THRESHOLD": "16384",
        "HVD_REDUCE_VECTOR": "0",
    })


def test_reduce_stats_no_init_required():
    """reduce_stats()/reduce_bench() are process-global — usable before
    init (bench.py's `reduce` config relies on this)."""
    fast0, fe0, scalar0, se0 = hvd.reduce_stats()
    secs = hvd.reduce_bench(5, 4096, iters=1, vector=True)  # kFloat32
    assert secs > 0
    fast1, fe1, _, _ = hvd.reduce_stats()
    assert fast1 > fast0 and fe1 >= fe0 + 4096
    secs = hvd.reduce_bench(5, 4096, iters=1, vector=False)
    assert secs > 0
    _, _, scalar1, se1 = hvd.reduce_stats()
    assert scalar1 > scalar0 and se1 >= se0 + 4096


def test_reduce_bench_rejects_bad_dtype():
    with pytest.raises(ValueError):
        hvd.reduce_bench(99, 1024)
    with pytest.raises(ValueError):
        hvd.reduce_bench(5, 0)


def test_reduce_bench_all_dtypes_smoke():
    """Every DataType the kernels dispatch on completes a timed call."""
    # >= 0: the byte-wide kernels finish 1024 elems inside the timer's
    # microsecond resolution; negative would be the error signal.
    for dt in (0, 1, 2, 3, 4, 5, 6, 7, 8):  # u8..bool + bf16
        assert hvd.reduce_bench(dt, 1024, iters=1, vector=True) >= 0
        assert hvd.reduce_bench(dt, 1024, iters=1, vector=False) >= 0


def test_metrics_sample_core_stats_uninitialized():
    """sample_core_stats degrades to the reduce counters only when the
    core is down — pipeline gauges need an initialized core."""
    from horovod_tpu.observability import metrics
    if hvd.is_initialized():  # other tests may have left a core up
        pytest.skip("core initialized in-process")
    with pytest.raises(ValueError):
        metrics.sample_core_stats()
