"""horovod_tpu.keras — Keras front door (reference: horovod/keras +
horovod/tensorflow/keras): re-exports the TF binding plus callbacks."""

from ..tensorflow import (  # noqa: F401
    Adasum,
    Average,
    Compression,
    DistributedOptimizer,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allreduce,
    alltoall,
    broadcast,
    broadcast_object,
    broadcast_variables,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    metric_average,
    rank,
    shutdown,
    size,
)
from .._keras import callbacks, load_model  # noqa: F401
from .._keras.callbacks import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    CommitStateCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    UpdateBatchStateCallback,
    UpdateEpochStateCallback,
)
