"""Queue-depth-driven autoscale policy for the serving plane.

Pure Python (no jax, no sockets) — the policy is a fold over load
observations, so tests drive it with synthetic sequences the same way
tests/test_serving_scheduler.py drives the batcher.

Wiring (docs/serving.md has the full picture):

- The serving loop's rank 0 publishes ``{queue_depth, batch_fill,
  kv_occupancy}`` to the rendezvous KV at ``/ctl/serve_load`` every
  boundary interval (:func:`runner.elastic.worker.report_serve_load`).
- The elastic driver consumes those keys in its main loop, feeds them
  here, and when the target changes publishes a new epoch whose ACTIVE
  set is capped at the target. Scale-up promotes hot spares — workers
  already rendezvoused and heartbeating, so the latency from "queue too
  deep" to "more ranks decoding" is one incremental epoch, not a cold
  spawn (PR 8's promotion machinery, reused verbatim). Scale-down parks
  excess workers back into the spare pool rather than exiting them, so
  the next burst is equally cheap.

Hysteresis: a scale decision needs ``patience`` CONSECUTIVE
observations on the same side of the band. A Poisson arrival process
crosses any threshold constantly; without the dwell requirement the
fleet would thrash epochs (each epoch is a re-rendezvous the whole job
pays for).
"""

DEFAULT_HIGH_DEPTH = 8      # queue deeper than this wants more ranks
DEFAULT_LOW_DEPTH = 1       # queue at/below this with slack wants fewer
DEFAULT_LOW_FILL = 0.5      # ...but only when the batch is half idle
DEFAULT_PATIENCE = 3        # consecutive observations before acting


class AutoscalePolicy:
    """Fold load observations into a target world size.

    ``observe`` returns the NEW target when a resize is warranted, else
    None. Targets move one rank at a time (each resize is an epoch; big
    jumps are better paced than batched) and clamp to [min_np, max_np].
    """

    def __init__(self, min_np, max_np, high_depth=DEFAULT_HIGH_DEPTH,
                 low_depth=DEFAULT_LOW_DEPTH, low_fill=DEFAULT_LOW_FILL,
                 patience=DEFAULT_PATIENCE):
        if max_np < min_np:
            raise ValueError(f"max_np {max_np} < min_np {min_np}")
        if high_depth <= low_depth:
            raise ValueError(f"high_depth {high_depth} must exceed "
                             f"low_depth {low_depth} (hysteresis band)")
        self.min_np = int(min_np)
        self.max_np = int(max_np)
        self.high_depth = int(high_depth)
        self.low_depth = int(low_depth)
        self.low_fill = float(low_fill)
        self.patience = max(1, int(patience))
        self.target = self.min_np
        self._up_streak = 0
        self._down_streak = 0

    def observe(self, queue_depth, batch_fill):
        """One load sample -> new target np, or None (hold)."""
        if queue_depth > self.high_depth:
            self._up_streak += 1
            self._down_streak = 0
        elif queue_depth <= self.low_depth and batch_fill < self.low_fill:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        if self._up_streak >= self.patience and self.target < self.max_np:
            self.target += 1
            self._up_streak = 0
            return self.target
        if (self._down_streak >= self.patience
                and self.target > self.min_np):
            self.target -= 1
            self._down_streak = 0
            return self.target
        return None
