"""Continuous-batching scheduler + paged-KV page accounting (jax-free).

The serving plane's control half. Everything here is deliberately plain
Python/numpy — no jax import anywhere in this module — so the scheduling
invariants (admission, eviction, page conservation, batch-fill
monotonicity) are testable without an accelerator stack, the same way
:mod:`horovod_tpu.parallel.schedules` keeps its pipeline tables
numpy-only (tests/test_pipeline_schedules.py is the idiom this module's
tests mirror).

Model (vLLM-style continuous batching, scoped to what the decode engine
in :mod:`.engine` executes):

- The KV cache is ``n_pages`` fixed-size pages of ``page_size`` token
  slots each. A request owns ceil(context_len / page_size) pages,
  recorded in its **block table** — the indirection that lets requests
  of wildly different lengths share ONE jit'd decode step
  (``docs/serving.md``).
- The batch is ``max_batch`` *slots*. A request keeps its slot for its
  whole running life (the engine indexes cache writes by slot-stable
  block tables, so slot churn would mean recompilation or copies).
- **Admission happens at token boundaries**: after every decode step the
  scheduler evicts finished requests (EOS / max-tokens), grows pages for
  requests crossing a page boundary, and admits waiting requests into
  free slots while their first allocation (prompt pages + one decode
  page) fits. That is the whole continuous-batching optimization — a
  static batch instead holds admissions until the ENTIRE batch drains.
- **Preemption**: when a running request crosses a page boundary and no
  page is free, the *youngest* running request is evicted back to the
  waiting queue (its pages freed, its generated tokens kept so the
  re-prefill replays prompt + generated prefix). Admission-reserved
  pages can therefore never deadlock the batch: the oldest request can
  always finish.

Page accounting contract (tests/test_serving_scheduler.py pins these):
``free + distinct-owned == n_pages - 1`` at every boundary (page 0 is
the engine's trash page for masked writes and is never handed out), a
page's refcount equals the number of holders referencing it (requests
plus at most one prefix-cache reference), and ``free()``/``share()`` of
a page not currently owned raise BEFORE mutation instead of corrupting
the pool.
"""

import collections
import dataclasses
import math
import os


def _int(raw, default):
    try:
        return int(raw or default)
    except ValueError:
        return default


# Knob defaults (CLI `--serve-*` / YAML `serve:` / env HVD_SERVE_* —
# docs/running.md knob table; parity held by tools/hvdlint.py).
DEFAULT_PAGE_SIZE = 16
DEFAULT_KV_PAGES = 256
DEFAULT_MAX_BATCH = 8
DEFAULT_PREFIX_CACHE = 1   # radix-tree shared-prefix KV reuse (ISSUE 16)
DEFAULT_SPEC_TOKENS = 0    # speculative decoding draft-k (0 = off)


def serve_knobs():
    """The serve loop's HVD_SERVE_* env knobs (set directly or via the
    tpurun --serve-* flags / YAML `serve:` section — docs/running.md)."""
    mode = os.environ.get("HVD_SERVE_MODE", "") or "continuous"
    return {
        "page_size": _int(os.environ.get("HVD_SERVE_PAGE_SIZE", ""),
                          DEFAULT_PAGE_SIZE),
        "kv_pages": _int(os.environ.get("HVD_SERVE_KV_PAGES", ""),
                         DEFAULT_KV_PAGES),
        "max_batch": _int(os.environ.get("HVD_SERVE_MAX_BATCH", ""),
                          DEFAULT_MAX_BATCH),
        "mode": mode,
        "prefix_cache": _int(os.environ.get("HVD_SERVE_PREFIX_CACHE", ""),
                             DEFAULT_PREFIX_CACHE),
        "spec_tokens": _int(os.environ.get("HVD_SERVE_SPEC_TOKENS", ""),
                            DEFAULT_SPEC_TOKENS),
    }


class PageError(RuntimeError):
    """KV-page accounting violation (double-free / foreign page)."""


class PageAllocator:
    """Fixed pool of KV pages with a free list and refcounted ownership.

    Page 0 is reserved as the engine's trash page (inactive batch slots
    route their cache writes there) and is never allocated. ``alloc`` is
    all-or-nothing so a half-admitted request can never leak pages.

    Sharing is copy-on-write in the degenerate (and only) case paged
    prefix reuse needs: pages are shared exclusively at page-aligned
    *prefix* boundaries, and a request only ever writes K/V at positions
    >= its own context length — which always land in pages it owns
    exclusively. So "copy" never actually happens; ``share`` bumps a
    refcount and ``free`` decrements it, returning the page to the pool
    only when the last reference drops. Double-free and
    refcount-underflow raise :class:`PageError` BEFORE any mutation.
    """

    def __init__(self, n_pages, page_size):
        if n_pages < 2:
            raise ValueError(f"need >= 2 KV pages (1 is the reserved "
                             f"trash page), got {n_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._free = collections.deque(range(1, self.n_pages))
        self._ref = {}             # page -> refcount (>= 1 while owned)

    @property
    def usable_pages(self):
        """Pages that can ever be handed out (excludes the trash page)."""
        return self.n_pages - 1

    def free_pages(self):
        return len(self._free)

    def used_pages(self):
        """Distinct pages currently owned (each counted once however
        many references it has — physical pool pressure)."""
        return len(self._ref)

    def refcount(self, page):
        """Current reference count of `page` (0 when free/unallocated)."""
        return self._ref.get(page, 0)

    def occupancy(self):
        """Fraction of usable pages currently owned — the
        SERVE_KV_OCCUPANCY gauge."""
        return len(self._ref) / max(1, self.usable_pages)

    def alloc(self, n):
        """Take `n` pages or none. Returns the page list (each at
        refcount 1), or None when the pool cannot cover the request."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        return pages

    def share(self, pages):
        """Take an additional reference on already-owned pages (a
        prefix-cache hit forking a cached prefix into a new request).
        Sharing a page that is not currently owned raises PageError
        BEFORE any refcount changes."""
        pages = list(pages)
        for p in pages:
            if p not in self._ref:
                raise PageError(f"share of unowned KV page {p} (stale "
                                f"prefix-cache entry or foreign page)")
        for p in pages:
            self._ref[p] += 1

    def free(self, pages):
        """Drop one reference per page; a page returns to the pool only
        at refcount 0. A page not currently owned (double free,
        refcount underflow, or a number that was never allocated) raises
        PageError BEFORE any state changes — the pool stays consistent."""
        pages = list(pages)
        counts = collections.Counter(pages)
        for p, n in counts.items():
            if self._ref.get(p, 0) < n:
                raise PageError(f"free of unowned KV page {p} (double "
                                f"free, refcount underflow, or foreign "
                                f"page)")
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                del self._ref[p]
                self._free.append(p)


_WAITING, _RUNNING, _DONE = "waiting", "running", "done"


@dataclasses.dataclass
class Request:
    """One generation request. ``prompt`` is the token list; the
    scheduler only reads its length — the engine feeds the tokens."""
    rid: int
    prompt: list
    max_new_tokens: int
    arrival_t: float = 0.0
    eos_id: int = -1           # -1: never matches (length-capped only)

    # lifecycle (scheduler-owned)
    state: str = _WAITING
    slot: int = -1
    pages: list = dataclasses.field(default_factory=list)
    generated: list = dataclasses.field(default_factory=list)
    admitted_t: float = 0.0
    first_token_t: float = 0.0  # TTFT anchor (0 until the first token)
    finished_t: float = 0.0
    finish_reason: str = ""
    preemptions: int = 0
    admit_seq: int = -1         # admission order (preemption picks max)
    cached_tokens: int = 0      # prompt tokens covered by a prefix hit

    @property
    def prompt_len(self):
        return len(self.prompt)

    @property
    def context_len(self):
        """Tokens currently in the KV cache once running: the prompt plus
        every generated token (each decode step appends one)."""
        return len(self.prompt) + len(self.generated)

    def pages_needed(self, page_size, extra_tokens=1):
        """Pages for the current context plus `extra_tokens` upcoming
        positions (admission reserves the first decode slot too, so a
        fresh admit can always take at least one step)."""
        return math.ceil((self.context_len + extra_tokens) / page_size)


class ContinuousBatcher:
    """Token-boundary scheduler over a PageAllocator and `max_batch`
    engine slots.

    mode="continuous": admit into any free slot whenever pages allow.
    mode="static": the A/B baseline — admissions only happen when the
    running set is EMPTY (classic padded static batching: the batch
    drains fully, finished requests' slots idle until the last one ends).
    """

    def __init__(self, allocator, max_batch=DEFAULT_MAX_BATCH,
                 mode="continuous", prefix_cache=None, spec_tokens=0):
        if mode not in ("continuous", "static"):
            raise ValueError(f"serve mode must be 'continuous' or "
                             f"'static', got {mode!r}")
        if spec_tokens < 0:
            raise ValueError(f"spec_tokens must be >= 0, got "
                             f"{spec_tokens}")
        self.alloc = allocator
        self.max_batch = int(max_batch)
        self.mode = mode
        self.prefix = prefix_cache  # PrefixCache or None (reuse off)
        self.spec_tokens = int(spec_tokens)
        self.waiting = collections.deque()
        self.running = {}          # slot -> Request
        self.done = []
        self._admit_seq = 0
        self.stats = {"admissions": 0, "evictions": 0, "preemptions": 0,
                      "tokens": 0, "prefix_hit_tokens": 0,
                      "prefix_prompt_tokens": 0, "spec_steps": 0,
                      "spec_accepted": 0, "spec_rejected": 0}

    @property
    def _lookahead(self):
        """Token positions a request must own pages for beyond its
        current context before the next step: 1 for the plain decode
        write, plus draft-k when speculating (a spec step writes K/V for
        the last token AND all k drafts before accept/reject resolves,
        so page growth must reserve the whole window up front)."""
        return 1 + self.spec_tokens

    # -- gauges -----------------------------------------------------------

    def queue_depth(self):
        return len(self.waiting)

    def batch_fill(self):
        """Fraction of engine slots doing useful work this step — the
        SERVE_BATCH_FILL gauge (the quantity static batching wastes)."""
        return len(self.running) / max(1, self.max_batch)

    def kv_occupancy(self):
        return self.alloc.occupancy()

    # -- submission -------------------------------------------------------

    def submit(self, req, now=0.0):
        req.arrival_t = now if req.arrival_t == 0.0 else req.arrival_t
        req.state = _WAITING
        self.waiting.append(req)

    # -- token boundary ---------------------------------------------------

    def on_tokens(self, tokens_by_slot, now=0.0):
        """Record one decode step's outputs (slot -> token id, or slot ->
        token id LIST when a speculative step emitted several accepted
        tokens at once), then run the boundary: evict finished, grow
        pages (preempting if starved), admit. A list is consumed in
        order and truncated at the first EOS / max-tokens hit — trailing
        accepted drafts past a finish are dropped, exactly as if they
        were never accepted (rejection IS just not appending: the block
        table simply never extends over the stale K/V). Returns the list
        of requests evicted as DONE this boundary."""
        finished = []
        for slot, toks in tokens_by_slot.items():
            req = self.running.get(slot)
            if req is None:
                continue
            if isinstance(toks, int):
                toks = [toks]
            for tok in toks:
                req.generated.append(tok)
                self.stats["tokens"] += 1
                if req.first_token_t == 0.0:
                    req.first_token_t = now
                if tok == req.eos_id:
                    req.finish_reason = "eos"
                elif len(req.generated) >= req.max_new_tokens:
                    req.finish_reason = "max_tokens"
                if req.finish_reason:
                    finished.append(self._finish(req, now))
                    break
        self._grow_pages(now)
        self.admit(now)
        return finished

    def _finish(self, req, now):
        del self.running[req.slot]
        self.alloc.free(req.pages)
        req.pages = []
        req.state = _DONE
        req.finished_t = now
        req.slot = -1
        self.done.append(req)
        self.stats["evictions"] += 1
        return req

    def _take_pages(self, n):
        """alloc(n), reclaiming LRU unreferenced prefix-cache pages
        first when the pool alone cannot cover it. Cached prefixes are
        opportunistic — live requests always outrank them."""
        got = self.alloc.alloc(n)
        if got is None and self.prefix is not None:
            self.prefix.evict(n - self.alloc.free_pages())
            got = self.alloc.alloc(n)
        return got

    def _grow_pages(self, now):
        """Every running request must own page slots for its next
        ``1 + spec_tokens`` token positions before the next step.
        Requests crossing a page boundary take pages (evicting stale
        prefix-cache pages first); page starvation preempts the youngest
        running request (freeing its pages) until the growth fits."""
        for slot in sorted(self.running):
            req = self.running.get(slot)
            if req is None:
                continue  # preempted by an earlier growth this boundary
            while len(req.pages) < req.pages_needed(
                    self.alloc.page_size, extra_tokens=self._lookahead):
                got = self._take_pages(1)
                if got is not None:
                    req.pages.extend(got)
                    continue
                victim = max(self.running.values(),
                             key=lambda r: r.admit_seq)
                if victim is req:
                    # Nothing younger to preempt: this request IS the
                    # youngest. Preempt it rather than stall the batch.
                    self._preempt(req, now)
                    break
                self._preempt(victim, now)

    def _preempt(self, req, now):
        """Back to the waiting queue, pages freed, generated prefix kept
        (the re-prefill replays prompt + generated so no tokens are
        lost). Preempted requests go to the FRONT of the queue — they
        have priority over never-admitted work."""
        del self.running[req.slot]
        self.alloc.free(req.pages)
        req.pages = []
        req.slot = -1
        req.state = _WAITING
        req.cached_tokens = 0   # re-resolved against the cache at readmit
        req.preemptions += 1
        self.stats["preemptions"] += 1
        self.waiting.appendleft(req)

    def admit(self, now=0.0):
        """Fill free slots from the waiting queue while the first
        allocation fits. With a prefix cache attached, admission first
        resolves the longest cached page-aligned strict prefix of the
        prompt: those pages are SHARED (refcount bump, no copy — the
        request never writes below its own context length) and only the
        novel remainder is allocated. Returns newly admitted requests
        (they need a prefill of their uncached suffix before the next
        decode step)."""
        if self.mode == "static" and self.running:
            return []
        admitted = []
        free_slots = [s for s in range(self.max_batch)
                      if s not in self.running]
        while self.waiting and free_slots:
            req = self.waiting[0]
            shared, cached = [], 0
            if self.prefix is not None:
                shared, cached = self.prefix.lookup(req.prompt)
                # Pin the hit before any allocation can LRU-evict it:
                # at refcount 2 these pages are invisible to evict().
                self.alloc.share(shared)
            need = req.pages_needed(self.alloc.page_size,
                                    extra_tokens=self._lookahead)
            pages = self._take_pages(need - len(shared))
            if pages is None:
                if shared:
                    self.alloc.free(shared)  # unpin the aborted hit
                break  # head-of-line: keep arrival order, wait for pages
            self.waiting.popleft()
            req.pages = shared + pages
            req.cached_tokens = cached
            req.slot = free_slots.pop(0)
            req.state = _RUNNING
            req.admitted_t = now
            req.admit_seq = self._admit_seq
            self._admit_seq += 1
            self.running[req.slot] = req
            self.stats["admissions"] += 1
            if self.prefix is not None:
                self.stats["prefix_hit_tokens"] += cached
                self.stats["prefix_prompt_tokens"] += req.prompt_len
            admitted.append(req)
        return admitted

    def register_prefilled(self, req):
        """Publish a freshly prefilled request's full prompt pages into
        the prefix cache (no-op without one). Called by the serve loop
        once the prompt's K/V is actually materialized — registering at
        admission would let a second request hit pages whose suffix was
        never written."""
        if self.prefix is not None and req.slot >= 0:
            self.prefix.insert(req.prompt, req.pages)

    def prefix_hit_ratio(self):
        """Fraction of admitted prompt tokens served from cached pages —
        the SERVE_PREFIX_HIT_RATIO gauge (0.0 until the first admission
        with a cache attached)."""
        total = self.stats["prefix_prompt_tokens"]
        return self.stats["prefix_hit_tokens"] / total if total else 0.0

    def block_table(self, req, max_blocks):
        """The request's page list padded with trash page 0 to the
        engine's fixed block-table width."""
        if len(req.pages) > max_blocks:
            raise ValueError(
                f"request {req.rid} holds {len(req.pages)} pages > "
                f"max_blocks {max_blocks} (context "
                f"{req.context_len} too long for the cache geometry)")
        return list(req.pages) + [0] * (max_blocks - len(req.pages))

    def idle(self):
        return not self.waiting and not self.running
