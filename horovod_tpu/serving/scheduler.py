"""Continuous-batching scheduler + paged-KV page accounting (jax-free).

The serving plane's control half. Everything here is deliberately plain
Python/numpy — no jax import anywhere in this module — so the scheduling
invariants (admission, eviction, page conservation, batch-fill
monotonicity) are testable without an accelerator stack, the same way
:mod:`horovod_tpu.parallel.schedules` keeps its pipeline tables
numpy-only (tests/test_pipeline_schedules.py is the idiom this module's
tests mirror).

Model (vLLM-style continuous batching, scoped to what the decode engine
in :mod:`.engine` executes):

- The KV cache is ``n_pages`` fixed-size pages of ``page_size`` token
  slots each. A request owns ceil(context_len / page_size) pages,
  recorded in its **block table** — the indirection that lets requests
  of wildly different lengths share ONE jit'd decode step
  (``docs/serving.md``).
- The batch is ``max_batch`` *slots*. A request keeps its slot for its
  whole running life (the engine indexes cache writes by slot-stable
  block tables, so slot churn would mean recompilation or copies).
- **Admission happens at token boundaries**: after every decode step the
  scheduler evicts finished requests (EOS / max-tokens), grows pages for
  requests crossing a page boundary, and admits waiting requests into
  free slots while their first allocation (prompt pages + one decode
  page) fits. That is the whole continuous-batching optimization — a
  static batch instead holds admissions until the ENTIRE batch drains.
- **Preemption**: when a running request crosses a page boundary and no
  page is free, the *youngest* running request is evicted back to the
  waiting queue (its pages freed, its generated tokens kept so the
  re-prefill replays prompt + generated prefix). Admission-reserved
  pages can therefore never deadlock the batch: the oldest request can
  always finish.

Page accounting contract (tests/test_serving_scheduler.py pins these):
``free + sum(owned) == n_pages - 1`` at every boundary (page 0 is the
engine's trash page for masked writes and is never handed out), a page
is never owned twice, and ``free()`` of a page not currently owned
raises instead of corrupting the pool.
"""

import collections
import dataclasses
import math
import os


def _int(raw, default):
    try:
        return int(raw or default)
    except ValueError:
        return default


# Knob defaults (CLI `--serve-*` / YAML `serve:` / env HVD_SERVE_* —
# docs/running.md knob table; parity held by tools/hvdlint.py).
DEFAULT_PAGE_SIZE = 16
DEFAULT_KV_PAGES = 256
DEFAULT_MAX_BATCH = 8


def serve_knobs():
    """The serve loop's HVD_SERVE_* env knobs (set directly or via the
    tpurun --serve-* flags / YAML `serve:` section — docs/running.md)."""
    mode = os.environ.get("HVD_SERVE_MODE", "") or "continuous"
    return {
        "page_size": _int(os.environ.get("HVD_SERVE_PAGE_SIZE", ""),
                          DEFAULT_PAGE_SIZE),
        "kv_pages": _int(os.environ.get("HVD_SERVE_KV_PAGES", ""),
                         DEFAULT_KV_PAGES),
        "max_batch": _int(os.environ.get("HVD_SERVE_MAX_BATCH", ""),
                          DEFAULT_MAX_BATCH),
        "mode": mode,
    }


class PageError(RuntimeError):
    """KV-page accounting violation (double-free / foreign page)."""


class PageAllocator:
    """Fixed pool of KV pages with a free list and strict ownership.

    Page 0 is reserved as the engine's trash page (inactive batch slots
    route their cache writes there) and is never allocated. ``alloc`` is
    all-or-nothing so a half-admitted request can never leak pages.
    """

    def __init__(self, n_pages, page_size):
        if n_pages < 2:
            raise ValueError(f"need >= 2 KV pages (1 is the reserved "
                             f"trash page), got {n_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._free = collections.deque(range(1, self.n_pages))
        self._owned = set()

    @property
    def usable_pages(self):
        """Pages that can ever be handed out (excludes the trash page)."""
        return self.n_pages - 1

    def free_pages(self):
        return len(self._free)

    def used_pages(self):
        return len(self._owned)

    def occupancy(self):
        """Fraction of usable pages currently owned — the
        SERVE_KV_OCCUPANCY gauge."""
        return len(self._owned) / max(1, self.usable_pages)

    def alloc(self, n):
        """Take `n` pages or none. Returns the page list, or None when
        the pool cannot cover the request."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        pages = [self._free.popleft() for _ in range(n)]
        self._owned.update(pages)
        return pages

    def free(self, pages):
        """Return pages to the pool. A page not currently owned (double
        free, or a number that was never allocated) raises PageError
        BEFORE any state changes — the pool stays consistent."""
        pages = list(pages)
        for p in pages:
            if p not in self._owned:
                raise PageError(f"free of unowned KV page {p} (double "
                                f"free or foreign page)")
        for p in pages:
            self._owned.discard(p)
            self._free.append(p)


_WAITING, _RUNNING, _DONE = "waiting", "running", "done"


@dataclasses.dataclass
class Request:
    """One generation request. ``prompt`` is the token list; the
    scheduler only reads its length — the engine feeds the tokens."""
    rid: int
    prompt: list
    max_new_tokens: int
    arrival_t: float = 0.0
    eos_id: int = -1           # -1: never matches (length-capped only)

    # lifecycle (scheduler-owned)
    state: str = _WAITING
    slot: int = -1
    pages: list = dataclasses.field(default_factory=list)
    generated: list = dataclasses.field(default_factory=list)
    admitted_t: float = 0.0
    first_token_t: float = 0.0  # TTFT anchor (0 until the first token)
    finished_t: float = 0.0
    finish_reason: str = ""
    preemptions: int = 0
    admit_seq: int = -1         # admission order (preemption picks max)

    @property
    def prompt_len(self):
        return len(self.prompt)

    @property
    def context_len(self):
        """Tokens currently in the KV cache once running: the prompt plus
        every generated token (each decode step appends one)."""
        return len(self.prompt) + len(self.generated)

    def pages_needed(self, page_size, extra_tokens=1):
        """Pages for the current context plus `extra_tokens` upcoming
        positions (admission reserves the first decode slot too, so a
        fresh admit can always take at least one step)."""
        return math.ceil((self.context_len + extra_tokens) / page_size)


class ContinuousBatcher:
    """Token-boundary scheduler over a PageAllocator and `max_batch`
    engine slots.

    mode="continuous": admit into any free slot whenever pages allow.
    mode="static": the A/B baseline — admissions only happen when the
    running set is EMPTY (classic padded static batching: the batch
    drains fully, finished requests' slots idle until the last one ends).
    """

    def __init__(self, allocator, max_batch=DEFAULT_MAX_BATCH,
                 mode="continuous"):
        if mode not in ("continuous", "static"):
            raise ValueError(f"serve mode must be 'continuous' or "
                             f"'static', got {mode!r}")
        self.alloc = allocator
        self.max_batch = int(max_batch)
        self.mode = mode
        self.waiting = collections.deque()
        self.running = {}          # slot -> Request
        self.done = []
        self._admit_seq = 0
        self.stats = {"admissions": 0, "evictions": 0, "preemptions": 0,
                      "tokens": 0}

    # -- gauges -----------------------------------------------------------

    def queue_depth(self):
        return len(self.waiting)

    def batch_fill(self):
        """Fraction of engine slots doing useful work this step — the
        SERVE_BATCH_FILL gauge (the quantity static batching wastes)."""
        return len(self.running) / max(1, self.max_batch)

    def kv_occupancy(self):
        return self.alloc.occupancy()

    # -- submission -------------------------------------------------------

    def submit(self, req, now=0.0):
        req.arrival_t = now if req.arrival_t == 0.0 else req.arrival_t
        req.state = _WAITING
        self.waiting.append(req)

    # -- token boundary ---------------------------------------------------

    def on_tokens(self, tokens_by_slot, now=0.0):
        """Record one decode step's outputs (slot -> token id), then run
        the boundary: evict finished, grow pages (preempting if starved),
        admit. Returns the list of requests evicted as DONE this
        boundary."""
        finished = []
        for slot, tok in tokens_by_slot.items():
            req = self.running.get(slot)
            if req is None:
                continue
            req.generated.append(tok)
            self.stats["tokens"] += 1
            if req.first_token_t == 0.0:
                req.first_token_t = now
            if tok == req.eos_id:
                req.finish_reason = "eos"
            elif len(req.generated) >= req.max_new_tokens:
                req.finish_reason = "max_tokens"
            if req.finish_reason:
                finished.append(self._finish(req, now))
        self._grow_pages(now)
        self.admit(now)
        return finished

    def _finish(self, req, now):
        del self.running[req.slot]
        self.alloc.free(req.pages)
        req.pages = []
        req.state = _DONE
        req.finished_t = now
        req.slot = -1
        self.done.append(req)
        self.stats["evictions"] += 1
        return req

    def _grow_pages(self, now):
        """Every running request must own a page slot for its NEXT token
        position before the next decode step. Requests crossing a page
        boundary take one page; page starvation preempts the youngest
        running request (freeing its pages) until the growth fits."""
        for slot in sorted(self.running):
            req = self.running.get(slot)
            if req is None:
                continue  # preempted by an earlier growth this boundary
            while len(req.pages) < req.pages_needed(self.alloc.page_size):
                got = self.alloc.alloc(1)
                if got is not None:
                    req.pages.extend(got)
                    continue
                victim = max(self.running.values(),
                             key=lambda r: r.admit_seq)
                if victim is req:
                    # Nothing younger to preempt: this request IS the
                    # youngest. Preempt it rather than stall the batch.
                    self._preempt(req, now)
                    break
                self._preempt(victim, now)

    def _preempt(self, req, now):
        """Back to the waiting queue, pages freed, generated prefix kept
        (the re-prefill replays prompt + generated so no tokens are
        lost). Preempted requests go to the FRONT of the queue — they
        have priority over never-admitted work."""
        del self.running[req.slot]
        self.alloc.free(req.pages)
        req.pages = []
        req.slot = -1
        req.state = _WAITING
        req.preemptions += 1
        self.stats["preemptions"] += 1
        self.waiting.appendleft(req)

    def admit(self, now=0.0):
        """Fill free slots from the waiting queue while the first
        allocation fits. Returns newly admitted requests (they need a
        prefill before the next decode step)."""
        if self.mode == "static" and self.running:
            return []
        admitted = []
        free_slots = [s for s in range(self.max_batch)
                      if s not in self.running]
        while self.waiting and free_slots:
            req = self.waiting[0]
            need = req.pages_needed(self.alloc.page_size)
            pages = self.alloc.alloc(need)
            if pages is None:
                break  # head-of-line: keep arrival order, wait for pages
            self.waiting.popleft()
            req.pages = pages
            req.slot = free_slots.pop(0)
            req.state = _RUNNING
            req.admitted_t = now
            req.admit_seq = self._admit_seq
            self._admit_seq += 1
            self.running[req.slot] = req
            self.stats["admissions"] += 1
            admitted.append(req)
        return admitted

    def block_table(self, req, max_blocks):
        """The request's page list padded with trash page 0 to the
        engine's fixed block-table width."""
        if len(req.pages) > max_blocks:
            raise ValueError(
                f"request {req.rid} holds {len(req.pages)} pages > "
                f"max_blocks {max_blocks} (context "
                f"{req.context_len} too long for the cache geometry)")
        return list(req.pages) + [0] * (max_blocks - len(req.pages))

    def idle(self):
        return not self.waiting and not self.running
