"""The serve loop: open-loop Poisson load in, tokens + latency spans out.

One iteration = one token boundary:

1. submit every request whose (open-loop) arrival time has passed —
   arrivals do NOT wait for capacity; the queue absorbs bursts and the
   queue DEPTH is what the autoscaler watches,
2. admit + prefill newcomers. Same-boundary cache-miss admissions are
   prefilled in ONE batched call (``engine.make_batched_prefill``;
   singleton fallback counted); prefix-cache hits fill only their novel
   suffix, one ``prefill_chunk``-token chunk per boundary, so a long
   cold prompt never monopolizes a decode boundary. Completing a
   prefill emits the request's first token — TTFT is arrival → that
   token, queueing and prefill included — and registers the prompt's
   pages in the prefix cache,
3. one jit'd decode step over every fully-prefilled slot — or, with
   ``spec_tokens > 0``, one SPECULATIVE step: draft k tokens per slot
   (:mod:`.speculate`), score them all in a single q_len=k+1 target
   pass, and emit the accepted run + bonus token (bit-identical to
   plain greedy; rejected drafts are just block-table truncations),
4. feed the tokens back through the scheduler boundary (evict finished,
   grow pages, admit into the freed slots) and sample the SERVE_* gauges.

Latency accounting (docs/serving.md has the formal definitions):
TTFT = first_token_t - arrival_t per request; inter-token latency (ITL)
= the gaps between a request's consecutive token timestamps. The
summary reports p50/p99 over all requests' TTFTs and over ALL gaps.

Every request also becomes one ``serve.request`` span (arrival →
finish, with rid/tokens/ttft_ms args) on the observability timeline, so
a merged trace shows request lifetimes above the per-step
``serve.prefill`` / ``serve.chunk_prefill`` / ``serve.decode_step`` /
``serve.spec_step`` spans.

Kill switches: ``HVD_SERVE_PREFIX_CACHE=0`` (or ``prefix_cache=False``)
and ``spec_tokens=0`` restore the PR 14 paths exactly — no prefix /
speculation engine is even built and the new SERVE_* metrics see zero
activity.
"""

import time

import numpy as np

from ..observability import metrics as _metrics
from ..observability import spans as _spans
from . import engine, kv_cache, speculate
from .prefix_cache import PrefixCache
from .scheduler import (DEFAULT_KV_PAGES, DEFAULT_MAX_BATCH,
                        DEFAULT_PAGE_SIZE, ContinuousBatcher, PageAllocator,
                        Request, serve_knobs)


# Latest ServeLoop snapshot, surfaced as hvd.serve_stats() (same lazy
# module-registry idiom as hvd.checkpoint_stats()).
_LAST_STATS = {}


def serve_stats():
    """Most recent ServeLoop boundary snapshot (empty dict before any
    loop has run) — queue/fill/occupancy gauges plus the prefix-cache
    and speculation counters."""
    return dict(_LAST_STATS)


def poisson_requests(n, rate, rng, prompt_len=(4, 32), max_new=(4, 64),
                     vocab=256, eos_id=-1):
    """Synthetic open-loop load: `n` requests with exponential
    inter-arrival gaps (rate = requests/second) and uniform prompt /
    max-new-token draws. The max_new spread is what continuous batching
    monetizes: short requests finish early and their slots refill while
    a static batch would idle them until the longest request drains."""
    reqs, t = [], 0.0
    lo_p, hi_p = prompt_len
    lo_n, hi_n = max_new
    for i in range(n):
        t += float(rng.exponential(1.0 / rate))
        prompt = rng.integers(0, vocab,
                              size=int(rng.integers(lo_p, hi_p + 1)))
        reqs.append(Request(
            rid=i, prompt=[int(x) for x in prompt],
            max_new_tokens=int(rng.integers(lo_n, hi_n + 1)),
            arrival_t=t, eos_id=eos_id))
    return reqs


def shared_prefix_requests(n, rate, rng, prefix_len=24, tail_len=(2, 8),
                           max_new=(4, 16), vocab=256, eos_id=-1):
    """The prefix-cache A/B workload: every prompt is one common
    ``prefix_len``-token system prompt plus a short unique tail — the
    shape real traffic has (shared templates, per-user suffixes). With
    the cache on, every admission after the first should hit the shared
    prefix's pages."""
    prefix = [int(x) for x in rng.integers(0, vocab, size=prefix_len)]
    reqs, t = [], 0.0
    lo_t, hi_t = tail_len
    lo_n, hi_n = max_new
    for i in range(n):
        t += float(rng.exponential(1.0 / rate))
        tail = [int(x) for x in
                rng.integers(0, vocab,
                             size=int(rng.integers(lo_t, hi_t + 1)))]
        reqs.append(Request(
            rid=i, prompt=prefix + tail,
            max_new_tokens=int(rng.integers(lo_n, hi_n + 1)),
            arrival_t=t, eos_id=eos_id))
    return reqs


class ServeLoop:
    """Continuous-batching serve loop over one model replica.

    `mode` picks the scheduler ("continuous" vs the "static" A/B
    baseline); the engine paths are identical either way — the A/B
    isolates the scheduling policy. `load_reporter`, when set, is called
    every `report_interval` boundaries with (queue_depth, batch_fill,
    kv_occupancy) — wire it to runner.elastic.worker.report_serve_load
    to drive the driver's queue-depth autoscaler.

    Serving-v2 knobs (None = read the HVD_SERVE_* env knob):

    - ``prefix_cache``: radix-tree shared-prefix KV reuse
      (HVD_SERVE_PREFIX_CACHE, default on). Hits share pages and
      chunk-fill only the novel suffix.
    - ``spec_tokens``: speculative draft length k
      (HVD_SERVE_SPEC_TOKENS, default 0 = off). ``drafter`` plugs in
      any ``propose(context, k)`` implementation (default
      :class:`~horovod_tpu.serving.speculate.NGramDrafter`).
    - ``prefill_chunk``: tokens per chunked-prefill call (default
      2 pages); ``batch_prefill=False`` forces the per-request prefill
      fallback (the counted A/B baseline).
    """

    def __init__(self, params, cfg, geo=None, mesh=None,
                 max_batch=DEFAULT_MAX_BATCH, mode="continuous",
                 load_reporter=None, report_interval=16,
                 prefix_cache=None, spec_tokens=None, drafter=None,
                 prefill_chunk=None, batch_prefill=True):
        if geo is None:
            geo = kv_cache.geometry(DEFAULT_KV_PAGES, DEFAULT_PAGE_SIZE,
                                    cfg.max_seq_len)
        knobs = serve_knobs()
        use_prefix = (knobs["prefix_cache"] != 0 if prefix_cache is None
                      else bool(prefix_cache))
        self.spec_tokens = max(0, knobs["spec_tokens"]
                               if spec_tokens is None else int(spec_tokens))
        self.params = params
        self.cfg = cfg
        self.geo = geo
        self.mesh = mesh
        self.max_batch = int(max_batch)
        self.mode = mode
        self.load_reporter = load_reporter
        self.report_interval = int(report_interval)
        self.prefill_chunk = (min(geo.max_kv, 2 * geo.page_size)
                              if prefill_chunk is None
                              else int(prefill_chunk))
        self.prefill_fn = engine.make_prefill(cfg, geo, mesh)
        self.decode_fn = engine.make_decode_step(cfg, geo, mesh, max_batch)
        self.bprefill_fn = (engine.make_batched_prefill(cfg, geo, mesh)
                            if batch_prefill and self.max_batch > 1
                            else None)
        self.chunk_fn = (engine.make_chunk_step(
            cfg, geo, mesh, q_len=self.prefill_chunk)
            if use_prefix else None)
        self.spec_fn = (engine.make_chunk_step(
            cfg, geo, mesh, q_len=self.spec_tokens + 1)
            if self.spec_tokens > 0 else None)
        self.drafter = drafter if drafter is not None \
            else speculate.NGramDrafter()
        self.cache = kv_cache.make_cache(cfg, geo, mesh)
        self.alloc = PageAllocator(geo.n_pages, geo.page_size)
        self.prefix = PrefixCache(self.alloc) if use_prefix else None
        self.batcher = ContinuousBatcher(self.alloc, max_batch, mode,
                                         prefix_cache=self.prefix,
                                         spec_tokens=self.spec_tokens)
        self.loop_stats = {"prefill_single": 0, "prefill_batched": 0,
                           "prefill_batch_calls": 0, "chunk_fills": 0}
        self._fills = {}   # rid -> (admit_seq, tokens materialized)

    def warmup(self):
        """Compile every engine jit outside any measured window. Every
        cache write routes to trash page 0 (all-zero block table,
        all-inactive batch), so the cache stays semantically untouched.
        bench.py calls this before starting the A/B clock so compile
        time never pollutes the throughput comparison."""
        toks = np.zeros(self.geo.max_kv, np.int32)
        bt = np.zeros(self.geo.max_blocks, np.int32)
        self.cache, logits = self.prefill_fn(
            self.params, self.cache, toks, np.int32(1), bt)
        int(engine.greedy(logits))
        B, mb = self.max_batch, self.geo.max_blocks
        self.cache, logits = self.decode_fn(
            self.params, self.cache, np.zeros(B, np.int32),
            np.zeros(B, np.int32), np.zeros((B, mb), np.int32),
            np.zeros(B, bool))
        np.asarray(engine.greedy(logits))
        if self.bprefill_fn is not None:
            self.cache, logits = self.bprefill_fn(
                self.params, self.cache,
                np.zeros((B, self.geo.max_kv), np.int32),
                np.ones(B, np.int32), np.zeros((B, mb), np.int32),
                np.zeros(B, bool))
            np.asarray(engine.greedy(logits))
        if self.chunk_fn is not None:
            self.cache, logits = self.chunk_fn(
                self.params, self.cache,
                np.zeros((1, self.prefill_chunk), np.int32),
                np.zeros(1, np.int32), np.zeros((1, mb), np.int32),
                np.zeros(1, bool))
            np.asarray(engine.greedy(logits))
        if self.spec_fn is not None:
            self.cache, logits = self.spec_fn(
                self.params, self.cache,
                np.zeros((B, self.spec_tokens + 1), np.int32),
                np.zeros(B, np.int32), np.zeros((B, mb), np.int32),
                np.zeros(B, bool))
            np.asarray(engine.greedy(logits))

    # -- per-request engine calls ----------------------------------------

    def _prefill(self, req):
        """Run the request's full (re-)prefill and return its next
        token — the counted singleton fallback path."""
        ctx = list(req.prompt) + list(req.generated)
        toks = np.zeros(self.geo.max_kv, np.int32)
        toks[:len(ctx)] = ctx
        bt = np.asarray(self.batcher.block_table(req, self.geo.max_blocks),
                        np.int32)
        with _spans.span("serve.prefill", cat="serve", rid=req.rid,
                         context=len(ctx)):
            self.cache, logits = self.prefill_fn(
                self.params, self.cache, toks, np.int32(len(ctx)), bt)
        self.loop_stats["prefill_single"] += 1
        return int(engine.greedy(logits))

    def _batched_prefill(self, group):
        """All of `group`'s full prefills in ONE padded call; returns
        {slot: first token}. Rows beyond the group are inactive (trash
        writes)."""
        B, mb, pad = self.max_batch, self.geo.max_blocks, self.geo.max_kv
        toks = np.zeros((B, pad), np.int32)
        lengths = np.ones(B, np.int32)
        tables = np.zeros((B, mb), np.int32)
        active = np.zeros(B, bool)
        for row, req in enumerate(group):
            ctx = list(req.prompt) + list(req.generated)
            toks[row, :len(ctx)] = ctx
            lengths[row] = len(ctx)
            tables[row] = self.batcher.block_table(req, mb)
            active[row] = True
        with _spans.span("serve.prefill", cat="serve", batched=len(group),
                         context=int(lengths[:len(group)].sum())):
            self.cache, logits = self.bprefill_fn(
                self.params, self.cache, toks, lengths, tables, active)
        out = np.asarray(engine.greedy(logits))
        self.loop_stats["prefill_batched"] += len(group)
        self.loop_stats["prefill_batch_calls"] += 1
        return {req.slot: int(out[row]) for row, req in enumerate(group)}

    def _chunk_fill(self, req):
        """Advance a prefix-hit request's suffix fill by ONE chunk.
        Returns (done, first_token_or_None); `done` means the whole
        context is materialized and the final chunk's last real
        position produced the request's next token."""
        ctx = list(req.prompt) + list(req.generated)
        target = len(ctx)
        state = self._fills.get(req.rid)
        filled = (state[1] if state is not None
                  and state[0] == req.admit_seq else req.cached_tokens)
        end = min(filled + self.prefill_chunk, target)
        toks = np.zeros((1, self.prefill_chunk), np.int32)
        toks[0, :end - filled] = ctx[filled:end]
        bt = np.asarray(
            self.batcher.block_table(req, self.geo.max_blocks),
            np.int32)[None]
        with _spans.span("serve.chunk_prefill", cat="serve", rid=req.rid,
                         start=filled, end=end, target=target):
            self.cache, logits = self.chunk_fn(
                self.params, self.cache, toks,
                np.asarray([filled], np.int32), bt, np.ones(1, bool))
        self.loop_stats["chunk_fills"] += 1
        if end >= target:
            self._fills.pop(req.rid, None)
            out = np.asarray(engine.greedy(logits))
            return True, int(out[0, end - 1 - filled])
        self._fills[req.rid] = (req.admit_seq, end)
        return False, None

    def _decode(self, ready):
        """One jit'd decode step over the fully-prefilled slots; returns
        {slot: token}."""
        B, mb = self.max_batch, self.geo.max_blocks
        tokens = np.zeros(B, np.int32)
        positions = np.zeros(B, np.int32)
        tables = np.zeros((B, mb), np.int32)
        active = np.zeros(B, bool)
        for slot, req in ready.items():
            tokens[slot] = req.generated[-1]
            positions[slot] = req.context_len - 1
            tables[slot] = self.batcher.block_table(req, mb)
            active[slot] = True
        with _spans.span("serve.decode_step", cat="serve",
                         fill=self.batcher.batch_fill()):
            self.cache, logits = self.decode_fn(
                self.params, self.cache, tokens, positions, tables, active)
        out = np.asarray(engine.greedy(logits))
        return {s: int(out[s]) for s in ready}

    def _spec_decode(self, ready):
        """One speculative step over the fully-prefilled slots: draft k
        tokens per slot, score [last, d_1..d_k] in a single q_len=k+1
        target pass, resolve accept/reject host-side. Returns
        {slot: [accepted tokens + bonus]} — 1 to k+1 tokens per slot,
        bit-identical to what k+1 plain greedy steps would emit."""
        B, mb = self.max_batch, self.geo.max_blocks
        k = self.spec_tokens
        tokens = np.zeros((B, k + 1), np.int32)
        positions = np.zeros(B, np.int32)
        tables = np.zeros((B, mb), np.int32)
        active = np.zeros(B, bool)
        drafts = {}
        for slot, req in ready.items():
            ctx = list(req.prompt) + list(req.generated)
            d = list(self.drafter.propose(ctx, k))[:k]
            d += [0] * (k - len(d))   # padded lanes are just cheap guesses
            drafts[slot] = d
            tokens[slot] = [ctx[-1]] + d
            positions[slot] = len(ctx) - 1
            tables[slot] = self.batcher.block_table(req, mb)
            active[slot] = True
        with _spans.span("serve.spec_step", cat="serve", draft_k=k,
                         fill=self.batcher.batch_fill()):
            self.cache, logits = self.spec_fn(
                self.params, self.cache, tokens, positions, tables, active)
        out = np.asarray(engine.greedy(logits))        # [B, k+1]
        result = {}
        st = self.batcher.stats
        for slot, req in ready.items():
            emitted, _, rejected = speculate.accept_drafts(
                drafts[slot], [int(x) for x in out[slot]])
            # The request's remaining token budget (max_new and cache
            # room) bounds what the boundary may consume.
            room = min(req.max_new_tokens - len(req.generated),
                       self.geo.max_kv - req.context_len)
            emitted = emitted[:max(1, room)]
            st["spec_steps"] += 1
            st["spec_accepted"] += len(emitted) - 1
            st["spec_rejected"] += rejected
            result[slot] = emitted
        return result

    # -- the loop ---------------------------------------------------------

    def run(self, requests, clock=time.monotonic):
        """Serve `requests` (arrival_t = seconds from start) to
        completion; returns (summary dict, finished Request list)."""
        for r in requests:
            if r.prompt_len >= self.geo.max_kv:
                raise ValueError(f"request {r.rid}: prompt {r.prompt_len} "
                                 f">= cache context {self.geo.max_kv}")
            # Cap generation to the cache geometry so a block table can
            # never overflow mid-decode.
            r.max_new_tokens = min(r.max_new_tokens,
                                   self.geo.max_kv - r.prompt_len)
        pending = sorted(requests, key=lambda r: r.arrival_t)
        token_times = {}          # rid -> [t, ...] production timestamps
        finished = []
        prefilled = {}            # rid -> admit_seq at last prefill
        fill_samples, occ_samples = [], []
        boundaries = 0
        wall_t0_us = time.time_ns() // 1000
        t0 = clock()
        preempt_seen = 0
        pfx_evict_seen = 0
        spec_rej_seen = 0

        def _now():
            return clock() - t0

        def _boundary(done, produced_at):
            nonlocal preempt_seen, boundaries, pfx_evict_seen, spec_rej_seen
            for req in done:
                prefilled.pop(req.rid, None)
                self._fills.pop(req.rid, None)
                finished.append(req)
                ttft = req.first_token_t - req.arrival_t
                _metrics.SERVE_TTFT_SECONDS.observe(max(0.0, ttft))
                gaps = np.diff(token_times.get(req.rid, []))
                if len(gaps):
                    _metrics.SERVE_ITL_SECONDS.observe(float(np.mean(gaps)))
                _spans.event("serve.request",
                             wall_t0_us + req.arrival_t * 1e6,
                             (req.finished_t - req.arrival_t) * 1e6,
                             cat="serve", rid=req.rid,
                             tokens=len(req.generated),
                             reason=req.finish_reason,
                             preemptions=req.preemptions,
                             cached_tokens=req.cached_tokens,
                             ttft_ms=round(ttft * 1e3, 3))
            _metrics.SERVE_QUEUE_DEPTH.set(self.batcher.queue_depth())
            _metrics.SERVE_BATCH_FILL.set(self.batcher.batch_fill())
            _metrics.SERVE_KV_OCCUPANCY.set(self.batcher.kv_occupancy())
            _metrics.SERVE_TOKENS.inc(len(produced_at))
            new_preempt = self.batcher.stats["preemptions"] - preempt_seen
            if new_preempt:
                _metrics.SERVE_PREEMPTIONS.inc(new_preempt)
                preempt_seen = self.batcher.stats["preemptions"]
            # Kill-switch contract: with the feature off these metric
            # objects see ZERO activity (no set, no inc).
            if self.prefix is not None:
                _metrics.SERVE_PREFIX_HIT_RATIO.set(
                    self.batcher.prefix_hit_ratio())
                new_ev = self.prefix.stats["evictions"] - pfx_evict_seen
                if new_ev:
                    _metrics.SERVE_PREFIX_EVICTIONS.inc(new_ev)
                    pfx_evict_seen = self.prefix.stats["evictions"]
            if self.spec_tokens > 0:
                st = self.batcher.stats
                if st["spec_steps"]:
                    _metrics.SERVE_SPEC_ACCEPTED_PER_STEP.set(
                        st["spec_accepted"] / st["spec_steps"])
                new_rej = st["spec_rejected"] - spec_rej_seen
                if new_rej:
                    _metrics.SERVE_SPEC_REJECTED.inc(new_rej)
                    spec_rej_seen = st["spec_rejected"]
            fill_samples.append(self.batcher.batch_fill())
            occ_samples.append(self.batcher.kv_occupancy())
            boundaries += 1
            self._publish()
            if (self.load_reporter is not None
                    and boundaries % self.report_interval == 0):
                self.load_reporter(self.batcher.queue_depth(),
                                   self.batcher.batch_fill(),
                                   self.batcher.kv_occupancy())

        def _emit(by_slot):
            """Feed produced tokens through the scheduler boundary with
            timestamps for exactly the tokens the boundary will keep."""
            t = _now()
            rids = []
            for s, toks in by_slot.items():
                req = self.batcher.running[s]
                rids.append(req.rid)
                toks = [toks] if isinstance(toks, int) else toks
                kept, gen = 0, len(req.generated)
                for tok in toks:
                    kept += 1
                    gen += 1
                    if tok == req.eos_id or gen >= req.max_new_tokens:
                        break
                token_times.setdefault(req.rid, []).extend([t] * kept)
            done = self.batcher.on_tokens(by_slot, t)
            _boundary(done, rids)

        while pending or not self.batcher.idle():
            now = _now()
            while pending and pending[0].arrival_t <= now:
                self.batcher.submit(pending.pop(0), now)
            self.batcher.admit(now)
            # Prefill anything (re-)admitted since its last prefill.
            # Cache-miss prompts (cached_tokens == 0) take the full
            # prefill — batched when several admitted at this boundary —
            # and each completion's token runs a boundary which may
            # admit more, so rescan. Prefix hits advance ONE chunk per
            # outer boundary (the `advanced` set) so a long suffix
            # interleaves with decode steps instead of stalling them.
            advanced = set()
            while True:
                todo = [r for r in self.batcher.running.values()
                        if prefilled.get(r.rid) != r.admit_seq]
                plain = sorted((r for r in todo if r.cached_tokens == 0),
                               key=lambda r: r.admit_seq)
                if plain:
                    if self.bprefill_fn is not None and len(plain) > 1:
                        by_slot = self._batched_prefill(plain)
                        for r in plain:
                            prefilled[r.rid] = r.admit_seq
                            self.batcher.register_prefilled(r)
                        _emit(by_slot)
                    else:
                        req = plain[0]
                        tok = self._prefill(req)
                        prefilled[req.rid] = req.admit_seq
                        self.batcher.register_prefilled(req)
                        _emit({req.slot: tok})
                    continue
                progressed = False
                for req in sorted(todo, key=lambda r: r.admit_seq):
                    if req.rid in advanced:
                        continue
                    advanced.add(req.rid)
                    progressed = True
                    done_fill, tok = self._chunk_fill(req)
                    if done_fill:
                        prefilled[req.rid] = req.admit_seq
                        self.batcher.register_prefilled(req)
                        _emit({req.slot: tok})
                        break   # boundary may have changed the todo set
                if not progressed:
                    break
            ready = {s: r for s, r in self.batcher.running.items()
                     if prefilled.get(r.rid) == r.admit_seq}
            if ready:
                if self.spec_fn is not None:
                    _emit(self._spec_decode(ready))
                else:
                    _emit(self._decode(ready))
            elif not self.batcher.running and pending:
                # Idle until the next arrival (open loop: don't spin).
                time.sleep(min(0.005,
                               max(0.0, pending[0].arrival_t - _now())))

        summary = self._summary(finished, token_times, _now(),
                                fill_samples, occ_samples)
        self._publish()
        return summary, finished

    def _publish(self):
        """Refresh the hvd.serve_stats() snapshot."""
        st = self.batcher.stats
        snap = {
            "mode": self.mode,
            "queue_depth": self.batcher.queue_depth(),
            "batch_fill": round(self.batcher.batch_fill(), 4),
            "kv_occupancy": round(self.batcher.kv_occupancy(), 4),
            "tokens": st["tokens"],
            "admissions": st["admissions"],
            "preemptions": st["preemptions"],
            "prefix_cache": self.prefix is not None,
            "prefix_hit_ratio": round(self.batcher.prefix_hit_ratio(), 4),
            "prefix_evictions": (self.prefix.stats["evictions"]
                                 if self.prefix is not None else 0),
            "prefix_nodes": (len(self.prefix)
                             if self.prefix is not None else 0),
            "spec_tokens": self.spec_tokens,
            "spec_steps": st["spec_steps"],
            "spec_accepted_per_step": round(
                st["spec_accepted"] / st["spec_steps"], 4)
            if st["spec_steps"] else 0.0,
            "spec_rejected": st["spec_rejected"],
        }
        snap.update(self.loop_stats)
        _LAST_STATS.clear()
        _LAST_STATS.update(snap)

    def _summary(self, finished, token_times, duration, fills, occs):
        ttfts = [r.first_token_t - r.arrival_t for r in finished]
        gaps = np.concatenate(
            [np.diff(ts) for ts in token_times.values() if len(ts) > 1]
        ) if any(len(ts) > 1 for ts in token_times.values()) else np.array([0.0])
        tokens = sum(len(r.generated) for r in finished)
        st = self.batcher.stats
        return {
            "mode": self.mode,
            "requests": len(finished),
            "tokens": int(tokens),
            "duration_s": round(float(duration), 4),
            "tok_s": round(tokens / max(duration, 1e-9), 2),
            "ttft_p50_ms": _pct_ms(ttfts, 50),
            "ttft_p99_ms": _pct_ms(ttfts, 99),
            "itl_p50_ms": _pct_ms(gaps, 50),
            "itl_p99_ms": _pct_ms(gaps, 99),
            "batch_fill_mean": round(float(np.mean(fills)), 4) if fills
            else 0.0,
            "kv_occupancy_mean": round(float(np.mean(occs)), 4) if occs
            else 0.0,
            "preemptions": st["preemptions"],
            "prefix_hit_ratio": round(self.batcher.prefix_hit_ratio(), 4),
            "prefix_evictions": (self.prefix.stats["evictions"]
                                 if self.prefix is not None else 0),
            "spec_steps": st["spec_steps"],
            "spec_accepted_per_step": round(
                st["spec_accepted"] / st["spec_steps"], 4)
            if st["spec_steps"] else 0.0,
            "spec_rejected": st["spec_rejected"],
            "prefill_single": self.loop_stats["prefill_single"],
            "prefill_batched": self.loop_stats["prefill_batched"],
            "prefill_batch_calls": self.loop_stats["prefill_batch_calls"],
            "chunk_fills": self.loop_stats["chunk_fills"],
        }


def _pct_ms(xs, q):
    if not len(xs):
        return 0.0
    return round(float(np.percentile(np.asarray(xs), q)) * 1e3, 3)
