"""The serve loop: open-loop Poisson load in, tokens + latency spans out.

One iteration = one token boundary:

1. submit every request whose (open-loop) arrival time has passed —
   arrivals do NOT wait for capacity; the queue absorbs bursts and the
   queue DEPTH is what the autoscaler watches,
2. admit + prefill newcomers (each prefill emits the request's first
   token — TTFT is arrival → that token, queueing and prefill included),
3. one jit'd decode step over every occupied slot,
4. feed the tokens back through the scheduler boundary (evict finished,
   grow pages, admit into the freed slots) and sample the SERVE_* gauges.

Latency accounting (docs/serving.md has the formal definitions):
TTFT = first_token_t - arrival_t per request; inter-token latency (ITL)
= the gaps between a request's consecutive token timestamps. The
summary reports p50/p99 over all requests' TTFTs and over ALL gaps.

Every request also becomes one ``serve.request`` span (arrival →
finish, with rid/tokens/ttft_ms args) on the observability timeline, so
a merged trace shows request lifetimes above the per-step
``serve.prefill`` / ``serve.decode_step`` spans.
"""

import time

import numpy as np

from ..observability import metrics as _metrics
from ..observability import spans as _spans
from . import engine, kv_cache
from .scheduler import (DEFAULT_KV_PAGES, DEFAULT_MAX_BATCH,
                        DEFAULT_PAGE_SIZE, ContinuousBatcher, PageAllocator,
                        Request)


def poisson_requests(n, rate, rng, prompt_len=(4, 32), max_new=(4, 64),
                     vocab=256, eos_id=-1):
    """Synthetic open-loop load: `n` requests with exponential
    inter-arrival gaps (rate = requests/second) and uniform prompt /
    max-new-token draws. The max_new spread is what continuous batching
    monetizes: short requests finish early and their slots refill while
    a static batch would idle them until the longest request drains."""
    reqs, t = [], 0.0
    lo_p, hi_p = prompt_len
    lo_n, hi_n = max_new
    for i in range(n):
        t += float(rng.exponential(1.0 / rate))
        prompt = rng.integers(0, vocab,
                              size=int(rng.integers(lo_p, hi_p + 1)))
        reqs.append(Request(
            rid=i, prompt=[int(x) for x in prompt],
            max_new_tokens=int(rng.integers(lo_n, hi_n + 1)),
            arrival_t=t, eos_id=eos_id))
    return reqs


class ServeLoop:
    """Continuous-batching serve loop over one model replica.

    `mode` picks the scheduler ("continuous" vs the "static" A/B
    baseline); the engine paths are identical either way — the A/B
    isolates the scheduling policy. `load_reporter`, when set, is called
    every `report_interval` boundaries with (queue_depth, batch_fill,
    kv_occupancy) — wire it to runner.elastic.worker.report_serve_load
    to drive the driver's queue-depth autoscaler."""

    def __init__(self, params, cfg, geo=None, mesh=None,
                 max_batch=DEFAULT_MAX_BATCH, mode="continuous",
                 load_reporter=None, report_interval=16):
        if geo is None:
            geo = kv_cache.geometry(DEFAULT_KV_PAGES, DEFAULT_PAGE_SIZE,
                                    cfg.max_seq_len)
        self.params = params
        self.cfg = cfg
        self.geo = geo
        self.mesh = mesh
        self.max_batch = int(max_batch)
        self.mode = mode
        self.load_reporter = load_reporter
        self.report_interval = int(report_interval)
        self.prefill_fn = engine.make_prefill(cfg, geo, mesh)
        self.decode_fn = engine.make_decode_step(cfg, geo, mesh, max_batch)
        self.cache = kv_cache.make_cache(cfg, geo, mesh)
        self.alloc = PageAllocator(geo.n_pages, geo.page_size)
        self.batcher = ContinuousBatcher(self.alloc, max_batch, mode)

    def warmup(self):
        """Compile the prefill/decode/argmax jits outside any measured
        window. Every cache write routes to trash page 0 (all-zero block
        table, all-inactive batch), so the cache stays semantically
        untouched. bench.py calls this before starting the A/B clock so
        compile time never pollutes the throughput comparison."""
        toks = np.zeros(self.geo.max_kv, np.int32)
        bt = np.zeros(self.geo.max_blocks, np.int32)
        self.cache, logits = self.prefill_fn(
            self.params, self.cache, toks, np.int32(1), bt)
        int(engine.greedy(logits))
        B = self.max_batch
        self.cache, logits = self.decode_fn(
            self.params, self.cache, np.zeros(B, np.int32),
            np.zeros(B, np.int32),
            np.zeros((B, self.geo.max_blocks), np.int32),
            np.zeros(B, bool))
        np.asarray(engine.greedy(logits))

    # -- per-request engine calls ----------------------------------------

    def _prefill(self, req):
        """Run the request's (re-)prefill and return its next token."""
        ctx = list(req.prompt) + list(req.generated)
        toks = np.zeros(self.geo.max_kv, np.int32)
        toks[:len(ctx)] = ctx
        bt = np.asarray(self.batcher.block_table(req, self.geo.max_blocks),
                        np.int32)
        with _spans.span("serve.prefill", cat="serve", rid=req.rid,
                         context=len(ctx)):
            self.cache, logits = self.prefill_fn(
                self.params, self.cache, toks, np.int32(len(ctx)), bt)
        return int(engine.greedy(logits))

    def _decode(self):
        """One jit'd decode step over every occupied slot; returns
        {slot: token}."""
        B, mb = self.max_batch, self.geo.max_blocks
        tokens = np.zeros(B, np.int32)
        positions = np.zeros(B, np.int32)
        tables = np.zeros((B, mb), np.int32)
        active = np.zeros(B, bool)
        for slot, req in self.batcher.running.items():
            tokens[slot] = req.generated[-1]
            positions[slot] = req.context_len - 1
            tables[slot] = self.batcher.block_table(req, mb)
            active[slot] = True
        with _spans.span("serve.decode_step", cat="serve",
                         fill=self.batcher.batch_fill()):
            self.cache, logits = self.decode_fn(
                self.params, self.cache, tokens, positions, tables, active)
        out = np.asarray(engine.greedy(logits))
        return {s: int(out[s]) for s in list(self.batcher.running)}

    # -- the loop ---------------------------------------------------------

    def run(self, requests, clock=time.monotonic):
        """Serve `requests` (arrival_t = seconds from start) to
        completion; returns (summary dict, finished Request list)."""
        for r in requests:
            if r.prompt_len >= self.geo.max_kv:
                raise ValueError(f"request {r.rid}: prompt {r.prompt_len} "
                                 f">= cache context {self.geo.max_kv}")
            # Cap generation to the cache geometry so a block table can
            # never overflow mid-decode.
            r.max_new_tokens = min(r.max_new_tokens,
                                   self.geo.max_kv - r.prompt_len)
        pending = sorted(requests, key=lambda r: r.arrival_t)
        token_times = {}          # rid -> [t, ...] production timestamps
        finished = []
        prefilled = {}            # rid -> admit_seq at last prefill
        fill_samples, occ_samples = [], []
        boundaries = 0
        wall_t0_us = time.time_ns() // 1000
        t0 = clock()
        preempt_seen = 0

        def _now():
            return clock() - t0

        def _boundary(done, produced_at):
            nonlocal preempt_seen, boundaries
            for req in done:
                prefilled.pop(req.rid, None)
                finished.append(req)
                ttft = req.first_token_t - req.arrival_t
                _metrics.SERVE_TTFT_SECONDS.observe(max(0.0, ttft))
                gaps = np.diff(token_times.get(req.rid, []))
                if len(gaps):
                    _metrics.SERVE_ITL_SECONDS.observe(float(np.mean(gaps)))
                _spans.event("serve.request",
                             wall_t0_us + req.arrival_t * 1e6,
                             (req.finished_t - req.arrival_t) * 1e6,
                             cat="serve", rid=req.rid,
                             tokens=len(req.generated),
                             reason=req.finish_reason,
                             preemptions=req.preemptions,
                             ttft_ms=round(ttft * 1e3, 3))
            _metrics.SERVE_QUEUE_DEPTH.set(self.batcher.queue_depth())
            _metrics.SERVE_BATCH_FILL.set(self.batcher.batch_fill())
            _metrics.SERVE_KV_OCCUPANCY.set(self.batcher.kv_occupancy())
            _metrics.SERVE_TOKENS.inc(len(produced_at))
            new_preempt = self.batcher.stats["preemptions"] - preempt_seen
            if new_preempt:
                _metrics.SERVE_PREEMPTIONS.inc(new_preempt)
                preempt_seen = self.batcher.stats["preemptions"]
            fill_samples.append(self.batcher.batch_fill())
            occ_samples.append(self.batcher.kv_occupancy())
            boundaries += 1
            if (self.load_reporter is not None
                    and boundaries % self.report_interval == 0):
                self.load_reporter(self.batcher.queue_depth(),
                                   self.batcher.batch_fill(),
                                   self.batcher.kv_occupancy())

        while pending or not self.batcher.idle():
            now = _now()
            while pending and pending[0].arrival_t <= now:
                self.batcher.submit(pending.pop(0), now)
            self.batcher.admit(now)
            # Prefill anything (re-)admitted since its last prefill. Each
            # prefill's token runs a boundary, which may admit more — so
            # rescan until the running set is fully prefilled.
            while True:
                todo = [r for r in self.batcher.running.values()
                        if prefilled.get(r.rid) != r.admit_seq]
                if not todo:
                    break
                req = min(todo, key=lambda r: r.admit_seq)
                tok = self._prefill(req)
                prefilled[req.rid] = req.admit_seq
                t = _now()
                token_times.setdefault(req.rid, []).append(t)
                done = self.batcher.on_tokens({req.slot: tok}, t)
                _boundary(done, (req.rid,))
            if self.batcher.running:
                by_slot = self._decode()
                t = _now()
                rids = [self.batcher.running[s].rid for s in by_slot]
                for s in by_slot:
                    token_times.setdefault(
                        self.batcher.running[s].rid, []).append(t)
                done = self.batcher.on_tokens(by_slot, t)
                _boundary(done, rids)
            elif pending:
                # Idle until the next arrival (open loop: don't spin).
                time.sleep(min(0.005,
                               max(0.0, pending[0].arrival_t - _now())))

        return self._summary(finished, token_times, _now(),
                             fill_samples, occ_samples), finished

    def _summary(self, finished, token_times, duration, fills, occs):
        ttfts = [r.first_token_t - r.arrival_t for r in finished]
        gaps = np.concatenate(
            [np.diff(ts) for ts in token_times.values() if len(ts) > 1]
        ) if any(len(ts) > 1 for ts in token_times.values()) else np.array([0.0])
        tokens = sum(len(r.generated) for r in finished)
        return {
            "mode": self.mode,
            "requests": len(finished),
            "tokens": int(tokens),
            "duration_s": round(float(duration), 4),
            "tok_s": round(tokens / max(duration, 1e-9), 2),
            "ttft_p50_ms": _pct_ms(ttfts, 50),
            "ttft_p99_ms": _pct_ms(ttfts, 99),
            "itl_p50_ms": _pct_ms(gaps, 50),
            "itl_p99_ms": _pct_ms(gaps, 99),
            "batch_fill_mean": round(float(np.mean(fills)), 4) if fills
            else 0.0,
            "kv_occupancy_mean": round(float(np.mean(occs)), 4) if occs
            else 0.0,
            "preemptions": self.batcher.stats["preemptions"],
        }


def _pct_ms(xs, q):
    if not len(xs):
        return 0.0
    return round(float(np.percentile(np.asarray(xs), q)) * 1e3, 3)
