"""Decode engine: jit'd prefill + single-token decode over the paged KV
cache, built directly on :mod:`horovod_tpu.models.transformer` params.

Two compiled paths, compiled ONCE each regardless of the request mix:

- **prefill**: one request's (padded) prompt through the full causal
  forward, writing every layer's K/V into the request's pages via its
  block table and returning the last real position's logits. Padding
  rows compute garbage that is either overwritten by the first decode
  write or masked by the decode read — never branched on.
- **decode_step**: ONE token for every batch slot simultaneously —
  embed at the slot's position, append K/V into the page slot the
  block table names, attend over the gathered pages under a
  ``kv_pos <= position`` causal mask, next-token logits out. Inactive
  slots run the same program with their writes routed to trash page 0.

Both paths resolve their attention kernel through
``transformer.resolve_attn`` with the REAL (q_len, kv_len, causal)
shape — the decode step is q_len=1 against ``max_kv`` cached tokens,
which must resolve to "gather" (a [B,H,1,KV] score tensor is linear in
KV; there is nothing for flash's q-tiling to eliminate). That contract
is exactly the heuristic fix this module forced (resolve_attn keyed on
query length alone would also have misfiled long chunked prefills).

The batch-slot ↔ request mapping, page ownership, and admission policy
live host-side in :mod:`.scheduler`; this module never allocates.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models import transformer as tfm
from ..models.transformer import _ffn, _layer_norm, _moe_ffn
from . import kv_cache


def _constrain(x, mesh, spec):
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec))


def _check_decode_impl(cfg, geo, mesh):
    impl = tfm.resolve_attn(cfg, 1, mesh, kv_len=geo.max_kv, causal=True)
    if impl != "gather":
        raise ValueError(
            f"serving decode needs the gather attention path for its "
            f"q_len=1 paged reads, but attn_impl={cfg.attn_impl!r} "
            f"resolved to {impl!r}; use attn_impl='auto' or 'gather'")


def _ffn_block(x, layer, cfg):
    h = _layer_norm(x, layer["ln2"])
    if cfg.n_experts > 0:
        return x + _moe_ffn(h, layer, cfg)
    return x + _ffn(h, layer, cfg)


def _qkv(h, layer, cfg):
    qkv = jnp.einsum("bsd,dchk->cbshk", h,
                     layer["wqkv"].astype(cfg.compute_dtype))
    return qkv[0], qkv[1], qkv[2]


def make_prefill(cfg, geo, mesh=None, prefill_pad=None):
    """Compiled ``(params, cache, tokens, length, block_table) ->
    (cache, logits)``.

    tokens: [prefill_pad] int32 (zero-padded); length: scalar int32 real
    token count; block_table: [max_blocks] int32 page ids (trash 0 past
    the owned pages). Returns the updated cache and the last REAL
    position's next-token logits [vocab] (float32).

    ``prefill_pad`` defaults to the full cache width ``geo.max_kv`` so a
    preempted request can replay prompt + generated prefix through the
    same compiled program; it must cover whole pages.
    """
    _check_decode_impl(cfg, geo, mesh)
    pad = geo.max_kv if prefill_pad is None else int(prefill_pad)
    if pad % geo.page_size != 0:
        raise ValueError(f"prefill_pad {pad} must be a multiple of "
                         f"page_size {geo.page_size}")
    if pad > cfg.max_seq_len:
        raise ValueError(
            f"prefill_pad {pad} exceeds the model's max_seq_len "
            f"{cfg.max_seq_len} (pos_embed rows); shrink the cache "
            f"geometry or raise max_seq_len")
    n_blocks = pad // geo.page_size
    dt = cfg.compute_dtype
    kv_spec = kv_cache.spec(cfg)

    def prefill(params, cache, tokens, length, block_table):
        x = params["embed"].astype(dt)[tokens][None]
        x = x + params["pos_embed"].astype(dt)[:pad][None]
        ck, cv = cache["k"], cache["v"]
        scale = 1.0 / math.sqrt(cfg.head_dim)
        mask = jnp.tril(jnp.ones((pad, pad), bool))
        for li, layer in enumerate(params["layers"]):
            h = _layer_norm(x, layer["ln1"])
            q, k, v = _qkv(h, layer, cfg)
            # Page write: [1, pad, H, dh] -> [n_blocks, page, H, dh]
            # scattered through the block table (garbage past `length`
            # lands in owned-page slots the decode mask hides, or in
            # trash page 0).
            kp = k[0].reshape(n_blocks, geo.page_size,
                              cfg.n_heads, cfg.head_dim)
            vp = v[0].reshape(n_blocks, geo.page_size,
                              cfg.n_heads, cfg.head_dim)
            ck = ck.at[li, block_table[:n_blocks]].set(kp)
            cv = cv.at[li, block_table[:n_blocks]].set(vp)
            # Causal self-attention — the exact _attention math from
            # models/transformer.py (parity is pinned by
            # tests/test_serving.py against forward()).
            logits = jnp.einsum("bshk,bthk->bhst", q, k) * scale
            logits = jnp.where(mask, logits, jnp.finfo(dt).min)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   -1).astype(dt)
            ctx = jnp.einsum("bhst,bthk->bshk", probs, v)
            x = x + jnp.einsum("bshk,hkd->bsd", ctx,
                               layer["wo"].astype(dt))
            x = _ffn_block(x, layer, cfg)
        x = _layer_norm(x, params["final_ln"])
        last = jnp.take(x[0], length - 1, axis=0)
        logits = jnp.einsum("d,vd->v", last, params["embed"].astype(dt))
        ck = _constrain(ck, mesh, kv_spec)
        cv = _constrain(cv, mesh, kv_spec)
        return {"k": ck, "v": cv}, logits.astype(jnp.float32)

    return jax.jit(prefill, donate_argnums=(1,))


def make_decode_step(cfg, geo, mesh=None, max_batch=8):
    """Compiled ``(params, cache, tokens, positions, block_tables,
    active) -> (cache, logits)`` — one token for every slot.

    tokens/positions/active: [max_batch] (int32/int32/bool);
    block_tables: [max_batch, max_blocks] int32. ``positions[b]`` is the
    index the slot's token is WRITTEN at (its context length before this
    step); the causal read mask is ``kv_pos <= position``, so the step
    attends to everything cached plus itself. Inactive slots write to
    trash page 0 and their logits are garbage the scheduler never reads.
    """
    _check_decode_impl(cfg, geo, mesh)
    dt = cfg.compute_dtype
    kv_spec = kv_cache.spec(cfg)
    scale = 1.0 / math.sqrt(cfg.head_dim)
    max_kv = geo.max_kv

    def decode(params, cache, tokens, positions, block_tables, active):
        x = params["embed"].astype(dt)[tokens]
        x = x + params["pos_embed"].astype(dt)[positions]
        x = x[:, None, :]                                  # [B, 1, D]
        ck, cv = cache["k"], cache["v"]
        blk = positions // geo.page_size
        slot = positions % geo.page_size
        page_ids = jnp.take_along_axis(block_tables, blk[:, None],
                                       axis=1)[:, 0]
        page_ids = jnp.where(active, page_ids, 0)          # trash route
        slot_w = jnp.where(active, slot, 0)
        kv_mask = (jnp.arange(max_kv)[None, None, :]
                   <= positions[:, None, None])            # [B, 1, KV]
        for li, layer in enumerate(params["layers"]):
            h = _layer_norm(x, layer["ln1"])
            q, k, v = _qkv(h, layer, cfg)                  # [B, 1, H, dh]
            ck = ck.at[li, page_ids, slot_w].set(k[:, 0])
            cv = cv.at[li, page_ids, slot_w].set(v[:, 0])
            # Gather the slot's pages: [B, max_blocks, page, H, dh] ->
            # [B, max_kv, H, dh]; the block table IS the indirection
            # that lets every context length share this one program.
            kp = ck[li][block_tables].reshape(
                -1, max_kv, cfg.n_heads, cfg.head_dim)
            vp = cv[li][block_tables].reshape(
                -1, max_kv, cfg.n_heads, cfg.head_dim)
            logits = jnp.einsum("bshk,bthk->bhst", q, kp) * scale
            logits = jnp.where(kv_mask[:, :, None, :].swapaxes(1, 2),
                               logits, jnp.finfo(dt).min)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   -1).astype(dt)
            ctx = jnp.einsum("bhst,bthk->bshk", probs, vp)
            x = x + jnp.einsum("bshk,hkd->bsd", ctx,
                               layer["wo"].astype(dt))
            x = _ffn_block(x, layer, cfg)
        x = _layer_norm(x, params["final_ln"])
        logits = jnp.einsum("bsd,vd->bsv", x,
                            params["embed"].astype(dt))[:, 0]
        ck = _constrain(ck, mesh, kv_spec)
        cv = _constrain(cv, mesh, kv_spec)
        return {"k": ck, "v": cv}, logits.astype(jnp.float32)

    return jax.jit(decode, donate_argnums=(1,))


def _chunk_forward(params, cache, tokens, positions, block_tables,
                   active, *, cfg, geo, mesh):
    """Shared body for every multi-token paged step: embed a [B, Q]
    token window starting at each slot's ``positions[b]``, scatter its
    K/V through the block tables, attend over the gathered pages under
    a ``kv_pos <= position`` mask. Within-window causality falls out of
    the same mask because the window's own K/V is written BEFORE the
    gather — position p sees cached history plus window positions
    <= p. Returns (ck, cv, x[B, Q, D] after final_ln)."""
    dt = cfg.compute_dtype
    q_len = tokens.shape[1]
    max_kv = geo.max_kv
    scale = 1.0 / math.sqrt(cfg.head_dim)
    pos = positions[:, None] + jnp.arange(q_len)[None, :]    # [B, Q]
    pe = jnp.clip(pos, 0, cfg.max_seq_len - 1)
    x = (params["embed"].astype(dt)[tokens]
         + params["pos_embed"].astype(dt)[pe])               # [B, Q, D]
    ck, cv = cache["k"], cache["v"]
    blk = jnp.minimum(pos // geo.page_size, geo.max_blocks - 1)
    valid = (pos < max_kv) & active[:, None]
    page_ids = jnp.take_along_axis(block_tables, blk, axis=1)
    page_ids = jnp.where(valid, page_ids, 0)                 # trash route
    slot_w = jnp.where(valid, pos % geo.page_size, 0)
    kv_mask = (jnp.arange(max_kv)[None, None, :]
               <= pos[:, :, None])                           # [B, Q, KV]
    for li, layer in enumerate(params["layers"]):
        h = _layer_norm(x, layer["ln1"])
        q, k, v = _qkv(h, layer, cfg)                        # [B, Q, H, dh]
        ck = ck.at[li, page_ids, slot_w].set(k)
        cv = cv.at[li, page_ids, slot_w].set(v)
        kp = ck[li][block_tables].reshape(
            -1, max_kv, cfg.n_heads, cfg.head_dim)
        vp = cv[li][block_tables].reshape(
            -1, max_kv, cfg.n_heads, cfg.head_dim)
        logits = jnp.einsum("bshk,bthk->bhst", q, kp) * scale
        logits = jnp.where(kv_mask[:, None, :, :], logits,
                           jnp.finfo(dt).min)
        probs = jax.nn.softmax(logits.astype(jnp.float32),
                               -1).astype(dt)
        ctx = jnp.einsum("bhst,bthk->bshk", probs, vp)
        x = x + jnp.einsum("bshk,hkd->bsd", ctx,
                           layer["wo"].astype(dt))
        x = _ffn_block(x, layer, cfg)
    return ck, cv, _layer_norm(x, params["final_ln"])


def make_chunk_step(cfg, geo, mesh=None, q_len=None):
    """Compiled ``(params, cache, tokens, positions, block_tables,
    active) -> (cache, logits)`` — a ``q_len``-token window for every
    slot, the generalization of :func:`make_decode_step` to q_len > 1.

    tokens: [B, q_len] int32; positions: [B] int32 (the index
    ``tokens[b, 0]`` is written at); block_tables: [B, max_blocks];
    active: [B] bool. Returns logits for EVERY window position
    [B, q_len, vocab] (float32) — the caller picks the rows it trusts.

    Two serving paths compile this one program (with their own shapes):

    - **chunked prefill** (B=1, q_len=prefill_chunk): a cache-miss
      suffix fills chunk-by-chunk across decode boundaries instead of
      monopolizing one with a full-width prefill. The chunk's live
      score footprint [q_len, max_kv] is exactly what
      ``transformer.resolve_attn`` tiers on — q_len is the knob that
      walks this step from gather territory toward the flash
      crossover, and the inline math below is the gather-tier kernel
      (the einsum ``_attention`` parity path; on-TPU flash tiling of
      the same mask is a drop-in behind the same signature).
    - **speculative scoring** (B=max_batch, q_len=draft_k+1): one
      batched target pass scores ``[last_token, d_1..d_k]`` per slot;
      accept/reject happens host-side (:mod:`.speculate`).

    Writes for positions past ``max_kv`` or on inactive slots route to
    trash page 0, so padded draft lanes and short final chunks are
    branch-free.
    """
    _check_decode_impl(cfg, geo, mesh)
    q_len = geo.page_size if q_len is None else int(q_len)
    if q_len < 1:
        raise ValueError(f"chunk q_len must be >= 1, got {q_len}")
    if geo.max_kv > cfg.max_seq_len:
        raise ValueError(
            f"cache width {geo.max_kv} exceeds the model's max_seq_len "
            f"{cfg.max_seq_len} (pos_embed rows); shrink the geometry")
    # Consulted for the same reason decode pins "gather": the chunk's
    # REAL (q_len, kv_len, causal) footprint decides the kernel tier.
    tfm.resolve_attn(cfg, q_len, mesh, kv_len=geo.max_kv, causal=True)
    kv_spec = kv_cache.spec(cfg)

    def chunk(params, cache, tokens, positions, block_tables, active):
        ck, cv, x = _chunk_forward(params, cache, tokens, positions,
                                   block_tables, active,
                                   cfg=cfg, geo=geo, mesh=mesh)
        logits = jnp.einsum("bsd,vd->bsv", x,
                            params["embed"].astype(cfg.compute_dtype))
        ck = _constrain(ck, mesh, kv_spec)
        cv = _constrain(cv, mesh, kv_spec)
        return {"k": ck, "v": cv}, logits.astype(jnp.float32)

    return jax.jit(chunk, donate_argnums=(1,))


def make_batched_prefill(cfg, geo, mesh=None, prefill_pad=None):
    """Compiled ``(params, cache, tokens, lengths, block_tables,
    active) -> (cache, logits)`` — ALL same-boundary admissions'
    prompts in one padded call instead of one jit dispatch each.

    tokens: [B, prefill_pad] int32 (zero-padded per row); lengths: [B]
    int32 real token counts; block_tables: [B, max_blocks]; active: [B]
    bool (padding rows route to trash page 0). Returns each row's last
    REAL position's next-token logits [B, vocab] (float32) — identical
    math to :func:`make_prefill` row by row, because both write the
    window's K/V first and attend under the same causal mask
    (tests/test_serving.py pins the parity).
    """
    pad = geo.max_kv if prefill_pad is None else int(prefill_pad)
    if pad % geo.page_size != 0:
        raise ValueError(f"prefill_pad {pad} must be a multiple of "
                         f"page_size {geo.page_size}")
    if pad > cfg.max_seq_len:
        raise ValueError(
            f"prefill_pad {pad} exceeds the model's max_seq_len "
            f"{cfg.max_seq_len} (pos_embed rows); shrink the cache "
            f"geometry or raise max_seq_len")
    _check_decode_impl(cfg, geo, mesh)
    kv_spec = kv_cache.spec(cfg)

    def bprefill(params, cache, tokens, lengths, block_tables, active):
        positions = jnp.zeros(tokens.shape[:1], jnp.int32)
        ck, cv, x = _chunk_forward(params, cache, tokens, positions,
                                   block_tables, active,
                                   cfg=cfg, geo=geo, mesh=mesh)
        last = jnp.take_along_axis(
            x, jnp.clip(lengths - 1, 0, pad - 1)[:, None, None], axis=1)
        logits = jnp.einsum("bsd,vd->bsv", last,
                            params["embed"].astype(cfg.compute_dtype))
        ck = _constrain(ck, mesh, kv_spec)
        cv = _constrain(cv, mesh, kv_spec)
        return {"k": ck, "v": cv}, logits[:, 0].astype(jnp.float32)

    return jax.jit(bprefill, donate_argnums=(1,))


@functools.partial(jax.jit, static_argnums=())
def greedy(logits):
    """Greedy next token per row (float32 logits [.., vocab])."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)
