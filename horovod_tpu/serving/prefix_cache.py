"""Radix-tree shared-prefix KV reuse over the paged block tables
(jax-free).

Real serving traffic is dominated by shared prompt prefixes — system
prompts, few-shot templates, multi-turn histories. Because the PR 14
paged KV cache already addresses K/V through per-request block tables,
two requests whose prompts agree on a page-aligned prefix can point
their leading block-table entries at the SAME physical pages: the
prefill for those positions happens once, ever. This module is the
index that makes the match cheap: a radix tree whose edges are whole
pages (``page_size`` tokens keyed as a tuple), so lookup walks at most
``prompt_len / page_size`` dict hops.

Invariants (tests/test_serving_scheduler.py pins these):

- **One page per node.** A node's path from the root spells a
  page-aligned token prefix; ``node.page`` holds its K/V. Children are
  keyed by the next page's token tuple, so common prefixes share nodes
  by construction — the tree IS the dedup.
- **The cache is a holder like any other.** Every node owns exactly one
  allocator reference on its page (taken at ``insert``, dropped at
  ``evict``). A page referenced only by the cache has refcount 1;
  requests sharing it push it higher. Conservation
  (``free + distinct-owned == usable``) is unchanged.
- **Strict prefix only.** ``lookup`` never matches the whole prompt:
  the match is capped at ``(prompt_len - 1) // page_size`` pages so at
  least one novel token always remains to prefill — the first output
  token's logits must come from a real forward pass, and a request must
  always own the page it will write its next position into.
- **LRU eviction of unreferenced prefixes only.** ``evict`` frees
  least-recently-touched LEAF nodes whose page refcount is exactly 1
  (cache-only): an interior node's page can be needed by any descendant
  hit, and a page a live request shares must never return to the pool
  under it. Evicting a leaf can expose its parent as the next
  candidate, so eviction peels prefixes back-to-front.
- **Insert after materialization.** The serve loop registers a prompt
  only once its K/V is actually written (post-prefill); inserting at
  admission would let a second request hit pages whose suffix is still
  garbage.
"""


class _Node:
    __slots__ = ("key", "page", "parent", "children", "last_used")

    def __init__(self, key, page, parent, tick):
        self.key = key          # tuple of page_size token ids (root: None)
        self.page = page        # physical KV page (root: -1, unowned)
        self.parent = parent
        self.children = {}      # next-page token tuple -> _Node
        self.last_used = tick


class PrefixCache:
    """Radix tree of page-aligned cached prefixes over a
    :class:`~horovod_tpu.serving.scheduler.PageAllocator`.

    The cache never allocates pages itself — it adopts pages that a
    request already prefilled (``insert`` takes a ``share`` reference)
    and drops them under pressure (``evict``). The scheduler calls
    ``lookup`` at admission and ``evict`` when the free list runs dry.
    """

    def __init__(self, allocator):
        self.alloc = allocator
        self.page_size = allocator.page_size
        self._root = _Node(None, -1, None, 0)
        self._tick = 0
        self.stats = {"lookups": 0, "hits": 0, "hit_tokens": 0,
                      "inserts": 0, "nodes": 0, "evictions": 0}

    def _touch(self, node):
        self._tick += 1
        node.last_used = self._tick

    def _keys(self, prompt, n_pages):
        ps = self.page_size
        return [tuple(prompt[i * ps:(i + 1) * ps]) for i in range(n_pages)]

    # -- scheduler-facing ------------------------------------------------

    def lookup(self, prompt):
        """Longest cached page-aligned STRICT prefix of ``prompt``.
        Returns ``(pages, n_tokens)`` — the physical pages to share and
        how many prompt tokens they cover (0 on a miss). Touches the
        matched path for LRU but takes NO references; the caller shares
        the pages (or not) atomically with its admission decision."""
        self.stats["lookups"] += 1
        limit = max(0, (len(prompt) - 1) // self.page_size)
        node, pages = self._root, []
        for key in self._keys(prompt, limit):
            child = node.children.get(key)
            if child is None:
                break
            self._touch(child)
            pages.append(child.page)
            node = child
        if pages:
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += len(pages) * self.page_size
        return pages, len(pages) * self.page_size

    def insert(self, prompt, pages):
        """Register a materialized prompt's full pages. Walks existing
        nodes (which already hold these very pages for any shared
        prefix) and adopts only the novel tail, taking one ``share``
        reference per NEW node. Returns the number of nodes added."""
        n_full = min(len(prompt) // self.page_size, len(pages))
        self.stats["inserts"] += 1
        node, added = self._root, 0
        for i, key in enumerate(self._keys(prompt, n_full)):
            child = node.children.get(key)
            if child is None:
                self.alloc.share([pages[i]])
                child = _Node(key, pages[i], node, self._tick)
                node.children[key] = child
                self.stats["nodes"] += 1
                added += 1
            self._touch(child)
            node = child
        return added

    def evict(self, n):
        """Free up to ``n`` pages by dropping least-recently-used leaf
        nodes whose page is referenced ONLY by the cache (refcount 1).
        Freeing a leaf can make its parent evictable, so one call can
        peel a whole cold branch. Returns the number of pages freed."""
        freed = 0
        while freed < max(0, n):
            victim, oldest = None, None
            stack = list(self._root.children.values())
            while stack:
                node = stack.pop()
                if node.children:
                    stack.extend(node.children.values())
                elif self.alloc.refcount(node.page) == 1:
                    if oldest is None or node.last_used < oldest:
                        victim, oldest = node, node.last_used
            if victim is None:
                break
            self.alloc.free([victim.page])
            del victim.parent.children[victim.key]
            self.stats["nodes"] -= 1
            self.stats["evictions"] += 1
            freed += 1
        return freed

    # -- introspection ---------------------------------------------------

    def cached_pages(self):
        """Pages currently held by the tree (each exactly one cache
        reference)."""
        out, stack = [], list(self._root.children.values())
        while stack:
            node = stack.pop()
            out.append(node.page)
            stack.extend(node.children.values())
        return out

    def __len__(self):
        return self.stats["nodes"]
