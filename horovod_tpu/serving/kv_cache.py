"""Paged KV cache: the device-side half of the serving plane's memory.

Geometry: two arrays per cache, ``[n_layers, n_pages, page_size,
n_heads, head_dim]`` for K and V. A *page* holds ``page_size`` token
slots; requests own pages through the numpy-side
:class:`~horovod_tpu.serving.scheduler.PageAllocator` and reach them
through per-request **block tables** (page-id lists), so the jit'd
decode step (:mod:`.engine`) serves requests of any mix of lengths with
one compiled program — the indirection, not padding, absorbs the length
variance.

Page 0 is the **trash page**: the allocator never hands it out, and the
engine routes every masked write there (inactive batch slots, padding
positions), so the compiled scatter needs no branches.

Tensor-parallel layout: heads ride the mesh's ``model`` axis — the SAME
shard the attention weights already live on (models/transformer.py
``param_specs``: wqkv column-parallel over heads), so a decode step's
cache reads and writes are local to each TP shard and no K/V ever
crosses the interconnect. ``spec()`` returns the PartitionSpec;
:func:`make_cache` applies it when given a mesh.
"""

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class CacheGeometry:
    """Static shape half of the cache — everything the jit'd paths close
    over. max_kv (= max_blocks * page_size) is the fixed KV width every
    decode step gathers; per-request live length is masked, not shaped."""
    n_pages: int
    page_size: int
    max_blocks: int      # block-table width = max context pages/request

    @property
    def max_kv(self):
        return self.max_blocks * self.page_size


def geometry(n_pages, page_size, max_context):
    """Cache geometry for a max per-request context length (rounded up
    to whole pages)."""
    max_blocks = -(-int(max_context) // int(page_size))
    return CacheGeometry(n_pages=int(n_pages), page_size=int(page_size),
                         max_blocks=max_blocks)


def spec(cfg):
    """PartitionSpec of the K/V arrays: heads on the model axis (mirrors
    wqkv's column-parallel head shard)."""
    return P(None, None, None, cfg.model_axis, None)


def make_cache(cfg, geo, mesh=None):
    """Allocate the zeroed K/V arrays: {"k": [...], "v": [...]}, each
    [n_layers, n_pages, page_size, n_heads, head_dim] in the model's
    compute dtype. With a mesh, the arrays are placed sharded on the
    model axis (when that axis exists in the mesh)."""
    shape = (cfg.n_layers, geo.n_pages, geo.page_size, cfg.n_heads,
             cfg.head_dim)
    k = jnp.zeros(shape, cfg.compute_dtype)
    v = jnp.zeros(shape, cfg.compute_dtype)
    if mesh is not None and cfg.model_axis in mesh.axis_names:
        sh = NamedSharding(mesh, spec(cfg))
        k = jax.device_put(k, sh)
        v = jax.device_put(v, sh)
    return {"k": k, "v": v}


def cache_bytes(cfg, geo):
    """Total cache footprint in bytes (both K and V)."""
    per = (cfg.n_layers * geo.n_pages * geo.page_size * cfg.n_heads *
           cfg.head_dim * jnp.dtype(cfg.compute_dtype).itemsize)
    return 2 * per
