"""Serving plane: continuous-batching decode over a TP-sharded paged KV
cache, with queue-depth autoscaling through the elastic driver.

Layout (docs/serving.md is the architecture doc):

- :mod:`.scheduler` — jax-free continuous batcher + page allocator
- :mod:`.autoscale` — jax-free queue-depth policy for the driver
- :mod:`.kv_cache`  — paged K/V arrays, heads sharded on the TP axis
- :mod:`.engine`    — jit'd prefill / decode_step with block tables
- :mod:`.loop`      — the serve loop: Poisson load, latency spans, gauges

Lazy submodule access keeps the jax-free halves (scheduler, autoscale)
importable — by the elastic driver and by the pure-numpy tests — without
pulling jax into the process.
"""

import importlib

_SUBMODULES = ("scheduler", "autoscale", "kv_cache", "engine", "loop")


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
