"""Serving plane: continuous-batching decode over a TP-sharded paged KV
cache, with queue-depth autoscaling through the elastic driver.

Layout (docs/serving.md is the architecture doc):

- :mod:`.scheduler`    — jax-free continuous batcher + refcounted pages
- :mod:`.autoscale`    — jax-free queue-depth policy for the driver
- :mod:`.prefix_cache` — jax-free radix tree of shared page-aligned prefixes
- :mod:`.speculate`    — jax-free drafters + the spec accept/reject rule
- :mod:`.kv_cache`     — paged K/V arrays, heads sharded on the TP axis
- :mod:`.engine`       — jit'd prefill / decode / chunk steps with block tables
- :mod:`.loop`         — the serve loop: Poisson load, latency spans, gauges

Lazy submodule access keeps the jax-free halves (scheduler, autoscale,
prefix_cache, speculate) importable — by the elastic driver and by the
pure-numpy tests — without pulling jax into the process.
"""

import importlib

_SUBMODULES = ("scheduler", "autoscale", "prefix_cache", "speculate",
               "kv_cache", "engine", "loop")


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
