"""Speculative decoding drafters + the accept/reject rule (jax-free).

Speculative decoding turns k sequential decode steps into ONE batched
target step: a cheap **drafter** proposes ``k`` candidate tokens, the
target model scores ``[last_token, d_1 .. d_k]`` in a single forward
pass through the same paged block tables (``engine.make_chunk_step``
with ``q_len = k + 1``), and the token boundary keeps the longest
draft prefix the target agrees with.

Accept rule (greedy target — provably bit-identical to plain greedy
decoding, tests/test_serving.py pins it):

    g_i = argmax(logits at position i)        # i = 0 .. k
    a   = max prefix length with d_{i+1} == g_i for all i < a
    emit g_0 .. g_a                           # a accepted + 1 bonus

Position ``i``'s logits condition on ``last_token, d_1 .. d_i`` — valid
target output only while every consumed draft was itself accepted,
which is exactly ``i <= a``. The bonus token ``g_a`` is the target's
own next choice after the accepted run, so even a fully rejected draft
(a = 0) still emits one token: a spec step NEVER does worse than a
plain decode step, it only risks wasted draft-lane FLOPs.

Rejected drafts cost nothing to undo: their K/V was written at
positions ``context + a .. context + k - 1``, but the request's context
length only advances over accepted tokens, so the block table simply
never extends over the stale entries — the next step overwrites
position ``context'`` (= context + a + 1) first, and the causal mask
(``kv_pos <= position``) hides anything beyond. Rejection IS a
block-table truncation; preemption replay and EOS eviction semantics
are untouched.

Drafters are pluggable: anything with ``propose(context, k) ->
list[int]`` (at MOST k tokens; short or empty proposals are fine — the
serve loop pads, and padded lanes that match by luck are still
correct). :class:`NGramDrafter` is the zero-cost self-drafting
baseline; a learned draft model drops in behind the same method.
"""


class NGramDrafter:
    """Prompt-lookup / self-drafting: find the most recent earlier
    occurrence of the context's trailing ``n``-gram and propose the
    tokens that followed it.

    Free (no model, no state) and surprisingly effective wherever
    output echoes input or repeats itself — templated answers, code,
    retrieval-augmented prompts. ``n = 2`` is the standard
    prompt-lookup setting: long enough to avoid random unigram matches,
    short enough to fire often.
    """

    def __init__(self, n=2):
        if n < 1:
            raise ValueError(f"n-gram order must be >= 1, got {n}")
        self.n = int(n)

    def propose(self, context, k):
        n = self.n
        if k <= 0 or len(context) <= n:
            return []
        pattern = tuple(context[-n:])
        # Most recent match with a FULL k-token continuation wins:
        # recent continuations track the current "register" of the text
        # best, but a match too close to the end (the common case in a
        # repetition cycle — the trailing n-gram IS the cycle) has its
        # continuation cut off and would waste draft lanes. Fall back
        # to the longest partial continuation if no full one exists.
        best = []
        for i in range(len(context) - n - 1, -1, -1):
            if tuple(context[i:i + n]) == pattern:
                cont = list(context[i + n:i + n + k])
                if len(cont) == k:
                    return cont
                if len(cont) > len(best):
                    best = cont
        return best


class FixedDrafter:
    """Always proposes the same token sequence — a deterministic test
    double for pinning accept/reject arithmetic (not for serving)."""

    def __init__(self, tokens):
        self.tokens = list(tokens)

    def propose(self, context, k):
        return self.tokens[:k]


def accept_drafts(drafts, greedy):
    """Apply the accept rule: ``drafts`` are the k proposed tokens,
    ``greedy`` the k+1 target argmaxes from the spec step. Returns
    ``(emitted, accepted, rejected)`` where ``emitted`` is the token
    list to feed the boundary (``a`` accepted drafts + 1 bonus),
    ``accepted == a`` and ``rejected == k - a``."""
    k = len(drafts)
    if len(greedy) != k + 1:
        raise ValueError(f"spec step returned {len(greedy)} logits "
                         f"positions for {k} drafts (want k + 1)")
    a = 0
    while a < k and drafts[a] == greedy[a]:
        a += 1
    return list(greedy[:a + 1]), a, k - a
