"""Expert parallelism: MoE alltoall dispatch/combine.

The graded pattern from BASELINE.json ("hvd.alltoall + hvd.allgather — MoE
expert-parallel dispatch"): experts are sharded over a mesh axis; tokens are
routed top-1, packed into fixed-capacity per-expert buffers (one-hot einsum
— static shapes, MXU-friendly, no dynamic scatter), exchanged with ONE XLA
AllToAll each way over ICI, and combined back weighted by router
probability. Overflow tokens are dropped (standard Switch routing).

Use inside shard_map over the expert axis:

    out, aux = moe_dispatch_combine(x, logits, expert_fn, axis="expert",
                                    capacity_factor=1.25)

- x: [T, D] local tokens; logits: [T, E] router logits (E global experts,
  E % axis_size == 0); expert_fn: [E_local, N, D] -> [E_local, N, D] using
  the shard's local expert weights.
"""

import jax
import jax.numpy as jnp
from jax import lax


def moe_dispatch_combine(x, logits, expert_fn, axis, capacity_factor=1.25,
                         capacity=None):
    """Top-1 routed expert layer over mesh axis `axis`. Returns
    (out [T, D], aux dict with load-balancing stats)."""
    P = lax.psum(1, axis)
    T, D = x.shape
    E = logits.shape[-1]
    if E % P != 0:
        raise ValueError(f"{E} experts not divisible by axis size {P}")
    E_loc = E // P
    if capacity is None:
        capacity = max(1, int(T * capacity_factor / E))
    C = capacity

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                    # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], 1)[:, 0]
    mask = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)    # [T, E]

    # position of each token in its expert's queue; drop beyond capacity
    pos = (jnp.cumsum(mask, axis=0) - 1.0) * mask              # [T, E]
    keep = (pos < C).astype(jnp.float32) * mask
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), C,
                            dtype=jnp.float32) * keep[..., None]
    dispatch = pos_oh                                          # [T, E, C]
    combine = dispatch * gate[:, None, None]                   # [T, E, C]

    # pack per-expert buffers and exchange: [E, C, D] -> [E_loc, P*C, D]
    expert_in = jnp.einsum("td,tec->ecd", x.astype(jnp.float32), dispatch)
    expert_in = expert_in.astype(x.dtype)
    recv = lax.all_to_all(expert_in, axis, split_axis=0, concat_axis=1,
                          tiled=True)                          # [E_loc,P*C,D]
    out = expert_fn(recv)
    if out.shape != recv.shape:
        raise ValueError(f"expert_fn changed shape {recv.shape}->{out.shape}")
    back = lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                          tiled=True)                          # [E, C, D]
    y = jnp.einsum("ecd,tec->td", back.astype(jnp.float32), combine)

    # Switch-style load-balance stats (fraction routed vs mean prob per
    # expert, averaged over every shard's tokens with a psum — the
    # "allgather" half of the graded pattern, as a reduction).
    frac_routed = lax.pmean(mask.mean(axis=0), axis)           # [E]
    mean_prob = lax.pmean(probs.mean(axis=0), axis)            # [E]
    aux = {
        "load_balance_loss": E * jnp.sum(frac_routed * mean_prob),
        "dropped_fraction": 1.0 - lax.pmean(keep.sum() / T, axis),
        "capacity": C,
    }
    return y.astype(x.dtype), aux


def make_moe_layer(mesh, axis, w_in, w_out, capacity_factor=1.25):
    """Convenience: build a jitted MoE FFN over `mesh`.

    w_in: [E, D, F], w_out: [E, F, D] — sharded on dim0 over `axis`.
    Returns fn(x [T, D], logits [T, E]) -> [T, D] where T is the global
    token count (flatten any batch/sequence dims into T first; T must be
    divisible by the axis size).
    """
    import functools

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    espec = P(axis, None, None)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), espec, espec),
        out_specs=P(axis, None), check_vma=False)
    def fn(x, logits, w_in_l, w_out_l):
        def expert_fn(buf):  # [E_loc, N, D]
            h = jnp.einsum("end,edf->enf", buf.astype(jnp.float32),
                           w_in_l.astype(jnp.float32))
            h = jax.nn.gelu(h)
            return jnp.einsum("enf,efd->end", h,
                              w_out_l.astype(jnp.float32)).astype(buf.dtype)

        out, _ = moe_dispatch_combine(x, logits, expert_fn, axis,
                                      capacity_factor=capacity_factor)
        return out

    return lambda x, logits: fn(x, logits, w_in, w_out)
