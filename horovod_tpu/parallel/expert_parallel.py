"""Expert parallelism: MoE alltoall dispatch/combine.

The graded pattern from BASELINE.json ("hvd.alltoall + hvd.allgather — MoE
expert-parallel dispatch"): experts are sharded over a mesh axis; tokens are
routed top-1, packed into fixed-capacity per-expert buffers (one-hot einsum
— static shapes, MXU-friendly, no dynamic scatter), exchanged with ONE XLA
AllToAll each way over ICI, and combined back weighted by router
probability. Overflow tokens are dropped (standard Switch routing).

Use inside shard_map over the expert axis:

    out, aux = moe_dispatch_combine(x, logits, expert_fn, axis="expert",
                                    capacity_factor=1.25)

- x: [T, D] local tokens; logits: [T, E] router logits (E global experts,
  E % axis_size == 0); expert_fn: [E_local, N, D] -> [E_local, N, D] using
  the shard's local expert weights.
"""

import os

import jax
import jax.numpy as jnp
from jax import lax


def env_capacity_factor(default=1.25):
    """Router capacity factor from HVD_EP_CAPACITY_FACTOR (default 1.25,
    the standard Switch setting): per-expert queue slots = T * factor / E.
    Raising it trades buffer memory/wire bytes for fewer dropped tokens —
    the EP_* gauges (ep_stats) show where the current setting lands."""
    try:
        return float(os.environ.get("HVD_EP_CAPACITY_FACTOR", default))
    except (TypeError, ValueError):
        return default


def report_dispatch(dropped_fraction, tokens, dropped_tokens=None):
    """Publish one dispatch's capacity-clamp outcome to the core EP_*
    gauges (hvd_ep_report -> ep_stats). No-op (returns False) when the
    core is not initialized — pure-XLA runs have no gauge plane."""
    tokens = int(tokens)
    frac = float(dropped_fraction)
    if dropped_tokens is None:
        dropped_tokens = int(round(frac * tokens))
    dropped_tokens = max(0, min(int(dropped_tokens), tokens))
    try:
        import horovod_tpu as _hvd
        _hvd.ep_report(frac, tokens, dropped_tokens)
        return True
    except (ValueError, ImportError):
        return False


def moe_dispatch_combine(x, logits, expert_fn, axis, capacity_factor=1.25,
                         capacity=None):
    """Top-1 routed expert layer over mesh axis `axis`. Returns
    (out [T, D], aux dict with load-balancing stats)."""
    P = lax.psum(1, axis)
    T, D = x.shape
    E = logits.shape[-1]
    if E % P != 0:
        raise ValueError(f"{E} experts not divisible by axis size {P}")
    E_loc = E // P
    if capacity is None:
        capacity = max(1, int(T * capacity_factor / E))
    C = capacity

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                    # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], 1)[:, 0]
    mask = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)    # [T, E]

    # position of each token in its expert's queue; drop beyond capacity
    pos = (jnp.cumsum(mask, axis=0) - 1.0) * mask              # [T, E]
    keep = (pos < C).astype(jnp.float32) * mask
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), C,
                            dtype=jnp.float32) * keep[..., None]
    dispatch = pos_oh                                          # [T, E, C]
    combine = dispatch * gate[:, None, None]                   # [T, E, C]

    # pack per-expert buffers and exchange: [E, C, D] -> [E_loc, P*C, D]
    expert_in = jnp.einsum("td,tec->ecd", x.astype(jnp.float32), dispatch)
    expert_in = expert_in.astype(x.dtype)
    recv = lax.all_to_all(expert_in, axis, split_axis=0, concat_axis=1,
                          tiled=True)                          # [E_loc,P*C,D]
    out = expert_fn(recv)
    if out.shape != recv.shape:
        raise ValueError(f"expert_fn changed shape {recv.shape}->{out.shape}")
    back = lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                          tiled=True)                          # [E, C, D]
    y = jnp.einsum("ecd,tec->td", back.astype(jnp.float32), combine)

    # Switch-style load-balance stats (fraction routed vs mean prob per
    # expert, averaged over every shard's tokens with a psum — the
    # "allgather" half of the graded pattern, as a reduction).
    frac_routed = lax.pmean(mask.mean(axis=0), axis)           # [E]
    mean_prob = lax.pmean(probs.mean(axis=0), axis)            # [E]
    aux = {
        "load_balance_loss": E * jnp.sum(frac_routed * mean_prob),
        "dropped_fraction": 1.0 - lax.pmean(keep.sum() / T, axis),
        "capacity": C,
    }
    return y.astype(x.dtype), aux


def moe_dispatch_combine_ragged(x, logits, expert_fn, axis,
                                capacity_factor=1.25, peer_capacity=None,
                                expert_capacity=None):
    """Top-1 MoE layer whose dispatch is RAGGED on the wire (VERDICT r3
    #7; reference: MPIAlltoall's alltoallv splits, rebuilt for ICI).

    :func:`moe_dispatch_combine` ships dense [E, C, D] buffers — every
    expert slot crosses ICI whether routed or not. Here each shard packs
    only the tokens actually routed to each peer (sorted by destination,
    gathered into a [P, peer_capacity, D] slot buffer via
    ops.jax_ops.ragged_alltoall), so wire bytes follow the REAL routing
    distribution; the per-expert grouping happens after the exchange,
    locally. Tokens beyond ``peer_capacity`` (per destination shard) or
    ``expert_capacity`` (per local expert queue) are dropped — their
    output is zero, standard Switch semantics.

    Same contract as moe_dispatch_combine: call inside shard_map over
    ``axis`` with x [T, D], logits [T, E]; expert_fn maps
    [E_loc, N, D] -> [E_loc, N, D]. Returns (out [T, D], aux).
    """
    from ..ops.jax_ops import ragged_alltoall

    P = lax.psum(1, axis)
    T, D = x.shape
    E = logits.shape[-1]
    if E % P != 0:
        raise ValueError(f"{E} experts not divisible by axis size {P}")
    E_loc = E // P
    cap = peer_capacity or max(1, int(T * capacity_factor / P))
    C2 = expert_capacity or max(1, int(P * cap * capacity_factor / E_loc))

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                     # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], 1)[:, 0]
    dest = expert_idx // E_loc                                  # [T] shard
    local_e = expert_idx % E_loc                                # [T]

    # sort tokens by destination shard → contiguous per-peer blocks
    order = jnp.argsort(dest)
    inv = jnp.argsort(order)
    xs = jnp.take(x, order, axis=0)
    le_s = jnp.take(local_e, order)
    dest_s = jnp.take(dest, order)
    send_counts = jnp.sum(jax.nn.one_hot(dest, P, dtype=jnp.int32), 0)
    starts = jnp.cumsum(send_counts) - send_counts
    pos_in_block = jnp.arange(T, dtype=jnp.int32) - starts[dest_s]
    sent = pos_in_block < cap                                   # [T] sorted

    recv_x, recv_counts = ragged_alltoall(xs, send_counts, axis, cap)
    recv_le, _ = ragged_alltoall(le_s, send_counts, axis, cap)

    # local per-expert packing of the received rows (no wire cost)
    N = P * cap
    rows = recv_x.reshape(N, D).astype(jnp.float32)
    le = recv_le.reshape(N)
    slot = jnp.arange(cap, dtype=jnp.int32)
    rvalid = (slot[None, :] < recv_counts[:, None]).reshape(N)
    le_oh = jax.nn.one_hot(le, E_loc, dtype=jnp.float32) \
        * rvalid[:, None].astype(jnp.float32)                   # [N, E_loc]
    pos = (jnp.cumsum(le_oh, axis=0) - 1.0) * le_oh
    keep = (pos < C2).astype(jnp.float32) * le_oh
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), C2,
                            dtype=jnp.float32) * keep[..., None]
    expert_in = jnp.einsum("nd,nec->ecd", rows, pos_oh).astype(x.dtype)
    out = expert_fn(expert_in)                                  # [E_loc,C2,D]
    if out.shape != expert_in.shape:
        raise ValueError(
            f"expert_fn changed shape {expert_in.shape}->{out.shape}")
    rows_out = jnp.einsum("ecd,nec->nd", out.astype(jnp.float32), pos_oh)

    # return trip: slot layout is already [P, cap, D] grouped by source —
    # a plain tiled AllToAll routes every block straight back
    back = lax.all_to_all(rows_out.reshape(P, cap, D).astype(x.dtype),
                          axis, split_axis=0, concat_axis=0, tiled=True)
    flat = back.reshape(N, D)
    y_s = jnp.take(flat,
                   dest_s * cap + jnp.clip(pos_in_block, 0, cap - 1),
                   axis=0)
    y_s = y_s * sent[:, None].astype(x.dtype)
    y = jnp.take(y_s, inv, axis=0) * gate[:, None].astype(x.dtype)

    mask = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)
    frac_routed = lax.pmean(mask.mean(axis=0), axis)
    mean_prob = lax.pmean(probs.mean(axis=0), axis)
    # Survivors cleared BOTH capacity gates: sent past the peer slot AND
    # queued within the local expert's C2 (keep counts the latter among
    # received rows, so summing it globally counts end-to-end survivors —
    # the dense sibling's keep-mask accounting).
    survived = lax.psum(jnp.sum(keep), axis)
    total = lax.psum(jnp.float32(T), axis)
    aux = {
        "load_balance_loss": E * jnp.sum(frac_routed * mean_prob),
        "dropped_fraction": 1.0 - survived / total,
        "peer_capacity": cap,
        "expert_capacity": C2,
    }
    return y.astype(x.dtype), aux


def make_moe_layer(mesh, axis, w_in, w_out, capacity_factor=None,
                   ragged=False, report=True):
    """Convenience: build a jitted MoE FFN over `mesh`.

    w_in: [E, D, F], w_out: [E, F, D] — sharded on dim0 over `axis`.
    Returns fn(x [T, D], logits [T, E]) -> [T, D] where T is the global
    token count (flatten any batch/sequence dims into T first; T must be
    divisible by the axis size). ``ragged=True`` dispatches through
    :func:`moe_dispatch_combine_ragged` (alltoallv-style wire format)
    instead of the dense fixed-slot exchange. ``capacity_factor=None``
    resolves HVD_EP_CAPACITY_FACTOR (default 1.25); ``report=True``
    publishes each dispatch's dropped-token fraction to the core EP_*
    gauges via :func:`report_dispatch`.
    """
    import functools

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    if capacity_factor is None:
        capacity_factor = env_capacity_factor()
    dispatch = moe_dispatch_combine_ragged if ragged \
        else moe_dispatch_combine
    espec = P(axis, None, None)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), espec, espec),
        out_specs=(P(axis, None), P()), check_vma=False)
    def fn(x, logits, w_in_l, w_out_l):
        def expert_fn(buf):  # [E_loc, N, D]
            h = jnp.einsum("end,edf->enf", buf.astype(jnp.float32),
                           w_in_l.astype(jnp.float32))
            h = jax.nn.gelu(h)
            return jnp.einsum("enf,efd->end", h,
                              w_out_l.astype(jnp.float32)).astype(buf.dtype)

        out, aux = dispatch(x, logits, expert_fn, axis,
                            capacity_factor=capacity_factor)
        return out, aux["dropped_fraction"]

    def run(x, logits):
        out, dropped = fn(x, logits, w_in, w_out)
        if report:
            report_dispatch(float(dropped), x.shape[0])
        return out

    return run
