"""Single-controller SPMD parallelism over a jax.sharding.Mesh — the
ICI-fast path.

The reference scales *batch* only (data parallelism with ring allreduce).
This package provides that first-class (:mod:`data_parallel`) and, beyond
parity, the mesh/sharding machinery that makes TP / SP / EP / pipeline
schemes expressible the TPU way: annotate shardings, let XLA insert the
collectives (SURVEY.md §2.4).
"""

from .mesh import create_mesh, mesh_axis_size  # noqa: F401
from .data_parallel import make_train_step  # noqa: F401
from .ring_attention import (  # noqa: F401
    make_ring_attention,
    ring_attention,
    stripe_sequence,
    unstripe_sequence,
)
from .ulysses import make_ulysses_attention, ulysses_attention  # noqa: F401
from .expert_parallel import (  # noqa: F401
    env_capacity_factor,
    make_moe_layer,
    moe_dispatch_combine,
    moe_dispatch_combine_ragged,
    report_dispatch,
)
from .pipeline import (  # noqa: F401
    make_pipeline_train_step,
    make_pipeline_value_and_grad,
    pipeline_apply,
    shard_stage_params,
)
from .schedules import (  # noqa: F401
    resolve_schedule,
    schedule_info,
)
