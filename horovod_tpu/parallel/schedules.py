"""Pipeline schedule tables: GPipe, 1F1B, interleaved virtual stages, ZB-H1.

A schedule here is a set of trace-time numpy tables of shape ``[T, S]``
(ticks x pipe-axis devices) holding a microbatch index (or -1 for idle)
per micro-op kind — forward, backward, and (ZB only) deferred
weight-grad. The tables are baked into the compiled ``lax.scan`` in
:mod:`.pipeline`, so *counting their occupancy is measuring the real
artifact*: the same arrays that route microbatches through the scan
produce the ``bubble_fraction`` the bench and the gauges report.

Bubble accounting (the ``bubble_fraction`` everywhere in this repo):
a device-tick *slot* is occupied when that device has at least one
scheduled micro-op at that tick; ``bubble = 1 - busy_slots / (T * S)``.
Under this accounting the closed forms are

=================  =============================  =======================
schedule           bubble (training)              peak activation residency
=================  =============================  =======================
gpipe              (S-1)/(M+S-1)                  O(M) microbatches/stage
1f1b               (S-1)/(M+2S-2)                 O(S) (fused train scan)
interleaved (V)    (S-1)/(V*M+S-1)  [M >= S]      O(M) + V x more hops
zb (ZB-H1 split)   ~(S-1)/(2*(M+2S-2))            O(S) + deferred-W queue
=================  =============================  =======================

1F1B counts more total ticks than GPipe (M+2S-2 vs. M+S-1 because its
scan fuses forward and backward halves into single ticks) yet is
*strictly* less idle for every M and S>1 — each device sits exactly
``2s`` idle ticks out of M+2S-2 instead of ``S-1`` out of M+S-1 twice.
"""
import dataclasses
import os

import numpy as np

VALID_SCHEDULES = ("gpipe", "1f1b", "interleaved", "zb")
_ENV_KNOB = "HVD_PIPE_SCHEDULE"


def resolve_schedule(schedule=None, virtual_stages=None):
    """Resolve the schedule name and virtual-stage count V.

    Precedence: explicit ``schedule`` argument, then the
    ``HVD_PIPE_SCHEDULE`` env knob (``--pipeline-schedule`` /
    ``params: pipeline-schedule:`` in launch configs), then ``gpipe``.
    ``interleaved`` accepts an inline V as ``interleaved:V`` (default 2);
    ``virtual_stages`` overrides it.
    """
    raw = schedule if schedule is not None else os.environ.get(_ENV_KNOB)
    raw = (raw or "gpipe").strip().lower()
    name, _, vtxt = raw.partition(":")
    if name not in VALID_SCHEDULES:
        raise ValueError(
            f"unknown pipeline schedule {raw!r}: valid schedules are "
            f"gpipe, 1f1b, interleaved[:V], zb "
            f"({_ENV_KNOB} / --pipeline-schedule)")
    if vtxt and name != "interleaved":
        raise ValueError(
            f"pipeline schedule {raw!r}: only 'interleaved' takes a "
            f":V suffix")
    if virtual_stages is not None:
        v = int(virtual_stages)
    elif vtxt:
        v = int(vtxt)
    else:
        v = 2 if name == "interleaved" else 1
    if name == "interleaved":
        if v < 2:
            raise ValueError(
                f"interleaved schedule needs virtual_stages >= 2, got {v}")
    elif v != 1:
        raise ValueError(
            f"schedule {name!r} does not take virtual stages (got V={v}); "
            f"use schedule='interleaved:{v}'")
    return name, v


def schedule_label(name, virtual):
    """Categorical label recorded in the autotune CSV ``schedule``
    column (comma-free; '-' until a pipeline workload registers)."""
    return f"interleaved{virtual}" if name == "interleaved" else name


def suggest_n_microbatches(batch, m):
    """Nearest divisor of ``batch`` to the requested (invalid) ``m`` —
    used by the divisibility error so the fix is one copy-paste away."""
    divisors = [d for d in range(1, batch + 1) if batch % d == 0]
    return min(divisors, key=lambda d: (abs(d - m), -d))


def interleave_permutation(stages, virtual):
    """Host-side permutation mapping contiguous stage order to the
    interleaved device layout.

    ``stage_params`` arrive with leading dim S*V in *network order*
    (slice j feeds slice j+1). Device s must hold the non-contiguous
    slices {s, S+s, 2S+s, ...} so a P(axis) shard of the permuted array
    is exactly its V chunks: ``perm[s*V + k] = k*S + s``.
    """
    s_, v_ = int(stages), int(virtual)
    return np.array([k * s_ + s for s in range(s_) for k in range(v_)],
                    dtype=np.int64)


# ---------------------------------------------------------------------------
# Table builders. All return int32 numpy arrays of shape [T, S]; -1 = idle.
# ---------------------------------------------------------------------------


def _forward_tables(stages, n_microbatches, virtual):
    """Forward tables for the interleaved (V >= 2) scan.

    Virtual stage j = k*S + s (chunk k on device s) runs microbatch m at
    tick ``m + k*P + s`` with ``P = max(S, M)`` — collision-free on every
    device because two work items on device s would need microbatch
    indices P apart, and M <= P. The chunk-boundary hop (device S-1 ->
    device 0, wraparound ring) is produced at ``m+(k-1)*P+S-1`` but only
    consumed at ``m+k*P``: for P > S the activation waits ``P-S`` ticks
    in the consumer's microbatch-indexed inbox.
    """
    s_, m_, v_ = int(stages), int(n_microbatches), int(virtual)
    p_ = max(s_, m_)
    t_ = m_ + (v_ - 1) * p_ + s_ - 1  # last tick (M-1)+(V-1)P+(S-1), plus 1
    exec_mb = np.full((t_, s_), -1, dtype=np.int32)
    exec_chunk = np.zeros((t_, s_), dtype=np.int32)
    for k in range(v_):
        for m in range(m_):
            for s in range(s_):
                t = m + k * p_ + s
                assert exec_mb[t, s] < 0, "schedule collision"
                exec_mb[t, s] = m
                exec_chunk[t, s] = k
    # recv_mb[t, s]: microbatch whose activation arrives at device s at
    # the start of tick t (sent by ring predecessor at t-1); -1 = none.
    # The final virtual stage's output is recorded, not forwarded.
    recv_mb = np.full((t_, s_), -1, dtype=np.int32)
    for t in range(1, t_):
        for s in range(s_):
            prev = (s - 1) % s_
            pm, pk = exec_mb[t - 1, prev], exec_chunk[t - 1, prev]
            if pm < 0:
                continue
            j_send = pk * s_ + prev
            if j_send < s_ * v_ - 1:
                recv_mb[t, s] = pm
    return {"T": t_, "exec_mb": exec_mb, "exec_chunk": exec_chunk,
            "recv_mb": recv_mb}


def _onef1b_tables(stages, n_microbatches):
    """Fused 1F1B training tables: F(m) on stage s at tick ``s + m``,
    B(m) at tick ``2S-2-s + m`` — the backward wavefront runs the
    mirror-image slope so stage S-1 does F and B of the same microbatch
    in one tick (loss vjp seeds the reverse hop immediately)."""
    s_, m_ = int(stages), int(n_microbatches)
    t_ = m_ + 2 * s_ - 2
    f_mb = np.full((t_, s_), -1, dtype=np.int32)
    b_mb = np.full((t_, s_), -1, dtype=np.int32)
    for m in range(m_):
        for s in range(s_):
            f_mb[m + s, s] = m
            b_mb[2 * s_ - 2 - s + m, s] = m
    return {"T": t_, "f_mb": f_mb, "b_mb": b_mb}


def _zb_tables(stages, n_microbatches):
    """ZB-H1 tables: 1F1B with B split into Bx (dL/dx, stays on the 1F1B
    backward slot — the critical path) and Bw (dL/dw, deferred into the
    stage's idle ticks so weight-grad work fills the cooldown tail).

    Bw(m) goes to the earliest idle tick after its Bx; when a stage runs
    out of idle ticks (steady state has none) the remaining Bw co-locate
    with their own Bx tick, which degenerates to plain 1F1B for those
    microbatches — that is the honest limit of what one shape-stable
    ``lax.scan`` can express of ZB-H1, and exactly the half-bubble the
    paper's H1 variant claims: warmup idle (before any Bx exists) cannot
    be filled, cooldown idle can.
    """
    base = _onef1b_tables(stages, n_microbatches)
    s_, m_ = int(stages), int(n_microbatches)
    t_, f_mb, b_mb = base["T"], base["f_mb"], base["b_mb"]
    w_mb = np.full((t_, s_), -1, dtype=np.int32)
    for s in range(s_):
        idle = [t for t in range(t_)
                if f_mb[t, s] < 0 and b_mb[t, s] < 0]
        for m in range(m_):
            bx_t = 2 * s_ - 2 - s + m
            slot = next((t for t in idle if t > bx_t), None)
            if slot is None:
                w_mb[bx_t, s] = m          # co-located: plain 1F1B for m
            else:
                idle.remove(slot)
                w_mb[slot, s] = m
    # Reuse distance of the deferred (x, dy) ring buffer: slot m % Rw is
    # overwritten at Bx(m + Rw), so Rw must exceed the largest Bx->Bw gap.
    gap = 0
    for s in range(s_):
        for t in range(t_):
            m = w_mb[t, s]
            if m >= 0:
                gap = max(gap, t - (2 * s_ - 2 - s + m))
    return dict(base, w_mb=w_mb, w_ring=gap + 1)


# ---------------------------------------------------------------------------
# Occupancy accounting.
# ---------------------------------------------------------------------------


def _phases(busy):
    """(warmup, steady, cooldown) tick counts from a [T, S] busy mask:
    steady is the span at peak device occupancy."""
    occ = busy.sum(axis=1)
    peak = int(occ.max()) if occ.size else 0
    at_peak = np.flatnonzero(occ == peak)
    warmup = int(at_peak[0])
    cooldown = int(busy.shape[0] - 1 - at_peak[-1])
    return warmup, busy.shape[0] - warmup - cooldown, cooldown


@dataclasses.dataclass(frozen=True)
class ScheduleInfo:
    """Tick accounting for one (schedule, S, M, V) — the measured side
    of the ideal-vs-measured split: ``bubble_fraction`` is counted from
    the occupancy of the very tables the scan compiles, ``ideal_bubble``
    is the closed form the docs quote."""
    schedule: str
    label: str
    stages: int
    n_microbatches: int
    virtual_stages: int
    ticks: int
    busy_slots: int
    total_slots: int
    bubble_fraction: float
    ideal_bubble: float
    warmup_ticks: int
    steady_ticks: int
    cooldown_ticks: int

    def as_dict(self):
        return dataclasses.asdict(self)


def schedule_info(schedule, stages, n_microbatches, virtual_stages=None):
    """Build :class:`ScheduleInfo` for a schedule by counting occupied
    device-tick slots in its tables (training accounting: forward-only
    schedules mirror their forward table for the autodiff backward)."""
    name, v = resolve_schedule(schedule, virtual_stages)
    s_, m_ = int(stages), int(n_microbatches)
    if name in ("gpipe", "interleaved"):
        if name == "gpipe":
            t1 = m_ + s_ - 1
            fbusy = np.zeros((t1, s_), dtype=bool)
            for m in range(m_):
                for s in range(s_):
                    fbusy[m + s, s] = True
        else:
            tab = _forward_tables(s_, m_, v)
            fbusy = tab["exec_mb"] >= 0
        # Autodiff runs the transposed schedule: same occupancy, mirrored.
        busy = np.concatenate([fbusy, fbusy[::-1]], axis=0)
        ideal = ((s_ - 1) / (v * m_ + s_ - 1) if name == "interleaved"
                 else (s_ - 1) / (m_ + s_ - 1))
    elif name == "1f1b":
        tab = _onef1b_tables(s_, m_)
        busy = (tab["f_mb"] >= 0) | (tab["b_mb"] >= 0)
        ideal = (s_ - 1) / max(1, m_ + 2 * s_ - 2)
    else:  # zb
        tab = _zb_tables(s_, m_)
        busy = (tab["f_mb"] >= 0) | (tab["b_mb"] >= 0) | (tab["w_mb"] >= 0)
        ideal = (s_ - 1) / max(1, 2 * (m_ + 2 * s_ - 2))
    t_ = int(busy.shape[0])
    busy_slots = int(busy.sum())
    total = t_ * s_
    warm, steady, cool = _phases(busy)
    return ScheduleInfo(
        schedule=name, label=schedule_label(name, v), stages=s_,
        n_microbatches=m_, virtual_stages=v, ticks=t_,
        busy_slots=busy_slots, total_slots=total,
        bubble_fraction=1.0 - busy_slots / total, ideal_bubble=ideal,
        warmup_ticks=warm, steady_ticks=steady, cooldown_ticks=cool)
