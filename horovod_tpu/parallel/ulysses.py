"""Ulysses-style sequence parallelism: alltoall head scatter.

DeepSpeed-Ulysses pattern on the reference's own primitive (`hvd.alltoall`,
`horovod/common/ops/*_operations.cc` `*Alltoall` — SURVEY.md §2.4 names it
as the path to sequence parallelism): activations arrive sequence-sharded
[B, S/P, H, D]; one alltoall re-shards them head-wise [B, S, H/P, D] so
every device runs FULL-sequence attention on a slice of heads; a second
alltoall restores sequence sharding. Two alltoalls per attention instead of
a ring — better when H >= P and ICI alltoall bandwidth is plentiful.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax


def seq_to_heads(x, axis):
    """[B, S_blk, H, D] seq-sharded → [B, S, H_blk, D] head-sharded."""
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def heads_to_seq(x, axis):
    """Inverse of seq_to_heads."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def _full_attention(q, k, v, causal, scale):
    q32, k32, v32 = (t.astype(jnp.float32) for t in (q, k, v))
    s = jnp.einsum("bqhd,bkhd->bhqk", q32, k32) * scale
    if causal:
        S = s.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v32).astype(q.dtype)


def ulysses_attention(q, k, v, axis, causal=True, scale=None):
    """Attention over a sequence-sharded mesh axis via alltoall head
    scatter. q/k/v: [B, S_blk, H, D]; H must be divisible by the axis size.
    Returns [B, S_blk, H, D]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qh = seq_to_heads(q, axis)
    kh = seq_to_heads(k, axis)
    vh = seq_to_heads(v, axis)
    oh = _full_attention(qh, kh, vh, causal, scale)
    return heads_to_seq(oh, axis)


def make_ulysses_attention(mesh, axis="seq", causal=True, batch_axis=None):
    """shard_map wrapper: global [B, S, H, D] arrays seq-sharded on `axis`."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axis, axis, None, None)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def fn(q, k, v):
        return ulysses_attention(q, k, v, axis=axis, causal=causal)

    return fn
