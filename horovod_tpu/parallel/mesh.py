"""Device-mesh construction helpers.

A Mesh is the TPU-native replacement for the reference's communicator
machinery (``horovod/common/mpi/mpi_context.cc`` duplicated comms,
``process_set.cc`` rank subsets): named axes over the physical device grid;
collectives ride ICI along mesh axes.
"""

import numpy as np

import jax
from jax.sharding import Mesh


def create_mesh(axis_sizes=None, devices=None):
    """Build a Mesh from {axis_name: size}. One axis may be -1 (inferred).

    Defaults to a single 'data' axis over all local devices — the pure-DP
    layout matching the reference's one-rank-per-accelerator model.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {"data": n}
    names = list(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    n_infer = sum(1 for s in sizes if s == -1)
    if n_infer > 1:
        raise ValueError("at most one axis size may be -1")
    if n_infer == 1:
        known = int(np.prod([s for s in sizes if s != -1])) or 1
        if n % known != 0:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes = [n // known if s == -1 else s for s in sizes]
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices, "
            f"have {n}")
    grid = np.asarray(devices).reshape(sizes)
    return Mesh(grid, axis_names=tuple(names))


def mesh_axis_size(mesh, axis):
    return mesh.shape[axis]
