"""Data-parallel SPMD training step — the ICI-fast DistributedOptimizer.

The reference's hot path (SURVEY.md §3.2) is: backward hooks enqueue grads →
background thread fuses → NCCL ring → optimizer step. The TPU-native
equivalent compiles the WHOLE step — forward, backward, gradient mean,
update — as one XLA program over a Mesh: the gradient ``psum`` lowers to a
fused all-reduce on ICI that XLA overlaps with the backward pass. Fusion,
scheduling, and overlap are the compiler's job here; no background thread is
in the loop.
"""

import functools

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from jax import shard_map  # requires jax >= 0.8


def make_train_step(loss_fn, tx, mesh, data_axis="data", extra_reduce=None,
                    jit=True, donate=True):
    """Build `step(params, opt_state, batch) -> (params, opt_state, loss)`.

    - `loss_fn(params, batch) -> scalar loss` written for ONE shard of the
      batch (per-device view), like a per-rank Horovod step.
    - params/opt_state are replicated; batch is sharded on dim0 over
      `data_axis`.
    - Gradients are averaged with `lax.pmean` over `data_axis` (the ring
      allreduce analog), the optimizer applies replicated updates.
    """
    axes = (data_axis,) if isinstance(data_axis, str) else tuple(data_axis)

    def _pmean_all(x):
        for ax in axes:
            x = jax.lax.pmean(x, ax)
        return x

    # Replicated over every mesh axis; batch split on dim0 over data axes.
    rep = P()
    batch_spec = P(axes)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(rep, rep, batch_spec),
        out_specs=(rep, rep, rep),
        check_vma=False,
    )
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grads = jax.tree.map(_pmean_all, grads)
        if extra_reduce is not None:
            grads = extra_reduce(grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, _pmean_all(loss)

    if jit:
        step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return step


def shard_batch(batch, mesh, data_axis="data"):
    """Place a host batch so dim0 is split across the data axis."""
    spec = P(data_axis)
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, spec)), batch)


def replicate(tree, mesh):
    """Replicate params/opt_state across the mesh (reference:
    broadcast_parameters at start of training)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
