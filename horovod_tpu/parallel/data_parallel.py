"""Data-parallel SPMD training step — the ICI-fast DistributedOptimizer.

The reference's hot path (SURVEY.md §3.2) is: backward hooks enqueue grads →
background thread fuses → NCCL ring → optimizer step. The TPU-native
equivalent compiles the WHOLE step — forward, backward, gradient mean,
update — as one XLA program over a Mesh: the gradient ``psum`` lowers to a
fused all-reduce on ICI that XLA overlaps with the backward pass. Fusion,
scheduling, and overlap are the compiler's job here; no background thread is
in the loop.
"""

import functools

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from jax import shard_map  # requires jax >= 0.8


def make_train_step(loss_fn, tx, mesh, data_axis="data", extra_reduce=None,
                    jit=True, donate=True, accum_steps=1,
                    grad_reduce="mean", bucket_bytes=None,
                    compression=None):
    """Build `step(params, opt_state, batch) -> (params, opt_state, loss)`.

    - `loss_fn(params, batch) -> scalar loss` written for ONE shard of the
      batch (per-device view), like a per-rank Horovod step.
    - params/opt_state are replicated; batch is sharded on dim0 over
      `data_axis`.
    - Gradients are averaged with `lax.pmean` over `data_axis` (the ring
      allreduce analog), the optimizer applies replicated updates.
    - ``accum_steps=N`` is the compiled-path analog of the reference's
      ``backward_passes_per_step`` (local gradient aggregation): each
      device's batch shard is split into N microbatches, gradients
      accumulate locally via ``lax.scan`` (activation memory drops ~N×),
      and ONE pmean + update runs per step. The accumulated grads/loss
      are scaled by 1/N, so the result is identical to the full-shard
      gradient for a MEAN-type ``loss_fn`` (mean over examples — the
      usual case). A SUM-type loss changes scale by 1/N under
      accumulation; normalize inside ``loss_fn`` if you use one.
    - ``grad_reduce="adasum"`` replaces the pmean with the device-plane
      Adasum (ops/jax_ops.py `adasum` — the op=hvd.Adasum analog, VHDD
      over ICI; requires power-of-two axis sizes). The loss stays
      pmean-averaged either way.
    - ``bucket_bytes`` enables bucketed psum scheduling: gradient leaves
      are grouped — in reversed (≈ backward-completion) order, bounded by
      ``bucket_bytes`` per bucket and split on dtype changes — each
      bucket's raveled leaves concatenated and reduced as ONE pmean.
      Per-leaf tree.map emits collectives XLA tends to coalesce at the
      end of backward; per-bucket collectives give the scheduler
      independent units it can interleave with the (possibly remat'd)
      backward. Default None defers to HVD_BUCKET / HVD_BUCKET_BYTES
      (the core assembler's knobs); 0 disables. Applies to
      ``grad_reduce="mean"``; adasum keeps per-leaf reduction (bucket
      concatenation would change its per-tensor VHDD geometry).
    - ``compression`` (a ``hvd.Compression`` member) compresses the wire
      payload of the bucketed pmean: cast-equivalent compressors
      (``Compression.fp16`` / ``Compression.bf16`` — compression.py
      wire_cast_dtype) cast each float bucket to the wire dtype before the
      pmean and back after, halving ICI bytes. Engagement is counted via
      ``compression.record_wire_cast`` so ``hvd.compression_stats()``
      proves the kwarg is live; custom compressors, the unbucketed path,
      and adasum fall back to uncompressed (counted too). The core wire
      codecs (``Compression.int8`` / ``Compression.topk``) apply to the
      host TCP plane, not this in-graph path — route those through
      ``hvd.set_compression`` / HVD_COMPRESS instead.
    """
    import os

    axes = (data_axis,) if isinstance(data_axis, str) else tuple(data_axis)
    accum_steps = int(accum_steps)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if grad_reduce not in ("mean", "adasum"):
        raise ValueError(f"grad_reduce must be 'mean' or 'adasum', "
                         f"got {grad_reduce!r}")
    if bucket_bytes is None:
        bucket_bytes = int(os.environ.get("HVD_BUCKET_BYTES", str(32 << 20))) \
            if os.environ.get("HVD_BUCKET") == "1" else 0
    bucket_bytes = int(bucket_bytes)
    if grad_reduce != "mean":
        bucket_bytes = 0

    # Wire-cast routing, decided ONCE at build time (it is a property of
    # the compiled program, not of any one step): only cast-equivalent
    # compressors engage on the bucketed pmean path — and the decision is
    # counted either way so compression_stats() shows whether the kwarg
    # actually did anything.
    wire_dtype = None
    if compression is not None:
        from .. import compression as _compression

        wd = _compression.wire_cast_dtype(compression)
        if wd in ("float16", "bfloat16") and bucket_bytes > 0:
            wire_dtype = jnp.dtype(wd)
            _compression.record_wire_cast(True)
        elif wd is not None:
            _compression.record_wire_cast(False)

    # Gradient reducer picked ONCE at build time: "adasum" = the
    # device-plane Adasum (ops/jax_ops.py `adasum` — op=hvd.Adasum
    # analog, VHDD on ICI); "mean" = pmean ring. The LOSS is always
    # pmean'd — adasum applies to gradients.
    if grad_reduce == "adasum":
        from ..ops.jax_ops import adasum as _reduce_one
    else:
        _reduce_one = jax.lax.pmean

    def _pmean_all(x):
        for ax in axes:
            x = jax.lax.pmean(x, ax)
        return x

    def _grad_reduce_all(x):
        for ax in axes:
            x = _reduce_one(x, ax)
        return x

    def _bucketed_grad_reduce(grads):
        """One pmean per size-bounded bucket of raveled leaves, visited in
        reversed flatten order (the leaves whose grads complete first in
        backward). Buckets never mix dtypes — concatenate would promote."""
        leaves, treedef = jax.tree.flatten(grads)
        buckets, cur, cur_bytes = [], [], 0
        for i in reversed(range(len(leaves))):
            nbytes = leaves[i].size * leaves[i].dtype.itemsize
            if cur and (cur_bytes + nbytes > bucket_bytes
                        or leaves[cur[-1]].dtype != leaves[i].dtype):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
        if cur:
            buckets.append(cur)
        def _reduce_cast(x):
            # Wire cast: the pmean runs on the compressor's wire dtype
            # (halving ICI bytes) and the result is cast back, so params
            # stay full precision. Float buckets only — a bucket never
            # mixes dtypes, so one check covers all its leaves.
            if wire_dtype is not None and x.dtype in (jnp.float32,
                                                      jnp.float64):
                return _grad_reduce_all(x.astype(wire_dtype)).astype(x.dtype)
            return _grad_reduce_all(x)

        out = [None] * len(leaves)
        for b in buckets:
            if len(b) == 1:
                out[b[0]] = _reduce_cast(leaves[b[0]])
                continue
            flat = jnp.concatenate([leaves[i].ravel() for i in b])
            red = _reduce_cast(flat)
            off = 0
            for i in b:
                n = leaves[i].size
                out[i] = red[off:off + n].reshape(leaves[i].shape)
                off += n
        return jax.tree.unflatten(treedef, out)

    def _shard_grad(params, batch):
        if accum_steps == 1:
            return jax.value_and_grad(loss_fn)(params, batch)

        def split(x):
            if x.shape[0] % accum_steps != 0:
                raise ValueError(
                    f"per-device batch dim0 ({x.shape[0]}) must be "
                    f"divisible by accum_steps ({accum_steps})")
            return x.reshape((accum_steps, x.shape[0] // accum_steps)
                             + x.shape[1:])

        micro = jax.tree.map(split, batch)

        def body(carry, mb):
            loss_acc, grad_acc = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, mb)
            return (loss_acc + loss,
                    jax.tree.map(jnp.add, grad_acc, grads)), None

        # Accumulators in the loss's / grads' own dtypes: an f32-hardcoded
        # carry breaks lax.scan's carry-type invariant (e.g. f64 loss
        # under jax_enable_x64).
        first = jax.tree.map(lambda x: x[0], micro)
        loss_shape = jax.eval_shape(loss_fn, params, first)
        zero = (jnp.zeros(loss_shape.shape, loss_shape.dtype),
                jax.tree.map(jnp.zeros_like, params))
        (loss_sum, grad_sum), _ = jax.lax.scan(body, zero, micro)
        scale = 1.0 / accum_steps
        return loss_sum * scale, jax.tree.map(lambda g: g * scale, grad_sum)

    # Replicated over every mesh axis; batch split on dim0 over data axes.
    rep = P()
    batch_spec = P(axes)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(rep, rep, batch_spec),
        out_specs=(rep, rep, rep),
        check_vma=False,
    )
    def step(params, opt_state, batch):
        loss, grads = _shard_grad(params, batch)
        if bucket_bytes > 0:
            grads = _bucketed_grad_reduce(grads)
        else:
            grads = jax.tree.map(_grad_reduce_all, grads)
        if extra_reduce is not None:
            grads = extra_reduce(grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, _pmean_all(loss)

    if jit:
        step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return step


def shard_batch(batch, mesh, data_axis="data"):
    """Place a host batch so dim0 is split across the data axis."""
    spec = P(data_axis)
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, spec)), batch)


def replicate(tree, mesh):
    """Replicate params/opt_state across the mesh (reference:
    broadcast_parameters at start of training)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
