"""Ring attention — context parallelism over a mesh axis.

Long-context training shards the *sequence* across devices; attention then
needs every query block to see every key/value block. Ring attention
(Liu et al., blockwise parallel transformers) keeps K/V sharded and rotates
each shard around the ring with `lax.ppermute` while accumulating the
attention output with an online (streaming) softmax — O(S/P) memory per
device and the rotation overlaps with the block matmuls on ICI.

The reference has no sequence parallelism at all (SURVEY.md §2.4: "scales
batch, never sequence"); its closest primitive is `hvd.alltoall`
(see :mod:`.ulysses`). This module is the beyond-parity TPU-native answer.

Use inside `shard_map` with q/k/v sequence-sharded over `axis`, or wrap
with :func:`make_ring_attention`.

Implementation notes:
- block 0 (the local block) is computed before the loop, so only p-1
  rotations are issued — no K/V block is sent and then discarded;
- under `causal=True`, blocks that are fully masked (source shard index
  greater than ours) skip their matmuls via `lax.cond` — the rotation
  still happens, but no FLOPs are burned. (Work remains skewed toward
  high-index shards; striped/zig-zag sequence layout is the known fix and
  can be layered on by permuting the sequence before sharding.)
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax


def ring_attention(q, k, v, axis, causal=True, scale=None):
    """Blockwise ring attention over mesh axis `axis`.

    q, k, v: [B, S_blk, H, D] — the local sequence block of each shard.
    Returns [B, S_blk, H, D] (dtype of q); softmax statistics in fp32.

    With `causal=True`, global causality is enforced across blocks: shard i
    holds global positions [i*S_blk, (i+1)*S_blk).
    """
    p = lax.psum(1, axis)
    my = lax.axis_index(axis)
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    dt = q.dtype

    q32 = q.astype(jnp.float32)
    perm = [(i, (i + 1) % p) for i in range(p)]  # ring: pass K/V to right

    def accumulate(acc, k_blk, v_blk, src):
        """Online-softmax update of (o, m, l) with one K/V block."""
        o, m, l = acc
        s = jnp.einsum("bqhd,bkhd->bhqk", q32,
                       k_blk.astype(jnp.float32)) * scale
        if causal:
            q_pos = my * S + jnp.arange(S)
            k_pos = src * S + jnp.arange(S)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # all-masked rows keep m=-inf; guard the exp against inf-inf
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), m, m - m_safe))
        w = jnp.exp(s - m_safe[..., None])
        if causal:
            w = jnp.where(mask[None, None], w, 0.0)
        l = l * corr + w.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", w, v_blk.astype(jnp.float32))
        o = o * corr.transpose(0, 2, 1)[..., None] + pv
        return o, m_new, l

    acc = (jnp.zeros((B, S, H, D), jnp.float32),          # o
           jnp.full((B, H, S), -jnp.inf, jnp.float32),    # m
           jnp.zeros((B, H, S), jnp.float32))             # l
    # local block first: only p-1 rotations needed
    acc = accumulate(acc, k, v, my)

    def body(carry, i):
        acc, k_blk, v_blk = carry
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        src = (my - i) % p  # whose block we now hold
        if causal:
            # src > my → every position is masked: skip the matmuls
            acc = lax.cond(src > my,
                           lambda a, kb, vb, s_: a,
                           accumulate,
                           acc, k_blk, v_blk, src)
        else:
            acc = accumulate(acc, k_blk, v_blk, src)
        return (acc, k_blk, v_blk), None

    # scan (not fori_loop): reverse-mode AD must flow through the ring for
    # training; fori_loop is not differentiable.
    (acc, _, _), _ = lax.scan(body, (acc, k, v), jnp.arange(1, p))
    o, m, l = acc
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zeros, not NaN
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(dt)


def make_ring_attention(mesh, axis="seq", causal=True, batch_axis=None,
                        head_axis=None, jit=True):
    """Wrap ring_attention in shard_map over `mesh`: takes/returns global
    [B, S, H, D] arrays sequence-sharded on `axis`, optionally
    batch-sharded on `batch_axis` and head-sharded on `head_axis` (tensor
    parallelism composes: each head group runs its own ring)."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axis, axis, head_axis, None)

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def fn(q, k, v):
        return ring_attention(q, k, v, axis=axis, causal=causal)

    return jax.jit(fn) if jit else fn
