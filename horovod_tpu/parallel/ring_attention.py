"""Ring attention — context parallelism over a mesh axis.

Long-context training shards the *sequence* across devices; attention then
needs every query block to see every key/value block. Ring attention
(Liu et al., blockwise parallel transformers) keeps K/V sharded and rotates
each shard around the ring with `lax.ppermute` while accumulating the
attention output with an online (streaming) softmax — O(S/P) memory per
device and the rotation overlaps with the block matmuls on ICI.

The reference has no sequence parallelism at all (SURVEY.md §2.4: "scales
batch, never sequence"); its closest primitive is `hvd.alltoall`
(see :mod:`.ulysses`). This module is the beyond-parity TPU-native answer.

Use inside `shard_map` with q/k/v sequence-sharded over `axis`, or wrap
with :func:`make_ring_attention`.

Implementation notes:
- block 0 (the local block) is computed before the loop, so only p-1
  rotations are issued — no K/V block is sent and then discarded;
- under `causal=True` with the default contiguous layout, fully-masked
  blocks (source shard index greater than ours) skip their matmuls via
  `lax.cond`. The predicate is a per-device runtime scalar (axis_index),
  so it survives as a real XLA conditional in each device's partitioned
  program — but the ring rotates in lockstep, so WALL TIME is still set
  by the busiest device each step: the skip saves energy, not latency.
- `layout="striped"` is the real causal load-balance fix (striped /
  zig-zag attention): device i holds global positions {i, i+p, i+2p, ...},
  so every (query-shard, key-shard) block pair is ~half-masked and every
  device does equal work every rotation. Use :func:`stripe_sequence` /
  :func:`unstripe_sequence` to move between contiguous and striped
  order at the program boundary.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax


def _merge_partials(o1, lse1, o2, lse2):
    """Merge two normalized partial attentions by their log-sum-exp:
    o = o1*exp(lse1-lse) + o2*exp(lse2-lse), lse = logaddexp(lse1, lse2).
    o*: [B, S, H, D] f32; lse*: [B, H, S] f32 (-1e30 sentinel = empty —
    finite, so the exp/logaddexp algebra never produces inf-inf NaNs)."""
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse).transpose(0, 2, 1)[..., None]
    w2 = jnp.exp(lse2 - lse).transpose(0, 2, 1)[..., None]
    return o1 * w1 + o2 * w2, lse


def ring_attention(q, k, v, axis, causal=True, scale=None,
                   layout="contiguous", inner="einsum",
                   inner_interpret=None, inner_block=256):
    """Blockwise ring attention over mesh axis `axis`.

    q, k, v: [B, S_blk, H, D] — the local sequence block of each shard.
    Returns [B, S_blk, H, D] (dtype of q); softmax statistics in fp32.

    With `causal=True`, global causality is enforced across blocks.
    `layout` declares which global positions this shard holds:
    ``"contiguous"`` — shard i holds [i*S_blk, (i+1)*S_blk);
    ``"striped"`` — shard i holds {i, i+p, i+2p, ...} (striped/zig-zag
    attention: equal causal work on every device; see
    :func:`stripe_sequence`).

    ``inner`` picks how each (q-shard, k-shard) block pair is computed:
    ``"einsum"`` — XLA matmuls with an [S_blk, S_blk] logits tensor;
    ``"flash"`` — the fused pallas kernel
    (:func:`horovod_tpu.ops.pallas_attention.flash_attention_lse`), which
    keeps per-pair memory at O(S_blk·D) so the LOCAL block can itself be
    many thousands of tokens; partials are merged by log-sum-exp. The
    cross-shard causal masks map onto the kernel's modes exactly:
    contiguous → full/"diag"/skip, striped → "diag" vs "strict" (q > k).
    """
    if layout not in ("contiguous", "striped"):
        raise ValueError(f"unknown layout: {layout!r}")
    if inner not in ("einsum", "flash"):
        raise ValueError(f"unknown inner: {inner!r}")
    p = lax.psum(1, axis)
    my = lax.axis_index(axis)
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    dt = q.dtype
    if inner == "flash":
        return _ring_attention_flash(q, k, v, axis, causal, scale, layout,
                                     p, my, inner_interpret, inner_block)

    q32 = q.astype(jnp.float32)
    perm = [(i, (i + 1) % p) for i in range(p)]  # ring: pass K/V to right

    def positions(shard):
        if layout == "striped":
            return shard + p * jnp.arange(S)
        return shard * S + jnp.arange(S)

    def accumulate(acc, k_blk, v_blk, src):
        """Online-softmax update of (o, m, l) with one K/V block."""
        o, m, l = acc
        s = jnp.einsum("bqhd,bkhd->bhqk", q32,
                       k_blk.astype(jnp.float32)) * scale
        if causal:
            q_pos = positions(my)
            k_pos = positions(src)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # all-masked rows keep m=-inf; guard the exp against inf-inf
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), m, m - m_safe))
        w = jnp.exp(s - m_safe[..., None])
        if causal:
            w = jnp.where(mask[None, None], w, 0.0)
        l = l * corr + w.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", w, v_blk.astype(jnp.float32))
        o = o * corr.transpose(0, 2, 1)[..., None] + pv
        return o, m_new, l

    acc = (jnp.zeros((B, S, H, D), jnp.float32),          # o
           jnp.full((B, H, S), -jnp.inf, jnp.float32),    # m
           jnp.zeros((B, H, S), jnp.float32))             # l
    # local block first: only p-1 rotations needed
    acc = accumulate(acc, k, v, my)

    def body(carry, i):
        acc, k_blk, v_blk = carry
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        src = (my - i) % p  # whose block we now hold
        if causal and layout == "contiguous":
            # src > my → every position is masked: skip the matmuls.
            # (Striped layout never skips: every block pair is ~half
            # unmasked, which is exactly what balances the ring.)
            acc = lax.cond(src > my,
                           lambda a, kb, vb, s_: a,
                           accumulate,
                           acc, k_blk, v_blk, src)
        else:
            acc = accumulate(acc, k_blk, v_blk, src)
        return (acc, k_blk, v_blk), None

    # scan (not fori_loop): reverse-mode AD must flow through the ring for
    # training; fori_loop is not differentiable.
    (acc, _, _), _ = lax.scan(body, (acc, k, v), jnp.arange(1, p))
    o, m, l = acc
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zeros, not NaN
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(dt)


def _ring_attention_flash(q, k, v, axis, causal, scale, layout, p, my,
                          interpret, block):
    """Flash-kernel ring body: each block pair runs the fused kernel
    locally, partials merge by log-sum-exp, K/V rotate on ppermute.

    interpret=None auto-selects: native Mosaic on TPU, the Pallas
    interpreter elsewhere (the kernel is TPU-targeted)."""
    from ..ops.pallas_attention import flash_attention_lse

    B, S, H, D = q.shape
    dt = q.dtype
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    perm = [(i, (i + 1) % p) for i in range(p)]

    def fl(mode):
        def f(acc, k_blk, v_blk):
            o_p, lse_p = flash_attention_lse(
                q, k_blk, v_blk, mode=mode, sm_scale=scale,
                block=block, interpret=interpret)
            return _merge_partials(acc[0], acc[1],
                                   o_p.astype(jnp.float32), lse_p)
        return f

    def skip(acc, k_blk, v_blk):
        return acc

    def accumulate(acc, k_blk, v_blk, src):
        if not causal:
            return fl("none")(acc, k_blk, v_blk)
        if layout == "striped":
            # striped: q_pos = my + p*i, k_pos = src + p*j →  visible iff
            # i > j, plus the diagonal j == i when my >= src.
            return lax.cond(my >= src, fl("diag"), fl("strict"),
                            acc, k_blk, v_blk)
        # contiguous: earlier shards fully visible, own shard causal,
        # later shards fully masked.
        return lax.cond(src == my, fl("diag"),
                        lambda a, kb, vb: lax.cond(src < my, fl("none"),
                                                   skip, a, kb, vb),
                        acc, k_blk, v_blk)

    acc = (jnp.zeros((B, S, H, D), jnp.float32),
           jnp.full((B, H, S), -1e30, jnp.float32))
    acc = accumulate(acc, k, v, my)

    def body(carry, i):
        acc, k_blk, v_blk = carry
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        src = (my - i) % p
        return (accumulate(acc, k_blk, v_blk, src), k_blk, v_blk), None

    (acc, _, _), _ = lax.scan(body, (acc, k, v), jnp.arange(1, p))
    return acc[0].astype(dt)


def stripe_sequence(x, p, seq_dim=1):
    """Permute a contiguous global sequence into striped order: after
    sharding dim `seq_dim` into p equal blocks, shard i holds global
    positions {i, i+p, ...} in increasing order. Apply to q/k/v (and
    inverse to the output) around a `layout="striped"` ring."""
    S = x.shape[seq_dim]
    idx = jnp.arange(S).reshape(S // p, p).T.reshape(-1)
    return jnp.take(x, idx, axis=seq_dim)


def unstripe_sequence(x, p, seq_dim=1):
    """Inverse of :func:`stripe_sequence`."""
    S = x.shape[seq_dim]
    idx = jnp.argsort(jnp.arange(S).reshape(S // p, p).T.reshape(-1))
    return jnp.take(x, idx, axis=seq_dim)


def make_ring_attention(mesh, axis="seq", causal=True, batch_axis=None,
                        head_axis=None, jit=True, layout="contiguous",
                        inner="einsum", inner_interpret=None,
                        inner_block=256):
    """Wrap ring_attention in shard_map over `mesh`: takes/returns global
    [B, S, H, D] arrays sequence-sharded on `axis`, optionally
    batch-sharded on `batch_axis` and head-sharded on `head_axis` (tensor
    parallelism composes: each head group runs its own ring).

    With ``layout="striped"`` the inputs are re-ordered into striped
    position order on the way in and restored on the way out, so the
    caller keeps contiguous sequences while every device does equal
    causal work (striped/zig-zag attention). That convenience costs four
    global sequence permutations (resharding traffic) PER CALL — for a
    many-layer model, stripe the token stream ONCE outside the model with
    :func:`stripe_sequence` and call the ring with already-striped inputs
    instead. Without causality striping buys nothing, so ``causal=False``
    ignores ``layout`` and skips the permutes entirely."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axis, axis, head_axis, None)
    p = mesh.shape[axis]
    striped = layout == "striped" and causal

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def fn(q, k, v):
        return ring_attention(q, k, v, axis=axis, causal=causal,
                              layout="striped" if striped else "contiguous",
                              inner=inner, inner_interpret=inner_interpret,
                              inner_block=inner_block)

    def wrapped(q, k, v):
        if striped:
            q, k, v = (stripe_sequence(t, p) for t in (q, k, v))
            return unstripe_sequence(fn(q, k, v), p)
        return fn(q, k, v)

    return jax.jit(wrapped) if jit else wrapped
