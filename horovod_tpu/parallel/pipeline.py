"""Pipeline parallelism over a mesh axis (BEYOND REFERENCE).

The reference has no pipeline parallelism and no p2p send/recv API at
all (SURVEY.md §2.4: "PP — absent; no send/recv"). On TPU the natural
p2p primitive is `lax.ppermute` over an ICI-adjacent mesh axis, and the
natural execution form is a microbatch pipeline expressed as ONE
`lax.scan` inside `shard_map` — every stage runs the same compiled
program, activations hop stage→stage with a single collective-permute
per tick, and XLA overlaps the permute with the next tick's compute.

WHICH microbatch each (tick, stage) slot runs is a *schedule* — a
trace-time table from :mod:`.schedules` baked into the scan:

* ``gpipe`` (default): all forwards, then autodiff's mirrored backward.
  Simplest; bubble (S-1)/(M+S-1); O(M) activation residency.
* ``1f1b``: the training step fuses forward and backward into single
  ticks (PipeDream-flush order) — stage S-1 runs F(m) and B(m) in the
  same tick, so peak activation residency drops to O(S) (a 2S-1-slot
  ring of stage inputs, recompute-based vjp) and the bubble shrinks to
  (S-1)/(M+2S-2). Forward-only :func:`pipeline_apply` is unchanged by
  construction (1F1B reorders the *training* ticks only).
* ``interleaved`` (``interleaved:V``): each device hosts V
  non-contiguous stage slices (``stage_params`` leading dim S·V), the
  hop ring wraps around, and the bubble divides by ~V at the cost of V×
  more ppermute hops per microbatch.
* ``zb``: best-effort ZB-H1 — 1F1B with the backward split via
  ``jax.vjp`` into a dL/dx tick (critical path) and a deferred dL/dw
  tick placed into the stage's idle ticks, filling the cooldown tail.
  Gated honest: if the split cannot be made shape-stable it falls back
  to 1F1B and counts the fallback (PIPELINE_ZB_FALLBACKS).

Autodiff flows through the gpipe/interleaved schedules (scan + ppermute
are both differentiable; the transpose of a forward hop is the reverse
hop); 1f1b/zb hand-schedule the backward inside the same scan because
their point *is* the backward order.

Scope: `pipeline_apply` is the forward primitive (differentiable),
`make_pipeline_value_and_grad` the schedule-aware loss/grad engine, and
`make_pipeline_train_step` the packaged loop. `stage_fn` must be
shape-preserving ([mb, ...] -> [mb, ...]): classic homogeneous-stack
pipelining (transformer blocks). Pick the schedule with the
``schedule=`` kwarg or the ``HVD_PIPE_SCHEDULE`` / ``--pipeline-schedule``
knob; see docs/perf_tuning.md §Pipeline schedules for the when-to-pick
table.
"""
import functools
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..observability import metrics as _metrics
from ..observability import spans as _spans
from . import schedules as _schedules

# Re-exported for callers that already import the pipeline module.
resolve_schedule = _schedules.resolve_schedule
schedule_info = _schedules.schedule_info


def _check_stage_leading_dim(tree, n_slices, axis, virtual=1):
    """Single validation (and single error format) for the stage-leading
    dim, shared by `pipeline_apply` and `shard_stage_params`. A mismatch
    would SILENTLY compute the wrong function: shard_map hands each
    device shape[0]/S rows and the stage selection would drop the rest
    (e.g. 8 stage slices on 4 devices = even stages only)."""
    for leaf in jax.tree.leaves(tree):
        shape = jnp.shape(leaf)
        if len(shape) < 1 or shape[0] != n_slices:
            hint = (f" (= {n_slices // virtual} stages x {virtual} "
                    f"virtual slices)" if virtual > 1 else "")
            raise ValueError(
                f"stage_params leaf shape {shape} must lead with the "
                f"pipeline stage count {n_slices}{hint} "
                f"(mesh axis {axis!r})")


def _register_autotune_workload(label):
    """Best-effort: record the active pipeline schedule into the native
    autotune CSV's ``schedule`` column (categorical, '-' until a
    pipeline workload registers — same "operator opted in" discipline as
    the compress arm). Never *imports* basics: that would trigger the
    native build for pure-JAX pipeline users; only an already-loaded
    core is told."""
    mod = sys.modules.get("horovod_tpu.basics")
    if mod is None:
        return False
    try:
        return bool(mod.basics.register_pipeline_workload(label))
    except Exception:
        return False


def _record_schedule(info):
    """Trace-time schedule metadata (one per compile, not per step —
    per-tick device work is XLA's, visible through the xplane profiler,
    not host counters)."""
    _register_autotune_workload(info.label)
    if not _metrics.enabled():
        return
    _metrics.PIPELINE_TRACES.labels(
        stages=str(info.stages), microbatches=str(info.n_microbatches),
        schedule=info.label).inc()
    _metrics.PIPELINE_BUBBLE.set(info.ideal_bubble)
    _metrics.PIPELINE_BUBBLE_MEASURED.set(info.bubble_fraction)
    _metrics.PIPELINE_TICKS.set(info.ticks)


def _resolve_m(n_microbatches, S, B):
    """M (default S — see the pipeline_apply docstring note) plus the
    divisibility check with a copy-pasteable suggestion."""
    M = int(n_microbatches or S)
    if B % M != 0:
        near = _schedules.suggest_n_microbatches(B, M)
        raise ValueError(
            f"batch {B} not divisible into {M} microbatches; nearest "
            f"valid n_microbatches is {near}")
    return M


def pipeline_apply(stage_fn, stage_params, x, mesh, axis="pipe",
                   n_microbatches=None, batch_axis=None, schedule=None,
                   virtual_stages=None):
    """Run ``x`` through the pipeline stages laid out on ``mesh[axis]``.

    Args:
      stage_fn: ``(params_for_one_stage, h) -> h`` with ``h`` of shape
        ``[microbatch, ...]`` (shape-preserving).
      stage_params: pytree whose leaves have a leading stage dim of size
        S == mesh.shape[axis] (stage s uses ``leaf[s]``); for the
        interleaved schedule the leading dim is S·V in *network order*
        (slice j feeds slice j+1) and this function routes slice j to
        device ``j % S`` internally.
      x: ``[batch, ...]`` input; ``batch`` must divide into
        ``n_microbatches`` equal microbatches.
      n_microbatches: number of microbatches M. Defaults to S — the
        minimum that keeps every stage busy in steady state, but also
        the M that MAXIMIZES the bubble fraction (gpipe idles
        (S-1)/(2S-1) ≈ half the schedule at M=S). Prefer M >= 4S when
        the batch allows; see docs/perf_tuning.md §Pipeline schedules.
      batch_axis: optional second mesh axis composing DATA parallelism
        with the pipeline (pp x dp): each microbatch's rows shard over
        it, every data replica runs the same pipeline schedule on its
        shard, and the per-tick ppermute stays within the pipe axis.
        Gradients need NO extra collective: params are replicated over
        ``batch_axis``, so shard_map's transpose already psums their
        cotangent across the data shards — ``jax.grad`` of a loss on
        these outputs IS the full-batch gradient (asserted in
        tests/test_pipeline.py); adding a manual psum would double-count.
      schedule: ``"gpipe"`` | ``"1f1b"`` | ``"interleaved[:V]"`` |
        ``"zb"`` (default: the ``HVD_PIPE_SCHEDULE`` env knob, then
        gpipe). Forward execution is identical for gpipe/1f1b/zb — those
        schedules reorder *training* ticks (see
        :func:`make_pipeline_train_step`); interleaved changes the
        forward layout itself.
      virtual_stages: V for the interleaved schedule (overrides the
        ``:V`` suffix; default 2).

    Returns ``[batch, ...]`` outputs (replicated across the pipe axis;
    sharded over ``batch_axis`` when given).
    """
    name, V = _schedules.resolve_schedule(schedule, virtual_stages)
    S = int(mesh.shape[axis])
    B = x.shape[0]
    M = _resolve_m(n_microbatches, S, B)
    _check_stage_leading_dim(stage_params, S * V, axis, virtual=V)
    info = _schedules.schedule_info(name, S, M, V)
    _record_schedule(info)
    mb = B // M
    xm = x.reshape((M, mb) + x.shape[1:])
    # Microbatch rows shard over batch_axis (dp compose); the stage dim
    # of the params shards over the pipe axis either way.
    x_spec = P(None, batch_axis) if batch_axis else P()

    if V == 1:
        out = _gpipe_forward(stage_fn, stage_params, xm, mesh, axis,
                             S, M, x_spec)
    else:
        out = _interleaved_forward(stage_fn, stage_params, xm, mesh,
                                   axis, S, M, V, x_spec)
    return out.reshape((B,) + out.shape[2:])


def _gpipe_forward(stage_fn, stage_params, xm, mesh, axis, S, M, x_spec):
    """The classic wavefront: stage s runs microbatch m at tick s+m —
    the forward order every non-interleaved schedule shares."""
    fwd = [(i, i + 1) for i in range(S - 1)]

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(axis), x_spec), out_specs=x_spec,
                       check_vma=False)
    def run(params, xm):
        # Each shard sees its own stage slice with a leading dim of 1.
        p_s = jax.tree.map(lambda a: a[0], params)
        s = lax.axis_index(axis)
        last = S - 1

        def tick(carry, t):
            cur, out = carry
            active = (t - s >= 0) & (t - s < M)
            y = stage_fn(p_s, cur)
            # Mask the bubble: inactive ticks contribute nothing (and
            # their gradients vanish through the where).
            y = jnp.where(active, y, jnp.zeros_like(y))
            # Last stage records its finished microbatch. Mask the VALUE,
            # not the buffer: selecting between two full copies of `out`
            # would defeat in-place dynamic_update_slice inside the scan
            # (O(M) full-output copies). Non-recording ticks write zeros
            # into slot 0 of an all-zero buffer before its real (later)
            # write, so results are identical.
            m_out = t - last
            rec = (s == last) & (m_out >= 0)
            idx = jnp.clip(m_out, 0, M - 1)
            out = lax.dynamic_update_slice(
                out, jnp.where(rec, y, jnp.zeros_like(y))[None],
                (idx,) + (0,) * y.ndim)
            # Hop forward one stage; stage 0 ingests the next microbatch.
            shifted = lax.ppermute(y, axis, fwd) if S > 1 else y
            nxt = xm[jnp.clip(t + 1, 0, M - 1)]
            nxt = jnp.where(t + 1 < M, nxt, jnp.zeros_like(nxt))
            cur = jnp.where(s == 0, nxt, shifted)
            return (cur, out), None

        cur0 = jnp.where(s == 0, xm[0], jnp.zeros_like(xm[0]))
        out0 = jnp.zeros_like(xm)
        (cur, out), _ = lax.scan(tick, (cur0, out0),
                                 jnp.arange(M + S - 1))
        # Only the last stage holds real outputs; psum replicates them
        # (every other shard contributes zeros).
        return lax.psum(out, axis)

    return run(stage_params, xm)


def _interleaved_forward(stage_fn, stage_params, xm, mesh, axis,
                         S, M, V, x_spec):
    """Interleaved virtual stages: device s hosts chunks {s, S+s, ...};
    the hop ring wraps (device S-1 -> device 0 carries the chunk-k ->
    chunk-k+1 boundary). Chunk-boundary activations can wait up to
    max(S, M) - S ticks for their consumer, so each device keeps a
    microbatch-indexed inbox (one extra trash slot absorbs idle-tick
    writes without branching on the buffer)."""
    tabs = _schedules._forward_tables(S, M, V)
    T = tabs["T"]
    EXM = jnp.asarray(tabs["exec_mb"])
    EXK = jnp.asarray(tabs["exec_chunk"])
    RXM = jnp.asarray(tabs["recv_mb"])
    ring = [(i, (i + 1) % S) for i in range(S)]
    # Route network-order slice j = k*S + s to device s in chunk order:
    # after this take, a P(axis) shard of the leading dim holds exactly
    # its V chunks as rows k = 0..V-1.
    perm = jnp.asarray(_schedules.interleave_permutation(S, V))
    params_dev = jax.tree.map(lambda a: jnp.take(a, perm, axis=0),
                              stage_params)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(axis), x_spec), out_specs=x_spec,
                       check_vma=False)
    def run(params, xm):
        s = lax.axis_index(axis)

        def tick(carry, t):
            inbox, out, rx = carry
            # Deliver last tick's hop into the microbatch-indexed inbox
            # (idle ticks write rx=zeros to the trash slot M).
            rm = RXM[t, s]
            inbox = lax.dynamic_update_slice(
                inbox, rx[None],
                (jnp.where(rm >= 0, rm, M),) + (0,) * rx.ndim)
            m = EXM[t, s]
            k = EXK[t, s]
            act = m >= 0
            mc = jnp.clip(m, 0, M - 1)
            fresh = (s == 0) & (k == 0)
            x_in = jnp.where(
                fresh, xm[mc],
                lax.dynamic_index_in_dim(inbox, mc, 0, keepdims=False))
            p_k = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(
                    a, jnp.clip(k, 0, V - 1), 0, keepdims=False), params)
            y = stage_fn(p_k, x_in)
            y = jnp.where(act, y, jnp.zeros_like(y))
            # The final virtual stage records; everyone else forwards.
            rec = act & (s == S - 1) & (k == V - 1)
            out = lax.dynamic_update_slice(
                out, jnp.where(rec, y, jnp.zeros_like(y))[None],
                (jnp.where(rec, mc, M),) + (0,) * y.ndim)
            rx = lax.ppermute(y, axis, ring) if S > 1 else y
            return (inbox, out, rx), None

        inbox0 = jnp.zeros((M + 1,) + xm.shape[1:], xm.dtype)
        out0 = jnp.zeros((M + 1,) + xm.shape[1:], xm.dtype)
        rx0 = jnp.zeros_like(xm[0])
        (_, out, _), _ = lax.scan(tick, (inbox0, out0, rx0),
                                  jnp.arange(T))
        return lax.psum(out[:M], axis)

    return run(params_dev, xm)


def shard_stage_params(stage_params, mesh, axis="pipe", virtual_stages=1):
    """Place a stage-leading pytree with stage s's slice on the axis's
    s-th device row (host->mesh placement helper). With
    ``virtual_stages=V`` the leading dim is S·V (network order; the
    interleaved `pipeline_apply` routes slices to their hosting device
    at trace time)."""
    S = int(mesh.shape[axis])
    V = int(virtual_stages)
    _check_stage_leading_dim(stage_params, S * V, axis, virtual=V)

    def place(a):
        a = np.asarray(a)
        sh = NamedSharding(mesh, P(axis))
        return jax.make_array_from_callback(a.shape, sh,
                                            lambda idx: a[idx])
    return jax.tree.map(place, stage_params)


# ---------------------------------------------------------------------------
# Training: schedule-aware value-and-grad.
# ---------------------------------------------------------------------------


def _plan_zb(S, M):
    """ZB-H1 tables, or (None, reason) when the split schedule can't be
    made shape-stable in one `lax.scan` — the counted fallback path."""
    if S < 2:
        return None, "single_stage"
    try:
        tabs = _schedules._zb_tables(S, M)
        w_mb, Rw = tabs["w_mb"], tabs["w_ring"]
        # Verify the deferred (x, dy) ring never aliases: slot m % Rw is
        # rewritten at Bx(m + Rw), which must come after Bw(m) reads it.
        for s in range(S):
            for t in range(tabs["T"]):
                m = int(w_mb[t, s])
                if m < 0:
                    continue
                next_write = 2 * S - 2 - s + m + Rw  # Bx tick of m + Rw
                if m + Rw < M and next_write <= t:
                    return None, "ring_alias"
        if int((w_mb >= 0).sum()) != S * M:
            return None, "unplaced_bw"
    except Exception:
        return None, "table_error"
    return tabs, None


def make_pipeline_value_and_grad(stage_fn, loss_fn, mesh, axis="pipe",
                                 n_microbatches=None, batch_axis=None,
                                 schedule=None, virtual_stages=None):
    """``vg(stage_params, batch) -> (loss, grads)`` under the chosen
    schedule. gpipe/interleaved differentiate the forward scan (autodiff
    runs the mirrored backward); 1f1b/zb hand-schedule the backward in a
    fused forward/backward scan with an O(S) activation ring
    (recompute-based ``jax.vjp`` per backward tick).

    The fused schedules require every ``batch`` leaf to lead with the
    batch dim and ``loss_fn`` to be mean-decomposable over microbatches
    (true for the usual mean MSE / mean cross-entropy): the loss is
    computed per microbatch at the last stage *inside* the scan and the
    cotangent seeded immediately — that in-scan seeding is what lets B
    ticks interleave with F ticks at all. Gradients and loss match the
    autodiff schedules to float tolerance (asserted in
    tests/test_pipeline.py: schedules change timing, not math).
    """
    name, V = _schedules.resolve_schedule(schedule, virtual_stages)
    S = int(mesh.shape[axis])

    if name in ("gpipe", "interleaved"):
        sched_arg = f"interleaved:{V}" if name == "interleaved" else name

        def vg(params, batch):
            def objective(p):
                out = pipeline_apply(
                    stage_fn, p, batch["x"], mesh, axis, n_microbatches,
                    batch_axis=batch_axis, schedule=sched_arg)
                return loss_fn(out, batch)
            return jax.value_and_grad(objective)(params)
        vg.schedule_label = _schedules.schedule_label(name, V)
        return vg

    # Fused 1F1B / ZB-H1. M is static here (tables are trace-time).
    M = int(n_microbatches or S)
    zb_tabs = None
    if name == "zb":
        zb_tabs, reason = _plan_zb(S, M)
        if zb_tabs is None:
            if _metrics.enabled():
                _metrics.PIPELINE_ZB_FALLBACKS.labels(reason=reason).inc()
            name = "1f1b"
    tabs = zb_tabs if zb_tabs is not None else _schedules._onef1b_tables(S, M)
    info = _schedules.schedule_info(name, S, M, 1)
    vg = _fused_value_and_grad(stage_fn, loss_fn, mesh, axis, S, M,
                               batch_axis, tabs, zb=zb_tabs is not None,
                               info=info)
    vg.schedule_label = info.label
    return vg


def _fused_value_and_grad(stage_fn, loss_fn, mesh, axis, S, M,
                          batch_axis, tabs, zb, info):
    """The fused 1F1B/ZB scan: per tick, an F half (wavefront forward,
    ring-buffered stage input), a B half (recompute vjp; dx hops the
    reverse ring, dp accumulates — or, under ZB, is deferred), and under
    ZB a W half replaying a saved (x, dy) pair for the weight grad."""
    T = tabs["T"]
    FM = jnp.asarray(tabs["f_mb"])
    BM = jnp.asarray(tabs["b_mb"])
    WM = jnp.asarray(tabs["w_mb"]) if zb else None
    Rw = int(tabs.get("w_ring", 1))
    # Stage-input ring: F(m) writes slot m % R at tick s+m, B(m) reads
    # it at 2S-2-s+m; R = 2S-1 outlives every read (the next writer of
    # the slot, F(m+R), lands strictly after). Slot R is the trash slot
    # for idle-tick writes.
    R = max(1, 2 * S - 1)
    fwd = [(i, i + 1) for i in range(S - 1)]
    rev = [(i + 1, i) for i in range(S - 1)]
    dp_n = int(mesh.shape[batch_axis]) if batch_axis else 1
    x_spec = P(None, batch_axis) if batch_axis else P()

    def vg(params, batch):
        x = batch["x"]
        B = x.shape[0]
        _resolve_m(M, S, B)  # reuse the divisibility error + suggestion
        _check_stage_leading_dim(params, S, axis)
        _record_schedule(info)
        mb = B // M

        def to_microbatches(a):
            if a.shape[0] != B:
                raise ValueError(
                    f"fused pipeline schedules need every batch leaf to "
                    f"lead with the batch dim {B}, got shape {a.shape}")
            return a.reshape((M, mb) + a.shape[1:])
        bm_tree = jax.tree.map(to_microbatches, batch)

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(axis), x_spec),
                           out_specs=(P(), P(axis)),
                           check_vma=False)
        def run(params, bm):
            p_s = jax.tree.map(lambda a: a[0], params)
            s = lax.axis_index(axis)
            last = S - 1
            xm = bm["x"]

            def tick(carry, t):
                cur, dyx, buf, wx, wdy, gacc, lacc = carry
                # ---- F half: the gpipe wavefront ----
                fm = FM[t, s]
                fact = fm >= 0
                fmc = jnp.clip(fm, 0, M - 1)
                x_in = jnp.where(s == 0, xm[fmc], cur)
                x_in = jnp.where(fact, x_in, jnp.zeros_like(x_in))
                y = stage_fn(p_s, x_in)
                y = jnp.where(fact, y, jnp.zeros_like(y))
                # Ring-buffer the stage INPUT (recompute vjp at B).
                buf = lax.dynamic_update_slice(
                    buf, x_in[None],
                    (jnp.where(fact, fmc % R, R),) + (0,) * x_in.ndim)
                # Last stage seeds the cotangent from the per-microbatch
                # loss in the SAME tick (B(m) and F(m) share it there).
                mb_t = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(
                        a, fmc, 0, keepdims=False), bm)
                lval, dy_seed = jax.value_and_grad(
                    lambda o: loss_fn(o, mb_t))(y)
                lacc = lacc + jnp.where(
                    (s == last) & fact, lval, 0.0).astype(lacc.dtype)
                # ---- B half: dx on the critical path ----
                bmx = BM[t, s]
                bact = bmx >= 0
                bmc = jnp.clip(bmx, 0, M - 1)
                x_saved = lax.dynamic_index_in_dim(
                    buf, bmc % R, 0, keepdims=False)
                dy_in = jnp.where(s == last, dy_seed / (M * dp_n), dyx)
                dy_in = jnp.where(bact, dy_in, jnp.zeros_like(dy_in))
                _, pullback = jax.vjp(stage_fn, p_s, x_saved)
                dp, dx = pullback(dy_in)
                dx = jnp.where(bact, dx, jnp.zeros_like(dx))
                if zb:
                    # Defer dL/dw: park (x, dy) and replay at the W tick
                    # scheduled into this stage's idle tail.
                    wslot = (jnp.where(bact, bmc % Rw, Rw),)
                    wx = lax.dynamic_update_slice(
                        wx, x_saved[None], wslot + (0,) * x_saved.ndim)
                    wdy = lax.dynamic_update_slice(
                        wdy, dy_in[None], wslot + (0,) * dy_in.ndim)
                    wm = WM[t, s]
                    wact = wm >= 0
                    wmc = jnp.clip(wm, 0, M - 1)
                    xw = lax.dynamic_index_in_dim(
                        wx, wmc % Rw, 0, keepdims=False)
                    dyw = lax.dynamic_index_in_dim(
                        wdy, wmc % Rw, 0, keepdims=False)
                    _, pb_w = jax.vjp(stage_fn, p_s, xw)
                    dpw, _ = pb_w(dyw)
                    gacc = jax.tree.map(
                        lambda g, d: g + jnp.where(wact, d,
                                                   jnp.zeros_like(d)),
                        gacc, dpw)
                else:
                    gacc = jax.tree.map(
                        lambda g, d: g + jnp.where(bact, d,
                                                   jnp.zeros_like(d)),
                        gacc, dp)
                # ---- hops ----
                cur = lax.ppermute(y, axis, fwd) if S > 1 else y
                dyx = lax.ppermute(dx, axis, rev) if S > 1 else dx
                return (cur, dyx, buf, wx, wdy, gacc, lacc), None

            zeros_mb = jnp.zeros_like(xm[0])
            buf0 = jnp.zeros((R + 1,) + xm.shape[1:], xm.dtype)
            wn = (Rw + 1) if zb else 1  # dummy 1-slot when unused
            wx0 = jnp.zeros((wn,) + xm.shape[1:], xm.dtype)
            wdy0 = jnp.zeros((wn,) + xm.shape[1:], xm.dtype)
            gacc0 = jax.tree.map(jnp.zeros_like, p_s)
            carry0 = (zeros_mb, zeros_mb, buf0, wx0, wdy0, gacc0,
                      jnp.zeros((), jnp.float32))
            (c, d, b_, w1, w2, gacc, lacc), _ = lax.scan(
                tick, carry0, jnp.arange(T))
            loss = lax.psum(lacc / M, axis)  # nonzero on stage S-1 only
            if batch_axis:
                loss = lax.psum(loss, batch_axis) / dp_n
                # dy was pre-scaled by 1/(M*dp_n); summing replica grads
                # completes the full-batch mean.
                gacc = jax.tree.map(
                    lambda g: lax.psum(g, batch_axis), gacc)
            grads = jax.tree.map(lambda g: g[None], gacc)
            return loss, grads

        return run(params, bm_tree)
    return vg


def make_pipeline_train_step(stage_fn, loss_fn, tx, mesh, axis="pipe",
                             n_microbatches=None, batch_axis=None,
                             jit=True, schedule=None, virtual_stages=None):
    """Standard train step over the pipeline: ``loss_fn(outputs, batch)``
    -> scalar; grads w.r.t. the stage-sharded params; optimizer applies
    per-stage updates in place. ``batch_axis`` composes data parallelism
    (see pipeline_apply — grads come out already reduced); ``schedule``
    picks the tick order (see the module docstring — 1f1b/zb run the
    fused forward/backward scan, which needs a mean-decomposable
    ``loss_fn``; gradients are schedule-invariant). Returns
    ``step(stage_params, opt_state, batch) -> (params, opt_state, loss)``.

    With metrics enabled at build time (``HVD_METRICS=1``) the step is
    wrapped to count PIPELINE_STEPS and emit PIPELINE_STEP /
    PIPELINE_{WARMUP,STEADY,COOLDOWN} timeline spans (the phase spans
    are tick-proportional estimates of the measured step wall time —
    the host cannot observe intra-XLA tick boundaries).
    """
    vg = make_pipeline_value_and_grad(
        stage_fn, loss_fn, mesh, axis, n_microbatches,
        batch_axis=batch_axis, schedule=schedule,
        virtual_stages=virtual_stages)

    import optax

    def step(params, opt_state, batch):
        loss, grads = vg(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    stepc = jax.jit(step, donate_argnums=(0, 1)) if jit else step
    if not _metrics.enabled():
        return stepc

    name, V = _schedules.resolve_schedule(schedule, virtual_stages)
    S = int(mesh.shape[axis])
    info = _schedules.schedule_info(name, S, int(n_microbatches or S), V)

    def timed_step(params, opt_state, batch):
        t0 = time.perf_counter_ns()
        params, opt_state, loss = stepc(params, opt_state, batch)
        jax.block_until_ready(loss)
        dur_us = (time.perf_counter_ns() - t0) // 1000
        _metrics.PIPELINE_STEPS.labels(schedule=info.label).inc()
        end_us = time.time_ns() // 1000
        start_us = end_us - dur_us
        _spans.event("PIPELINE_STEP", start_us, dur_us, cat="pipeline",
                     schedule=info.label, ticks=info.ticks,
                     bubble=round(info.bubble_fraction, 4))
        # Tick-proportional phase estimates of the measured wall time.
        tot = max(1, info.ticks)
        w_us = dur_us * info.warmup_ticks // tot
        c_us = dur_us * info.cooldown_ticks // tot
        s_us = dur_us - w_us - c_us
        _spans.event("PIPELINE_WARMUP", start_us, w_us,
                     cat="pipeline", estimate=True)
        _spans.event("PIPELINE_STEADY", start_us + w_us, s_us,
                     cat="pipeline", estimate=True)
        _spans.event("PIPELINE_COOLDOWN", start_us + w_us + s_us, c_us,
                     cat="pipeline", estimate=True)
        return params, opt_state, loss

    return timed_step
