"""Pipeline parallelism over a mesh axis (BEYOND REFERENCE).

The reference has no pipeline parallelism and no p2p send/recv API at
all (SURVEY.md §2.4: "PP — absent; no send/recv"). On TPU the natural
p2p primitive is `lax.ppermute` over an ICI-adjacent mesh axis, and the
natural schedule is the GPipe microbatch pipeline expressed as ONE
`lax.scan` inside `shard_map` — every stage runs the same compiled
program, activations hop stage→stage with a single collective-permute
per tick, and XLA overlaps the permute with the next tick's compute.
Autodiff flows through the whole schedule (scan + ppermute are both
differentiable; the transpose of a forward hop is the reverse hop), so
the backward pipeline comes for free instead of being hand-scheduled
the way GPU frameworks do it.

Scope: `pipeline_apply` is the forward primitive (differentiable — take
`jax.grad` of a loss on its outputs to train);
`make_pipeline_train_step` packages the standard loss/grad/update loop.
`stage_fn` must be shape-preserving ([mb, ...] -> [mb, ...]): classic
homogeneous-stack pipelining (transformer blocks). The pipeline bubble
is the usual (S-1)/(M+S-1) fraction — pick n_microbatches >> stages.
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..observability import metrics as _metrics


def pipeline_apply(stage_fn, stage_params, x, mesh, axis="pipe",
                   n_microbatches=None, batch_axis=None):
    """Run ``x`` through S pipeline stages laid out on ``mesh[axis]``.

    Args:
      stage_fn: ``(params_for_one_stage, h) -> h`` with ``h`` of shape
        ``[microbatch, ...]`` (shape-preserving).
      stage_params: pytree whose leaves have a leading stage dim of size
        S == mesh.shape[axis] (stage s uses ``leaf[s]``).
      x: ``[batch, ...]`` input; ``batch`` must divide into
        ``n_microbatches`` equal microbatches.
      n_microbatches: number of microbatches M (default: S, the minimum
        that keeps every stage busy in steady state).
      batch_axis: optional second mesh axis composing DATA parallelism
        with the pipeline (pp x dp): each microbatch's rows shard over
        it, every data replica runs the same pipeline schedule on its
        shard, and the per-tick ppermute stays within the pipe axis.
        Gradients need NO extra collective: params are replicated over
        ``batch_axis``, so shard_map's transpose already psums their
        cotangent across the data shards — ``jax.grad`` of a loss on
        these outputs IS the full-batch gradient (asserted in
        tests/test_pipeline.py); adding a manual psum would double-count.

    Returns ``[batch, ...]`` outputs (replicated across the pipe axis;
    sharded over ``batch_axis`` when given).
    """
    S = int(mesh.shape[axis])
    M = int(n_microbatches or S)
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    # A stage-count mismatch would SILENTLY compute the wrong function:
    # shard_map hands each device shape[0]/S rows and `a[0]` would drop
    # the rest (e.g. 8 stage slices on 4 devices = even stages only).
    for leaf in jax.tree.leaves(stage_params):
        if leaf.ndim < 1 or leaf.shape[0] != S:
            raise ValueError(
                f"stage_params leaf shape {jnp.shape(leaf)} must lead "
                f"with the pipeline stage count {S} (mesh axis {axis!r})")
    if _metrics.enabled():
        # Trace-time schedule metadata (this body runs once per compile,
        # not per step — per-tick device work is XLA's, visible through
        # the xplane profiler, not host counters).
        _metrics.PIPELINE_TRACES.labels(
            stages=str(S), microbatches=str(M)).inc()
        _metrics.PIPELINE_BUBBLE.set((S - 1) / (M + S - 1))
    mb = B // M
    xm = x.reshape((M, mb) + x.shape[1:])

    fwd = [(i, i + 1) for i in range(S - 1)]
    # Microbatch rows shard over batch_axis (dp compose); the stage dim
    # of the params shards over the pipe axis either way.
    x_spec = P(None, batch_axis) if batch_axis else P()

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(axis), x_spec), out_specs=x_spec,
                       check_vma=False)
    def run(params, xm):
        # Each shard sees its own stage slice with a leading dim of 1.
        p_s = jax.tree.map(lambda a: a[0], params)
        s = lax.axis_index(axis)
        last = S - 1

        def tick(carry, t):
            cur, out = carry
            active = (t - s >= 0) & (t - s < M)
            y = stage_fn(p_s, cur)
            # Mask the bubble: inactive ticks contribute nothing (and
            # their gradients vanish through the where).
            y = jnp.where(active, y, jnp.zeros_like(y))
            # Last stage records its finished microbatch. Mask the VALUE,
            # not the buffer: selecting between two full copies of `out`
            # would defeat in-place dynamic_update_slice inside the scan
            # (O(M) full-output copies). Non-recording ticks write zeros
            # into slot 0 of an all-zero buffer before its real (later)
            # write, so results are identical.
            m_out = t - last
            rec = (s == last) & (m_out >= 0)
            idx = jnp.clip(m_out, 0, M - 1)
            out = lax.dynamic_update_slice(
                out, jnp.where(rec, y, jnp.zeros_like(y))[None],
                (idx,) + (0,) * y.ndim)
            # Hop forward one stage; stage 0 ingests the next microbatch.
            shifted = lax.ppermute(y, axis, fwd) if S > 1 else y
            nxt = xm[jnp.clip(t + 1, 0, M - 1)]
            nxt = jnp.where(t + 1 < M, nxt, jnp.zeros_like(nxt))
            cur = jnp.where(s == 0, nxt, shifted)
            return (cur, out), None

        cur0 = jnp.where(s == 0, xm[0], jnp.zeros_like(xm[0]))
        out0 = jnp.zeros_like(xm)
        (cur, out), _ = lax.scan(tick, (cur0, out0),
                                 jnp.arange(M + S - 1))
        # Only the last stage holds real outputs; psum replicates them
        # (every other shard contributes zeros).
        return lax.psum(out, axis)

    out = run(stage_params, xm)
    return out.reshape((B,) + out.shape[2:])


def shard_stage_params(stage_params, mesh, axis="pipe"):
    """Place a [S, ...]-leading pytree with stage s's slice on the
    axis's s-th device row (host->mesh placement helper)."""
    S = int(mesh.shape[axis])

    def place(a):
        a = np.asarray(a)
        if a.ndim < 1 or a.shape[0] != S:
            raise ValueError(
                f"stage param leaf shape {a.shape} must lead with the "
                f"stage count {S} (mesh axis {axis!r})")
        sh = NamedSharding(mesh, P(axis))
        return jax.make_array_from_callback(a.shape, sh,
                                            lambda idx: a[idx])
    return jax.tree.map(place, stage_params)


def make_pipeline_train_step(stage_fn, loss_fn, tx, mesh, axis="pipe",
                             n_microbatches=None, batch_axis=None,
                             jit=True):
    """Standard train step over the pipeline: ``loss_fn(outputs, batch)``
    -> scalar; grads w.r.t. the stage-sharded params; optimizer applies
    per-stage updates in place. ``batch_axis`` composes data parallelism
    (see pipeline_apply — grads come out already reduced). Returns
    ``step(stage_params, opt_state, batch) -> (params, opt_state, loss)``.
    """
    def objective(params, batch):
        out = pipeline_apply(stage_fn, params, batch["x"], mesh, axis,
                             n_microbatches, batch_axis=batch_axis)
        return loss_fn(out, batch)

    import optax

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(objective)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1)) if jit else step
