"""Process sets: collectives over subsets of ranks.

TPU-native counterpart of the reference's ``horovod/common/process_sets.py``
+ ``process_set.cc``: a :class:`ProcessSet` names a subset of global ranks and
every collective accepts ``process_set=``. Registration is itself a collective
(all ranks must call :func:`add_process_set` with the same ranks). These are
the building block for hierarchical/hybrid parallelism (e.g. per-replica-group
allreduce in dp×tp meshes).
"""

from .basics import _lib, basics
from .ops import collective_ops as _ops


class ProcessSet:
    def __init__(self, ranks, process_set_id=None):
        self._ranks = sorted(int(r) for r in ranks)
        self.process_set_id = process_set_id

    @property
    def ranks(self):
        # The global set spans all ranks; its membership is only known after
        # init, so resolve lazily.
        if self.process_set_id == 0 and not self._ranks and basics.is_initialized():
            self._ranks = list(range(basics.size()))
        return self._ranks

    @ranks.setter
    def ranks(self, value):
        self._ranks = sorted(int(r) for r in value)

    def included(self):
        if self.process_set_id == 0:
            return True
        return basics.rank() in self.ranks

    def rank(self):
        """This process's rank within the set, or -1 if not a member."""
        if self.process_set_id is None:
            raise ValueError("process set has not been registered")
        return _lib.hvd_process_set_rank(self.process_set_id)

    def size(self):
        if self.process_set_id is None:
            return len(self.ranks)
        return _lib.hvd_process_set_size(self.process_set_id)

    def __repr__(self):
        return f"ProcessSet(id={self.process_set_id}, ranks={self.ranks})"


global_process_set = ProcessSet([], process_set_id=0)


def add_process_set(process_set_or_ranks):
    """Collectively register a process set; all ranks must call this with the
    same ranks in the same order relative to other collectives."""
    if isinstance(process_set_or_ranks, ProcessSet):
        ps = process_set_or_ranks
    else:
        ps = ProcessSet(process_set_or_ranks)
    ps.process_set_id = _ops.add_process_set_collective(ps.ranks)
    return ps


def remove_process_set(process_set):
    """Collectively deregister a process set."""
    if process_set.process_set_id in (None, 0):
        raise ValueError("cannot remove the global process set")
    _ops.remove_process_set_collective(process_set.process_set_id)
    process_set.process_set_id = None
