"""horovod_tpu.spark — run ranks inside Spark executors, plus the
estimator layer.

Reference parity: ``horovod/spark/__init__.py`` (``horovod.spark.run``:
one rank per Spark task, results collected to the driver). The estimator
layer lives in :mod:`.keras` (``KerasEstimator``), :mod:`.torch`
(``TorchEstimator``) and :mod:`.lightning` (``LightningEstimator``, the
reference's ``lightning/estimator.py`` analog over the LightningModule
protocol) — ``fit(df)`` materializes the DataFrame to the :mod:`.store`
(filesystem-abstracted: local, dbfs:/, and fsspec-backed hdfs/gs/s3
behind one ``FilesystemStore`` class), trains N ranks through a backend
(negotiated local processes by default, barrier Spark tasks via
:class:`~horovod_tpu.spark.params.SparkBackend`), and returns a
transformer model. Everything except ``run()`` itself is importable and
usable without pyspark — see the README descope note for what changes
without petastorm (``.npz`` shards instead of parquet).

Like the reference, each Spark task becomes one rank of a fresh job. The
driver hosts the HMAC-signed KV store; rank 0 registers a controller port
probed on ITS OWN executor node through the same negotiation path tpurun
multi-host launches and the ray backend use (runner/network.py) — no
remote port is ever guessed from the driver.
"""
import cloudpickle


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark.run requires the 'pyspark' package, which "
            "is not installed in this environment (see the README descope "
            "note). horovod_tpu.spark.store works without pyspark; for a "
            "programmatic multi-rank launcher use horovod_tpu.ray."
        ) from e


def run(fn, args=(), kwargs=None, num_proc=None, extra_env=None,
        timeout=600.0):
    """Run ``fn(*args, **kwargs)`` as ``num_proc`` ranks inside barrier
    Spark tasks; returns per-rank results ordered by rank (reference:
    ``horovod.spark.run``)."""
    _require_pyspark()
    from pyspark import BarrierTaskContext, SparkContext

    from ..runner.program import host_negotiation_kv, run_negotiated_payload

    sc = SparkContext.getOrCreate()
    n = num_proc or int(sc.defaultParallelism)
    # The driver's address as reachable by executors: probe toward the
    # cluster master when its host is known, else fall back to fqdn.
    master = sc.master or ""
    probe_hosts = []
    if "://" in master:
        host = master.split("://", 1)[1].rsplit(":", 1)[0]
        if host and host != "local":
            probe_hosts.append(host)
    rdv, extra = host_negotiation_kv("spark-job", probe_hosts,
                                     extra_env=extra_env, timeout=timeout)
    try:
        payload = cloudpickle.dumps((fn, tuple(args), dict(kwargs or {})))

        def task(_):
            ctx = BarrierTaskContext.get()
            rank = ctx.partitionId()
            # Scope per stage attempt: a retried barrier stage must not
            # read the dead prior attempt's port registrations.
            attempt = getattr(ctx, "stageAttemptNumber", lambda: 0)()
            out = run_negotiated_payload(rank, n, payload, extra,
                                         scope_suffix=f"try{attempt}")
            return [(rank, out)]

        rdd = sc.parallelize(range(n), n).barrier()
        results = rdd.mapPartitions(task).collect()
        return [out for _, out in sorted(results)]
    finally:
        rdv.stop()


from .store import LocalStore, Store  # noqa: E402,F401
