"""Artifact stores for estimator-style training (reference:
``horovod/spark/common/store.py`` — ``Store``, ``FilesystemStore``,
``LocalStore``, ``HDFSStore``, ``DBFSLocalStore``).

A Store names where intermediate data, checkpoints and logs live AND owns
the byte IO to get there. The path layout lives in
:class:`FilesystemStore`; the actual filesystem is a small adapter object
(open/exists/makedirs/delete) so remote backends drop in behind one class
(VERDICT r4 missing #2): ``LocalStore`` binds the local filesystem,
``HDFSStore``/``GCSStore``/``S3Store`` bind a pyarrow/fsspec filesystem
when one of those libraries is present (neither is installable in this
zero-egress build — constructing them without a driver raises the descope
error instead of failing deep inside training), and ``DBFSLocalStore`` is
the reference's Databricks special case (``dbfs:/...`` is the same data
as the fuse mount ``/dbfs/...``). The estimator layer reads and writes
shards/checkpoints exclusively through ``store.open_read`` /
``store.open_write``, never bare ``open()`` — tested against an
in-memory filesystem in tests/test_data_and_stores.py.
"""
import io
import os
import posixpath
import shutil


class Store:
    """Abstract artifact store (reference: common/store.py `Store`)."""

    # -- path layout -------------------------------------------------------
    def get_train_data_path(self, idx=None):
        raise NotImplementedError

    def get_val_data_path(self, idx=None):
        raise NotImplementedError

    def get_checkpoint_path(self, run_id):
        raise NotImplementedError

    def get_logs_path(self, run_id):
        raise NotImplementedError

    # -- byte IO -----------------------------------------------------------
    def exists(self, path):
        raise NotImplementedError

    def open_read(self, path):
        """Binary-read file object for a store path."""
        raise NotImplementedError

    def open_write(self, path):
        """Binary-write file object for a store path (parents created)."""
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    @staticmethod
    def create(prefix_path):
        """Factory routing on the URL scheme (reference parity:
        `Store.create`)."""
        p = str(prefix_path)
        if p.startswith("hdfs://"):
            return HDFSStore(p)
        if p.startswith("dbfs:/"):
            return DBFSLocalStore(p)
        if p.startswith("gs://"):
            return GCSStore(p)
        if p.startswith("s3://"):
            return S3Store(p)
        return LocalStore(p)


class FilesystemStore(Store):
    """Path layout + IO over a pluggable filesystem adapter.

    ``fs`` needs four methods (the fsspec/pyarrow common denominator):
    ``open(path, mode)`` ('rb'/'wb'), ``exists(path)``,
    ``makedirs(path)`` (idempotent), ``delete(path)`` (recursive, missing
    ok). Anything speaking that protocol — local disk, HDFS, GCS, an
    in-memory fake — gives a fully working store.
    """

    def __init__(self, prefix_path, fs):
        self.prefix_path = str(prefix_path).rstrip("/")
        self.fs = fs
        self.fs.makedirs(self.prefix_path)

    def _sub(self, *parts):
        # Every store path is a directory (shard sets, checkpoint dirs,
        # log dirs) — create it so writers can address files inside
        # directly.
        p = posixpath.join(self.prefix_path, *parts)
        self.fs.makedirs(p)
        return p

    def get_train_data_path(self, idx=None):
        return self._sub("intermediate_train_data" +
                         (f".{idx}" if idx is not None else ""))

    def get_val_data_path(self, idx=None):
        return self._sub("intermediate_val_data" +
                         (f".{idx}" if idx is not None else ""))

    def get_checkpoint_path(self, run_id):
        return self._sub("runs", str(run_id), "checkpoint")

    def get_logs_path(self, run_id):
        return self._sub("runs", str(run_id), "logs")

    def exists(self, path):
        return self.fs.exists(path)

    def open_read(self, path):
        return self.fs.open(path, "rb")

    def open_write(self, path):
        self.fs.makedirs(posixpath.dirname(path))
        return self.fs.open(path, "wb")

    def delete(self, path):
        self.fs.delete(path)


class LocalFilesystem:
    """The local-disk adapter behind LocalStore."""

    def open(self, path, mode):
        return open(path, mode)

    def exists(self, path):
        return os.path.exists(path)

    def makedirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)


class LocalStore(FilesystemStore):
    """Store rooted at a local (or NFS-mounted) directory."""

    def __init__(self, prefix_path):
        super().__init__(os.path.abspath(str(prefix_path)),
                         LocalFilesystem())


class DBFSLocalStore(LocalStore):
    """Databricks DBFS via its fuse mount (reference: `DBFSLocalStore` —
    dbfs:/path and /dbfs/path are the same files)."""

    @staticmethod
    def translate(prefix_path):
        p = str(prefix_path)
        if p.startswith("dbfs:/"):
            p = "/dbfs/" + p[len("dbfs:/"):].lstrip("/")
        return p

    def __init__(self, prefix_path):
        super().__init__(self.translate(prefix_path))


def _fsspec_filesystem(scheme, lib_hint):
    """Build an adapter from fsspec or pyarrow.fs, the two libraries that
    actually speak these protocols. Neither is installable in this
    zero-egress environment, so in this build the constructor raising is
    the documented behavior (README descopes) — but the code path is the
    real one: any site with the library present gets a working store
    through the same four-method adapter LocalStore uses."""
    try:
        import fsspec

        class _FsspecAdapter:
            def __init__(self):
                # Raises inside when the scheme's driver is missing
                # (gcsfs/s3fs not installed, pyarrow-hdfs without a JVM…)
                self._fs = fsspec.filesystem(scheme)

            def open(self, path, mode):
                return self._fs.open(path, mode)

            def exists(self, path):
                return self._fs.exists(path)

            def makedirs(self, path):
                self._fs.makedirs(path, exist_ok=True)

            def delete(self, path):
                if self._fs.exists(path):
                    self._fs.rm(path, recursive=True)

        return _FsspecAdapter()
    except Exception as e:  # noqa: BLE001 — driver construction can fail
        # many ways (ImportError for gcsfs/s3fs, OSError for a JVM-less
        # pyarrow hdfs, ...); all mean the same thing here.
        cause = e
    raise ImportError(
        f"a {scheme}:// store needs a working {lib_hint} (or fsspec) "
        f"driver, unavailable in this environment ({cause}) — see the "
        f"README descope notes; use a local/NFS path, or inject a "
        f"filesystem adapter via FilesystemStore(prefix, fs=...)") \
        from cause


class HDFSStore(FilesystemStore):
    """HDFS-backed store (reference: `HDFSStore`, petastorm-era)."""

    def __init__(self, prefix_path, fs=None):
        super().__init__(prefix_path,
                         fs or _fsspec_filesystem("hdfs", "pyarrow/hdfs"))


class GCSStore(FilesystemStore):
    """GCS-backed store (beyond reference: the TPU-native deployment
    target's object store)."""

    def __init__(self, prefix_path, fs=None):
        super().__init__(prefix_path,
                         fs or _fsspec_filesystem("gs", "gcsfs"))


class S3Store(FilesystemStore):
    """S3-backed store."""

    def __init__(self, prefix_path, fs=None):
        super().__init__(prefix_path,
                         fs or _fsspec_filesystem("s3", "s3fs"))


class InMemoryFilesystem:
    """A dict-backed adapter for in-process use (conformance tests):
    proves (and guards) that the estimator data path never touches bare
    open(). It is process-local — pickling copies the dict — so the
    estimator layer refuses it for training runs, where rank subprocesses
    would write checkpoints into discarded copies."""

    process_local = True  # estimators must reject this fs (params.py)

    def __init__(self):
        self._files = {}
        self._dirs = set()

    def open(self, path, mode):
        if mode == "rb":
            if path not in self._files:
                raise FileNotFoundError(path)
            return io.BytesIO(self._files[path])
        if mode == "wb":
            fs = self

            class _Writer(io.BytesIO):
                def close(self):
                    fs._files[path] = self.getvalue()
                    super().close()

            return _Writer()
        raise ValueError(f"mode {mode!r} not supported")

    def exists(self, path):
        return path in self._files or path in self._dirs or any(
            f.startswith(path + "/") for f in self._files)

    def makedirs(self, path):
        self._dirs.add(path)

    def delete(self, path):
        self._files = {k: v for k, v in self._files.items()
                       if k != path and not k.startswith(path + "/")}
        self._dirs = {d for d in self._dirs
                      if d != path and not d.startswith(path + "/")}
