"""Artifact stores for estimator-style training (reference:
``horovod/spark/common/store.py`` — ``Store``, ``LocalStore``; the HDFS and
DBFS variants are descoped with pyspark, see the README).

A Store names where intermediate data, checkpoints and logs live. It has
no pyspark dependency — the estimator/runner layer passes paths around; IO
happens with ordinary filesystem calls here.
"""
import os
import shutil


class Store:
    """Abstract artifact store."""

    def get_train_data_path(self, idx=None):
        raise NotImplementedError

    def get_val_data_path(self, idx=None):
        raise NotImplementedError

    def get_checkpoint_path(self, run_id):
        raise NotImplementedError

    def get_logs_path(self, run_id):
        raise NotImplementedError

    def exists(self, path):
        raise NotImplementedError

    @staticmethod
    def create(prefix_path):
        """Factory (reference parity): local filesystem paths only in this
        build; hdfs:// / dbfs:// schemes are descoped with pyspark."""
        for scheme in ("hdfs://", "dbfs://", "s3://", "gs://"):
            if str(prefix_path).startswith(scheme):
                raise NotImplementedError(
                    f"{scheme} stores are descoped in this build (see "
                    f"README); use a local/NFS path")
        return LocalStore(prefix_path)


class LocalStore(Store):
    """Store rooted at a local (or NFS-mounted) directory."""

    def __init__(self, prefix_path):
        self.prefix_path = os.path.abspath(str(prefix_path))
        os.makedirs(self.prefix_path, exist_ok=True)

    def _sub(self, *parts):
        # Every store path is a directory (parquet datasets, checkpoint
        # dirs, log dirs) — create it so indexed and un-indexed variants
        # behave identically for writers.
        p = os.path.join(self.prefix_path, *parts)
        os.makedirs(p, exist_ok=True)
        return p

    def get_train_data_path(self, idx=None):
        return self._sub("intermediate_train_data" +
                         (f".{idx}" if idx is not None else ""))

    def get_val_data_path(self, idx=None):
        return self._sub("intermediate_val_data" +
                         (f".{idx}" if idx is not None else ""))

    def get_checkpoint_path(self, run_id):
        return self._sub("runs", str(run_id), "checkpoint")

    def get_logs_path(self, run_id):
        return self._sub("runs", str(run_id), "logs")

    def exists(self, path):
        return os.path.exists(path)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)
