"""LightningEstimator — estimator-style data-parallel training of a
PyTorch-Lightning-protocol module (reference:
``horovod/spark/lightning/estimator.py`` ``TorchEstimator`` — the
lightning estimator family — and ``lightning/datamodule.py``).

The reference drives a real ``pytorch_lightning.Trainer`` with a Horovod
accelerator plugin. pytorch_lightning is not installed in this
environment, so this build consumes the Lightning *protocol* instead of
the library: anything whose module implements the LightningModule core
contract —

- ``training_step(batch, batch_idx) -> loss | {"loss": loss, ...}``
- ``configure_optimizers() -> optimizer | [optimizers] |
  ([optimizers], [schedulers]) | {"optimizer": ...}``
- optional ``validation_step(batch, batch_idx) -> loss | {...}``
- ``forward`` for inference (it is a torch ``nn.Module``)

— trains data-parallel through the torch binding
(``broadcast_parameters`` + ``DistributedOptimizer`` gradient hooks), so
a real ``pl.LightningModule`` works unmodified (it satisfies the same
protocol), and so does the conformance shim in
``tests/shims/pytorch_lightning``. Rank 0 checkpoints the state_dict to
the store; a :class:`LightningModel` transformer comes back.
"""
import os

import cloudpickle
import numpy as np

from .params import (EstimatorParams, HorovodModel, load_shard,
                     open_artifact)


def _first_optimizer(configured):
    """Normalize every configure_optimizers() return shape the Lightning
    contract allows down to (optimizer, scheduler_or_None). Multi-optimizer
    setups (GAN-style manual optimization) are rejected loudly — the
    reference's Horovod accelerator has the same single-optimizer limit."""
    def unwrap_sched(s):
        # Lightning also allows an lr_scheduler CONFIG dict
        # ({"scheduler": sch, "interval": ..., ...}); only the scheduler
        # itself is actionable here (per-epoch stepping).
        if isinstance(s, dict):
            return s.get("scheduler")
        return s

    def reject_multi():
        raise ValueError("multi-optimizer LightningModules are not "
                         "supported (single-optimizer limit, as in the "
                         "reference's Horovod accelerator)")

    c = configured
    if isinstance(c, dict):
        # "optimizer" may itself be a single optimizer or a (length-1)
        # list of them — recurse so both unwrap/validate the same way.
        opt, inner = _first_optimizer(c["optimizer"])
        sched = unwrap_sched(c.get("lr_scheduler"))
        return opt, sched if sched is not None else inner
    if isinstance(c, (list, tuple)):
        # Two-sequence form — Lightning's ([opts], [scheds]), which user
        # code also writes as a list of two lists.
        if len(c) == 2 and isinstance(c[0], (list, tuple)):
            opts, scheds = c
            if len(opts) != 1:
                reject_multi()
            sched = unwrap_sched(scheds[0]) if scheds else None
            opt, inner_sched = _first_optimizer(opts[0])
            return opt, sched if sched is not None else inner_sched
        # Flat sequence of optimizers (or of per-optimizer config dicts).
        if len(c) != 1:
            reject_multi()
        return _first_optimizer(c[0])
    return c, None


def _step_loss(out):
    """training_step may return a tensor or a {"loss": ...} dict."""
    if isinstance(out, dict):
        return out["loss"]
    return out


def _train_fn(spec):
    """Per-rank training body (fresh process, slot env already set)."""
    import torch

    import horovod_tpu.torch as hvd

    hvd.init()
    r = hvd.rank()
    torch.manual_seed(spec["seed"] + r)

    module = cloudpickle.loads(spec["module"])
    hvd.broadcast_parameters(module.state_dict(), root_rank=0)
    optimizer, scheduler = _first_optimizer(module.configure_optimizers())
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=module.named_parameters())

    store = spec.get("store")
    X, Y = load_shard(spec["train_path"], r, store)
    X, Y = torch.from_numpy(X), torch.from_numpy(Y)
    bs, n = spec["batch_size"], len(X)

    history = []
    for epoch in range(spec["epochs"]):
        order = torch.randperm(n) if spec["shuffle"] else torch.arange(n)
        total, seen = 0.0, 0
        module.train()
        for batch_idx, i in enumerate(range(0, n, bs)):
            idx = order[i:i + bs]
            optimizer.zero_grad()
            loss = _step_loss(module.training_step((X[idx], Y[idx]),
                                                   batch_idx))
            loss.backward()
            optimizer.step()
            total += float(loss) * len(idx)
            seen += len(idx)
        if scheduler is not None:
            scheduler.step()
        history.append(hvd.metric_average(total / max(seen, 1),
                                          f"est_loss_{epoch}"))

    val = None
    Xv, Yv = load_shard(spec["val_path"], r, store)
    if len(Xv) and hasattr(module, "validation_step"):
        module.eval()
        with torch.no_grad():
            out = module.validation_step(
                (torch.from_numpy(Xv), torch.from_numpy(Yv)), 0)
        try:
            val = hvd.metric_average(float(_step_loss(out)), "est_val_loss")
        except (KeyError, TypeError):
            val = None  # validation_step returned nothing loss-shaped

    state = {k: v.cpu() for k, v in module.state_dict().items()}
    if r == 0:
        with open_artifact(store, os.path.join(spec["ckpt_path"],
                                               "module.pt")) as f:
            torch.save(state, f)
    hvd.shutdown()
    return {"loss_history": history, "val_loss": val,
            "state_dict": state if r == 0 else None}


class LightningEstimator(EstimatorParams):
    """Data-parallel estimator over a LightningModule-protocol object
    (reference: horovod/spark/lightning/estimator.py).

    ``model`` is the module; loss and optimizer live INSIDE it
    (``training_step`` / ``configure_optimizers``), so the base
    estimator's ``loss``/``optimizer`` parameters do not apply.
    """

    def _check_params(self):
        if self.model is None:
            raise ValueError("model (a LightningModule-protocol object) "
                             "is required")
        for method in ("training_step", "configure_optimizers"):
            if not callable(getattr(self.model, method, None)):
                raise ValueError(
                    f"model must implement {method}() — the "
                    f"LightningModule core protocol (see module docstring)")
        if not self.feature_cols or not self.label_cols:
            raise ValueError("feature_cols and label_cols are required")
        if self.num_proc < 1:
            raise ValueError("num_proc must be >= 1")

    def fit(self, df):
        self._check_params()
        store, run_id = self._prepare_store()
        train_path, val_path, _ = self._materialize(df, run_id)
        ckpt_path = store.get_checkpoint_path(run_id)

        spec = {
            "module": cloudpickle.dumps(self.model),
            "batch_size": self.batch_size,
            "epochs": self.epochs,
            "shuffle": self.shuffle,
            "seed": self.seed,
            "train_path": train_path,
            "val_path": val_path,
            "ckpt_path": ckpt_path,
            "store": store,
        }
        results = self._run(_train_fn, spec)
        rank0 = results[0]
        module = cloudpickle.loads(spec["module"])
        module.load_state_dict(rank0["state_dict"])
        return LightningModel(
            model=module, feature_cols=self.feature_cols,
            label_cols=self.label_cols, history=rank0["loss_history"],
            val_loss=rank0["val_loss"], checkpoint_path=ckpt_path)


class LightningModel(HorovodModel):
    """Fitted transformer over the trained module (reference:
    lightning/estimator.py TorchModel)."""

    def __init__(self, model, feature_cols, label_cols, history=None,
                 val_loss=None, checkpoint_path=None, output_cols=None):
        super().__init__(feature_cols, label_cols, output_cols)
        self.model = model
        self.history = history or []
        self.val_loss = val_loss
        self.checkpoint_path = checkpoint_path

    def _predict(self, X):
        import torch

        self.model.eval()
        with torch.no_grad():
            x = torch.from_numpy(np.array(X, dtype=np.float32, copy=True))
            return self.model(x).numpy()

    @classmethod
    def load(cls, model, checkpoint_path, feature_cols, label_cols,
             output_cols=None, store=None):
        """Rebuild from a store checkpoint written by fit(): ``model`` is
        an architecture instance to load the state_dict into."""
        import io

        import torch

        with open_artifact(store, os.path.join(checkpoint_path,
                                               "module.pt"), "rb") as f:
            # Buffer: torch.load needs a seekable file, and the adapter
            # contract doesn't promise one (streaming object stores).
            state = torch.load(io.BytesIO(f.read()), weights_only=True)
        model.load_state_dict(state)
        return cls(model, feature_cols, label_cols,
                   checkpoint_path=checkpoint_path, output_cols=output_cols)
