"""KerasEstimator — estimator-style data-parallel Keras training
(reference: ``horovod/spark/keras/estimator.py`` ``KerasEstimator`` /
``KerasModel``).

``fit(df)`` materializes the DataFrame to the store, launches ``num_proc``
ranks through the backend (local negotiated processes by default, barrier
Spark tasks with :class:`~horovod_tpu.spark.params.SparkBackend`), trains
with the Keras binding (``DistributedOptimizer`` +
``BroadcastGlobalVariablesCallback`` + ``MetricAverageCallback``), has
rank 0 checkpoint the weights to the store, and returns a
:class:`KerasModel` whose ``transform`` appends prediction columns.
"""
import os

import numpy as np

from .params import (EstimatorParams, HorovodModel, load_shard,
                     open_artifact)


def _train_fn(spec):
    """Per-rank training body (runs in a fresh process with slot env set)."""
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    import tensorflow as tf

    import horovod_tpu.keras as hvd

    hvd.init()
    r = hvd.rank()
    tf.keras.utils.set_random_seed(spec["seed"] + r)

    store = spec.get("store")
    X, Y = load_shard(spec["train_path"], r, store)
    model = tf.keras.models.model_from_json(
        spec["model_json"], custom_objects=spec["custom_objects"] or None)
    model.set_weights(spec["weights"])
    opt = spec["optimizer"]
    opt = (tf.keras.optimizers.deserialize(opt) if isinstance(opt, dict)
           else tf.keras.optimizers.get(opt))
    model.compile(optimizer=hvd.DistributedOptimizer(opt),
                  loss=spec["loss"], metrics=list(spec["metrics"]))
    callbacks = [hvd.BroadcastGlobalVariablesCallback(0),
                 hvd.MetricAverageCallback()]
    hist = model.fit(X, Y, batch_size=spec["batch_size"],
                     epochs=spec["epochs"], shuffle=spec["shuffle"],
                     verbose=spec["verbose"], callbacks=callbacks)

    # Validation scores averaged across ranks (each rank holds one shard).
    val = None
    Xv, Yv = load_shard(spec["val_path"], r, store)
    if len(Xv):
        scores = model.evaluate(Xv, Yv, batch_size=spec["batch_size"],
                                verbose=0)
        scores = np.atleast_1d(np.asarray(scores, np.float64))
        val = [float(hvd.metric_average(s, f"est_val_{i}"))
               for i, s in enumerate(scores)]

    weights = model.get_weights()
    if r == 0:
        with open_artifact(store, os.path.join(spec["ckpt_path"],
                                               "model_weights.npz")) as f:
            np.savez(f, *weights)
    hvd.shutdown()
    return {
        "history": {k: [float(x) for x in v]
                    for k, v in hist.history.items()},
        "val": val,
        "weights": weights if r == 0 else None,
    }


class KerasEstimator(EstimatorParams):
    """Data-parallel Keras estimator (reference: KerasEstimator).

    Usage::

        est = KerasEstimator(model=m, optimizer="adam", loss="mse",
                             feature_cols=["x0", "x1"], label_cols=["y"],
                             batch_size=16, epochs=10, num_proc=2,
                             store=LocalStore("/tmp/store"))
        keras_model = est.fit(df)           # pandas or pyspark DataFrame
        out = keras_model.transform(df)     # adds "y__output"
    """

    def __init__(self, optimizer="adam", metrics=(), custom_objects=None,
                 **kwargs):
        super().__init__(**kwargs)
        self.optimizer = optimizer
        self.metrics = list(metrics)
        self.custom_objects = dict(custom_objects or {})

    def fit(self, df):
        import tensorflow as tf

        self._check_params()
        store, run_id = self._prepare_store()
        train_path, val_path, _ = self._materialize(df, run_id)
        ckpt_path = store.get_checkpoint_path(run_id)

        opt = self.optimizer
        if not isinstance(opt, str):
            opt = tf.keras.optimizers.serialize(opt)
        spec = {
            "model_json": self.model.to_json(),
            "weights": self.model.get_weights(),
            "optimizer": opt,
            "loss": self.loss,
            "metrics": self.metrics,
            "custom_objects": self.custom_objects,
            "batch_size": self.batch_size,
            "epochs": self.epochs,
            "shuffle": self.shuffle,
            "seed": self.seed,
            "verbose": self.verbose,
            "train_path": train_path,
            "val_path": val_path,
            "ckpt_path": ckpt_path,
            "store": store,
        }
        results = self._run(_train_fn, spec)
        rank0 = results[0]
        return KerasModel(
            model_json=spec["model_json"], weights=rank0["weights"],
            custom_objects=self.custom_objects,
            feature_cols=self.feature_cols, label_cols=self.label_cols,
            history=rank0["history"], val_scores=rank0["val"],
            checkpoint_path=ckpt_path)


class KerasModel(HorovodModel):
    """Fitted model: a lightweight transformer over the trained weights
    (reference: KerasModel Spark Transformer)."""

    def __init__(self, model_json, weights, custom_objects, feature_cols,
                 label_cols, history=None, val_scores=None,
                 checkpoint_path=None, output_cols=None):
        super().__init__(feature_cols, label_cols, output_cols)
        self.model_json = model_json
        self.weights = weights
        self.custom_objects = dict(custom_objects or {})
        self.history = history or {}
        self.val_scores = val_scores
        self.checkpoint_path = checkpoint_path
        self._model = None

    @property
    def keras_model(self):
        """The trained tf.keras model (built lazily)."""
        if self._model is None:
            import tensorflow as tf

            self._model = tf.keras.models.model_from_json(
                self.model_json, custom_objects=self.custom_objects or None)
            self._model.set_weights(self.weights)
        return self._model

    def _predict(self, X):
        return self.keras_model.predict(X, verbose=0)

    @classmethod
    def load(cls, model_json, checkpoint_path, feature_cols, label_cols,
             custom_objects=None, output_cols=None, store=None):
        """Rebuild a fitted model from a store checkpoint written by fit.
        Pass the ``store`` for checkpoints living behind a remote
        filesystem adapter."""
        import io

        with open_artifact(store, os.path.join(checkpoint_path,
                                               "model_weights.npz"),
                           "rb") as f:
            with np.load(io.BytesIO(f.read())) as z:
                weights = [z[k] for k in z.files]
        return cls(model_json, weights, custom_objects, feature_cols,
                   label_cols, checkpoint_path=checkpoint_path,
                   output_cols=output_cols)
