"""TorchEstimator — estimator-style data-parallel PyTorch training
(reference: ``horovod/spark/torch/estimator.py`` ``TorchEstimator`` /
``TorchModel``).

Same shape as :mod:`horovod_tpu.spark.keras`: ``fit(df)`` materializes the
DataFrame to the store, launches ``num_proc`` ranks through the backend,
trains with the torch binding (``broadcast_parameters`` +
``DistributedOptimizer`` gradient hooks), rank 0 checkpoints the
state_dict to the store, and a :class:`TorchModel` transformer comes back.
"""
import os

import cloudpickle
import numpy as np

from .params import (EstimatorParams, HorovodModel, load_shard,
                     open_artifact)


def _train_fn(spec):
    """Per-rank training body (fresh process, slot env already set)."""
    import torch

    import horovod_tpu.torch as hvd

    hvd.init()
    r = hvd.rank()
    torch.manual_seed(spec["seed"] + r)

    model = cloudpickle.loads(spec["model"])
    loss_fn = cloudpickle.loads(spec["loss"])
    opt_class, opt_defaults = cloudpickle.loads(spec["optimizer"])
    optimizer = opt_class(model.parameters(), **opt_defaults)

    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    store = spec.get("store")
    X, Y = load_shard(spec["train_path"], r, store)
    X, Y = torch.from_numpy(X), torch.from_numpy(Y)
    bs, n = spec["batch_size"], len(X)

    history = []
    for epoch in range(spec["epochs"]):
        order = torch.randperm(n) if spec["shuffle"] else torch.arange(n)
        total, seen = 0.0, 0
        model.train()
        for i in range(0, n, bs):
            idx = order[i:i + bs]
            optimizer.zero_grad()
            loss = loss_fn(model(X[idx]), Y[idx])
            loss.backward()
            optimizer.step()
            total += float(loss) * len(idx)
            seen += len(idx)
        history.append(hvd.metric_average(total / max(seen, 1),
                                          f"est_loss_{epoch}"))

    val = None
    Xv, Yv = load_shard(spec["val_path"], r, store)
    if len(Xv):
        model.eval()
        with torch.no_grad():
            vloss = float(loss_fn(model(torch.from_numpy(Xv)),
                                  torch.from_numpy(Yv)))
        val = hvd.metric_average(vloss, "est_val_loss")

    state = {k: v.cpu() for k, v in model.state_dict().items()}
    if r == 0:
        with open_artifact(store, os.path.join(spec["ckpt_path"],
                                               "model.pt")) as f:
            torch.save(state, f)
    hvd.shutdown()
    return {"loss_history": history, "val_loss": val,
            "state_dict": state if r == 0 else None}


class TorchEstimator(EstimatorParams):
    """Data-parallel PyTorch estimator (reference: TorchEstimator).

    ``optimizer`` is a torch optimizer instance bound to ``model`` (its
    class + defaults are rebuilt per rank, reference semantics) or a
    callable ``params -> optimizer``. ``loss`` is a callable, e.g.
    ``torch.nn.MSELoss()``.
    """

    def __init__(self, optimizer=None, **kwargs):
        super().__init__(**kwargs)
        self.optimizer = optimizer

    def _serialize_optimizer(self):
        import torch

        opt = self.optimizer
        if opt is None:
            return cloudpickle.dumps((torch.optim.SGD, {"lr": 0.01}))
        if isinstance(opt, torch.optim.Optimizer):
            return cloudpickle.dumps((type(opt), dict(opt.defaults)))
        if callable(opt):
            # Factory: wrap so the worker sees the same (class, kwargs)
            # calling convention.
            return cloudpickle.dumps((opt, {}))
        raise TypeError(f"optimizer must be a torch optimizer instance or "
                        f"a params->optimizer callable, got {type(opt)}")

    def _check_params(self):
        super()._check_params()
        if not callable(self.loss):
            raise ValueError("loss must be a callable (e.g. nn.MSELoss())")

    def fit(self, df):
        self._check_params()
        store, run_id = self._prepare_store()
        train_path, val_path, _ = self._materialize(df, run_id)
        ckpt_path = store.get_checkpoint_path(run_id)

        spec = {
            "model": cloudpickle.dumps(self.model),
            "optimizer": self._serialize_optimizer(),
            "loss": cloudpickle.dumps(self.loss),
            "batch_size": self.batch_size,
            "epochs": self.epochs,
            "shuffle": self.shuffle,
            "seed": self.seed,
            "train_path": train_path,
            "val_path": val_path,
            "ckpt_path": ckpt_path,
            "store": store,
        }
        results = self._run(_train_fn, spec)
        rank0 = results[0]
        model = cloudpickle.loads(spec["model"])
        model.load_state_dict(rank0["state_dict"])
        return TorchModel(
            model=model, feature_cols=self.feature_cols,
            label_cols=self.label_cols, history=rank0["loss_history"],
            val_loss=rank0["val_loss"], checkpoint_path=ckpt_path)


class TorchModel(HorovodModel):
    """Fitted model over the trained module (reference: TorchModel)."""

    def __init__(self, model, feature_cols, label_cols, history=None,
                 val_loss=None, checkpoint_path=None, output_cols=None):
        super().__init__(feature_cols, label_cols, output_cols)
        self.model = model
        self.history = history or []
        self.val_loss = val_loss
        self.checkpoint_path = checkpoint_path

    def _predict(self, X):
        import torch

        self.model.eval()
        with torch.no_grad():
            # copy: df-backed arrays can be read-only views, which torch
            # rejects for zero-copy tensor construction.
            x = torch.from_numpy(np.array(X, dtype=np.float32, copy=True))
            return self.model(x).numpy()

    @classmethod
    def load(cls, model, checkpoint_path, feature_cols, label_cols,
             output_cols=None, store=None):
        """Rebuild a fitted model from a store checkpoint written by fit:
        ``model`` is an architecture instance to load the state_dict into.
        Pass the ``store`` for checkpoints living behind a remote
        filesystem adapter."""
        import io

        import torch

        with open_artifact(store, os.path.join(checkpoint_path,
                                               "model.pt"), "rb") as f:
            # Buffer: torch.load needs a seekable file, and the adapter
            # contract doesn't promise one (streaming object stores).
            state = torch.load(io.BytesIO(f.read()), weights_only=True)
        model.load_state_dict(state)
        return cls(model, feature_cols, label_cols,
                   checkpoint_path=checkpoint_path, output_cols=output_cols)
