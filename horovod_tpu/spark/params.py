"""Shared estimator machinery (reference: ``horovod/spark/common/params.py``
``EstimatorParams`` and ``horovod/spark/common/backend.py`` ``SparkBackend``).

The reference materializes the DataFrame to parquet with petastorm and
launches ranks inside Spark executors. Neither petastorm nor pyspark is
assumed here: DataFrames are pandas (a pyspark DataFrame is accepted and
converted via ``toPandas()`` when pyspark is present), shards are written to
the :class:`~horovod_tpu.spark.store.Store` as ``.npz`` files, and training
runs through a :class:`Backend` — by default N negotiated local ranks (the
same launch path ``tpurun`` and :class:`~horovod_tpu.ray.RayExecutor` use),
or barrier Spark tasks via :func:`horovod_tpu.spark.run` when pyspark is
available.
"""
import os
import tempfile
import time
import uuid

import numpy as np

from .store import LocalStore, Store


class Backend:
    """Where estimator ranks run (reference: common/backend.py)."""

    def run(self, fn, args, num_proc, env, timeout):
        raise NotImplementedError


class LocalBackend(Backend):
    """N local processes with negotiated slot env (the default here; the
    reference's default SparkBackend needs a live SparkContext)."""

    def run(self, fn, args, num_proc, env, timeout):
        from ..ray.runner import RayExecutor

        ex = RayExecutor(num_proc, backend="local", env=env,
                         timeout=timeout).start()
        try:
            return ex.run(fn, args=args)
        finally:
            ex.shutdown()


class SparkBackend(Backend):
    """Ranks as barrier Spark tasks (reference: SparkBackend → horovod.spark
    gloo/mpi run). Requires pyspark."""

    def run(self, fn, args, num_proc, env, timeout):
        from . import run as spark_run

        return spark_run(fn, args=args, num_proc=num_proc, extra_env=env,
                         timeout=timeout)


class EstimatorParams:
    """Common estimator parameters (reference: EstimatorParams — model,
    loss, feature/label cols, batch size, epochs, validation, store,
    backend, num_proc, shuffle, verbose)."""

    def __init__(self, model=None, loss=None, feature_cols=None,
                 label_cols=None, batch_size=32, epochs=1, validation=None,
                 num_proc=2, backend=None, store=None, run_id=None,
                 shuffle=True, verbose=0, seed=0, timeout=600.0):
        self.model = model
        self.loss = loss
        self.feature_cols = list(feature_cols or [])
        self.label_cols = list(label_cols or [])
        self.batch_size = int(batch_size)
        self.epochs = int(epochs)
        self.validation = validation
        self.num_proc = int(num_proc)
        self.backend = backend or LocalBackend()
        self.store = store
        self.run_id = run_id
        self.shuffle = bool(shuffle)
        self.verbose = int(verbose)
        self.seed = int(seed)
        self.timeout = float(timeout)

    # -- shared fit plumbing ----------------------------------------------

    def _check_params(self):
        if self.model is None:
            raise ValueError("model is required")
        if self.loss is None:
            raise ValueError("loss is required")
        if not self.feature_cols or not self.label_cols:
            raise ValueError("feature_cols and label_cols are required")
        if self.num_proc < 1:
            raise ValueError("num_proc must be >= 1")

    def _prepare_store(self):
        """Returns ``(store, run_id)``. A fresh run_id is minted per fit()
        when the user didn't pin one — otherwise a second fit() on the same
        estimator would overwrite the first run's shards and checkpoint."""
        if self.store is None:
            self.store = LocalStore(
                tempfile.mkdtemp(prefix="hvd-estimator-"))
        elif not isinstance(self.store, Store):
            self.store = Store.create(self.store)
        if getattr(getattr(self.store, "fs", None), "process_local",
                   False):
            raise ValueError(
                "this store's filesystem is process-local (e.g. "
                "InMemoryFilesystem): rank subprocesses would checkpoint "
                "into pickled copies that are thrown away — use a store "
                "whose filesystem is shared across processes")
        # uuid suffix: wall-clock alone collides when two fits share a
        # store in the same millisecond, silently cross-contaminating
        # shards and checkpoints.
        run_id = self.run_id or (f"run-{int(time.time() * 1000)}-"
                                 f"{uuid.uuid4().hex[:8]}")
        return self.store, run_id

    def _materialize(self, df, run_id):
        """Split ``df`` into train/val and write one ``.npz`` shard per rank
        under the store's intermediate data paths (reference: petastorm
        parquet materialization in common/util.py ``prepare_data``).

        Every rank gets exactly the same number of rows (the remainder is
        dropped, train and val): unequal shards would give ranks different
        per-epoch step counts and deadlock the per-batch gradient allreduce,
        and a val set reaching only some ranks would strand the others out
        of the validation metric_average. Equal shards also mean val is
        empty on ALL ranks or none, so workers can gate on their own shard.

        Returns ``(train_path, val_path, n_val_rows_per_rank)``.
        """
        df = _as_pandas(df)
        missing = [c for c in self.feature_cols + self.label_cols
                   if c not in df.columns]
        if missing:
            raise ValueError(f"columns not in DataFrame: {missing}")

        X = df[self.feature_cols].to_numpy(dtype=np.float32)
        Y = df[self.label_cols].to_numpy(dtype=np.float32)
        n = len(df)
        rng = np.random.default_rng(self.seed)
        order = rng.permutation(n) if self.shuffle else np.arange(n)

        # validation: a fraction (random tail of the shuffled order) or a
        # boolean column naming the validation rows (reference semantics).
        if isinstance(self.validation, str):
            mask = df[self.validation].to_numpy().astype(bool)
            val_idx = order[mask[order]]
            train_idx = order[~mask[order]]
        elif self.validation:
            n_val = int(n * float(self.validation))
            val_idx, train_idx = order[:n_val], order[n_val:]
        else:
            val_idx, train_idx = order[:0], order

        if len(train_idx) < self.num_proc:
            raise ValueError(
                f"{len(train_idx)} training rows cannot feed "
                f"{self.num_proc} ranks")
        per_rank = len(train_idx) // self.num_proc
        train_idx = train_idx[:per_rank * self.num_proc]
        val_per_rank = len(val_idx) // self.num_proc
        val_idx = val_idx[:val_per_rank * self.num_proc]

        train_path = self.store.get_train_data_path(run_id)
        val_path = self.store.get_val_data_path(run_id)
        for r in range(self.num_proc):
            tr = train_idx[r::self.num_proc]
            va = val_idx[r::self.num_proc]
            # All shard IO rides the store's filesystem adapter, so
            # remote stores (store.py FilesystemStore) work unchanged.
            with self.store.open_write(
                    os.path.join(train_path, f"shard-{r}.npz")) as f:
                np.savez(f, X=X[tr], Y=Y[tr])
            with self.store.open_write(
                    os.path.join(val_path, f"shard-{r}.npz")) as f:
                np.savez(f, X=X[va], Y=Y[va])
        return train_path, val_path, val_per_rank

    def _run(self, fn, spec):
        """Launch the per-rank training fn through the backend."""
        env = {"JAX_PLATFORMS": "cpu"}  # estimator workers never need a TPU
        return self.backend.run(fn, (spec,), self.num_proc, env,
                                self.timeout)


def _as_pandas(df):
    import pandas as pd

    if isinstance(df, pd.DataFrame):
        return df
    # pyspark DataFrame (or anything else exposing toPandas()).
    if hasattr(df, "toPandas"):
        return df.toPandas()
    raise TypeError(f"expected a pandas (or pyspark) DataFrame, got "
                    f"{type(df).__name__}")


def open_artifact(store, path, mode="wb"):
    """Checkpoint/artifact IO through the store's filesystem adapter —
    the ONE place the store-vs-bare-IO choice lives (estimator specs
    always carry the store; the bare branch serves direct _train_fn use
    outside an estimator)."""
    if store is not None:
        return store.open_write(path) if "w" in mode \
            else store.open_read(path)
    return open(path, mode)


def load_shard(path, rank, store=None):
    """Read rank's materialized shard → (X, Y) float32 arrays, through
    :func:`open_artifact` (store adapter when present, local IO
    otherwise)."""
    import io

    name = os.path.join(path, f"shard-{rank}.npz")
    with open_artifact(store, name, "rb") as f:
        with np.load(io.BytesIO(f.read())) as z:
            return z["X"], z["Y"]


class HorovodModel:
    """Base for fitted models (reference: common/estimator.py
    ``HorovodModel`` — a Spark Transformer; here ``transform`` appends
    prediction columns to a pandas DataFrame)."""

    def __init__(self, feature_cols, label_cols, output_cols=None):
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.output_cols = list(
            output_cols or [f"{c}__output" for c in self.label_cols])

    def _predict(self, X):
        raise NotImplementedError

    def transform(self, df):
        df = _as_pandas(df).copy()
        X = df[self.feature_cols].to_numpy(dtype=np.float32)
        pred = np.asarray(self._predict(X))
        if pred.ndim == 1:
            pred = pred[:, None]
        if pred.shape[1] != len(self.output_cols):
            raise ValueError(
                f"model produced {pred.shape[1]} outputs for "
                f"{len(self.output_cols)} output_cols")
        for j, c in enumerate(self.output_cols):
            df[c] = pred[:, j]
        return df
