"""horovod_tpu — a TPU-native distributed training framework with the
capability surface of Horovod (reference: DEKHTIARJonathan/horovod, a fork of
horovod/horovod).

Architecture (see SURVEY.md at the repo root):

- A **C++ core** (``csrc/`` → ``lib/libhvd_tpu.so``) runs one background
  thread per process that negotiates tensor readiness across ranks over a TCP
  control plane, fuses small tensors, and executes collectives — the
  reference's ``operations.cc``/``controller.cc`` design, rebuilt without
  MPI/Gloo/NCCL.
- The **host data plane** is a ring/pairwise TCP backend (reference analog:
  ``mpi_operations.cc``/``gloo_operations.cc``) used for correctness tests,
  CPU tensors, and DCN-crossing traffic.
- The **TPU data plane** is XLA collectives over ICI: inside ``jit``,
  gradients are averaged with ``psum``/``reduce_scatter`` on a
  ``jax.sharding.Mesh`` (``horovod_tpu.ops.jax_ops``,
  ``horovod_tpu.parallel``) — zero host round-trips, fused by XLA.

Public API mirrors the reference: ``init/rank/size/...``, the five
collectives (+ grouped, async, process-set variants), ``DistributedOptimizer``
wrappers per framework, elastic state/run, timeline, and a ``tpurun``
launcher.
"""

__version__ = "0.1.0"

from .basics import basics as _basics
from .exceptions import (  # noqa: F401
    CheckpointError,
    HorovodInternalError,
    HostsUpdatedInterrupt,
    RankEvictedError,
)
from .compression import Compression  # noqa: F401
from .ops.collective_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    allgather_object,
    broadcast_object,
    grouped_allgather,
    grouped_allgather_async,
    grouped_allreduce,
    grouped_allreduce_async,
    grouped_reducescatter,
    grouped_reducescatter_async,
    join,
    poll,
    reducescatter,
    reducescatter_async,
    synchronize,
)
from .process_sets import (  # noqa: F401
    ProcessSet,
    add_process_set,
    global_process_set,
    remove_process_set,
)

def _maybe_init_jax_mesh():
    """Join the job-wide jax.distributed mesh when the launcher provisioned
    one — static jobs (rank 0 hosts the coordination service) AND elastic
    jobs (the driver hosts a per-epoch service; workers join as recoverable
    clients — see horovod_tpu/jax/distributed.py). Gated so non-JAX users
    (torch/TF workers) never pay a jax import."""
    import os as _os
    import sys as _sys

    # Gate BEFORE importing .jax: the subpackage __init__ imports jax and
    # optax at module level, which a torch/TF worker must never pay (and
    # may not even have installed).
    gate = _os.environ.get("HVD_JAX_DISTRIBUTED")
    if gate == "0" or not _os.environ.get("HVD_JAX_COORD_ADDR"):
        return
    if "jax" not in _sys.modules and gate != "1":
        return
    from .jax import distributed as _jd

    _jd.maybe_initialize_from_env()


def init():
    """Initialize the core. Under an elastic job (HVD_ELASTIC=1, spawned by
    `tpurun --min-np/...`) this first rendezvouses with the driver's KV
    store for the current epoch's rank/size/controller assignment. When the
    launcher provisioned a jax.distributed coordinator (static multi-process
    jobs), all processes also join ONE global device mesh so in-jit
    collectives cross process boundaries over ICI."""
    import os as _os

    observability.maybe_start_endpoint()
    if _os.environ.get("HVD_ELASTIC") == "1":
        from .runner.elastic import worker as _worker

        rc = _worker.rendezvous_init()
        _maybe_init_jax_mesh()
        return rc
    from .runner import network as _network

    if _network.NEGOTIATE in (_os.environ.get("HVD_CONTROLLER_ADDR", ""),
                              _os.environ.get("HVD_JAX_COORD_ADDR", "")):
        # Multi-host static launch: rank 0 registers real ports probed on
        # ITS host; everyone else reads them (runner/network.py — the
        # driver/task-service analog).
        _network.negotiate_endpoints_from_env()
    rc = _basics.init()
    _maybe_init_jax_mesh()
    return rc


def shutdown():
    import sys as _sys

    if "horovod_tpu.jax.distributed" in _sys.modules:
        from .jax import distributed as _jd

        _jd.shutdown()
    return _basics.shutdown()
is_initialized = _basics.is_initialized
rank = _basics.rank
size = _basics.size
local_rank = _basics.local_rank
local_size = _basics.local_size
cross_rank = _basics.cross_rank
cross_size = _basics.cross_size
mpi_threads_supported = _basics.mpi_threads_supported
nccl_built = _basics.nccl_built
start_timeline = _basics.start_timeline
stop_timeline = _basics.stop_timeline
cache_stats = _basics.cache_stats
autotune_state = _basics.autotune_state
autotune_stats = _basics.autotune_stats
zerocopy_stats = _basics.zerocopy_stats
zerocopy_state = _basics.zerocopy_state
reduce_stats = _basics.reduce_stats
reduce_bench = _basics.reduce_bench
pipeline_stats = _basics.pipeline_stats
pipeline_state = _basics.pipeline_state
shm_stats = _basics.shm_stats
shm_state = _basics.shm_state
bucket_stats = _basics.bucket_stats
bucket_state = _basics.bucket_state
compress_stats = _basics.compress_stats
compress_state = _basics.compress_state
set_compression = _basics.set_compression
wire_stats = _basics.wire_stats
wire_state = _basics.wire_state
alltoall_stats = _basics.alltoall_stats
alltoall_state = _basics.alltoall_state
ep_report = _basics.ep_report
ep_stats = _basics.ep_stats
reduce_pool_stats = _basics.reduce_pool_stats
hier_stats = _basics.hier_stats
elastic_stats = _basics.elastic_stats
elastic_state = _basics.elastic_state
fault_trigger = _basics.fault_trigger
lockdep_stats = _basics.lockdep_stats
lockdep_report = _basics.lockdep_report
lockdep_selftest = _basics.lockdep_selftest
peer_tx_bytes = _basics.peer_tx_bytes
op_backends = _basics.op_backends
backend_uses = _basics.backend_uses


def checkpoint_stats():
    """This process's state-plane counters (horovod_tpu/checkpoint.py):
    saves / commits / aborted_commits prove the crash-safe commit
    protocol's accounting, ``bytes``/``bytes_read``/``fragments_fetched``
    quantify the sharded write and reshard-on-read paths, and
    ``snapshot_stall_ms`` vs ``write_ms`` is the async overlap the
    ``bench.py ckpt`` A/B measures. See docs/checkpoint.md."""
    from . import checkpoint as _checkpoint

    return _checkpoint.checkpoint_stats()


def serve_stats():
    """The latest serve-loop boundary snapshot
    (horovod_tpu/serving/loop.py): queue depth / batch fill / KV
    occupancy gauges plus the serving-v2 counters — prefix-cache hit
    ratio, evictions and live radix-tree size, speculative
    accepted-tokens-per-step and rejections, and the batched/chunked
    prefill path counts. Empty until a ServeLoop has run a boundary;
    kill switches (HVD_SERVE_PREFIX_CACHE=0, spec_tokens=0) show as
    zero activity here. See docs/serving.md."""
    from .serving import loop as _serve_loop

    return _serve_loop.serve_stats()


def compression_stats():
    """One merged view of every compression surface: the core wire codecs
    (int8 error-feedback ring / top-k allgather — compress_stats()) plus
    the binding-level wire-cast counters (compression.record_wire_cast).
    ``engagements`` totals every compressed op either layer performed and
    ``bytes_saved`` / ``compression_ratio`` quantify the wire reduction;
    all zeros proves the kill switch (compression off) left every byte
    uncompressed."""
    from . import compression as _compression

    core = compress_stats()
    casts = _compression.stats()
    raw, wire = core["raw_bytes"], core["wire_bytes"]
    return {
        "int8_ops": core["int8_ops"],
        "topk_ops": core["topk_ops"],
        "raw_bytes": raw,
        "wire_bytes": wire,
        "bytes_saved": raw - wire,
        "compression_ratio": (raw / wire) if wire > 0 else 0.0,
        "residual_norm": core["residual_norm"],
        "residual_buckets": core["residual_buckets"],
        "wire_cast_engaged": casts["engaged"],
        "wire_cast_fallback": casts["fallback"],
        "engagements": core["int8_ops"] + core["topk_ops"] + casts["engaged"],
    }


def mpi_built():
    return False


def gloo_built():
    return False


def tpu_built():
    """True when a TPU backend is available to JAX in this process."""
    try:
        import jax

        return any(d.platform.startswith(("tpu", "axon")) for d in jax.devices())
    except Exception:
        return False


from .ops import zerocopy as bridge  # noqa: E402  (hvd.bridge.stats / as_buffer)
from . import elastic  # noqa: F401,E402  (hvd.elastic.run / State / ObjectState)
from . import profiler  # noqa: F401,E402  (xplane trace windows + op ranges)
from . import observability  # noqa: F401,E402  (metrics / stall / spans)
