"""Shared Keras support (reference: horovod/_keras/__init__.py)."""
