"""Shared Keras support (reference: horovod/_keras/__init__.py)."""


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=None, **dist_kwargs):
    """Load a Keras model saved with ``model.save()``, with its optimizer
    deserialized straight into a ``DistributedOptimizer`` (reference:
    horovod/_keras ``load_model`` — the wrap happens inside
    ``from_config`` via ``custom_objects``, so optimizer slot state and
    hyperparameters survive the round trip; recompiling after load would
    lose them).

    ``custom_optimizers``: extra optimizer classes to wrap (the standard
    tf.keras optimizers are covered); ``custom_objects``: passed through
    to ``tf.keras.models.load_model``; ``compression`` and
    ``dist_kwargs`` forward to ``DistributedOptimizer``.
    """
    import tensorflow as tf

    from ..tensorflow import DistributedOptimizer

    def wrap_cls(opt_cls):
        class _Wrapped(opt_cls):
            @classmethod
            def from_config(cls, config, **kw):
                opt = opt_cls.from_config(config, **kw)
                return DistributedOptimizer(opt, compression=compression,
                                            **dist_kwargs)

        _Wrapped.__name__ = opt_cls.__name__
        return _Wrapped

    std = [tf.keras.optimizers.SGD, tf.keras.optimizers.Adam,
           tf.keras.optimizers.AdamW, tf.keras.optimizers.RMSprop,
           tf.keras.optimizers.Adagrad, tf.keras.optimizers.Adadelta,
           tf.keras.optimizers.Adamax, tf.keras.optimizers.Nadam,
           tf.keras.optimizers.Ftrl]
    objs = {cls.__name__: wrap_cls(cls)
            for cls in std + list(custom_optimizers or [])}
    if custom_objects:
        objs.update(custom_objects)
    return tf.keras.models.load_model(filepath, custom_objects=objs)
