"""Keras callbacks (reference: horovod/_keras/callbacks.py):
BroadcastGlobalVariablesCallback, MetricAverageCallback,
LearningRateWarmupCallback, LearningRateScheduleCallback, and the elastic
Commit/UpdateBatch/UpdateEpoch state callbacks."""

import numpy as np


def _keras():
    import tensorflow as tf

    return tf.keras


class BroadcastGlobalVariablesCallback:
    """Broadcast all model/optimizer variables from root at train start
    so every rank begins identical."""

    def __new__(cls, root_rank=0):
        keras = _keras()

        from .. import tensorflow as hvd_tf

        class _CB(keras.callbacks.Callback):
            def __init__(self):
                super().__init__()
                self._done = False

            def on_train_begin(self, logs=None):
                if self._done:
                    return
                hvd_tf.broadcast_variables(self.model.variables,
                                           root_rank=root_rank)
                self._done = True

        return _CB()


class MetricAverageCallback:
    """Average epoch metrics over ranks at epoch end (reference:
    MetricAverageCallback)."""

    def __new__(cls):
        keras = _keras()

        from .. import tensorflow as hvd_tf

        class _CB(keras.callbacks.Callback):
            def on_epoch_end(self, epoch, logs=None):
                if logs:
                    for k, v in list(logs.items()):
                        try:
                            logs[k] = hvd_tf.metric_average(
                                float(v), name=f"metric.{k}")
                        except (TypeError, ValueError):
                            pass

        return _CB()


class LearningRateWarmupCallback:
    """Linear LR warmup over the first `warmup_epochs` from lr/size to lr
    (reference: LearningRateWarmupCallback; scaling rule from the
    Facebook 1-hour-ImageNet recipe the reference cites).

    `momentum_correction=True` rescales SGD momentum accumulators by
    new_lr/old_lr on every LR change (the reference's behavior), keeping
    the effective update magnitude continuous through warmup. Optimizer
    momentum variables are located by name; optimizers without any are
    unaffected."""

    def __new__(cls, initial_lr, warmup_epochs=5, momentum_correction=True,
                steps_per_epoch=None, verbose=0):
        keras = _keras()

        from .. import tensorflow as hvd_tf

        class _CB(keras.callbacks.Callback):
            def __init__(self):
                super().__init__()
                self.steps = 0

            def _set_lr(self, lr):
                opt = self.model.optimizer
                old = float(opt.learning_rate.numpy()) \
                    if hasattr(opt.learning_rate, "numpy") \
                    else float(opt.learning_rate)
                try:
                    opt.learning_rate.assign(lr)
                except AttributeError:
                    opt.learning_rate = lr
                if momentum_correction and old > 0 and lr != old:
                    for v in getattr(opt, "variables", []):
                        path = getattr(v, "path", getattr(v, "name", ""))
                        if "momentum" in path:
                            v.assign(v * (lr / old))
                if verbose:
                    print(f"LearningRateWarmup: lr={lr:g}")

            def on_train_batch_begin(self, batch, logs=None):
                if steps_per_epoch is None:
                    return
                total = warmup_epochs * steps_per_epoch
                if self.steps < total:
                    frac = (self.steps + 1) / total
                    size = hvd_tf.size()
                    lr = initial_lr * (1.0 / size + frac * (1 - 1.0 / size))
                    self._set_lr(lr)
                self.steps += 1

            def on_epoch_begin(self, epoch, logs=None):
                if steps_per_epoch is not None:
                    return
                if epoch < warmup_epochs:
                    size = hvd_tf.size()
                    frac = (epoch + 1) / warmup_epochs
                    self._set_lr(initial_lr *
                                 (1.0 / size + frac * (1 - 1.0 / size)))
                elif epoch == warmup_epochs:
                    self._set_lr(initial_lr)

        return _CB()


class LearningRateScheduleCallback:
    """Multiply LR by `multiplier` within [start_epoch, end_epoch)
    (reference: LearningRateScheduleCallback)."""

    def __new__(cls, initial_lr, multiplier, start_epoch=0, end_epoch=None,
                staircase=True):
        keras = _keras()

        class _CB(keras.callbacks.Callback):
            def on_epoch_begin(self, epoch, logs=None):
                if epoch < start_epoch:
                    return
                if end_epoch is not None and epoch >= end_epoch:
                    return
                m = multiplier(epoch) if callable(multiplier) else multiplier
                lr = initial_lr * m
                opt = self.model.optimizer
                try:
                    opt.learning_rate.assign(lr)
                except AttributeError:
                    opt.learning_rate = lr

        return _CB()


# -- elastic callbacks (reference: CommitStateCallback etc.) ----------------

class CommitStateCallback:
    """state.commit() every `batches_per_commit` batches."""

    def __new__(cls, state, batches_per_commit=1):
        keras = _keras()

        class _CB(keras.callbacks.Callback):
            def on_train_batch_end(self, batch, logs=None):
                if (batch + 1) % batches_per_commit == 0:
                    state.commit()

        return _CB()


class UpdateBatchStateCallback:
    def __new__(cls, state):
        keras = _keras()

        class _CB(keras.callbacks.Callback):
            def on_train_batch_end(self, batch, logs=None):
                state.batch = batch

        return _CB()


class UpdateEpochStateCallback:
    def __new__(cls, state):
        keras = _keras()

        class _CB(keras.callbacks.Callback):
            def on_epoch_end(self, epoch, logs=None):
                state.epoch = epoch

        return _CB()
