"""horovod_tpu.mxnet — the MXNet framework binding.

Reference parity: ``horovod/mxnet/__init__.py`` + ``mpi_ops.py`` (+ the
``mpi_ops.cc``/``adapter.cc`` C++ extension) — ``DistributedOptimizer``
wrapping an ``mx.optimizer.Optimizer`` so gradients are allreduced before
each update, ``DistributedTrainer`` doing the same for Gluon, and
``broadcast_parameters`` for both ``arg_params`` dicts and Gluon
``ParameterDict``s. The reference needs a C++ extension because its
NDArrays live on CUDA streams; here (as with the torch binding) MXNet is a
host-memory frontend to the same native core, bridged via numpy views.

Real MXNet is NOT installed in this build's environment (upstream is
archived; see README descope note); the binding's full surface executes
end-to-end in CI against the numpy-backed conformance shim in
``tests/shims/mxnet`` (``tests/workers/mxnet_worker.py``).
"""

try:
    import mxnet as mx
    from mxnet import ndarray as nd
except ImportError as e:  # pragma: no cover - exercised via tests
    raise ImportError(
        "horovod_tpu.mxnet requires the 'mxnet' package, which is not "
        "installed in this environment (see the README descope note). "
        "The JAX, TensorFlow, Keras and Torch bindings are available."
    ) from e

import numpy as np

from ..basics import basics as _basics
from ..compression import Compression  # noqa: F401
from ..exceptions import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from ..ops import collective_ops as _core
from ..ops.collective_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    barrier,
    join,
)
from ..process_sets import (  # noqa: F401
    ProcessSet,
    add_process_set,
    global_process_set,
    remove_process_set,
)


def init():
    import horovod_tpu as _pkg

    return _pkg.init()


shutdown = _basics.shutdown
is_initialized = _basics.is_initialized
rank = _basics.rank
size = _basics.size
local_rank = _basics.local_rank
local_size = _basics.local_size
cross_rank = _basics.cross_rank
cross_size = _basics.cross_size


def _to_numpy(t):
    return t.asnumpy() if isinstance(t, nd.NDArray) else np.asarray(t)


def _like(out_np, t):
    ctx = t.context if isinstance(t, nd.NDArray) else None
    a = nd.array(out_np, ctx=ctx, dtype=out_np.dtype)
    return a


# -- collectives (reference: horovod/mxnet/mpi_ops.py) ----------------------

def allreduce(tensor, op=Average, name=None, prescale_factor=1.0,
              postscale_factor=1.0, process_set=0):
    out = _core.allreduce(_to_numpy(tensor), op=op, name=name,
                          prescale_factor=prescale_factor,
                          postscale_factor=postscale_factor,
                          process_set=process_set)
    return _like(out, tensor)


def allreduce_(tensor, op=Average, name=None, process_set=0):
    """In-place variant (reference: hvd.allreduce_)."""
    out = _core.allreduce(_to_numpy(tensor), op=op, name=name,
                          process_set=process_set)
    tensor[:] = _like(out, tensor)
    return tensor


def grouped_allreduce(tensors, op=Average, name=None, process_set=0):
    outs = _core.grouped_allreduce([_to_numpy(t) for t in tensors], op=op,
                                   name=name, process_set=process_set)
    return [_like(o, t) for o, t in zip(outs, tensors)]


def allgather(tensor, name=None, process_set=0):
    out = _core.allgather(_to_numpy(tensor), name=name,
                          process_set=process_set)
    return _like(out, tensor)


def broadcast(tensor, root_rank=0, name=None, process_set=0):
    out = _core.broadcast(_to_numpy(tensor), root_rank=root_rank, name=name,
                          process_set=process_set)
    return _like(out, tensor)


def broadcast_(tensor, root_rank=0, name=None, process_set=0):
    out = _core.broadcast(_to_numpy(tensor), root_rank=root_rank, name=name,
                          process_set=process_set)
    tensor[:] = _like(out, tensor)
    return tensor


def alltoall(tensor, splits=None, name=None, process_set=0):
    res = _core.alltoall(_to_numpy(tensor), splits=splits, name=name,
                         process_set=process_set)
    if splits is None:
        return _like(res, tensor)
    out, recv_splits = res
    return _like(out, tensor), nd.array(np.asarray(recv_splits))


def reducescatter(tensor, op=Average, name=None, process_set=0):
    out = _core.reducescatter(_to_numpy(tensor), op=op, name=name,
                              process_set=process_set)
    return _like(out, tensor)


# -- parameter sync ----------------------------------------------------------

def broadcast_parameters(params, root_rank=0, prefix="param"):
    """Broadcast an ``arg_params``-style dict **or** a Gluon
    ``ParameterDict`` from ``root_rank`` (reference:
    hvd.broadcast_parameters)."""
    if hasattr(params, "items"):
        items = sorted(params.items())
    else:
        raise ValueError("broadcast_parameters expects a dict or "
                         "gluon ParameterDict")
    for name_, p in items:
        if hasattr(p, "data"):  # gluon Parameter
            try:
                t = p.data()
            except Exception:
                continue  # deferred-init parameter: nothing to sync yet
            broadcast_(t, root_rank=root_rank, name=f"{prefix}.{name_}")
        else:
            broadcast_(p, root_rank=root_rank, name=f"{prefix}.{name_}")


# -- optimizers (reference: horovod/mxnet/__init__.py) -----------------------

class DistributedOptimizer(mx.optimizer.Optimizer):
    """Wrap an ``mx.optimizer.Optimizer``: allreduce each gradient before
    the wrapped update (reference: hvd.DistributedOptimizer — module-style
    API)."""

    def __init__(self, optimizer, op=Average, num_groups=0, process_set=0):
        self._optimizer = optimizer
        self._op = op
        self._process_set = process_set
        self._num_groups = num_groups  # accepted for parity; grouping is
        # handled by the core's fusion buffer, not client-side batching.

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def _do_allreduce(self, index, grad):
        if isinstance(index, (tuple, list)):
            outs = grouped_allreduce(list(grad), op=self._op,
                                     name=f"grad.{index[0]}",
                                     process_set=self._process_set)
            for g, out in zip(grad, outs):
                g[:] = out
        else:
            allreduce_(grad, op=self._op, name=f"grad.{index}",
                       process_set=self._process_set)

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def create_state(self, index, weight):
        return self._optimizer.create_state(index, weight)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)


class DistributedTrainer(mx.gluon.Trainer):
    """Gluon trainer that allreduces gradients across ranks before each
    optimizer step (reference: hvd.DistributedTrainer)."""

    def __init__(self, params, optimizer, optimizer_params=None, op=Average,
                 process_set=0):
        # Scale the lr-applied gradient like the reference: average over
        # the process set happens in the core, so pass through unchanged.
        super().__init__(params, optimizer, optimizer_params,
                         kvstore=None)
        self._hvd_op = op
        self._hvd_process_set = process_set

    def _allreduce_grads(self):
        grads = []
        for param in self._params:
            if param.grad_req != "null":
                grads.extend(param.list_grad())
        if not grads:
            return
        outs = _core.grouped_allreduce([_to_numpy(g) for g in grads],
                                       op=self._hvd_op, name="trainer.grads",
                                       process_set=self._hvd_process_set)
        for g, out in zip(grads, outs):
            g[:] = _like(out, g)
