"""Profiler ranges around user-facing op calls — the TPU mapping of the
reference's NVTX integration (``horovod/common/nvtx_op_range.h``: an
``NvtxOpRange`` around every ``EnqueueTensorAllreduce``-level API call so
nsys traces show where framework time goes).

On TPU the system profiler is XLA's xplane trace (``jax.profiler``), so:

- :func:`start` / :func:`stop` open and close a trace window
  (``jax.profiler.start_trace``/``stop_trace``; view in TensorBoard or
  Perfetto) — the counterpart of running under nsys.
- :func:`op_range` wraps the collective entry points in
  :mod:`horovod_tpu.ops.collective_ops` with
  ``jax.profiler.TraceAnnotation`` ranges named ``hvd.<op>``.

Annotation is OFF unless ``HVD_PROFILER=1`` is set or :func:`start` has
been called: the torch/TF bindings must not pay a jax import (nor
per-call annotation overhead) when nobody is tracing, matching the
reference's register-once-and-noop NVTX behavior when no collector is
attached.
"""
import contextlib
import os

_enabled = os.environ.get("HVD_PROFILER", "0") == "1"
_active_logdir = None

_NOOP = contextlib.nullcontext()


def enabled():
    return _enabled


def start(logdir):
    """Begin an xplane trace window at ``logdir`` (reference analog: start
    collecting under nsys). Enables op ranges for the rest of the process."""
    global _enabled, _active_logdir
    import jax

    jax.profiler.start_trace(str(logdir))
    _enabled = True
    _active_logdir = str(logdir)
    return _active_logdir


def stop():
    """Close the trace window opened by :func:`start`."""
    global _active_logdir
    import jax

    jax.profiler.stop_trace()
    _active_logdir = None


def op_range(name):
    """Context manager marking one user-facing op call (reference:
    ``NVTX_OP_RANGE`` macro). A shared no-op when profiling is off."""
    if not _enabled:
        return _NOOP
    import jax

    return jax.profiler.TraceAnnotation(name)
