"""Sharded, async, crash-safe checkpointing — the state plane for an
elastic fleet (ROADMAP item 2; docs/checkpoint.md has the full spec).

Format (``hvd-sharded-v1``). Each rank writes only its own addressable
shards — no gather, no full-array host pull on any rank:

    <dir>/<step>.tmp/rank_<r>/shard_NNNN.npy   per-shard payloads
    <dir>/<step>.tmp/rank_<r>/shards.json      per-rank shard manifest
    <dir>/<step>/MANIFEST.json                 global manifest (committed)

Commit protocol: every member writes + fsyncs its shards and per-rank
manifest, then meets a named barrier (``ckpt.shards.<step>``); the set
root merges the rank manifests, validates that the shards tile every
tensor's global shape, fsyncs ``MANIFEST.json``, and atomically renames
``<step>.tmp → <step>`` — a crash at ANY point before the rename leaves
the previous checkpoint as latest (``latest_step`` never resolves a
``.tmp`` staging dir or a dir without a committed manifest). Both
barriers are core collectives, so with ``HVD_PEER_TIMEOUT_MS`` armed a
writer that dies mid-save surfaces to survivors as ``RankEvictedError``
through the PR 8 liveness/eviction path instead of wedging them.

Async: ``save(..., async_=True)`` device-to-host copies the pytree (the
only step-blocking part, measured as the ``ckpt.snapshot_stall`` span +
gauge) and hands serialization/IO/commit to a background writer thread
overlapped with compute. At most one save is in flight; a new ``save``
or ``wait()`` joins it first and re-raises its failure. Every member of
the process set must agree on ``async_`` — the commit barriers are
collectives.

Restore reshards: ``restore`` at world size M reads the global manifest
from a save at world size N, computes the index ranges each target leaf
needs, and fetches/assembles only the overlapping shard fragments —
what turns elastic spare promotion into fetch-only-your-shard. Legacy
orbax checkpoints (``_METADATA`` marker) still restore through orbax;
new saves never touch orbax. Counters: ``hvd.checkpoint_stats()``.
"""
import io
import json
import os
import shutil
import signal
import threading
import time
import zlib

import numpy as np

from .basics import basics as _basics
from .exceptions import CheckpointError
from .observability import metrics as _metrics
from .observability import spans as _spans
from .ops import collective_ops as _core

FORMAT = "hvd-sharded-v1"
MANIFEST = "MANIFEST.json"
_RANK_MANIFEST = "shards.json"


def _dist_initialized():
    """jax.distributed.is_initialized with a fallback for jax releases
    that don't expose it (0.4.x): probe the distributed client state."""
    import jax

    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    try:
        from jax._src import distributed as _d

        return _d.global_state.client is not None
    except Exception:
        return False


def _ckptr():
    """Orbax Checkpointer confined to this process — kept ONLY for the
    legacy read path (checkpoints written before the sharded format)."""
    import jax
    import orbax.checkpoint as ocp

    me = jax.process_index() if _dist_initialized() else 0
    return ocp.Checkpointer(
        ocp.StandardCheckpointHandler(),
        multiprocessing_options=ocp.options.MultiprocessingOptions(
            primary_host=me, active_processes={me}))


def _resolve_set(process_set):
    """(set_id, root, member_ranks): the writer/commit root is the set's
    LOWEST member — hardcoding global rank 0 would silently commit
    nothing for a set excluding it. Non-global sets must be passed as
    ProcessSet objects (a bare id carries no membership)."""
    if hasattr(process_set, "process_set_id"):
        ranks = sorted(int(r) for r in process_set.ranks)
        return (int(process_set.process_set_id),
                (ranks[0] if ranks else 0), ranks)
    ps = int(process_set)
    if ps != 0:
        raise ValueError(
            "pass a ProcessSet object for non-global process sets: the "
            "checkpoint writer/root is the set's lowest member, which a "
            "bare id cannot name")
    return 0, 0, list(range(_basics.size()))


# ---------------------------------------------------------------------------
# Stats (hvd.checkpoint_stats()) — plain counters, always on; the CKPT_*
# metric families mirror them only under HVD_METRICS.

_stats_lock = threading.Lock()
_stats = {
    "saves": 0,              # save() calls entered
    "commits": 0,            # checkpoints durably committed (renamed)
    "aborted_commits": 0,    # saves that died before the rename
    "bytes": 0,              # shard bytes this rank wrote
    "snapshot_stall_ms": 0.0,  # last device->host snapshot stall
    "write_ms": 0.0,         # last write+commit time (off-path if async)
    "restores": 0,           # restore() calls that returned a tree
    "bytes_read": 0,         # shard-file bytes this rank fetched
    "fragments_fetched": 0,  # shard files read during reshard assembly
    "last_committed_step": -1,
}


def checkpoint_stats():
    """Snapshot of this process's checkpoint counters (see module doc)."""
    with _stats_lock:
        return dict(_stats)


def _bump(**kv):
    with _stats_lock:
        for k, v in kv.items():
            if k in ("snapshot_stall_ms", "write_ms", "last_committed_step"):
                _stats[k] = v
            else:
                _stats[k] += v


# ---------------------------------------------------------------------------
# latest_step

def _is_committed(path):
    """A step directory counts only when its commit marker is present:
    the sharded format's MANIFEST.json, or the legacy orbax _METADATA
    (possibly nested under <step>/default/ by an older revision)."""
    return (os.path.exists(os.path.join(path, MANIFEST))
            or os.path.exists(os.path.join(path, "_METADATA"))
            or os.path.exists(os.path.join(path, "default", "_METADATA")))


def latest_step(directory):
    """Newest COMMITTED checkpoint step in `directory`, or None.

    ``<step>.tmp`` staging dirs and integer-named dirs lacking a commit
    marker (a crashed writer's leftovers) are never resolved as latest —
    the crash-safety half of the commit protocol's contract.
    """
    d = str(directory)
    if not os.path.isdir(d):
        return None
    steps = [int(n) for n in os.listdir(d)
             if n.isdigit() and os.path.isdir(os.path.join(d, n))
             and _is_committed(os.path.join(d, n))]
    return max(steps) if steps else None


# ---------------------------------------------------------------------------
# Save: snapshot (step-blocking) + write/commit (inline or background)

class _InFlight:
    __slots__ = ("thread", "step", "error")

    def __init__(self, thread, step):
        self.thread = thread
        self.step = step
        self.error = None


_inflight = None


def wait():
    """Block until the in-flight async save (if any) commits; re-raises
    the writer thread's failure here, on the caller's thread."""
    global _inflight
    inf = _inflight
    if inf is None:
        return
    inf.thread.join()
    _inflight = None
    if inf.error is not None:
        raise inf.error


def _resolve_dir(directory):
    d = directory if directory is not None else os.environ.get("HVD_CKPT_DIR")
    if not d:
        raise ValueError(
            "no checkpoint directory: pass one or set HVD_CKPT_DIR")
    return str(d)


def _norm_index(index, shape):
    """Shard index -> [[start, stop], ...] with concrete bounds (a shard
    index from jax may carry None bounds on replicated dims)."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(int(dim))
        if step != 1:
            raise CheckpointError(f"non-unit shard stride {sl} unsupported")
        out.append([int(start), int(stop)])
    return out


def _is_jax_array(x):
    try:
        import jax

        return isinstance(x, jax.Array)
    except Exception:
        return False


def _flatten_named(tree):
    """[(name, leaf)] with stable pytree-path names, plus the treedef."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(kp), leaf) for kp, leaf in flat], treedef


def _snapshot(tree, root):
    """Device->host copy of this rank's contribution — the ONLY part of a
    save that blocks the step. jax.Array leaves contribute their
    addressable replica-0 shards (exactly one rank holds each); other
    leaves (plain numpy, scalars) are written whole by the set root,
    preserving the restore-returns-the-root's-values contract for
    unsharded state."""
    t0 = time.perf_counter()
    named, _ = _flatten_named(tree)
    me = _basics.rank()
    tensors, shards = {}, []
    for name, leaf in named:
        if _is_jax_array(leaf):
            gshape = tuple(int(s) for s in leaf.shape)
            dtype = np.dtype(leaf.dtype)
            for sh in leaf.addressable_shards:
                if sh.replica_id != 0:
                    continue
                shards.append((name, _norm_index(sh.index, gshape),
                               np.asarray(sh.data)))
        else:
            arr = np.asarray(leaf)
            gshape, dtype = arr.shape, arr.dtype
            if me == root:
                shards.append(
                    (name, [[0, int(d)] for d in gshape], arr))
        tensors[name] = {"global_shape": [int(d) for d in gshape],
                         "dtype": np.dtype(dtype).name}
    stall_ms = (time.perf_counter() - t0) * 1e3
    _bump(snapshot_stall_ms=stall_ms)
    if _metrics.enabled():
        _metrics.CKPT_SNAPSHOT_STALL_SECONDS.set(stall_ms / 1e3)
        _spans.event("ckpt.snapshot_stall",
                     time.time_ns() // 1000 - int(stall_ms * 1e3),
                     int(stall_ms * 1e3), cat="ckpt")
    return tensors, shards


def _fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_shards(rankdir, tensors, shards, step, rank):
    """Write this rank's shard payloads + per-rank manifest, all fsynced
    before returning — the barrier that follows asserts durability."""
    if os.path.isdir(rankdir):
        shutil.rmtree(rankdir)  # stale leftovers from an aborted attempt
    os.makedirs(rankdir)
    entries, nbytes = [], 0
    for i, (name, index, arr) in enumerate(shards):
        fname = f"shard_{i:04d}.npy"
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(arr))
        data = buf.getvalue()
        with open(os.path.join(rankdir, fname), "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        entries.append({"name": name, "index": index, "file": fname,
                        "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                        "nbytes": len(data)})
        nbytes += len(data)
    rm = {"format": FORMAT, "step": int(step), "rank": int(rank),
          "tensors": tensors, "shards": entries}
    with open(os.path.join(rankdir, _RANK_MANIFEST), "w") as f:
        json.dump(rm, f)
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(rankdir)
    _bump(bytes=nbytes)
    if _metrics.enabled():
        _metrics.CKPT_BYTES_WRITTEN.inc(nbytes)
    return nbytes


def _box_volume(index):
    v = 1
    for s, e in index:
        v *= max(0, e - s)
    return v


def _merge_and_commit(directory, staging, final, step, members):
    """Root half of the commit: merge rank manifests, validate coverage,
    fsync MANIFEST.json, atomically rename the staging dir."""
    tensors, merged = None, []
    for r in members:
        rman = os.path.join(staging, f"rank_{r}", _RANK_MANIFEST)
        try:
            with open(rman) as f:
                rm = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointError(
                f"step {step}: rank {r} manifest {rman} unreadable: {e}")
        if tensors is None:
            tensors = rm["tensors"]
        for sh in rm["shards"]:
            merged.append(dict(sh, rank=int(r)))
    # Drop rank dirs that are not part of this commit (a crashed attempt
    # at a different world size leaves them behind in the staging dir).
    keep = {f"rank_{r}" for r in members}
    for n in os.listdir(staging):
        if n.startswith("rank_") and n not in keep:
            shutil.rmtree(os.path.join(staging, n), ignore_errors=True)
    # Coverage: the deduped shard boxes of every tensor must tile its
    # global shape exactly — else the checkpoint could restore silently
    # wrong, which is the one thing this module must never do.
    by_name = {}
    for sh in merged:
        by_name.setdefault(sh["name"], set()).add(
            tuple((s, e) for s, e in sh["index"]))
    for name, meta in tensors.items():
        vol = int(np.prod([int(d) for d in meta["global_shape"]] or [1]))
        got = sum(_box_volume(b) for b in by_name.get(name, ()))
        if got != vol:
            raise CheckpointError(
                f"step {step}: tensor {name} shards cover {got} of {vol} "
                f"elements — refusing to commit a torn checkpoint")
    manifest = {"format": FORMAT, "step": int(step),
                "world_size": len(members), "tensors": tensors,
                "shards": merged}
    mpath = os.path.join(staging, MANIFEST)
    with open(mpath, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(staging)
    if os.path.isdir(final):  # re-save of an existing step
        old = final + ".old"
        shutil.rmtree(old, ignore_errors=True)
        os.rename(final, old)
        os.rename(staging, final)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.rename(staging, final)
    _fsync_dir(directory)


def _write_and_commit(directory, step, tensors, shards, ps, root, members):
    """Serialization + IO + the two-barrier commit — everything a save
    does OFF the step path when async. Runs on the caller's thread for
    sync saves and on the background writer thread for async ones."""
    me = _basics.rank()
    t0 = time.perf_counter()
    try:
        with _spans.span("ckpt.write", cat="ckpt", step=int(step)):
            staging = os.path.join(directory, f"{int(step)}.tmp")
            final = os.path.join(directory, str(int(step)))
            rankdir = os.path.join(staging, f"rank_{me}")
            os.makedirs(staging, exist_ok=True)
            _write_shards(rankdir, tensors, shards, step, me)
            if (os.environ.get("HVD_CKPT_TEST_CRASH") == str(int(step))
                    and me == root):
                # Chaos hook (tests/test_chaos.py): the writer dies with
                # durable shards but NO commit — survivors must evict it
                # via the liveness path and restore the previous step.
                os.kill(os.getpid(), signal.SIGKILL)
            _core.barrier(process_set=ps, name=f"ckpt.shards.{int(step)}")
            with _spans.span("ckpt.commit", cat="ckpt", step=int(step)):
                if me == root:
                    _merge_and_commit(directory, staging, final, step,
                                      members)
                _core.barrier(process_set=ps,
                              name=f"ckpt.commit.{int(step)}")
    except BaseException:
        _bump(aborted_commits=1)
        if _metrics.enabled():
            _metrics.CKPT_ABORTED_COMMITS.inc()
        raise
    write_ms = (time.perf_counter() - t0) * 1e3
    _bump(commits=1, write_ms=write_ms, last_committed_step=int(step))
    if _metrics.enabled():
        _metrics.CKPT_COMMITS.inc()
        _metrics.CKPT_WRITE_SECONDS.set(write_ms / 1e3)
        _metrics.CKPT_LAST_COMMITTED_STEP.set(int(step))
    if me == root:
        _report_commit(int(step))


def _report_commit(step):
    """Tell the elastic driver the last durably committed step (it rides
    elastic_stats and each epoch's assignments, so a promoted spare can
    resolve its restore step without a collective). Best-effort."""
    try:
        from .runner.elastic import worker as _ew

        if _ew.is_elastic():
            _ew.report_ckpt_commit(step)
    except Exception:
        pass


def save(directory, step, tree, process_set=0, async_=None):
    """Write `tree` (a pytree of arrays) as checkpoint `step`.

    Every member of the process set writes its own addressable shards;
    the set root commits (global manifest + atomic rename) only after a
    named barrier confirms every rank's shards are durable. Sync saves
    return after the commit barrier; ``async_=True`` returns right after
    the device->host snapshot and commits on a background writer thread
    (:func:`wait` joins it; a prior async failure re-raises on the next
    ``save``/``wait``). ``async_=None`` reads ``HVD_CKPT_ASYNC``; the
    flag must agree across the set — the commit barriers are
    collectives. ``directory=None`` falls back to ``HVD_CKPT_DIR``.
    """
    global _inflight
    if async_ is None:
        async_ = os.environ.get("HVD_CKPT_ASYNC", "0") == "1"
    directory = _resolve_dir(directory)
    wait()  # at-most-one-in-flight; surfaces the previous save's failure
    _bump(saves=1)
    if _metrics.enabled():
        _metrics.CKPT_SAVES.inc()
    ps, root, members = _resolve_set(process_set)
    with _spans.span("ckpt.save", cat="ckpt", step=int(step),
                     mode="async" if async_ else "sync"):
        tensors, shards = _snapshot(tree, root)
        if not async_:
            _write_and_commit(directory, step, tensors, shards, ps, root,
                              members)
            return
        inf = _InFlight(None, int(step))

        def _run():
            try:
                _write_and_commit(directory, step, tensors, shards, ps,
                                  root, members)
            except BaseException as e:  # surfaced on the next save/wait
                inf.error = e

        inf.thread = threading.Thread(
            target=_run, name=f"ckpt-writer-{int(step)}", daemon=True)
        _inflight = inf
        inf.thread.start()


# ---------------------------------------------------------------------------
# Restore (with reshard)

def _load_manifest(path):
    mpath = os.path.join(path, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except OSError as e:
        raise CheckpointError(f"{mpath}: unreadable: {e}")
    except ValueError as e:
        raise CheckpointError(
            f"{mpath}: torn manifest (not parseable as JSON: {e}) — the "
            f"checkpoint did not commit intact")
    if manifest.get("format") != FORMAT:
        raise CheckpointError(
            f"{mpath}: unknown format {manifest.get('format')!r} "
            f"(expected {FORMAT})")
    return manifest


class _ShardReader:
    """Reads + verifies shard files on demand, caching per restore call
    (several addressable devices of one target leaf may need fragments
    from the same shard file)."""

    def __init__(self, path, manifest):
        self.path = path
        self.by_name = {}
        for sh in manifest["shards"]:
            self.by_name.setdefault(sh["name"], []).append(sh)
        self._cache = {}

    def load(self, sh):
        key = (sh["rank"], sh["file"])
        if key in self._cache:
            return self._cache[key]
        fpath = os.path.join(self.path, f"rank_{sh['rank']}", sh["file"])
        try:
            with open(fpath, "rb") as f:
                data = f.read()
        except OSError as e:
            raise CheckpointError(
                f"missing shard rank_{sh['rank']}/{sh['file']} for tensor "
                f"{sh['name']}: {e}")
        if (zlib.crc32(data) & 0xFFFFFFFF) != sh["crc32"]:
            raise CheckpointError(
                f"checksum mismatch in shard rank_{sh['rank']}/"
                f"{sh['file']} for tensor {sh['name']}")
        arr = np.load(io.BytesIO(data), allow_pickle=False)
        self._cache[key] = arr
        _bump(bytes_read=len(data), fragments_fetched=1)
        if _metrics.enabled():
            _metrics.CKPT_BYTES_READ.inc(len(data))
            _metrics.CKPT_FRAGMENTS.inc()
        return arr

    def read_region(self, name, bounds, dtype):
        """Assemble the [start, stop) region `bounds` of tensor `name`
        from only the shard fragments that overlap it."""
        out = np.empty([e - s for s, e in bounds], dtype)
        want = _box_volume(bounds)
        covered = 0
        for sh in self.by_name.get(name, ()):
            inter = []
            for (ws, we), (ss, se) in zip(bounds, sh["index"]):
                s, e = max(ws, ss), min(we, se)
                if s >= e:
                    inter = None
                    break
                inter.append((s, e))
            if inter is None and bounds:
                continue
            arr = self.load(sh)
            if bounds:
                dst = tuple(slice(s - ws, e - ws)
                            for (s, e), (ws, we) in zip(inter, bounds))
                src = tuple(slice(s - ss, e - ss)
                            for (s, e), (ss, se) in zip(inter, sh["index"]))
                out[dst] = arr[src]
                covered += _box_volume(inter)
            else:  # scalar
                out[()] = arr[()]
                covered += 1
        if covered != want:
            raise CheckpointError(
                f"tensor {name}: region {bounds} only {covered}/{want} "
                f"elements covered by shards — refusing a partial restore")
        return out


def _restore_sharded(path, tree_like):
    """Reshard-on-read: every target leaf fetches only the index ranges
    it needs. A jax.Array leaf keeps its sharding — each addressable
    device pulls exactly its own region; other leaves assemble the full
    tensor on host."""
    manifest = _load_manifest(path)
    reader = _ShardReader(path, manifest)
    tensors = manifest["tensors"]
    named, treedef = _flatten_named(tree_like)
    out = []
    for name, leaf in named:
        if name not in tensors:
            raise CheckpointError(
                f"{os.path.join(path, MANIFEST)}: no tensor {name} in the "
                f"checkpoint (saved tree differs from tree_like)")
        meta = tensors[name]
        gshape = tuple(int(d) for d in meta["global_shape"])
        dtype = np.dtype(meta["dtype"])
        if _is_jax_array(leaf):
            import jax

            if tuple(int(s) for s in leaf.shape) != gshape:
                raise CheckpointError(
                    f"tensor {name}: tree_like shape "
                    f"{tuple(leaf.shape)} != saved shape {gshape}")

            def _cb(idx, _n=name, _g=gshape, _d=dtype):
                return reader.read_region(_n, _norm_index(idx, _g), _d)

            out.append(jax.make_array_from_callback(
                gshape, leaf.sharding, _cb))
        else:
            out.append(reader.read_region(
                name, [[0, d] for d in gshape], dtype))
    import jax

    return jax.tree_util.tree_unflatten(treedef, out)


def _restore_orbax(path, tree_like):
    """Legacy read path: checkpoints written by the pre-sharded revisions
    of this module (orbax StandardSave; an even older revision nested the
    payload under <step>/default/)."""
    import jax
    import orbax.checkpoint as ocp

    legacy = os.path.join(path, "default")
    if os.path.isdir(legacy) and not os.path.exists(
            os.path.join(path, "_METADATA")):
        path = legacy
    with _ckptr() as ck:
        return ck.restore(
            path, args=ocp.args.StandardRestore(
                jax.tree.map(np.asarray, tree_like)))


def restore(directory, tree_like, step=None, process_set=0,
            coordinate=True):
    """Restore a checkpoint into the structure (and shardings) of
    `tree_like`; returns (tree, step) or (None, None) when no committed
    checkpoint exists.

    With ``coordinate=True`` the set's root resolves which step to load
    (`step` or the latest) and broadcasts its choice so every member
    reads the SAME checkpoint even if a newer one lands mid-call.

    ``coordinate=False`` skips the broadcast and resolves locally —
    REQUIRED when ranks may reach this call with different collective
    histories (e.g. startup code before ``hvd.elastic.run``, where a
    mid-run joiner executes it while veterans sit in ``state.sync()``):
    a collective here would deadlock the job. The commit protocol writes
    atomically, so a locally visible committed step is complete; on a
    shared filesystem all ranks resolve the same latest step unless a
    save is racing — exactly the window ``coordinate=True`` exists for.
    """
    directory = _resolve_dir(directory)
    ps, root, _ = _resolve_set(process_set)
    if not coordinate:
        chosen = step if step is not None else latest_step(directory)
    else:
        if _basics.rank() == root:
            chosen = step if step is not None else latest_step(directory)
        else:
            chosen = None
        chosen = _core.broadcast_object(chosen, root_rank=root,
                                        name="ckpt.step", process_set=ps)
    if chosen is None:
        return None, None
    path = os.path.join(directory, str(int(chosen)))
    with _spans.span("ckpt.restore", cat="ckpt", step=int(chosen)):
        if os.path.exists(os.path.join(path, MANIFEST)):
            out = _restore_sharded(path, tree_like)
        elif _is_committed(path):
            out = _restore_orbax(path, tree_like)
        else:
            raise CheckpointError(
                f"{path}: no committed checkpoint ({MANIFEST} and the "
                f"legacy _METADATA marker are both absent)")
    _bump(restores=1)
    if _metrics.enabled():
        _metrics.CKPT_RESTORES.inc()
    return out, int(chosen)
