"""Rank-aware on-disk checkpointing, delegated to orbax.

Reference parity: SURVEY.md §5 checkpoint/resume — the reference ships no
custom on-disk format; examples/docs follow the "rank 0 writes
framework-native checkpoints" pattern, and the TPU build should delegate
to orbax while keeping the elastic in-memory State protocol
(horovod_tpu/elastic.py) for fast rollback. These helpers wrap that
pattern for multi-process jobs:

- :func:`save` — the set's root writes the pytree via orbax; everyone
  barriers so no rank races ahead of a half-written checkpoint.
- :func:`restore` — every rank reads the same step (the root picks the
  latest and broadcasts its choice, so ranks can't disagree after a
  partial save).
- :func:`latest_step` — newest step on disk, or None.

Cross-rank coordination is THIS module's (core barrier + broadcast step
agreement); orbax runs with its multihost sync confined to the calling
process — the synchronous ``Checkpointer``, not ``CheckpointManager``,
because under an initialized ``jax.distributed`` mesh the manager runs
global barriers and the preemption service, which deadlock/fail when
only the root enters orbax (elastic and tpurun jobs form such a mesh).

Single-process use works too (the collectives are no-ops at size 1).
Layout: ``<directory>/<step>/`` per checkpoint, written atomically by
orbax (a plain-integer directory name is a complete checkpoint).
"""
import os

import numpy as np

from .basics import basics as _basics
from .ops import collective_ops as _core


def _dist_initialized():
    """jax.distributed.is_initialized with a fallback for jax releases
    that don't expose it (0.4.x): probe the distributed client state."""
    import jax

    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    try:
        from jax._src import distributed as _d

        return _d.global_state.client is not None
    except Exception:
        return False


def _ckptr():
    import jax
    import orbax.checkpoint as ocp

    me = jax.process_index() if _dist_initialized() else 0
    return ocp.Checkpointer(
        ocp.StandardCheckpointHandler(),
        multiprocessing_options=ocp.options.MultiprocessingOptions(
            primary_host=me, active_processes={me}))


def _resolve_set(process_set):
    """(set_id, root_global_rank): the writer/broadcast root is the set's
    LOWEST member — hardcoding global rank 0 would silently write nothing
    for a set excluding it. Non-global sets must be passed as ProcessSet
    objects (a bare id carries no membership)."""
    if hasattr(process_set, "process_set_id"):
        ranks = process_set.ranks
        return int(process_set.process_set_id), (min(ranks) if ranks else 0)
    ps = int(process_set)
    if ps != 0:
        raise ValueError(
            "pass a ProcessSet object for non-global process sets: the "
            "checkpoint writer/root is the set's lowest member, which a "
            "bare id cannot name")
    return 0, 0


def latest_step(directory):
    """Newest complete checkpoint step in `directory`, or None. Orbax
    writes atomically (tmp-suffixed dir + rename), so a plain-integer
    directory name is a finished checkpoint."""
    d = str(directory)
    if not os.path.isdir(d):
        return None
    steps = [int(n) for n in os.listdir(d)
             if n.isdigit() and os.path.isdir(os.path.join(d, n))]
    return max(steps) if steps else None


def save(directory, step, tree, process_set=0):
    """Write `tree` (a pytree of arrays) as checkpoint `step`; the set's
    root writes, every member returns only after the write is durable.
    The barrier is named by `step` so elastic joiners (whose auto-name
    counters differ from veterans') negotiate the same tensor."""
    import orbax.checkpoint as ocp

    ps, root = _resolve_set(process_set)
    if _basics.rank() == root:
        os.makedirs(str(directory), exist_ok=True)
        with _ckptr() as ck:
            ck.save(os.path.join(str(directory), str(int(step))),
                    args=ocp.args.StandardSave(_to_host(tree)),
                    force=True)
    _core.barrier(process_set=ps, name=f"ckpt.save.{int(step)}")


def restore(directory, tree_like, step=None, process_set=0,
            coordinate=True):
    """Restore a checkpoint into the structure of `tree_like`.

    With ``coordinate=True`` the set's root resolves which step to load
    (`step` or the latest) and broadcasts its choice so every member
    reads the SAME checkpoint even if a newer one lands mid-call.
    Returns (tree, step) or (None, None) if no checkpoint exists.

    ``coordinate=False`` skips the broadcast and resolves locally —
    REQUIRED when ranks may reach this call with different collective
    histories (e.g. startup code before ``hvd.elastic.run``, where a
    mid-run joiner executes it while veterans sit in ``state.sync()``):
    a collective here would deadlock the job. Orbax writes atomically,
    so a locally visible plain-integer step directory is complete; on a
    shared filesystem all ranks resolve the same latest step unless a
    save is racing — exactly the window ``coordinate=True`` exists for.
    """
    import orbax.checkpoint as ocp

    ps, root = _resolve_set(process_set)
    if not coordinate:
        chosen = step if step is not None else latest_step(directory)
    else:
        if _basics.rank() == root:
            chosen = step if step is not None else latest_step(directory)
        else:
            chosen = None
        chosen = _core.broadcast_object(chosen, root_rank=root,
                                        name="ckpt.step", process_set=ps)
    if chosen is None:
        return None, None
    path = os.path.join(str(directory), str(int(chosen)))
    # Back-compat: an earlier revision wrote via orbax CheckpointManager,
    # which nests the payload under <step>/default/.
    legacy = os.path.join(path, "default")
    if os.path.isdir(legacy) and not os.path.exists(
            os.path.join(path, "_METADATA")):
        path = legacy
    with _ckptr() as ck:
        out = ck.restore(
            path, args=ocp.args.StandardRestore(_to_host(tree_like)))
    return out, int(chosen)


def _to_host(tree):
    """Orbax round-trips numpy; device arrays (jax) are pulled to host."""
    import jax

    return jax.tree.map(np.asarray, tree)
