"""Rank-aware on-disk checkpointing, delegated to orbax.

Reference parity: SURVEY.md §5 checkpoint/resume — the reference ships no
custom on-disk format; examples/docs follow the "rank 0 writes
framework-native checkpoints" pattern, and the TPU build should delegate
to orbax while keeping the elastic in-memory State protocol
(horovod_tpu/elastic.py) for fast rollback. These helpers wrap that
pattern for multi-process jobs:

- :func:`save` — rank 0 writes the pytree via orbax; everyone barriers so
  no rank races ahead of a half-written checkpoint.
- :func:`restore` — every rank reads the same step (rank 0 picks the
  latest and broadcasts its choice, so ranks can't disagree after a
  partial save).
- :func:`latest_step` — newest step on disk, or None.

Single-process use works too (the collectives are no-ops at size 1).
"""
import os

import numpy as np

from .basics import basics as _basics
from .ops import collective_ops as _core


def _mgr(directory):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(os.path.abspath(str(directory)))


def _resolve_set(process_set):
    """(set_id, root_global_rank): the writer/broadcast root is the set's
    LOWEST member — hardcoding global rank 0 would silently write nothing
    for a set excluding it. Non-global sets must be passed as ProcessSet
    objects (a bare id carries no membership)."""
    if hasattr(process_set, "process_set_id"):
        ranks = process_set.ranks
        return int(process_set.process_set_id), (min(ranks) if ranks else 0)
    ps = int(process_set)
    if ps != 0:
        raise ValueError(
            "pass a ProcessSet object for non-global process sets: the "
            "checkpoint writer/root is the set's lowest member, which a "
            "bare id cannot name")
    return 0, 0


def latest_step(directory):
    """Newest checkpoint step in `directory`, or None."""
    with _mgr(directory) as mgr:
        return mgr.latest_step()


def save(directory, step, tree, process_set=0):
    """Write `tree` (a pytree of arrays) as checkpoint `step`; the set's
    root writes, every member returns only after the write is durable."""
    import orbax.checkpoint as ocp

    ps, root = _resolve_set(process_set)
    if _basics.rank() == root:
        with _mgr(directory) as mgr:
            mgr.save(int(step),
                     args=ocp.args.StandardSave(_to_host(tree)))
            mgr.wait_until_finished()
    _core.barrier(process_set=ps)


def restore(directory, tree_like, step=None, process_set=0):
    """Restore a checkpoint into the structure of `tree_like`.

    The set's root resolves which step to load (`step` or the latest) and
    broadcasts its choice so every member reads the SAME checkpoint even
    if a newer one landed mid-call. Returns (tree, step) or (None, None)
    if no checkpoint exists.
    """
    import orbax.checkpoint as ocp

    ps, root = _resolve_set(process_set)
    with _mgr(directory) as mgr:
        if _basics.rank() == root:
            chosen = step if step is not None else mgr.latest_step()
        else:
            chosen = None
        chosen = _core.broadcast_object(chosen, root_rank=root,
                                        name="ckpt.step", process_set=ps)
        if chosen is None:
            return None, None
        out = mgr.restore(
            int(chosen),
            args=ocp.args.StandardRestore(_to_host(tree_like)))
    return out, int(chosen)


def _to_host(tree):
    """Orbax round-trips numpy; device arrays (jax) are pulled to host."""
    import jax

    return jax.tree.map(np.asarray, tree)
