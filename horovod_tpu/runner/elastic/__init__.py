"""Elastic launcher machinery (reference: horovod/runner/elastic/).

- :mod:`.discovery` — host discovery (user script → {host: slots}).
- :mod:`.driver` — ElasticDriver: membership monitoring, worker lifecycle,
  blacklisting, epoch-based rendezvous over the HTTP KV store.
- :mod:`.worker` — worker-side rendezvous client + host-update
  notification polling.
"""
