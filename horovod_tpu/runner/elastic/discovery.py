"""Host discovery (reference: horovod/runner/elastic/discovery.py).

`HostDiscoveryScript` runs the user's script; its stdout is one
`host` or `host:slots` per line — the current available cluster. Polled
periodically by the ElasticDriver.
"""

import subprocess


class HostDiscovery:
    def find_available_hosts_and_slots(self):
        """→ dict {hostname: slots}."""
        raise NotImplementedError


class FixedHosts(HostDiscovery):
    def __init__(self, hosts):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self):
        return dict(self._hosts)


class HostDiscoveryScript(HostDiscovery):
    def __init__(self, script, default_slots=1, timeout=10.0):
        self._script = script
        self._default_slots = default_slots
        self._timeout = timeout

    def find_available_hosts_and_slots(self):
        out = subprocess.run(
            self._script, shell=True, capture_output=True, text=True,
            timeout=self._timeout)
        if out.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed rc={out.returncode}: "
                f"{out.stderr.strip()}")
        hosts = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                host, slots = line.rsplit(":", 1)
                hosts[host] = int(slots)
            else:
                hosts[line] = self._default_slots
        return hosts
