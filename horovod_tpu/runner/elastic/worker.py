"""Worker-side elastic runtime: epoch rendezvous + host-update polling.

Reference parity: `horovod/runner/elastic/worker.py`
(`WorkerNotificationManager/Service`) — except the reference pushes
HostsUpdatedInterrupt to an HTTP server inside each worker; here workers
poll the driver's KV store epoch counter (`/ctl/epoch`), which needs no
per-worker server and survives NAT/loopback setups identically.

Env contract (set by the elastic driver at spawn):
- HVD_ELASTIC=1
- HVD_RENDEZVOUS_ADDR=host:port  (driver KV store)
- HVD_WORKER_ID=host/slot-uuid   (stable identity across epochs)
"""

import json
import os
import threading
import time

from .. import http_server

POLL_INTERVAL_S = 0.5


def is_elastic():
    return os.environ.get("HVD_ELASTIC") == "1"


def _rdv_addr():
    return os.environ["HVD_RENDEZVOUS_ADDR"]


def _rdv_secret():
    """Per-job HMAC key (hex in the spawn env); None for legacy/unsigned."""
    s = os.environ.get("HVD_RENDEZVOUS_SECRET")
    return bytes.fromhex(s) if s else None


def _worker_id():
    return os.environ["HVD_WORKER_ID"]


def _liveness_enabled():
    """KV liveness heartbeats ride the same knob as the control-plane
    heartbeat (HVD_PEER_TIMEOUT_MS > 0): off means zero extra traffic."""
    try:
        return int(os.environ.get("HVD_PEER_TIMEOUT_MS", "0")) > 0
    except ValueError:
        return False


def current_epoch():
    try:
        return int(http_server.read_kv(_rdv_addr(), "ctl", "epoch",
                                       secret_key=_rdv_secret()))
    except Exception:
        return -1


def fetch_assignment(epoch, timeout=600.0):
    """Wait for this worker's assignment in `epoch`. Returns dict or the
    string directive "exit"."""
    raw = http_server.read_kv(_rdv_addr(), f"assign-{epoch}", _worker_id(),
                              secret_key=_rdv_secret(), wait=True,
                              timeout=timeout)
    val = raw.decode()
    if val == "exit":
        return "exit"
    return json.loads(val)


def report_eviction(rank, epoch):
    """Tell the driver a named rank was evicted from the control plane
    (RankEvictedError reached this worker). The driver maps the rank back
    to a worker id via its per-epoch rank map, kills the wedged process,
    and records a transient failure — without this push it would wait for
    the liveness backstop to notice. Best-effort: the epoch poll + stale
    liveness remain the fallback."""
    try:
        http_server.put_kv(
            _rdv_addr(), "ctl", f"evict/{_worker_id()}",
            json.dumps({"rank": int(rank), "epoch": int(epoch)}).encode(),
            secret_key=_rdv_secret())
    except Exception:
        pass


def report_serve_load(queue_depth, batch_fill, kv_occupancy=0.0):
    """Publish the serving loop's load sample to the driver's KV store
    (/ctl/serve_load/<wid>) for queue-depth autoscaling. Rank 0 of the
    serve loop calls this each boundary interval; the driver consumes
    the keys, folds them through its AutoscalePolicy, and republishes
    the epoch with a resized active set (serving/autoscale.py). Best
    effort like report_eviction: a lost sample just delays the next
    scale decision by one interval."""
    try:
        http_server.put_kv(
            _rdv_addr(), "ctl", f"serve_load/{_worker_id()}",
            json.dumps({"queue_depth": int(queue_depth),
                        "batch_fill": float(batch_fill),
                        "kv_occupancy": float(kv_occupancy)}).encode(),
            secret_key=_rdv_secret())
    except Exception:
        pass


def report_ckpt_commit(step):
    """Publish the last durably committed checkpoint step to the driver
    (/ctl/ckpt/<wid>). The set root calls this after every commit
    (checkpoint.py); the driver consumes the keys, tracks the max, and
    republishes it both in /ctl/elastic_stats (→ hvd.elastic_stats()
    ['last_ckpt_step']) and in every subsequent epoch's assignments — so
    a promoted spare resolves its restore step WITHOUT a collective
    (checkpoint.restore coordinate=False + last_committed_step()). Best
    effort like report_eviction: a lost report just means joiners fall
    back to latest_step() on the shared directory."""
    try:
        http_server.put_kv(
            _rdv_addr(), "ctl", f"ckpt/{_worker_id()}",
            str(int(step)).encode(), secret_key=_rdv_secret())
    except Exception:
        pass


def last_committed_step():
    """The newest checkpoint step the driver has confirmed committed, or
    None. Reads the epoch assignment first (HVD_CKPT_STEP, no network),
    then falls back to the driver stats snapshot — for (re)joiners and
    promoted spares picking their manifest-path restore step."""
    v = os.environ.get("HVD_CKPT_STEP")
    if v not in (None, ""):
        try:
            return int(v)
        except ValueError:
            pass
    s = fetch_driver_stats().get("last_ckpt_step", -1)
    return int(s) if int(s) >= 0 else None


_driver_stats_cache = {}
_driver_stats_ts = 0.0
_DRIVER_STATS_TTL_S = 2.0


def fetch_driver_stats():
    """Best-effort snapshot of the driver-side elastic counters
    (promotions, incremental/full epochs, driver evictions) published at
    `/ctl/elastic_stats`. Cached briefly so hvd.elastic_stats() stays
    cheap enough to sample per step; {} when the driver has published
    nothing (e.g. pre-eviction) or the KV store is unreachable."""
    global _driver_stats_cache, _driver_stats_ts
    now = time.monotonic()
    if now - _driver_stats_ts < _DRIVER_STATS_TTL_S:
        return dict(_driver_stats_cache)
    try:
        raw = http_server.read_kv(_rdv_addr(), "ctl", "elastic_stats",
                                  secret_key=_rdv_secret())
        _driver_stats_cache = {k: int(v)
                               for k, v in json.loads(raw.decode()).items()}
    except Exception:
        _driver_stats_cache = dict(_driver_stats_cache)
    _driver_stats_ts = now
    return dict(_driver_stats_cache)


def request_reset(epoch):
    """Push a reset request to the driver (reference:
    WorkerNotificationService): this worker hit an internal error and needs
    a NEW rendezvous epoch even though every process may still be alive.
    The driver marks membership dirty and publishes one promptly instead of
    the worker stalling toward the rendezvous timeout."""
    try:
        http_server.put_kv(_rdv_addr(), "ctl", f"reset/{_worker_id()}",
                           str(epoch).encode(), secret_key=_rdv_secret())
    except Exception:
        pass  # best-effort: the epoch poll remains the fallback


def apply_assignment(a):
    os.environ["HVD_RANK"] = str(a["rank"])
    os.environ["HVD_SIZE"] = str(a["size"])
    os.environ["HVD_LOCAL_RANK"] = str(a["local_rank"])
    os.environ["HVD_LOCAL_SIZE"] = str(a["local_size"])
    os.environ["HVD_CROSS_RANK"] = str(a["cross_rank"])
    os.environ["HVD_CROSS_SIZE"] = str(a["cross_size"])
    os.environ["HVD_CONTROLLER_ADDR"] = a["controller"]
    # Last committed checkpoint step rides every assignment so a promoted
    # spare knows where to restore from before it runs any collective.
    if a.get("ckpt_step") is not None:
        os.environ["HVD_CKPT_STEP"] = str(a["ckpt_step"])
    if a.get("scope"):
        os.environ["HVD_ENDPOINT_SCOPE"] = a["scope"]
    if a.get("rdv"):
        # Mixed local+remote epoch: negotiate against the driver's ROUTABLE
        # address, not the loopback one this worker may have been spawned
        # with — a local rank 0 derives its registered controller IP from
        # the interface toward the KV store, and 127.0.0.1 would be
        # unreachable for the remote ranks.
        os.environ["HVD_RENDEZVOUS_ADDR"] = a["rdv"]
    # The driver hosts a jax.distributed coordination service per epoch;
    # workers join it as recoverable clients (jax/distributed.py). A
    # single-worker epoch publishes no address — clear any stale one.
    if a.get("jax_coord"):
        os.environ["HVD_JAX_COORD_ADDR"] = a["jax_coord"]
        os.environ["HVD_JAX_COORD_MODE"] = "client"
    else:
        os.environ.pop("HVD_JAX_COORD_ADDR", None)
        os.environ.pop("HVD_JAX_COORD_MODE", None)


def rendezvous_init():
    """First init for an elastic worker: wait for the first epoch that can
    include this worker (HVD_SPAWN_EPOCH, set by the driver at spawn — a
    stale current epoch's assignment table will never contain this id),
    then init the core. Called from hvd.init() when HVD_ELASTIC=1."""
    from ...basics import basics

    # Start the poll thread before parking: a hot spare heartbeats from it
    # while it waits, long before elastic.run() would have started it.
    notification_manager.init()
    epoch = _wait_epoch_at_least(int(os.environ.get("HVD_SPAWN_EPOCH", 0)))
    a = fetch_assignment(epoch)
    if a == "exit":
        raise SystemExit(0)
    if isinstance(a, dict) and a.get("spare"):
        epoch, a = _park_as_spare(epoch)
    apply_assignment(a)
    notification_manager.set_epoch(epoch)
    _negotiate()
    basics.init()
    return epoch


def rendezvous_reset():
    """Re-rendezvous after a failure/membership change: shutdown the core,
    tear down the per-epoch jax mesh (PJRT client + backends — SURVEY.md §7
    hard part (c); reference: ncclCommAbort + communicator rebuild), wait
    for a NEW epoch, re-init both planes with its assignment."""
    import sys

    from ...basics import basics

    if basics.is_initialized():
        basics.shutdown()
    if "jax" in sys.modules:
        # Tear down even when no mesh was live this epoch: a size-1 epoch
        # still creates a local backend that would block the next epoch's
        # mesh formation (initialize requires uninitialized backends).
        from ...jax import distributed as _jd

        _jd.teardown()
    # Tell the driver we need a new epoch NOW: if this reset came from a
    # HorovodInternalError with every process still alive, no death will
    # ever bump the epoch for us. (A membership-change reset already has a
    # newer epoch pending; the driver ignores stale requests.)
    request_reset(notification_manager.epoch)
    epoch = _wait_epoch_at_least(notification_manager.epoch + 1)
    a = fetch_assignment(epoch)
    if a == "exit":
        raise SystemExit(0)
    if isinstance(a, dict) and a.get("spare"):
        epoch, a = _park_as_spare(epoch)
    apply_assignment(a)
    notification_manager.set_epoch(epoch)
    _negotiate()
    basics.init()
    # Same gate as hvd.init(): never import the jax subpackage (and its
    # jax/optax module-level dependencies) into non-JAX workers.
    import horovod_tpu

    horovod_tpu._maybe_init_jax_mesh()
    return epoch


def _negotiate():
    """Resolve 'negotiate' endpoints for this epoch: rank 0 registers real
    ports probed on ITS host (runner/network.py — replaces the driver
    guessing a remote host's free port with random.randint)."""
    from .. import network

    if os.environ.get("HVD_CONTROLLER_ADDR") == network.NEGOTIATE:
        network.negotiate_endpoints_from_env()


def _wait_epoch_at_least(n, timeout=600.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        e = current_epoch()
        if e >= n:
            return e
        time.sleep(POLL_INTERVAL_S)
    raise TimeoutError(f"no rendezvous epoch >= {n} within {timeout}s")


def _park_as_spare(epoch):
    """Hot-spare parking: this worker is rendezvoused with the driver but
    holds no rank. Keep heartbeating (the notification poll thread does
    that) and wait for a promotion — an epoch whose assignment table gives
    this id a real rank. Parking is unbounded on purpose: a spare's whole
    job is to wait. Returns (epoch, assignment) on promotion; raises
    SystemExit when the driver retires the spare."""
    notification_manager.set_epoch(epoch)
    while True:
        try:
            epoch = _wait_epoch_at_least(epoch + 1)
        except TimeoutError:
            continue  # still parked; keep waiting
        a = fetch_assignment(epoch)
        if a == "exit":
            raise SystemExit(0)
        if isinstance(a, dict) and a.get("spare"):
            notification_manager.set_epoch(epoch)
            continue
        try:  # promotion marker for merged traces (core side: TCP_EVICT)
            from ...observability import spans as _spans

            _spans.instant("ELASTIC_PROMOTE", epoch=epoch,
                           rank=a.get("rank", -1) if isinstance(a, dict)
                           else -1)
        except Exception:
            pass
        return epoch, a


class WorkerNotificationManager:
    """Polls the driver's epoch counter; a bump while training means the
    membership changed → notify registered States so the next commit()
    raises HostsUpdatedInterrupt."""

    def __init__(self):
        self._listeners = []
        self._lock = threading.Lock()
        self._thread = None
        self.epoch = -1

    def init(self):
        if not is_elastic() or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._poll, daemon=True)
        self._thread.start()

    def set_epoch(self, e):
        self.epoch = e

    def register_listener(self, state):
        with self._lock:
            self._listeners.append(state)

    def remove_listener(self, state):
        with self._lock:
            if state in self._listeners:
                self._listeners.remove(state)

    def _poll(self):
        liveness_on = _liveness_enabled()
        seq = 0
        while True:
            time.sleep(POLL_INTERVAL_S)
            if liveness_on:
                # Driver-side wedge backstop: PUT a monotonically
                # increasing sequence number; the driver tracks *when the
                # value last changed* on its own clock (no cross-host
                # clock comparison). A SIGSTOP'd worker stops bumping it
                # even when the core's control plane is mid-collective and
                # the coordinator cannot observe the wedge.
                seq += 1
                try:
                    http_server.put_kv(
                        _rdv_addr(), "ctl", f"alive/{_worker_id()}",
                        str(seq).encode(), secret_key=_rdv_secret())
                except Exception:
                    pass
            try:
                e = current_epoch()
            except Exception:
                continue
            if self.epoch >= 0 and e > self.epoch:
                with self._lock:
                    listeners = list(self._listeners)
                for s in listeners:
                    s.on_hosts_updated()


notification_manager = WorkerNotificationManager()
